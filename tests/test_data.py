"""Data-plane tests (loaders, transforms, sampler, prefetch, partitions) —
the NDArraySpec/MinibatchSamplerSpec analogs (reference:
src/test/scala/libs/MinibatchSamplerSpec.scala)."""

import itertools

import numpy as np
import pytest

from sparknet_tpu.data import (
    MinibatchSampler, PartitionedDataset, PrefetchIterator,
    center_crop, compute_mean_image, load_cifar10_binary, load_mnist_idx,
    make_minibatches, random_crop_mirror, subtract_mean,
    write_cifar10_binary, write_mnist_idx,
)
from sparknet_tpu.data.minibatch import batch_feed


def test_cifar_binary_roundtrip(tmp_path, np_rng):
    images = np_rng.integers(0, 256, size=(10, 3, 32, 32))
    labels = np_rng.integers(0, 10, size=10)
    p = str(tmp_path / "batch.bin")
    write_cifar10_binary(p, images, labels)
    x, y = load_cifar10_binary(p)
    np.testing.assert_array_equal(x, images.astype(np.float32))
    np.testing.assert_array_equal(y, labels)
    xs, ys = load_cifar10_binary([p, p], shuffle=True, seed=1)
    assert len(ys) == 20


def test_mnist_idx_roundtrip(tmp_path, np_rng):
    images = np_rng.integers(0, 256, size=(7, 1, 28, 28))
    labels = np_rng.integers(0, 10, size=7)
    ip, lp = str(tmp_path / "im.idx3"), str(tmp_path / "lb.idx1")
    write_mnist_idx(ip, lp, images, labels)
    x, y = load_mnist_idx(ip, lp)
    np.testing.assert_array_equal(x, images.astype(np.float32))
    np.testing.assert_array_equal(y, labels)


def test_make_minibatches_drops_remainder(np_rng):
    x = np_rng.normal(size=(10, 3, 4, 4)).astype(np.float32)
    y = np.arange(10)
    bs = make_minibatches(x, y, 4)
    assert len(bs) == 2  # 10 // 4, remainder dropped
    np.testing.assert_array_equal(bs[1][1], [4, 5, 6, 7])


def test_minibatch_sampler_contiguous_run(np_rng):
    batches = [(np.full((2, 1), i), np.full((2,), i)) for i in range(10)]
    s = MinibatchSampler(batches, num=4, seed=3)
    got = [int(lab[0]) for _, lab in s]
    assert len(got) == 4
    assert got == list(range(got[0], got[0] + 4))  # contiguous
    with pytest.raises(ValueError):
        MinibatchSampler(batches, num=11)


def test_mean_and_crops(np_rng):
    imgs = np_rng.integers(0, 256, size=(8, 3, 8, 8)).astype(np.float32)
    mean = compute_mean_image(imgs)
    assert mean.shape == (3, 8, 8)
    np.testing.assert_allclose(subtract_mean(imgs, mean).mean(axis=0),
                               np.zeros((3, 8, 8)), atol=1e-3)
    cc = center_crop(imgs, 4)
    np.testing.assert_array_equal(cc, imgs[:, :, 2:6, 2:6])
    rng = np.random.default_rng(0)
    rc = random_crop_mirror(imgs, 4, rng, mean=mean)
    assert rc.shape == (8, 3, 4, 4)


def test_batch_feed_applies_preprocess():
    batches = [(np.ones((2, 3, 4, 4)), np.zeros(2))]
    feed = list(batch_feed(iter(batches), preprocess=lambda x: x * 2))
    np.testing.assert_array_equal(feed[0]["data"],
                                  2 * np.ones((2, 3, 4, 4), np.float32))


def test_prefetch_iterator_order_and_error():
    out = list(PrefetchIterator(iter(range(100)), depth=4))
    assert out == list(range(100))

    def bad():
        yield 1
        raise RuntimeError("boom")

    it = PrefetchIterator(bad())
    assert next(it) == 1
    with pytest.raises(RuntimeError, match="boom"):
        list(it)


def test_prefetch_producer_error_propagates_even_with_full_queue():
    """Failure semantics: a producer exception must reach the consumer on
    next() even when staged items sit ahead of it in the queue (the
    consumer drains the good items, THEN sees the error — no silent
    truncation of the stream)."""
    def bad():
        yield 1
        yield 2
        raise ValueError("producer died")

    it = PrefetchIterator(bad(), depth=1)
    assert next(it) == 1
    assert next(it) == 2
    with pytest.raises(ValueError, match="producer died"):
        next(it)
    # the error is sticky: the iterator stays failed, not silently empty
    with pytest.raises(ValueError, match="producer died"):
        next(it)


def test_prefetch_transform_error_propagates():
    it = PrefetchIterator(iter([1, 2]), transform=lambda x: 1 // 0)
    with pytest.raises(ZeroDivisionError):
        next(it)


def test_prefetch_close_after_error_does_not_deadlock_or_leak():
    """close() after a producer error must return promptly and reap the
    daemon thread — the InternalThread lifecycle contract
    (internal_thread.hpp:29-42) under failure."""
    import time

    def bad():
        yield 1
        raise RuntimeError("late failure")

    it = PrefetchIterator(bad(), depth=1)
    assert next(it) == 1
    t0 = time.monotonic()
    it.close()
    assert time.monotonic() - t0 < 5.0, "close() hung after producer error"
    assert not it._thread.is_alive(), "producer thread leaked"
    with pytest.raises(RuntimeError, match="late failure"):
        next(it)  # the error stays visible after close, never masked


def test_prefetch_close_with_blocked_producer_does_not_deadlock():
    """A producer blocked on a FULL queue (endless source, consumer gone)
    must be released by close() — otherwise it would pin staged device
    memory for the rest of the process."""

    it = PrefetchIterator(itertools.count(), depth=2)
    assert next(it) == 0
    it.close()
    it._thread.join(timeout=5.0)
    assert not it._thread.is_alive(), "producer stuck on full queue"


def test_prefetch_slow_feed_fault_injection(monkeypatch):
    """SPARKNET_FAULT=slow_feed:<dur> delays every produced batch — the
    degraded-input-pipeline chaos mode."""
    import time

    monkeypatch.setenv("SPARKNET_FAULT", "slow_feed:30ms")
    monkeypatch.setenv("SPARKNET_FAULT_ATTEMPT", "0")
    t0 = time.monotonic()
    out = list(PrefetchIterator(iter(range(4)), depth=1))
    elapsed = time.monotonic() - t0
    assert out == list(range(4))
    assert elapsed >= 0.12, f"slow_feed not applied ({elapsed:.3f}s)"


def test_partitioned_dataset():
    ds = PartitionedDataset.from_items(range(10), 3)
    assert ds.num_partitions == 3
    assert ds.count() == 10
    assert sorted(ds.partition_sizes(), reverse=True) == [4, 3, 3]
    doubled = ds.map(lambda x: 2 * x)
    assert doubled.reduce(lambda a, b: a + b) == 90
    co = ds.coalesce(2)
    assert co.num_partitions == 2 and co.count() == 10


def test_partition_rebalance_recovers_all_records_in_order():
    """The elastic re-shard primitive: dropping a dead worker's partition
    then rebalancing over the survivors must re-cover EVERY record, keep
    order, and balance sizes within 1."""
    ds = PartitionedDataset([[0, 1, 2], [3, 4, 5], [6, 7, 8], [9, 10, 11]])
    survivors = ds.without_partitions([3]).rebalance(3)
    assert survivors.num_partitions == 3
    assert survivors.partition_sizes() == [3, 3, 3]
    flat = [x for p in survivors.partitions for x in p]
    assert flat == list(range(9))            # order preserved, none lost
    # re-covering the DEAD worker's records: rebalance the full set
    reformed = ds.rebalance(3)
    assert reformed.count() == 12
    assert reformed.partition_sizes() == [4, 4, 4]
    assert [x for p in reformed.partitions for x in p] == list(range(12))
    # a rejoin at the next round boundary re-grows the partition count
    regrown = reformed.rebalance(4)
    assert regrown.partition_sizes() == [3, 3, 3, 3]
    # uneven splits stay contiguous and within-1 balanced
    odd = PartitionedDataset([list(range(10))]).rebalance(3)
    assert odd.partition_sizes() == [4, 3, 3]
    assert odd.partitions[0] == [0, 1, 2, 3]


def test_partition_rebalance_validates():
    ds = PartitionedDataset([[1], [2]])
    with pytest.raises(IndexError, match="out of range"):
        ds.without_partitions([5])
    with pytest.raises(ValueError, match="num_partitions"):
        ds.rebalance(0)


# ---------------------------------------------------------------------------
# synthgen: the generalization-bearing learning-proxy dataset
# ---------------------------------------------------------------------------

def test_synthgen_determinism_and_world_sharing():
    from sparknet_tpu.data.synthgen import synth_splits, synth_textures

    x1, y1 = synth_textures(64, seed=11)
    x2, y2 = synth_textures(64, seed=11)
    np.testing.assert_array_equal(x1, x2)
    np.testing.assert_array_equal(y1, y2)
    assert x1.shape == (64, 3, 32, 32) and x1.dtype == np.float32
    assert x1.min() >= 0.0 and x1.max() <= 255.0
    # different sample seed, same texture world -> different images
    x3, _ = synth_textures(64, seed=12)
    assert not np.array_equal(x1, x3)

    tx, ty, vx, vy = synth_splits(128, 64)
    assert tx.shape[0] == 128 and vx.shape[0] == 64
    assert not np.array_equal(tx[:64], vx)  # disjoint sample streams
    assert set(np.unique(ty)) <= set(range(10))


def test_synthgen_not_linearly_saturable():
    """The round-4 verdict's core complaint: the old proxy was linearly
    separable (accuracy 1.0 by iter 1000).  A least-squares linear
    readout over raw pixels must NOT solve this dataset, while class
    structure must still be present (above chance)."""
    from sparknet_tpu.data.synthgen import synth_splits

    tx, ty, vx, vy = synth_splits(1500, 500)
    A = tx.reshape(len(ty), -1).astype(np.float64)
    A = np.concatenate([A, np.ones((len(ty), 1))], axis=1)
    T = np.eye(10)[ty]
    W, *_ = np.linalg.lstsq(A, T, rcond=1e-6)
    B = vx.reshape(len(vy), -1).astype(np.float64)
    B = np.concatenate([B, np.ones((len(vy), 1))], axis=1)
    acc = float((np.argmax(B @ W, 1) == vy).mean())
    assert 0.12 < acc < 0.6, f"linear probe accuracy {acc}"
