"""Binary protobuf interchange tests — the analog of the reference's
test_upgrade_proto.cpp + test_io.cpp + the snapshot/restore halves of
test_gradient_based_solver.cpp.  Includes a bidirectional cross-check
against the *official* protobuf implementation (protoc-generated pb2 over
the reference caffe.proto), when protoc is available."""

import shutil
import subprocess
import sys

import jax.numpy as jnp
import numpy as np
import pytest

from sparknet_tpu.models import lenet
from sparknet_tpu.proto import (
    load_solver_prototxt_with_net,
    parse,
)
from sparknet_tpu.proto.caffe_pb import NetParameter, SolverParameter
from sparknet_tpu.proto.caffemodel import (
    array_to_blob,
    load_caffemodel,
    load_mean_binaryproto,
    load_net_binaryproto,
    load_solverstate,
    save_caffemodel,
    save_mean_binaryproto,
    save_solverstate,
)
from sparknet_tpu.proto.wireformat import decode, encode
from sparknet_tpu.solvers import Solver

REF_PROTO = "/root/reference/caffe/src/caffe/proto/caffe.proto"
SOLVER_TXT = 'base_lr: 0.01\nmomentum: 0.9\nlr_policy: "fixed"\n'


# ---------------------------------------------------------------------------
# wire codec
# ---------------------------------------------------------------------------

def test_solver_prototxt_binary_roundtrip():
    text = open(
        "/root/reference/caffe/models/bvlc_googlenet/solver.prototxt").read()
    m = parse(text)
    raw = encode(m, "SolverParameter")
    sp = SolverParameter.from_pmsg(decode(raw, "SolverParameter"))
    ref = SolverParameter.from_pmsg(m)
    assert sp.lr_policy == ref.lr_policy
    assert sp.base_lr == pytest.approx(ref.base_lr)  # float32 storage
    assert sp.momentum == pytest.approx(ref.momentum)
    assert sp.max_iter == ref.max_iter
    assert sp.stepvalue == ref.stepvalue or sp.stepsize == ref.stepsize
    # re-encode is byte-stable
    assert encode(decode(raw, "SolverParameter"), "SolverParameter") == raw


def test_net_prototxt_binary_roundtrip():
    text = open(
        "/root/reference/caffe/models/bvlc_alexnet/train_val.prototxt").read()
    m = parse(text)
    raw = encode(m, "NetParameter")
    got = NetParameter.from_pmsg(decode(raw, "NetParameter"))
    ref = NetParameter.from_pmsg(m)
    assert [l.name for l in got.layer] == [l.name for l in ref.layer]
    assert [l.type for l in got.layer] == [l.type for l in ref.layer]
    conv_got = next(l for l in got.layer if l.name == "conv2")
    conv_ref = next(l for l in ref.layer if l.name == "conv2")
    assert int(conv_got.sub("convolution_param").get("group")) == \
        int(conv_ref.sub("convolution_param").get("group"))


def test_scale_bias_input_params_roundtrip():
    """Post-fork upstream fields (Scale/Bias/Input) must survive the wire —
    ResNet-class zoo models carry scale_param in their .caffemodel."""
    m = parse('layer { name: "s" type: "Scale" '
              'scale_param { bias_term: true axis: 1 } }\n'
              'layer { name: "in" type: "Input" '
              'input_param { shape { dim: 1 dim: 3 } } }')
    raw = encode(m, "NetParameter")
    net = NetParameter.from_pmsg(decode(raw, "NetParameter"))
    assert bool(net.layer[0].sub("scale_param").get("bias_term")) is True
    from sparknet_tpu.proto.caffe_pb import BlobShape
    shp = BlobShape.from_pmsg(net.layer[1].sub("input_param").get("shape"))
    assert shp.dim == [1, 3]


def test_layout_mismatch_rejected(tmp_path):
    """Same-size but different-layout blobs must raise, not silently
    reshape (Caffe shape CHECK semantics)."""
    a = _solver()
    key = next(iter(a.params))
    shape = np.asarray(a.params[key][0]).shape
    bad = {key: [np.zeros(shape[::-1], np.float32)]
           + [np.asarray(b) for b in a.params[key][1:]]}
    with pytest.raises(ValueError, match="incompatible"):
        a.copy_trained_layers_from(bad)


def test_negative_and_bool_fields_roundtrip():
    sp_msg = parse("random_seed: -1\ntest_initialization: false\n"
                   "clip_gradients: -1.0\n")
    raw = encode(sp_msg, "SolverParameter")
    sp = SolverParameter.from_pmsg(decode(raw, "SolverParameter"))
    assert sp.random_seed == -1
    assert sp.test_initialization is False
    assert sp.clip_gradients == -1.0


# ---------------------------------------------------------------------------
# caffemodel / binaryproto
# ---------------------------------------------------------------------------

def test_caffemodel_save_load_roundtrip(tmp_path):
    rng = np.random.default_rng(0)
    params = {
        "conv1": [rng.normal(size=(4, 3, 3, 3)).astype(np.float32),
                  rng.normal(size=(4,)).astype(np.float32)],
        "fc1": [rng.normal(size=(10, 36)).astype(np.float32)],
    }
    path = str(tmp_path / "model.caffemodel")
    save_caffemodel(path, params)
    loaded = load_caffemodel(path)
    assert set(loaded) == {"conv1", "fc1"}
    for k in params:
        for a, b in zip(params[k], loaded[k]):
            np.testing.assert_array_equal(a, b)


def test_legacy_blob_shape_load(tmp_path):
    """Legacy (num,channels,height,width) BlobProto spellings load and
    reshape into new-style nets (Blob::ShapeEquals legacy tolerance,
    reference: blob.cpp)."""
    from sparknet_tpu.proto.textformat import PMessage
    w = np.arange(20, dtype=np.float32)
    blob = PMessage()
    for k, v in zip(("num", "channels", "height", "width"), (1, 1, 4, 5)):
        blob.add(k, v)
    blob.add("data", w)
    lmsg = PMessage()
    lmsg.add("name", "ip")
    lmsg.add("blobs", blob)
    netmsg = PMessage()
    netmsg.add("layer", lmsg)
    path = tmp_path / "legacy.caffemodel"
    path.write_bytes(encode(netmsg, "NetParameter"))
    loaded = load_caffemodel(str(path))
    assert loaded["ip"][0].shape == (1, 1, 4, 5)


def test_v1_format_caffemodel_loads(tmp_path):
    """V1-format files (repeated V1LayerParameter ``layers``, enum types) —
    the format of every published BVLC zoo .caffemodel (reference:
    upgrade_proto.cpp UpgradeV1Net)."""
    from sparknet_tpu.proto.textformat import PMessage
    w = np.arange(6, dtype=np.float32).reshape(2, 3)
    blob = array_to_blob(w)
    v1 = PMessage()
    v1.add("name", "ip1")
    v1.add("type", "INNER_PRODUCT")
    v1.add("bottom", "data")
    v1.add("top", "ip1")
    v1.add("blobs", blob)
    netmsg = PMessage()
    netmsg.add("name", "v1net")
    netmsg.add("layers", v1)
    raw = encode(netmsg, "NetParameter")
    net = NetParameter.from_pmsg(decode(raw, "NetParameter"))
    assert net.layer[0].type == "InnerProduct"  # V1 enum -> V2 name
    assert net.layer[0].name == "ip1"
    np.testing.assert_array_equal(net.layer[0].blobs[0], w)
    loaded = load_caffemodel(raw)
    np.testing.assert_array_equal(loaded["ip1"][0], w)


def test_mean_binaryproto_roundtrip(tmp_path):
    mean = np.random.default_rng(0).normal(size=(3, 8, 8)).astype(np.float32)
    path = str(tmp_path / "mean.binaryproto")
    save_mean_binaryproto(path, mean)
    np.testing.assert_allclose(load_mean_binaryproto(path), mean, rtol=1e-6)


# ---------------------------------------------------------------------------
# Solver integration
# ---------------------------------------------------------------------------

def _solver(batch=4):
    sp = load_solver_prototxt_with_net(SOLVER_TXT, lenet(batch, batch))
    return Solver(sp, seed=0)


def _feed(batch=4, n=64):
    rng = np.random.default_rng(1)
    while True:
        yield {"data": rng.normal(size=(batch, 1, 28, 28)).astype(np.float32),
               "label": rng.integers(0, 10, size=(batch,)).astype(np.float32)}


def test_solver_caffe_snapshot_restore_equivalence(tmp_path):
    """Training N steps, caffe-format snapshot, restore into a fresh solver,
    then continuing, matches uninterrupted training — the core assertion of
    test_gradient_based_solver.cpp's snapshot tests."""
    a = _solver()
    a.set_train_data(_feed())
    a.step(3)
    model, state = a.snapshot_caffe(str(tmp_path / "snap"))
    a.step(2)

    b = _solver()
    b.load_weights(model)
    b.restore_caffe(state)
    assert b.iter == 3
    # re-align the data stream: a consumed 3 batches before the fork
    it = _feed()
    for _ in range(3):
        next(it)
    b.set_train_data(it)
    b._rng = a._rng  # jitter alignment is not part of the snapshot contract
    b.step(2)
    for k in a.params:
        for x, y in zip(a.params[k], b.params[k]):
            np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                       rtol=2e-4, atol=2e-5)


def test_solver_hdf5_snapshot_restore_equivalence(tmp_path):
    """``snapshot_format: HDF5`` writes .caffemodel.h5/.solverstate.h5 in
    the reference layout (solver.cpp:449-459 SnapshotToHDF5,
    sgd_solver.cpp:275-338, net.cpp:926-975 ToHDF5) and restores to the
    exact state the binaryproto path restores to."""
    h5py = pytest.importorskip("h5py")
    sp_txt = SOLVER_TXT + "snapshot_format: HDF5\n"
    sp = load_solver_prototxt_with_net(sp_txt, lenet(4, 4))
    assert sp.snapshot_format == "HDF5"
    a = Solver(sp, seed=0)
    a.set_train_data(_feed())
    a.step(3)
    model, state = a.snapshot_caffe(str(tmp_path / "snap"))
    assert model.endswith(".caffemodel.h5")
    assert state.endswith(".solverstate.h5")

    # reference on-disk layout: data/<layer>/<i> groups, history/<i>
    with h5py.File(model) as f:
        assert "conv1" in f["data"] and "0" in f["data"]["conv1"]
    with h5py.File(state) as f:
        assert int(np.asarray(f["iter"])) == 3 and "0" in f["history"]

    # cross-format: restoring h5 == restoring binaryproto
    bp = _solver()
    bp_model, bp_state = None, None
    a.sp.snapshot_format = "BINARYPROTO"
    bp_model, bp_state = a.snapshot_caffe(str(tmp_path / "snap_bp"))

    h = _solver()
    h.load_weights(model)
    h.restore_caffe(state)
    bp.load_weights(bp_model)
    bp.restore_caffe(bp_state)
    assert h.iter == bp.iter == 3
    for k in h.params:
        for x, y in zip(h.params[k], bp.params[k]):
            np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                       rtol=1e-6)
    for slot in h.state:
        if slot == "iter":
            continue
        for k in h.state[slot]:
            for x, y in zip(h.state[slot][k], bp.state[slot][k]):
                np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                           rtol=1e-6)


SIAMESE_SOLVER_NET = """
name: "siamese"
layer { name: "d" type: "JavaData" top: "a" top: "label"
        java_data_param { shape { dim: 4 dim: 8 } shape { dim: 4 } } }
layer { name: "ip_a" type: "InnerProduct" bottom: "a" top: "fa"
        param { name: "w" }
        inner_product_param { num_output: 8
                              weight_filler { type: "xavier" }
                              bias_filler { type: "constant" value: 1 } } }
layer { name: "ip_b" type: "InnerProduct" bottom: "fa" top: "fb"
        param { name: "w" }
        inner_product_param { num_output: 8
                              weight_filler { type: "xavier" }
                              bias_filler { type: "constant" value: 2 } } }
layer { name: "loss" type: "EuclideanLoss" bottom: "fb" bottom: "a"
        top: "loss" }
"""


def test_caffemodel_interop_with_shared_params(tmp_path):
    """A partially-shared net saves caffemodels with FULL per-layer blob
    lists (Net::ToProto convention — Caffe CHECK_EQs blob counts on load)
    and loads them back through the sharing map."""
    from sparknet_tpu.proto import load_net_prototxt
    from sparknet_tpu.proto.caffemodel import load_net_binaryproto

    def make():
        sp = load_solver_prototxt_with_net(
            SOLVER_TXT, load_net_prototxt(SIAMESE_SOLVER_NET))
        return Solver(sp, seed=0)

    a = make()
    assert len(a.params["ip_a"]) == 2 and len(a.params["ip_b"]) == 1
    model, _ = a.snapshot_caffe(str(tmp_path / "shared"))

    # the file carries 2 blobs for BOTH ip layers (sharer repeats the weight)
    net = load_net_binaryproto(model)
    by_name = {lp.name: lp for lp in net.layer}
    assert len(by_name["ip_a"].blobs) == 2
    assert len(by_name["ip_b"].blobs) == 2
    np.testing.assert_allclose(by_name["ip_a"].blobs[0],
                               by_name["ip_b"].blobs[0])  # same shared w

    b = make()
    b.load_weights(model)
    for k in a.params:
        for x, y in zip(a.params[k], b.params[k]):
            np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                       rtol=1e-6)


def test_load_weights_sniffs_caffemodel(tmp_path):
    a = _solver()
    path = str(tmp_path / "w.caffemodel")
    save_caffemodel(path, {k: [np.asarray(b) for b in v]
                           for k, v in a.params.items()})
    b = _solver(batch=2)
    b.load_weights(path)
    for k in a.params:
        for x, y in zip(a.params[k], b.params[k]):
            np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


# ---------------------------------------------------------------------------
# Cross-check vs official protobuf (skipped when protoc is unavailable)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def caffe_pb2(tmp_path_factory):
    if shutil.which("protoc") is None:
        pytest.skip("protoc not available")
    gen = tmp_path_factory.mktemp("protogen")
    shutil.copy(REF_PROTO, gen / "caffe.proto")
    subprocess.run(["protoc", "--python_out=.", "caffe.proto"],
                   cwd=gen, check=True)
    sys.path.insert(0, str(gen))
    try:
        import caffe_pb2 as mod
    except Exception as e:  # pragma: no cover
        pytest.skip(f"generated pb2 unusable: {e}")
    finally:
        sys.path.remove(str(gen))
    return mod


def test_interop_with_official_protobuf(caffe_pb2):
    net = caffe_pb2.NetParameter()
    net.name = "interop"
    l = net.layer.add()
    l.name = "conv1"
    l.type = "Convolution"
    l.bottom.append("data")
    l.top.append("conv1")
    l.convolution_param.num_output = 4
    l.convolution_param.kernel_size.append(3)
    b = l.blobs.add()
    b.shape.dim.extend([4, 3, 3, 3])
    b.data.extend(np.arange(108, dtype=np.float32).tolist())

    # official encode -> our decode
    got = NetParameter.from_pmsg(decode(net.SerializeToString(), "NetParameter"))
    assert got.name == "interop"
    assert got.layer[0].blobs[0].shape == (4, 3, 3, 3)
    assert got.layer[0].blobs[0].sum() == np.arange(108).sum()

    # our encode -> official decode
    raw2 = encode(decode(net.SerializeToString(), "NetParameter"),
                  "NetParameter")
    net2 = caffe_pb2.NetParameter()
    net2.ParseFromString(raw2)
    assert net2.layer[0].name == "conv1"
    assert list(net2.layer[0].blobs[0].shape.dim) == [4, 3, 3, 3]
    np.testing.assert_array_equal(
        np.asarray(net2.layer[0].blobs[0].data),
        np.arange(108, dtype=np.float32))


def test_solverstate_interop_with_official(caffe_pb2, tmp_path):
    path = str(tmp_path / "s.solverstate")
    hist = [np.arange(4, dtype=np.float32), np.ones((2, 2), np.float32)]
    save_solverstate(path, 42, hist, learned_net="m.caffemodel",
                     current_step=7)
    st = caffe_pb2.SolverState()
    st.ParseFromString(open(path, "rb").read())
    assert st.iter == 42
    assert st.current_step == 7
    assert st.learned_net == "m.caffemodel"
    assert len(st.history) == 2
    np.testing.assert_array_equal(np.asarray(st.history[0].data),
                                  hist[0])
    back = load_solverstate(path)
    assert back["iter"] == 42
    np.testing.assert_array_equal(back["history"][1], hist[1])
