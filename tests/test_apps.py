"""App-tier tests: the ImageNet tar->label->decode chain on fabricated
archives, RoundFeed assembly semantics, and an in-process CifarApp smoke run
— the closest analog of the reference's (ignored) ImageNetLoaderSpec plus
the CifarApp path it never unit-tested."""

import io
import os
import tarfile

import numpy as np
import pytest

from sparknet_tpu.data.imagenet import (
    decode_and_resize, list_tars, read_label_map, stream_tar_images,
    load_imagenet,
)
from sparknet_tpu.data.partition import PartitionedDataset
from sparknet_tpu.apps.common import RoundFeed, eval_feed


def _jpeg_bytes(color):
    from PIL import Image
    arr = np.zeros((32, 48, 3), np.uint8)
    arr[:] = color
    buf = io.BytesIO()
    Image.fromarray(arr).save(buf, format="JPEG", quality=95)
    return buf.getvalue()


@pytest.fixture
def imagenet_fixture(tmp_path):
    """Two tars of colored JPEGs + a train.txt label map."""
    labels = {}
    for t in range(2):
        tar_path = tmp_path / f"chunk{t}.tar"
        with tarfile.open(tar_path, "w") as tf:
            for i in range(4):
                name = f"img_{t}_{i}.JPEG"
                data = _jpeg_bytes((40 * i, 10, 255 - 40 * i))
                info = tarfile.TarInfo(name)
                info.size = len(data)
                tf.addfile(info, io.BytesIO(data))
                labels[name] = t * 4 + i
    label_file = tmp_path / "train.txt"
    with open(label_file, "w") as f:
        for name, lab in labels.items():
            f.write(f"{name} {lab}\n")
        f.write("missing_from_tars.JPEG 99\n")
    return str(tmp_path), str(label_file)


def test_label_map_and_tar_listing(imagenet_fixture):
    root, label_file = imagenet_fixture
    labels = read_label_map(label_file)
    assert labels["img_0_0.JPEG"] == 0 and labels["img_1_3.JPEG"] == 7
    tars = list_tars(root)
    assert [os.path.basename(t) for t in tars] == ["chunk0.tar", "chunk1.tar"]


def test_stream_and_decode(imagenet_fixture):
    root, label_file = imagenet_fixture
    labels = read_label_map(label_file)
    pairs = list(stream_tar_images(list_tars(root)[0], labels))
    assert len(pairs) == 4
    decoded = list(decode_and_resize(iter(pairs), size=16))
    assert len(decoded) == 4
    img, lab = decoded[0]
    assert img.shape == (3, 16, 16) and 0 <= lab < 4


def test_decode_drops_corrupt(imagenet_fixture):
    pairs = [(b"corrupt bytes", 0), (_jpeg_bytes((1, 2, 3)), 1)]
    out = list(decode_and_resize(iter(pairs), size=8))
    assert len(out) == 1 and out[0][1] == 1


def test_load_imagenet_partitions(imagenet_fixture):
    root, label_file = imagenet_fixture
    ds = load_imagenet(root, label_file, num_partitions=4, size=8)
    assert ds.count() == 8
    assert ds.num_partitions == 4


def test_round_feed_shapes_and_preprocess(np_rng):
    items = [(np.full((3, 8, 8), i, np.float32), i % 5) for i in range(40)]
    ds = PartitionedDataset.from_items(items, 2)
    feed = RoundFeed(ds, per_worker_batch=4, batches_per_round=3,
                     preprocess=lambda x: x * 2.0, seed=0)
    round_ = feed.next_round()
    assert round_["data"].shape == (3, 8, 3, 8, 8)
    assert round_["label"].shape == (3, 8)
    # preprocess applied (values doubled)
    assert round_["data"].max() >= 2.0

    with pytest.raises(ValueError, match="< batches_per_round"):
        RoundFeed(ds, per_worker_batch=4, batches_per_round=99)


def test_round_feed_prefetch_overlap():
    """The feed thread must run ahead of the consumer: after one round is
    consumed, the NEXT round's preprocessing happens in the background with
    no further pull — the double-buffering the reference's JavaData path
    lacked (reference: java_data_layer.cpp:36-44, SURVEY.md §7.2(5))."""
    import time

    from sparknet_tpu.data.prefetch import device_feed

    calls: list[float] = []

    def preproc(x):
        calls.append(time.monotonic())
        return x

    items = [(np.zeros((1, 4, 4), np.float32), i % 5) for i in range(32)]
    ds = PartitionedDataset.from_items(items, 2)
    feed = RoundFeed(ds, per_worker_batch=2, batches_per_round=2, preprocess=preproc)
    per_round = 2 * 2  # tau × partitions preprocess calls per round
    it = device_feed(feed.rounds(), depth=1)
    first = next(it)
    assert first["data"].shape == (2, 4, 1, 4, 4)
    # consumer holds round 1 and never pulls again; the background thread
    # must still assemble (preprocess) round 2 on its own
    deadline = time.monotonic() + 10.0
    while len(calls) < 2 * per_round and time.monotonic() < deadline:
        time.sleep(0.01)
    assert len(calls) >= 2 * per_round, (
        f"prefetch thread idle: only {len(calls)} preprocess calls")


def test_round_feed_streaming_slices_only():
    """Rounds must stack only the sampled slice, never whole partitions:
    records are probed through a counting __getitem__ proxy."""
    class CountingList(list):
        def __init__(self, items):
            super().__init__(items)
            self.slices: list[slice] = []

        def __getitem__(self, key):
            if isinstance(key, slice):
                self.slices.append(key)
            return super().__getitem__(key)

    items = [(np.zeros((1, 4, 4), np.float32), i % 5) for i in range(100)]
    part = CountingList(items)
    ds = PartitionedDataset.__new__(PartitionedDataset)
    ds.partitions = [part]
    feed = RoundFeed(ds, per_worker_batch=4, batches_per_round=2, seed=0)
    feed.next_round()
    # exactly tau slices of batch size, no whole-partition reads
    assert len(part.slices) == 2
    for s in part.slices:
        assert s.stop - s.start == 4


def test_eval_feed_covers_partitions(np_rng):
    items = [(np.zeros((3, 4, 4), np.float32), i % 3) for i in range(24)]
    ds = PartitionedDataset.from_items(items, 4)
    factory, steps = eval_feed(ds, per_worker_batch=2)
    batches = list(factory())
    assert len(batches) == steps == 3
    assert batches[0]["data"].shape == (8, 3, 4, 4)


def test_cifar_app_smoke(tmp_path):
    from sparknet_tpu.apps import cifar_app
    scores = cifar_app.main([
        "--workers", "4", "--rounds", "2", "--synthetic", "--tau", "2",
        "--batch", "10", "--test-interval", "0",
        "--log-dir", str(tmp_path),
        "--snapshot", str(tmp_path / "snap.npz"),
    ])
    assert "accuracy" in scores and "loss" in scores
    assert (tmp_path / "snap.npz").exists()
    logs = list(tmp_path.glob("training_log_*.txt"))
    assert logs and "round 1" in logs[0].read_text()


def test_streaming_lazy_partitions(imagenet_fixture):
    """load_imagenet holds only a tar index; records decode on slice
    access (bounded RSS — VERDICT r1 weak #8).  RoundFeed over lazy
    partitions touches exactly the sampled window."""
    root, label_file = imagenet_fixture
    ds = load_imagenet(root, label_file, num_partitions=2, size=8)
    assert ds.count() == 8
    parts = ds.partitions
    assert all(p.decoded_count == 0 for p in parts)  # nothing decoded yet

    feed = RoundFeed(ds, per_worker_batch=2, batches_per_round=2, seed=0)
    round_ = feed.next_round()
    assert round_["data"].shape == (2, 4, 3, 8, 8)
    touched = sum(p.decoded_count for p in parts)
    assert touched == 8  # 2 steps x 2 workers x batch 2 — and nothing more

    # eval feed stays lazy too
    factory, steps = eval_feed(ds, per_worker_batch=2)
    list(factory())
    assert steps == 2


def test_streaming_drop_accounting(tmp_path):
    """Undecodable tar members are drop-accounted and substituted so batch
    shapes stay static (ScaleAndConvert.scala:23-25 drop semantics)."""
    import tarfile as tarmod
    tar_path = tmp_path / "bad.tar"
    good = _jpeg_bytes((9, 9, 9))
    with tarmod.open(tar_path, "w") as tf:
        for name, data in [("a.JPEG", good), ("b.JPEG", b"not a jpeg"),
                           ("c.JPEG", good)]:
            info = tarmod.TarInfo(name)
            info.size = len(data)
            tf.addfile(info, io.BytesIO(data))
    (tmp_path / "labels.txt").write_text(
        "a.JPEG 0\nb.JPEG 1\nc.JPEG 2\n")
    ds = load_imagenet(str(tmp_path), str(tmp_path / "labels.txt"),
                       num_partitions=1, size=8)
    part = ds.partitions[0]
    recs = part[0:3]
    assert len(recs) == 3 and all(r[0].shape == (3, 8, 8) for r in recs)
    assert part.dropped == 1


def test_object_store_dispatch(imagenet_fixture):
    from sparknet_tpu.data.objectstore import LocalStore, get_store
    root, _ = imagenet_fixture
    store, prefix = get_store(f"file://{root}")
    assert isinstance(store, LocalStore) and prefix == ""
    keys = store.list_keys()
    assert "chunk0.tar" in keys
    with store.open("chunk0.tar") as f:
        assert f.read(2) != b""
    # ranged read equals seek+read
    whole = open(os.path.join(root, "chunk0.tar"), "rb").read()
    assert store.open_range("chunk0.tar", 10, 5) == whole[10:15]

    with pytest.raises(ImportError, match="boto3"):
        get_store("s3://bucket/prefix")
    # gs:// fails cleanly whether the client lib or credentials are absent
    with pytest.raises((ImportError, RuntimeError),
                       match="google-cloud-storage|unreachable"):
        get_store("gs://bucket/prefix")


def test_imagenet_app_tar_chain(tmp_path):
    """The ImageNet app end-to-end over a real multi-tar set through the
    streaming (lazy-decode) ingestion tier — the bounded-RSS dry-run of
    VERDICT r1 next-step 8.  Needs enough images per partition for
    tau x batch contiguous runs."""
    import tarfile as tarmod

    from sparknet_tpu.apps import imagenet_app

    labels = {}
    n_per_tar, n_tars = 24, 2
    for t in range(n_tars):
        tar_path = tmp_path / f"train{t}.tar"
        with tarmod.open(tar_path, "w") as tf:
            for i in range(n_per_tar):
                name = f"img_{t}_{i}.JPEG"
                data = _jpeg_bytes(((37 * i) % 256, 80, (11 * i) % 256))
                info = tarmod.TarInfo(name)
                info.size = len(data)
                tf.addfile(info, io.BytesIO(data))
                labels[name] = i % 4
    with open(tmp_path / "train.txt", "w") as f:
        for name, lab in labels.items():
            f.write(f"{name} {lab}\n")

    scores = imagenet_app.main([
        "--workers", "2", "--rounds", "2", "--tau", "2", "--batch", "4",
        "--model", "alexnet", "--classes", "4", "--resize", "32",
        "--crop", "24", "--test-interval", "0",
        "--tar-dir", str(tmp_path), "--label-file", str(tmp_path / "train.txt"),
        "--log-dir", str(tmp_path),
    ])
    assert "loss" in scores
