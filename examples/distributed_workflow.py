"""The SparkNet distributed workflow, end to end on a device mesh.

Mirrors what the reference's apps drive through Spark (ImageNetApp.scala
/ CifarApp.scala: shard data -> broadcast weights -> per-worker local
steps -> collect & average -> distributed eval), as the three trainer
strategies this framework compiles into single mesh programs:

  sync          per-step gradient averaging   (P2PSync, parallel.cpp)
  local_sgd     tau-step weight averaging     (the SparkNet algorithm)
  hierarchical  both composed on a (host, chip) pod mesh

Run:  python examples/distributed_workflow.py    (8 virtual CPU devices
      via XLA_FLAGS=--xla_force_host_platform_device_count=8, or a real
      multi-chip platform)
"""

import os
import sys
import tempfile

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
    import jax
    jax.config.update("jax_platforms", "cpu")

import jax  # noqa: E402

from sparknet_tpu.models import lenet  # noqa: E402
from sparknet_tpu.parallel import (  # noqa: E402
    DistributedTrainer, TrainerConfig, make_mesh, make_pod_mesh,
)
from sparknet_tpu.proto import load_solver_prototxt_with_net  # noqa: E402

# lr 0.01: each local_sgd worker sees batch 4 here — 0.05 genuinely
# diverges in that regime (same setting the distributed tests use)
SOLVER = 'base_lr: 0.01\nmomentum: 0.9\nlr_policy: "fixed"\n'


def make_data(rng, tau, global_batch):
    """[tau, global_batch, ...] round feeds — a worker's rows are its
    partition slice (the zipPartitions placement)."""
    n = tau * global_batch
    y = rng.integers(0, 10, size=n)
    x = rng.normal(scale=0.3, size=(n, 1, 28, 28)).astype(np.float32)
    for k in range(10):
        x[y == k, :, k % 28, :] += 2.0
    return {"data": x.reshape(tau, global_batch, 1, 28, 28),
            "label": y.reshape(tau, global_batch).astype(np.float32)}


def main() -> None:
    n_dev = len(jax.devices())
    assert n_dev >= 8, f"want 8 devices for the demo, have {n_dev}"
    rng = np.random.default_rng(0)
    sp = load_solver_prototxt_with_net(SOLVER, lenet(32, 32))
    tau, global_batch = 5, 32

    # -- SparkNet rounds: tau local steps then weight averaging ----------
    tr = DistributedTrainer(sp, make_mesh(8),
                            TrainerConfig(strategy="local_sgd", tau=tau),
                            seed=0)
    losses = [tr.train_round(make_data(rng, tau, global_batch))
              for _ in range(6)]
    print(f"local_sgd: loss {losses[0]:.3f} -> {losses[-1]:.3f} "
          f"over {tr.iter} iters on {tr.n_workers} workers")
    assert losses[-1] < 0.5 * losses[0]

    # -- distributed eval: per-worker scores masked + psum'd -------------
    eval_data = make_data(rng, 1, global_batch)
    feed = iter([{"data": eval_data["data"][0],
                  "label": eval_data["label"][0]}] * 4)
    scores = tr.test(feed, num_steps=4)
    acc = scores["accuracy"] / scores["__test_batches__"]
    print(f"eval: accuracy {acc:.3f} over "
          f"{int(scores['__test_batches__'])} worker-batches")

    # -- snapshot / restore (momentum history included) ------------------
    with tempfile.TemporaryDirectory() as td:
        path = os.path.join(td, "round.npz")
        tr.snapshot(path)
        tr2 = DistributedTrainer(
            sp, make_mesh(8), TrainerConfig(strategy="local_sgd", tau=tau),
            seed=1)
        tr2.restore(path)
        assert tr2.iter == tr.iter
        print(f"restored at iter {tr2.iter}; next round loss "
              f"{tr2.train_round(make_data(rng, tau, global_batch)):.3f}")

    # -- the composed pod: chip psum x host weight averaging -------------
    pod = make_pod_mesh(2, 4)
    hier = DistributedTrainer(sp, pod,
                              TrainerConfig(strategy="hierarchical",
                                            tau=tau), seed=0)
    hloss = [hier.train_round(make_data(rng, tau, global_batch))
             for _ in range(6)]
    print(f"hierarchical 2x4: loss {hloss[0]:.3f} -> {hloss[-1]:.3f} "
          f"(chip-axis psum per step, host-axis average per tau)")
    assert hloss[-1] < 0.5 * hloss[0]
    print("OK: distributed workflow complete")


if __name__ == "__main__":
    main()
