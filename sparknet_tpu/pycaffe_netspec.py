"""``caffe.net_spec`` shim — programmatic net construction in the
pycaffe idiom (reference: caffe/python/caffe/net_spec.py)::

    from sparknet_tpu.pycaffe_compat import layers as L, params as P, NetSpec
    n = NetSpec()
    n.conv1 = L.Convolution(n.data, kernel_size=5, num_output=20,
                            weight_filler=dict(type='xavier'))
    n.pool1 = L.Pooling(n.conv1, kernel_size=2, stride=2,
                        pool=P.Pooling.MAX)
    n.loss = L.SoftmaxWithLoss(n.score, n.label)
    net_param = n.to_proto()          # a typed NetParameter
    text = str(n.to_proto())          # prototxt text

Kwarg routing matches the reference's param_name_dict(): a layer type's
kwargs land in its ``<type>_param`` sub-message (derived from the
LayerParameter schema, e.g. Convolution -> convolution_param), except
LayerParameter-level fields (loss_weight, param, include, ...) and
explicit ``*_param=dict(...)`` sub-messages.  ``ntop`` controls the
number of returned tops, ``in_place=True`` reuses the bottom name
(net_spec.py Function semantics).
"""

from __future__ import annotations

from typing import Any

from .proto.caffe_pb import NetParameter
from .proto.textformat import serialize
from .proto.wireformat import MESSAGES

# LayerParameter-level fields assignable directly from kwargs
_TOP_LEVEL = {"loss_weight", "param", "phase", "include", "exclude"}

# message-type -> field map derived from the schema, the reference's
# param_name_dict(): ConvolutionParameter -> Convolution -> convolution_param
_PARAM_FIELDS = {name for _num, (name, kind) in
                 MESSAGES["LayerParameter"].items()
                 if name.endswith("_param")}
_TYPE_TO_PARAM = {}
_PARAM_MSG_TYPE = {}
for _num, (_name, _kind) in MESSAGES["LayerParameter"].items():
    if _name.endswith("_param") and _kind.startswith("msg:"):
        _t = _kind[4:]
        _PARAM_MSG_TYPE[_name] = _t
        if _t.endswith("Parameter"):
            _TYPE_TO_PARAM[_t[:-len("Parameter")]] = _name


def _check_param_fields(field: str, sub: dict) -> None:
    """Reject misspelled sub-message fields at build time, like
    net_spec's protobuf assignment would (reference net_spec.py
    assign_proto raising on nonexistent fields)."""
    schema = MESSAGES.get(_PARAM_MSG_TYPE.get(field, ""), None)
    if schema is None:
        return  # param message without a wire schema: accept as-is
    known = {name for _n, (name, _k) in schema.items()}
    bad = sorted(set(sub) - known)
    if bad:
        raise ValueError(
            f"{field} has no field(s) {bad} (known: {sorted(known)})")


def _state_rule(rule: dict):
    """include/exclude kwarg dict -> NetStateRule (phase accepts 'TRAIN'/
    'TEST' strings, Phase enums, or 0/1 ints)."""
    from .proto.caffe_pb import NetStateRule, Phase
    rule = dict(rule)
    phase = rule.pop("phase", None)
    if isinstance(phase, str):
        phase = Phase[phase]
    elif isinstance(phase, int):
        phase = Phase(phase)
    stage = rule.pop("stage", [])
    not_stage = rule.pop("not_stage", [])
    min_level = rule.pop("min_level", None)
    max_level = rule.pop("max_level", None)
    if rule:
        raise ValueError(f"unknown NetStateRule field(s) {sorted(rule)}")
    return NetStateRule(
        phase=phase,
        min_level=min_level,
        max_level=max_level,
        stage=[stage] if isinstance(stage, str) else list(stage),
        not_stage=([not_stage] if isinstance(not_stage, str)
                   else list(not_stage)),
    )


class Top:
    """A named layer output; bottoms of later layers (net_spec.py Top)."""

    def __init__(self, fn: "Function", n: int):
        self.fn = fn
        self.n = n


class Function:
    """One layer call: type + input Tops + params (net_spec.py Function)."""

    def __init__(self, type_name: str, inputs: tuple, params: dict):
        self.type_name = type_name
        self.inputs = inputs
        for t in inputs:
            if not isinstance(t, Top):
                raise TypeError(
                    f"{type_name} bottoms must be Tops (got {type(t).__name__})"
                    f" — pass n.<blob>, not raw values")
        self.params = dict(params)
        self.ntop = self.params.pop("ntop", 1)
        self.in_place = self.params.pop("in_place", False)
        if self.in_place and (self.ntop != 1 or len(inputs) != 1):
            raise ValueError("in_place requires exactly one bottom and top")
        unknown = [k for k in self.params
                   if k not in _TOP_LEVEL and not k.endswith("_param")
                   and self.type_name not in _TYPE_TO_PARAM]
        if unknown:
            raise ValueError(
                f"layer type {self.type_name!r} has no default param "
                f"message; pass explicit <name>_param=dict(...) for "
                f"{unknown}")
        for k in self.params:
            if k.endswith("_param") and k not in _PARAM_FIELDS:
                raise ValueError(f"unknown LayerParameter field {k!r}")
        # misspelled fields fail NOW, like net_spec's protobuf assignment
        default_field = _TYPE_TO_PARAM.get(self.type_name)
        bare = {k: v for k, v in self.params.items()
                if k not in _TOP_LEVEL and not k.endswith("_param")}
        if bare and default_field:
            _check_param_fields(default_field, bare)
        for k, v in self.params.items():
            if k.endswith("_param") and isinstance(v, dict):
                _check_param_fields(k, v)
        self.tops = tuple(Top(self, i) for i in range(self.ntop))

    def _layer_param(self, names: dict["Top", str],
                     blob_names: dict["Top", str]) -> Any:
        from .models.dsl import layer as dsl_layer

        bottoms = [blob_names[t] for t in self.inputs]
        if self.in_place:
            tops = list(bottoms)
        else:
            tops = [blob_names[t] for t in self.tops]
        top_level: dict[str, Any] = {}
        type_params: dict[str, Any] = {}
        default_field = _TYPE_TO_PARAM.get(self.type_name)
        for k, v in self.params.items():
            if k in _TOP_LEVEL:
                top_level[k] = v
            elif k.endswith("_param"):
                type_params[k] = dict(v)
            else:
                type_params.setdefault(default_field, {})[k] = v
        for field, sub in type_params.items():
            _check_param_fields(field, sub)
        # layer NAME is the assigned attr even in-place (Caffe idiom:
        # name "relu1", bottom/top both "conv1"); blob names differ
        name = names[self.tops[0]]
        lp = dsl_layer(name, self.type_name, bottoms, tops,
                       phase=top_level.get("phase"),
                       param=top_level.get("param"), **type_params)
        if "loss_weight" in top_level:
            lw = top_level["loss_weight"]
            lp.loss_weight = (list(lw) if isinstance(lw, (list, tuple))
                              else [float(lw)])
        for key in ("include", "exclude"):
            if key in top_level:
                rules = top_level[key]
                if isinstance(rules, dict):
                    rules = [rules]
                setattr(lp, key, [_state_rule(r) for r in rules])
        return lp


class _Layers:
    """``L``: attribute access builds layer Functions (net_spec.py layers)."""

    def __getattr__(self, type_name: str):
        def build(*inputs, **params):
            fn = Function(type_name, inputs, params)
            if fn.ntop == 0:
                return fn
            if fn.ntop == 1:
                return fn.tops[0]
            return fn.tops
        return build


class _ParamEnum:
    def __init__(self, prefix: str):
        self._prefix = prefix

    def __getattr__(self, name: str):
        # enums serialize by bare NAME in proto text format (EnumToken);
        # a plain str would be quoted like a string field
        from .proto.textformat import EnumToken
        return EnumToken(name)


class _Params:
    """``P``: enum access, e.g. P.Pooling.MAX -> bare enum token "MAX"
    (net_spec.py params, which resolves protobuf enum values; our config
    tree keeps enum names, tagged so prototxt serialization leaves them
    unquoted)."""

    def __getattr__(self, msg_name: str) -> _ParamEnum:
        return _ParamEnum(msg_name)


layers = _Layers()
params = _Params()


class _ProtoWrapper:
    """to_proto() result: a typed NetParameter whose str() is prototxt
    (the pycaffe idiom ``f.write(str(n.to_proto()))``)."""

    def __init__(self, net_param: NetParameter):
        self.net_param = net_param

    def __str__(self) -> str:
        return serialize(self.net_param.to_pmsg())

    def __getattr__(self, name):
        return getattr(self.net_param, name)


class NetSpec:
    """Named collection of Tops; to_proto() assembles the NetParameter
    (net_spec.py NetSpec)."""

    def __init__(self):
        super().__setattr__("tops", {})

    def __setattr__(self, name: str, value) -> None:
        if not isinstance(value, Top):
            raise TypeError(
                f"NetSpec attributes must be layer Tops (n.{name} = "
                f"L.<Type>(...)); got {type(value).__name__}")
        self.tops[name] = value

    def __getattr__(self, name: str) -> Top:
        try:
            return self.tops[name]
        except KeyError:
            raise AttributeError(name) from None

    def __delattr__(self, name: str) -> None:
        del self.tops[name]

    def to_proto(self) -> _ProtoWrapper:
        # name every reachable Top: assigned names win; autonames for
        # unassigned tops of multi-top functions (net_spec.py to_proto)
        names: dict[Top, str] = {}
        for name, top in self.tops.items():
            names.setdefault(top, name)

        fns: list[Function] = []
        seen: set[int] = set()

        def visit(fn: Function) -> None:
            if id(fn) in seen:
                return
            seen.add(id(fn))
            for t in fn.inputs:
                visit(t.fn)
            fns.append(fn)

        for top in self.tops.values():
            visit(top.fn)
        autonum = 0
        for fn in fns:
            for t in fn.tops:
                if t not in names:
                    if t is fn.tops[0]:
                        names[t] = f"{fn.type_name.lower()}{autonum}"
                        autonum += 1
                    else:
                        names[t] = f"{names[fn.tops[0]]}_top{t.n}"

        # blob name: an in-place chain keeps the original bottom's blob
        # (the assigned attr still names the LAYER, net_spec semantics)
        blob_names: dict[Top, str] = {}

        def blob_name(t: Top) -> str:
            if t not in blob_names:
                blob_names[t] = (blob_name(t.fn.inputs[0])
                                 if t.fn.in_place else names[t])
            return blob_names[t]

        for fn in fns:
            for t in list(fn.inputs) + list(fn.tops):
                blob_name(t)
        layer_params = [fn._layer_param(names, blob_names) for fn in fns]
        return _ProtoWrapper(NetParameter(name="", layer=layer_params))
