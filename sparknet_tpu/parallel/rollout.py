"""Canary rollout controller: promote on judged health, auto-roll back
on sustained SLO breach, survive our own death (WALKTHROUGH §6.20).

The deployment plane has three layers with one source of truth each:

- the **registry** (:mod:`.registry`) owns artifacts and the per-model
  ``channels.json`` (stable/canary pointers + canary weight);
- the **router** mirrors the channel file as a :class:`.router
  .RolloutState` — weighted, deterministic, pin-respecting placement;
- this controller owns the TRANSITIONS between channel states, and
  journals every transition to ``rollout.jsonl`` BEFORE applying it.

State machine per model (weight only ever non-zero inside CANARY)::

    STABLE --start_canary--> CANARY --promote---> STABLE (new version)
                               |
                               +--rollback-----> STABLE (old version)

The judge is the PR 9 burn-rate discipline applied per version: each
poll reads the canary's own :class:`~.serving.SLOMonitor` verdict (which
is already multi-window with a minimum-request floor — blips never
page); only ``breach_polls`` CONSECUTIVE breach verdicts trigger
rollback, and promotion requires ``judge_s`` of sustained health over at
least ``min_requests`` observed requests.  An optional ``bands`` hook
feeds perfwatch-style regression verdicts into the same judgment.

Rollback discipline, in order: canary traffic weight → 0 (router first —
stop the bleeding), channel pointer reverted (the durable truth), canary
replicas drained through the PR 11 drain fences (admitted work still
completes), flight-recorder dump + ``rollout_events_total`` metrics.

**Crash consistency.**  Every transition is write-ahead journaled
(``*_begin`` line, fsync, apply, ``*_done`` line).  ``resume`` replays
the journal: a ``promote_begin`` without its ``done`` is re-applied to
completion (fully promoted), any other in-flight state rolls back to
fully stable — a dead controller means NOBODY is judging the canary, so
traffic must not keep flowing to it.  Either way a scheduler death
mid-rollout recovers to exactly one of {fully stable, fully promoted},
never a half-promoted fleet, and replaying twice is a no-op.

Env knobs (defaults in :class:`RolloutConfig`):
  SPARKNET_ROLLOUT_CANARY_FRACTION — traffic share a new canary starts
                                     with (0.1).
  SPARKNET_ROLLOUT_JUDGE_S         — sustained-health seconds before
                                     promote (8).
  SPARKNET_ROLLOUT_POLL_S          — judge poll interval (0.5).
  SPARKNET_ROLLOUT_MIN_REQUESTS    — observed-request floor before
                                     promote (20).
  SPARKNET_ROLLOUT_BREACH_POLLS    — consecutive breach verdicts that
                                     trigger rollback (2).
"""

from __future__ import annotations

import dataclasses
import json
import os
import time
from typing import Any, Callable

from ..utils import knobs, telemetry
from .registry import ModelRegistry, versioned
from .router import RolloutState

__all__ = ["RolloutError", "RolloutConfig", "RolloutController",
           "replay", "status", "JOURNAL"]

JOURNAL = "rollout.jsonl"
_JOURNAL_VERSION = 1


class RolloutError(RuntimeError):
    """A rollout operation that cannot proceed (no stable baseline,
    canary == stable, no canary in flight, ...)."""


def _env_f(name: str, default: float) -> float:
    raw = knobs.raw(name)
    return float(raw) if raw else default


def _env_i(name: str, default: int) -> int:
    raw = knobs.raw(name)
    return int(raw) if raw else default


@dataclasses.dataclass(frozen=True)
class RolloutConfig:
    fraction: float = 0.1       # initial canary traffic share
    judge_s: float = 8.0        # sustained health before promote
    poll_s: float = 0.5         # judge poll interval
    min_requests: int = 20      # observed-request floor before promote
    breach_polls: int = 2       # consecutive breach verdicts -> rollback

    def __post_init__(self):
        if not 0.0 < self.fraction <= 1.0:
            raise ValueError(f"canary fraction must be in (0, 1], "
                             f"got {self.fraction}")
        if self.judge_s <= 0 or self.poll_s <= 0:
            raise ValueError("judge_s and poll_s must be > 0")
        if self.min_requests < 1 or self.breach_polls < 1:
            raise ValueError("min_requests and breach_polls must be >= 1")

    @classmethod
    def from_env(cls) -> "RolloutConfig":
        return cls(
            fraction=_env_f("SPARKNET_ROLLOUT_CANARY_FRACTION", 0.1),
            judge_s=_env_f("SPARKNET_ROLLOUT_JUDGE_S", 8.0),
            poll_s=_env_f("SPARKNET_ROLLOUT_POLL_S", 0.5),
            min_requests=_env_i("SPARKNET_ROLLOUT_MIN_REQUESTS", 20),
            breach_polls=_env_i("SPARKNET_ROLLOUT_BREACH_POLLS", 2))


class RolloutController:
    """Drives one registry's channel transitions (see module docstring).

    The fleet wiring is injected, so the controller is deployment-shape
    blind:

    ``ensure(name)``
        bring replicas serving versioned name ``name`` up (idempotent —
        resume re-ensures).
    ``retire(name)``
        drain replicas serving ``name`` through the router's drain
        fences and release them (idempotent; absent name is a no-op —
        resume retires versions whose replicas may never have existed).
    ``verdict(name)``
        the per-version SLO verdict doc for ``name`` (the
        ``SLOMonitor.evaluate()`` shape: ``{"state": "ok"|"breach",
        "windows": {...}, ...}``), or None when not yet measurable.
    ``bands(name)`` (optional)
        perfwatch-style band violations for ``name`` as a list of
        reason strings; non-empty judges as a breach poll.
    """

    def __init__(self, registry: ModelRegistry, workdir: str, *,
                 ensure: Callable[[str], Any],
                 retire: Callable[[str], Any],
                 verdict: Callable[[str], dict | None],
                 bands: Callable[[str], list] | None = None,
                 router=None, cfg: RolloutConfig | None = None,
                 clock: Callable[[], float] = time.monotonic):
        self.registry = registry
        self.workdir = os.path.abspath(workdir)
        self.path = os.path.join(self.workdir, JOURNAL)
        self.ensure = ensure
        self.retire = retire
        self.verdict = verdict
        self.bands = bands
        self.router = router
        self.cfg = cfg or RolloutConfig.from_env()
        self._clock = clock
        self._seq = sum(1 for _ in _read_journal(self.path))
        self._streak: dict[str, int] = {}        # consecutive breaches
        self._healthy_since: dict[str, float] = {}
        self._last_verdict: dict[str, Any] = {}  # last JOURNALED state
        self._m_events = telemetry.get_registry().counter(
            "rollout_events_total", "rollout decision-log events by kind")

    # -- the decision log -------------------------------------------------
    def _log(self, ev: str, model: str, **kw: Any) -> None:
        """Append one decision record — fsynced BEFORE the transition it
        describes is applied (write-ahead: resume must never learn less
        than the fleet already did)."""
        rec = {"v": _JOURNAL_VERSION, "seq": self._seq,
               "t": time.time(), "ev": ev, "model": model, **kw}
        self._seq += 1
        os.makedirs(self.workdir, exist_ok=True)
        with open(self.path, "a") as f:
            f.write(json.dumps(rec) + "\n")
            f.flush()
            os.fsync(f.fileno())
        self._m_events.inc(ev=ev)
        telemetry.get_recorder().record(f"rollout_{ev}", model=model,
                                        **{k: v for k, v in kw.items()
                                           if isinstance(v, (str, int,
                                                             float))})

    # -- transitions ------------------------------------------------------
    def start_canary(self, model: str, version: str,
                     weight: float | None = None) -> dict[str, Any]:
        """Open a canary: ``version`` takes ``weight`` of ``model``'s
        plain-name traffic (default SPARKNET_ROLLOUT_CANARY_FRACTION)."""
        ch = self.registry.channels(model)
        if ch["stable"] is None:
            raise RolloutError(
                f"model {model!r} has no stable version to canary "
                f"against — set the stable channel first (a canary with "
                f"no baseline has nothing to roll back TO)")
        if ch["stable"] == version:
            raise RolloutError(
                f"model {model!r}: version {version} IS the stable "
                f"version — nothing to roll out")
        if ch["canary"] is not None and ch["canary"] != version:
            raise RolloutError(
                f"model {model!r} already has canary {ch['canary']} in "
                f"flight — promote or roll it back first")
        self.registry.manifest(model, version)   # typed when unpublished
        w = self.cfg.fraction if weight is None else float(weight)
        self._log("canary_begin", model, version=version, weight=w,
                  stable=ch["stable"])
        self._apply_canary(model, ch["stable"], version, w)
        self._log("canary_live", model, version=version, weight=w,
                  stable=ch["stable"])
        self._streak[model] = 0
        self._healthy_since.pop(model, None)
        return {"model": model, "stable": ch["stable"], "canary": version,
                "weight": w}

    def _apply_canary(self, model: str, stable: str, canary: str,
                      weight: float) -> None:
        self.ensure(versioned(model, stable))
        self.ensure(versioned(model, canary))
        self.registry.set_channels(model, stable=stable, canary=canary,
                                   weight=weight)
        if self.router is not None:
            self.router.set_rollout(RolloutState(
                model=model, stable=stable, canary=canary, weight=weight))

    def judge(self, model: str) -> str:
        """One judge poll: ``"canary"`` (keep watching), ``"promote"``
        (sustained health), or ``"rollback"`` (sustained breach)."""
        ch = self.registry.channels(model)
        if ch["canary"] is None:
            raise RolloutError(f"model {model!r} has no canary in "
                               f"flight — nothing to judge")
        name = versioned(model, ch["canary"])
        v = self.verdict(name)
        violations = list(self.bands(name)) if self.bands else []
        state = "none" if v is None else v.get("state", "none")
        breach = state == "breach" or bool(violations)
        if self._last_verdict.get(model) != (state, bool(violations)):
            # journal verdict TRANSITIONS only (a long canary must not
            # grow the journal by poll count)
            self._last_verdict[model] = (state, bool(violations))
            self._log("judge", model, version=ch["canary"], state=state,
                      band_violations=len(violations))
        if breach:
            self._streak[model] = self._streak.get(model, 0) + 1
            self._healthy_since.pop(model, None)
            if self._streak[model] >= self.cfg.breach_polls:
                return "rollback"
            return "canary"
        self._streak[model] = 0
        now = self._clock()
        since = self._healthy_since.setdefault(model, now)
        windows = (v or {}).get("windows") or {}
        seen = max(int((windows.get("slow") or {}).get("requests", 0)),
                   int((windows.get("fast") or {}).get("requests", 0)))
        if now - since >= self.cfg.judge_s and seen >= self.cfg.min_requests:
            return "promote"
        return "canary"

    def promote(self, model: str) -> dict[str, Any]:
        """The canary becomes stable; the old stable drains away."""
        ch = self.registry.channels(model)
        if ch["canary"] is None:
            raise RolloutError(f"model {model!r} has no canary in "
                               f"flight — nothing to promote")
        self._log("promote_begin", model, version=ch["canary"],
                  stable=ch["stable"])
        self._apply_promote(model, ch["stable"], ch["canary"])
        self._log("promote_done", model, version=ch["canary"],
                  stable=ch["canary"])
        return self.registry.channels(model)

    def _apply_promote(self, model: str, old_stable: str | None,
                       canary: str) -> None:
        self.ensure(versioned(model, canary))  # before the pointer moves
        self.registry.set_channels(model, stable=canary, canary=None,
                                   weight=0.0)
        if self.router is not None:
            # stable-only state stays installed: in a fully versioned
            # fleet the plain name must keep resolving to SOME version
            self.router.set_rollout(RolloutState(model=model,
                                                 stable=canary))
        if old_stable and old_stable != canary:
            self.retire(versioned(model, old_stable))

    def rollback(self, model: str, reason: str) -> dict[str, Any]:
        """Traffic off, pointer reverted, canary drained, evidence kept
        (flight dump) — in that order."""
        ch = self.registry.channels(model)
        if ch["canary"] is None:
            raise RolloutError(f"model {model!r} has no canary in "
                               f"flight — nothing to roll back")
        self._log("rollback_begin", model, version=ch["canary"],
                  stable=ch["stable"], reason=reason)
        self._apply_rollback(model, ch["stable"], ch["canary"], reason)
        self._log("rollback_done", model, version=ch["canary"],
                  stable=ch["stable"], reason=reason)
        return self.registry.channels(model)

    def _apply_rollback(self, model: str, stable: str | None,
                        canary: str | None, reason: str) -> None:
        if self.router is not None:
            # stop the bleeding FIRST: pending placements go all-stable
            # before the durable pointer or the drain move (the
            # stable-only state stays installed so the plain name keeps
            # resolving in a fully versioned fleet)
            if stable:
                self.router.set_rollout(RolloutState(model=model,
                                                     stable=stable))
            else:
                self.router.clear_rollout(model)
        self.registry.set_channels(model, canary=None, weight=0.0)
        if canary:
            self.retire(versioned(model, canary))
        rec = telemetry.get_recorder()
        rec.record("rollout_rollback", model=model, version=canary,
                   reason=reason)
        rec.dump("rollout_rollback")   # the evidence survives us
        self._streak.pop(model, None)
        self._healthy_since.pop(model, None)

    # -- recovery ---------------------------------------------------------
    def resume(self) -> dict[str, str]:
        """Replay the journal to a consistent terminal state per model
        (see module docstring); returns ``{model: action}`` where action
        is ``"promoted"`` / ``"rolled_back"`` / ``"consistent"``."""
        out: dict[str, str] = {}
        for model, st in replay(self.path).items():
            if st["phase"] == "promoting":
                # the decision to promote was durably made: finish it
                self._apply_promote(model, st["stable"], st["canary"])
                self._log("promote_done", model, version=st["canary"],
                          stable=st["canary"], resumed=True)
                out[model] = "promoted"
            elif st["phase"] in ("canary_starting", "canary",
                                 "rolling_back"):
                # nobody was judging while we were dead — an unjudged
                # canary must not keep taking traffic
                reason = (st.get("last_rollback_reason")
                          or "controller death mid-canary")
                self._apply_rollback(model, st["stable"], st["canary"],
                                     reason)
                self._log("rollback_done", model, version=st["canary"],
                          stable=st["stable"], reason=reason,
                          resumed=True)
                out[model] = "rolled_back"
            else:
                out[model] = "consistent"
        return out

    # -- the closed loop --------------------------------------------------
    def run(self, model: str, version: str, weight: float | None = None,
            timeout_s: float | None = None) -> str:
        """start_canary + judge-poll until terminal.  Returns
        ``"promoted"`` or ``"rolled_back"``; a timeout rolls back (an
        undecidable canary is a failed canary)."""
        self.start_canary(model, version, weight)
        deadline = (None if timeout_s is None
                    else self._clock() + timeout_s)
        while True:
            d = self.judge(model)
            if d == "promote":
                self.promote(model)
                return "promoted"
            if d == "rollback":
                self.rollback(model,
                              reason=f"sustained SLO breach "
                                     f"({self.cfg.breach_polls} polls)")
                return "rolled_back"
            if deadline is not None and self._clock() > deadline:
                self.rollback(model, reason="judge timeout — canary "
                                            "never became promotable")
                return "rolled_back"
            time.sleep(self.cfg.poll_s)


# ---------------------------------------------------------------------------
# Journal replay (also the offline-status path: works with the
# controller dead, which is exactly when status matters most)
# ---------------------------------------------------------------------------

def _read_journal(path: str):
    try:
        f = open(path)
    except OSError:
        return
    with f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                return   # torn tail: everything before it still counts
            if isinstance(rec, dict):
                yield rec


def replay(path: str) -> dict[str, dict[str, Any]]:
    """Fold ``rollout.jsonl`` into per-model channel state:
    ``{model: {phase, stable, canary, weight, last_verdict,
    last_rollback_reason, events}}``.  Unknown events are skipped (a
    newer controller's journal still replays for status)."""
    out: dict[str, dict[str, Any]] = {}
    for rec in _read_journal(path):
        m = rec.get("model")
        if not m:
            continue
        st = out.setdefault(m, {
            "phase": "idle", "stable": None, "canary": None,
            "weight": 0.0, "last_verdict": None,
            "last_rollback_reason": None, "events": 0})
        st["events"] += 1
        ev = rec.get("ev")
        if ev == "canary_begin":
            st.update(phase="canary_starting", canary=rec.get("version"),
                      stable=rec.get("stable", st["stable"]),
                      weight=rec.get("weight", 0.0))
        elif ev == "canary_live":
            st["phase"] = "canary"
        elif ev == "judge":
            st["last_verdict"] = rec.get("state")
        elif ev == "promote_begin":
            st["phase"] = "promoting"
        elif ev == "promote_done":
            st.update(phase="stable",
                      stable=rec.get("stable", st["canary"]),
                      canary=None, weight=0.0)
        elif ev == "rollback_begin":
            st["phase"] = "rolling_back"
            st["last_rollback_reason"] = rec.get("reason")
        elif ev == "rollback_done":
            st.update(phase="stable", canary=None, weight=0.0,
                      last_rollback_reason=rec.get(
                          "reason", st["last_rollback_reason"]))
    return out


def status(workdir: str) -> dict[str, dict[str, Any]] | None:
    """The rollout section for ``tools/fleet.py status``: journal-replayed
    per-model channel state, or None when this workdir never rolled
    anything out."""
    state = replay(os.path.join(os.path.abspath(workdir), JOURNAL))
    return state or None
