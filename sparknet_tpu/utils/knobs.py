"""Typed registry for every ``SPARKNET_*`` configuration knob.

The env surface grew one knob at a time across twelve PRs; this module
makes it a declared contract instead of folklore.  Every knob has a
name, type, default, one-line doc, and an owner module; the registry is
the single source of truth for

- **runtime reads** — production code reads knobs through :func:`raw` /
  :func:`get_int` / :func:`get_float` / :func:`get_bool` (or a helper
  that delegates here).  Reading a name that was never registered
  raises :class:`UnknownKnob` — a typo'd knob fails loudly instead of
  silently meaning "default".
- **static enforcement** — ``sparknet_tpu/analysis`` (rule family KR)
  flags env reads that bypass the registry, reads of unregistered
  names, and registered-but-never-read knobs (dead registrations).
- **docs** — ``KNOBS.md`` is emitted from this table
  (``tools/lint.py knobs --emit``) and drift-gated in CI
  (``knobs --check``).
- **deprecation** — a knob marked ``deprecated`` lints as a warning
  (DP001) for one release; once ``removed`` it stays registered as a
  tombstone so any surviving mention fails lint (DP002) and a runtime
  read raises :class:`RemovedKnob` naming the replacement.

Design constraints: imports nothing from the rest of ``sparknet_tpu``
(safe to import from anywhere, including ``utils`` leaves), and never
caches values — every accessor reads ``os.environ`` live, so tests
that monkeypatch the env keep working and the existing latch-at-trace/
latch-at-construction semantics stay where they are implemented today
(tuner, fusion, Net), not here.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Callable, Iterable

__all__ = [
    "Knob", "KnobError", "UnknownKnob", "RemovedKnob", "InvalidKnobValue",
    "get", "all_knobs", "raw", "is_set", "get_str", "get_int", "get_float",
    "get_bool", "knobs_md", "DEPRECATED_SYMBOLS",
]


class KnobError(Exception):
    """Base for knob-registry errors."""


class UnknownKnob(KnobError, KeyError):
    """An env read of a SPARKNET_* name that was never registered."""


class RemovedKnob(KnobError, KeyError):
    """An env read of a knob whose deprecation window has closed."""


class InvalidKnobValue(KnobError, ValueError):
    """A set knob whose value does not parse as the registered type."""


@dataclasses.dataclass(frozen=True)
class Knob:
    """One registered env knob (see module docstring for the contract)."""

    name: str
    type: str                  # bool | int | float | str | enum | path | spec
    default: str               # unset-behavior, in env spelling ("" = unset)
    doc: str                   # one line, imperative, shows up in KNOBS.md
    owner: str                 # repo-relative path of the owning module
    choices: tuple[str, ...] = ()          # for type == "enum"
    validator: Callable[[str], object] | None = None
    deprecated: str = ""       # window OPEN:  "r<N>: use X instead"
    removed: str = ""          # window CLOSED: "r<N>: use X instead"


_REGISTRY: dict[str, Knob] = {}


def _register(*knobs: Knob) -> None:
    for k in knobs:
        if k.name in _REGISTRY:
            raise ValueError(f"duplicate knob registration: {k.name}")
        _REGISTRY[k.name] = k


def get(name: str) -> Knob:
    """The registry entry for ``name``; raises :class:`UnknownKnob` /
    :class:`RemovedKnob` — the same check every accessor runs first."""
    try:
        k = _REGISTRY[name]
    except KeyError:
        raise UnknownKnob(
            f"{name} is not a registered knob — add it to "
            f"sparknet_tpu/utils/knobs.py (and KNOBS.md via "
            f"`python tools/lint.py knobs --emit`)") from None
    if k.removed:
        raise RemovedKnob(f"{name} was removed ({k.removed})")
    return k


def all_knobs() -> list[Knob]:
    """Every registered knob (tombstones included), sorted by name."""
    return sorted(_REGISTRY.values(), key=lambda k: k.name)


def raw(name: str, default: str | None = None) -> str | None:
    """Registry-checked ``os.environ.get``.  The one primitive every
    other accessor (and the module-local ``_env_*`` helpers that
    delegate here) bottoms out in."""
    get(name)
    return os.environ.get(name, default)


def is_set(name: str) -> bool:
    """True when the knob is present AND non-empty."""
    return bool(raw(name))


def get_str(name: str, default: str = "") -> str:
    val = raw(name)
    return default if val is None or val == "" else val


def get_int(name: str, default: int) -> int:
    val = raw(name)
    if val is None or val == "":
        return default
    try:
        return int(val)
    except ValueError:
        raise InvalidKnobValue(
            f"{name} must be an integer, got {val!r}") from None


def get_float(name: str, default: float) -> float:
    val = raw(name)
    if val is None or val == "":
        return default
    try:
        return float(val)
    except ValueError:
        raise InvalidKnobValue(
            f"{name} must be a number, got {val!r}") from None


def get_bool(name: str, default: bool) -> bool:
    """Tri-state env bool: ``"0"`` -> False, ``"1"`` -> True, unset or
    anything else -> ``default``.  Sites with historical one-sided
    parses (``== "1"`` opt-ins, ``!= "1"`` opt-outs) compare
    :func:`raw` directly to keep their exact semantics."""
    val = raw(name)
    if val == "0":
        return False
    if val == "1":
        return True
    return default


# ---------------------------------------------------------------------------
# The registry.  Grouped by owner; keep each doc to one line — it becomes
# the KNOBS.md table.  default "" means "unset", with the unset behavior
# stated in the doc line.
# ---------------------------------------------------------------------------

_register(
    # --- graph: lowering autotuner (WALKTHROUGH §6.15) ---
    Knob("SPARKNET_TUNE", "enum", "auto",
         "Lowering-table mode: off = built-in defaults, auto = committed "
         "profiles/<backend>/tuning.json, else a table path.",
         "sparknet_tpu/graph/tuner.py", choices=("off", "auto", "<path>")),
    Knob("SPARKNET_TUNE_REPS", "int", "5",
         "Timed repetitions per tuning candidate.",
         "sparknet_tpu/graph/tuner.py"),
    Knob("SPARKNET_TUNE_TARGET_S", "float", "0.1",
         "Target measured seconds per candidate (reps auto-scale down).",
         "sparknet_tpu/graph/tuner.py"),
    Knob("SPARKNET_TUNE_WARMUP", "int", "2",
         "Untimed warmup iterations per tuning candidate.",
         "sparknet_tpu/graph/tuner.py"),
    # --- graph: fusion + structure toggles ---
    Knob("SPARKNET_FUSE", "enum", "auto",
         "Vertical fusion plan: off/0 = unfused, auto = committed profile "
         "worklist, all = every legal chain, else a plan-file path.",
         "sparknet_tpu/graph/fusion.py",
         choices=("off", "0", "auto", "all", "<path>")),
    Knob("SPARKNET_NO_HFUSE", "bool", "",
         "Set to 1 to disable horizontal inception-branch fusion "
         "(latched at Net construction).",
         "sparknet_tpu/graph/net.py"),
    Knob("SPARKNET_NO_S2D", "bool", "",
         "Set to 1 to disable the space-to-depth stem conv rewrite.",
         "sparknet_tpu/ops/vision.py"),
    Knob("SPARKNET_PALLAS_MAXPOOL", "bool", "",
         "Set to 1 to opt in to the Pallas maxpool backward kernel on TPU.",
         "sparknet_tpu/ops/vision.py"),
    Knob("SPARKNET_PALLAS_LRN", "bool", "",
         "Set to 1 to opt in to the Pallas cross-channel LRN kernel on TPU.",
         "sparknet_tpu/ops/vision.py"),
    # --- chaos / fault injection ---
    Knob("SPARKNET_FAULT", "spec", "",
         "Comma-separated fault specs (e.g. crash_after:3,slow_feed:200ms) "
         "injected by utils.faults; empty = no chaos.",
         "sparknet_tpu/utils/faults.py"),
    Knob("SPARKNET_FAULT_ATTEMPT", "int", "0",
         "Relaunch attempt index; faults can gate on it so a fault fires "
         "once, not on every restart.",
         "sparknet_tpu/utils/faults.py"),
    # --- cluster bring-up / launcher contract ---
    Knob("SPARKNET_COORDINATOR", "str", "",
         "Coordinator address for jax.distributed; set with NUM_PROCS and "
         "PROC_ID together (launcher env contract).",
         "sparknet_tpu/parallel/cluster.py"),
    Knob("SPARKNET_NUM_PROCS", "int", "",
         "World size under the launcher env contract.",
         "sparknet_tpu/parallel/cluster.py"),
    Knob("SPARKNET_PROC_ID", "int", "0",
         "This process's rank under the launcher env contract; also the "
         "telemetry/heartbeat shard rank.",
         "sparknet_tpu/parallel/cluster.py"),
    Knob("SPARKNET_CONNECT_RETRIES", "int", "3",
         "Coordinator connect attempts (TIME_WAIT races on relaunch).",
         "sparknet_tpu/parallel/cluster.py"),
    Knob("SPARKNET_CONNECT_BACKOFF", "float", "0.5",
         "Base seconds for exponential connect backoff.",
         "sparknet_tpu/parallel/cluster.py"),
    Knob("SPARKNET_CONNECT_JITTER", "float", "0.25",
         "Jitter fraction on connect backoff (de-lockstep relaunched "
         "ranks).",
         "sparknet_tpu/parallel/cluster.py"),
    # --- resilience / supervision ---
    Knob("SPARKNET_RESTART_COUNT", "int", "0",
         "Exported by the supervisor to relaunched children: restarts so "
         "far.",
         "sparknet_tpu/parallel/resilience.py"),
    Knob("SPARKNET_INCARNATION", "int", "0",
         "Elastic re-form incarnation, exported to children and stamped "
         "on telemetry.",
         "sparknet_tpu/parallel/resilience.py"),
    Knob("SPARKNET_HEARTBEAT_DIR", "path", "",
         "Directory for liveness beat files; empty disables the health "
         "plane.",
         "sparknet_tpu/parallel/health.py"),
    Knob("SPARKNET_LEASE_S", "float", "2",
         "Heartbeat lease duration: a host whose relayed beats are older "
         "than LEASE_S * LEASE_MISSES is SUSPECT (suspended, never "
         "killed) until it heals or a down-probe confirms death.",
         "sparknet_tpu/parallel/health.py"),
    Knob("SPARKNET_LEASE_MISSES", "int", "3",
         "Consecutive missed leases before a host turns SUSPECT.",
         "sparknet_tpu/parallel/health.py"),
    # --- host transport (the remote half of the pod fleet) ---
    Knob("SPARKNET_SSH_CMD", "str", "",
         "ssh binary for the SshTransport wire path (default 'ssh'); "
         "point it at a local fake-ssh script to drive the real remote "
         "argv/env/stdio plumbing in CI without an sshd.  Setting it "
         "also makes named-but-loopback addresses (127.0.0.1, "
         "localhost) take the ssh path.",
         "sparknet_tpu/parallel/transport.py"),
    Knob("SPARKNET_SHIP_CHUNK_MB", "float", "4",
         "Chunk size (MB) for crc-verified artifact/checkpoint shipping "
         "ranged reads.",
         "sparknet_tpu/parallel/transport.py"),
    Knob("SPARKNET_SHIP_RETRIES", "int", "4",
         "Attempts for one artifact ship (resumable: each retry keeps "
         "the destination's valid prefix).",
         "sparknet_tpu/parallel/transport.py"),
    Knob("SPARKNET_FENCE_BASE", "int", "0",
         "Fleet-stamped incarnation fence base (episode * 1e5); the "
         "runner adds its attempt number to mint SPARKNET_FENCE_TOKEN. "
         "0/unset = fencing off.",
         "sparknet_tpu/parallel/resilience.py"),
    Knob("SPARKNET_FENCE_TOKEN", "int", "0",
         "This writer's incarnation fence token: checkpoint dirs refuse "
         "publishes from tokens below the dir's claimed fence (the "
         "zombie-writer guard).  Minted by the launch stack, not set by "
         "hand.",
         "sparknet_tpu/utils/checkpoint.py"),
    # --- checkpointing / IO ---
    Knob("SPARKNET_ASYNC_CKPT", "bool", "1",
         "Set to 0 to force synchronous checkpoint writes (default "
         "async).",
         "sparknet_tpu/utils/checkpoint.py"),
    Knob("SPARKNET_IO_RETRIES", "int", "3",
         "Attempts for retryable storage IO (io_retry policy).",
         "sparknet_tpu/utils/retry.py"),
    Knob("SPARKNET_IO_BACKOFF", "float", "0.05",
         "Base seconds for storage IO retry backoff.",
         "sparknet_tpu/utils/retry.py"),
    # --- telemetry plane ---
    Knob("SPARKNET_TELEMETRY", "bool", "1",
         "Set to 0 to no-op the whole telemetry plane (metrics, spans, "
         "flight recorder).",
         "sparknet_tpu/utils/telemetry.py"),
    Knob("SPARKNET_TELEMETRY_RANK", "int", "",
         "Telemetry shard rank for processes outside the launcher "
         "contract; wins over PROC_ID.",
         "sparknet_tpu/utils/telemetry.py"),
    Knob("SPARKNET_TRACE_DIR", "path", "",
         "Write Chrome-trace JSONL shards and flight dumps here; empty "
         "disables tracing.",
         "sparknet_tpu/utils/telemetry.py"),
    Knob("SPARKNET_METRICS_SNAP", "path", "",
         "Write metrics_rank*.json/.prom snapshots here; empty disables.",
         "sparknet_tpu/utils/telemetry.py"),
    Knob("SPARKNET_METRICS_SNAP_S", "float", "2",
         "Minimum seconds between metrics snapshots.",
         "sparknet_tpu/utils/telemetry.py"),
    Knob("SPARKNET_FLIGHT_EVENTS", "int", "256",
         "Flight-recorder ring size.",
         "sparknet_tpu/utils/telemetry.py"),
    Knob("SPARKNET_RUN_ID", "str", "",
         "Correlation run id stamped on all telemetry; derived per "
         "process when unset.",
         "sparknet_tpu/utils/telemetry.py"),
    Knob("SPARKNET_FLEET_JOB", "str", "",
         "Fleet job tag exported to tenant processes; joins their "
         "telemetry to the scheduler's story.",
         "sparknet_tpu/parallel/fleet.py"),
    Knob("SPARKNET_FLEET_HOSTS", "spec", "",
         "Host inventory for multi-host placement: "
         "'name=devices[@addr],...' inline or a path to a JSON list of "
         "{name, devices, addr}; unset = single-host device budget.",
         "sparknet_tpu/parallel/fleet.py"),
    Knob("SPARKNET_FLEET_HOST", "str", "",
         "Host label the launcher stamps on each worker (the gang's "
         "primary host for fleet tenants); joins per-host telemetry "
         "and heartbeats to the placement story.",
         "sparknet_tpu/tools/launch.py"),
    Knob("SPARKNET_FLEET_HOSTVEC", "str", "",
         "Comma-separated per-slot host labels of the gang's placement, "
         "exported to fleet tenant processes.",
         "sparknet_tpu/parallel/fleet.py"),
    # --- data plane ---
    Knob("SPARKNET_QUARANTINE_FRACTION", "float", "0",
         "Max fraction of an epoch the decode quarantine may swallow.",
         "sparknet_tpu/data/integrity.py"),
    Knob("SPARKNET_QUARANTINE_RECORDS", "int", "0",
         "Absolute quarantined-record budget added to the fraction.",
         "sparknet_tpu/data/integrity.py"),
    Knob("SPARKNET_FEED_WORKERS", "int", "",
         "Decode-pool width; 0 = serial reference path; unset = cpu "
         "count capped at 8.",
         "sparknet_tpu/data/pipeline.py"),
    Knob("SPARKNET_FEED_DEPTH", "int", "4",
         "Prefetch queue depth (batches).",
         "sparknet_tpu/data/pipeline.py"),
    Knob("SPARKNET_FEED_PUTTERS", "int", "2",
         "Device-put staging threads in DeviceFeeder.",
         "sparknet_tpu/data/prefetch.py"),
    Knob("SPARKNET_FEED_STALL_S", "float", "",
         "Feeder stall detector timeout in seconds; unset disables.",
         "sparknet_tpu/data/prefetch.py"),
    Knob("SPARKNET_RECORD_READERS", "int", "",
         "Ranged-read pool width for record-shard feeds; 0 = serial "
         "reference path; unset = SPARKNET_FEED_WORKERS.",
         "sparknet_tpu/data/records.py"),
    Knob("SPARKNET_RECORD_SHARD_MB", "int", "64",
         "Shard roll size in MiB for the record-shard converter.",
         "sparknet_tpu/data/records.py"),
    Knob("SPARKNET_CACHE_SHARDS", "int", "4",
         "RAM tier of the ShardCache: resident shard count before LRU "
         "eviction (evictees spill to disk when spill is enabled).",
         "sparknet_tpu/data/pipeline.py"),
    Knob("SPARKNET_CACHE_SPILL_DIR", "path", "",
         "Disk spill tier directory for ShardCache evictees; unset "
         "disables the spill tier (evict = drop).",
         "sparknet_tpu/data/pipeline.py"),
    Knob("SPARKNET_CACHE_SPILL_SHARDS", "int", "16",
         "Max shards held in the ShardCache disk spill tier (oldest "
         "spill files deleted beyond it).",
         "sparknet_tpu/data/pipeline.py"),
    Knob("SPARKNET_AUG_DEVICE", "bool", "1",
         "Run crop/mirror/mean/scale augmentation inside the compiled "
         "train step (host ships raw uint8); 0 = host-side numpy path "
         "(bit-identical at the same seed).",
         "sparknet_tpu/solvers/solver.py"),
    # --- serving engine ---
    Knob("SPARKNET_SERVE_SHAPES", "spec", "1,4,16,64",
         "Padded batch shapes the engine pre-compiles "
         "(comma-separated ints).",
         "sparknet_tpu/parallel/serving.py"),
    Knob("SPARKNET_SERVE_MAX_DELAY_MS", "float", "5.0",
         "Micro-batching window: max milliseconds a request waits for "
         "batchmates.",
         "sparknet_tpu/parallel/serving.py"),
    Knob("SPARKNET_SERVE_QUEUE", "int", "256",
         "Admission queue depth; beyond it requests get typed "
         "rejections.",
         "sparknet_tpu/parallel/serving.py"),
    Knob("SPARKNET_SERVE_INFLIGHT", "int", "2",
         "Dispatched-but-not-demuxed batch window (async dispatch "
         "pipelining).",
         "sparknet_tpu/parallel/serving.py"),
    Knob("SPARKNET_SERVE_HBM_MB", "float", "2048",
         "HBM budget for resident models (LRU eviction above it).",
         "sparknet_tpu/parallel/serving.py"),
    Knob("SPARKNET_SERVE_DTYPE", "str", "bf16",
         "Serving activation dtype.",
         "sparknet_tpu/parallel/serving.py"),
    Knob("SPARKNET_SERVE_QUOTAS", "spec", "",
         "Per-tenant offered-QPS caps, tenant=qps comma-separated; "
         "* = every unlisted tenant.",
         "sparknet_tpu/parallel/serving.py"),
    Knob("SPARKNET_SERVE_FORCE_ADMIT", "bool", "",
         "Set to 1 to bypass admission control (load-test harness only).",
         "sparknet_tpu/parallel/serving.py"),
    Knob("SPARKNET_SLO_P99_MS", "float", "",
         "Declared p99 latency SLO in ms; unset/0 = latency SLO "
         "undeclared.",
         "sparknet_tpu/parallel/serving.py"),
    Knob("SPARKNET_SLO_REJECT_BUDGET", "float", "0.02",
         "Rejection-rate error budget for SLO burn accounting.",
         "sparknet_tpu/parallel/serving.py"),
    Knob("SPARKNET_SLO_WINDOW_S", "float", "60",
         "Slow burn-rate window seconds.",
         "sparknet_tpu/parallel/serving.py"),
    Knob("SPARKNET_SLO_FAST_S", "float", "5",
         "Fast burn-rate window seconds.",
         "sparknet_tpu/parallel/serving.py"),
    # --- router / autoscaler ---
    Knob("SPARKNET_ROUTER_SPILL_DEPTH", "int", "16",
         "Queue depth at the home replica beyond which the router "
         "spills to the next ring member.",
         "sparknet_tpu/parallel/router.py"),
    Knob("SPARKNET_ROUTER_FAILOVERS", "int", "3",
         "Max alternate replicas tried before a typed routing failure.",
         "sparknet_tpu/parallel/router.py"),
    Knob("SPARKNET_ROUTER_DRAIN_S", "float", "30",
         "Seconds a draining replica keeps answering in-flight work.",
         "sparknet_tpu/parallel/router.py"),
    Knob("SPARKNET_AUTOSCALE_MIN", "int", "1",
         "Replica floor.",
         "sparknet_tpu/parallel/autoscale.py"),
    Knob("SPARKNET_AUTOSCALE_MAX", "int", "4",
         "Replica ceiling (device budget).",
         "sparknet_tpu/parallel/autoscale.py"),
    Knob("SPARKNET_AUTOSCALE_UP_QUEUE", "float", "8.0",
         "Mean queue depth per replica that triggers scale-up.",
         "sparknet_tpu/parallel/autoscale.py"),
    Knob("SPARKNET_AUTOSCALE_DOWN_IDLE_S", "float", "10.0",
         "Idle seconds before a replica is eligible for scale-down.",
         "sparknet_tpu/parallel/autoscale.py"),
    Knob("SPARKNET_AUTOSCALE_COOLDOWN_S", "float", "5.0",
         "Minimum seconds between scaling decisions.",
         "sparknet_tpu/parallel/autoscale.py"),
    Knob("SPARKNET_AUTOSCALE_EVAL_S", "float", "1.0",
         "Policy evaluation period seconds.",
         "sparknet_tpu/parallel/autoscale.py"),
    # --- deployment plane (model registry + canary rollout) ---
    Knob("SPARKNET_REGISTRY_DIR", "path", "",
         "Root of the immutable model registry (version bundles + "
         "per-model channel files). Unset = deployment plane off, "
         "plain by-name serving.",
         "sparknet_tpu/parallel/registry.py"),
    Knob("SPARKNET_ROLLOUT_CANARY_FRACTION", "float", "0.1",
         "Traffic share a newly started canary takes (0, 1].",
         "sparknet_tpu/parallel/rollout.py"),
    Knob("SPARKNET_ROLLOUT_JUDGE_S", "float", "8.0",
         "Sustained-health seconds before the judge promotes a canary.",
         "sparknet_tpu/parallel/rollout.py"),
    Knob("SPARKNET_ROLLOUT_POLL_S", "float", "0.5",
         "Judge poll interval seconds.",
         "sparknet_tpu/parallel/rollout.py"),
    Knob("SPARKNET_ROLLOUT_MIN_REQUESTS", "int", "20",
         "Observed-request floor before a canary is promotable (blips "
         "over tiny samples never decide a rollout).",
         "sparknet_tpu/parallel/rollout.py"),
    Knob("SPARKNET_ROLLOUT_BREACH_POLLS", "int", "2",
         "Consecutive breach verdicts that trigger auto-rollback "
         "(multi-window burn discipline: one blip never pages).",
         "sparknet_tpu/parallel/rollout.py"),
    # --- communication-efficient rounds (trainer τ / codec / overlap) ---
    Knob("SPARKNET_TAU", "int", "",
         "Steps per round for driver-built trainers (comm_config_from_env; "
         "the paper's swept τ knob — unset keeps the config's tau).",
         "sparknet_tpu/parallel/trainer.py"),
    Knob("SPARKNET_COMM_CODEC", "str", "",
         "Weight-delta exchange codec for driver-built trainers: none / "
         "bf16 / int8 / int8_channel (or any comms.register_codec name).",
         "sparknet_tpu/parallel/trainer.py"),
    Knob("SPARKNET_COMM_OVERLAP", "bool", "",
         "Set to 1 to dispatch the encode/exchange/decode tail without "
         "host blocking (overlapped averaging; bit-identical results).",
         "sparknet_tpu/parallel/trainer.py"),
    # --- hybrid model+data sharding (partition rule tables) ---
    Knob("SPARKNET_SHARD", "str", "",
         "Partition rule table for driver-built trainers: off (pure data "
         "parallelism, the default), auto (zoo defaults: FC/inner-product "
         "weights shard across chips, convs replicate), or the path of a "
         "versioned JSON rule table (parallel/partition.py).",
         "sparknet_tpu/parallel/trainer.py"),
    Knob("SPARKNET_SHARD_CKPT", "bool", "",
         "Set to 1 to write round checkpoints in the per-shard layout "
         "(one npz tile per shard + the common npz, every file sha256-"
         "pinned in the manifest); only meaningful with a live shard "
         "plan.",
         "sparknet_tpu/parallel/trainer.py"),
    # --- CI gates (read by the tier-1 runner, not by library code) ---
    Knob("SPARKNET_LINT", "bool", "1",
         "Set to 0 to skip the sparklint gate in tools/run_tier1.sh "
         "(default on).",
         "tools/run_tier1.sh"),
    Knob("SPARKNET_SOAK", "bool", "",
         "Set to 1 to run the 2-run chaos soak smoke in run_tier1.sh.",
         "tools/run_tier1.sh"),
    Knob("SPARKNET_SOAK_SEED", "int", "",
         "Seed override for the chaos soak smoke.",
         "tools/run_tier1.sh"),
    Knob("SPARKNET_FLEETSOAK", "bool", "",
         "Set to 1 to run the 2-job fleet soak smoke in run_tier1.sh.",
         "tools/run_tier1.sh"),
    Knob("SPARKNET_PODSOAK", "bool", "",
         "Set to 1 to run the simulated 3-host pod burn-in slice in "
         "run_tier1.sh.",
         "tools/run_tier1.sh"),
    Knob("SPARKNET_NETSOAK", "bool", "",
         "Set to 1 to run the network chaos burn-in (partition-suspend-"
         "heal + fenced-zombie episodes over the fake-ssh transport) in "
         "run_tier1.sh.",
         "tools/run_tier1.sh"),
    Knob("SPARKNET_SOAK_QPS", "float", "4.0",
         "Pod burn-in base offered QPS (the diurnal curve's mean).",
         "tools/soak.py"),
    Knob("SPARKNET_SOAK_FLASH_X", "float", "2.5",
         "Pod burn-in flash-crowd multiplier over the base QPS.",
         "tools/soak.py"),
    Knob("SPARKNET_SOAK_LEG_S", "float", "4.0",
         "Pod burn-in seconds per traffic leg.",
         "tools/soak.py"),
    Knob("SPARKNET_FEEDBENCH", "bool", "",
         "Set to 1 to run the input-pipeline bench gate in run_tier1.sh.",
         "tools/run_tier1.sh"),
    Knob("SPARKNET_RECORDBENCH", "bool", "",
         "Set to 1 to run the record-shard parity gate (feedbench "
         "--records-leg, clean + corrupt) in run_tier1.sh.",
         "tools/run_tier1.sh"),
    Knob("SPARKNET_ROUNDBENCH", "bool", "",
         "Set to 1 to run the round-overhead bench gate in run_tier1.sh.",
         "tools/run_tier1.sh"),
    Knob("SPARKNET_SERVESMOKE", "bool", "",
         "Set to 1 to run the serving smoke gate in run_tier1.sh.",
         "tools/run_tier1.sh"),
    Knob("SPARKNET_FLEETSERVESMOKE", "bool", "",
         "Set to 1 to run the fleet-serving smoke gate in run_tier1.sh.",
         "tools/run_tier1.sh"),
    Knob("SPARKNET_OBSSMOKE", "bool", "",
         "Set to 1 to run the observability smoke gate in run_tier1.sh.",
         "tools/run_tier1.sh"),
    Knob("SPARKNET_FUSEBENCH", "bool", "",
         "Set to 1 to run the fusion bench gate in run_tier1.sh.",
         "tools/run_tier1.sh"),
    Knob("SPARKNET_TUNEBENCH", "bool", "",
         "Set to 1 to run the autotuner loop gate in run_tier1.sh.",
         "tools/run_tier1.sh"),
    Knob("SPARKNET_PERFGATE", "bool", "",
         "Set to 1 to run the perf regression gate in run_tier1.sh.",
         "tools/run_tier1.sh"),
    Knob("SPARKNET_ROLLSMOKE", "bool", "",
         "Set to 1 to run the rollout chaos leg (canary promote + "
         "planted-bad-canary rollback + controller-kill resume) in "
         "run_tier1.sh.",
         "tools/run_tier1.sh"),
    Knob("SPARKNET_COMMBENCH", "bool", "",
         "Set to 1 to run the comm-codec parity gate (codec-none "
         "bit-identity, EF invariant, overlap stall) in run_tier1.sh.",
         "tools/run_tier1.sh"),
    Knob("SPARKNET_SHARDSMOKE", "bool", "",
         "Set to 1 to run the hybrid-sharding parity gate (sharded-vs-"
         "replicated bit-parity, per-shard checkpoint roundtrip, elastic "
         "re-tile, boundary-bytes shrink) in run_tier1.sh.",
         "tools/run_tier1.sh"),
    # --- tombstones: window closed, any surviving mention fails lint ---
    Knob("SPARKNET_LRN_CUMSUM", "bool", "",
         "REMOVED: pin LRN window-sum form per key in the SPARKNET_TUNE "
         "table instead.",
         "sparknet_tpu/graph/tuner.py",
         removed="r14: use a SPARKNET_TUNE table pin (winner=cumsum / "
                 "reduce_window)"),
    Knob("SPARKNET_FUSE_PALLAS", "bool", "",
         "REMOVED: pin the lrn_epilogue lowering per key in the "
         "SPARKNET_TUNE table instead.",
         "sparknet_tpu/graph/tuner.py",
         removed="r14: use a SPARKNET_TUNE table pin (winner=reference / "
                 "pallas)"),
)

# Symbols (not knobs) past their deprecation window: any surviving
# reference in scanned code fails lint (DP002).  Seeded with the PR-12
# shims this release deletes — the rule that would have flagged them.
DEPRECATED_SYMBOLS: dict[str, str] = {
    "deprecated_lrn_cumsum_pin":
        "r14: removed with SPARKNET_LRN_CUMSUM; pin via SPARKNET_TUNE",
    "_shim_pin":
        "r14: removed with the PR-12 env shims; pin via SPARKNET_TUNE",
}


# ---------------------------------------------------------------------------
# KNOBS.md emission
# ---------------------------------------------------------------------------

_MD_HEADER = """\
# SPARKNET_* knob reference

Auto-generated from `sparknet_tpu/utils/knobs.py` by
`python tools/lint.py knobs --emit` — do not edit by hand;
`tools/lint.py knobs --check` gates drift in CI.

Conventions: bool knobs take `0`/`1` (the doc line states which side is
the default); `default` is the unset behavior; removed knobs are listed
last as tombstones (mentioning them fails lint).
"""


def _md_table(rows: Iterable[Knob]) -> list[str]:
    out = ["| Knob | Type | Default | Owner | Doc |",
           "| --- | --- | --- | --- | --- |"]
    for k in rows:
        default = k.default if k.default != "" else "*(unset)*"
        out.append(f"| `{k.name}` | {k.type} | {default} | `{k.owner}` | "
                   f"{k.doc} |")
    return out


def knobs_md() -> str:
    """The full KNOBS.md text."""
    live = [k for k in all_knobs() if not k.removed]
    dead = [k for k in all_knobs() if k.removed]
    lines = [_MD_HEADER]
    by_owner: dict[str, list[Knob]] = {}
    for k in live:
        by_owner.setdefault(k.owner, []).append(k)
    for owner in sorted(by_owner):
        lines.append(f"\n## `{owner}`\n")
        lines.extend(_md_table(by_owner[owner]))
    if dead:
        lines.append("\n## Removed (tombstones)\n")
        lines.append("| Knob | Removed | Replacement |")
        lines.append("| --- | --- | --- |")
        for k in dead:
            since, _, repl = k.removed.partition(": ")
            lines.append(f"| `{k.name}` | {since} | {repl or k.doc} |")
    lines.append("")
    return "\n".join(lines)
