"""Headline benchmark: CaffeNet (AlexNet-class) training throughput.

Methodology mirrors the reference's published numbers — 20 training
iterations at batch 256, full forward+backward+update, data resident on
device (reference: caffe/docs/performance_hardware.md:19-25, the `caffe
train` 20-iter protocol; best single-GPU baseline 19.2 s ⇒ ≈267 img/s on
K40+cuDNN).  Prints ONE JSON line.
"""

from __future__ import annotations

import json
import time

import numpy as np

BASELINE_IMG_S = 267.0  # K40 + cuDNN, performance_hardware.md:24
BATCH = 256
ITERS = 20
WARMUP = 3
REPS = 5  # tunneled chip shows ~2x run-to-run variance; report the median


def main() -> None:
    import jax
    import jax.numpy as jnp

    from sparknet_tpu.models import caffenet
    from sparknet_tpu.proto import load_solver_prototxt_with_net
    from sparknet_tpu.solvers import Solver

    sp = load_solver_prototxt_with_net(
        'base_lr: 0.01\nmomentum: 0.9\nweight_decay: 0.0005\n'
        'lr_policy: "step"\ngamma: 0.1\nstepsize: 100000\n',
        caffenet(BATCH, BATCH))
    solver = Solver(sp, seed=0)

    rng = np.random.default_rng(0)
    data = jnp.asarray(rng.normal(size=(1, BATCH, 3, 227, 227)).astype(np.float32))
    label = jnp.asarray(rng.integers(0, 1000, size=(1, BATCH)).astype(np.float32))
    batch = {"data": data, "label": label}

    step_rng = jax.random.PRNGKey(0)
    params, state = solver.params, solver.state
    for i in range(WARMUP):
        step_rng, sub = jax.random.split(step_rng)
        params, state, loss = solver._step(params, state, i, batch, sub)
    jax.block_until_ready(loss)

    rates = []
    it = WARMUP
    for _ in range(REPS):
        t0 = time.perf_counter()
        for _ in range(ITERS):
            step_rng, sub = jax.random.split(step_rng)
            params, state, loss = solver._step(params, state, it, batch, sub)
            it += 1
        jax.block_until_ready(loss)
        rates.append(BATCH * ITERS / (time.perf_counter() - t0))

    img_s = float(np.median(rates))
    print(json.dumps({
        "metric": "caffenet_train_images_per_sec",
        "value": round(img_s, 1),
        "unit": "img/s",
        "vs_baseline": round(img_s / BASELINE_IMG_S, 2),
    }))


if __name__ == "__main__":
    main()
