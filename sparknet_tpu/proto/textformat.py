"""Protobuf text-format (prototxt) parser and serializer, schema-free.

The reference delegates prototxt parsing to native code and round-trips the
binary back into the JVM (reference: libccaffe/ccaffe.cpp:213-242,
src/main/scala/libs/ProtoLoader.scala:9-29).  Here the text format is parsed
directly into a lightweight ordered multi-map, ``PMessage``; typed views over
it live in ``caffe_pb.py``.  Being schema-free, every field is stored as a
repeated list — the typed layer decides scalar-vs-repeated semantics, exactly
like protobuf's own descriptor layer does.

Supported syntax (everything the Caffe model zoo uses):
  - ``key: value`` scalars (int, float, bool, enum identifier, "string")
  - ``key { ... }`` and ``key: { ... }`` nested messages
  - repeated fields by repetition
  - ``#`` comments, arbitrary whitespace/newlines
  - ``key: [v1, v2]`` short-hand repeated scalars
"""

from __future__ import annotations

import re
from typing import Any, Iterator


class ParseError(ValueError):
    pass


class PMessage:
    """Ordered multi-map of field name -> list of values.

    Values are str/int/float/bool scalars or nested PMessage. Enum values are
    kept as strings (e.g. ``"MAX"``); the typed layer interprets them.
    """

    __slots__ = ("_fields",)

    def __init__(self) -> None:
        self._fields: dict[str, list[Any]] = {}

    # -- mutation ---------------------------------------------------------
    def add(self, key: str, value: Any) -> None:
        self._fields.setdefault(key, []).append(value)

    def set(self, key: str, value: Any) -> None:
        self._fields[key] = [value]

    def clear(self, key: str) -> None:
        self._fields.pop(key, None)

    # -- access -----------------------------------------------------------
    def get(self, key: str, default: Any = None) -> Any:
        vals = self._fields.get(key)
        if not vals:
            return default
        return vals[0]

    def get_all(self, key: str) -> list[Any]:
        return list(self._fields.get(key, []))

    def has(self, key: str) -> bool:
        return bool(self._fields.get(key))

    def keys(self) -> Iterator[str]:
        return iter(self._fields.keys())

    def items(self) -> Iterator[tuple[str, Any]]:
        for k, vals in self._fields.items():
            for v in vals:
                yield k, v

    def __contains__(self, key: str) -> bool:
        return self.has(key)

    def __repr__(self) -> str:
        return f"PMessage({dict(self._fields)!r})"

    def __eq__(self, other: object) -> bool:
        return isinstance(other, PMessage) and self._fields == other._fields


_TOKEN_RE = re.compile(
    r"""
    (?P<ws>\s+)
  | (?P<comment>\#[^\n]*)
  | (?P<string>"(?:\\.|[^"\\])*"|'(?:\\.|[^'\\])*')
  | (?P<punct>[{}:,\[\]])
  | (?P<atom>[^\s{}:,\[\]"']+)
    """,
    re.VERBOSE,
)


def _tokenize(text: str) -> list[tuple[str, str, int]]:
    tokens: list[tuple[str, str, int]] = []
    pos = 0
    line = 1
    n = len(text)
    while pos < n:
        m = _TOKEN_RE.match(text, pos)
        if m is None:
            raise ParseError(f"line {line}: unexpected character {text[pos]!r}")
        kind = m.lastgroup
        val = m.group()
        if kind not in ("ws", "comment"):
            tokens.append((kind, val, line))
        line += val.count("\n")
        pos = m.end()
    return tokens


_INT_RE = re.compile(r"^[+-]?\d+$")
_FLOAT_RE = re.compile(r"^[+-]?(\d+\.\d*|\.\d+|\d+)([eE][+-]?\d+)?$|^[+-]?(inf|nan)$", re.IGNORECASE)


class EnumToken(str):
    """A bare (unquoted) identifier — an enum value in proto text format.
    Distinguishing it from quoted strings makes serialization lossless:
    enums stay bare, every plain string gets quoted (an uppercase layer
    NAME like "CONV1" must not be written as a bare token real protobuf
    would reject, nor may a name like "NAN" reparse as a float)."""

    __slots__ = ()


def _convert_atom(atom: str) -> Any:
    if _INT_RE.match(atom):
        return int(atom)
    if atom in ("true", "True"):
        return True
    if atom in ("false", "False"):
        return False
    if _FLOAT_RE.match(atom):
        return float(atom)
    return EnumToken(atom)  # enum identifier


def _unquote(s: str) -> str:
    body = s[1:-1]
    return body.encode("raw_unicode_escape").decode("unicode_escape")


class _Parser:
    def __init__(self, tokens: list[tuple[str, str, int]]):
        self.tokens = tokens
        self.i = 0

    def peek(self) -> tuple[str, str, int] | None:
        return self.tokens[self.i] if self.i < len(self.tokens) else None

    def next(self) -> tuple[str, str, int]:
        tok = self.peek()
        if tok is None:
            raise ParseError("unexpected end of input")
        self.i += 1
        return tok

    def expect(self, value: str) -> None:
        kind, val, line = self.next()
        if val != value:
            raise ParseError(f"line {line}: expected {value!r}, got {val!r}")

    def parse_message(self, top_level: bool) -> PMessage:
        msg = PMessage()
        while True:
            tok = self.peek()
            if tok is None:
                if top_level:
                    return msg
                raise ParseError("unexpected end of input inside message")
            kind, val, line = tok
            if val == "}":
                if top_level:
                    raise ParseError(f"line {line}: unmatched '}}'")
                self.next()
                return msg
            if kind != "atom":
                raise ParseError(f"line {line}: expected field name, got {val!r}")
            self.next()
            field = val
            tok2 = self.peek()
            if tok2 is None:
                raise ParseError(f"line {line}: field {field!r} missing value")
            if tok2[1] == "{":
                self.next()
                msg.add(field, self.parse_message(top_level=False))
            elif tok2[1] == ":":
                self.next()
                self.parse_value(msg, field)
            else:
                raise ParseError(
                    f"line {line}: expected ':' or '{{' after {field!r}, got {tok2[1]!r}"
                )
        # unreachable

    def parse_value(self, msg: PMessage, field: str) -> None:
        kind, val, line = self.next()
        if val == "{":
            msg.add(field, self.parse_message(top_level=False))
        elif val == "[":
            while True:
                tok = self.peek()
                if tok is None:
                    raise ParseError(f"line {line}: unterminated list for {field!r}")
                if tok[1] == "]":
                    self.next()
                    break
                k2, v2, l2 = self.next()
                if k2 == "string":
                    msg.add(field, _unquote(v2))
                elif k2 == "atom":
                    msg.add(field, _convert_atom(v2))
                else:
                    raise ParseError(f"line {l2}: bad list element {v2!r}")
                if self.peek() and self.peek()[1] == ",":
                    self.next()
        elif kind == "string":
            # adjacent string concatenation ("a" "b" -> "ab")
            parts = [_unquote(val)]
            while self.peek() and self.peek()[0] == "string":
                parts.append(_unquote(self.next()[1]))
            msg.add(field, "".join(parts))
        elif kind == "atom":
            msg.add(field, _convert_atom(val))
        else:
            raise ParseError(f"line {line}: bad value {val!r} for field {field!r}")


def parse(text: str) -> PMessage:
    """Parse prototxt text into a PMessage."""
    return _Parser(_tokenize(text)).parse_message(top_level=True)


def _format_scalar(v: Any) -> str:
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, float):
        return repr(v)
    if isinstance(v, int):
        return str(v)
    if isinstance(v, EnumToken):
        return str(v)
    if isinstance(v, str):
        escaped = v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")
        return f'"{escaped}"'
    raise TypeError(f"cannot serialize {v!r}")


def serialize(msg: PMessage, indent: int = 0) -> str:
    """Serialize a PMessage back to prototxt text (round-trip capable)."""
    pad = "  " * indent
    out: list[str] = []
    for key, val in msg.items():
        if isinstance(val, PMessage):
            out.append(f"{pad}{key} {{\n{serialize(val, indent + 1)}{pad}}}\n")
        else:
            out.append(f"{pad}{key}: {_format_scalar(val)}\n")
    return "".join(out)
