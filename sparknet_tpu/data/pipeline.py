"""Parallel, vectorized feed pipeline: decode pool, buffers, shard cache.

BENCH_r05 measured the device compute path at 18,149 img/s while the
end-to-end feed-in-loop leg delivered 70.7 img/s — a 32× host-side gap
(``feed_compute_ratio: 32.2``).  That is the same wall the reference hit:
its per-minibatch JVM callback fed Caffe one image at a time through JNA
(reference: caffe/src/caffe/layers/java_data_layer.cpp:36-44, the measured
hot spot of CallbackBenchmarkSpec), and both Caffe (arXiv 1408.5093) and
Caffe con Troll (arXiv 1504.04343) showed that batched, parallelized
host-side decode/transform is where shallow engineering buys an order of
magnitude.  This module is that engineering, as four composable pieces:

- :class:`DecodePool` — an ORDER-PRESERVING thread pool: work items go in
  serially (so stateful pulls — DB cursors, fault-injection coin flips,
  quarantine epoch accounting — stay deterministic), results come out in
  submission order, and exceptions raised by the work function surface at
  the failing item's ordinal position exactly as a serial loop would see
  them.  ``workers=0`` is the serial reference path: identical ordering,
  identical error positions, zero threads — the parity oracle the tests
  and ``tools/feedbench.py`` compare against.  A worker thread that DIES
  (not raises — dies) surfaces as a typed :class:`DecodeWorkerError` on
  the consumer, never a hang.
- :class:`FeedStats` — per-stage wall-time accounting (read / decode /
  transform / device_put) so the bench's ``feed_in_loop`` JSON can say
  WHERE feed time goes instead of one opaque number — ``read`` is the
  object-store/disk IO stage the records path books its ranged reads
  to, so a slow store is attributable separately from a slow host.
- :class:`BufferRing` — preallocated rotating output buffers for
  batch-level transforms.  Opt-in: the caller owns the aliasing contract
  (a buffer is reused after ``size`` further batches, so the ring must be
  deeper than every downstream queue that holds batches concurrently).
- :class:`ShardCache` — a bounded LRU over materialized (decoded)
  partitions so multi-epoch training pays decode once per shard, not once
  per epoch (used via ``PartitionedDataset.cached``).

Knobs (shared by ``db_feed``, ``device_feed``, the launcher, and bench):

- ``SPARKNET_FEED_WORKERS`` — decode pool width (default: cpu count,
  capped at 8; 0 = serial reference path).
- ``SPARKNET_FEED_DEPTH``   — prefetch depth for ``device_feed`` (default
  4: deep double-buffering so decode, transform, and host→HBM transfer
  all hide under device steps).
"""

from __future__ import annotations

import os
import queue
import threading
import time
from collections import OrderedDict
from typing import Any, Callable, Iterator, Sequence

import numpy as np

from ..utils import knobs, telemetry


def _env_int(name: str, default: int) -> int:
    raw = knobs.raw(name, "")
    if not raw:
        return default
    try:
        return int(raw)
    except ValueError:
        raise ValueError(f"{name} must be an integer, got {raw!r}") from None


def feed_workers(default: int | None = None) -> int:
    """Decode-pool width: ``SPARKNET_FEED_WORKERS``, else ``default``,
    else cpu count capped at 8.  0 means the serial reference path."""
    if default is None:
        default = min(os.cpu_count() or 1, 8)
    n = _env_int("SPARKNET_FEED_WORKERS", default)
    if n < 0:
        raise ValueError(f"SPARKNET_FEED_WORKERS must be >= 0, got {n}")
    return n


def feed_depth(default: int = 4) -> int:
    """Prefetch depth: ``SPARKNET_FEED_DEPTH``, else ``default``."""
    n = _env_int("SPARKNET_FEED_DEPTH", default)
    if n < 1:
        raise ValueError(f"SPARKNET_FEED_DEPTH must be >= 1, got {n}")
    return n


class FeedStats:
    """Thread-safe per-stage time/count accounting for one feed.

    Stage seconds are summed across whichever threads ran the stage, so
    with a parallel pool ``decode_s`` is cpu-seconds (it can exceed wall
    time — that is the point of the pool).  ``snapshot()`` returns totals;
    ``per_batch()`` divides by delivered batches for the bench JSON."""

    STAGES = ("read", "decode", "transform", "device_put")

    def __init__(self):
        self._lock = threading.Lock()
        self._s = {k: 0.0 for k in self.STAGES}
        self.batches = 0
        self.records = 0
        self.cache_hits = 0        # RAM-tier hits (back-compat meaning)
        self.cache_disk_hits = 0   # served from the local-disk spill tier
        self.cache_misses = 0      # every tier missed: origin materialize

    def note(self, stage: str, seconds: float, records: int = 0) -> None:
        with self._lock:
            self._s[stage] = self._s.get(stage, 0.0) + seconds
            self.records += records
        # telemetry plane: the stage timing the pipeline already measured
        # becomes a histogram sample and (when tracing) a retroactive
        # span — DecodePool / transforms / DeviceFeed all route through
        # here, so one hook instruments every feed stage
        telemetry.get_registry().histogram(
            "feed_stage_seconds",
            "host feed pipeline stage latency").observe(seconds,
                                                        stage=stage)
        telemetry.note_span(f"feed.{stage}", seconds, cat="feed")

    def count_batch(self, records: int = 0) -> None:
        with self._lock:
            self.batches += 1
            self.records += records
        telemetry.get_registry().counter(
            "feed_batches_total", "batches delivered to the consumer"
        ).inc()

    def note_cache(self, hit: bool, tier: str = "ram") -> None:
        """Record one shard-cache lookup outcome.  ``tier`` labels WHICH
        tier served a hit (``ram`` or ``disk``); a miss means every tier
        missed.  ``cache_hits`` keeps its pre-tier meaning (RAM hits) so
        existing consumers and the bench JSON stay comparable."""
        with self._lock:
            if hit and tier == "disk":
                self.cache_disk_hits += 1
            elif hit:
                self.cache_hits += 1
            else:
                self.cache_misses += 1
        telemetry.get_registry().counter(
            "feed_cache_total", "shard cache lookups by outcome and tier"
        ).inc(result="hit" if hit else "miss",
              tier=tier if hit else "none")

    class _Timer:
        __slots__ = ("_stats", "_stage", "_records", "_t0")

        def __init__(self, stats, stage, records):
            self._stats, self._stage, self._records = stats, stage, records

        def __enter__(self):
            self._t0 = time.perf_counter()
            return self

        def __exit__(self, *exc):
            self._stats.note(self._stage,
                             time.perf_counter() - self._t0, self._records)

    def timed(self, stage: str, records: int = 0) -> "FeedStats._Timer":
        return self._Timer(self, stage, records)

    def snapshot(self) -> dict[str, Any]:
        with self._lock:
            out = {f"{k}_s": round(v, 6) for k, v in self._s.items()}
            out.update(batches=self.batches, records=self.records,
                       cache_hits=self.cache_hits,
                       cache_disk_hits=self.cache_disk_hits,
                       cache_misses=self.cache_misses)
            return out

    def per_batch(self) -> dict[str, float]:
        """Average stage seconds per delivered batch (0.0 before the
        first batch)."""
        with self._lock:
            n = max(self.batches, 1)
            return {f"{k}_s": round(v / n, 6) for k, v in self._s.items()}


class DecodeWorkerError(RuntimeError):
    """A pipeline worker thread died without producing its result (thread
    death, not a work-function exception — those propagate as themselves
    at their ordinal position).  Carries the pool name and the ordinal of
    the orphaned item so the failure is attributable, never a hang."""

    def __init__(self, name: str, ticket: int, detail: str = ""):
        self.pool = name
        self.ticket = ticket
        suffix = f": {detail}" if detail else ""
        super().__init__(
            f"{name} pipeline worker died before producing item "
            f"#{ticket}{suffix}")


_STOP = object()


class DecodePool:
    """Order-preserving parallel map with a bounded in-flight window.

    Items are submitted serially (``submit``) and consumed serially
    (``result``), in the same order; only the work function ``fn`` runs
    on the pool threads.  That split is what keeps a stateful producer
    deterministic: DB cursor advance, fault-injection coin flips, and
    quarantine epoch accounting all happen on the caller's thread in the
    exact sequence the serial path would produce, while the pure decode
    work fans out.

    Exception contract: an exception raised BY ``fn`` is re-raised from
    ``result()`` at that item's position (so ``DataCorruptionError``
    reaches the quarantine in serial order); a worker thread that dies
    without recording a result raises :class:`DecodeWorkerError` from
    ``result()`` within ~``2 × _POLL_S`` — a crashed pipeline is a typed
    error, never a hang.

    ``workers=0`` runs ``fn`` lazily on the consumer thread at
    ``result()`` time — the serial reference path with identical
    ordering, used for parity tests and as the no-thread fallback.
    """

    _POLL_S = 0.1

    def __init__(self, fn: Callable[[Any], Any], workers: int | None = None,
                 window: int | None = None, name: str = "decode",
                 stats: FeedStats | None = None, stage: str = "decode"):
        self.fn = fn
        self.name = name
        self.workers = feed_workers() if workers is None else int(workers)
        if self.workers < 0:
            raise ValueError(f"workers must be >= 0, got {self.workers}")
        self._window = int(window) if window else max(2, 2 * self.workers)
        self._stats = stats
        self._stage = stage
        self._closed = False
        self._next_submit = 0
        self._next_consume = 0
        if self.workers == 0:
            self._pending: "queue.SimpleQueue[Any]" = queue.SimpleQueue()
            self._threads: list[threading.Thread] = []
            return
        self._in: "queue.Queue[Any]" = queue.Queue()
        self._cond = threading.Condition()
        self._results: dict[int, tuple[bool, Any]] = {}
        self._threads = [
            threading.Thread(target=self._run, name=f"{name}-{i}",
                             daemon=True)
            for i in range(self.workers)]
        for t in self._threads:
            t.start()

    # -- worker side ------------------------------------------------------
    def _run(self) -> None:
        while True:
            item = self._in.get()
            if item is _STOP:
                return
            ticket, payload = item
            t0 = time.perf_counter()
            try:
                value, ok = self.fn(payload), True
            except BaseException as e:  # re-raised at the item's ordinal
                value, ok = e, False
            if self._stats is not None:
                self._stats.note(self._stage, time.perf_counter() - t0)
            with self._cond:
                self._results[ticket] = (ok, value)
                self._cond.notify_all()

    # -- consumer side ----------------------------------------------------
    @property
    def in_flight(self) -> int:
        return self._next_submit - self._next_consume

    def submit(self, item: Any) -> int:
        """Enqueue one work item; blocks while the in-flight window is
        full (backpressure), returns the item's ticket."""
        if self._closed:
            raise RuntimeError(f"{self.name} pool is closed")
        ticket = self._next_submit
        self._next_submit += 1
        if self.workers == 0:
            self._pending.put(item)
            return ticket
        with self._cond:
            while (self._next_submit - self._next_consume > self._window
                   and not self._closed):
                self._check_workers(ticket)
                self._cond.wait(self._POLL_S)
        self._in.put((ticket, item))
        return ticket

    def _check_workers(self, ticket: int) -> None:
        if not any(t.is_alive() for t in self._threads):
            raise DecodeWorkerError(
                self.name, ticket, "no live workers left in the pool")

    def result(self) -> Any:
        """The next result in submission order; re-raises the work
        function's exception for that item, or DecodeWorkerError if the
        pool died under it."""
        if self._next_consume >= self._next_submit:
            raise RuntimeError(
                f"{self.name} pool: result() with nothing in flight")
        ticket = self._next_consume
        if self.workers == 0:
            item = self._pending.get_nowait()
            self._next_consume += 1
            t0 = time.perf_counter()
            try:
                return self.fn(item)
            finally:
                if self._stats is not None:
                    self._stats.note(self._stage, time.perf_counter() - t0)
        with self._cond:
            while ticket not in self._results:
                # the wait is a short poll that re-checks pool liveness:
                # a dead pool is a typed error on the consumer, not a hang
                self._check_workers(ticket)
                self._cond.wait(self._POLL_S)
            ok, value = self._results.pop(ticket)
            self._next_consume += 1
            self._cond.notify_all()
        if ok:
            return value
        raise value

    def imap(self, it) -> Iterator[Any]:
        """Order-preserving parallel map over an iterator.  A background
        pump thread advances the source and submits under the window's
        backpressure; results are yielded in source order.  An exception
        raised by the SOURCE is re-raised after every already-submitted
        item has been yielded (drain-then-fail, matching
        ``PrefetchIterator`` semantics)."""
        if self.workers == 0:
            for item in it:
                self.submit(item)
                yield self.result()
            return
        src_err: list[BaseException] = []
        src_done = threading.Event()

        def pump() -> None:
            try:
                for item in it:
                    if self._closed:
                        return
                    self.submit(item)
            except BaseException as e:
                src_err.append(e)
            finally:
                src_done.set()
                with self._cond:
                    self._cond.notify_all()

        t = threading.Thread(target=pump, name=f"{self.name}-pump",
                             daemon=True)
        t.start()
        while True:
            with self._cond:
                while (self._next_consume >= self._next_submit
                       and not src_done.is_set()):
                    self._cond.wait(self._POLL_S)
            if self._next_consume < self._next_submit:
                yield self.result()
                continue
            if src_err:
                raise src_err[0]
            return

    def close(self) -> None:
        """Stop the workers and drop queued work.  In-flight results are
        discarded; safe to call more than once."""
        self._closed = True
        if self.workers == 0:
            return
        while True:  # drop queued-but-unstarted work
            try:
                self._in.get_nowait()
            except queue.Empty:
                break
        for _ in self._threads:
            self._in.put(_STOP)
        with self._cond:
            self._cond.notify_all()
        for t in self._threads:
            t.join(timeout=5.0)

    def __enter__(self) -> "DecodePool":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class BufferRing:
    """A rotation of ``size`` preallocated output buffers for batch-level
    transforms — the allocation-free hot path.

    Aliasing contract (the caller's to uphold): buffer k is handed out
    again after ``size`` further ``take()`` calls, so every downstream
    stage that holds batches concurrently (prefetch queue depth + staging
    window + the consumer's working batch) must together hold FEWER than
    ``size`` — size it ``depth + window + 2``.  ``db_feed`` only rotates
    buffers when explicitly asked (``buffers=N``)."""

    def __init__(self, size: int):
        if size < 2:
            raise ValueError(f"BufferRing needs size >= 2, got {size}")
        self.size = size
        self._bufs: list[np.ndarray] = []
        self._i = 0
        self._shape: tuple | None = None
        self._dtype = None

    def take(self, shape: tuple, dtype=np.float32) -> np.ndarray:
        """The next buffer in rotation (contents undefined).  A shape or
        dtype change drops the old rotation and starts a new one."""
        if self._shape != shape or self._dtype != dtype:
            self._bufs = []
            self._shape, self._dtype = shape, dtype
            self._i = 0
        if len(self._bufs) < self.size:
            self._bufs.append(np.empty(shape, dtype))
            return self._bufs[-1]
        buf = self._bufs[self._i % self.size]
        self._i += 1
        return buf


def cache_shards(default: int = 4) -> int:
    """RAM-tier capacity: ``SPARKNET_CACHE_SHARDS``, else ``default``."""
    n = _env_int("SPARKNET_CACHE_SHARDS", default)
    if n < 1:
        raise ValueError(f"SPARKNET_CACHE_SHARDS must be >= 1, got {n}")
    return n


def cache_spill_dir() -> str | None:
    """Disk spill tier directory: ``SPARKNET_CACHE_SPILL_DIR`` (empty =
    spill disabled, the pre-tier behavior)."""
    return knobs.get_str("SPARKNET_CACHE_SPILL_DIR", "") or None


def cache_spill_shards(default: int = 16) -> int:
    """Disk-tier capacity: ``SPARKNET_CACHE_SPILL_SHARDS``."""
    n = _env_int("SPARKNET_CACHE_SPILL_SHARDS", default)
    if n < 1:
        raise ValueError(
            f"SPARKNET_CACHE_SPILL_SHARDS must be >= 1, got {n}")
    return n


class ShardCache:
    """Tiered bounded cache of materialized shards: host RAM LRU, with
    RAM evictions spilled to local-disk files instead of discarded.

    Multi-epoch training re-reads every shard once per epoch; for lazy
    partitions (``imagenet.LazyTarPartition`` decodes on slice access)
    that means paying the full decode each time, and for record shards
    streamed from an object store it means re-paying the wire.  The RAM
    tier keeps up to ``max_shards`` materialized values; when ``spill_dir``
    is set (default: the ``SPARKNET_CACHE_SPILL_DIR`` knob), up to
    ``max_spill`` RAM-evicted shards land as pickle files on local disk,
    so the fallback on a RAM miss is a local read, not the origin store.
    Lookup order: RAM → disk (hit promotes back to RAM) → materialize.

    Values may be any picklable materialization — decoded record lists
    (``CachedPartition``) or whole-shard ``bytes`` blobs
    (``records.RecordShard.attach_cache``); the cache stores whatever
    ``materialize()`` returns, uncoerced.

    Per-tier outcomes land in ``FeedStats`` (``cache_hits`` = RAM,
    ``cache_disk_hits``, ``cache_misses``) and the ``feed_cache_total``
    counter's ``tier`` label, so perfwatch can attribute a feed breach
    to the tier that missed.  Thread-safe; one cache is shared across
    all partitions of a ``PartitionedDataset.cached()`` view."""

    def __init__(self, max_shards: int = 4,
                 stats: FeedStats | None = None,
                 spill_dir: str | None = None,
                 max_spill: int | None = None):
        if max_shards < 1:
            raise ValueError(f"max_shards must be >= 1, got {max_shards}")
        self.max_shards = max_shards
        self._lock = threading.Lock()
        self._cache: "OrderedDict[Any, Any]" = OrderedDict()
        self._stats = stats
        self.spill_dir = cache_spill_dir() if spill_dir is None else (
            spill_dir or None)
        self.max_spill = (cache_spill_shards() if max_spill is None
                          else int(max_spill))
        self._spilled: "OrderedDict[Any, str]" = OrderedDict()  # key->path
        self.hits = 0
        self.disk_hits = 0
        self.misses = 0
        self.spills = 0

    # -- disk tier --------------------------------------------------------
    def _spill_path(self, key: Any) -> str:
        import zlib
        tag = zlib.crc32(repr(key).encode()) & 0xFFFFFFFF
        return os.path.join(self.spill_dir, f"shard-{tag:08x}.pkl")

    def _spill(self, key: Any, value: Any) -> None:
        """Write one RAM-evicted shard to the disk tier (atomic tmp +
        rename; a torn spill file can never be loaded).  Called under
        the lock — spills are rare (one per RAM eviction) and keeping
        them ordered keeps the disk-tier LRU exact."""
        import pickle
        os.makedirs(self.spill_dir, exist_ok=True)
        path = self._spill_path(key)
        tmp = path + ".tmp"
        try:
            with open(tmp, "wb") as f:
                pickle.dump((key, value), f, protocol=4)
            os.replace(tmp, path)
        except OSError:
            # a full/unwritable spill disk degrades to no-spill, it
            # must not kill the feed
            try:
                os.unlink(tmp)
            except OSError:
                pass
            return
        self._spilled.pop(key, None)
        self._spilled[key] = path
        self.spills += 1
        while len(self._spilled) > self.max_spill:
            _, old = self._spilled.popitem(last=False)
            try:
                os.unlink(old)
            except OSError:
                pass

    def _load_spilled(self, key: Any) -> Any | None:
        """Try the disk tier; verifies the stored key (crc32 tags can
        collide) and treats any unreadable file as a miss."""
        import pickle
        path = self._spilled.get(key)
        if path is None:
            return None
        try:
            with open(path, "rb") as f:
                stored_key, value = pickle.load(f)
        except (OSError, pickle.UnpicklingError, EOFError, ValueError):
            self._spilled.pop(key, None)
            return None
        if stored_key != key:
            return None
        return value

    def _insert(self, key: Any, value: Any) -> None:
        """RAM-tier insert + LRU eviction (under the lock); evictees go
        to the disk tier when one is configured."""
        self._cache[key] = value
        self._cache.move_to_end(key)
        while len(self._cache) > self.max_shards:
            old_key, old_value = self._cache.popitem(last=False)
            if self.spill_dir:
                self._spill(old_key, old_value)

    def get(self, key: Any, materialize: Callable[[], Any]) -> Any:
        with self._lock:
            if key in self._cache:
                self._cache.move_to_end(key)
                self.hits += 1
                if self._stats is not None:
                    self._stats.note_cache(True)
                return self._cache[key]
            if self.spill_dir:
                value = self._load_spilled(key)
                if value is not None:
                    self.disk_hits += 1
                    if self._stats is not None:
                        self._stats.note_cache(True, tier="disk")
                    self._insert(key, value)   # promote back to RAM
                    return value
        # materialize OUTSIDE the lock: decode of shard A must not block
        # a cache hit on shard B
        value = materialize()
        with self._lock:
            self.misses += 1
            if self._stats is not None:
                self._stats.note_cache(False)
            self._insert(key, value)
            return value

    def tier_counts(self) -> dict[str, int]:
        with self._lock:
            return {"ram_hits": self.hits, "disk_hits": self.disk_hits,
                    "misses": self.misses, "spills": self.spills,
                    "ram_shards": len(self._cache),
                    "disk_shards": len(self._spilled)}

    def __len__(self) -> int:
        with self._lock:
            return len(self._cache)


class CachedPartition:
    """A partition view that materializes its backing partition through a
    shared :class:`ShardCache` on first access.  Satisfies the
    ``__len__``/``__getitem__`` contract ``PartitionedDataset`` keeps for
    lazy partitions."""

    def __init__(self, base: Sequence, key: Any, cache: ShardCache):
        self._base = base
        self._key = key
        self._cache = cache

    def _records(self) -> Sequence:
        return self._cache.get(self._key, lambda: self._base[:])

    def __len__(self) -> int:
        return len(self._base)

    def __getitem__(self, idx):
        return self._records()[idx]

    def __iter__(self):
        return iter(self._records())
