"""plot_training_log — chart a training log (reference:
caffe/tools/extra/plot_training_log.py.example, all 8 chart types).

  0: Test accuracy  vs. Iters        1: Test accuracy  vs. Seconds
  2: Test loss      vs. Iters        3: Test loss      vs. Seconds
  4: Train learning rate vs. Iters   5: Train learning rate vs. Seconds
  6: Train loss     vs. Iters        7: Train loss     vs. Seconds

Seconds come from the glog timestamp prefix the Solver emits
(utils/glog.log_line; reference: tools/extra/extract_seconds.py), the
learning rate from the per-display-interval "Iteration N, lr = R" lines
(reference: sgd_solver.cpp:104-106).  A log missing those lines (e.g.
produced before they were emitted) raises a clear error for the chart
types that need them rather than plotting a wrong axis.

Usage:
  python -m sparknet_tpu.tools.plot_training_log CHART_TYPE OUT.png \
      LOG [LOG ...]
"""

from __future__ import annotations

import argparse
import os

# chart type -> (title, y field, x field, train|test)
_CHARTS = {
    0: ("Test accuracy vs. Iters", "accuracy", "Iters", "test"),
    1: ("Test accuracy vs. Seconds", "accuracy", "Seconds", "test"),
    2: ("Test loss vs. Iters", "loss", "Iters", "test"),
    3: ("Test loss vs. Seconds", "loss", "Seconds", "test"),
    4: ("Train learning rate vs. Iters", "lr", "Iters", "train"),
    5: ("Train learning rate vs. Seconds", "lr", "Seconds", "train"),
    6: ("Train loss vs. Iters", "loss", "Iters", "train"),
    7: ("Train loss vs. Seconds", "loss", "Seconds", "train"),
}


def _series(path: str, field: str, xfield: str, which: str):
    """-> {label_suffix: (xs, ys)} — one series per test net, so
    multi-test-net logs don't interleave into a zigzag."""
    from .parse_log import parse_log
    train, test = parse_log(path)
    if which == "train":
        rows = [(r.seconds if xfield == "Seconds" else r.iter,
                 r.lr if field == "lr" else r.loss) for r in train]
        missing = [i for i, (x, y) in enumerate(rows)
                   if x is None or y is None]
        if rows and len(missing) == len(rows):
            what = ("glog timestamps" if xfield == "Seconds"
                    else "'Iteration N, lr =' lines")
            raise ValueError(
                f"{path}: no {what} found — this log predates the "
                f"Solver emitting them, so chart x/y field "
                f"{xfield}/{field} cannot be drawn")
        rows = [(x, y) for x, y in rows if x is not None and y is not None]
        return {"": ([x for x, _ in rows], [y for _, y in rows])}
    by_net: dict[int, tuple[list, list]] = {}
    for (it, net), row in sorted(test.items()):
        if field in row:
            x = row.get("Seconds") if xfield == "Seconds" else it
            if x is None:
                raise ValueError(
                    f"{path}: test pass at iter {it} has no glog "
                    f"timestamp; Seconds charts need timestamped logs")
            xs, ys = by_net.setdefault(net, ([], []))
            xs.append(x)
            ys.append(row[field])
    multi = len(by_net) > 1
    return {(f" (test net #{n})" if multi else ""): s
            for n, s in sorted(by_net.items())}


def plot(chart_type: int, out_path: str, logs: list[str]) -> None:
    if chart_type not in _CHARTS:
        raise ValueError(
            f"unknown chart type {chart_type} "
            f"(supported: {sorted(_CHARTS)})")
    title, field, xfield, which = _CHARTS[chart_type]

    import matplotlib
    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    fig, ax = plt.subplots(figsize=(8, 5))
    for path in logs:
        series = _series(path, field, xfield, which)
        if not any(xs for xs, _ in series.values()):
            raise ValueError(f"{path}: no {which} '{field}' entries found")
        for suffix, (xs, ys) in series.items():
            ax.plot(xs, ys, marker=".", linewidth=1,
                    label=os.path.basename(path) + suffix)
    ax.set_xlabel(xfield)
    ax.set_ylabel(title.split(" vs.")[0])
    ax.set_title(title)
    ax.legend(loc="best")
    fig.tight_layout()
    fig.savefig(out_path)
    plt.close(fig)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("chart_type", type=int)
    ap.add_argument("out_path")
    ap.add_argument("logs", nargs="+")
    args = ap.parse_args(argv)
    plot(args.chart_type, args.out_path, args.logs)
    print(args.out_path)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
