"""Drive: the DeepDream loop shape — forward to a mid layer, set its diff,
ranged backward to the input, ascend — through `import caffe`."""
import jax; jax.config.update("jax_platforms", "cpu")
import numpy as np
from sparknet_tpu import pycaffe_compat
pycaffe_compat.install()
import caffe  # resolves to the shim

NET = """
name: "dream"
input: "data"
input_shape { dim: 1 dim: 3 dim: 16 dim: 16 }
layer { name: "conv1" type: "Convolution" bottom: "data" top: "conv1"
  convolution_param { num_output: 8 kernel_size: 3 pad: 1
    weight_filler { type: "xavier" } } }
layer { name: "relu1" type: "ReLU" bottom: "conv1" top: "conv1" }
layer { name: "conv2" type: "Convolution" bottom: "conv1" top: "conv2"
  convolution_param { num_output: 8 kernel_size: 3 pad: 1
    weight_filler { type: "xavier" } } }
"""
net = caffe.Net(NET, phase=caffe.TEST)
rng = np.random.default_rng(0)
img = rng.normal(size=(1, 3, 16, 16)).astype(np.float32) * 0.1
obj = []
for step in range(8):  # gradient-ascent loop, deepdream.py make_step shape
    net.blobs["data"].data[...] = img
    net.forward(end="conv2")
    act = net.blobs["conv2"].data
    obj.append(float((act ** 2).sum()) / 2)
    net.blobs["conv2"].diff[...] = act          # d(0.5*||a||^2)/da = a
    g = net.backward(start="conv2")["data"]
    img = img + 0.5 * g / (np.abs(g).mean() + 1e-8)
assert obj[-1] > obj[0] * 1.5, obj  # the objective climbs
print("deepdream-loop drive OK:", [round(o, 2) for o in (obj[0], obj[-1])])
