"""Per-op tests: Caffe-exact shape inference, value checks against naive
numpy references, and gradient checks via jax.test_util.check_grads — the
GradientChecker analog (reference:
caffe/include/caffe/test/test_gradient_check_util.hpp:19)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.test_util import check_grads

from sparknet_tpu.models.dsl import layer
from sparknet_tpu.ops import get_layer_impl
from sparknet_tpu.ops.vision import pool_output_size


def make(type_, **type_params):
    return layer("t", type_, ["b0"], ["t0"], **type_params)


def apply_op(lp, bottoms, params=(), train=True, rng=None):
    impl = get_layer_impl(lp.type)
    out = impl.apply(lp, list(params), [jnp.asarray(b) for b in bottoms],
                     train, rng)
    if getattr(impl, "has_state", False):
        out = out[0]
    return out


# -- convolution ------------------------------------------------------------

def test_conv_shapes_caffe_floor(rng):
    # (in + 2p - k)/s + 1 floor: caffe base_conv_layer.cpp
    lp = make("Convolution", convolution_param={
        "num_output": 8, "kernel_size": 3, "stride": 2, "pad": 1})
    impl = get_layer_impl("Convolution")
    assert impl.out_shapes(lp, [(2, 3, 11, 11)]) == [(2, 8, 6, 6)]
    params = impl.init(rng, lp, [(2, 3, 11, 11)])
    assert params[0].shape == (8, 3, 3, 3)
    assert params[1].shape == (8,)
    y = apply_op(lp, [np.ones((2, 3, 11, 11), np.float32)], params)
    assert y[0].shape == (2, 8, 6, 6)


def test_conv_matches_numpy(rng, np_rng):
    x = np_rng.normal(size=(1, 2, 5, 5)).astype(np.float32)
    w = np_rng.normal(size=(3, 2, 3, 3)).astype(np.float32)
    b = np_rng.normal(size=(3,)).astype(np.float32)
    lp = make("Convolution", convolution_param={
        "num_output": 3, "kernel_size": 3})
    y = np.asarray(apply_op(lp, [x], [jnp.asarray(w), jnp.asarray(b)])[0])
    # naive correlation
    ref = np.zeros((1, 3, 3, 3), np.float32)
    for o in range(3):
        for i in range(3):
            for j in range(3):
                patch = x[0, :, i:i + 3, j:j + 3]
                ref[0, o, i, j] = np.sum(patch * w[o]) + b[o]
    np.testing.assert_allclose(y, ref, rtol=1e-4, atol=1e-5)


def test_space_to_depth_conv_exact(rng, np_rng, monkeypatch):
    """The stride-phase regroup rewrite (vision._space_to_depth_conv,
    engaged for small-C strided stems) must match the direct strided conv
    bitwise-close, forward and gradient, across stem geometries."""
    from sparknet_tpu.ops import vision

    impl = get_layer_impl("Convolution")
    geoms = [  # (C, H, W, num_output, k, s, p) — CaffeNet & GoogLeNet stems
        (3, 35, 35, 8, 11, 4, 0),
        (3, 32, 32, 8, 7, 2, 3),
        (2, 17, 19, 4, 5, 3, 1),
    ]
    for c, h, w, o, k, s, p in geoms:
        lp = make("Convolution", convolution_param={
            "num_output": o, "kernel_size": k, "stride": s, "pad": p})
        params = impl.init(rng, lp, [(2, c, h, w)])
        x = jnp.asarray(np_rng.normal(size=(2, c, h, w)).astype(np.float32))
        assert vision._s2d_eligible(c, k, k, s, s, p, p, 1, 1, 1)

        def loss(pp, xx):
            return jnp.sum(jnp.sin(impl.apply(lp, pp, [xx], False, None)[0]))

        y1, g1 = jax.value_and_grad(loss)(params, x)
        monkeypatch.setenv("SPARKNET_NO_S2D", "1")
        assert not vision._s2d_eligible(c, k, k, s, s, p, p, 1, 1, 1)
        y2, g2 = jax.value_and_grad(loss)(params, x)
        monkeypatch.delenv("SPARKNET_NO_S2D")
        np.testing.assert_allclose(np.asarray(y1), np.asarray(y2), rtol=2e-5)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-4, atol=1e-5)


def test_space_to_depth_gating():
    """Grouped, dilated, stride-1, and wide-C convs must NOT be rewritten."""
    from sparknet_tpu.ops import vision
    ok = vision._s2d_eligible
    assert not ok(3, 11, 11, 4, 4, 0, 0, 1, 1, 2)      # grouped
    assert not ok(3, 11, 11, 4, 4, 0, 0, 2, 2, 1)      # dilated
    assert not ok(3, 3, 3, 1, 1, 1, 1, 1, 1, 1)        # stride 1
    assert not ok(64, 3, 3, 2, 2, 1, 1, 1, 1, 1)       # C*s*s > 64
    assert not ok(3, 2, 2, 4, 4, 0, 0, 1, 1, 1)        # kernel < stride
    assert ok(3, 11, 11, 4, 4, 0, 0, 1, 1, 1)


def test_grouped_conv(rng):
    lp = make("Convolution", convolution_param={
        "num_output": 4, "kernel_size": 1, "group": 2})
    impl = get_layer_impl("Convolution")
    params = impl.init(rng, lp, [(1, 4, 2, 2)])
    assert params[0].shape == (4, 2, 1, 1)
    y = apply_op(lp, [np.ones((1, 4, 2, 2), np.float32)], params)
    assert y[0].shape == (1, 4, 2, 2)


def test_conv_gradients(rng, np_rng):
    lp = make("Convolution", convolution_param={
        "num_output": 2, "kernel_size": 3, "pad": 1, "stride": 2})
    impl = get_layer_impl("Convolution")
    params = impl.init(rng, lp, [(2, 3, 6, 6)])
    x = jnp.asarray(np_rng.normal(size=(2, 3, 6, 6)).astype(np.float32))

    def f(w, b, x):
        return impl.apply(lp, [w, b], [x], True, None)[0]

    check_grads(f, (params[0], params[1], x), order=1, modes=["rev"],
                atol=1e-2, rtol=1e-2)


def test_deconv_shape_and_transpose_equivalence(rng, np_rng):
    # deconv out = s(in-1) + k - 2p (deconv_layer.cpp)
    lp = make("Deconvolution", convolution_param={
        "num_output": 3, "kernel_size": 4, "stride": 2, "pad": 1})
    impl = get_layer_impl("Deconvolution")
    assert impl.out_shapes(lp, [(1, 2, 5, 5)]) == [(1, 3, 10, 10)]
    params = impl.init(rng, lp, [(1, 2, 5, 5)])
    assert params[0].shape == (2, 3, 4, 4)
    # equivalence: deconv(x, w) == vjp of conv wrt input with same geometry
    x = jnp.asarray(np_rng.normal(size=(1, 2, 5, 5)).astype(np.float32))
    w = params[0]
    y = impl.apply(lp, [w, jnp.zeros(3)], [x], True, None)[0]

    clp = make("Convolution", convolution_param={
        "num_output": 2, "kernel_size": 4, "stride": 2, "pad": 1,
        "bias_term": False})
    cimpl = get_layer_impl("Convolution")

    def conv_fn(inp):
        # conv maps (1,3,10,10) -> (1,2,5,5) with weight (out=2, in=3, 4, 4),
        # which is exactly the deconv blob (C_in=2, C_out=3, kh, kw)
        return cimpl.apply(clp, [w], [inp], True, None)[0]

    _, vjp = jax.vjp(conv_fn, jnp.zeros((1, 3, 10, 10)))
    ref = vjp(x)[0]
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref), rtol=1e-4,
                               atol=1e-4)


# -- pooling ----------------------------------------------------------------

def test_pool_output_size_ceil():
    # caffe pooling ceil: e.g. 6->3 with k3 s2: ceil((6-3)/2)+1 = 3
    assert pool_output_size(6, 6, 3, 3, 2, 2, 0, 0) == (3, 3)
    # 7 -> ceil((7-3)/2)+1 = 3
    assert pool_output_size(7, 7, 3, 3, 2, 2, 0, 0) == (3, 3)
    # 8 -> ceil(5/2)+1 = 4  (torch floor would give 3)
    assert pool_output_size(8, 8, 3, 3, 2, 2, 0, 0) == (4, 4)
    # padding clip: start of last window must be < h + p
    assert pool_output_size(4, 4, 2, 2, 2, 2, 1, 1) == (3, 3)


def test_max_pool_values():
    x = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)
    lp = make("Pooling", pooling_param={"pool": "MAX", "kernel_size": 2,
                                        "stride": 2})
    y = np.asarray(apply_op(lp, [x])[0])
    np.testing.assert_array_equal(y[0, 0], [[5, 7], [13, 15]])


def test_ave_pool_caffe_denominator():
    # with padding, caffe divides by the window clipped to [0, dim+pad)
    x = np.ones((1, 1, 2, 2), np.float32)
    lp = make("Pooling", pooling_param={"pool": "AVE", "kernel_size": 2,
                                        "stride": 2, "pad": 1})
    y = np.asarray(apply_op(lp, [x])[0])
    # out 2x2; each window covers exactly 1 real pixel but denominator is the
    # clipped window: corner windows span [−1,1)x[−1,1) -> clipped to
    # [−1,1)∩[0,3)=2x2... caffe: hstart=-1, hend=min(1, 2+1)=1 -> size 2x2=4?
    # Actually caffe clips hend to h+pad=3 (no-op here), pool_size=(1-(-1))²=4,
    # then sums only real pixels (1) -> 0.25.
    np.testing.assert_allclose(y[0, 0], [[0.25, 0.25], [0.25, 0.25]])


def test_global_pooling():
    x = np.arange(8, dtype=np.float32).reshape(1, 2, 2, 2)
    lp = make("Pooling", pooling_param={"pool": "AVE", "global_pooling": True})
    y = np.asarray(apply_op(lp, [x])[0])
    np.testing.assert_allclose(y.reshape(2), [1.5, 5.5])


def test_pool_gradients(np_rng):
    x = jnp.asarray(np_rng.normal(size=(1, 2, 6, 6)).astype(np.float32))
    for method in ("MAX", "AVE"):
        lp = make("Pooling", pooling_param={"pool": method, "kernel_size": 3,
                                            "stride": 2})
        impl = get_layer_impl("Pooling")
        f = lambda x: impl.apply(lp, [], [x], True, None)[0]
        check_grads(f, (x,), order=1, modes=["rev"], atol=1e-2, rtol=1e-2)


def test_stochastic_pool_train_samples_proportionally(np_rng):
    # non-overlapping 2x2 windows; element picked with prob ∝ value
    # (pooling_layer.cu StoPoolForwardTrain)
    x = np.zeros((1, 1, 2, 2), np.float32)
    x[0, 0] = [[1.0, 3.0], [0.0, 0.0]]
    lp = make("Pooling", pooling_param={"pool": "STOCHASTIC",
                                        "kernel_size": 2, "stride": 2})
    picks = []
    for i in range(400):
        y = np.asarray(apply_op(lp, [x], train=True,
                                rng=jax.random.PRNGKey(i))[0])
        assert y.reshape(()) in (1.0, 3.0)  # always a window element
        picks.append(float(y.reshape(())))
    frac3 = sum(1 for p in picks if p == 3.0) / len(picks)
    assert 0.65 < frac3 < 0.85  # expect 0.75


def test_stochastic_pool_train_gradient_routes_to_sample(np_rng):
    # d(sum y)/dx is a one-hot mask per (non-overlapping) window at the
    # sampled element — StoPoolBackward semantics
    x = jnp.asarray(np_rng.uniform(0.1, 1.0, size=(2, 3, 4, 4))
                    .astype(np.float32))
    lp = make("Pooling", pooling_param={"pool": "STOCHASTIC",
                                        "kernel_size": 2, "stride": 2})
    impl = get_layer_impl("Pooling")
    key = jax.random.PRNGKey(7)
    f = lambda x: jnp.sum(impl.apply(lp, [], [x], True, key)[0])
    g = np.asarray(jax.grad(f)(x))
    assert set(np.unique(g)) == {0.0, 1.0}
    # exactly one selected element per 2x2 window
    gsum = g.reshape(2, 3, 2, 2, 2, 2).sum(axis=(3, 5))
    np.testing.assert_array_equal(gsum, np.ones((2, 3, 2, 2)))
    # and the sampled value is what the forward returned
    y = np.asarray(impl.apply(lp, [], [x], True, key)[0])
    picked = (g * np.asarray(x)).reshape(2, 3, 2, 2, 2, 2).sum(axis=(3, 5))
    np.testing.assert_allclose(picked, y, rtol=1e-6)


def test_stochastic_pool_test_mode_weighted_average(np_rng):
    x = np.abs(np_rng.normal(size=(1, 2, 4, 4))).astype(np.float32)
    lp = make("Pooling", pooling_param={"pool": "STOCHASTIC",
                                        "kernel_size": 2, "stride": 2})
    y = np.asarray(apply_op(lp, [x], train=False)[0])
    # sum x^2 / sum x per window
    xr = x.reshape(1, 2, 2, 2, 2, 2).transpose(0, 1, 2, 4, 3, 5)
    num = (xr ** 2).sum(axis=(-1, -2))
    den = xr.sum(axis=(-1, -2))
    np.testing.assert_allclose(y, num / den, rtol=1e-5)
    assert get_layer_impl("Pooling").needs_rng(lp, train=True)
    assert not get_layer_impl("Pooling").needs_rng(lp, train=False)


# -- LRN --------------------------------------------------------------------

def test_lrn_across_channels_matches_numpy(np_rng):
    x = np_rng.normal(size=(2, 6, 3, 3)).astype(np.float32)
    lp = make("LRN", lrn_param={"local_size": 5, "alpha": 1e-4, "beta": 0.75})
    y = np.asarray(apply_op(lp, [x])[0])
    ref = np.empty_like(x)
    C = x.shape[1]
    for c in range(C):
        lo, hi = max(0, c - 2), min(C, c + 3)
        ssum = np.sum(x[:, lo:hi] ** 2, axis=1)
        scale = 1.0 + (1e-4 / 5) * ssum
        ref[:, c] = x[:, c] / scale ** 0.75
    np.testing.assert_allclose(y, ref, rtol=1e-5, atol=1e-6)


def test_lrn_gradient(np_rng):
    x = jnp.asarray(np_rng.normal(size=(1, 4, 3, 3)).astype(np.float32))
    lp = make("LRN", lrn_param={"local_size": 3, "alpha": 0.1, "beta": 0.75})
    impl = get_layer_impl("LRN")
    f = lambda x: impl.apply(lp, [], [x], True, None)[0]
    check_grads(f, (x,), order=1, modes=["rev"], atol=1e-2, rtol=1e-2)


def test_lrn_cumsum_reformulation_matches_default(np_rng, monkeypatch,
                                                  tmp_path):
    """A tuning-table pin of the cumsum lowering (prefix-sum window
    reformulation of the cross-channel sum) must match the
    reduce_window path to float tolerance — the window total is the
    same set of addends, associated differently — including the clipped
    windows at both channel edges, and its gradient must check (cumsum
    transpose)."""
    from sparknet_tpu.graph import tuner
    x = np_rng.normal(size=(2, 9, 5, 5)).astype(np.float32)
    lp = make("LRN", lrn_param={"local_size": 5, "alpha": 1e-2,
                                "beta": 0.75})
    base = np.asarray(apply_op(lp, [x])[0])
    key = tuner.key_str("lrn", x.shape, jnp.float32, tuner.lrn_extra(5))
    path = tmp_path / "pin.json"
    tuner.TuningTable(tuner._backend(), [
        {"key": key, "winner": "cumsum", "timings": {}}]).save(str(path))
    monkeypatch.setenv("SPARKNET_TUNE", str(path))
    tuner._clear_caches()
    fast = np.asarray(apply_op(lp, [x])[0])
    np.testing.assert_allclose(fast, base, rtol=1e-5, atol=1e-6)
    # bf16 input keeps its dtype out (f32 prefix accumulation inside)
    yb = apply_op(lp, [jnp.asarray(x, jnp.bfloat16)])[0]
    assert yb.dtype == jnp.bfloat16
    impl = get_layer_impl("LRN")
    f = lambda x: impl.apply(lp, [], [x], True, None)[0]
    check_grads(f, (jnp.asarray(x),), order=1, modes=["rev"],
                atol=1e-2, rtol=1e-2)


# -- inner product ----------------------------------------------------------

def test_inner_product(rng, np_rng):
    lp = make("InnerProduct", inner_product_param={"num_output": 7})
    impl = get_layer_impl("InnerProduct")
    assert impl.out_shapes(lp, [(4, 3, 2, 2)]) == [(4, 7)]
    params = impl.init(rng, lp, [(4, 3, 2, 2)])
    assert params[0].shape == (7, 12)
    x = np_rng.normal(size=(4, 3, 2, 2)).astype(np.float32)
    y = np.asarray(apply_op(lp, [x], params)[0])
    ref = x.reshape(4, 12) @ np.asarray(params[0]).T + np.asarray(params[1])
    np.testing.assert_allclose(y, ref, rtol=1e-4, atol=1e-5)


def test_inner_product_transpose(rng, np_rng):
    lp = make("InnerProduct", inner_product_param={"num_output": 5,
                                                   "transpose": True})
    impl = get_layer_impl("InnerProduct")
    params = impl.init(rng, lp, [(2, 6)])
    assert params[0].shape == (6, 5)


# -- neuron layers ----------------------------------------------------------

def test_relu_negative_slope():
    x = np.array([[-2.0, 3.0]], np.float32)
    lp = make("ReLU", relu_param={"negative_slope": 0.1})
    y = np.asarray(apply_op(lp, [x])[0])
    np.testing.assert_allclose(y, [[-0.2, 3.0]], rtol=1e-6)


def test_dropout_train_test(rng):
    x = np.ones((100, 100), np.float32)
    lp = make("Dropout", dropout_param={"dropout_ratio": 0.5})
    y_test = np.asarray(apply_op(lp, [x], train=False)[0])
    np.testing.assert_array_equal(y_test, x)
    y_train = np.asarray(apply_op(lp, [x], train=True, rng=rng)[0])
    # inverted dropout: survivors scaled by 2, mean preserved
    assert set(np.unique(y_train)) <= {0.0, 2.0}
    assert abs(y_train.mean() - 1.0) < 0.05


def test_power_exp_log_bnll_threshold_absval(np_rng):
    x = np.abs(np_rng.normal(size=(3, 4)).astype(np.float32)) + 0.5
    cases = [
        (make("Power", power_param={"power": 2.0, "scale": 3.0, "shift": 1.0}),
         (1 + 3 * x) ** 2),
        (make("Exp"), np.exp(x)),
        (make("Exp", exp_param={"base": 2.0}), 2.0 ** x),
        (make("Log"), np.log(x)),
        (make("AbsVal"), np.abs(x)),
        (make("BNLL"), np.log1p(np.exp(x))),
        (make("Threshold", threshold_param={"threshold": 1.0}),
         (x > 1.0).astype(np.float32)),
        (make("TanH"), np.tanh(x)),
        (make("Sigmoid"), 1 / (1 + np.exp(-x))),
    ]
    for lp, ref in cases:
        y = np.asarray(apply_op(lp, [x])[0])
        np.testing.assert_allclose(y, ref, rtol=1e-4, atol=1e-5,
                                   err_msg=lp.type)


def test_prelu(rng):
    lp = make("PReLU")
    impl = get_layer_impl("PReLU")
    params = impl.init(rng, lp, [(1, 3, 2, 2)])
    assert params[0].shape == (3,)
    np.testing.assert_allclose(np.asarray(params[0]), [0.25] * 3)
    x = -np.ones((1, 3, 2, 2), np.float32)
    y = np.asarray(apply_op(lp, [x], params)[0])
    np.testing.assert_allclose(y, -0.25 * np.ones_like(x))


# -- shape/common layers ----------------------------------------------------

def test_concat_slice_roundtrip(np_rng):
    x = np_rng.normal(size=(2, 6, 2, 2)).astype(np.float32)
    slp = layer("s", "Slice", ["b"], ["a", "b2", "c"],
                slice_param={"slice_point": [1, 3]})
    parts = apply_op(slp, [x])
    assert [p.shape[1] for p in parts] == [1, 2, 3]
    clp = layer("c", "Concat", ["a", "b2", "c"], ["out"])
    y = apply_op(clp, parts)[0]
    np.testing.assert_array_equal(np.asarray(y), x)


def test_flatten_reshape():
    x = np.zeros((2, 3, 4, 5), np.float32)
    f = make("Flatten")
    assert apply_op(f, [x])[0].shape == (2, 60)
    r = make("Reshape", reshape_param={"shape": {"dim": [0, -1, 10]}})
    assert apply_op(r, [x])[0].shape == (2, 6, 10)


def test_eltwise(np_rng):
    a = np_rng.normal(size=(2, 3)).astype(np.float32)
    b = np_rng.normal(size=(2, 3)).astype(np.float32)
    lp = layer("e", "Eltwise", ["a", "b"], ["o"],
               eltwise_param={"operation": "SUM", "coeff": [1.0, -1.0]})
    np.testing.assert_allclose(np.asarray(apply_op(lp, [a, b])[0]), a - b,
                               rtol=1e-6)
    lp2 = layer("e", "Eltwise", ["a", "b"], ["o"],
                eltwise_param={"operation": "MAX"})
    np.testing.assert_allclose(np.asarray(apply_op(lp2, [a, b])[0]),
                               np.maximum(a, b))
    lp3 = layer("e", "Eltwise", ["a", "b"], ["o"],
                eltwise_param={"operation": "PROD"})
    np.testing.assert_allclose(np.asarray(apply_op(lp3, [a, b])[0]), a * b,
                               rtol=1e-6)


def test_softmax_and_argmax(np_rng):
    x = np_rng.normal(size=(3, 5)).astype(np.float32)
    y = np.asarray(apply_op(make("Softmax"), [x])[0])
    e = np.exp(x - x.max(1, keepdims=True))
    np.testing.assert_allclose(y, e / e.sum(1, keepdims=True), rtol=1e-5,
                               atol=1e-6)
    am = np.asarray(apply_op(make("ArgMax"), [x])[0])
    np.testing.assert_array_equal(am.reshape(3), x.argmax(1))


def test_accuracy_topk():
    scores = np.array([[1, 2, 3], [3, 2, 1], [1, 3, 2]], np.float32)
    labels = np.array([2, 0, 0], np.float32)
    lp = layer("a", "Accuracy", ["s", "l"], ["acc"])
    acc = float(apply_op(lp, [scores, labels])[0])
    assert acc == pytest.approx(2 / 3)
    lp5 = layer("a", "Accuracy", ["s", "l"], ["acc"],
                accuracy_param={"top_k": 2})
    acc2 = float(apply_op(lp5, [scores, labels])[0])
    assert acc2 == pytest.approx(2 / 3)  # sample 3: label 0 ranks 3rd


def test_batchnorm_train_updates_stats(rng, np_rng):
    lp = make("BatchNorm")
    impl = get_layer_impl("BatchNorm")
    params = impl.init(rng, lp, [(4, 3, 2, 2)])
    x = jnp.asarray(np_rng.normal(loc=5.0, size=(4, 3, 2, 2)).astype(np.float32))
    (tops, new_params) = impl.apply(lp, params, [x], True, None)
    y = np.asarray(tops[0])
    assert abs(y.mean()) < 1e-5 and abs(y.std() - 1.0) < 1e-2
    # running stats accumulated
    assert float(new_params[2][0]) == pytest.approx(1.0)
    np.testing.assert_allclose(np.asarray(new_params[0]),
                               np.asarray(x.mean(axis=(0, 2, 3))), rtol=1e-4)
    # inference path uses the stats
    (tops2, _) = impl.apply(lp, new_params, [x], False, None)
    y2 = np.asarray(tops2[0])
    assert abs(y2.mean()) < 0.2


def test_scale_bias(rng, np_rng):
    x = np_rng.normal(size=(2, 3, 2, 2)).astype(np.float32)
    slp = make("Scale", scale_param={"bias_term": True})
    impl = get_layer_impl("Scale")
    params = impl.init(rng, slp, [x.shape])
    assert params[0].shape == (3,) and params[1].shape == (3,)
    y = np.asarray(apply_op(slp, [x], [jnp.full(3, 2.0), jnp.full(3, 1.0)])[0])
    np.testing.assert_allclose(y, 2 * x + 1, rtol=1e-5)


def test_mvn(np_rng):
    x = np_rng.normal(loc=3.0, scale=2.0, size=(2, 3, 4, 4)).astype(np.float32)
    y = np.asarray(apply_op(make("MVN"), [x])[0])
    m = y.mean(axis=(2, 3))
    s = y.std(axis=(2, 3))
    np.testing.assert_allclose(m, np.zeros_like(m), atol=1e-5)
    np.testing.assert_allclose(s, np.ones_like(s), atol=1e-2)


def test_embed(rng):
    lp = make("Embed", embed_param={"num_output": 4, "input_dim": 10})
    impl = get_layer_impl("Embed")
    params = impl.init(rng, lp, [(3,)])
    assert params[0].shape == (10, 4)
    idx = np.array([1, 5, 9], np.float32)
    y = apply_op(lp, [idx], params)[0]
    assert y.shape == (3, 4)


def test_tile_reduction_batchreindex(np_rng):
    x = np_rng.normal(size=(2, 3)).astype(np.float32)
    t = make("Tile", tile_param={"axis": 1, "tiles": 2})
    assert apply_op(t, [x])[0].shape == (2, 6)
    r = make("Reduction", reduction_param={"operation": "MEAN", "axis": 1})
    np.testing.assert_allclose(np.asarray(apply_op(r, [x])[0]), x.mean(1),
                               rtol=1e-5)
    br = layer("br", "BatchReindex", ["x", "i"], ["o"])
    idx = np.array([1, 1, 0], np.float32)
    y = np.asarray(apply_op(br, [x, idx])[0])
    np.testing.assert_array_equal(y, x[[1, 1, 0]])


# -- losses -----------------------------------------------------------------

def test_softmax_with_loss_matches_manual(np_rng):
    x = np_rng.normal(size=(4, 5)).astype(np.float32)
    labels = np.array([0, 1, 2, 3], np.float32)
    lp = layer("l", "SoftmaxWithLoss", ["x", "y"], ["loss"])
    loss = float(apply_op(lp, [x, labels])[0])
    e = np.exp(x - x.max(1, keepdims=True))
    p = e / e.sum(1, keepdims=True)
    ref = -np.mean(np.log(p[np.arange(4), labels.astype(int)]))
    assert loss == pytest.approx(ref, rel=1e-5)


def test_softmax_loss_ignore_label(np_rng):
    x = np_rng.normal(size=(4, 5)).astype(np.float32)
    labels = np.array([0, 1, 255, 3], np.float32)
    # ignore_label must drop sample 2 from both sum and count
    lp = layer("l", "SoftmaxWithLoss", ["x", "y"], ["loss"],
               loss_param={"ignore_label": 255})
    loss = float(apply_op(lp, [x, labels])[0])
    e = np.exp(x - x.max(1, keepdims=True))
    p = e / e.sum(1, keepdims=True)
    keep = [0, 1, 3]
    ref = -np.mean(np.log(p[keep, labels.astype(int)[keep]]))
    assert loss == pytest.approx(ref, rel=1e-4)


def test_euclidean_loss(np_rng):
    a = np_rng.normal(size=(3, 4)).astype(np.float32)
    b = np_rng.normal(size=(3, 4)).astype(np.float32)
    lp = layer("l", "EuclideanLoss", ["a", "b"], ["loss"])
    loss = float(apply_op(lp, [a, b])[0])
    assert loss == pytest.approx(((a - b) ** 2).sum() / 6, rel=1e-5)


def test_hinge_loss():
    s = np.array([[0.5, -0.5], [0.2, 0.3]], np.float32)
    y = np.array([0, 1], np.float32)
    lp = layer("l", "HingeLoss", ["s", "y"], ["loss"])
    # margins: sample0: max(0,1-0.5)+max(0,1-0.5)=1.0; sample1:
    # max(0,1+0.2)+max(0,1-0.3)=1.9 -> mean 1.45
    assert float(apply_op(lp, [s, y])[0]) == pytest.approx((1.0 + 1.9) / 2)


def test_sigmoid_ce_loss(np_rng):
    x = np_rng.normal(size=(3, 4)).astype(np.float32)
    t = (np_rng.uniform(size=(3, 4)) > 0.5).astype(np.float32)
    lp = layer("l", "SigmoidCrossEntropyLoss", ["x", "t"], ["loss"])
    loss = float(apply_op(lp, [x, t])[0])
    p = 1 / (1 + np.exp(-x))
    ref = -np.sum(t * np.log(p) + (1 - t) * np.log(1 - p)) / 3
    assert loss == pytest.approx(ref, rel=1e-4)


def test_contrastive_loss(np_rng):
    a = np_rng.normal(size=(4, 3)).astype(np.float32)
    b = np_rng.normal(size=(4, 3)).astype(np.float32)
    y = np.array([1, 0, 1, 0], np.float32)
    lp = layer("l", "ContrastiveLoss", ["a", "b", "y"], ["loss"])
    loss = float(apply_op(lp, [a, b, y])[0])
    d2 = ((a - b) ** 2).sum(1)
    d = np.sqrt(d2)
    neg = np.maximum(1.0 - d, 0) ** 2
    ref = np.sum(y * d2 + (1 - y) * neg) / 8
    assert loss == pytest.approx(ref, rel=1e-3)


def test_softmax_loss_normalize_false_axis(np_rng):
    """normalize=false divides by outer_num_ = prod(shape[:axis]), not the
    batch dim (softmax_loss_layer.cpp Forward) — differs when axis != 1."""
    x = np_rng.normal(size=(2, 3, 5)).astype(np.float32)  # axis=2: C=5
    labels = np_rng.integers(0, 5, size=(2, 3)).astype(np.float32)
    lp = layer("l", "SoftmaxWithLoss", ["x", "y"], ["loss"],
               softmax_param={"axis": 2}, loss_param={"normalize": False})
    loss = float(apply_op(lp, [x, labels])[0])
    logp = np.log(np.exp(x) / np.exp(x).sum(-1, keepdims=True))
    nll = -np.take_along_axis(
        logp, labels.astype(np.int64)[..., None], axis=-1)
    ref = nll.sum() / (2 * 3)  # outer_num_ = 6, not batch 2
    assert loss == pytest.approx(ref, rel=1e-4)


def test_filter_layer_eager_and_taint(np_rng):
    x = np_rng.normal(size=(4, 3)).astype(np.float32)
    sel = np.array([1, 0, 1, 0], np.float32)
    lp = layer("f", "Filter", ["x", "sel"], ["out"])
    out = apply_op(lp, [x, sel])[0]
    np.testing.assert_allclose(np.asarray(out), x[[0, 2]])

    # downstream of Filter: a consumer whose params ignore the batch dim
    # (InnerProduct axis=1) still builds — it runs fine eager — but one
    # whose param shapes depend on the batch dim (axis=0) is rejected
    from sparknet_tpu.graph import Net
    from sparknet_tpu.proto import load_net_prototxt
    ok_txt = """
    layer { name: "d" type: "Input" top: "x" top: "sel"
            input_param { shape { dim: 4 dim: 3 } shape { dim: 4 } } }
    layer { name: "f" type: "Filter" bottom: "x" bottom: "sel" top: "fx" }
    layer { name: "ip" type: "InnerProduct" bottom: "fx" top: "y"
            inner_product_param { num_output: 2
                                  weight_filler { type: "xavier" } } }
    """
    net = Net(load_net_prototxt(ok_txt))
    params = net.init(jax.random.PRNGKey(0))
    out = net.apply(params, {"x": jnp.asarray(x), "sel": jnp.asarray(sel)},
                    train=False)
    assert out.blobs["y"].shape == (2, 2)  # eager: real filtered batch

    bad_txt = ok_txt.replace("num_output: 2",
                             "num_output: 2 axis: 0")
    with pytest.raises(ValueError, match="data-dependent batch"):
        Net(load_net_prototxt(bad_txt))


def test_loss_gradients(np_rng):
    x = jnp.asarray(np_rng.normal(size=(4, 5)).astype(np.float32))
    labels = jnp.asarray(np.array([0, 1, 2, 3], np.float32))
    lp = layer("l", "SoftmaxWithLoss", ["x", "y"], ["loss"])
    impl = get_layer_impl("SoftmaxWithLoss")
    f = lambda x: impl.apply(lp, [], [x, labels], True, None)[0]
    check_grads(f, (x,), order=1, modes=["rev"], atol=1e-2, rtol=1e-2)


def test_infogain_loss_source_file(tmp_path, np_rng):
    """H supplied via infogain_loss_param.source (a BlobProto file) matches
    the third-bottom variant (infogain_loss_layer.cpp LayerSetUp)."""
    from sparknet_tpu.proto.caffemodel import save_mean_binaryproto

    probs = np.abs(np_rng.normal(size=(4, 3))).astype(np.float32)
    probs /= probs.sum(1, keepdims=True)
    labels = np.array([0, 1, 2, 1], np.float32)
    H = np.eye(3, dtype=np.float32) * 2.0
    path = str(tmp_path / "H.binaryproto")
    save_mean_binaryproto(path, H[None])

    lp3 = layer("l", "InfogainLoss", ["p", "y", "H"], ["loss"])
    ref = float(apply_op(lp3, [probs, labels, H])[0])
    lp2 = layer("l", "InfogainLoss", ["p", "y"], ["loss"],
                infogain_loss_param={"source": path})
    got = float(apply_op(lp2, [probs, labels])[0])
    assert got == pytest.approx(ref, rel=1e-5)


def test_accuracy_per_class_top(np_rng):
    scores = np.array([[3.0, 1.0, 0.0],
                       [0.0, 2.0, 1.0],
                       [1.0, 0.0, 3.0],
                       [2.0, 1.0, 0.0]], np.float32)
    labels = np.array([0, 1, 2, 1], np.float32)  # last one wrong (pred 0)
    lp = layer("a", "Accuracy", ["s", "y"], ["acc", "per_class"])
    from sparknet_tpu.ops import get_layer_impl
    impl = get_layer_impl("Accuracy")
    assert impl.out_shapes(lp, [(4, 3), (4,)]) == [(), (3,)]
    acc, per = apply_op(lp, [scores, labels])
    assert float(acc) == pytest.approx(0.75)
    np.testing.assert_allclose(np.asarray(per), [1.0, 0.5, 1.0])
