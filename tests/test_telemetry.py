"""Telemetry plane: metrics registry, span tracer, flight recorder,
trace merge, and the SPARKNET_TELEMETRY=0 off-path contract.

The off-path tests are the load-bearing ones: every hot seam (trainer
rounds, feed stages, serving demux) calls into this module per round /
per batch, so the disabled plane must be shared-singleton no-ops that
allocate nothing and never touch the filesystem.
"""

import glob
import json
import os
import subprocess
import sys

import pytest

from sparknet_tpu.utils import telemetry

pytestmark = pytest.mark.obs

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def tel(monkeypatch):
    """A clean telemetry plane: singletons dropped before AND after, so
    neighboring tests never see this test's env or registry."""
    for k in ("SPARKNET_TELEMETRY", "SPARKNET_TRACE_DIR",
              "SPARKNET_METRICS_SNAP", "SPARKNET_METRICS_SNAP_S",
              "SPARKNET_RUN_ID", "SPARKNET_TELEMETRY_RANK",
              "SPARKNET_FLIGHT_EVENTS"):
        monkeypatch.delenv(k, raising=False)
    telemetry.reset()
    yield monkeypatch
    telemetry.reset()


# ---------------------------------------------------------------------------
# Registry
# ---------------------------------------------------------------------------

def test_registry_counter_gauge_histogram(tel):
    reg = telemetry.get_registry()
    c = reg.counter("req_total", "requests")
    c.inc()
    c.inc(2, tenant="acme")
    assert c.value() == 1.0
    assert c.value(tenant="acme") == 2.0
    g = reg.gauge("depth")
    g.set(5)
    g.inc()
    g.dec(2)
    assert g.value() == 4.0
    h = reg.histogram("lat_seconds", buckets=(0.01, 0.1, 1.0))
    for v in (0.005, 0.05, 0.5, 5.0):
        h.observe(v)
    (key, (counts, total, n)), = h._samples()
    assert counts == [1, 1, 1, 1] and n == 4
    assert total == pytest.approx(5.555)
    # idempotent by name, typed on kind mismatch
    assert reg.counter("req_total") is c
    with pytest.raises(ValueError, match="already registered"):
        reg.gauge("req_total")


def test_registry_renders_parseable_prometheus(tel):
    sys.path.insert(0, os.path.join(REPO, "tools"))
    from obs import parse_prometheus

    reg = telemetry.get_registry()
    reg.counter("a_total", "with \"quotes\" and \\slashes").inc(
        3, path='/x"y\\z')
    reg.gauge("b").set(2.5, comp="feed")
    reg.histogram("c_seconds", buckets=(0.1, 1.0)).observe(0.2)
    text = reg.render()
    samples = parse_prometheus(text)
    assert samples["a_total"] == [('{path="/x\\"y\\\\z"}', 3.0)]
    assert samples["b"] == [('{comp="feed"}', 2.5)]
    # cumulative buckets + +Inf + sum + count
    assert [v for _, v in samples["c_seconds_bucket"]] == [0.0, 1.0, 1.0]
    assert samples["c_seconds_count"] == [("", 1.0)]


def test_histogram_timer_and_collector(tel):
    reg = telemetry.get_registry()
    h = reg.histogram("t_seconds")
    with h.time(op="x"):
        pass
    (_, (_, _, n)), = h._samples()
    assert n == 1
    calls = []
    reg.add_collector(lambda: calls.append(1) or reg.gauge("live").set(7))
    reg.add_collector(lambda: 1 / 0)   # broken collector must not break
    assert "live 7" in reg.render()
    assert calls == [1]


def test_snapshot_roundtrip_and_fold(tel, tmp_path):
    tel.setenv("SPARKNET_METRICS_SNAP", str(tmp_path))
    tel.setenv("SPARKNET_METRICS_SNAP_S", "0")
    telemetry.reset()
    reg = telemetry.get_registry()
    reg.counter("n_total").inc(3)
    reg.histogram("h_seconds", buckets=(1.0,)).observe(0.5)
    path = reg.maybe_snapshot()
    assert path and os.path.exists(path)
    doc = json.load(open(path))
    assert doc["metrics"]["n_total"]["samples"][0]["value"] == 3.0
    assert os.path.exists(path.replace(".json", ".prom"))

    # fold two ranks: counters sum, gauges newest-wins, histograms merge
    d2 = json.loads(json.dumps(doc))
    d2["t"] = doc["t"] + 1
    d2["rank"] = 1
    d2["metrics"]["g"] = {"kind": "gauge", "help": "", "samples": [
        {"labels": {}, "value": 9.0}]}
    p2 = tmp_path / "metrics_rank1.json"
    p2.write_text(json.dumps(d2))
    folded = telemetry.fold_snapshots([str(path), str(p2)])
    assert folded["n_total"]["samples"][0]["value"] == 6.0
    assert folded["h_seconds"]["samples"][0]["count"] == 2
    assert folded["g"]["samples"][0]["value"] == 9.0


# ---------------------------------------------------------------------------
# Tracer + flight recorder
# ---------------------------------------------------------------------------

def test_tracer_shard_spans_and_correlation(tel, tmp_path):
    tel.setenv("SPARKNET_TRACE_DIR", str(tmp_path))
    tel.setenv("SPARKNET_RUN_ID", "t-run")
    tel.setenv("SPARKNET_TELEMETRY_RANK", "3")
    telemetry.reset()
    assert telemetry.tracing()
    with telemetry.span("work", cat="test", round=7):
        pass
    telemetry.note_span("late", 0.25, cat="test")
    telemetry.instant("mark", cat="test")
    telemetry.get_tracer().flush()
    shard, = glob.glob(str(tmp_path / "trace_t-run_rank3_*.jsonl"))
    events = [json.loads(l) for l in open(shard)]
    spans = {e["name"]: e for e in events if e.get("ph") == "X"}
    assert set(spans) == {"work", "late"}
    for e in spans.values():
        assert e["args"]["run"] == "t-run" and e["args"]["rank"] == 3
    assert spans["work"]["args"]["round"] == 7
    assert spans["late"]["dur"] == 250000
    assert any(e.get("ph") == "i" and e["name"] == "mark" for e in events)


def test_flight_recorder_ring_and_dump(tel, tmp_path):
    tel.setenv("SPARKNET_FLIGHT_EVENTS", "8")
    telemetry.reset()
    rec = telemetry.get_recorder()
    for i in range(20):
        rec.record("tick", i=i)
    tail = rec.tail()
    assert len(tail) == 8 and tail[-1]["i"] == 19   # bounded ring
    doc = rec.dump("guard_trip", directory=str(tmp_path))
    assert doc["reason"] == "guard_trip" and len(doc["events"]) == 8
    assert "run" in doc and "rank" in doc
    dump, = glob.glob(str(tmp_path / "flight_rank*guard_trip.json"))
    assert json.load(open(dump))["events"] == doc["events"]


def test_obs_merge_aligns_and_checks(tel, tmp_path):
    sys.path.insert(0, os.path.join(REPO, "tools"))
    from obs import check_trace, load_shards, merge_events, trace_rollup

    for rank, t0 in ((0, 5_000_000), (1, 5_200_000)):
        tel.setenv("SPARKNET_TRACE_DIR", str(tmp_path))
        tel.setenv("SPARKNET_RUN_ID", "m")
        tel.setenv("SPARKNET_TELEMETRY_RANK", str(rank))
        telemetry.reset()
        tr = telemetry.get_tracer()
        tr.complete("round", "trainer", t0, 1000, {"round": rank})
        tr.flush()
        # distinct shard files per "rank": pid is shared in-process, so
        # rename the shard the way two real processes would differ
        os.replace(tr.path, str(tmp_path / f"trace_m_rank{rank}_x.jsonl"))
    telemetry.reset()
    events, shards = load_shards(str(tmp_path))
    assert len(shards) == 2
    merged = merge_events(events)
    rollup = trace_rollup(merged["traceEvents"])
    assert check_trace(merged["traceEvents"], rollup, expect_ranks=2) == []
    timed = [e for e in merged["traceEvents"] if "ts" in e]
    assert timed[0]["ts"] == 0                     # rebased to origin
    assert merged["otherData"]["epoch_us_origin"] == 5_000_000
    assert [e["ts"] for e in timed] == sorted(e["ts"] for e in timed)
    assert sorted(rollup["ranks"]) == ["0", "1"]
    # a shard-less dir and a rank shortfall are detected, not ignored
    assert check_trace(merged["traceEvents"], rollup, expect_ranks=3)


# ---------------------------------------------------------------------------
# The SPARKNET_TELEMETRY=0 off path
# ---------------------------------------------------------------------------

def test_disabled_plane_is_shared_noops(tel, tmp_path):
    tel.setenv("SPARKNET_TELEMETRY", "0")
    tel.setenv("SPARKNET_TRACE_DIR", str(tmp_path / "trace"))
    tel.setenv("SPARKNET_METRICS_SNAP", str(tmp_path / "snap"))
    telemetry.reset()
    reg = telemetry.get_registry()
    # every ask returns the SAME shared null metric — zero per-seam state
    c = reg.counter("a_total")
    assert c is reg.gauge("b") is reg.histogram("c") is telemetry.NULL_METRIC
    c.inc(5, x=1)
    c.observe(2.0)
    assert c.value() == 0.0
    assert c.time() is telemetry.NULL_SPAN
    # spans and the recorder are no-ops; tracing is off despite the dir
    assert telemetry.span("x", round=1) is telemetry.NULL_SPAN
    assert telemetry.get_tracer() is None and not telemetry.tracing()
    rec = telemetry.get_recorder()
    rec.record("guard_trip", round=3)
    assert rec.tail() == []
    assert rec.dump("guard_trip")["events"] == []
    telemetry.note_span("y", 1.0)
    telemetry.instant("z")
    # nothing rendered, nothing snapshotted, nothing on disk
    assert reg.render() == "" and reg.snapshot() == {}
    assert reg.write_snapshot() is None and reg.maybe_snapshot() is None
    assert not os.path.exists(tmp_path / "trace")
    assert not os.path.exists(tmp_path / "snap")


def test_disabled_plane_allocates_nothing_per_round(tel):
    """The no-op registry's per-round cost: zero retained allocations.
    1000 simulated rounds of the trainer's per-round telemetry calls
    must not grow traced memory at all — the off switch is free."""
    import tracemalloc

    tel.setenv("SPARKNET_TELEMETRY", "0")
    telemetry.reset()
    reg = telemetry.get_registry()
    c = reg.counter("rounds_total")
    g = reg.gauge("stall_seconds")
    h = reg.histogram("stage_seconds")

    def one_round(i):
        c.inc()
        g.set(float(i), comp="harvest")
        h.observe(0.001, stage="decode")
        with telemetry.span("trainer.round", round=i):
            pass
        telemetry.note_span("feed.decode", 0.001)
        reg.maybe_snapshot()

    for i in range(1000):   # warm lazy interpreter/method caches fully
        one_round(i)
    tracemalloc.start()
    try:
        before, _ = tracemalloc.get_traced_memory()
        for i in range(1000):
            one_round(i)
        after, _ = tracemalloc.get_traced_memory()
    finally:
        tracemalloc.stop()
    grown = after - before
    # a single retained object per round would show as >= 28 KB here;
    # the sub-KB floor is tracemalloc's own frame bookkeeping noise
    assert grown < 2048, (
        f"disabled telemetry retained {grown} bytes over 1000 rounds")


def test_trainer_seam_survives_disabled_plane(tel):
    """The trainer's cached metric handles work as no-ops end to end:
    FeedStats (the feed seam) records through a disabled plane without
    side effects."""
    tel.setenv("SPARKNET_TELEMETRY", "0")
    telemetry.reset()
    from sparknet_tpu.data.pipeline import FeedStats

    st = FeedStats()
    with st.timed("decode", records=4):
        pass
    st.count_batch(4)
    st.note_cache(True)
    snap = st.snapshot()
    assert snap["batches"] == 1 and snap["cache_hits"] == 1
    assert snap["records"] == 8 and snap["decode_s"] >= 0.0
    assert telemetry.get_registry().render() == ""


# ---------------------------------------------------------------------------
# Off-path parity: the existing correctness gates, telemetry disabled
# ---------------------------------------------------------------------------

def test_roundbench_parity_with_telemetry_off(tmp_path):
    """tools/roundbench.py (sync-vs-async bit parity + stall accounting)
    passes identically under SPARKNET_TELEMETRY=0 — the off switch
    cannot perturb the outer loop's numerics or its stall numbers."""
    env = {k: v for k, v in os.environ.items()
           if not k.startswith("SPARKNET_")}
    env.update(JAX_PLATFORMS="cpu", SPARKNET_TELEMETRY="0")
    out = tmp_path / "rb.json"
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "roundbench.py"),
         "--rounds", "3", "--out", str(out)],
        env=env, capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, r.stdout + r.stderr
    doc = json.load(open(out))
    assert doc["ok"] and "stall" in json.dumps(doc)


def test_serving_bit_identity_with_telemetry_off(tmp_path):
    """tools/serveload.py --smoke (batched-vs-solo bit identity +
    admission control) passes under SPARKNET_TELEMETRY=0."""
    env = {k: v for k, v in os.environ.items()
           if not k.startswith("SPARKNET_")}
    env.update(JAX_PLATFORMS="cpu", SPARKNET_TELEMETRY="0")
    out = tmp_path / "sl.json"
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "serveload.py"),
         "--smoke", "--out", str(out)],
        env=env, capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, r.stdout + r.stderr
    v = json.load(open(out))["verdicts"]
    assert v["bit_identical"] and v["overload_rejected"]
