from .textformat import PMessage, parse, serialize, ParseError
from .wireformat import WireError, decode as decode_wire, encode as encode_wire
from .caffemodel import (
    array_to_blob,
    load_caffemodel,
    load_mean_binaryproto,
    load_net_binaryproto,
    load_solverstate,
    save_caffemodel,
    save_mean_binaryproto,
    save_solverstate,
)
from .caffe_pb import (
    blob_to_array,
    BlobShape,
    FillerParameter,
    LayerParameter,
    NetParameter,
    NetState,
    NetStateRule,
    SolverParameter,
    Phase,
    load_net_prototxt,
    save_net_prototxt,
    load_solver_prototxt,
    load_solver_prototxt_with_net,
    replace_data_layers,
)
