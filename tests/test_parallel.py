"""Distributed-training tests on the virtual 8-device CPU mesh — the
multi-node coverage the reference never had (SURVEY.md §4.1: "there are no
distributed tests"; the CPU_ONLY analog per §4.3)."""

import itertools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sparknet_tpu.data import make_minibatches
from sparknet_tpu.models import lenet
from sparknet_tpu.parallel import DistributedTrainer, TrainerConfig, make_mesh
from sparknet_tpu.proto import load_solver_prototxt_with_net
from sparknet_tpu.solvers import Solver

SOLVER_TXT = 'base_lr: 0.05\nmomentum: 0.9\nlr_policy: "fixed"\n'


def synth(np_rng, n, shape=(1, 28, 28), num_classes=10):
    labels = np_rng.integers(0, num_classes, size=n)
    x = np_rng.normal(scale=0.3, size=(n, *shape)).astype(np.float32)
    for k in range(num_classes):
        x[labels == k, :, k % shape[1], :] += 2.0
    return x, labels.astype(np.float32)


def round_batches(np_rng, tau, global_batch):
    x, y = synth(np_rng, tau * global_batch)
    return {"data": x.reshape(tau, global_batch, 1, 28, 28),
            "label": y.reshape(tau, global_batch)}


def test_mesh_shapes():
    mesh = make_mesh(8)
    assert mesh.shape == {"data": 8, "model": 1}
    mesh2 = make_mesh(8, model_parallel=2)
    assert mesh2.shape == {"data": 4, "model": 2}
    with pytest.raises(ValueError):
        make_mesh(6, model_parallel=4)


@pytest.mark.parametrize("strategy", ["sync", "local_sgd"])
def test_distributed_loss_decreases(strategy, np_rng):
    # lr 0.01: local_sgd workers see batch 4 — 0.05 genuinely diverges there
    sp = load_solver_prototxt_with_net(
        'base_lr: 0.01\nmomentum: 0.9\nlr_policy: "fixed"\n', lenet(32, 32))
    mesh = make_mesh(8)
    tr = DistributedTrainer(sp, mesh, TrainerConfig(strategy=strategy, tau=5),
                            seed=0)
    assert tr.n_workers == 8
    losses = [tr.train_round(round_batches(np_rng, 5, 32)) for _ in range(6)]
    assert losses[0] == pytest.approx(np.log(10), rel=0.3)
    assert losses[-1] < 0.5 * losses[0]
    assert tr.iter == 30


def test_sync_matches_single_process_bigbatch(np_rng):
    """Gradient-pmean over 4 shards of batch 32 == single-device batch 32
    (the correctness invariant P2PSync relies on)."""
    sp = load_solver_prototxt_with_net(SOLVER_TXT, lenet(32, 32))
    x, y = synth(np_rng, 64)

    single = Solver(sp, seed=0)
    mesh = make_mesh(4)
    tr = DistributedTrainer(sp, mesh, TrainerConfig(strategy="sync", tau=1),
                            seed=0)
    # same seed -> identical initial params
    np.testing.assert_allclose(np.asarray(single.params["conv1"][0]),
                               np.asarray(tr.params["conv1"][0]))
    single.set_train_data(itertools.cycle(
        [{"data": x[i:i + 32], "label": y[i:i + 32]} for i in range(0, 64, 32)]))
    single.step(2)
    for i in range(0, 64, 32):
        tr.train_round({"data": x[i:i + 32][None], "label": y[i:i + 32][None]})

    for k in single.params:
        for a, b in zip(single.params[k], tr.params[k]):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-4, atol=2e-5)


def test_local_sgd_weight_averaging_semantics(np_rng):
    """After one round of τ=3, params must equal the mean of what each
    worker would have computed alone on its shard (SparkNet's
    WeightCollection.add / scalarDivide invariant)."""
    sp = load_solver_prototxt_with_net(SOLVER_TXT, lenet(8, 8))
    mesh = make_mesh(2)
    tr = DistributedTrainer(sp, mesh, TrainerConfig(strategy="local_sgd",
                                                    tau=3), seed=0)
    init_params = jax.tree_util.tree_map(np.asarray, tr.params)
    batches = round_batches(np_rng, 3, 16)
    tr.train_round(batches)

    # replay each worker locally with a plain Solver starting from the same
    # params and its own data shard + the same per-worker rng stream
    rng0 = jax.random.PRNGKey(0)
    _, run_rng = jax.random.split(rng0)          # trainer's self._rng
    round_rng, _ = jax.random.split(run_rng)     # rng passed into round 1
    worker_params = []
    for w in range(2):
        s = Solver(sp, seed=0)
        s.params = jax.tree_util.tree_map(jnp.asarray, init_params)
        shard = {k: v[:, 8 * w:8 * (w + 1)] for k, v in batches.items()}
        feed = iter([{k: v[t] for k, v in shard.items()} for t in range(3)])
        s.set_train_data(feed)
        # mirror the trainer's rng chain for this worker
        wrng = jax.random.fold_in(round_rng, w)
        for _ in range(3):
            wrng, sub = jax.random.split(wrng)
            batch = next(s._train_iter)
            stacked = {k: jnp.asarray(v)[None] for k, v in batch.items()}
            s.params, s.state, _ = s._step(s.params, s.state, s.iter, stacked, sub)
            s.iter += 1
        worker_params.append(s.params)

    for k in worker_params[0]:
        for i, blob in enumerate(worker_params[0][k]):
            avg = (np.asarray(blob) + np.asarray(worker_params[1][k][i])) / 2
            np.testing.assert_allclose(np.asarray(tr.params[k][i]), avg,
                                       rtol=2e-4, atol=2e-5)


def test_distributed_test_aggregation(np_rng):
    sp = load_solver_prototxt_with_net(SOLVER_TXT, lenet(32, 32))
    mesh = make_mesh(8)
    tr = DistributedTrainer(sp, mesh, TrainerConfig(strategy="sync"), seed=0)
    x, y = synth(np_rng, 64)
    feed = itertools.cycle([{"data": x[i:i + 32], "label": y[i:i + 32]}
                            for i in range(0, 64, 32)])
    scores = tr.test(feed, num_steps=2)
    assert "accuracy" in scores
    # raw worker-batch sums + count (ImageNetApp.scala:139-140 contract)
    assert scores["__test_batches__"] == 16  # 8 workers × 2 steps
    assert 0.0 <= scores["accuracy"] / scores["__test_batches__"] <= 1.0


def test_trainer_snapshot_restore(tmp_path, np_rng):
    sp = load_solver_prototxt_with_net(SOLVER_TXT, lenet(16, 16))
    mesh = make_mesh(4)
    cfg = TrainerConfig(strategy="local_sgd", tau=2)
    tr = DistributedTrainer(sp, mesh, cfg, seed=0)
    tr.train_round(round_batches(np_rng, 2, 16))
    p = str(tmp_path / "dist.npz")
    tr.snapshot(p)
    tr2 = DistributedTrainer(sp, mesh, cfg, seed=5)
    tr2.restore(p)
    assert tr2.iter == 2
    np.testing.assert_allclose(np.asarray(tr2.params["conv1"][0]),
                               np.asarray(tr.params["conv1"][0]))
    # momentum state restored per-worker
    chex_tree = jax.tree_util.tree_leaves(tr2.state)
    assert all(l.shape[0] == 4 for l in chex_tree)


def test_restore_rejects_mismatched_strategy_or_workers(tmp_path, np_rng):
    sp = load_solver_prototxt_with_net(SOLVER_TXT, lenet(16, 16))
    tr = DistributedTrainer(sp, make_mesh(4),
                            TrainerConfig(strategy="sync"), seed=0)
    p = str(tmp_path / "sync.npz")
    tr.snapshot(p)
    wrong_strategy = DistributedTrainer(
        sp, make_mesh(4), TrainerConfig(strategy="local_sgd"), seed=0)
    with pytest.raises(ValueError, match="strategy"):
        wrong_strategy.restore(p)
    wrong_mesh = DistributedTrainer(
        sp, make_mesh(8), TrainerConfig(strategy="sync"), seed=0)
    with pytest.raises(ValueError, match="workers"):
        wrong_mesh.restore(p)


def test_eval_batch_divisibility(np_rng):
    sp = load_solver_prototxt_with_net(SOLVER_TXT, lenet(8, 8))
    tr = DistributedTrainer(sp, make_mesh(8), TrainerConfig(), seed=0)
    feed = iter([{"data": np.zeros((60, 1, 28, 28), np.float32),
                  "label": np.zeros(60, np.float32)}])
    with pytest.raises(ValueError, match="not divisible"):
        tr.test(feed, 1)


def test_batch_divisibility_validation(np_rng):
    sp = load_solver_prototxt_with_net(SOLVER_TXT, lenet(8, 8))
    tr = DistributedTrainer(sp, make_mesh(8), TrainerConfig(tau=1), seed=0)
    with pytest.raises(ValueError, match="not divisible"):
        tr.train_round({"data": np.zeros((1, 12, 1, 28, 28), np.float32),
                        "label": np.zeros((1, 12), np.float32)})
    with pytest.raises(ValueError, match="!= tau"):
        tr.train_round({"data": np.zeros((2, 16, 1, 28, 28), np.float32),
                        "label": np.zeros((2, 16), np.float32)})


def test_iter_size_matches_bigbatch(np_rng):
    """iter_size accumulation inside the compiled round: 2 micro-batches of
    B accumulated then normalized == one batch of 2B (solver.cpp:221-224
    semantics; fixes ADVICE r1 #1)."""
    x, y = synth(np_rng, 32)
    mesh = make_mesh(4)

    sp2 = load_solver_prototxt_with_net(
        SOLVER_TXT + "iter_size: 2\n", lenet(16, 16))
    tr2 = DistributedTrainer(sp2, mesh, TrainerConfig(strategy="sync", tau=1),
                             seed=0)
    assert tr2.batches_per_round == 2
    tr2.train_round({"data": x.reshape(2, 16, 1, 28, 28),
                     "label": y.reshape(2, 16)})

    sp1 = load_solver_prototxt_with_net(SOLVER_TXT, lenet(32, 32))
    tr1 = DistributedTrainer(sp1, mesh, TrainerConfig(strategy="sync", tau=1),
                             seed=0)
    tr1.train_round({"data": x.reshape(1, 32, 1, 28, 28),
                     "label": y.reshape(1, 32)})

    for k in tr1.params:
        for a, b in zip(tr1.params[k], tr2.params[k]):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-5)


def test_iter_size_local_sgd_runs(np_rng):
    sp = load_solver_prototxt_with_net(
        SOLVER_TXT + "iter_size: 2\n", lenet(16, 16))
    tr = DistributedTrainer(sp, make_mesh(4),
                            TrainerConfig(strategy="local_sgd", tau=2), seed=0)
    assert tr.batches_per_round == 4
    x, y = synth(np_rng, 4 * 16)
    loss = tr.train_round({"data": x.reshape(4, 16, 1, 28, 28),
                           "label": y.reshape(4, 16)})
    assert np.isfinite(loss)
    assert tr.iter == 2  # iter counts steps, not micro-batches


def test_trainer_snapshot_on_schedule(tmp_path, np_rng):
    """sp.snapshot fires at round boundaries when an iter multiple is
    crossed (reference: solver.cpp:270-277)."""
    import os

    sp = load_solver_prototxt_with_net(SOLVER_TXT, lenet(8, 8))
    sp.snapshot = 4
    sp.snapshot_prefix = str(tmp_path / "sched")
    tr = DistributedTrainer(sp, make_mesh(4),
                            TrainerConfig(strategy="sync", tau=2), seed=0)
    for _ in range(2):
        tr.train_round(round_batches(np_rng, 2, 8))
    assert os.path.exists(str(tmp_path / "sched") + "_iter_4.npz")


def test_sync_state_only_pmean_preserves_replication(np_rng):
    """BN-bearing net under sync DP: running stats stay replicated while
    only state blobs ride the per-step collective (VERDICT r1 weak #7)."""
    from sparknet_tpu.models.dsl import java_data_layer, layer, net_param

    net = net_param("bn_net", [
        java_data_layer("input", ["data", "label"], None, (16, 1, 8, 8),
                        (16,)),
        layer("conv1", "Convolution", ["data"], ["conv1"],
              convolution_param={"num_output": 4, "kernel_size": 3,
                                 "weight_filler": {"type": "xavier"}}),
        layer("bn1", "BatchNorm", ["conv1"], ["bn1"]),
        layer("relu1", "ReLU", ["bn1"], ["bn1r"]),
        layer("ip", "InnerProduct", ["bn1r"], ["ip"],
              inner_product_param={"num_output": 10,
                                   "weight_filler": {"type": "xavier"}}),
        layer("loss", "SoftmaxWithLoss", ["ip", "label"], ["loss"]),
    ])
    sp = load_solver_prototxt_with_net(SOLVER_TXT, net)
    tr = DistributedTrainer(sp, make_mesh(4),
                            TrainerConfig(strategy="sync", tau=2), seed=0)
    x, y = synth(np_rng, 32, shape=(1, 8, 8))
    loss = tr.train_round({"data": x.reshape(2, 16, 1, 8, 8),
                           "label": y.reshape(2, 16)})
    assert np.isfinite(loss)
    # replicated out_spec holds: all per-device copies of the BN stats agree
    bn_key = next(k for k in tr.params if "bn" in k)
    for blob in tr.params[bn_key]:
        shards = [np.asarray(s.data) for s in blob.addressable_shards]
        for s in shards[1:]:
            np.testing.assert_allclose(shards[0], s, rtol=1e-6)


def test_device_preprocess_round(np_rng):
    """TrainerConfig.device_preprocess crops/mirrors/mean-subtracts inside
    the compiled round: the net sees crop-sized inputs while the feed
    ships raw full-size images (the TPU-native feed-bottleneck fix)."""
    from sparknet_tpu.models.dsl import java_data_layer, layer, net_param
    from sparknet_tpu.parallel import device_crop_mirror_mean

    crop, full = 6, 8
    net = net_param("devpre", [
        java_data_layer("input", ["data", "label"], None,
                        (8, 1, crop, crop), (8,)),
        layer("ip", "InnerProduct", ["data"], ["ip"],
              inner_product_param={"num_output": 4,
                                   "weight_filler": {"type": "xavier"}}),
        layer("loss", "SoftmaxWithLoss", ["ip", "label"], ["loss"]),
    ])
    sp = load_solver_prototxt_with_net(SOLVER_TXT, net)
    mean = np_rng.normal(size=(1, full, full)).astype(np.float32)
    for strategy in ("local_sgd", "sync"):
        tr = DistributedTrainer(
            sp, make_mesh(2),
            TrainerConfig(strategy=strategy, tau=2,
                          device_preprocess=device_crop_mirror_mean(
                              crop, mirror=True, mean=mean)), seed=0)
        x = np_rng.normal(size=(2, 8, 1, full, full)).astype(np.float32)
        y = np_rng.integers(0, 4, size=(2, 8)).astype(np.float32)
        loss = tr.train_round({"data": x, "label": y})
        assert np.isfinite(loss), strategy


def test_device_preprocess_deterministic_semantics(np_rng):
    """With crop == input size and mirror off, the on-device path reduces
    to exactly the host path's mean subtraction — same round result."""
    from sparknet_tpu.models.dsl import java_data_layer, layer, net_param
    from sparknet_tpu.parallel import device_crop_mirror_mean

    size = 6
    net = net_param("devpre_eq", [
        java_data_layer("input", ["data", "label"], None,
                        (8, 1, size, size), (8,)),
        layer("ip", "InnerProduct", ["data"], ["ip"],
              inner_product_param={"num_output": 3,
                                   "weight_filler": {"type": "xavier"}}),
        layer("loss", "SoftmaxWithLoss", ["ip", "label"], ["loss"]),
    ])
    sp = load_solver_prototxt_with_net(SOLVER_TXT, net)
    mean = np_rng.normal(size=(1, size, size)).astype(np.float32)
    x = np_rng.normal(size=(2, 8, 1, size, size)).astype(np.float32)
    y = np_rng.integers(0, 3, size=(2, 8)).astype(np.float32)

    tr_host = DistributedTrainer(
        sp, make_mesh(2), TrainerConfig(strategy="sync", tau=2), seed=0)
    loss_host = tr_host.train_round({"data": x - mean, "label": y})

    tr_dev = DistributedTrainer(
        sp, make_mesh(2),
        TrainerConfig(strategy="sync", tau=2,
                      device_preprocess=device_crop_mirror_mean(
                          size, mirror=False, mean=mean)), seed=0)
    loss_dev = tr_dev.train_round({"data": x, "label": y})
    np.testing.assert_allclose(float(loss_host), float(loss_dev), rtol=1e-5)
    for k in tr_host.params:
        for a, b in zip(tr_host.params[k], tr_dev.params[k]):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-5, atol=1e-6)


def test_device_preprocess_crop_sized_mean(np_rng):
    """A crop-sized (pycaffe mean-file style) mean works on the device
    path, subtracted after cropping; a nonsense shape fails clearly."""
    import pytest

    from sparknet_tpu.parallel import device_crop_mirror_mean

    crop, full = 4, 6
    mean_c = np_rng.normal(size=(1, crop, crop)).astype(np.float32)
    pre = device_crop_mirror_mean(crop, mirror=False, mean=mean_c)
    x = np_rng.normal(size=(2, 3, 1, full, full)).astype(np.float32)
    import jax
    out = pre({"data": x}, jax.random.PRNGKey(0))["data"]
    assert out.shape == (2, 3, 1, crop, crop)

    bad = device_crop_mirror_mean(crop, mean=np.zeros((1, 5, 5), np.float32))
    with pytest.raises(ValueError, match="matches neither"):
        bad({"data": x}, jax.random.PRNGKey(0))


def test_uneven_partition_eval_matches_per_worker_truth(np_rng):
    """Reference semantics for unequal partitions (each zipPartitions
    worker tests its OWN `len` batches — ImageNetApp.scala:108-141): the
    masked SPMD eval must equal per-worker truth computed one partition
    at a time on a 1-device mesh."""
    from sparknet_tpu.apps.common import eval_feed
    from sparknet_tpu.data.partition import PartitionedDataset

    def mk_items(n, seed):
        r = np.random.default_rng(seed)
        return [(r.normal(size=(1, 28, 28)).astype(np.float32),
                 float(r.integers(0, 10))) for _ in range(n)]

    # sizes 6,4,4,2 with batch 2 -> per-worker steps 3,2,2,1; lockstep 3
    parts = [mk_items(6, 0), mk_items(4, 1), mk_items(4, 2), mk_items(2, 3)]
    ds = PartitionedDataset(parts)
    factory, steps = eval_feed(ds, per_worker_batch=2)
    assert steps == 3

    sp = load_solver_prototxt_with_net(SOLVER_TXT, lenet(8, 8))
    tr = DistributedTrainer(sp, make_mesh(4), TrainerConfig(), seed=0)
    totals = tr.test(factory(), steps)
    assert totals["__test_batches__"] == 8.0  # 3+2+2+1

    # ground truth: a single-worker mesh scores each partition's batches
    sp1 = load_solver_prototxt_with_net(SOLVER_TXT, lenet(2, 2))
    tr1 = DistributedTrainer(sp1, make_mesh(1), TrainerConfig(), seed=0)
    for k in tr.params:  # identical weights
        for a, b in zip(tr.params[k], tr1.params[k]):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b))
    truth: dict = {}
    for p in parts:
        for t in range(len(p) // 2):
            recs = p[t * 2:(t + 1) * 2]
            feed1 = iter([{
                "data": np.stack([r[0] for r in recs]),
                "label": np.asarray([r[1] for r in recs], np.float32)}])
            s = tr1.test(feed1, 1)
            for k, v in s.items():
                truth[k] = truth.get(k, 0.0) + v
    assert truth.pop("__test_batches__") == 8.0
    for k, v in truth.items():
        np.testing.assert_allclose(totals[k], v, rtol=1e-5, atol=1e-6,
                                   err_msg=k)


BN_DP_NET = """
name: "bn_dp"
layer { name: "data" type: "Input" top: "data"
  input_param { shape { dim: 8 dim: 1 dim: 12 dim: 12 } } }
layer { name: "label" type: "Input" top: "label"
  input_param { shape { dim: 8 } } }
layer { name: "conv1" type: "Convolution" bottom: "data" top: "conv1"
  convolution_param { num_output: 4 kernel_size: 3
    weight_filler { type: "gaussian" std: 0.1 } } }
layer { name: "bn1" type: "BatchNorm" bottom: "conv1" top: "bn1" }
layer { name: "sc1" type: "Scale" bottom: "bn1" top: "sc1"
  scale_param { bias_term: true } }
layer { name: "relu1" type: "ReLU" bottom: "sc1" top: "sc1" }
layer { name: "ip" type: "InnerProduct" bottom: "sc1" top: "ip"
  inner_product_param { num_output: 5 weight_filler { type: "xavier" } } }
layer { name: "loss" type: "SoftmaxWithLoss" bottom: "ip" bottom: "label" }
"""


def test_local_sgd_averages_bn_running_stats(np_rng):
    """SparkNet's weight averaging iterates EVERY blob — BatchNorm
    running stats included (WeightCollection.add, Net.scala:27-46 sums
    all weights of all layers; the driver then scalarDivides).  The
    local_sgd round must do the same: after one round the BN blobs equal
    the mean of the per-worker stats, which genuinely differ across data
    shards."""
    from sparknet_tpu.proto import load_net_prototxt

    sp = load_solver_prototxt_with_net(SOLVER_TXT,
                                       load_net_prototxt(BN_DP_NET))
    mesh = make_mesh(2)
    tau = 2
    tr = DistributedTrainer(sp, mesh, TrainerConfig(strategy="local_sgd",
                                                    tau=tau), seed=0)
    init_params = jax.tree_util.tree_map(np.asarray, tr.params)
    batches = {
        "data": np_rng.normal(size=(tau, 16, 1, 12, 12)).astype(np.float32),
        "label": np_rng.integers(0, 5, size=(tau, 16)).astype(np.float32),
    }
    tr.train_round(batches)

    # replay each worker locally with a plain Solver from the same params
    # and its own shard + the trainer's per-worker rng stream
    rng0 = jax.random.PRNGKey(0)
    _, run_rng = jax.random.split(rng0)          # trainer's self._rng
    round_rng, _ = jax.random.split(run_rng)     # rng passed into round 1
    worker_params = []
    for w in range(2):
        s = Solver(sp, seed=0)
        s.params = jax.tree_util.tree_map(jnp.asarray, init_params)
        shard = {k: v[:, 8 * w:8 * (w + 1)] for k, v in batches.items()}
        feed = iter([{k: v[t] for k, v in shard.items()}
                     for t in range(tau)])
        s.set_train_data(feed)
        wrng = jax.random.fold_in(round_rng, w)
        for _ in range(tau):
            wrng, sub = jax.random.split(wrng)
            batch = next(s._train_iter)
            stacked = {k: jnp.asarray(v)[None] for k, v in batch.items()}
            s.params, s.state, _ = s._step(s.params, s.state, s.iter,
                                           stacked, sub)
            s.iter += 1
        worker_params.append(s.params)

    # the running mean/var genuinely diverged across shards (averaging is
    # non-trivial), while the scale factor advanced identically
    for i in (0, 1):
        assert not np.allclose(np.asarray(worker_params[0]["bn1"][i]),
                               np.asarray(worker_params[1]["bn1"][i]))
    # every blob of every layer — BN stats and scale factor included —
    # equals the per-worker mean
    for k in worker_params[0]:
        for i, blob in enumerate(worker_params[0][k]):
            avg = (np.asarray(blob)
                   + np.asarray(worker_params[1][k][i])) / 2
            np.testing.assert_allclose(np.asarray(tr.params[k][i]), avg,
                                       rtol=2e-4, atol=2e-5,
                                       err_msg=f"{k}[{i}]")


# ---------------------------------------------------------------------------
# Hierarchical two-level DP: (host, chip) mesh — per-step grad psum over
# chips (P2PSync tier, parallel.cpp:271-360) x tau-step weight averaging
# over hosts (Spark round tier, ImageNetApp.scala:100-182), composed.
# ---------------------------------------------------------------------------

from sparknet_tpu.parallel import make_pod_mesh


def _tree_allclose(a, b, rtol=2e-4, atol=2e-5):
    for k in a:
        for i, blob in enumerate(a[k]):
            np.testing.assert_allclose(
                np.asarray(blob), np.asarray(b[k][i]), rtol=rtol, atol=atol,
                err_msg=f"{k}[{i}]")


def test_pod_mesh_shapes():
    mesh = make_pod_mesh(2, 4)
    assert mesh.shape == {"host": 2, "chip": 4}
    with pytest.raises(ValueError):
        make_pod_mesh(3, 4)  # 12 > 8 devices
    with pytest.raises(ValueError, match="hierarchical"):
        DistributedTrainer(
            load_solver_prototxt_with_net(SOLVER_TXT, lenet(8, 8)),
            make_mesh(8), TrainerConfig(strategy="hierarchical"))


def test_hierarchical_loss_decreases(np_rng):
    sp = load_solver_prototxt_with_net(
        'base_lr: 0.01\nmomentum: 0.9\nlr_policy: "fixed"\n', lenet(32, 32))
    tr = DistributedTrainer(sp, make_pod_mesh(2, 4),
                            TrainerConfig(strategy="hierarchical", tau=5),
                            seed=0)
    assert tr.n_workers == 8 and tr.n_hosts == 2 and tr.n_chips == 4
    losses = [tr.train_round(round_batches(np_rng, 5, 32)) for _ in range(6)]
    assert losses[0] == pytest.approx(np.log(10), rel=0.3)
    assert losses[-1] < 0.5 * losses[0]
    assert tr.iter == 30


def test_hierarchical_one_host_collapses_to_sync(np_rng):
    """A 1xN pod has no host tier to average over: every round must match
    the flat per-step-gradient strategy exactly (momentum included — the
    single host owns the one optimizer state, like sync's)."""
    sp = load_solver_prototxt_with_net(SOLVER_TXT, lenet(16, 16))
    hier = DistributedTrainer(sp, make_pod_mesh(1, 4),
                              TrainerConfig(strategy="hierarchical", tau=2),
                              seed=0)
    flat = DistributedTrainer(sp, make_mesh(4),
                              TrainerConfig(strategy="sync", tau=2), seed=0)
    for _ in range(3):
        batches = round_batches(np_rng, 2, 16)
        lh = hier.train_round(batches)
        lf = flat.train_round(batches)
        assert lh == pytest.approx(lf, rel=1e-5)
    _tree_allclose(hier.params, flat.params)


def test_hierarchical_one_chip_collapses_to_local_sgd(np_rng):
    """An Nx1 pod has no chip tier to psum over: every round must match
    flat tau-step weight averaging exactly (per-worker == per-host
    optimizer states)."""
    sp = load_solver_prototxt_with_net(SOLVER_TXT, lenet(8, 8))
    hier = DistributedTrainer(sp, make_pod_mesh(4, 1),
                              TrainerConfig(strategy="hierarchical", tau=3),
                              seed=0)
    flat = DistributedTrainer(sp, make_mesh(4),
                              TrainerConfig(strategy="local_sgd", tau=3),
                              seed=0)
    for _ in range(2):
        batches = round_batches(np_rng, 3, 16)
        lh = hier.train_round(batches)
        lf = flat.train_round(batches)
        assert lh == pytest.approx(lf, rel=1e-5)
    _tree_allclose(hier.params, flat.params)


def test_hierarchical_tau1_plain_sgd_collapses_to_flat_sync(np_rng):
    """With tau=1 and a stateless rule (momentum 0), averaging per-host
    UPDATES equals updating with the all-device mean gradient, so a 2x4
    pod matches flat 8-way sync across rounds (the update is linear in
    the gradient)."""
    sp = load_solver_prototxt_with_net(
        'base_lr: 0.05\nlr_policy: "fixed"\nweight_decay: 0.001\n',
        lenet(16, 16))
    hier = DistributedTrainer(sp, make_pod_mesh(2, 4),
                              TrainerConfig(strategy="hierarchical", tau=1),
                              seed=0)
    flat = DistributedTrainer(sp, make_mesh(8),
                              TrainerConfig(strategy="sync", tau=1), seed=0)
    for _ in range(3):
        batches = round_batches(np_rng, 1, 16)
        hier.train_round(batches)
        flat.train_round(batches)
    _tree_allclose(hier.params, flat.params)


def test_hierarchical_composition_replay(np_rng):
    """The definitional test: a 2x2 tau=2 hierarchical round equals, per
    host, a flat 2-chip sync trainer run on that host's rows for tau
    rounds, with the two hosts' results then averaged by hand."""
    sp = load_solver_prototxt_with_net(SOLVER_TXT, lenet(8, 8))
    tau = 2
    hier = DistributedTrainer(sp, make_pod_mesh(2, 2),
                              TrainerConfig(strategy="hierarchical",
                                            tau=tau), seed=0)
    init = jax.tree_util.tree_map(np.asarray, hier.params)
    batches = round_batches(np_rng, tau, 16)  # [tau, 16, ...]
    hier.train_round(batches)

    host_params = []
    for h in range(2):
        sub = DistributedTrainer(sp, make_mesh(2),
                                 TrainerConfig(strategy="sync", tau=1),
                                 seed=0)
        sub.params = jax.tree_util.tree_map(
            lambda x: jnp.asarray(x), init)
        rows = {k: v[:, 8 * h:8 * (h + 1)] for k, v in batches.items()}
        for t in range(tau):
            sub.train_round({k: v[t][None] for k, v in rows.items()})
        host_params.append(jax.tree_util.tree_map(np.asarray, sub.params))

    avg = jax.tree_util.tree_map(
        lambda a, b: (a + b) / 2, host_params[0], host_params[1])
    _tree_allclose(hier.params, avg)


def test_hierarchical_bn_one_host_matches_sync(np_rng):
    """BatchNorm running stats under the chip tier follow sync's
    per-step re-averaging (state_keys pmean over chips)."""
    from sparknet_tpu.proto import load_net_prototxt
    sp = load_solver_prototxt_with_net(SOLVER_TXT,
                                       load_net_prototxt(BN_DP_NET))
    hier = DistributedTrainer(sp, make_pod_mesh(1, 2),
                              TrainerConfig(strategy="hierarchical", tau=2),
                              seed=0)
    flat = DistributedTrainer(sp, make_mesh(2),
                              TrainerConfig(strategy="sync", tau=2), seed=0)
    batches = {
        "data": np_rng.normal(size=(2, 16, 1, 12, 12)).astype(np.float32),
        "label": np_rng.integers(0, 5, size=(2, 16)).astype(np.float32),
    }
    hier.train_round(batches)
    flat.train_round(batches)
    _tree_allclose(hier.params, flat.params)


def test_hierarchical_snapshot_restore(tmp_path, np_rng):
    sp = load_solver_prototxt_with_net(SOLVER_TXT, lenet(8, 8))
    cfg = TrainerConfig(strategy="hierarchical", tau=2)
    tr = DistributedTrainer(sp, make_pod_mesh(2, 2), cfg, seed=0)
    tr.train_round(round_batches(np_rng, 2, 16))
    path = str(tmp_path / "hier.npz")
    tr.snapshot(path)

    tr2 = DistributedTrainer(sp, make_pod_mesh(2, 2), cfg, seed=1)
    tr2.restore(path)
    assert tr2.iter == tr.iter
    _tree_allclose(tr2.params, tr.params, rtol=0, atol=0)
    # deterministic net: the next round from restored state matches
    batches = round_batches(np_rng, 2, 16)
    assert tr.train_round(batches) == pytest.approx(
        tr2.train_round(batches), rel=1e-6)

    # a different host tiling must be refused (per-host optimizer state)
    tr41 = DistributedTrainer(sp, make_pod_mesh(4, 1), cfg, seed=0)
    with pytest.raises(ValueError, match="hosts"):
        tr41.restore(path)


def test_vmap_local_sgd_matches_mesh_trainer(np_rng):
    """tools/learning_proxy.py runs 8-way local SGD on ONE chip by
    vmapping the per-worker update over a stacked param/state axis and
    averaging at the tau boundary; this pins that form against the mesh
    trainer's local_sgd round (deterministic net, identical data
    assignment), so the proxy's 8-way numbers speak for the mesh
    implementation."""
    from sparknet_tpu.graph.net import Net
    from sparknet_tpu.proto import NetState, Phase
    from sparknet_tpu.solvers.step import make_step_fns
    from sparknet_tpu.solvers.update_rules import make_update_rule

    W, tau, b = 2, 3, 8
    sp = load_solver_prototxt_with_net(SOLVER_TXT, lenet(W * b, W * b))
    tr = DistributedTrainer(sp, make_mesh(W),
                            TrainerConfig(strategy="local_sgd", tau=tau),
                            seed=0)
    batches = round_batches(np_rng, tau, W * b)
    tr.train_round(batches)

    # the vmap form, exactly as the proxy builds it
    net = Net(sp.net_param or sp.train_net_param, NetState(Phase.TRAIN))
    rule = make_update_rule(sp)
    rng0 = jax.random.PRNGKey(0)
    _, init_rng = jax.random.split(rng0)     # the trainer's init chain
    params0 = net.init(init_rng)
    state0 = rule.init(params0)
    _, local_update, _ = make_step_fns(
        sp, net, rule, net.lr_mult_tree(params0),
        net.decay_mult_tree(params0), in_scan=True)
    vm = jax.vmap(local_update, in_axes=(0, 0, None, 0, 0))

    stack = lambda t: jax.tree_util.tree_map(
        lambda x: jnp.broadcast_to(x[None], (W,) + x.shape), t)
    wparams, wstate = stack(params0), stack(state0)
    for t in range(tau):
        # worker w sees rows [w*b:(w+1)*b] — the shard_map row split
        micro = {k: jnp.asarray(v[t]).reshape((W, 1, b) + v[t].shape[1:])
                 for k, v in batches.items()}
        wparams, wstate, _ = vm(wparams, wstate, t,
                                micro, jax.random.split(rng0, W))
    avg = jax.tree_util.tree_map(lambda x: x.mean(0), wparams)
    _tree_allclose(tr.params, avg)


def test_vmap_hierarchical_matches_mesh_trainer(np_rng):
    """make_host_step (tools/learning_proxy.py) — the single-chip vmap
    restatement of the hierarchical strategy's per-step chip-mean update
    — pinned against the mesh trainer's (host, chip) round, so the
    proxy's hierarchical curve speaks for the mesh implementation."""
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "learning_proxy",
        os.path.join(os.path.dirname(__file__), os.pardir,
                     "tools", "learning_proxy.py"))
    lp = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(lp)

    from sparknet_tpu.graph.net import Net
    from sparknet_tpu.proto import NetState, Phase
    from sparknet_tpu.solvers.step import make_step_fns
    from sparknet_tpu.solvers.update_rules import make_update_rule

    H, C, tau, b = 2, 2, 2, 4
    sp = load_solver_prototxt_with_net(SOLVER_TXT,
                                       lenet(H * C * b, H * C * b))
    tr = DistributedTrainer(sp, make_pod_mesh(H, C),
                            TrainerConfig(strategy="hierarchical",
                                          tau=tau), seed=0)
    batches = round_batches(np_rng, tau, H * C * b)
    tr.train_round(batches)

    net = Net(sp.net_param or sp.train_net_param, NetState(Phase.TRAIN))
    rule = make_update_rule(sp)
    rng0 = jax.random.PRNGKey(0)
    _, init_rng = jax.random.split(rng0)     # the trainer's init chain
    params0 = net.init(init_rng)
    state0 = rule.init(params0)
    lr_m = net.lr_mult_tree(params0)
    dc_m = net.decay_mult_tree(params0)
    _, _, accum = make_step_fns(sp, net, rule, lr_m, dc_m, in_scan=True)
    host_step = lp.make_host_step(sp, rule, lr_m, dc_m, accum)
    vm_host = jax.vmap(host_step, in_axes=(0, 0, None, 0, 0))

    stack = lambda t: jax.tree_util.tree_map(
        lambda x: jnp.broadcast_to(x[None], (H,) + x.shape), t)
    hparams, hstate = stack(params0), stack(state0)
    for t in range(tau):
        # mesh batch rows shard host-major over (host, chip)
        micro = {k: jnp.asarray(v[t]).reshape((H, C, 1, b)
                                              + v[t].shape[1:])
                 for k, v in batches.items()}
        rngs = jax.random.split(rng0, H * C).reshape(H, C, 2)
        hparams, hstate, _ = vm_host(hparams, hstate, t, micro, rngs)
    avg = jax.tree_util.tree_map(lambda x: x.mean(0), hparams)
    _tree_allclose(tr.params, avg)


def test_hierarchical_bn_composition_replay(np_rng):
    """BN running stats under the COMPOSED topology (2 hosts x 2 chips,
    tau=2): each host behaves as a flat 2-chip sync trainer on its rows
    (per-step chip re-averaging of the stats), and the tau boundary
    averages them across hosts with the weights — pinned against that
    exact replay."""
    from sparknet_tpu.proto import load_net_prototxt

    sp = load_solver_prototxt_with_net(SOLVER_TXT,
                                       load_net_prototxt(BN_DP_NET))
    tau = 2
    hier = DistributedTrainer(sp, make_pod_mesh(2, 2),
                              TrainerConfig(strategy="hierarchical",
                                            tau=tau), seed=0)
    init = jax.tree_util.tree_map(np.asarray, hier.params)
    batches = {
        "data": np_rng.normal(size=(tau, 16, 1, 12, 12)).astype(np.float32),
        "label": np_rng.integers(0, 5, size=(tau, 16)).astype(np.float32),
    }
    hier.train_round(batches)

    host_params = []
    for h in range(2):
        sub = DistributedTrainer(sp, make_mesh(2),
                                 TrainerConfig(strategy="sync", tau=1),
                                 seed=0)
        sub.params = jax.tree_util.tree_map(jnp.asarray, init)
        rows = {k: v[:, 8 * h:8 * (h + 1)] for k, v in batches.items()}
        for t in range(tau):
            sub.train_round({k: v[t][None] for k, v in rows.items()})
        host_params.append(jax.tree_util.tree_map(np.asarray, sub.params))

    # the BN running stats genuinely diverged across the two hosts
    # (the host average is non-trivial)
    for i in (0, 1):
        assert not np.allclose(host_params[0]["bn1"][i],
                               host_params[1]["bn1"][i])
    avg = jax.tree_util.tree_map(
        lambda a, b: (a + b) / 2, host_params[0], host_params[1])
    _tree_allclose(hier.params, avg)
