"""Drive: the LMDB-builder + mean-file pycaffe data workflow through
`import caffe` — array_to_datum -> convert_imageset-style LMDB ->
compute mean -> BlobProto mean file -> Transformer."""
import jax; jax.config.update("jax_platforms", "cpu")
import os, tempfile
import numpy as np
from sparknet_tpu import pycaffe_compat
pycaffe_compat.install()
import caffe

rng = np.random.default_rng(0)
work = tempfile.mkdtemp(prefix="pb2drive_")

# 1. build an LMDB the pycaffe way: Datum messages -> SerializeToString
from sparknet_tpu.data.lmdb_io import write_lmdb
imgs = rng.integers(0, 256, size=(6, 3, 8, 8)).astype(np.uint8)
db_path = os.path.join(work, "train_lmdb")
write_lmdb(db_path, [
    (f"{i:08d}".encode(),
     caffe.io.array_to_datum(img, label=i % 3).SerializeToString())
    for i, img in enumerate(imgs)])

# 2. read it back through the data plane
from sparknet_tpu.data.db import open_db, datum_to_array
r = open_db(db_path, "LMDB")
k, v = r.first()
arr, label = datum_to_array(v)
assert label == 0 and arr.shape == (3, 8, 8)
np.testing.assert_allclose(arr, imgs[0].astype(np.float32))
r.close()

# 3. mean file: write with the framework tool, read with the pycaffe idiom
from sparknet_tpu.proto import save_mean_binaryproto
mean = imgs.astype(np.float32).mean(0)
mean_path = os.path.join(work, "mean.binaryproto")
save_mean_binaryproto(mean_path, mean)
blob = caffe.proto.caffe_pb2.BlobProto()
blob.ParseFromString(open(mean_path, "rb").read())
mu = caffe.io.blobproto_to_array(blob).reshape(3, 8, 8)
np.testing.assert_allclose(mu, mean, rtol=1e-6)

# 4. feed the mean into a Transformer (the deploy-preprocessing chain)
t = caffe.io.Transformer({"data": (1, 3, 8, 8)})
t.set_transpose("data", (2, 0, 1))
t.set_mean("data", mu)
x = t.preprocess("data", imgs[0].transpose(1, 2, 0).astype(np.float32))
assert x.shape == (3, 8, 8)
print("pb2 data-workflow drive OK: lmdb", len(imgs), "samples, mean",
      round(float(mu.mean()), 2))
