from .common import RoundFeed, run_training
