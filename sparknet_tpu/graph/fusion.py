"""Profile-driven vertical fusion: worklist -> plan -> fused execution.

The executor has fused horizontally since round 5 (sibling 1x1 convs,
``net.py:_detect_hfuse_groups``).  This module generalizes the idea
vertically: conv+bias+relu(+pool/LRN) *chains* become one execution
block, planned from the committed profile tables instead of hard-coded
pattern matching — the Caffeinated-FPGAs / Caffe-con-Troll argument
that with a fixed layer library, cross-layer fusion is where the
residual throughput hides.

Three layers, smallest surface first:

- **Worklist** (:func:`fusion_worklist`, :func:`chain_kind`): rank one
  profile capture's unfused chains by reclaimable ms against the
  capture's own best fused-chain bandwidth.  This is the ranking
  ``tools/perfwatch.py diff`` ships as its fusion worklist — it lives
  here so the planner consumes the SAME code, not a copy.
- **Legality** (:func:`chain_candidates`): the statically fusable
  chains of a built ``Net`` — linear Conv -> [ReLU] -> [Pool] -> [LRN]
  runs where every intermediate blob has exactly ONE consumer (its own
  chain successor, at the right in-place version), no member carries a
  loss weight, is stateful, or needs an rng, and no member overlaps a
  horizontal-fusion group.  Violating any of these would change
  observable semantics, so illegal chains are REFUSED, never silently
  mangled.
- **Plan** (:class:`FusionPlan`, :func:`resolve_plan`): the explicit,
  reproducible record of what fuses.  ``SPARKNET_FUSE`` selects the
  source — ``off`` (today's per-layer execution, bit-for-bit),
  ``auto`` (derive from the committed ``profiles/<model>/op_table.json``
  worklist; the default), ``all`` (every legal chain — the
  testing/parity-gate mode), or a ``fusion_plan.json`` path (replay a
  recorded plan; members that are no longer legal are refused with a
  reason).  ``profiles/<model>/fusion_plan.json`` written by
  ``tools/profile_step.py`` records what a capture actually applied.

Execution itself stays in ``graph/net.py`` (``_apply_fused_chain``):
the conv runs as XLA (its MXU tiling is already optimal), and an
LRN-tailed chain finishes in the fused epilogue op
(``ops.vision.lrn_chain_epilogue``) — one VMEM trip on TPU via the
Pallas kernel, a scale-residual custom-VJP reformulation on other
backends — instead of XLA's reduce_window chain (the 555 GB/s row the
worklist ranks first).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
from typing import TYPE_CHECKING, Any, Mapping

from ..utils import knobs

if TYPE_CHECKING:  # pragma: no cover - typing only
    from .net import Net

_REPO_ROOT = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))

PLAN_VERSION = 1
PLAN_FILENAME = "fusion_plan.json"

# ---------------------------------------------------------------------------
# Worklist — the perfwatch `diff` chain ranking, as a library
# ---------------------------------------------------------------------------

# layers achieving more than this are MXU-bound (big convs / FCs), not
# bandwidth-bound fusion candidates
_MXU_GFLOPS_S = 5000.0
# the aggregation pseudo-row profile tables carry
_NON_LAYERS = ("(outside layers)",)


def chain_kind(layer: str) -> str:
    """Classify a by_layer row name into the chain family it tails."""
    name = layer.lower()
    if "norm" in name:
        return "conv+bias+relu+LRN"
    if "pool" in name:
        return "conv+bias+relu+pool"
    if "relu" in name:
        return "bias+relu"
    return "elementwise chain"


def fusion_worklist(doc: Mapping[str, Any], *, top: int = 12,
                    min_pct: float = 0.3) -> dict:
    """Rank the unfused conv+bias+relu(+pool/LRN) chains of one capture
    by reclaimable ms against the capture's own best fused-chain
    bandwidth (the VERDICT.md method: the googlenet LRN chains run at
    555 GB/s where neighboring fused chains reach ~1013 GB/s).

    Rows whose scope already names a fused chain (``a+b`` scopes — the
    horizontal groups and this pass's own vertical chains) are not
    candidates: they are the pass's OUTPUT.  They report under
    ``fused_chains`` with an ``at_ref_band`` verdict instead, so a
    re-capture shows each fused chain against the reference band it was
    fused to reach."""
    all_rows = [r for r in doc.get("by_layer") or []
                if r.get("op") not in _NON_LAYERS]
    rows = [r for r in all_rows
            if r.get("gb_per_s") and r.get("total_ms")]
    if not rows:
        if all_rows:
            # CPU-runtime thunk traces attribute layers (via the HLO
            # op_name join) but carry no bytes_accessed stats — time
            # exists, bandwidth doesn't, so ranking-vs-roofline would
            # be invented numbers
            return {"note": "by_layer rows carry no bandwidth stats "
                            "(CPU runtime trace) — the worklist needs "
                            "a device capture",
                    "candidates": []}
        return {"note": "capture has no by_layer table — profile with "
                        "tools/profile_step.py to get one",
                "candidates": []}
    # reference bandwidth: the best a non-trivial chain in THIS capture
    # actually achieves (pct floor keeps sub-0.1% slivers from setting
    # an unreachable bar)
    ref_rows = [r for r in rows if (r.get("pct") or 0.0) >= 0.8]
    ref = max((r["gb_per_s"] for r in ref_rows), default=None)
    if ref is None:
        ref = max(r["gb_per_s"] for r in rows)
    candidates = []
    fused_chains = []
    for r in rows:
        gb = r["gb_per_s"]
        if "+" in r["op"]:
            if (r.get("pct") or 0.0) >= min_pct:
                fused_chains.append({
                    "chain": r["op"], "total_ms": r["total_ms"],
                    "gb_per_s": gb, "ref_gb_per_s": round(ref, 1),
                    "at_ref_band": bool(gb >= 0.95 * ref)})
            continue
        if (r.get("pct") or 0.0) < min_pct:
            continue
        if (r.get("gflops_per_s") or 0.0) > _MXU_GFLOPS_S:
            continue   # MXU-bound: more bandwidth won't buy anything
        if gb >= 0.95 * ref:
            continue   # already at the fused-chain roofline
        reclaim = r["total_ms"] * (1.0 - gb / ref)
        kind = chain_kind(r["op"])
        cand = {"chain": r["op"], "kind": kind,
                "total_ms": r["total_ms"], "pct": r.get("pct"),
                "gb_per_s": gb, "ref_gb_per_s": round(ref, 1),
                "reclaimable_ms": round(reclaim, 2)}
        if "LRN" in kind:
            cand["note"] = ("LRN chain — the class VERDICT.md pins at "
                            "555 GB/s (googlenet bf16 conv2/norm2) vs "
                            "~1013 GB/s on neighboring fused chains")
        candidates.append(cand)
    candidates.sort(key=lambda c: -c["reclaimable_ms"])
    out = {"ref_gb_per_s": round(ref, 1),
           "reclaimable_ms_total": round(
               sum(c["reclaimable_ms"] for c in candidates), 2),
           "candidates": candidates[:top]}
    if fused_chains:
        out["fused_chains"] = fused_chains
    return out


# ---------------------------------------------------------------------------
# Plan model
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class FusedChain:
    """One vertical chain: ``members[0]`` is the head Convolution, the
    rest follow in graph order.  ``epilogue`` names how the tail
    executes: ``"relu+lrn"`` / ``"lrn"`` run the LRN (and the folded
    ReLU) in the fused epilogue op; ``"none"`` runs every member's own
    impl inside one scope (XLA fuses those fine — the block exists for
    attribution and as the seam later kernels land in)."""

    members: list[str]
    kind: str
    epilogue: str = "none"
    source: dict | None = None     # the worklist row that motivated it

    def scope(self) -> str:
        return "+".join(self.members)

    def to_doc(self) -> dict:
        doc = {"members": list(self.members), "kind": self.kind,
               "epilogue": self.epilogue}
        if self.source:
            doc["source"] = dict(self.source)
        return doc


@dataclasses.dataclass
class FusionPlan:
    """What fuses, where the decision came from, and what was refused —
    the committed, reproducible record (``fusion_plan.json``)."""

    model: str
    source: str                    # "off"|"auto:<path>"|"all"|"file:<path>"
    chains: list[FusedChain] = dataclasses.field(default_factory=list)
    refused: list[dict] = dataclasses.field(default_factory=list)
    version: int = PLAN_VERSION

    def plan_id(self) -> str:
        """Short stable id for perf-ledger fingerprints: ``off`` when
        nothing fuses, else ``vf<N>-<hash of the member lists>`` — two
        captures pool into one baseline band iff they fused the same
        chains."""
        if not self.chains:
            return "off"
        canon = "|".join(sorted(";".join(c.members) for c in self.chains))
        return (f"vf{len(self.chains)}-"
                f"{hashlib.sha1(canon.encode()).hexdigest()[:8]}")

    def to_doc(self) -> dict:
        return {"version": self.version, "model": self.model,
                "source": self.source, "plan_id": self.plan_id(),
                "chains": [c.to_doc() for c in self.chains],
                "refused": list(self.refused)}

    def save(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump(self.to_doc(), f, indent=1)
            f.write("\n")

    @classmethod
    def from_doc(cls, doc: Mapping[str, Any]) -> "FusionPlan":
        if int(doc.get("version", 0)) > PLAN_VERSION:
            raise ValueError(
                f"fusion plan version {doc.get('version')} is newer than "
                f"this build understands ({PLAN_VERSION})")
        chains = [FusedChain(members=list(c["members"]),
                             kind=c.get("kind", "?"),
                             epilogue=c.get("epilogue", "none"),
                             source=c.get("source"))
                  for c in doc.get("chains") or []]
        return cls(model=str(doc.get("model") or "unknown"),
                   source=str(doc.get("source") or "file"),
                   chains=chains,
                   refused=list(doc.get("refused") or []))

    @classmethod
    def load(cls, path: str) -> "FusionPlan":
        with open(path) as f:
            return cls.from_doc(json.load(f))


# ---------------------------------------------------------------------------
# Legality — the statically fusable chains of a built Net
# ---------------------------------------------------------------------------

# member grammar after the head Convolution, in required order (each
# stage optional, at most one of each)
_STAGE_ORDER = ("ReLU", "Pooling", "LRN")


def _member_legal(node, train_and_test=True) -> str | None:
    """None when the node can join a chain, else the refusal reason."""
    if len(node.bottoms) != 1 or len(node.tops) != 1:
        return f"{node.lp.name}: multi-bottom/top layers don't chain"
    if getattr(node.impl, "has_state", False):
        return f"{node.lp.name}: stateful layer"
    if node.impl.needs_rng(node.lp, True) or node.impl.needs_rng(node.lp,
                                                                 False):
        return f"{node.lp.name}: stochastic layer (needs rng)"
    if any(w for w in node.loss_weights()):
        return f"{node.lp.name}: carries a loss weight"
    return None


def _lrn_epilogue_kind(net: "Net", node) -> str | None:
    """"lrn" when this LRN member can run as the fused epilogue op,
    else None (it then runs as its own impl inside the block)."""
    p = node.lp.sub("lrn_param")
    region = str(p.get("norm_region", "ACROSS_CHANNELS"))
    shape = net.blob_shapes.get(node.bottoms[0])
    if region == "ACROSS_CHANNELS" and shape is not None and len(shape) == 4:
        return "lrn"
    return None


def _relu_foldable(node) -> bool:
    """Zero-slope ReLU folds into the LRN epilogue kernel; a leaky
    slope keeps its own (still in-block) elementwise op."""
    return float(node.lp.sub("relu_param").get("negative_slope", 0.0)) == 0.0


def chain_candidates(net: "Net") -> list[FusedChain]:
    """Every maximal legal chain in ``net``, in graph order.

    Legality (each rule keeps fused semantics identical to per-layer
    execution):

    - head is a single-bottom/single-top ``Convolution`` that is not a
      member of a horizontal 1x1-sibling group (hfuse owns those);
    - successors follow the Conv -> ReLU -> Pooling -> LRN grammar;
    - every intermediate top has exactly ONE consumer — the next chain
      member — *at the produced in-place version* (a blob re-read after
      an in-place rewrite is a different tensor; the version map is the
      same discipline hfuse uses).  Single-consumer also guarantees the
      intermediate is not a net output, so skipping its blob assignment
      in the fused run is observationally safe;
    - no member is stateful, stochastic, or loss-weighted.
    """
    hfused: set[str] = set()
    if getattr(net, "_hfuse_enabled", False):
        for members in getattr(net, "_hfuse_first", {}).values():
            hfused.update(m.lp.name for m in members)

    # versioned consumer map: (blob, version) -> consumer node indices
    ver: dict[str, int] = dict.fromkeys(net.input_blobs, 0)
    consumers: dict[tuple[str, int], list[int]] = {}
    produced_ver: dict[int, dict[str, int]] = {}   # node idx -> top vers
    for i, node in enumerate(net.nodes):
        for b in node.bottoms:
            consumers.setdefault((b, ver.get(b, 0)), []).append(i)
        produced_ver[i] = {}
        for t in node.tops:
            ver[t] = ver.get(t, 0) + 1
            produced_ver[i][t] = ver[t]

    chains: list[FusedChain] = []
    taken: set[str] = set()
    for i, node in enumerate(net.nodes):
        if node.lp.type != "Convolution" or node.lp.name in taken:
            continue
        if node.lp.name in hfused:
            continue
        if _member_legal(node) is not None:
            continue
        members = [node]
        idxs = [i]
        stage = -1   # index into _STAGE_ORDER consumed so far
        cur = node
        cur_i = i
        while True:
            top = cur.tops[0]
            cons = consumers.get((top, produced_ver[cur_i][top]), [])
            if len(cons) != 1:
                break
            nxt_i = cons[0]
            nxt = net.nodes[nxt_i]
            if nxt.lp.type not in _STAGE_ORDER:
                break
            nstage = _STAGE_ORDER.index(nxt.lp.type)
            if nstage <= stage:
                break
            if _member_legal(nxt) is not None:
                break
            if nxt.lp.name in taken or nxt.lp.name in hfused:
                break
            if (nxt.lp.type == "Pooling"
                    and str(nxt.lp.sub("pooling_param").get(
                        "pool", "MAX")) == "STOCHASTIC"):
                break   # needs_rng covers train; test mode is odd too
            members.append(nxt)
            idxs.append(nxt_i)
            stage = nstage
            cur, cur_i = nxt, nxt_i
            if nxt.lp.type == "LRN":
                break   # grammar: nothing chains past the LRN tail
        if len(members) < 2:
            continue
        kind = "conv+bias" if _conv_has_bias(members[0]) else "conv"
        epilogue = "none"
        for m in members[1:]:
            kind += {"ReLU": "+relu", "Pooling": "+pool",
                     "LRN": "+LRN"}[m.lp.type]
        tail = members[-1]
        if tail.lp.type == "LRN":
            ep = _lrn_epilogue_kind(net, tail)
            if ep:
                prev = members[-2]
                if prev.lp.type == "ReLU" and _relu_foldable(prev):
                    epilogue = "relu+lrn"
                else:
                    epilogue = "lrn"
        chains.append(FusedChain(
            members=[m.lp.name for m in members], kind=kind,
            epilogue=epilogue))
        taken.update(m.lp.name for m in members)
    return chains


def _conv_has_bias(node) -> bool:
    return bool(node.lp.sub("convolution_param").get("bias_term", True))


# ---------------------------------------------------------------------------
# Plan derivation
# ---------------------------------------------------------------------------

def plan_all(net: "Net", source: str = "all") -> FusionPlan:
    """Fuse every legal chain — the parity-gate / testing planner."""
    return FusionPlan(model=net.name or "unknown", source=source,
                      chains=chain_candidates(net))


def plan_from_profile(net: "Net", op_table: Mapping[str, Any],
                      source: str) -> FusionPlan:
    """The profile-driven planner: fuse exactly the chains the capture's
    worklist names (any member name matches — the profiled scope is
    usually the chain's LRN/pool tail), in worklist order.  Candidates
    that name no legal chain are recorded as refused with the reason —
    a hotspot the pass cannot legally fuse should be visible, not
    silently dropped."""
    cands = chain_candidates(net)
    by_member = {m: c for c in cands for m in c.members}
    wl = fusion_worklist(op_table)
    plan = FusionPlan(model=net.name or "unknown", source=source)
    seen: set[str] = set()
    for row in wl.get("candidates") or []:
        chain = by_member.get(row.get("chain"))
        if chain is None:
            plan.refused.append({
                "candidate": row.get("chain"),
                "reason": "no legal chain contains this layer "
                          "(fan-out, stateful/stochastic member, "
                          "loss-weighted top, or not in this net)"})
            continue
        key = chain.scope()
        if key in seen:
            continue
        seen.add(key)
        chain = dataclasses.replace(
            chain, source={"chain": row.get("chain"),
                           "reclaimable_ms": row.get("reclaimable_ms"),
                           "gb_per_s": row.get("gb_per_s"),
                           "ref_gb_per_s": row.get("ref_gb_per_s")})
        plan.chains.append(chain)
    return plan


def plan_from_file(net: "Net", path: str) -> FusionPlan:
    """Replay a recorded plan, re-validating every chain against the
    net's CURRENT legal set: a chain whose member list no longer
    matches a legal chain is refused (graph drift must not resurrect a
    stale fusion), everything else applies exactly as recorded."""
    loaded = FusionPlan.load(path)
    legal = {tuple(c.members): c for c in chain_candidates(net)}
    plan = FusionPlan(model=net.name or loaded.model,
                      source=f"file:{path}", refused=list(loaded.refused))
    for c in loaded.chains:
        cur = legal.get(tuple(c.members))
        if cur is None:
            plan.refused.append({
                "candidate": "+".join(c.members),
                "reason": "recorded chain is not legal in this net "
                          "(member list does not match any legal chain)"})
            continue
        plan.chains.append(dataclasses.replace(cur, source=c.source))
    return plan


# model-name -> committed profile directory (the zoo nets capitalize;
# profile dirs are the bench-model slugs)
def model_slug(name: str | None) -> str:
    return (name or "").lower().replace("_", "").replace("-", "")


_PROFILE_CACHE: dict[str, tuple[float, dict | None]] = {}


def default_profile_table(model_name: str | None,
                          repo: str | None = None) -> tuple[dict, str] | None:
    """The committed ``profiles/<model>/op_table.json`` for a net name
    (``GoogleNet`` -> ``profiles/googlenet``), or None.  Prefers the
    plain capture over dtype-suffixed variants so the ``auto`` plan is
    stable; cached by mtime (Net construction is not hot, but fleets
    build many Nets)."""
    repo = repo or _REPO_ROOT
    slug = model_slug(model_name)
    if not slug:
        return None
    pdir = os.path.join(repo, "profiles")
    try:
        names = sorted(os.listdir(pdir))
    except OSError:
        return None
    hits = [n for n in names
            if model_slug(n) == slug or n == slug]
    # plain name first, then the shortest suffixed variant
    hits.sort(key=lambda n: (n != slug, len(n)))
    for n in hits:
        path = os.path.join(pdir, n, "op_table.json")
        try:
            mtime = os.path.getmtime(path)
        except OSError:
            continue
        cached = _PROFILE_CACHE.get(path)
        if cached and cached[0] == mtime:
            doc = cached[1]
        else:
            try:
                with open(path) as f:
                    doc = json.load(f)
            except (OSError, json.JSONDecodeError):
                doc = None
            _PROFILE_CACHE[path] = (mtime, doc)
        if doc is not None:
            return doc, os.path.relpath(path, repo)
    return None


def resolve_plan(net: "Net") -> FusionPlan | None:
    """Read ``SPARKNET_FUSE`` (latched at Net construction, like the
    hfuse toggle — flipping the env after the first jitted step could
    never retrace the cached executable) and build the plan.

    ``off``/``0`` -> None (today's per-layer execution, bit-for-bit);
    ``auto`` (default) -> derive from the committed profile worklist —
    models without a committed profile run unfused; ``all`` -> every
    legal chain; anything else -> a plan-file path."""
    env = (knobs.raw("SPARKNET_FUSE") or "auto").strip()
    if env in ("off", "0"):
        return None
    if env == "all":
        return plan_all(net)
    if env == "auto":
        hit = default_profile_table(net.name)
        if hit is None:
            return FusionPlan(model=net.name or "unknown",
                              source="auto:no-profile")
        doc, rel = hit
        return plan_from_profile(net, doc, source=f"auto:{rel}")
    if not os.path.isfile(env):
        raise ValueError(
            f"SPARKNET_FUSE={env!r}: not off|auto|all and no such plan "
            f"file — a typo here must not silently change what executes")
    return plan_from_file(net, env)
