#!/usr/bin/env python
"""Closed-loop serving load generator — the latency-vs-offered-QPS story.

Drives the serving plane with closed-loop clients and emits one
BENCH-style JSON report covering the three acceptance claims of the
serving subsystem:

(a) **dynamic batching wins**: saturation throughput of the
    micro-batching engine vs a batch=1 engine (same model, same compiled
    kernels, shapes pinned to ``(1,)`` and coalescing off) — the
    Caffe-con-Troll "the harness is the win" number.
(b) **overload degrades into typed rejections, not latency collapse**:
    at 2x the measured saturation QPS the bounded queue + admission
    control keep the p99 of ACCEPTED requests under an explicit bound
    (``2·queue/throughput + 5·p99_sat + delay``) while the rejection
    counters absorb the excess.
(c) **batching never changes answers**: every completed request in every
    paced sweep point is compared bit-for-bit against its solo-run
    reference at the same compiled shape (``solo_references``).

Modes:
  in-process (default)  build the engine here; full report incl. (a)-(c).
  --url http://…        drive a running tools/serve.py over HTTP
                        (timing + rejection legs; exactness needs
                        engine-side references, so it is skipped).
  --smoke               ~2 s CI gate: tiny sweep, hard-asserts (b) and
                        (c) (+ prints (a)); non-zero exit on violation —
                        wired as SPARKNET_SERVESMOKE=1 in run_tier1.sh.

Usage:
  JAX_PLATFORMS=cpu python tools/serveload.py --model lenet \
      --seconds 2 --clients 16 --out BENCH_serving_cpu.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _log(msg: str) -> None:
    print(f"[serveload] {msg}", file=sys.stderr, flush=True)


class _ReadyFuture:
    """Future shim for synchronous transports (one HTTP round trip per
    client thread — remote windows degrade to window=1 semantics)."""

    def __init__(self, value):
        self._value = value

    def done(self) -> bool:
        return True

    def result(self, timeout=None):
        return self._value


def make_remote_submit(url: str, model: str, tenant: str):
    """HTTP transport for run_closed_loop: 429s re-raise as the engine's
    typed Overloaded so rejection accounting matches in-process runs."""
    from sparknet_tpu.classify import remote_classify
    from sparknet_tpu.parallel.serving import Overloaded, ServeResult

    def submit(idx: int, x: np.ndarray) -> _ReadyFuture:
        try:
            d = remote_classify(url, model, x, tenant=tenant)
        except RuntimeError as e:
            if "HTTP 429" in str(e):
                raise Overloaded("queue_full", str(e)) from None
            raise
        return _ReadyFuture(ServeResult(
            model=d["model"], probs=np.asarray(d["probs"], np.float32),
            tenant=tenant, request_id=d["request_id"],
            queue_ms=d["queue_ms"], infer_ms=d["infer_ms"],
            total_ms=d["total_ms"], batch_n=d["batch_n"],
            padded_to=d["padded_to"]))

    return submit


def run_report(model: str = "lenet", weights: str | None = None,
               shapes: tuple[int, ...] | None = None,
               delay_ms: float | None = None, queue: int | None = None,
               dtype: str | None = None, clients: int = 8,
               window: int = 16,
               seconds: float = 2.0, inputs_n: int = 32, seed: int = 0,
               fractions: tuple[float, ...] = (0.25, 0.5, 1.0),
               overload_x: float = 2.0,
               url: str | None = None) -> dict:
    """The full load report (see module docstring).  In-process unless
    ``url`` is given."""
    from sparknet_tpu.parallel.serving import (
        InferenceEngine, ModelHouse, ServeConfig, run_closed_loop,
        solo_references,
    )

    base = ServeConfig()
    cfg = ServeConfig(
        batch_shapes=shapes or base.batch_shapes,
        max_delay_ms=base.max_delay_ms if delay_ms is None else delay_ms,
        max_queue=queue or base.max_queue,
        dtype=dtype or base.dtype, seed=seed)
    rng = np.random.default_rng(seed)

    report: dict = {
        "metric": "serving_dynamic_vs_batch1_speedup_x",
        "unit": "x",
        "model": model,
        "mode": "remote" if url else "in_process",
        "clients": clients,
        "window": window,
        "seconds_per_point": seconds,
        "batch_shapes": list(cfg.batch_shapes),
        "max_delay_ms": cfg.max_delay_ms,
        "max_queue": cfg.max_queue,
        "dtype": cfg.dtype,
    }

    if url:
        from sparknet_tpu.classify import http_json
        info = http_json(f"{url.rstrip('/')}/v1/models")["models"]
        if model not in info:
            raise SystemExit(f"server has no model {model!r} "
                             f"(loaded: {sorted(info)})")
        in_shape = tuple(info[model]["in_shape"])
        inputs = [rng.normal(size=in_shape).astype(np.float32)
                  for _ in range(inputs_n)]
        refs = None
        submit = make_remote_submit(url.rstrip("/"), model, "loadgen")
        engine = None
        batch1 = None
        lm = None
    else:
        house = ModelHouse(cfg)
        lm = house.load(model, weights=weights)
        report["model_info"] = lm.info()
        engine = InferenceEngine(house, cfg)
        inputs = [rng.normal(size=lm.in_shape).astype(np.float32)
                  for _ in range(inputs_n)]
        _log(f"building solo references over {len(cfg.batch_shapes)} "
             f"shapes × {inputs_n} inputs")
        refs = solo_references(lm, inputs)
        submit = None

        # leg (a) baseline: batch=1 serving — same kernels, harness off
        b1cfg = ServeConfig(batch_shapes=(1,), max_delay_ms=0.0,
                            max_queue=cfg.max_queue, dtype=cfg.dtype,
                            seed=seed)
        b1house = ModelHouse(b1cfg)
        b1house.load(model, weights=weights)
        with InferenceEngine(b1house, b1cfg) as b1eng:
            batch1 = run_closed_loop(b1eng, model, inputs,
                                     clients=clients, window=window,
                                     duration_s=seconds)
        _log(f"batch1 saturation: {batch1['achieved_qps']} qps "
             f"(p50 {batch1['p50_ms']} ms)")
        report["batch1"] = batch1

    # dynamic saturation (leg (a) numerator, and the yardstick for (b))
    sat = run_closed_loop(engine, model, inputs, clients=clients,
                          window=window, duration_s=seconds, refs=refs,
                          submit=submit)
    _log(f"dynamic saturation: {sat['achieved_qps']} qps "
         f"(p50 {sat['p50_ms']} ms, p99 {sat['p99_ms']} ms)")
    report["saturation"] = sat
    sat_qps = max(sat["achieved_qps"], 1.0)

    # the p99 bound: queue drain time at measured throughput (doubled
    # for slack) + deadline + 5x the saturation p99 — crossing it means
    # the queue is NOT bounding latency, i.e. admission control failed.
    # Declared as the engine's latency SLO so GET /slo and the per-leg
    # slo_* verdicts below judge against the bound this very run
    # measured.
    p99_bound_ms = (2000.0 * cfg.max_queue / sat_qps
                    + 5.0 * max(sat["p99_ms"], 1.0) + cfg.max_delay_ms)
    report["p99_bound_ms"] = round(p99_bound_ms, 1)
    if engine is not None:
        engine.slo.p99_ms = p99_bound_ms
        # fence off the saturation probe: its engine-level rejections
        # are the probe working as intended, not paced-leg budget spend
        engine.slo.reset()

    # paced sweep with the exactness audit at every point (claim (c))
    sweep = []
    for frac in fractions:
        point = run_closed_loop(engine, model, inputs, clients=clients,
                                window=window, duration_s=seconds,
                                offered_qps=max(frac * sat_qps, 1.0),
                                refs=refs, submit=submit)
        point["fraction_of_saturation"] = frac
        _log(f"sweep {frac:.2f}x ({point['offered_qps']} qps offered): "
             f"achieved {point['achieved_qps']} "
             f"p50 {point['p50_ms']} p99 {point['p99_ms']} "
             f"rejected {point['rejected']} "
             f"mismatches {point['exact_mismatches']}")
        sweep.append(point)
    report["sweep"] = sweep
    if engine is not None:
        # SLO verdict over the paced traffic (before overload): must be
        # healthy — paced legs stay inside both the rejection budget
        # and the declared p99 bound
        report["slo_paced"] = engine.slo.evaluate()
        _log(f"slo after paced sweep: {report['slo_paced']['state']} "
             f"(burn fast "
             f"{report['slo_paced']['windows']['fast']['burn']}x)")

    # overload leg (claim (b)): 2x saturation through the bounded queue.
    # Client concurrency must exceed the admission bound or the closed
    # loop can never present more work than the engine accepts — scale
    # the window so clients*window comfortably overfills the queue.
    over_window = max(window,
                      (int(1.5 * cfg.max_queue) + clients - 1) // clients)
    over = run_closed_loop(engine, model, inputs, clients=clients,
                           window=over_window, duration_s=seconds,
                           offered_qps=overload_x * sat_qps,
                           refs=refs, submit=submit)
    over["fraction_of_saturation"] = overload_x
    report["overload"] = over
    _log(f"overload {overload_x}x: achieved {over['achieved_qps']} "
         f"p99 {over['p99_ms']} (bound {p99_bound_ms:.0f}) "
         f"rejected {over['rejected']}")
    if engine is not None:
        # SLO verdict under overload: the rejection budget burns (the
        # typed rejections ARE the error budget spend), so this leg
        # must breach — with a flight-recorder dump capturing the
        # breaching windows
        report["slo_overload"] = engine.slo.evaluate()
        _log(f"slo under overload: {report['slo_overload']['state']} "
             f"(burn fast "
             f"{report['slo_overload']['windows']['fast']['burn']}x, "
             f"dumps {report['slo_overload']['flight_dumps']})")

    if not url:
        import jax
        d = jax.devices()[0]
        report["device"] = f"{d.platform}/{d.device_kind}"
    from sparknet_tpu.utils import perfledger
    report["provenance"] = perfledger.provenance(perfledger.fingerprint(
        model=model, dtype=cfg.dtype, batch=max(cfg.batch_shapes),
        world=1, device=report.get("device")))

    mismatches = sum(p["exact_mismatches"] or 0 for p in sweep)
    mismatches += sat["exact_mismatches"] or 0
    mismatches += over["exact_mismatches"] or 0
    speedup = (round(sat["achieved_qps"]
                     / max(batch1["achieved_qps"], 1e-9), 2)
               if batch1 else None)
    report["value"] = speedup
    report["verdicts"] = {
        # (a) harness win at saturation
        "batching_speedup_x": speedup,
        "batching_beats_4x": (None if speedup is None else speedup >= 4.0),
        # (b) bounded p99 + typed rejections + no throughput collapse
        "overload_rejected": over["rejected"],
        "overload_p99_bounded": over["p99_ms"] <= p99_bound_ms,
        "overload_no_collapse":
            over["achieved_qps"] >= 0.5 * sat_qps,
        # (c) bit-identical to solo runs at every swept QPS
        "exact_mismatches": None if refs is None else mismatches,
        "bit_identical": None if refs is None else mismatches == 0,
        # SLO monitor verdicts (in-process only): paced traffic healthy,
        # overload a declared breach with a flight dump
        "slo_paced_healthy": (report.get("slo_paced", {}).get("state")
                              == "ok" if engine is not None else None),
        "slo_overload_breached": (
            report.get("slo_overload", {}).get("state") == "breach"
            if engine is not None else None),
    }
    if engine is not None:
        report["engine_stats"] = engine.stats()
        engine.stop()
    return report


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description="closed-loop serving load "
                                             "generator")
    ap.add_argument("--model", default="lenet")
    ap.add_argument("--weights", default=None)
    ap.add_argument("--shapes", default=None,
                    help="compiled batch shapes, e.g. 1,4,16,64")
    ap.add_argument("--delay-ms", type=float, default=None)
    ap.add_argument("--queue", type=int, default=None)
    ap.add_argument("--dtype", choices=("bf16", "f32"), default=None)
    ap.add_argument("--clients", type=int, default=8)
    ap.add_argument("--window", type=int, default=16,
                    help="outstanding requests per client (pipelined "
                         "frontend; total concurrency = clients*window)")
    ap.add_argument("--seconds", type=float, default=2.0,
                    help="duration per sweep point")
    ap.add_argument("--inputs", type=int, default=32,
                    help="distinct-input pool size")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--overload-x", type=float, default=2.0)
    ap.add_argument("--url", default=None,
                    help="drive a running tools/serve.py instead of an "
                         "in-process engine")
    ap.add_argument("--out", default=None, help="write the JSON report "
                                                "here (stdout always)")
    ap.add_argument("--smoke", action="store_true",
                    help="~2 s CI gate: assert bounded p99 under "
                         "overload + bit-identical results; rc!=0 on "
                         "violation")
    args = ap.parse_args(argv)

    if args.smoke:
        args.seconds = min(args.seconds, 0.4)
        args.clients = min(args.clients, 4)
        args.window = min(args.window, 16)
        args.queue = args.queue or 32   # overload must trip the bound
        shapes = (1, 4, 8)
        # paced below saturation: pacing AT capacity on the smoke's
        # tiny queue rejects legitimately, which would make the
        # "paced traffic holds its SLO" assert vacuous
        fractions = (0.5,)
    else:
        shapes = (tuple(int(s) for s in args.shapes.split(","))
                  if args.shapes else None)
        fractions = (0.25, 0.5, 1.0)

    report = run_report(
        model=args.model, weights=args.weights, shapes=shapes,
        delay_ms=args.delay_ms, queue=args.queue, dtype=args.dtype,
        clients=args.clients, window=args.window, seconds=args.seconds,
        inputs_n=args.inputs, seed=args.seed, fractions=fractions,
        overload_x=args.overload_x, url=args.url)
    report["captured_at"] = time.strftime("%Y-%m-%dT%H:%M:%S%z")
    line = json.dumps(report)
    print(line, flush=True)
    if args.out:
        with open(args.out, "w") as f:
            json.dump(report, f, indent=1)

    if args.smoke:
        v = report["verdicts"]
        bad = []
        if v["bit_identical"] is False:
            bad.append(f"{v['exact_mismatches']} result mismatches vs "
                       f"solo references")
        if not v["overload_p99_bounded"]:
            bad.append(f"overload p99 {report['overload']['p99_ms']} ms "
                       f"over bound {report['p99_bound_ms']} ms")
        if not v["overload_rejected"]:
            bad.append("overload produced zero rejections (admission "
                       "control never engaged)")
        if v["slo_paced_healthy"] is False:
            bad.append("SLO monitor reported a breach under paced "
                       "traffic")
        if v["slo_overload_breached"] is False:
            bad.append("SLO monitor failed to declare a breach under "
                       "2x overload")
        if bad:
            _log("SMOKE FAIL: " + "; ".join(bad))
            return 1
        _log(f"smoke ok: speedup {v['batching_speedup_x']}x, overload "
             f"p99 {report['overload']['p99_ms']} ms "
             f"<= {report['p99_bound_ms']} ms with "
             f"{v['overload_rejected']} rejections, bit-identical")
    return 0


if __name__ == "__main__":
    sys.exit(main())
