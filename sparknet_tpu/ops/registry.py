"""Layer registry — string type -> implementation.

Mirrors Caffe's ``LayerRegistry`` + ``REGISTER_LAYER_CLASS`` (reference:
caffe/include/caffe/layer_factory.hpp:55-136), but an "implementation" here
is a stateless object with pure functions: shape inference, parameter
initialization, and forward application.  Backward is free — the whole net is
differentiated by ``jax.grad``; there is no per-layer Backward_cpu/gpu to
write (reference: caffe/include/caffe/layer.hpp:335-341 dispatch).
"""

from __future__ import annotations

from typing import Any, Callable, Sequence

import jax

from ..proto.caffe_pb import LayerParameter

Shape = tuple[int, ...]


class LayerImpl:
    """Base layer implementation.

    Subclasses override:
      - ``out_shapes(lp, bottom_shapes)``: infer top shapes (concrete python
        ints — runs at graph-build time, keeping everything static for XLA).
      - ``init(rng, lp, bottom_shapes)``: create learnable blobs (list of
        arrays), mirroring each Caffe layer's ``LayerSetUp`` filler logic.
      - ``apply(lp, params, bottoms, train, rng)``: forward compute. Returns
        the list of top arrays, or ``(tops, new_params)`` for layers with
        forward-updated state (BatchNorm running stats).
    """

    type: str = ""

    def min_bottoms(self) -> int:
        return 1

    def out_shapes(self, lp: LayerParameter, bottom_shapes: Sequence[Shape]) -> list[Shape]:
        return [tuple(bottom_shapes[0])]

    def init(self, rng: jax.Array, lp: LayerParameter,
             bottom_shapes: Sequence[Shape]) -> list[jax.Array]:
        return []

    def apply(self, lp: LayerParameter, params: Sequence[jax.Array],
              bottoms: Sequence[jax.Array], train: bool,
              rng: jax.Array | None) -> Any:
        raise NotImplementedError(self.type)

    def is_loss(self) -> bool:
        """Whether top[0] carries an implicit loss_weight of 1
        (Caffe: Layer::SetUp assigns loss weight to *Loss layers)."""
        return self.type.endswith("Loss")

    def needs_rng(self, lp: LayerParameter, train: bool = True) -> bool:
        """Whether apply() requires an rng in the given mode (Dropout only
        when training; DummyData with random fillers in any phase)."""
        return False

    def per_net_copy(self) -> "LayerImpl":
        """Impl instance to bind into a Net being built.  Stateless layers
        (the default) return the registry singleton; layers holding
        per-net host state override to return a fresh copy (caffe
        instantiates layer objects per net — net.cpp Init)."""
        return self

    def top_has_batch_axis(self, lp: LayerParameter, top_index: int) -> bool:
        """Whether the given top carries the minibatch as axis 0.  Used by
        distributed eval to decide batch-sum vs element-wise aggregation
        (a per-class accuracy vector must NOT be summed over axis 0 even
        if its length equals the batch).  Reducing layers (losses,
        Accuracy) override to False."""
        return True


_REGISTRY: dict[str, LayerImpl] = {}


def register_layer(type_name: str) -> Callable[[type], type]:
    def deco(cls: type) -> type:
        impl = cls()
        impl.type = type_name
        if type_name in _REGISTRY:
            raise ValueError(f"layer type {type_name!r} registered twice")
        _REGISTRY[type_name] = impl
        return cls
    return deco


def get_layer_impl(type_name: str) -> LayerImpl:
    try:
        return _REGISTRY[type_name]
    except KeyError:
        raise KeyError(
            f"Unknown layer type: {type_name!r} (known: {sorted(_REGISTRY)})"
        ) from None


def registered_types() -> list[str]:
    return sorted(_REGISTRY)
