"""Structured tracing — the profiling tier the reference lacked.

The reference's tracing is wall-clock logs + CUDA-event timers (reference:
caffe/src/caffe/util/benchmark.cpp:26-145, app logs CifarApp.scala:41-50,
Spark event log ImageNetApp.scala:44; SURVEY.md §5 "No structured
tracing").  Here: ``jax.profiler`` traces viewable in TensorBoard/Perfetto,
plus annotation helpers that mark app phases inside the trace.
"""

from __future__ import annotations

import contextlib
from typing import Iterator

import jax


@contextlib.contextmanager
def trace(log_dir: str) -> Iterator[None]:
    """Capture a device+host profiler trace for the enclosed block
    (open in TensorBoard's profile tab or Perfetto)."""
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def annotate(name: str):
    """Named region inside a trace (TraceAnnotation), usable as decorator
    or context manager."""
    return jax.profiler.TraceAnnotation(name)


@contextlib.contextmanager
def server(port: int = 9999) -> Iterator[None]:
    """Live profiling server for `jax.profiler`-compatible clients."""
    s = jax.profiler.start_server(port)
    try:
        yield
    finally:
        del s


def device_memory_summary() -> list[dict]:
    """Per-device HBM usage (bytes in use / limit / peak) — the
    observability the reference's SyncedMemory world never exposed; used
    by `caffe device_query` and available for app logs."""
    out = []
    for d in jax.devices():
        stats = getattr(d, "memory_stats", lambda: None)() or {}
        out.append({
            "device": f"{d.platform}:{d.id}",
            "kind": d.device_kind,
            "bytes_in_use": stats.get("bytes_in_use"),
            "peak_bytes_in_use": stats.get("peak_bytes_in_use"),
            "bytes_limit": stats.get("bytes_limit"),
        })
    return out


def save_memory_profile(path: str) -> None:
    """Write a pprof-format device memory profile
    (jax.profiler.save_device_memory_profile)."""
    jax.profiler.save_device_memory_profile(path)


# Benchmark-harness pieces shared by bench.py and tools/profile_step.py so
# the profiled program IS the benchmarked one: model table, solver config,
# per-step FLOPs estimate, peak table, and the scanned train block.

BENCH_SOLVER_PROTOTXT = (
    'base_lr: 0.01\nmomentum: 0.9\nweight_decay: 0.0005\n'
    'lr_policy: "step"\ngamma: 0.1\nstepsize: 100000\n')


def build_bench_model(name: str, batch: int):
    """(net_param, input_shape, num_classes) for a benchmark model name."""
    from ..models import caffenet, googlenet, lenet, vgg16
    if name == "lenet":
        return lenet(batch, batch), (1, 28, 28), 10
    if name == "googlenet":
        return googlenet(batch, batch, crop=224), (3, 224, 224), 1000
    if name == "vgg16":
        return vgg16(batch, batch, crop=224), (3, 224, 224), 1000
    if name == "caffenet":
        return caffenet(batch, batch), (3, 227, 227), 1000
    raise ValueError(f"unknown bench model {name!r}")


def record_fusion_plan(net, out_dir: str | None = None) -> str:
    """The capture-stamping half of the vertical fusion pass
    (graph/fusion.py): returns the net's plan id (the perf-ledger
    fingerprint field — "off" when nothing fuses) and, given a profile
    ``out_dir``, writes ``fusion_plan.json`` next to the op_table so the
    capture is reproducible — ``SPARKNET_FUSE=<that file>`` replays
    exactly the chains this capture ran, and refused hotspots are on
    record rather than silently dropped.  Shared by bench.py and
    tools/profile_step.py so the benchmarked and the profiled program
    stamp identically."""
    import os
    plan = getattr(net, "_fuse_plan", None)
    if out_dir is not None and plan is not None:
        plan.save(os.path.join(out_dir, "fusion_plan.json"))
    return net.fuse_plan_id()


def record_tuning(net, out_dir: str | None = None) -> str:
    """The capture-stamping half of the lowering autotuner
    (graph/tuner.py), mirroring :func:`record_fusion_plan`: returns the
    net's tune-plan id (the perf-ledger ``tune_plan`` fingerprint field
    — "off" when no table is active) and, given a profile ``out_dir``,
    copies the active tuning table next to the op_table so the capture
    is reproducible — ``SPARKNET_TUNE=<that file>`` replays exactly the
    lowerings this capture ran."""
    import os
    from ..graph import tuner
    tune_id = net.tune_plan_id() if hasattr(net, "tune_plan_id") else "off"
    if out_dir is not None and tune_id != "off":
        table = tuner.active_table()
        if table is not None and table.table_id() == tune_id:
            table.save(os.path.join(out_dir, "tuning.json"))
    return tune_id


def step_cost_flops(solver, batch) -> float | None:
    """Model FLOPs of one compiled train step via XLA cost analysis
    (best-effort; a fori_loop block would undercount — cost the single
    step).  Returns None with a stderr breadcrumb where the backend
    doesn't support cost analysis."""
    import sys
    try:
        lowered = solver._step.lower(solver.params, solver.state, 0, batch,
                                     jax.random.PRNGKey(1))
        cost = lowered.compile().cost_analysis()
        if cost:
            cost = cost[0] if isinstance(cost, (list, tuple)) else cost
            return float(cost.get("flops", 0.0)) or None
    except Exception as e:
        print(f"[profiling] cost_analysis unavailable: {e}", file=sys.stderr)
    return None


# bf16 peak FLOP/s by device kind (public spec sheets) — the MFU
# denominator shared by bench.py and tools/profile_step.py.
_PEAK_FLOPS_BF16 = {
    "TPU v5 lite": 197e12, "TPU v5e": 197e12,
    "TPU v5p": 459e12, "TPU v5": 459e12,
    "TPU v4": 275e12, "TPU v4 lite": 138e12,
    "TPU v3": 123e12, "TPU v2": 46e12,
    "TPU v6 lite": 918e12, "TPU v6e": 918e12,
}


def peak_flops(device_kind: str) -> float | None:
    """bf16 peak FLOP/s for a jax device_kind, or None if unknown."""
    return _PEAK_FLOPS_BF16.get(device_kind)


def fwd_cost_flops(jitted_fwd, *args) -> float | None:
    """Model FLOPs of any jitted forward via XLA cost analysis
    (best-effort, like :func:`step_cost_flops`) — shared by the eval-MFU
    numerator and the serving plane's per-model FLOPs estimate."""
    import sys
    try:
        lowered = jitted_fwd.lower(*args)
        cost = lowered.compile().cost_analysis()
        if cost:
            cost = cost[0] if isinstance(cost, (list, tuple)) else cost
            return float(cost.get("flops", 0.0)) or None
    except Exception as e:
        print(f"[profiling] cost_analysis unavailable: {e}", file=sys.stderr)
    return None


def eval_cost_flops(solver, batch) -> float | None:
    """Model FLOPs of one compiled test-net forward (the eval-pass MFU
    numerator), via XLA cost analysis like :func:`step_cost_flops`."""
    return fwd_cost_flops(solver._test_fwd, solver.params, batch, None)


def scanned_eval_block(solver, iters: int):
    """Forward-only analog of :func:`scanned_train_block`: ``iters``
    test-net forward passes as ONE compiled fori_loop, with a scalar
    loop-carried perturbation of the input so XLA can neither hoist nor
    elide the forward (the shared-weights eval pass the bench's
    eval_images_per_sec times; `caffe time`'s forward leg,
    caffe/tools/caffe.cpp:290-376).

    Returns ``block(params, batch, s0) -> s`` (an opaque scalar)."""
    import jax.numpy as jnp
    from jax import lax

    fwd = solver._make_test_forward(solver.test_net)

    def block_fn(params, batch, s0):
        def body(i, s):
            b = {k: (v + (s * 1e-20).astype(v.dtype)
                     if jnp.issubdtype(v.dtype, jnp.floating) else v)
                 for k, v in batch.items()}
            out = fwd(params, b)
            taps = [jnp.sum(v).astype(jnp.float32)
                    for v in jax.tree_util.tree_leaves(out)]
            return jnp.sum(jnp.stack(taps)) * 1e-20
        return lax.fori_loop(0, iters, body, s0)

    return jax.jit(block_fn)


def scanned_train_block(solver, iters: int):
    """The production-shaped benchmark block: ``iters`` solver steps as ONE
    compiled fori_loop with donated params/state — the same execution model
    as DistributedTrainer.train_round.  Shared by bench.py and
    tools/profile_step.py so the profiled program IS the benchmarked one.

    Returns ``block(params, state, it0, batch, rng) -> (params, state,
    rng, loss)``.
    """
    import jax.numpy as jnp
    from jax import lax

    raw_step = solver.make_train_step()

    def block_fn(params, state, it0, batch, rng):
        def body(i, carry):
            params, state, rng, _loss = carry
            rng, sub = jax.random.split(rng)
            params, state, loss = raw_step(params, state, it0 + i,
                                           batch, sub)
            return (params, state, rng, loss)
        return lax.fori_loop(0, iters, body,
                             (params, state, rng, jnp.zeros(())))

    return jax.jit(block_fn, donate_argnums=(0, 1))
