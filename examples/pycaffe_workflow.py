"""The classic pycaffe workflow, unchanged on this framework.

Mirrors the reference's pycaffe examples (caffe/examples/00-classification
and 01-learning-lenet notebooks, python/caffe/test usage): build a net
with NetSpec, train it with get_solver, inspect blobs/params, do net
surgery, save/reload, and classify with a Transformer-preprocessed input.

Run:  python examples/pycaffe_workflow.py        (CPU or TPU)
"""

import os
import sys
import tempfile

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Honor JAX_PLATFORMS=cpu even with a TPU plugin installed: some plugins
# (the tunneled axon one on this rig) ignore the env var and hang backend
# init when unreachable; the config route is always respected.
if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
    import jax
    jax.config.update("jax_platforms", "cpu")

from sparknet_tpu import pycaffe_compat  # noqa: E402

pycaffe_compat.install()

import caffe  # noqa: E402  (resolves to the shim)
from caffe import layers as L, params as P  # noqa: E402


def make_nets(workdir: str) -> str:
    """Author train/test nets with NetSpec and a solver prototxt."""
    n = caffe.NetSpec()
    n.data, n.label = L.DummyData(
        dummy_data_param=dict(
            shape=[dict(dim=[32, 1, 12, 12]), dict(dim=[32])],
            data_filler=[dict(type="gaussian", std=1.0),
                         dict(type="constant", value=1.0)]),
        ntop=2)
    n.conv1 = L.Convolution(n.data, kernel_size=3, num_output=8,
                            weight_filler=dict(type="xavier"))
    n.relu1 = L.ReLU(n.conv1, in_place=True)
    n.pool1 = L.Pooling(n.relu1, kernel_size=2, stride=2,
                        pool=P.Pooling.MAX)
    n.score = L.InnerProduct(n.pool1, num_output=3,
                             weight_filler=dict(type="xavier"))
    n.loss = L.SoftmaxWithLoss(n.score, n.label)
    n.acc = L.Accuracy(n.score, n.label, include=dict(phase="TEST"))
    net_path = os.path.join(workdir, "net.prototxt")
    with open(net_path, "w") as f:
        f.write(str(n.to_proto()))

    solver_path = os.path.join(workdir, "solver.prototxt")
    with open(solver_path, "w") as f:
        f.write('net: "net.prototxt"\nbase_lr: 0.1\nmomentum: 0.9\n'
                'test_iter: 2\ntest_interval: 1000\nrandom_seed: 1\n')
    return solver_path


def main() -> None:
    workdir = tempfile.mkdtemp(prefix="pycaffe_example_")
    solver_path = make_nets(workdir)
    os.chdir(workdir)  # net: reference resolves like Caffe (cwd first)

    # --- train ----------------------------------------------------------
    solver = caffe.get_solver(solver_path)
    l0 = solver.step(1)
    l1 = solver.step(60)
    print(f"loss {l0:.3f} -> {l1:.3f} after {solver.iter} iters")

    # --- inspect --------------------------------------------------------
    print("layers:", [(ly.type, [b.shape for b in ly.blobs])
                      for ly in solver.net.layers][:3], "...")
    out = solver.test_nets[0].forward()
    print("test net loss:", float(out["loss"]))

    # --- net surgery + save/reload -------------------------------------
    solver.net.params["score"][0].data[...] *= 0.5
    model_path = os.path.join(workdir, "surgery.caffemodel")
    solver.net.save(model_path)
    net = caffe.Net(open(os.path.join(workdir, "net.prototxt")).read(),
                    weights=model_path, phase=caffe.TEST)
    out = net.forward()
    print("reloaded net forward loss:", float(out["loss"]))

    # --- Transformer-preprocessed classification -----------------------
    deploy = caffe.NetSpec()
    deploy.data = L.Input(input_param=dict(
        shape=dict(dim=[1, 1, 12, 12])))
    deploy.conv1 = L.Convolution(deploy.data, kernel_size=3, num_output=8)
    deploy.relu1 = L.ReLU(deploy.conv1, in_place=True)
    deploy.pool1 = L.Pooling(deploy.relu1, kernel_size=2, stride=2,
                             pool=P.Pooling.MAX)
    deploy.score = L.InnerProduct(deploy.pool1, num_output=3)
    deploy.prob = L.Softmax(deploy.score)
    dnet = caffe.Net(str(deploy.to_proto()), weights=model_path,
                     phase=caffe.TEST)
    t = caffe.io.Transformer({"data": dnet.blobs["data"].shape})
    t.set_transpose("data", (2, 0, 1))
    img = np.random.default_rng(0).uniform(size=(12, 12, 1)).astype(np.float32)
    dnet.blobs["data"].data[...] = t.preprocess("data", img)
    probs = dnet.forward()["prob"]
    print("class probabilities:", np.round(probs[0], 3))
    assert abs(probs.sum() - 1.0) < 1e-4

    # --- deploy-time reshape (the batch-size idiom) ---------------------
    dnet.blobs["data"].reshape(5, 1, 12, 12)
    dnet.blobs["data"].data[...] = np.random.default_rng(1).uniform(
        size=(5, 1, 12, 12)).astype(np.float32)
    probs5 = dnet.forward()["prob"]
    print("after reshape to batch 5:", probs5.shape)
    assert probs5.shape == (5, 3)

    # --- batched scoring over many samples ------------------------------
    imgs = np.random.default_rng(2).uniform(
        size=(13, 1, 12, 12)).astype(np.float32)
    outs = dnet.forward_all(data=imgs)
    print("forward_all over 13 samples:", outs["prob"].shape)
    assert outs["prob"].shape == (13, 3)

    # --- saliency via ranged backward (the DeepDream pattern) -----------
    dnet.blobs["data"].reshape(1, 1, 12, 12)
    dnet.blobs["data"].data[...] = t.preprocess("data", img)
    dnet.forward(end="score")
    dnet.blobs["score"].diff[...] = np.eye(3, dtype=np.float32)[0]
    sal = dnet.backward(start="score")["data"]
    print("saliency |grad| for class 0:", round(float(np.abs(sal).sum()), 4))
    assert sal.shape == (1, 1, 12, 12) and np.any(sal != 0)
    print("OK")


if __name__ == "__main__":
    main()
