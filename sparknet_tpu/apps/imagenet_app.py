"""ImageNetApp — AlexNet/CaffeNet on ImageNet-style data (reference:
src/main/scala/apps/ImageNetApp.scala).

Phase parity with the reference: tar → JPEG → force-resize 256 (:84-95 via
ScaleAndConvert) → distributed mean image (:84, ComputeMean) → τ=50 rounds
(:144) with train-time random-crop-227+mirror+mean-subtract closures
(:155-169) and center-crop test preprocessing (:117-131), eval every 10
rounds aggregated across workers (:106-141).  The crop/mirror/mean hot loop
runs in the native C++ pipeline; ``--synthetic`` fabricates resized images
so the app smoke-runs with no dataset.

Run:  python -m sparknet_tpu.apps.imagenet_app --workers 8 --rounds 3 \
          --synthetic --batch 16
"""

from __future__ import annotations

import argparse
import functools
import os
import time

import numpy as np

from typing import Any

from ..data.imagenet import load_imagenet
from ..data.partition import PartitionedDataset
from ..data.transforms import center_crop, random_crop_mirror
from ..models import alexnet, caffenet, googlenet, vgg16
from ..parallel import (
    DistributedTrainer,
    TrainerConfig,
    device_crop_mirror_mean,
    make_mesh,
)
from ..proto import load_solver_prototxt_with_net
from ..utils.timing import PhaseLogger
from ..parallel.cluster import global_max
from .common import RoundFeed, eval_feed, run_training

SOLVER = """
base_lr: 0.01
momentum: 0.9
weight_decay: 0.0005
lr_policy: "step"
gamma: 0.1
stepsize: 100000
"""

MODELS = {"alexnet": alexnet, "caffenet": caffenet, "googlenet": googlenet,
          "vgg16": vgg16}


def synthetic_imagenet(n: int, size: int, classes: int, seed: int = 0):
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, classes, size=n)
    x = rng.normal(scale=30.0, size=(n, 3, size, size)).astype(np.float32) + 120
    for i in range(n):
        k = labels[i]
        x[i, k % 3, (7 * k) % size, :] += 80.0
    return np.clip(x, 0, 255), labels.astype(np.int32)


def main(argv=None) -> dict[str, Any]:
    ap = argparse.ArgumentParser(description="ImageNet parameter-averaging app")
    ap.add_argument("--workers", type=int, default=None)
    ap.add_argument("--tar-dir", default=None,
                    help="directory of .tar archives of JPEGs")
    ap.add_argument("--label-file", default=None, help="train.txt label map")
    ap.add_argument("--test-tar-dir", default=None)
    ap.add_argument("--test-label-file", default=None)
    ap.add_argument("--synthetic", action="store_true")
    ap.add_argument("--model", choices=sorted(MODELS), default="caffenet")
    ap.add_argument("--classes", type=int, default=1000)
    ap.add_argument("--batch", type=int, default=32,
                    help="per-worker minibatch size")
    ap.add_argument("--tau", type=int, default=50,
                    help="local steps per round (ImageNetApp.scala:144)")
    ap.add_argument("--rounds", type=int, default=10)
    ap.add_argument("--test-interval", type=int, default=10)
    ap.add_argument("--strategy", choices=["local_sgd", "sync"],
                    default="local_sgd")
    ap.add_argument("--resize", type=int, default=256)
    ap.add_argument("--crop", type=int, default=None,
                    help="default 227 (AlexNet-class) / 224 (GoogLeNet, VGG)")
    ap.add_argument("--base-lr", type=float, default=None)
    ap.add_argument("--device-preprocess", action="store_true",
                    help="random crop/mirror/mean INSIDE the compiled "
                         "round (host ships raw full-size images — for "
                         "hosts whose CPUs can't keep up with the chips)")
    ap.add_argument("--snapshot", default=None)
    ap.add_argument("--log-dir", default=".")
    args = ap.parse_args(argv)

    from ..utils.platform import honor_platform_env
    honor_platform_env()
    crop = args.crop or (227 if args.model in ("alexnet", "caffenet") else 224)

    log = PhaseLogger(os.path.join(
        args.log_dir, f"training_log_{int(time.time())}.txt"))
    mesh = make_mesh(args.workers)
    workers = mesh.shape["data"]

    if args.synthetic or args.tar_dir is None:
        log.log("using synthetic ImageNet-like data")
        need = args.batch * workers * (args.tau + 2)
        train_x, train_y = synthetic_imagenet(need, args.resize, args.classes, 1)
        test_x, test_y = synthetic_imagenet(
            max(args.batch * workers * 2, 64), args.resize, args.classes, 2)
        train_ds = PartitionedDataset.from_items(
            list(zip(train_x, train_y)), workers)
        test_ds = PartitionedDataset.from_items(
            list(zip(test_x, test_y)), workers)
    else:
        log.log(f"loading tars from {args.tar_dir}")
        train_ds = load_imagenet(args.tar_dir, args.label_file, workers,
                                 size=args.resize)
        test_ds = load_imagenet(args.test_tar_dir or args.tar_dir,
                                args.test_label_file or args.label_file,
                                workers, size=args.resize)
    log.log(f"train/test partitions: {train_ds.partition_sizes()} / "
            f"{test_ds.partition_sizes()}")

    # distributed mean image over train partitions (ComputeMean analog; the
    # per-partition sums run in the native pipeline)
    from .. import native
    acc = np.zeros((3, args.resize, args.resize), np.float64)
    count = 0
    for p in train_ds.partitions:
        # chunked so the accumulation never copies a whole partition
        for i in range(0, len(p), 64):
            imgs = np.stack([x for x, _ in p[i:i + 64]]).astype(np.float32)
            native.accumulate_mean(imgs, acc)
        count += len(p)
    mean = (acc / max(count, 1)).astype(np.float32)
    log.log("computed mean image")

    test_pre = functools.partial(center_crop, crop=crop, mean=mean)
    if args.device_preprocess:
        train_pre = None  # host ships raw images; crop runs on-device
        device_pre = device_crop_mirror_mean(crop, mirror=True, mean=mean)
    else:
        train_pre = functools.partial(random_crop_mirror, crop=crop,
                                      rng=np.random.default_rng(7),
                                      mean=mean)
        device_pre = None

    net = MODELS[args.model](args.batch * workers, args.batch * workers,
                             crop=crop)
    sp = load_solver_prototxt_with_net(SOLVER, net)
    if args.base_lr is not None:
        sp.base_lr = args.base_lr
    trainer = DistributedTrainer(
        sp, mesh, TrainerConfig(strategy=args.strategy, tau=args.tau,
                                device_preprocess=device_pre), seed=0)
    log.log(f"built {args.model} on {workers}-worker mesh "
            f"({args.strategy}, tau={args.tau}, crop={crop}, "
            f"{'device' if device_pre else 'host'} preprocess)")

    feed = RoundFeed(train_ds, args.batch, trainer.batches_per_round,
                     preprocess=train_pre, seed=3)
    test_factory, test_steps = eval_feed(test_ds, args.batch,
                                         preprocess=lambda x: test_pre(x))
    test_steps = global_max(test_steps)  # lockstep step count across hosts
    scores = run_training(trainer, feed, test_factory, test_steps,
                          rounds=args.rounds,
                          test_interval=args.test_interval, logger=log)
    if args.snapshot:
        trainer.snapshot(args.snapshot)
        log.log(f"snapshot -> {args.snapshot}")
    return scores


if __name__ == "__main__":
    main()
