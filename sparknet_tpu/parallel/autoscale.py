"""SLO-driven autoscaler: per-model replica counts from observed pain.

PR 9's :class:`~sparknet_tpu.parallel.serving.SLOMonitor` can only
*report* a burn; this module closes the loop (ROADMAP item 3): it
samples each replica's queue depth, rejection counters, and SLO verdict
— the same facts the health beacons already carry — and turns them into
scale decisions inside the fleet's device budget.

The policy (deliberately boring, fully inspectable):

- **Scale up** one replica for a model when the fleet shows *pressure*:
  mean per-replica backlog (engine queue depth + router outstanding)
  reaches ``up_queue``, OR any replica's SLO is in breach, OR typed
  rejections grew since the last sample.  A scale-up that the device
  budget refuses is RECORDED (``up_blocked``) rather than queued — the
  budget is the training tenants' protection, not a suggestion.
- **Scale down** one replica when the model has been *idle* (zero
  backlog, zero new rejections) for ``down_idle_s`` — never below
  ``min_replicas``.  The victim is drained (see
  :class:`~sparknet_tpu.parallel.router.RouterDrainHook`) before any
  signal, so scale-down is lossless by construction.
- **Cooldown** ``cooldown_s`` separates consecutive decisions per model
  so a launch's warm-up (compile!) can land before it is judged.

Every decision (including holds-with-reason like ``up_blocked``) is
kept as the model's ``last`` record and atomically persisted to
``autoscale.json`` so ``tools/fleet.py status`` shows the last scale
decision + reason with no live channel — the same offline-status
posture the fleet journal takes.

Env knobs (defaults in :class:`AutoscaleConfig`):
  SPARKNET_AUTOSCALE_MIN        — floor replicas per model (1).
  SPARKNET_AUTOSCALE_MAX        — ceiling replicas per model (4).
  SPARKNET_AUTOSCALE_UP_QUEUE   — mean per-replica backlog that means
                                  pressure (8).
  SPARKNET_AUTOSCALE_DOWN_IDLE_S— idle seconds before a scale-down (10).
  SPARKNET_AUTOSCALE_COOLDOWN_S — seconds between decisions per model (5).
  SPARKNET_AUTOSCALE_EVAL_S     — sampler period (1).

The sampler input is a plain callable (``stats_fn``) returning

    {model: [{"rid": ..., "queue_depth": int, "outstanding": int,
              "rejected_total": int, "slo_breach": bool}, ...]}

so the tests drive the policy with scripted stats and a fake clock, and
:class:`~sparknet_tpu.parallel.router.ServingFleet` feeds it from
beacons + router state (see :func:`fleet_stats_fn`).
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
from typing import Any, Callable, Mapping

from ..utils import telemetry
from .serving import _env_float


@dataclasses.dataclass(frozen=True)
class AutoscaleConfig:
    min_replicas: int = dataclasses.field(
        default_factory=lambda: int(_env_float("SPARKNET_AUTOSCALE_MIN",
                                               1)))
    max_replicas: int = dataclasses.field(
        default_factory=lambda: int(_env_float("SPARKNET_AUTOSCALE_MAX",
                                               4)))
    up_queue: float = dataclasses.field(
        default_factory=lambda: _env_float("SPARKNET_AUTOSCALE_UP_QUEUE",
                                           8.0))
    down_idle_s: float = dataclasses.field(
        default_factory=lambda: _env_float(
            "SPARKNET_AUTOSCALE_DOWN_IDLE_S", 10.0))
    cooldown_s: float = dataclasses.field(
        default_factory=lambda: _env_float(
            "SPARKNET_AUTOSCALE_COOLDOWN_S", 5.0))
    sample_every_s: float = dataclasses.field(
        default_factory=lambda: _env_float("SPARKNET_AUTOSCALE_EVAL_S",
                                           1.0))

    def __post_init__(self):
        if self.min_replicas < 0:
            raise ValueError(f"min_replicas must be >= 0, "
                             f"got {self.min_replicas}")
        if self.max_replicas < max(self.min_replicas, 1):
            raise ValueError(
                f"max_replicas ({self.max_replicas}) must be >= "
                f"max(min_replicas, 1) ({max(self.min_replicas, 1)})")
        if self.up_queue <= 0:
            raise ValueError(f"up_queue must be > 0, got {self.up_queue}")
        if self.down_idle_s <= 0 or self.cooldown_s < 0 \
                or self.sample_every_s <= 0:
            raise ValueError(
                f"down_idle_s ({self.down_idle_s}) must be > 0, "
                f"cooldown_s ({self.cooldown_s}) >= 0, sample_every_s "
                f"({self.sample_every_s}) > 0")


class Autoscaler:
    """The decision loop (policy in the module docstring).

    ``scale_up(model) -> bool`` and ``scale_down(model) -> str | None``
    are the actuation callbacks (:class:`ServingFleet` wires its own);
    a ``False`` / ``None`` return means the action was refused (budget,
    no victim) and is recorded as a blocked decision."""

    def __init__(self, stats_fn: Callable[[], Mapping[str, list]],
                 scale_up: Callable[[str], bool],
                 scale_down: Callable[[str], Any],
                 cfg: AutoscaleConfig | None = None,
                 state_path: str | None = None,
                 clock: Callable[[], float] = time.monotonic):
        self.cfg = cfg or AutoscaleConfig()
        self.stats_fn = stats_fn
        self.scale_up = scale_up
        self.scale_down = scale_down
        self.state_path = state_path
        self._clock = clock
        self._lock = threading.Lock()
        self.last: dict[str, dict[str, Any]] = {}    # model -> decision
        self.decisions: list[dict[str, Any]] = []    # bounded trail
        self._last_rejected: dict[str, int] = {}
        self._idle_since: dict[str, float] = {}
        self._last_action_at: dict[str, float] = {}
        self.evaluations = 0
        self.sample_errors = 0
        self.last_sample_error: str | None = None
        reg = telemetry.get_registry()
        self._m_dec = reg.counter(
            "autoscale_decisions_total", "autoscaler decisions by action")
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # -- policy -----------------------------------------------------------
    def _decide_model(self, model: str, replicas: list[dict],
                      now: float) -> dict[str, Any] | None:
        n = len(replicas)
        backlog = sum(int(r.get("queue_depth") or 0)
                      + int(r.get("outstanding") or 0) for r in replicas)
        mean_backlog = backlog / n if n else 0.0
        rejected = sum(int(r.get("rejected_total") or 0)
                       for r in replicas)
        rej_delta = max(rejected - self._last_rejected.get(model, 0), 0)
        self._last_rejected[model] = rejected
        breach = any(r.get("slo_breach") for r in replicas)

        pressure = []
        if n and mean_backlog >= self.cfg.up_queue:
            pressure.append(f"backlog {mean_backlog:.1f}/replica >= "
                            f"{self.cfg.up_queue:g}")
        if breach:
            pressure.append("SLO breach")
        if rej_delta:
            pressure.append(f"+{rej_delta} rejections")

        if pressure:
            self._idle_since.pop(model, None)
        elif backlog == 0 and n:
            self._idle_since.setdefault(model, now)
        else:
            self._idle_since.pop(model, None)

        cooling = (now - self._last_action_at.get(model, -1e18)
                   < self.cfg.cooldown_s)
        if n < self.cfg.min_replicas and not cooling:
            # below the floor: replicas died faster than the fleet could
            # requeue them (bulk host loss).  Backfill onto surviving
            # hosts within budget — this is availability repair, so it
            # outranks the pressure/idle policy.
            ok = bool(self.scale_up(model))
            self._last_action_at[model] = now
            return {"action": "up" if ok else "up_blocked",
                    "reason": f"{n} < min_replicas "
                              f"{self.cfg.min_replicas} — backfill"
                              + ("" if ok else " blocked: device budget "
                                               "has no free gang"),
                    "replicas": n}
        if pressure and n < self.cfg.max_replicas and not cooling:
            ok = bool(self.scale_up(model))
            self._last_action_at[model] = now
            return {"action": "up" if ok else "up_blocked",
                    "reason": "; ".join(pressure)
                              + ("" if ok else " — device budget has no "
                                               "free gang"),
                    "replicas": n}
        if pressure and n >= self.cfg.max_replicas:
            # at the ceiling: the typed rejections ARE the absorption —
            # record it so status explains why nothing moved
            return {"action": "hold_at_max",
                    "reason": "; ".join(pressure)
                              + f" — at max_replicas {self.cfg.max_replicas}",
                    "replicas": n}
        idle_for = (now - self._idle_since[model]
                    if model in self._idle_since else 0.0)
        if (idle_for >= self.cfg.down_idle_s
                and n > self.cfg.min_replicas and not cooling):
            victim = self.scale_down(model)
            self._last_action_at[model] = now
            self._idle_since.pop(model, None)
            return {"action": "down" if victim else "down_blocked",
                    "reason": f"idle {idle_for:.1f}s >= "
                              f"{self.cfg.down_idle_s:g}s"
                              + (f" — draining {victim}" if victim
                                 else " — no victim"),
                    "replicas": n}
        return None

    def evaluate(self) -> list[dict[str, Any]]:
        """One policy pass over a fresh sample; returns (and records)
        the decisions it took."""
        now = self._clock()
        stats = self.stats_fn()
        out = []
        for model, replicas in sorted(stats.items()):
            dec = self._decide_model(model, list(replicas), now)
            if dec is None:
                continue
            dec.update(model=model, at=round(now, 3))
            out.append(dec)
            with self._lock:
                self.last[model] = dec
                self.decisions.append(dec)
                del self.decisions[:-64]
            self._m_dec.inc(action=dec["action"])
            telemetry.get_recorder().record(
                "autoscale", model=model, action=dec["action"],
                reason=dec["reason"])
        with self._lock:
            self.evaluations += 1
        self._persist(stats, now)
        return out

    # -- persistence (the offline-status channel) -------------------------
    def _persist(self, stats: Mapping[str, list], now: float) -> None:
        if not self.state_path:
            return
        with self._lock:
            doc = {
                "t": time.time(),
                "evaluations": self.evaluations,
                "config": dataclasses.asdict(self.cfg),
                "models": {
                    m: {"replicas": len(reps),
                        "backlog": sum(int(r.get("queue_depth") or 0)
                                       + int(r.get("outstanding") or 0)
                                       for r in reps),
                        "last": self.last.get(m)}
                    for m, reps in sorted(stats.items())},
            }
        tmp = f"{self.state_path}.tmp.{os.getpid()}"
        os.makedirs(os.path.dirname(self.state_path) or ".",
                    exist_ok=True)
        try:
            with open(tmp, "w") as f:
                json.dump(doc, f, indent=1)
            os.replace(tmp, self.state_path)
        except OSError:
            pass   # an unwritable state file must not kill the sampler

    # -- lifecycle --------------------------------------------------------
    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(target=self._loop,
                                        name="autoscaler", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.cfg.sample_every_s + 5.0)
            self._thread = None

    def _loop(self) -> None:
        while not self._stop.wait(self.cfg.sample_every_s):
            try:
                self.evaluate()
            except Exception as e:
                # a broken scrape must not kill the sampler — park it
                # for summary() instead of swallowing
                self.sample_errors += 1
                self.last_sample_error = f"{type(e).__name__}: {e}"


def fleet_stats_fn(fleet) -> Callable[[], dict[str, list]]:
    """Build the autoscaler's sampler over a
    :class:`~sparknet_tpu.parallel.router.ServingFleet`: per replica,
    the engine-side backlog from its health beacon's serving extras
    (queue_depth, rejected, SLO state) joined with the router's own
    outstanding count — no extra channel, the beacons the fleet status
    table already reads."""

    def stats() -> dict[str, list]:
        out: dict[str, list] = {}
        for name, model in sorted(fleet._model_of.items()):
            job = fleet.sched.jobs.get(name)
            if job is None or job.state not in ("RUNNING", "PREEMPTING"):
                continue
            rec: dict[str, Any] = {
                "rid": name,
                "outstanding": fleet.router.outstanding(name),
                "queue_depth": 0, "rejected_total": 0,
                "slo_breach": False,
            }
            for _rank, beat in fleet.sched._heartbeats(job).items():
                extras = beat.get("extras") or {}
                if not extras.get("serving"):
                    continue
                rec["queue_depth"] = int(extras.get("queue_depth") or 0)
                rejected = extras.get("rejected") or {}
                rec["rejected_total"] = (
                    sum(rejected.values())
                    if isinstance(rejected, Mapping) else int(rejected))
                rec["slo_breach"] = ((extras.get("slo") or {}).get(
                    "state") == "breach")
                break
            out.setdefault(model, []).append(rec)
        return out

    return stats
