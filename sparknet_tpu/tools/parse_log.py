"""parse_log — split a training log into train/test CSVs (reference:
caffe/tools/extra/parse_log.py, which greps glog output for
"Iteration N, loss" and "Test net output" lines; this framework's
Solver prints the same shapes — solver.py step/solve/_print_test_scores).

Usage:
  python -m sparknet_tpu.tools.parse_log LOGFILE [OUT_DIR]

Writes LOGFILE.train (NumIters,loss) and LOGFILE.test
(NumIters,TestNet,<output columns>) into OUT_DIR (default: the log's
directory), mirroring the reference's <log>.train/<log>.test CSVs.
"""

from __future__ import annotations

import argparse
import csv
import os
import re

_FLOAT = r"([-+]?(?:[0-9][0-9.]*(?:[eE][-+]?\d+)?|nan|inf))"
_ITER_RE = re.compile(r"Iteration (\d+), loss = " + _FLOAT)
_TESTING_RE = re.compile(r"Iteration (\d+), Testing net \(#(\d+)\)")
_TEST_RE = re.compile(
    r"Test net(?: #(\d+))? output: (\S+?)(?:\[(\d+)\])? = " + _FLOAT)


def parse_log(path: str):
    """-> (train_rows, test_rows): train [(iter, loss)], test
    {(iter, net_id): {column: value}} in encounter order."""
    train: list[tuple[int, float]] = []
    test: dict[tuple[int, int], dict[str, float]] = {}
    cur_iter = 0
    cur_test_net = 0
    with open(path) as f:
        for line in f:
            m = _ITER_RE.search(line)
            if m:
                cur_iter = int(m.group(1))
                train.append((cur_iter, float(m.group(2))))
                continue
            m = _TESTING_RE.search(line)
            if m:  # the authoritative iteration for following scores —
                #    covers the pre-training pass on resume, where no
                #    "Iteration N, loss" line has printed yet
                cur_iter = int(m.group(1))
                cur_test_net = int(m.group(2))
                continue
            m = _TEST_RE.search(line)
            if m:
                net_id = int(m.group(1) or cur_test_net)
                col = m.group(2)
                if m.group(3) is not None:  # indexed per-class outputs
                    col = f"{col}[{m.group(3)}]"
                test.setdefault((cur_iter, net_id), {})[col] = \
                    float(m.group(4))
    return train, test


def write_csvs(path: str, out_dir: str | None = None) -> tuple[str, str]:
    train, test = parse_log(path)
    out_dir = out_dir or (os.path.dirname(os.path.abspath(path)))
    base = os.path.join(out_dir, os.path.basename(path))
    train_path, test_path = base + ".train", base + ".test"
    with open(train_path, "w", newline="") as f:
        w = csv.writer(f)
        w.writerow(["NumIters", "loss"])
        w.writerows(train)
    cols: list[str] = []
    for row in test.values():
        for k in row:
            if k not in cols:
                cols.append(k)
    with open(test_path, "w", newline="") as f:
        w = csv.writer(f)
        w.writerow(["NumIters", "TestNet"] + cols)
        for (it, net_id), row in test.items():
            w.writerow([it, net_id] + [row.get(c, "") for c in cols])
    return train_path, test_path


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("logfile")
    ap.add_argument("out_dir", nargs="?", default=None)
    args = ap.parse_args(argv)
    train_path, test_path = write_csvs(args.logfile, args.out_dir)
    print(train_path)
    print(test_path)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
