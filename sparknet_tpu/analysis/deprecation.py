"""DP rules — deprecation hygiene.

The registry gives every knob a lifecycle: live -> ``deprecated``
(one release, DP001 warning) -> ``removed`` (tombstone, DP002 error).
Symbols follow the same arc through ``knobs.DEPRECATED_SYMBOLS``.
This is the rule that would have flagged the PR-12 LRN-cumsum /
fuse-pallas env shims the moment their window closed, instead of a
ROADMAP note owing their deletion.

  DP001  use of a knob inside its deprecation window (warning — fix
         before the window closes)
  DP002  mention of a removed knob outside the registry tombstone
  DP003  reference to a symbol past its deprecation window
"""

from __future__ import annotations

import ast

from .core import Finding, Project

_KNOBS_MODULE = "sparknet_tpu/utils/knobs.py"


def check(project: Project) -> list[Finding]:
    from sparknet_tpu.utils import knobs

    deprecated = {k.name: k.deprecated for k in knobs.all_knobs()
                  if k.deprecated and not k.removed}
    removed = {k.name: k.removed for k in knobs.all_knobs() if k.removed}
    dead_syms = dict(knobs.DEPRECATED_SYMBOLS)

    findings: list[Finding] = []
    for sf in project.files:
        if sf.rel == _KNOBS_MODULE:
            continue  # the tombstones themselves live here
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Constant) and \
                    isinstance(node.value, str):
                if node.value in removed:
                    f = project.finding(
                        sf, "DP002", "error", node.lineno,
                        f"{node.value} was removed ({removed[node.value]}) "
                        f"but is still mentioned here",
                        "delete the mention; the registry tombstone names "
                        "the replacement")
                    if f:
                        findings.append(f)
                elif node.value in deprecated:
                    f = project.finding(
                        sf, "DP001", "warning", node.lineno,
                        f"{node.value} is deprecated "
                        f"({deprecated[node.value]})",
                        "migrate before the one-release window closes")
                    if f:
                        findings.append(f)
            elif isinstance(node, (ast.Name, ast.Attribute)):
                name = node.id if isinstance(node, ast.Name) else node.attr
                if name in dead_syms:
                    f = project.finding(
                        sf, "DP003", "error", node.lineno,
                        f"{name} is past its deprecation window "
                        f"({dead_syms[name]})",
                        "delete the reference")
                    if f:
                        findings.append(f)
    return findings
