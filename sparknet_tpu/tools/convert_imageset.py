"""convert_imageset — image list file -> LMDB/LevelDB of Datum records
(reference: caffe/tools/convert_imageset.cpp).

Usage:
  python -m sparknet_tpu.tools.convert_imageset [flags] ROOTFOLDER LISTFILE DB_NAME

LISTFILE lines: "relative/path.jpg <label>".  Flags mirror the reference
tool: --backend lmdb|leveldb, --resize_height/--resize_width (force
resize), --shuffle, --gray, --encoded (store raw compressed bytes).
"""

from __future__ import annotations

import argparse
import os

import numpy as np


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("root")
    ap.add_argument("listfile")
    ap.add_argument("db_name")
    ap.add_argument("--backend", choices=["lmdb", "leveldb"], default="lmdb")
    ap.add_argument("--resize_height", type=int, default=0)
    ap.add_argument("--resize_width", type=int, default=0)
    ap.add_argument("--shuffle", action="store_true")
    ap.add_argument("--gray", action="store_true")
    ap.add_argument("--encoded", action="store_true",
                    help="store the raw compressed file bytes")
    args = ap.parse_args(argv)

    from ..data.db import array_to_datum, load_image, read_image_list

    entries = read_image_list(args.listfile, args.root)
    if args.shuffle:
        np.random.default_rng(0).shuffle(entries)

    def items():
        count = skipped = 0
        for i, (path, label) in enumerate(entries):
            key = b"%08d_%s" % (i, os.path.basename(path).encode())
            try:
                if args.encoded:
                    with open(path, "rb") as f:
                        datum = array_to_datum(None, label, encoded=f.read())
                else:
                    img = load_image(path, args.resize_height,
                                     args.resize_width, not args.gray)
                    datum = array_to_datum(img.astype(np.uint8), label)
            except Exception as e:  # undecodable -> skip, like the reference
                print(f"skip {path}: {e}")
                skipped += 1
                continue
            count += 1
            if count % 1000 == 0:
                print(f"processed {count} files")
            yield key, datum
        print(f"processed {count} files total ({skipped} skipped)")

    if args.backend == "lmdb":
        from ..data.lmdb_io import write_lmdb
        # materialize: the bulk writer sorts keys (already sorted here)
        write_lmdb(args.db_name, list(items()))
    else:
        from ..data.leveldb_io import write_leveldb
        write_leveldb(args.db_name, items())
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
