"""Fleet scheduler coverage: job-spec grammar, gang allocation, quota /
fair-share / aging arbitration, priority preemption, quarantine with
post-mortem, journal replay + scheduler-death recovery (reap survivors,
never double-launch), the launcher/runner hooks the fleet rides on
(``on_spawn``, ``cancel``), the injected ``preempt`` fault kind, and the
heartbeat ``extras`` telemetry channel.  The scheduler core is driven
through ``step()`` with fake runners for determinism; the end-to-end
paths use real subprocess stub jobs (no JAX) and one real driver job
(marker ``chaos``)."""

import json
import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from sparknet_tpu.parallel import health
from sparknet_tpu.parallel.fleet import (
    COMPLETED, PREEMPTING, QUARANTINED, QUEUED, RUNNING,
    ENV_JOB_TAG, FleetJournal, FleetScheduler, GangAllocator, JobSpec,
    _pid_is_fleet_job, format_status,
)
from sparknet_tpu.utils import faults

DRIVER = os.path.join(os.path.dirname(__file__), "multihost_driver.py")

pytestmark = pytest.mark.fleet


# ---------------------------------------------------------------------------
# JobSpec
# ---------------------------------------------------------------------------

def test_jobspec_json_roundtrip():
    spec = JobSpec(name="j1", tenant="acme", priority=3, world=8,
                   rounds=6, guard=True, fault="crash@round:2",
                   cmd=("prog", "--out", "{out}", "--ck", "{ckpt}"),
                   env={"K": "v"})
    again = JobSpec.from_json(json.loads(json.dumps(spec.to_json())))
    assert again == spec


@pytest.mark.parametrize("kw,msg", [
    (dict(name="bad name"), "bad job name"),
    (dict(name="j", world=0), "world"),
    (dict(name="j", rounds=0), "rounds"),
    (dict(name="j", cmd=("prog", "--x")), "{out}"),
    (dict(name="j", model="resnet50"), "no built-in driver"),
])
def test_jobspec_validation(kw, msg):
    with pytest.raises(ValueError, match=msg):
        JobSpec(**kw)


def test_jobspec_rejects_unknown_fields():
    with pytest.raises(ValueError, match="unknown JobSpec field"):
        JobSpec.from_json({"name": "j", "wrold": 4})


# ---------------------------------------------------------------------------
# gang allocation
# ---------------------------------------------------------------------------

def test_gang_allocator_all_or_nothing():
    a = GangAllocator(8)
    g1 = a.allocate(5)
    assert g1 is not None and len(g1) == 5 and a.free_count == 3
    assert a.allocate(4) is None          # would be partial: refused whole
    assert a.free_count == 3              # the refusal took nothing
    g2 = a.allocate(3)
    assert a.free_count == 0
    a.free(g1)
    assert a.free_count == 5
    assert a.allocate(5) is not None      # freed gang immediately reusable
    with pytest.raises(Exception):
        a.free(g2 + g2)                   # double free is loud


# ---------------------------------------------------------------------------
# scheduler core (fake runners, manual stepping)
# ---------------------------------------------------------------------------

class FakeRunner:
    """Stands in for ResilientRunner: blocks until released, then
    returns ``rc``.  ``behavior`` per job name:
      "complete"  — write the out artifact, rc 0
      "stop"      — rc 0 WITHOUT the artifact (checkpoint-and-stop)
      ("fail", n) — rc n
    """

    def __init__(self, job, behavior):
        self.job = job
        self.behavior = behavior
        self.release = threading.Event()
        self.canceled = False
        self.failure = None
        self.workdir = os.path.join(job.job_dir, "runner")

    def cancel(self):
        self.canceled = True
        self.release.set()

    def run(self):
        assert self.release.wait(timeout=30), "fake runner never released"
        b = self.behavior
        if b == "complete" and not self.canceled:
            with open(self.job.out_path, "w") as f:
                f.write("done")
            return 0
        if b == "stop" or self.canceled:
            return 0
        if isinstance(b, tuple) and b[0] == "fail":
            return b[1]
        return 0


class FakeFleet:
    """A FleetScheduler wired to FakeRunners, stepped manually."""

    def __init__(self, tmp_path, devices=8, **kw):
        self.behaviors = {}
        self.runners = {}

        def factory(job, cmd, env):
            r = FakeRunner(job, self.behaviors.get(job.name, "complete"))
            self.runners.setdefault(job.name, []).append(r)
            return r

        self.sched = FleetScheduler(str(tmp_path / "fleet"), devices,
                                    runner_factory=factory, **kw)

    def submit(self, behavior="complete", **kw):
        self.behaviors[kw["name"]] = behavior
        return self.sched.submit(JobSpec(**kw))

    def release(self, name):
        self.runners[name][-1].release.set()

    def settle(self, cond, timeout=10.0):
        """Step until ``cond()`` (supervisor threads are real, so results
        arrive asynchronously)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            self.sched.step()
            if cond():
                return
            time.sleep(0.01)
        raise AssertionError("condition never settled")


def test_gang_scheduling_and_quota(tmp_path):
    f = FakeFleet(tmp_path, devices=8, tenants={"acme": 4})
    a1 = f.submit(name="a1", tenant="acme", world=4)
    a2 = f.submit(name="a2", tenant="acme", world=4)
    b1 = f.submit(name="b1", tenant="beta", world=4)
    f.sched.step()
    # acme's quota (4) admits one of its jobs; beta fills the other gang
    assert a1.state == RUNNING and b1.state == RUNNING
    assert a2.state == QUEUED
    assert f.sched.allocator.free_count == 0
    f.release("a1")
    f.settle(lambda: a1.state == COMPLETED and a2.state == RUNNING)
    f.release("a2")
    f.release("b1")
    f.settle(lambda: f.sched.done())
    assert {j.state for j in f.sched.jobs.values()} == {COMPLETED}
    assert f.sched.allocator.free_count == 8


def test_fair_share_tiebreak_and_fifo(tmp_path):
    f = FakeFleet(tmp_path, devices=4, tenants={"acme": 8, "beta": 8})
    # acme already holds 4 slots; equal-priority queued jobs tie-break to
    # the tenant using the smaller share of its quota
    run = f.submit(name="hold", tenant="acme", world=4)
    f.sched.step()
    assert run.state == RUNNING
    qa = f.submit(name="qa", tenant="acme", world=4)
    qb = f.submit(name="qb", tenant="beta", world=4)
    ranked = sorted([qa, qb], key=f.sched._rank_key)
    assert ranked[0] is qb
    # same tenant, same priority: FIFO
    qa2 = f.submit(name="qa2", tenant="acme", world=4)
    assert sorted([qa2, qa], key=f.sched._rank_key)[0] is qa
    f.release("hold")
    # with hold done acme's usage is back to 0 — the tie resets to FIFO
    for name in ("qa", "qb", "qa2"):
        f.settle(lambda: f.sched.jobs[name].state == RUNNING)
        f.release(name)
    f.settle(lambda: f.sched.done())


def test_fair_share_decides_placement_under_contention(tmp_path):
    f = FakeFleet(tmp_path, devices=8, tenants={"acme": 8, "beta": 8})
    hold = f.submit(name="hold", tenant="acme", world=4)
    f.sched.step()
    assert hold.state == RUNNING
    qa = f.submit(name="qa", tenant="acme", world=4)
    qb = f.submit(name="qb", tenant="beta", world=4)
    f.sched.step()
    # one free gang, two equal-priority claimants: beta (0/8 of its
    # quota in use) beats acme (4/8 in use), despite acme's FIFO edge
    assert qb.state == RUNNING and qa.state == QUEUED
    f.release("hold")
    f.release("qb")
    f.settle(lambda: qa.state == RUNNING)
    f.release("qa")
    f.settle(lambda: f.sched.done())


def test_starvation_aging_reorders_but_never_preempts(tmp_path):
    now = [0.0]
    f = FakeFleet(tmp_path, devices=4, aging_rate=1.0,
                  clock=lambda: now[0])
    hi = f.submit(name="hi", priority=5, world=4)
    low = f.submit(name="low", priority=0, world=4)
    f.sched.step()
    assert hi.state == RUNNING and low.state == QUEUED
    # low starves for 100s: its EFFECTIVE priority dwarfs hi's, yet
    # preemption compares STATIC priorities only — aging reorders the
    # queue, it never evicts a runner
    now[0] = 100.0
    assert f.sched.effective_priority(low) == pytest.approx(100.0)
    f.sched.step()
    f.sched.step()
    assert hi.state == RUNNING and low.state == QUEUED
    # a fresh SAME-priority arrival is outranked by the starved job
    # (same static priority, so no preemption question arises)
    mid = f.submit(name="mid", priority=0, world=4)
    assert sorted([low, mid], key=f.sched._rank_key)[0] is low
    f.release("hi")
    f.settle(lambda: hi.state == COMPLETED and low.state == RUNNING)
    assert mid.state == QUEUED
    f.release("low")
    f.settle(lambda: mid.state == RUNNING)
    f.release("mid")
    f.settle(lambda: f.sched.done())


def test_priority_preemption_frees_the_gang(tmp_path):
    f = FakeFleet(tmp_path, devices=8, preempt_grace_s=30)
    v1 = f.submit(name="v1", priority=0, world=4, behavior="complete")
    v2 = f.submit(name="v2", priority=1, world=4, behavior="complete")
    f.sched.step()
    assert v1.state == RUNNING and v2.state == RUNNING
    urgent = f.submit(name="urgent", priority=50, world=8)
    f.sched.step()   # preemption decision: both victims evicted
    assert v1.state == PREEMPTING and v2.state == PREEMPTING
    assert f.runners["v1"][-1].canceled and f.runners["v2"][-1].canceled
    # canceled runners return rc 0 without the artifact -> requeued
    f.settle(lambda: v1.state == QUEUED and v2.state == QUEUED
             and urgent.state == RUNNING)
    assert v1.preempt_count == 1
    f.release("urgent")
    f.settle(lambda: urgent.state == COMPLETED
             and v1.state == RUNNING and v2.state == RUNNING)
    f.release("v1")
    f.release("v2")
    f.settle(lambda: f.sched.done())
    assert v1.state == COMPLETED and v2.state == COMPLETED


def test_no_preemption_without_strictly_higher_priority(tmp_path):
    f = FakeFleet(tmp_path, devices=4, preempt_grace_s=30)
    v = f.submit(name="v", priority=5, world=4)
    f.sched.step()
    assert v.state == RUNNING
    peer = f.submit(name="peer", priority=5, world=4)   # equal: must wait
    f.sched.step()
    f.sched.step()
    assert v.state == RUNNING and peer.state == QUEUED
    f.release("v")
    f.settle(lambda: v.state == COMPLETED and peer.state == RUNNING)
    f.release("peer")
    f.settle(lambda: f.sched.done())


def test_non_preemptible_jobs_are_never_evicted(tmp_path):
    f = FakeFleet(tmp_path, devices=4, preempt_grace_s=30)
    v = f.submit(name="pinned", priority=0, world=4, preemptible=False)
    f.sched.step()
    assert v.state == RUNNING
    urgent = f.submit(name="urgent", priority=99, world=4)
    f.sched.step()
    f.sched.step()
    assert v.state == RUNNING and urgent.state == QUEUED
    f.release("pinned")
    f.settle(lambda: v.state == COMPLETED and urgent.state == RUNNING)
    f.release("urgent")
    f.settle(lambda: f.sched.done())


def test_quarantine_writes_postmortem_and_reoffers_gang(tmp_path):
    f = FakeFleet(tmp_path, devices=4)
    bad = f.submit(name="bad", world=4, behavior=("fail", 7))
    good = f.submit(name="good", world=4)
    f.sched.step()
    assert bad.state == RUNNING and good.state == QUEUED
    f.release("bad")
    # the freed gang is re-offered to the queued job in the same pass
    f.settle(lambda: bad.state == QUARANTINED and good.state == RUNNING)
    post = json.load(open(os.path.join(bad.job_dir, "postmortem.json")))
    assert post["rc"] == 7 and post["job"] == "bad"
    # the telemetry flight-recorder tail rides along: the scheduling
    # decisions that led to the quarantine, embedded for the reader
    kinds = [e["kind"] for e in post["flight_recorder"]]
    assert "fleet_quarantine" in kinds and "fleet_launch" in kinds
    f.release("good")
    f.settle(lambda: f.sched.done())
    assert f.sched.run(tick_s=0.01) == 3   # quarantine -> nonzero fleet rc


def test_clean_stop_without_artifact_requeues_then_bounds(tmp_path):
    f = FakeFleet(tmp_path, devices=4, max_preempts=2)
    j = f.submit(name="stopper", world=4, behavior="stop")
    # each episode exits 0 without the artifact -> requeue; bounded by
    # max_preempts, then quarantined.  (QUEUED->RUNNING can flip inside
    # one step, so release each NEW runner as it appears.)
    released = set()
    deadline = time.monotonic() + 20
    while j.state != QUARANTINED and time.monotonic() < deadline:
        f.sched.step()
        runners = f.runners.get("stopper", [])
        if runners and runners[-1] not in released \
                and j.state == RUNNING:
            released.add(runners[-1])
            runners[-1].release.set()
        time.sleep(0.01)
    assert j.state == QUARANTINED
    assert j.preempt_count == 3        # 2 requeues allowed, 3rd is fatal
    post = json.load(open(os.path.join(j.job_dir, "postmortem.json")))
    assert "requeue loop" in post["reason"]


def test_duplicate_job_name_rejected(tmp_path):
    f = FakeFleet(tmp_path)
    f.submit(name="dup", world=1)
    with pytest.raises(Exception, match="duplicate"):
        f.submit(name="dup", world=1)


def test_status_and_format(tmp_path):
    f = FakeFleet(tmp_path, devices=8, tenants={"acme": 8})
    f.submit(name="s1", tenant="acme", world=4)
    f.sched.step()
    st = f.sched.status()
    assert st["devices"] == {"total": 8, "free": 4}
    assert st["tenants"]["acme"]["used"] == 4
    (row,) = st["jobs"]
    assert row["job"] == "s1" and row["state"] == RUNNING
    assert row["rounds_target"] == 4
    text = format_status(st)
    assert "s1" in text and "acme" in text and "RUNNING" in text
    f.release("s1")
    f.settle(lambda: f.sched.done())


# ---------------------------------------------------------------------------
# journal + scheduler-death recovery
# ---------------------------------------------------------------------------

def _stub_path(tmp_path):
    """A no-JAX training-job stand-in: counts rounds in a state file,
    SIGTERM checkpoints (the state file IS the checkpoint) and exits 0,
    completion writes the out artifact.  Resumes from the state file."""
    p = tmp_path / "stub.py"
    p.write_text(
        "import os, signal, sys, time\n"
        "state, rounds, tick, out = (sys.argv[1], int(sys.argv[2]),\n"
        "                            float(sys.argv[3]), sys.argv[4])\n"
        "stop = []\n"
        "signal.signal(signal.SIGTERM, lambda *a: stop.append(1))\n"
        "r = int(open(state).read()) if os.path.exists(state) else 0\n"
        "while r < rounds:\n"
        "    if stop:\n"
        "        sys.exit(0)\n"
        "    time.sleep(tick)\n"
        "    r += 1\n"
        "    with open(state, 'w') as f:\n"
        "        f.write(str(r))\n"
        "with open(out, 'w') as f:\n"
        "    f.write('done')\n")
    return str(p)


def _stub_spec(tmp_path, name, rounds=10, tick=0.02, **kw):
    return JobSpec(
        name=name, rounds=rounds,
        cmd=(sys.executable, _stub_path(tmp_path),
             "{ckpt}/state.txt", "{rounds}", str(tick), "{out}"),
        **kw)


def test_stub_fleet_completes_and_journal_replays(tmp_path):
    wd = str(tmp_path / "fleet")
    fleet = FleetScheduler(wd, 4, preempt_grace_s=5)
    fleet.submit(_stub_spec(tmp_path, "s1", world=2))
    fleet.submit(_stub_spec(tmp_path, "s2", world=2))
    assert fleet.run(tick_s=0.02, timeout_s=60) == 0
    events = [e["ev"] for e in
              FleetJournal.read(os.path.join(wd, "fleet_journal.jsonl"))]
    for ev in ("fleet", "submit", "launch", "pids", "exit", "complete",
               "done"):
        assert ev in events
    # resume of a finished fleet: everything stays COMPLETED and nothing
    # is ever launched again

    def exploding_factory(job, cmd, env):
        raise AssertionError(f"double launch of {job.name}!")

    again = FleetScheduler.resume(wd, runner_factory=exploding_factory)
    assert all(j.state == COMPLETED for j in again.jobs.values())
    assert again.run(tick_s=0.01) == 0


def test_resume_reaps_survivor_and_requeues(tmp_path):
    """Scheduler death with a live worker: the journal records the pid;
    resume must identify it (env tag through /proc), kill it, and requeue
    the job — which then resumes from its state file and completes."""
    wd = str(tmp_path / "fleet")
    spec = _stub_spec(tmp_path, "lone", rounds=40, tick=0.01, world=2)
    # fabricate the dead scheduler's journal: submitted, launched, pids.
    # The survivor itself runs a much longer round count, so it is still
    # alive when the resumed scheduler looks for it.
    sched = FleetScheduler(wd, 4)   # writes the fleet record
    job = sched.submit(spec)
    os.makedirs(job.ckpt_dir, exist_ok=True)
    proc = subprocess.Popen(
        [c.format(out=job.out_path, ckpt=job.ckpt_dir, world="2",
                  rounds="100000") for c in spec.cmd],
        env={**os.environ, ENV_JOB_TAG: "lone"})
    sched.journal.append("launch", job="lone", episode=1, slots=[0, 1])
    sched.journal.append("pids", job="lone", pids=[proc.pid])
    sched.journal.close()
    del sched
    time.sleep(0.3)
    assert proc.poll() is None and _pid_is_fleet_job(proc.pid, "lone")

    fleet = FleetScheduler.resume(wd)
    # the survivor was reaped before the job could be relaunched
    assert proc.wait(timeout=10) is not None
    job2 = fleet.jobs["lone"]
    assert job2.state == QUEUED
    # shrink the remaining work and let it finish from its checkpoint
    state = os.path.join(job2.ckpt_dir, "state.txt")
    resumed_from = int(open(state).read()) if os.path.exists(state) else 0
    assert fleet.run(tick_s=0.02, timeout_s=60) == 0
    assert job2.completed_ok()
    if resumed_from:
        # the second launch started from the survivor's checkpoint, not 0
        assert int(open(state).read()) >= resumed_from


def test_pid_identity_check_never_kills_strangers(tmp_path):
    # a live process WITHOUT our env tag is never "ours", whatever the
    # journal says — pid recycling must not let the fleet kill strangers
    stranger = subprocess.Popen([sys.executable, "-c",
                                 "import time; time.sleep(30)"])
    try:
        assert not _pid_is_fleet_job(stranger.pid, "anyjob")
        wd = str(tmp_path / "fleet")
        sched = FleetScheduler(wd, 4)
        sched.submit(_stub_spec(tmp_path, "ghost", rounds=1, world=1))
        sched.journal.append("pids", job="ghost", pids=[stranger.pid])
        sched.journal.close()
        fleet = FleetScheduler.resume(wd)
        assert stranger.poll() is None          # untouched
        assert fleet.run(tick_s=0.02, timeout_s=60) == 0
    finally:
        stranger.kill()


def test_stub_preempt_resume_e2e(tmp_path):
    """Fleet-level preemption against real processes: the victim's
    SIGTERM handler checkpoints (state file) and exits 0; the fleet
    requeues it; after the urgent job drains, the victim resumes FROM
    ITS CHECKPOINT and completes — no lost progress beyond the round in
    flight."""
    wd = str(tmp_path / "fleet")
    fleet = FleetScheduler(wd, 4, preempt_grace_s=5)
    victim = fleet.submit(_stub_spec(tmp_path, "victim", rounds=60,
                                     tick=0.03, world=4, priority=0))
    urgent = fleet.submit(_stub_spec(tmp_path, "urgent", rounds=5,
                                     tick=0.02, world=4, priority=50,
                                     not_before_s=0.4))
    assert fleet.run(tick_s=0.02, timeout_s=120) == 0
    assert victim.state == COMPLETED and urgent.state == COMPLETED
    assert victim.preempt_count >= 1
    assert int(open(os.path.join(victim.ckpt_dir,
                                 "state.txt")).read()) == 60
    assert fleet.live_worker_pids() == {}


# ---------------------------------------------------------------------------
# the hooks the fleet rides on
# ---------------------------------------------------------------------------

def test_launch_local_on_spawn_exposes_the_gang():
    from sparknet_tpu.tools.launch import launch_local
    seen = []
    rc = launch_local([sys.executable, "-c", "pass"], nprocs=2,
                      timeout=60, on_spawn=lambda procs: seen.append(procs))
    assert rc == 0
    assert len(seen) == 1 and len(seen[0]) == 2
    assert all(p.pid > 0 for p in seen[0])


def test_runner_cancel_stops_restarts(monkeypatch):
    from sparknet_tpu.parallel import resilience as R
    runner = R.ResilientRunner(["prog"], nprocs=2,
                               policy=R.RestartPolicy(max_restarts=5,
                                                      backoff_base=0.0))
    calls = []

    def fake_local(cmd, nprocs, **kw):
        calls.append(1)
        runner.cancel()       # cancel lands while the attempt is dying
        return 9

    monkeypatch.setattr(R, "launch_local", fake_local)
    assert runner.run() == 9
    assert len(calls) == 1            # no restart after the cancel
    assert runner.failure is None     # preempted, not failed


def test_runner_cancel_run_or_raise_is_typed(monkeypatch):
    from sparknet_tpu.parallel import resilience as R
    runner = R.ResilientRunner(["prog"], nprocs=2,
                               policy=R.RestartPolicy(max_restarts=5,
                                                      backoff_base=0.0))

    def fake_local(cmd, nprocs, **kw):
        runner.cancel()
        return 9

    monkeypatch.setattr(R, "launch_local", fake_local)
    with pytest.raises(R.ResilienceError, match="canceled"):
        runner.run_or_raise()


def test_preempt_fault_kind_fires_sigterm_once():
    spec = faults.parse_faults("preempt@round:2")[0]
    assert spec.kind == "preempt" and spec.round == 2
    kills = []
    inj = faults.FaultInjector((spec,), _kill=lambda pid, sig:
                               kills.append((pid, sig)))
    inj.on_round(0)
    inj.on_round(1)
    assert kills == []
    inj.on_round(2)
    assert kills == [(os.getpid(), signal.SIGTERM)]
    inj.on_round(2)            # once per process: the resumed replay
    assert len(kills) == 1     # must run clean
    with pytest.raises(ValueError, match="needs @round"):
        faults.parse_faults("preempt")


def test_heartbeat_extras_roundtrip(tmp_path):
    d = str(tmp_path / "hb")
    extras = {"stall_s": {"checkpoint": 0.12}, "feed": {"batches": 7}}
    health.write_beat(d, 3, 5, "round_end", extras=extras)
    beat = health.read_beat(d, 3)
    assert beat.extras == extras
    assert beat.round == 5
    # beats without extras (every pre-fleet writer) read back as None
    health.write_beat(d, 4, 5, "round_end")
    assert health.read_beat(d, 4).extras is None
    # the straggler monitor is oblivious to extras
    mon = health.StragglerMonitor(d, deadline_s=1e6)
    assert mon.check([3, 4]) == []


# ---------------------------------------------------------------------------
# real-driver end to end (one job preempted by fault, one clean)
# ---------------------------------------------------------------------------

def _clean_launch_env():
    saved = dict(os.environ)
    os.environ.pop("XLA_FLAGS", None)
    for k in list(os.environ):
        if k.startswith("SPARKNET_"):
            os.environ.pop(k)
    return saved


@pytest.mark.chaos
def test_driver_fleet_preempt_resume_bit_identical(tmp_path):
    """THE fleet acceptance path in miniature: a driver job that
    self-preempts at round 1 (SIGTERM -> snapshot -> clean exit ->
    fleet requeue -> resume) must finish with params bit-identical to
    an unpreempted run of the same config."""
    from sparknet_tpu.tools.launch import launch_local
    saved = _clean_launch_env()
    try:
        base = str(tmp_path / "base.npz")
        rc = launch_local(
            [sys.executable, DRIVER, "--strategy", "sync", "--out", base,
             "--local-devices", "4", "--rounds", "4"],
            nprocs=1, platform="cpu", timeout=300)
        assert rc == 0
        fleet = FleetScheduler(str(tmp_path / "fleet"), 4,
                               preempt_grace_s=20)
        job = fleet.submit(JobSpec(name="pre", world=4, rounds=4,
                                   fault="preempt@round:1"))
        assert fleet.run(tick_s=0.05, timeout_s=240) == 0
    finally:
        os.environ.clear()
        os.environ.update(saved)
    assert job.state == COMPLETED and job.preempt_count >= 1
    a, b = np.load(base), np.load(job.out_path)
    for k in a.files:
        if k.startswith("__"):
            continue
        assert np.array_equal(a[k], b[k]), f"param {k} diverged"
    assert fleet.live_worker_pids() == {}


def test_oversized_gang_rejected_at_submit(tmp_path):
    f = FakeFleet(tmp_path, devices=4)
    with pytest.raises(Exception, match="never be placed"):
        f.submit(name="huge", world=8)
