"""Wall-clock timing utilities.

The analog of Caffe's ``Timer``/``CPUTimer`` (reference:
caffe/src/caffe/util/benchmark.cpp:26-145, CUDA events) and the app-level
phase logging (reference: src/main/scala/apps/CifarApp.scala:41-50 elapsed
seconds per phase).  Device timing uses ``block_until_ready`` fences instead
of CUDA events.
"""

from __future__ import annotations

import time
from typing import Any

import jax


class Timer:
    def __init__(self) -> None:
        self._start = 0.0
        self._elapsed = 0.0
        self._running = False

    def start(self) -> "Timer":
        self._start = time.perf_counter()
        self._running = True
        return self

    def stop(self, fence: Any = None) -> float:
        """Stop; optionally fence on a jax value first so device work is
        included (the CUDA-event analog)."""
        if fence is not None:
            jax.block_until_ready(fence)
        if self._running:
            self._elapsed += time.perf_counter() - self._start
            self._running = False
        return self._elapsed

    def seconds(self) -> float:
        if self._running:
            return self._elapsed + (time.perf_counter() - self._start)
        return self._elapsed

    def milli_seconds(self) -> float:
        return self.seconds() * 1e3

    def reset(self) -> None:
        self._elapsed = 0.0
        self._running = False


class PhaseLogger:
    """Append-only phase log with elapsed seconds — the
    ``training_log_<ts>.txt`` analog (reference: CifarApp.scala:41-50)."""

    def __init__(self, path: str | None = None):
        self.path = path
        self.t0 = time.time()

    def log(self, msg: str) -> None:
        line = f"{time.time() - self.t0:10.3f}s  {msg}"
        print(line)
        if self.path:
            with open(self.path, "a") as f:
                f.write(line + "\n")
