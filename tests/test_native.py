"""Native data-pipeline tests: C++ path vs numpy fallback equivalence —
the CallbackBenchmarkSpec territory (reference:
src/test/scala/apps/CallbackBenchmarkSpec.scala measured the JNA feed path
this module replaces)."""

import io

import numpy as np
import pytest

from sparknet_tpu import native


def test_builds():
    assert native.available(), "native pipeline failed to build"


def test_decode_cifar_matches_numpy(np_rng):
    recs = np_rng.integers(0, 256, size=(5, 3073)).astype(np.uint8)
    images, labels = native.decode_cifar(recs)
    np.testing.assert_array_equal(labels, recs[:, 0].astype(np.int32))
    np.testing.assert_array_equal(
        images, recs[:, 1:].reshape(5, 3, 32, 32).astype(np.float32))


def test_crop_batch_matches_numpy(np_rng):
    batch = np_rng.normal(size=(6, 3, 12, 12)).astype(np.float32)
    ys = np_rng.integers(0, 5, size=6)
    xs = np_rng.integers(0, 5, size=6)
    flips = np_rng.integers(0, 2, size=6)
    mean = np_rng.normal(size=(3, 8, 8)).astype(np.float32)
    out = native.crop_batch(batch, 8, ys, xs, flips, mean)
    for i in range(6):
        ref = batch[i, :, ys[i]:ys[i] + 8, xs[i]:xs[i] + 8]
        if flips[i]:
            ref = ref[:, :, ::-1]
        np.testing.assert_allclose(out[i], ref - mean, rtol=1e-6)


def test_crop_batch_scalar_mean(np_rng):
    batch = np.ones((2, 1, 4, 4), np.float32) * 10
    out = native.crop_batch(batch, 2, np.zeros(2, np.int32),
                            np.zeros(2, np.int32), np.zeros(2, np.int32),
                            mean=3.0)
    np.testing.assert_allclose(out, np.full((2, 1, 2, 2), 7.0))


def test_crop_batch_out_of_bounds(np_rng):
    batch = np.zeros((1, 1, 4, 4), np.float32)
    with pytest.raises(RuntimeError):
        native.crop_batch(batch, 3, np.array([2], np.int32),
                          np.array([0], np.int32), np.array([0], np.int32))


def test_accumulate_mean(np_rng):
    imgs = np_rng.normal(size=(10, 3, 4, 4)).astype(np.float32)
    acc = np.zeros((3, 4, 4), np.float64)
    native.accumulate_mean(imgs, acc)
    np.testing.assert_allclose(acc, imgs.sum(axis=0), rtol=1e-5)


def _jpeg_bytes(arr: np.ndarray) -> bytes:
    from PIL import Image
    buf = io.BytesIO()
    Image.fromarray(arr).save(buf, format="JPEG", quality=95)
    return buf.getvalue()


def test_decode_jpeg_resize(np_rng):
    src = np.zeros((40, 60, 3), np.uint8)
    src[:, :30] = [255, 0, 0]
    src[:, 30:] = [0, 0, 255]
    out = native.decode_jpeg_resize(_jpeg_bytes(src), 20, 20)
    assert out is not None and out.shape == (3, 20, 20)
    # left half red-ish, right half blue-ish
    assert out[0, :, :8].mean() > 180 and out[2, :, :8].mean() < 80
    assert out[2, :, 12:].mean() > 180 and out[0, :, 12:].mean() < 80


def test_decode_jpeg_garbage_returns_none():
    assert native.decode_jpeg_resize(b"not a jpeg at all", 8, 8) is None
    assert native.decode_jpeg_resize(b"\xff\xd8\xff\xe0truncated", 8, 8) is None


def test_parse_datum_batch_matches_python():
    """Native batched Datum parse == per-record Python decode (u8 and
    float_data payloads), with clean fallback on mismatched shapes."""
    import numpy as np

    from sparknet_tpu import native
    from sparknet_tpu.data.db import array_to_datum, datum_to_array

    if not native.available():
        import pytest
        pytest.skip("no native toolchain")
    rng = np.random.default_rng(0)
    imgs = rng.integers(0, 256, size=(6, 3, 5, 4)).astype(np.uint8)
    labels = rng.integers(0, 9, size=6)
    recs = [array_to_datum(imgs[i], int(labels[i])) for i in range(6)]
    out, labs = native.parse_datum_batch(recs, 3, 5, 4)
    for i, r in enumerate(recs):
        ref_img, ref_lab = datum_to_array(r)
        np.testing.assert_array_equal(out[i], ref_img)
        assert labs[i] == ref_lab

    f = rng.normal(size=(2, 1, 2, 2)).astype(np.float32)
    frecs = [array_to_datum(f[i], i) for i in range(2)]
    fout, _ = native.parse_datum_batch(frecs, 1, 2, 2)
    np.testing.assert_allclose(fout, f, rtol=1e-6)

    assert native.parse_datum_batch(recs, 3, 9, 9) is None  # shape mismatch
