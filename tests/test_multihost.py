"""Two-process jax.distributed exercise on the CPU rig — real multi-host
coverage the reference never had (its only multi-worker exercise was the
live Spark apps; SURVEY.md §4.1).  Two coordinated processes × 2 virtual
CPU devices each form a 4-device global mesh; each process feeds only its
rows of the batch; the result must equal a single-process 4-device run of
the identical workload."""

import os
import subprocess
import sys

import numpy as np
import pytest

DRIVER = os.path.join(os.path.dirname(__file__), "multihost_driver.py")
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _clean_env():
    env = dict(os.environ)
    # the conftest's 8-device flags must not leak into subprocesses
    env.pop("XLA_FLAGS", None)
    for k in list(env):
        if k.startswith("SPARKNET_"):
            env.pop(k)
    env["JAX_PLATFORMS"] = "cpu"
    env["JAX_PLATFORM_NAME"] = "cpu"
    return env


def _run_single(out, strategy):
    subprocess.run(
        [sys.executable, DRIVER, "--strategy", strategy, "--out", out,
         "--local-devices", "4"],
        check=True, env=_clean_env(), cwd=REPO, timeout=420,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT)


@pytest.mark.parametrize("strategy", ["sync", "local_sgd", "hierarchical"])
def test_two_process_matches_single_process(tmp_path, strategy,
                                            multiprocess_cpu):
    """For "hierarchical" the two REAL processes are the two hosts of the
    2x2 pod mesh — per-step chip psum stays process-local, the tau-boundary
    weight average crosses the process boundary (the DCN tier), and the
    result must equal the single-process 2x2 virtual pod."""
    if not multiprocess_cpu:
        pytest.skip("CPU backend lacks multiprocess XLA computations")
    from sparknet_tpu.tools.launch import launch_local

    single = str(tmp_path / f"single_{strategy}.npz")
    multi = str(tmp_path / f"multi_{strategy}.npz")
    _run_single(single, strategy)

    # two coordinated processes via the launcher (spark-submit analog)
    old_env = dict(os.environ)
    os.environ.pop("XLA_FLAGS", None)
    try:
        rc = launch_local(
            [sys.executable, DRIVER, "--strategy", strategy, "--out", multi],
            nprocs=2, platform="cpu", devices_per_proc=2, timeout=420)
    finally:
        os.environ.clear()
        os.environ.update(old_env)
    assert rc == 0, f"distributed run failed rc={rc}"
    assert os.path.exists(multi), "process 0 wrote no output"

    a = np.load(single)
    b = np.load(multi)
    assert set(a.files) == set(b.files)
    np.testing.assert_allclose(a["__losses__"], b["__losses__"],
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(a["__scores__"], b["__scores__"],
                               rtol=1e-5, atol=1e-5)
    for k in a.files:
        if k.startswith("__"):
            continue
        np.testing.assert_allclose(a[k], b[k], rtol=1e-5, atol=1e-6,
                                   err_msg=f"param {k} diverged")


def test_four_process_matches_single_process(tmp_path, multiprocess_cpu):
    """4 processes × 2 devices = 8-device global mesh; must equal one
    process with 8 virtual devices bit-close (deeper than the 2×2
    minimum shape — VERDICT r2 weak #3)."""
    if not multiprocess_cpu:
        pytest.skip("CPU backend lacks multiprocess XLA computations")
    from sparknet_tpu.tools.launch import launch_local

    single = str(tmp_path / "single8.npz")
    multi = str(tmp_path / "multi8.npz")
    subprocess.run(
        [sys.executable, DRIVER, "--strategy", "sync", "--out", single,
         "--local-devices", "8", "--expect-devices", "8"],
        check=True, env=_clean_env(), cwd=REPO, timeout=420,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT)

    old_env = dict(os.environ)
    os.environ.pop("XLA_FLAGS", None)
    try:
        rc = launch_local(
            [sys.executable, DRIVER, "--strategy", "sync", "--out", multi,
             "--expect-devices", "8"],
            nprocs=4, platform="cpu", devices_per_proc=2, timeout=420)
    finally:
        os.environ.clear()
        os.environ.update(old_env)
    assert rc == 0, f"4-process run failed rc={rc}"
    a, b = np.load(single), np.load(multi)
    np.testing.assert_allclose(a["__losses__"], b["__losses__"],
                               rtol=1e-5, atol=1e-6)
    for k in a.files:
        if not k.startswith("__"):
            np.testing.assert_allclose(a[k], b[k], rtol=1e-5, atol=1e-6,
                                       err_msg=f"param {k} diverged")


def test_worker_death_is_reported_not_hung(tmp_path):
    """Failure path: one rank dies mid-job; the launcher must return a
    nonzero code within its timeout instead of hanging the job forever
    (the spark.task.maxFailures=1 fail-fast contract,
    CifarApp.scala:36)."""
    import time

    from sparknet_tpu.tools.launch import launch_local

    out = str(tmp_path / "doomed.npz")
    old_env = dict(os.environ)
    os.environ.pop("XLA_FLAGS", None)
    t0 = time.monotonic()
    try:
        rc = launch_local(
            [sys.executable, DRIVER, "--strategy", "sync", "--out", out,
             "--fail-rank", "1"],
            nprocs=2, platform="cpu", devices_per_proc=2, timeout=150)
    finally:
        os.environ.clear()
        os.environ.update(old_env)
    assert rc != 0, "worker death must surface as a failed job"
    assert time.monotonic() - t0 < 400, "launcher hung past its timeout"


def test_ssh_wire_contract_single_host(tmp_path):
    """The ssh wire itself, ungated: one host over the shim needs no
    multiprocess XLA, so THIS leg pins the remote command construction
    (BatchMode, cwd, env contract) on every tier-1 rig — including the
    ones where the 2-host mesh test below must skip."""
    from sparknet_tpu.tools.launch import free_port, launch_ssh

    shim_dir = tmp_path / "bin"
    shim_dir.mkdir()
    log = tmp_path / "ssh.log"
    shim = shim_dir / "ssh"
    shim.write_text(
        "#!/bin/bash\n"
        f"echo \"ARGS:$*\" >> {log}\n"
        "exec bash -c \"$4\"\n")
    shim.chmod(0o755)

    single = str(tmp_path / "single.npz")
    wired = str(tmp_path / "wired.npz")
    _run_single(single, "sync")

    old_env = dict(os.environ)
    os.environ.pop("XLA_FLAGS", None)
    for k in list(os.environ):
        if k.startswith("SPARKNET_"):
            os.environ.pop(k)
    os.environ["SPARKNET_SSH_CMD"] = str(shim)
    try:
        rc = launch_ssh(
            [sys.executable, DRIVER, "--strategy", "sync", "--out", wired,
             "--local-devices", "4"],
            hosts=["127.0.0.1"], coordinator_port=free_port(),
            cwd=REPO, timeout=420)
    finally:
        os.environ.clear()
        os.environ.update(old_env)
    assert rc == 0, f"ssh-shim single-host run failed rc={rc}"

    args = [l for l in log.read_text().strip().splitlines()
            if l.startswith("ARGS:")]
    assert len(args) == 1
    a = args[0]
    assert "-o BatchMode=yes" in a and "127.0.0.1" in a
    assert f"cd {REPO}" in a
    assert "SPARKNET_COORDINATOR=" in a
    assert "SPARKNET_NUM_PROCS='1'" in a and "SPARKNET_PROC_ID='0'" in a

    a, b = np.load(single), np.load(wired)
    np.testing.assert_allclose(a["__losses__"], b["__losses__"],
                               rtol=1e-5, atol=1e-6)
    for k in a.files:
        if not k.startswith("__"):
            np.testing.assert_allclose(a[k], b[k], rtol=1e-5, atol=1e-6)


def test_ssh_mode_via_shim(tmp_path, multiprocess_cpu):
    """Exercise launch_ssh end-to-end against a local `ssh` shim: the shim
    logs the wire command (host, BatchMode, env contract) and executes the
    remote string locally, so two fake 'hosts' form a real 2-process
    jax.distributed mesh.  This pins the ssh tier's command construction
    and env contract without an sshd (the pod itself stays
    live-system-untested, as documented in README)."""
    if not multiprocess_cpu:
        pytest.skip("CPU backend lacks multiprocess XLA computations")
    from sparknet_tpu.tools.launch import free_port, launch_ssh

    shim_dir = tmp_path / "bin"
    shim_dir.mkdir()
    log = tmp_path / "ssh.log"
    shim = shim_dir / "ssh"
    shim.write_text(
        "#!/bin/bash\n"
        f"echo \"ARGS:$*\" >> {log}\n"
        "# ssh -o BatchMode=yes <host> <remote>\n"
        "exec bash -c \"$4\"\n")
    shim.chmod(0o755)

    single = str(tmp_path / "single.npz")
    multi = str(tmp_path / "multi.npz")
    _run_single(single, "sync")

    old_env = dict(os.environ)
    os.environ.pop("XLA_FLAGS", None)
    for k in list(os.environ):
        if k.startswith("SPARKNET_"):
            os.environ.pop(k)
    # the fake-ssh knob: forces the ssh wire format even for localhost
    # addresses (otherwise the local transport would spawn directly)
    os.environ["SPARKNET_SSH_CMD"] = str(shim)
    try:
        rc = launch_ssh(
            [sys.executable, DRIVER, "--strategy", "sync", "--out", multi,
             "--local-devices", "2"],
            hosts=["127.0.0.1", "localhost"],
            coordinator_port=free_port(), cwd=REPO, timeout=420)
    finally:
        os.environ.clear()
        os.environ.update(old_env)
    assert rc == 0, f"ssh-shim run failed rc={rc}"

    # wire-command contract
    lines = log.read_text().strip().splitlines()
    args = [l for l in lines if l.startswith("ARGS:")]
    assert len(args) == 2
    assert any("127.0.0.1" in a for a in args)
    assert any("localhost" in a for a in args)
    for a in args:
        assert "-o BatchMode=yes" in a
        assert f"cd {REPO}" in a
        assert "SPARKNET_COORDINATOR=" in a
        assert "SPARKNET_NUM_PROCS='2'" in a
    assert any("SPARKNET_PROC_ID='0'" in a for a in args)
    assert any("SPARKNET_PROC_ID='1'" in a for a in args)

    # numerics equal the single-process run, like the local-mode test
    a, b = np.load(single), np.load(multi)
    np.testing.assert_allclose(a["__losses__"], b["__losses__"],
                               rtol=1e-5, atol=1e-6)
    for k in a.files:
        if not k.startswith("__"):
            np.testing.assert_allclose(a[k], b[k], rtol=1e-5, atol=1e-6)
