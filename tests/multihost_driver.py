"""Driver run by the two-process jax.distributed test (and reusable by
hand): trains a fixed lenet workload over the global mesh and dumps final
params.  Each process feeds only ITS rows of the deterministic global batch
(the per-host partition placement of ImageNetApp.scala:145).

Resilience rig: ``--ckpt-dir`` turns on round-granular checkpointing
(params + per-worker solver state + round counter + RNG, manifest with
checksum), and a relaunched driver auto-resumes from the newest valid
manifest.  Every round start passes through the fault-injection hook
(``SPARKNET_FAULT=crash@round:N@rank:R`` etc., utils/faults.py), so the
chaos tests can kill a rank deterministically and assert the restarted
job converges to the fault-free result.  Per-round data is derived from
the ROUND INDEX alone (not a running RNG stream), so a resumed round
refeeds exactly the batch the killed round would have seen.

Elastic rig: ``--elastic`` lets the trainer resume a checkpoint written
by a DIFFERENT worker count (the re-formed survivor set), ``--guard``
arms the numerical-integrity guard (NaN/Inf → rollback), and the round
loop is driven by ``tr.round`` so a guard rollback naturally replays the
dropped round.  Heartbeats are published whenever the launcher sets
SPARKNET_HEARTBEAT_DIR.  SIGTERM/SIGINT trigger one final round
checkpoint before a clean exit (preemption contract, utils/signals.py).

Invoked by sparknet_tpu.tools.launch (env contract) or standalone
single-process with --local-devices N.
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def round_batch(r, tau, global_batch):
    """Deterministic per-round lenet batch, a pure function of the round
    index — the property that makes round-granular resume exact."""
    import numpy as np
    rng = np.random.default_rng(1000 + r)
    y = rng.integers(0, 10, size=(tau, global_batch))
    x = rng.normal(scale=0.3, size=(tau, global_batch, 1, 28, 28)
                   ).astype(np.float32)
    for t in range(tau):
        for i, k in enumerate(y[t]):
            x[t, i, :, int(k) % 28, :] += 2.0
    return x, y


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--strategy", default="sync")
    ap.add_argument("--out", required=True)
    ap.add_argument("--local-devices", type=int, default=None,
                    help="single-process mode: virtual CPU device count")
    ap.add_argument("--expect-devices", type=int, default=4,
                    help="global device count the mesh must have "
                         "(0 = don't check — elastic worlds vary)")
    ap.add_argument("--rounds", type=int, default=2)
    ap.add_argument("--global-batch", type=int, default=16)
    ap.add_argument("--ckpt-dir", default=None,
                    help="round-granular checkpoint/auto-resume directory")
    ap.add_argument("--ckpt-every", type=int, default=1)
    ap.add_argument("--elastic", action="store_true",
                    help="allow resuming a checkpoint from a different "
                         "worker count (degraded-mode re-form)")
    ap.add_argument("--guard", action="store_true",
                    help="arm the numerical-integrity guard (needs "
                         "--ckpt-dir)")
    ap.add_argument("--audit-every", type=int, default=0,
                    help="cross-replica parameter audit cadence in rounds "
                         "(0 = off; needs --ckpt-dir)")
    ap.add_argument("--harvest-lag", type=int, default=0,
                    help="zero-stall outer loop: keep up to K rounds in "
                         "flight, harvesting loss/guard/audit verdicts "
                         "up to K rounds late (0 = synchronous)")
    ap.add_argument("--fail-rank", type=int, default=None,
                    help="failure-path mode: this rank dies (exit 3) after "
                         "the first round")
    args = ap.parse_args()

    if args.local_devices:
        flags = os.environ.get("XLA_FLAGS", "")
        os.environ["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count="
            f"{args.local_devices}").strip()
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ.setdefault("JAX_PLATFORM_NAME", "cpu")

    import jax
    jax.config.update("jax_platforms", "cpu")

    import numpy as np

    from sparknet_tpu.models import lenet
    from sparknet_tpu.parallel import DistributedTrainer, TrainerConfig, make_mesh
    from sparknet_tpu.parallel.cluster import (
        init_cluster_from_env, local_batch_slice,
    )
    from sparknet_tpu.proto import load_solver_prototxt_with_net
    from sparknet_tpu.utils import faults
    from sparknet_tpu.utils.signals import SolverAction, preemption_guard

    distributed = init_cluster_from_env()
    if args.strategy == "hierarchical":
        # host axis = real processes when distributed (so the weight
        # averaging crosses the process boundary like DCN would); a
        # single-process run folds the same 2-host topology virtually
        from sparknet_tpu.parallel import make_pod_mesh
        n_hosts = jax.process_count() if jax.process_count() > 1 else 2
        mesh = make_pod_mesh(n_hosts)
        n_devices = mesh.shape["host"] * mesh.shape["chip"]
    else:
        mesh = make_mesh()
        n_devices = mesh.shape["data"]
    if args.expect_devices:
        assert n_devices == args.expect_devices, (
            f"expected {args.expect_devices} global devices, got {n_devices}")

    GLOBAL_BATCH, TAU = args.global_batch, 2
    sp = load_solver_prototxt_with_net(
        'base_lr: 0.05\nmomentum: 0.9\nlr_policy: "fixed"\n',
        lenet(GLOBAL_BATCH, GLOBAL_BATCH))
    tr = DistributedTrainer(
        sp, mesh,
        TrainerConfig(strategy=args.strategy, tau=TAU,
                      checkpoint_dir=args.ckpt_dir,
                      checkpoint_every=args.ckpt_every,
                      elastic=args.elastic,
                      guard_numerics=args.guard,
                      audit_every=args.audit_every,
                      harvest_lag=args.harvest_lag),
        seed=0)
    rows = local_batch_slice(GLOBAL_BATCH)
    injector = faults.get_injector()
    rank = jax.process_index()
    if tr.resumed:
        print(f"driver: resumed at round {tr.round} (attempt "
              f"{injector.attempt})", flush=True)

    losses = []
    preempted = False
    with preemption_guard() as guard:
        # driven by tr.round, not a range(): a guard rollback rewinds
        # tr.round and the loop replays the dropped round.  The OUTER
        # loop covers the pipelined case: a deferred verdict can trip
        # during drain() — after the inner loop already exited — which
        # rewinds tr.round again, and the dropped rounds must replay.
        while True:
            while tr.round < args.rounds:
                action = guard.check()
                if action in (SolverAction.SNAPSHOT,
                              SolverAction.SNAPSHOT_STOP):
                    if args.ckpt_dir:
                        print(f"driver: signal checkpoint at round "
                              f"{tr.round}", flush=True)
                        tr.drain()   # settle in-flight rounds first
                        tr.save_round_checkpoint()
                        tr.flush_checkpoints()   # durable BEFORE the exit
                if action in (SolverAction.STOP,
                              SolverAction.SNAPSHOT_STOP):
                    print(f"driver: preempted; stopped cleanly at round "
                          f"boundary {tr.round}", flush=True)
                    preempted = True
                    break
                r = tr.round
                injector.on_round(r, rank=rank)
                x, y = round_batch(r, TAU, GLOBAL_BATCH)
                loss = tr.train_round(
                    {"data": x[:, rows],
                     "label": y[:, rows].astype(np.float32)})
                losses.append(loss)
                print(f"driver: round {r} done loss={loss:.4f}",
                      flush=True)
                if r == 0 and args.fail_rank is not None \
                        and jax.process_index() == args.fail_rank:
                    print(f"driver: rank {args.fail_rank} dying "
                          f"(failure-path test)", flush=True)
                    os._exit(3)
            if preempted:
                break
            # settle every in-flight verdict + async checkpoint write; a
            # trip here rewinds tr.round and the outer loop replays
            tr.drain()
            if tr.round >= args.rounds:
                break

    if preempted:
        return  # clean exit: the relaunch resumes from the checkpoint

    # pipelined mode: exact per-round losses live in tr.round_losses
    if args.harvest_lag:
        losses = [tr.round_losses[r] for r in range(args.rounds)]

    erng = np.random.default_rng(2000)
    eval_y = erng.integers(0, 10, size=(GLOBAL_BATCH,))
    eval_x = erng.normal(scale=0.3, size=(GLOBAL_BATCH, 1, 28, 28)
                         ).astype(np.float32)
    feed = iter([{"data": eval_x[rows],
                  "label": eval_y[rows].astype(np.float32)}] * 2)
    scores = tr.test(feed, num_steps=2)

    if jax.process_index() == 0:
        flat = {}
        for lname, blobs in tr.params.items():
            for i, b in enumerate(blobs):
                flat[f"{lname}/{i}"] = np.asarray(b)
        flat["__losses__"] = np.asarray(losses)
        flat["__guard_trips__"] = np.asarray(tr.guard_trips)
        flat["__audit_trips__"] = np.asarray(tr.audit_trips)
        flat["__scores__"] = np.asarray(
            [scores.get("loss", 0.0), scores.get("accuracy", 0.0)])
        np.savez(args.out, **flat)
        print(f"driver ok: distributed={distributed} "
              f"procs={jax.process_count()} losses={losses}")


if __name__ == "__main__":
    sys.exit(main())
