"""Device mesh construction and sharding helpers.

The communication tier of the framework.  The reference has two transport
stacks — Spark TCP broadcast/reduce between nodes (reference:
src/main/scala/apps/ImageNetApp.scala:102,178) and a CUDA P2P tree within a
node (reference: caffe/src/caffe/parallel.cpp:271-360) — and no
NCCL/MPI/Gloo anywhere (SURVEY.md §2.5).  Here both collapse into XLA
collectives over a ``jax.sharding.Mesh``: ``psum``/``pmean`` ride ICI within
a slice and DCN across slices, chosen by the compiler from the mesh
topology.  Multi-host extends the same mesh via the JAX distributed runtime
(``sparknet_tpu.parallel.cluster.init_cluster``).
"""

from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

DATA_AXIS = "data"
MODEL_AXIS = "model"
HOST_AXIS = "host"   # the slow tier: DCN / cross-host (τ-averaging)
CHIP_AXIS = "chip"   # the fast tier: ICI within a host (per-step psum)


def make_mesh(n_devices: int | None = None, *, model_parallel: int = 1,
              devices=None) -> Mesh:
    """A (data, model) mesh over the available devices.

    ``model_parallel=1`` (the parity default — the reference has no model
    parallelism, SURVEY.md §2.4) yields a pure data-parallel mesh; larger
    values carve an inner model axis for tensor-parallel shardings laid out
    on adjacent devices so its collectives ride the fastest ICI links.
    """
    devs = list(devices if devices is not None else jax.devices())
    if n_devices is not None:
        devs = devs[:n_devices]
    n = len(devs)
    if n % model_parallel:
        raise ValueError(f"{n} devices not divisible by mp={model_parallel}")
    arr = np.asarray(devs).reshape(n // model_parallel, model_parallel)
    return Mesh(arr, (DATA_AXIS, MODEL_AXIS))


def make_pod_mesh(n_hosts: int | None = None, chips_per_host: int | None = None,
                  *, devices=None) -> Mesh:
    """A (host, chip) mesh — the deployment topology SparkNet's two DP
    tiers compose onto: per-step gradient psum over the ``chip`` axis
    (ICI within a host — the reference's intra-node P2PSync,
    caffe/src/caffe/parallel.cpp:271-360) × τ-step weight averaging over
    the ``host`` axis (DCN across hosts — the reference's Spark
    driver rounds, ImageNetApp.scala:100-182).  Device order follows
    ``jax.devices()``, which groups each process's local devices
    contiguously — so on a real multi-host pod rows of the mesh ARE
    hosts and the chip-axis collectives ride ICI."""
    devs = list(devices if devices is not None else jax.devices())
    if n_hosts is None:
        n_hosts = max(jax.process_count(), 1)
    if n_hosts < 1:
        raise ValueError(f"pod mesh needs n_hosts >= 1, got {n_hosts}")
    if chips_per_host is None:
        chips_per_host = len(devs) // n_hosts
    if chips_per_host < 1:
        raise ValueError(
            f"pod mesh needs chips_per_host >= 1, got {chips_per_host}")
    need = n_hosts * chips_per_host
    if need > len(devs):
        raise ValueError(
            f"pod mesh {n_hosts}x{chips_per_host} needs {need} devices, "
            f"have {len(devs)}")
    arr = np.asarray(devs[:need]).reshape(n_hosts, chips_per_host)
    return Mesh(arr, (HOST_AXIS, CHIP_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())


def batch_sharded(mesh: Mesh, ndim: int = 1) -> NamedSharding:
    """Shard the leading (batch) axis across the data axis."""
    return NamedSharding(mesh, PartitionSpec(DATA_AXIS, *([None] * (ndim - 1))))


def put_global(value, sharding: NamedSharding) -> jax.Array:
    """Build a global array from a host value every process holds in full
    (weights, solver state).  Works on single-host meshes AND multi-host
    meshes with non-addressable devices — the replacement for the
    reference's ship-the-model-by-classloader replication (reference:
    CifarApp.scala:23-29; SURVEY.md §7.3 'per-host model replication must
    be explicit')."""
    value = np.asarray(value)
    return jax.make_array_from_callback(
        value.shape, sharding, lambda idx: value[idx])


def put_global_tree(tree, sharding):
    """Place a host pytree on the mesh.  ``sharding`` is either a single
    NamedSharding applied to every leaf (the replicated classic) or a
    matching pytree of NamedShardings — the hybrid-sharding path, where
    each parameter leaf carries its own placement from the partition
    rule table (``parallel/partition.py``)."""
    if isinstance(sharding, jax.sharding.Sharding):
        return jax.tree_util.tree_map(lambda x: put_global(x, sharding), tree)
    return jax.tree_util.tree_map(put_global, tree, sharding)


def stage_local(local_value, sharding: NamedSharding) -> jax.Array:
    """Assemble a global array from *per-process* local rows — the data
    path: each host contributes only its own partition slice of the batch
    (the zipPartitions placement of the reference, ImageNetApp.scala:145),
    and no host ever materializes the global batch."""
    if jax.process_count() == 1:
        return jax.device_put(local_value, sharding)
    return jax.make_array_from_process_local_data(
        sharding, np.asarray(local_value))
