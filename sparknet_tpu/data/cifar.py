"""CIFAR-10 binary-format IO.

Reads the standard CIFAR-10 binary batches — one record is a label byte
followed by 3072 CHW pixel bytes — as the reference's loader does
(reference: src/main/scala/loaders/CifarLoader.scala:65 readBatch; train-set
shuffle via random permutation at :34; mean image at :57-63).  A writer is
provided so tests can fabricate format-exact fixtures without network access
(the reference fetches real data via caffe/data/cifar10/get_cifar10.sh).
"""

from __future__ import annotations

import os

import numpy as np

CIFAR_SHAPE = (3, 32, 32)
_REC = 1 + 3 * 32 * 32


def load_cifar10_binary(paths: list[str] | str, shuffle: bool = False,
                        seed: int = 0) -> tuple[np.ndarray, np.ndarray]:
    """Read batch file(s) -> (images [N,3,32,32] float32 in [0,255],
    labels [N] int32)."""
    if isinstance(paths, str):
        paths = [paths]
    if not paths:
        raise FileNotFoundError("no CIFAR batch files given")
    for p in paths:
        if not os.path.exists(p):
            raise FileNotFoundError(f"CIFAR batch file not found: {p}")
    from .. import native
    images, labels = [], []
    for path in paths:
        raw = np.fromfile(path, dtype=np.uint8)
        if raw.size % _REC:
            raise ValueError(f"{path}: size {raw.size} not a multiple of {_REC}")
        imgs, labs = native.decode_cifar(raw.reshape(-1, _REC))
        labels.append(labs)
        images.append(imgs)
    x = np.concatenate(images)
    y = np.concatenate(labels)
    if shuffle:
        perm = np.random.default_rng(seed).permutation(len(x))
        x, y = x[perm], y[perm]
    return x, y


def write_cifar10_binary(path: str, images: np.ndarray,
                         labels: np.ndarray) -> None:
    """Write records in the binary batch format (test-fixture generator)."""
    n = len(labels)
    out = np.empty((n, _REC), np.uint8)
    out[:, 0] = np.asarray(labels, np.uint8)
    out[:, 1:] = np.asarray(images, np.uint8).reshape(n, -1)
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    out.tofile(path)
