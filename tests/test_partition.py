"""Hybrid model+data sharding (PR 20): the regex partition rule table
(``parallel/partition.py``), the trainer's tensor-sharded round —
bit parity against the replicated baseline for every strategy, codec
composition, the shard-aware audit with bitflip rollback — per-shard
checkpoint tiles, and the knob plumbing."""

import json
import os

import jax
import numpy as np
import pytest

from sparknet_tpu.models import lenet
from sparknet_tpu.parallel import (
    DistributedTrainer, TrainerConfig, comms, make_mesh, make_pod_mesh,
    partition,
)
from sparknet_tpu.parallel.trainer import comm_config_from_env
from sparknet_tpu.proto import load_solver_prototxt_with_net

SOLVER_TXT = 'base_lr: 0.005\nmomentum: 0.9\nlr_policy: "fixed"\n'


def _sp(batch=16):
    return load_solver_prototxt_with_net(SOLVER_TXT, lenet(batch, batch))


def _batch(r, tau=2, gb=16):
    rng = np.random.default_rng(900 + r)
    return {"data": rng.normal(size=(tau, gb, 1, 28, 28)
                               ).astype(np.float32),
            "label": rng.integers(0, 10, size=(tau, gb)
                                  ).astype(np.float32)}


def _run(tr, rounds=2, tau=2, gb=16):
    losses = [tr.train_round(_batch(r, tau, gb)) for r in range(rounds)]
    tr.drain()
    jax.block_until_ready(tr.params)
    return losses


def _params_np(tr):
    return {k: [np.asarray(b) for b in v] for k, v in tr.params.items()}


def _assert_bit_identical(pa, pb, msg=""):
    for name in pa:
        for i, x in enumerate(pa[name]):
            np.testing.assert_array_equal(
                x, pb[name][i], err_msg=f"{msg} param {name}[{i}]")


# ---------------------------------------------------------------------------
# rule grammar
# ---------------------------------------------------------------------------

def _leaves(**shapes):
    """{name: [leaf, ...]} WeightCollection stand-in from name->shapes."""
    return {name: [np.zeros(s, np.float32) for s in blobs]
            for name, blobs in shapes.items()}


def test_first_match_wins():
    rules = ((r"(^|/)ip1/0$", 0), (r"(^|/)ip", 1), (r".*", None))
    dims, fallbacks, unmatched = partition.match_partition_rules(
        rules, _leaves(ip1=[(8, 4), (8,)], ip2=[(4, 8), (4,)]), 2)
    # ip1/0 hits rule 0 (dim 0); ip1/1 and ip2/* fall through to rule 1
    # (dim 1 — ip2/0 has one, the biases do not and fall back)
    assert dims == {"ip1/0": 0, "ip2/0": 1}
    assert set(fallbacks) == {"ip1/1", "ip2/1"}
    assert unmatched == []


def test_scalar_leaves_never_partitioned():
    dims, fallbacks, _ = partition.match_partition_rules(
        ((r".*", 0),), {"bn1": [np.float32(1.0) * np.zeros(())]}, 2)
    assert dims == {} and fallbacks == ["bn1/0"]


def test_non_divisible_dim_falls_back():
    dims, fallbacks, _ = partition.match_partition_rules(
        partition.DEFAULT_RULES, _leaves(ip1=[(10, 4)]), 4)
    assert dims == {} and fallbacks == ["ip1/0"]


def test_unmatched_leaves_collected_all_at_once():
    # a table with no catch-all leaves every non-matching leaf undecided
    dims, fb, unmatched = partition.match_partition_rules(
        ((r"(^|/)ip1/0$", 0),),
        _leaves(conv1=[(4, 1, 5, 5), (4,)], ip1=[(8, 4)]), 2)
    assert dims == {"ip1/0": 0} and fb == []
    assert unmatched == ["conv1/0", "conv1/1"]


def test_resolve_plan_modes(tmp_path):
    leaves = _leaves(ip1=[(8, 4), (8,)], conv1=[(4, 1, 5, 5)])
    for mode in ("", "off", "dp", "0"):
        assert partition.resolve_plan(mode, leaves, axis="data",
                                      n_shards=4) is None
    # single shard -> None even under "auto"
    assert partition.resolve_plan("auto", leaves, axis="data",
                                  n_shards=1) is None
    plan = partition.resolve_plan("auto", leaves, axis="data", n_shards=4)
    assert plan is not None and plan.dims_dict() == {"ip1/0": 0}
    assert plan.table_id == f"auto-v{partition.RULE_TABLE_VERSION}"
    # a holey custom table raises, naming every undecided leaf
    holey = tmp_path / "holey.json"
    holey.write_text(json.dumps(
        {"version": 1, "rules": [{"pattern": r"(^|/)ip1/0$", "dim": 0}]}))
    with pytest.raises(ValueError, match="conv1/0"):
        partition.resolve_plan(str(holey), leaves, axis="data", n_shards=4)


def test_rule_table_version_refused(tmp_path):
    p = tmp_path / "t.json"
    p.write_text(json.dumps({"version": 2, "rules": [
        {"pattern": ".*", "dim": None}]}))
    with pytest.raises(ValueError, match="version 2"):
        partition.load_rule_table(str(p))
    p.write_text(json.dumps({"version": 1, "rules": [
        {"pattern": "(unclosed", "dim": None}]}))
    with pytest.raises(Exception):   # bad regex surfaces at load
        partition.load_rule_table(str(p))


def test_json_table_load_and_plan_id_stability(tmp_path):
    p = tmp_path / "t.json"
    p.write_text(json.dumps({"version": 1, "rules": [
        {"pattern": r"(^|/)ip[^/]*/0$", "dim": 0},
        {"pattern": ".*", "dim": None}]}))
    leaves = _leaves(ip1=[(8, 4)], conv1=[(4, 1, 5, 5)])
    a = partition.resolve_plan(str(p), leaves, axis="data", n_shards=4)
    b = partition.resolve_plan(str(p), leaves, axis="data", n_shards=4)
    assert a.table_id.startswith("table:")
    assert a.plan_id() == b.plan_id()           # content-hash stability
    assert partition.shard_plan_id(a) == a.plan_id()
    assert partition.shard_plan_id(None) == "dp"
    # a different shard count is a different placement -> different id
    c = partition.resolve_plan(str(p), leaves, axis="data", n_shards=2)
    assert c.plan_id() != a.plan_id()


def test_boundary_bytes_shrink_accounting():
    leaves = _leaves(ip1=[(8, 4), (8,)], conv1=[(4, 1, 5, 5)])
    plan = partition.resolve_plan("auto", leaves, axis="data", n_shards=4)
    full = partition.boundary_bytes_per_chip(leaves, None)
    shard = partition.boundary_bytes_per_chip(leaves, plan)
    # only ip1/0 (8*4*4 = 128 B) shrinks, to a quarter
    assert full - shard == 128 - 128 // 4
    # the codec-wire accounting agrees on the same plan
    none = comms.get_codec("none")
    assert (comms.sharded_exchange_bytes(none, leaves, 4, plan)
            < comms.exchange_bytes(none, leaves, 4))
    assert (comms.sharded_exchange_bytes(none, leaves, 4, None)
            == comms.exchange_bytes(none, leaves, 4))


# ---------------------------------------------------------------------------
# the tensor-sharded round: bit parity with the replicated baseline
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("strategy", ["local_sgd", "sync"])
def test_sharded_round_bit_identical_flat_mesh(strategy):
    mesh = make_mesh(4)
    dp = DistributedTrainer(_sp(), mesh,
                            TrainerConfig(strategy=strategy, tau=2,
                                          shard="off"), seed=0)
    sh = DistributedTrainer(_sp(), mesh,
                            TrainerConfig(strategy=strategy, tau=2,
                                          shard="auto"), seed=0)
    assert sh.shard_plan is not None
    assert sh.shard_plan.dims_dict() == {"ip1/0": 0}
    assert "ip2/0" in sh.shard_plan.fallbacks   # 10 rows % 4 != 0
    la, lb = _run(dp), _run(sh)
    assert la == lb
    _assert_bit_identical(_params_np(dp), _params_np(sh),
                          f"{strategy} sharded")


def test_sharded_round_bit_identical_hierarchical():
    pod = make_pod_mesh(2, 2)
    dp = DistributedTrainer(_sp(), pod,
                            TrainerConfig(strategy="hierarchical", tau=2,
                                          shard="off"), seed=0)
    sh = DistributedTrainer(_sp(), pod,
                            TrainerConfig(strategy="hierarchical", tau=2,
                                          shard="auto"), seed=0)
    assert sh.shard_plan is not None and sh.shard_plan.axis == "chip"
    la, lb = _run(dp), _run(sh)
    assert la == lb
    _assert_bit_identical(_params_np(dp), _params_np(sh), "hierarchical")


def test_sharded_compose_with_int8_codec_bit_identical():
    mesh = make_mesh(4)
    dp = DistributedTrainer(_sp(), mesh,
                            TrainerConfig(strategy="local_sgd", tau=2,
                                          comm_codec="int8", shard="off"),
                            seed=0)
    sh = DistributedTrainer(_sp(), mesh,
                            TrainerConfig(strategy="local_sgd", tau=2,
                                          comm_codec="int8",
                                          shard="auto"), seed=0)
    la, lb = _run(dp), _run(sh)
    assert la == lb
    _assert_bit_identical(_params_np(dp), _params_np(sh), "int8+shard")


def test_sharded_eval_matches_replicated():
    mesh = make_mesh(4)
    dp = DistributedTrainer(_sp(), mesh,
                            TrainerConfig(tau=2, shard="off"), seed=0)
    sh = DistributedTrainer(_sp(), mesh,
                            TrainerConfig(tau=2, shard="auto"), seed=0)
    _run(dp, rounds=1)
    _run(sh, rounds=1)
    fa = iter([{"data": _batch(9)["data"][0],
                "label": _batch(9)["label"][0]}] * 2)
    fb = iter([{"data": _batch(9)["data"][0],
                "label": _batch(9)["label"][0]}] * 2)
    sa, sb = dp.test(fa, num_steps=2), sh.test(fb, num_steps=2)
    assert sa == sb


# ---------------------------------------------------------------------------
# sharded safety plane: audit, rollback, per-shard checkpoints
# ---------------------------------------------------------------------------

def test_audit_under_sharding_catches_bitflip_and_rolls_back(tmp_path):
    cfg = TrainerConfig(strategy="local_sgd", tau=2, shard="auto",
                        checkpoint_dir=str(tmp_path), checkpoint_every=1,
                        audit_every=1)
    tr = DistributedTrainer(_sp(), make_mesh(4), cfg, seed=0)
    for r in range(2):
        tr.train_round(_batch(r))
    fps = tr.audit_params()
    assert np.asarray(fps).shape == (4, 2)    # [replicated, shard] columns
    assert tr._audit_ok(fps)
    tr._inject_bitflip(2)
    fps2 = tr.audit_params()
    assert tr._audit_culprits(fps2) == [2]
    # the next round's pre-round audit trips and rolls back
    assert np.isnan(tr.train_round(_batch(2)))
    assert tr.audit_trips == 1
    assert tr._audit_ok(tr.audit_params())
    assert np.isfinite(tr.train_round(_batch(2)))   # replay succeeds


def test_per_shard_checkpoint_roundtrip(tmp_path):
    cfg = TrainerConfig(strategy="local_sgd", tau=2, shard="auto",
                        shard_checkpoint=True,
                        checkpoint_dir=str(tmp_path), checkpoint_every=1)
    tr = DistributedTrainer(_sp(), make_mesh(4), cfg, seed=0)
    for r in range(2):
        tr.train_round(_batch(r))
    tr.flush_checkpoints()
    tiles = sorted(p.name for p in tmp_path.glob(
        "ckpt_round_00000002.shard*.npz"))
    assert len(tiles) == 4, tiles
    manifest = json.loads(
        (tmp_path / "manifest_00000002.json").read_text())
    assert manifest["shard_plan"] == tr.shard_plan_id
    assert set(manifest["shard_dims"]) == {"ip1/0"}
    assert len(manifest["shards"]) == 4
    # fresh trainer reassembles the tiles bit-exactly and continues
    tr2 = DistributedTrainer(_sp(), make_mesh(4), cfg, seed=99)
    assert tr2.resumed is not None
    _assert_bit_identical(_params_np(tr), _params_np(tr2), "resume")
    la = tr.train_round(_batch(2))
    lb = tr2.train_round(_batch(2))
    assert la == lb


def test_shard_checkpoint_corrupt_tile_is_skipped(tmp_path):
    cfg = TrainerConfig(strategy="local_sgd", tau=2, shard="auto",
                        shard_checkpoint=True,
                        checkpoint_dir=str(tmp_path), checkpoint_every=1)
    tr = DistributedTrainer(_sp(), make_mesh(4), cfg, seed=0)
    for r in range(2):
        tr.train_round(_batch(r))
    tr.flush_checkpoints()
    # rot one tile of the NEWEST checkpoint: resume must fall back to
    # the previous intact one, not assemble a corrupt params tree
    tile = tmp_path / "ckpt_round_00000002.shard01.npz"
    tile.write_bytes(b"rotten" + tile.read_bytes()[6:])
    tr2 = DistributedTrainer(_sp(), make_mesh(4), cfg, seed=99)
    assert tr2.resumed is not None
    assert tr2.round == 1


# ---------------------------------------------------------------------------
# knob plumbing + manifest/ledger stamps
# ---------------------------------------------------------------------------

def test_comm_config_from_env_shard_knobs(monkeypatch):
    base = TrainerConfig()
    assert base.shard == "off" and base.shard_checkpoint is False
    cfg = comm_config_from_env(base)
    assert cfg.shard == "off"            # unset knobs leave base alone
    monkeypatch.setenv("SPARKNET_SHARD", "auto")
    monkeypatch.setenv("SPARKNET_SHARD_CKPT", "1")
    cfg = comm_config_from_env(base)
    assert cfg.shard == "auto" and cfg.shard_checkpoint is True


def test_trainer_stamps_plan_id():
    tr = DistributedTrainer(_sp(), make_mesh(4),
                            TrainerConfig(shard="auto"), seed=0)
    assert tr.shard_plan_id.startswith("shard:")
    blob = tr._host_blob()
    assert blob["shard_plan"] == tr.shard_plan_id
    dp = DistributedTrainer(_sp(), make_mesh(4),
                            TrainerConfig(shard="off"), seed=0)
    assert dp.shard_plan_id == "dp"
    assert "shard_plan" not in dp._host_blob()


def test_perfledger_sharding_fingerprint_field():
    from sparknet_tpu.utils import perfledger
    fp = perfledger.fingerprint(model="lenet", dtype="f32", batch=16,
                                world=4)
    assert fp["sharding"] == "dp"        # historical default keeps gating
    fp2 = perfledger.fingerprint(model="lenet", dtype="f32", batch=16,
                                 world=4, sharding="shard:abc")
    assert fp2["sharding"] == "shard:abc"
    assert perfledger.fp_key(fp) != perfledger.fp_key(fp2)
