"""Drive: SPARKNET_PALLAS_MAXPOOL=1 inside a real Solver train loop."""
import os
os.environ["SPARKNET_PALLAS_MAXPOOL"] = "1"
import jax; jax.config.update("jax_platforms", "cpu")
import numpy as np
from sparknet_tpu.proto import load_net_prototxt, load_solver_prototxt_with_net
from sparknet_tpu.solvers import Solver

NET = """
name: "poolnet"
layer { name: "data" type: "Input" top: "data"
  input_param { shape { dim: 8 dim: 3 dim: 28 dim: 28 } } }
layer { name: "label" type: "Input" top: "label"
  input_param { shape { dim: 8 } } }
layer { name: "conv1" type: "Convolution" bottom: "data" top: "conv1"
  convolution_param { num_output: 8 kernel_size: 3 pad: 1
    weight_filler { type: "xavier" } } }
layer { name: "relu1" type: "ReLU" bottom: "conv1" top: "conv1" }
layer { name: "icp_pool" type: "Pooling" bottom: "conv1" top: "icp"
  pooling_param { pool: MAX kernel_size: 3 stride: 1 pad: 1 } }
layer { name: "pool2" type: "Pooling" bottom: "icp" top: "p2"
  pooling_param { pool: MAX kernel_size: 3 stride: 2 } }
layer { name: "ip" type: "InnerProduct" bottom: "p2" top: "ip"
  inner_product_param { num_output: 5 weight_filler { type: "xavier" } } }
layer { name: "loss" type: "SoftmaxWithLoss" bottom: "ip" bottom: "label" }
"""
sp = load_solver_prototxt_with_net(
    'base_lr: 0.05\nmomentum: 0.9\n', load_net_prototxt(NET))
s = Solver(sp, seed=0)
rng = np.random.default_rng(0)
x = rng.normal(size=(8, 3, 28, 28)).astype(np.float32)
y = rng.integers(0, 5, size=(8,)).astype(np.float32)
s.set_train_data(iter([{"data": x, "label": y}] * 30))
l0 = s.step(5); l1 = s.step(25)
assert np.isfinite(l1) and l1 < l0, (l0, l1)
# same trajectory as the select-and-scatter path
os.environ["SPARKNET_PALLAS_MAXPOOL"] = "0"
s2 = Solver(sp, seed=0)
s2.set_train_data(iter([{"data": x, "label": y}] * 30))
s2.step(5); l1b = s2.step(25)
assert abs(l1 - l1b) < 1e-4, (l1, l1b)
print(f"pallas maxpool drive OK: loss {l0:.4f} -> {l1:.4f} (matches s&s path {l1b:.4f})")
