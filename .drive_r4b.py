import jax; jax.config.update("jax_platforms", "cpu")
import numpy as np
from sparknet_tpu.proto import load_net_prototxt, load_solver_prototxt_with_net, replace_data_layers
from sparknet_tpu.solvers import Solver
netp = replace_data_layers(load_net_prototxt(open(
    "/root/reference/caffe/examples/cifar10/cifar10_full_sigmoid_train_test_bn.prototxt").read()),
    8, 8, 3, 32, 32)
sp = load_solver_prototxt_with_net(
    'base_lr: 0.001\nmomentum: 0.9\nlr_policy: "multistep"\ngamma: 0.1\n'
    'stepvalue: 5\nstepvalue: 10\n', netp)
s = Solver(sp, seed=0)
rng = np.random.default_rng(0)
feed = ({"data": rng.normal(size=(8, 3, 32, 32)).astype(np.float32),
         "label": rng.integers(0, 10, size=(8,)).astype(np.float32)} for _ in iter(int, 1))
s.set_train_data(feed)
l0 = s.step(15)
assert np.isfinite(l0)
scale = float(np.asarray(s.params["bn1"][2])[0])
assert abs(scale - sum(0.999**k for k in range(15))) < 1e-3, scale
out = s.test_net.apply_all(s.params, {"data": rng.normal(size=(8,3,32,32)).astype(np.float32),
                                      "label": np.zeros(8, np.float32)}, train=False)
assert np.isfinite(np.asarray(out["ip1"])).all()
print("BN solver drive OK: loss", round(l0, 4), "scale_factor", round(scale, 4))
