"""Drive: an unmodified pycaffe-style script front-to-back — mode calls,
seed, MemoryData binding, batched scoring via forward_all."""
import jax; jax.config.update("jax_platforms", "cpu")
import numpy as np
from sparknet_tpu import pycaffe_compat
pycaffe_compat.install()
import caffe

caffe.set_mode_gpu()          # line 1 of every pycaffe script
caffe.set_device(0)
caffe.set_random_seed(42)
print("layer types:", len(caffe.layer_type_list()))

NET = """
name: "mem"
layer { name: "data" type: "MemoryData" top: "data" top: "label"
  memory_data_param { batch_size: 4 channels: 1 height: 5 width: 5 } }
layer { name: "ip" type: "InnerProduct" bottom: "data" top: "ip"
  inner_product_param { num_output: 3 weight_filler { type: "xavier" } } }
layer { name: "prob" type: "Softmax" bottom: "ip" top: "prob" }
"""
net = caffe.Net(NET, phase=caffe.TEST)
rng = np.random.default_rng(0)
data = rng.normal(size=(8, 1, 5, 5)).astype(np.float32)
net.set_input_arrays(data, np.zeros(8, np.float32))
p1 = net.forward()["prob"]
p2 = net.forward()["prob"]
assert p1.shape == (4, 3) and not np.array_equal(p1, p2)

# batched scoring over an Input-declared deploy net
DEPLOY = """
name: "deploy"
input: "data"
input_shape { dim: 4 dim: 1 dim: 5 dim: 5 }
layer { name: "ip" type: "InnerProduct" bottom: "data" top: "ip"
  inner_product_param { num_output: 3 weight_filler { type: "xavier" } } }
layer { name: "prob" type: "Softmax" bottom: "ip" top: "prob" }
"""
dep = caffe.Net(DEPLOY, phase=caffe.TEST)
outs = dep.forward_all(data=rng.normal(size=(11, 1, 5, 5)).astype(np.float32))
assert outs["prob"].shape == (11, 3)
assert dep.blob_loss_weights["prob"] == 0.0
caffe._random_seed = None
print("pycaffe-script drive OK:", outs["prob"].sum(1)[:3].round(3))
