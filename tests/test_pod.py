"""Pod-scale fleet coverage (marker ``pod``): host inventory grammar,
cross-host all-or-nothing gang placement with serve anti-affinity,
host-granular failure attribution in the ResilientRunner (one host
death burns ONE restart-budget unit, not one per rank), whole-host
rejoin with the two-strike guard, the scheduler's host lifecycle
(draining → SNAPSHOT_STOP → requeue off-host; lost → kill → requeue
onto survivors), the cross-process host-control channel, the status
views' hosts section, and scheduler-death journal resume on a pod
(cross-host pid verification through the /proc identity check).

The scheduler core is driven through ``step()`` with fake runners for
determinism (same harness as test_fleet); the resume path uses a real
subprocess stub; the full burn-in episode is exercised end to end by
``tools/soak.py --pod`` (the SPARKNET_PODSOAK tier-1 gate) and by the
``slow``-marked test at the bottom."""

import json
import os
import subprocess
import sys
import threading
import time

import pytest

from sparknet_tpu.parallel.fleet import (
    COMPLETED, QUEUED, RUNNING,
    HOST_DRAINING, HOST_LIVE, HOST_LOST,
    ENV_JOB_TAG, FleetError, FleetScheduler, GangAllocator, HostPool,
    JobSpec, _pid_is_fleet_job, format_status,
    offline_status, request_mark_host,
)
from sparknet_tpu.parallel.resilience import (
    ElasticPolicy, ResilientRunner, RestartPolicy,
)

pytestmark = pytest.mark.pod

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# host inventory
# ---------------------------------------------------------------------------

def test_hostpool_inline_grammar_and_roundtrips(tmp_path):
    pool = HostPool.parse("a=4, b=2@10.0.0.7 ,c=1")
    assert len(pool) == 3 and pool.total_devices == 7
    assert pool.spec("a").addr == "local"
    assert pool.spec("b").addr == "10.0.0.7"
    assert "c" in pool and "z" not in pool
    # JSON round trip preserves order, budgets and addresses
    again = HostPool.from_json(json.loads(json.dumps(pool.to_json())))
    assert [(h.name, h.devices, h.addr) for h in again.specs()] == \
           [(h.name, h.devices, h.addr) for h in pool.specs()]
    # from_spec: a path to a JSON file, else the inline form
    p = tmp_path / "hosts.json"
    p.write_text(json.dumps(pool.to_json()))
    assert HostPool.from_spec(str(p)).total_devices == 7
    assert HostPool.from_spec("a=4,b=2@10.0.0.7,c=1").total_devices == 7


@pytest.mark.parametrize("text", [
    "",                    # empty inventory
    "a=0",                 # devices must be >= 1
    "a=four",              # not an int
    "a4",                  # missing name=devices
    "a=2,a=2",             # duplicate host
    "bad name=2",          # whitespace in a host name
])
def test_hostpool_rejects_bad_inventory(text):
    with pytest.raises(ValueError):
        HostPool.parse(text)


def test_hostpool_liveness_marks():
    pool = HostPool.parse("a=2,b=2")
    assert pool.placeable("a") and pool.lost() == []
    pool.mark("a", HOST_DRAINING)
    assert not pool.placeable("a") and pool.lost() == []
    pool.mark("a", HOST_LOST)
    assert pool.lost() == ["a"]
    pool.mark("a", HOST_LIVE)
    assert pool.placeable("a")
    with pytest.raises(FleetError, match="bad host state"):
        pool.mark("a", "zombie")
    with pytest.raises(FleetError, match="unknown host"):
        pool.mark("nope", HOST_LOST)


# ---------------------------------------------------------------------------
# cross-host gang placement
# ---------------------------------------------------------------------------

def test_pool_allocator_all_or_nothing_across_hosts():
    pool = HostPool.parse("a=4,b=4,c=4")
    al = GangAllocator(pool=pool)
    g = al.allocate(6)                       # must span two hosts
    assert g is not None and len(g) == 6
    assert len(set(al.hosts_of(g))) == 2
    # 6 slots remain across b+c: a 7-gang is refused WHOLE, nothing is
    # taken; a 5-gang spans the surviving hosts
    assert al.allocate(7) is None and al.free_count == 6
    g2 = al.allocate(5)
    assert g2 is not None and len(set(al.hosts_of(g2))) == 2
    al.free(g)
    al.free(g2)
    assert al.free_count == 12
    assert al.allocate(12) is not None       # the whole pod is one gang


def test_pool_allocator_skips_unplaceable_hosts():
    pool = HostPool.parse("a=4,b=4")
    al = GangAllocator(pool=pool)
    pool.mark("a", HOST_LOST)
    g = al.allocate(4)
    assert al.hosts_of(g) == ("b",)          # only the live host offers
    assert al.allocate(1) is None            # b is full, a is dead
    pool.mark("a", HOST_LIVE)
    assert al.hosts_of(al.allocate(1)) == ("a",)
    pool.mark("b", HOST_DRAINING)            # draining = stop placing,
    al.free(g)                               # but its slots free cleanly
    assert al.allocate(4) is None            # 3 left on a, b fenced off


def test_serve_anti_affinity_spreads_then_falls_back():
    pool = HostPool.parse("h0=4,h1=4,h2=4")
    al = GangAllocator(pool=pool)
    # two trainings pack the emptiest hosts first
    t0, t1 = al.allocate(3), al.allocate(3)
    assert al.hosts_of(t0) == ("h0",) and al.hosts_of(t1) == ("h1",)
    # replica 0 lands on the emptiest host; replica 1 avoids it, so one
    # host loss can never take every replica of the model at once
    r0 = al.allocate(1)
    assert al.hosts_of(r0) == ("h2",)
    r1 = al.allocate(1, avoid=al.hosts_of(r0))
    assert al.hosts_of(r1) != ("h2",)
    # SOFT anti-affinity: when only avoided hosts have room, the gang
    # still lands (capacity beats spread)
    r2 = al.allocate(4, avoid=("h0", "h1", "h2"))
    assert r2 is not None and len(r2) == 4


# ---------------------------------------------------------------------------
# host-granular attribution in the ResilientRunner
# ---------------------------------------------------------------------------

def _scripted_runner(monkeypatch, script, **kw):
    """A ResilientRunner whose launches are scripted: each entry is
    ``(rc, first_failure_rank_or_None)``."""
    it = iter(script)

    def fake_launch(self, attempt, report):
        rc, ff = next(it)
        if ff is not None:
            report["first_failure"] = ff
        return rc

    monkeypatch.setattr(ResilientRunner, "_launch_once", fake_launch)
    kw.setdefault("policy", RestartPolicy(max_restarts=3,
                                          backoff_base=0.01, jitter=0.0))
    return ResilientRunner(["job"], sleep=lambda s: None, **kw)


def test_host_death_burns_one_budget_unit(monkeypatch):
    """Both ranks of host 'a' die with the machine; the probe confirms it
    on the FIRST failed attempt — one re-form, one budget strike, zero
    wasted re-dials of the dead host."""
    r = _scripted_runner(
        monkeypatch, [(-9, 0), (0, None)],
        nprocs=4, host_map=["a", "a", "b", "c"],
        host_down_probe=lambda h: h == "a",
        elastic=ElasticPolicy(enabled=True, min_workers=1))
    assert r.run() == 0
    assert r.dropped_hosts == ["a"]
    assert r._drop_counts["a"] == 1          # ONE strike for 2 ranks
    assert r.nprocs == 2 and r.host_map == ["b", "c"]
    assert len(r.attempts) == 2              # no budget burned re-dialing
    assert r.incarnation == 1                # exactly one re-form


def test_host_attribution_heuristic_needs_two_distinct_ranks(monkeypatch):
    """Without a probe, one failing rank is a rank problem (normal
    restart); two DIFFERENT first deaths on one multi-rank host are a
    host problem (re-form)."""
    r = _scripted_runner(
        monkeypatch, [(-9, 0), (-9, 1), (0, None)],
        nprocs=4, host_map=["a", "a", "b", "c"],
        elastic=ElasticPolicy(enabled=True, min_workers=1))
    assert r.run() == 0
    # attempt 1 (rank 0 only) restarted in place; attempt 2 (rank 1,
    # same host) flipped the verdict to host-down
    assert [a.world for a in r.attempts] == [4, 4, 2]
    assert r.dropped_hosts == ["a"] and r.nprocs == 2
    assert r._drop_counts["a"] == 1 and r.incarnation == 1


def test_recovered_host_rejoins_whole(monkeypatch):
    """A dropped host rejoins with ALL its ranks in one membership
    change at the next relaunch boundary."""
    r = _scripted_runner(
        monkeypatch, [(-9, 0), (0, None)],
        nprocs=4, host_map=["a", "a", "b", "c"],
        host_down_probe=lambda h: h == "a",
        rejoin_probe=lambda slot: True,      # recovered by next launch
        elastic=ElasticPolicy(enabled=True, min_workers=1))
    assert r.run() == 0
    assert r.dropped_hosts == []             # readmitted
    assert r.nprocs == 4
    assert sorted(r.host_map) == ["a", "a", "b", "c"]


def test_twice_failed_host_is_out_for_good(monkeypatch):
    """Two strikes: a host that fails again after rejoining stays out —
    an always-True probe against a broken machine must not livelock the
    drop/rejoin cycle."""
    r = _scripted_runner(
        monkeypatch, [(-9, 0), (-9, 2), (0, None)],
        nprocs=4, host_map=["a", "a", "b", "c"],
        host_down_probe=lambda h: h == "a",
        rejoin_probe=lambda slot: True,
        elastic=ElasticPolicy(enabled=True, min_workers=1))
    assert r.run() == 0
    assert r._drop_counts["a"] == 2
    assert r.dropped_hosts == ["a"]          # still out, probe says yes
    assert r.nprocs == 2 and r.host_map == ["b", "c"]


def test_host_drop_respects_min_workers(monkeypatch):
    """A re-form that would shrink below min_workers is refused — the
    job fails loud instead of limping on a quorum too small to trust."""
    r = _scripted_runner(
        monkeypatch, [(-9, 0), (-9, 0), (-9, 0), (-9, 0)],
        nprocs=4, host_map=["a", "a", "a", "b"],
        host_down_probe=lambda h: h == "a",
        policy=RestartPolicy(max_restarts=3, backoff_base=0.01,
                             jitter=0.0),
        elastic=ElasticPolicy(enabled=True, min_workers=2))
    assert r.run() != 0
    assert r.dropped_hosts == []             # 4 - 3 = 1 < min_workers
    assert r.failure is not None


# ---------------------------------------------------------------------------
# scheduler host lifecycle (fake runners, manual stepping)
# ---------------------------------------------------------------------------

class FakeRunner:
    """ResilientRunner stand-in (same contract as test_fleet's): blocks
    until released; canceled → rc 0 without the out artifact."""

    def __init__(self, job, behavior):
        self.job = job
        self.behavior = behavior
        self.release = threading.Event()
        self.canceled = False
        self.failure = None
        self.workdir = os.path.join(job.job_dir, "runner")

    def cancel(self):
        self.canceled = True
        self.release.set()

    def run(self):
        assert self.release.wait(timeout=30), "fake runner never released"
        if self.behavior == "complete" and not self.canceled:
            with open(self.job.out_path, "w") as f:
                f.write("done")
            return 0
        return 0


class PodFleet:
    """A FleetScheduler on a simulated host pool, stepped manually."""

    def __init__(self, tmp_path, hosts="a=2,b=2", **kw):
        self.behaviors = {}
        self.runners = {}

        def factory(job, cmd, env):
            r = FakeRunner(job, self.behaviors.get(job.name, "complete"))
            self.runners.setdefault(job.name, []).append(r)
            return r

        self.sched = FleetScheduler(str(tmp_path / "fleet"), None,
                                    hosts=HostPool.parse(hosts),
                                    runner_factory=factory, **kw)

    def submit(self, behavior="complete", **kw):
        self.behaviors[kw["name"]] = behavior
        return self.sched.submit(JobSpec(**kw))

    def release(self, name):
        self.runners[name][-1].release.set()

    def settle(self, cond, timeout=10.0):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            self.sched.step()
            if cond():
                return
            time.sleep(0.01)
        raise AssertionError("condition never settled")


def test_host_lost_kills_gang_and_requeues_onto_survivors(tmp_path):
    f = PodFleet(tmp_path, hosts="a=2,b=2")
    j = f.submit(name="t0", world=2)
    f.sched.step()
    assert j.state == RUNNING
    dead = j.hosts[0]
    other = "b" if dead == "a" else "a"
    f.sched.mark_host(dead, HOST_LOST, by="test")
    # abrupt path: the gang is killed and requeued, then relaunched —
    # and never back onto the dead machine
    f.settle(lambda: j.state == RUNNING and len(f.runners["t0"]) == 2)
    assert j.hosts == (other,)
    assert j.preempt_count == 1
    f.release("t0")
    f.settle(lambda: j.state == COMPLETED)
    events = [e["ev"] for e in self_journal(f)]
    assert "host" in events and "host_kill" in events


def self_journal(f):
    from sparknet_tpu.parallel.fleet import FleetJournal
    return FleetJournal.read(
        os.path.join(f.sched.workdir, "fleet_journal.jsonl"))


def test_host_loss_strands_gang_when_no_capacity_remains(tmp_path):
    """A gang spanning both hosts dies with either; with half the pod
    gone it waits QUEUED (all-or-nothing) until the host returns."""
    f = PodFleet(tmp_path, hosts="a=2,b=2")
    j = f.submit(name="wide", world=4)
    f.sched.step()
    assert j.state == RUNNING and set(j.hosts) == {"a", "b"}
    f.sched.mark_host("b", HOST_LOST, by="test")
    f.settle(lambda: j.state == QUEUED)
    f.sched.step()
    f.sched.step()
    assert j.state == QUEUED                 # 2 live slots < world 4
    f.sched.mark_host("b", HOST_LIVE, by="test")
    f.settle(lambda: j.state == RUNNING)
    f.release("wide")
    f.settle(lambda: j.state == COMPLETED)


def test_host_draining_evicts_gracefully_and_fences_placement(tmp_path):
    f = PodFleet(tmp_path, hosts="a=2,b=2", preempt_grace_s=30)
    j = f.submit(name="t0", world=2)
    f.sched.step()
    assert j.state == RUNNING
    victim = j.hosts[0]
    other = "b" if victim == "a" else "a"
    f.sched.mark_host(victim, HOST_DRAINING, by="spot-notice")
    # graceful path: SNAPSHOT_STOP eviction (cancel, not a kill), then
    # requeue and relaunch — never back onto the draining host
    f.settle(lambda: j.state == RUNNING and len(f.runners["t0"]) == 2)
    assert f.runners["t0"][0].canceled
    assert j.preempt_count == 1
    assert j.hosts == (other,)               # drain fence held
    f.release("t0")
    f.settle(lambda: j.state == COMPLETED)


def test_host_control_channel_applies_cross_process_marks(tmp_path):
    f = PodFleet(tmp_path, hosts="a=2,b=2")
    # a separate process (tools/fleet.py mark-host, the chaos harness)
    # appends to host_control.jsonl; the scheduler applies it at step()
    request_mark_host(f.sched.workdir, "a", HOST_DRAINING, by="ops")
    f.sched.step()
    assert f.sched.pool.state["a"] == HOST_DRAINING
    # malformed and unknown-host records are loud but not fatal
    with open(os.path.join(f.sched.workdir, "host_control.jsonl"),
              "a") as fh:
        fh.write("not json\n")
        fh.write(json.dumps({"host": "ghost", "state": "lost"}) + "\n")
    request_mark_host(f.sched.workdir, "a", HOST_LIVE, by="ops")
    f.sched.step()
    assert f.sched.pool.state["a"] == HOST_LIVE
    with pytest.raises(FleetError, match="bad host state"):
        request_mark_host(f.sched.workdir, "a", "zombie")


def test_status_views_fold_hosts_live_and_offline(tmp_path):
    f = PodFleet(tmp_path, hosts="a=2,b=2@10.0.0.9")
    j = f.submit(name="t0", world=2)
    f.sched.step()
    f.sched.mark_host("b", HOST_DRAINING, by="test") \
        if j.hosts == ("a",) else f.sched.mark_host("a", HOST_DRAINING,
                                                    by="test")
    st = f.sched.status()
    host = j.hosts[0]
    assert st["hosts"][host]["used"] == 2
    assert st["hosts"][host]["gangs"] == ["t0"]
    drained = "b" if host == "a" else "a"
    assert st["hosts"][drained]["state"] == HOST_DRAINING
    text = format_status(st)
    assert "host" in text and drained in text and HOST_DRAINING in text
    # the offline reconstruction (tools/fleet.py status on a dead
    # scheduler's workdir) folds the same hosts section from the journal
    off = offline_status(f.sched.workdir)
    assert off["hosts"][host]["gangs"] == ["t0"]
    assert off["hosts"][drained]["state"] == HOST_DRAINING
    assert off["hosts"]["b"]["addr"] == "10.0.0.9"
    f.release("t0")
    f.settle(lambda: j.state == COMPLETED)


# ---------------------------------------------------------------------------
# scheduler death on a pod: journal resume + cross-host pid verification
# ---------------------------------------------------------------------------

def _stub_path(tmp_path):
    p = tmp_path / "stub.py"
    p.write_text(
        "import os, signal, sys, time\n"
        "state, rounds, tick, out = (sys.argv[1], int(sys.argv[2]),\n"
        "                            float(sys.argv[3]), sys.argv[4])\n"
        "stop = []\n"
        "signal.signal(signal.SIGTERM, lambda *a: stop.append(1))\n"
        "r = int(open(state).read()) if os.path.exists(state) else 0\n"
        "while r < rounds:\n"
        "    if stop:\n"
        "        sys.exit(0)\n"
        "    time.sleep(tick)\n"
        "    r += 1\n"
        "    with open(state, 'w') as f:\n"
        "        f.write(str(r))\n"
        "with open(out, 'w') as f:\n"
        "    f.write('done')\n")
    return str(p)


def _stub_spec(tmp_path, name, rounds=10, tick=0.02, **kw):
    return JobSpec(
        name=name, rounds=rounds,
        cmd=(sys.executable, _stub_path(tmp_path),
             "{ckpt}/state.txt", "{rounds}", str(tick), "{out}"),
        **kw)


def test_pod_resume_reaps_cross_host_survivor_and_requeues(tmp_path):
    """Scheduler death on a simulated 2-host rig: the journal records
    the gang's pids against its hosts; resume rebuilds the HostPool from
    the fleet record, identifies the survivor through the /proc env-tag
    check (pid recycling can't make it kill a stranger), reaps it, and
    requeues — the relaunch resumes from the survivor's checkpoint."""
    wd = str(tmp_path / "fleet")
    spec = _stub_spec(tmp_path, "lone", rounds=40, tick=0.01, world=2)
    sched = FleetScheduler(wd, None, hosts=HostPool.parse("a=2,b=2"))
    job = sched.submit(spec)
    os.makedirs(job.ckpt_dir, exist_ok=True)
    proc = subprocess.Popen(
        [c.format(out=job.out_path, ckpt=job.ckpt_dir, world="2",
                  rounds="100000") for c in spec.cmd],
        env={**os.environ, ENV_JOB_TAG: "lone"})
    sched.journal.append("launch", job="lone", episode=1, slots=[0, 1],
                         hosts=["a"])
    sched.journal.append("pids", job="lone", pids=[proc.pid])
    sched.journal.close()
    del sched
    time.sleep(0.3)
    assert proc.poll() is None and _pid_is_fleet_job(proc.pid, "lone")

    fleet = FleetScheduler.resume(wd)
    # the pool came back from the journal's fleet record
    assert fleet.pool is not None and fleet.pool.total_devices == 4
    assert sorted(h.name for h in fleet.pool.specs()) == ["a", "b"]
    # the survivor was reaped before the job could be relaunched
    assert proc.wait(timeout=10) is not None
    job2 = fleet.jobs["lone"]
    assert job2.state == QUEUED
    state = os.path.join(job2.ckpt_dir, "state.txt")
    resumed_from = int(open(state).read()) if os.path.exists(state) else 0
    assert fleet.run(tick_s=0.02, timeout_s=60) == 0
    assert job2.completed_ok()
    if resumed_from:
        assert int(open(state).read()) >= resumed_from


# ---------------------------------------------------------------------------
# the whole story at once: one slice of the standing burn-in
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_pod_burn_in_slice_end_to_end(tmp_path):
    """One seeded pod-soak slice on a simulated 3-host rig: mixed
    training+serving tenants, a host kill mid-load, a corrupt-upload
    burst through the quarantine plane, a flash crowd — every training
    must finish bit-identical to the fault-free baseline, every serving
    leg with zero errors and zero routed-answer mismatches, and the rig
    must wind down with zero orphans."""
    out = tmp_path / "verdict.json"
    rc = subprocess.call(
        [sys.executable, os.path.join(REPO, "tools", "soak.py"),
         "--pod", "3", "--pod-slice", "--seed", "7",
         "--workdir", str(tmp_path / "rig"), "--out", str(out)],
        env={**os.environ, "JAX_PLATFORMS": "cpu"}, cwd=REPO)
    assert rc == 0
    verdict = json.loads(out.read_text())
    assert verdict["ok"] and verdict["passed"] == 1
    ep = verdict["episodes"][0]
    assert ep["trainings"] and all(t["match"] for t in ep["trainings"])
    assert ep["slo_ok"] and not ep["orphans"]
    assert ep["chaos"]["host_kill"]
    assert ep["quarantine"]["ok"] and ep["quarantine"]["typed_overflow"]
