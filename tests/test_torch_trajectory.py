"""Training-trajectory equivalence vs an independent torch reimplementation.

The accuracy-parity proxy runnable on this rig (real CIFAR/ImageNet are
absent): the SAME cifar10_quick config — architecture from
examples/cifar10/cifar10_quick_train_test.prototxt, solver from
cifar10_quick_solver.prototxt (base_lr 0.001, momentum 0.9, weight_decay
0.004, lr_policy fixed), the SAME initial weights (moved through this
repo's own .caffemodel interchange), and the SAME synthetic batches —
must produce the SAME per-step loss curve in this framework and in a
from-scratch torch implementation whose update rule transcribes
sgd_solver.cpp:27-143 (Regularize: grad += λ·decay_mult·w; then
history = local_lr·grad + momentum·history; w -= history).

This is strictly stronger than the per-op cross-checks in
test_torch_crosscheck.py: it pins the whole loop — forward, backward,
regularization, momentum, lr_mult handling — over many steps, the way
test_gradient_based_solver.cpp pins the C++ solvers.
"""

import numpy as np
import pytest

torch = pytest.importorskip("torch")
import torch.nn.functional as F  # noqa: E402

import jax  # noqa: E402

from sparknet_tpu.proto import (  # noqa: E402
    load_net_prototxt,
    load_solver_prototxt_with_net,
    replace_data_layers,
)
from sparknet_tpu.solvers import Solver  # noqa: E402

REF_NET = "/root/reference/caffe/examples/cifar10/cifar10_quick_train_test.prototxt"
SOLVER_TXT = ("base_lr: 0.001\nmomentum: 0.9\nweight_decay: 0.004\n"
              'lr_policy: "fixed"\n')
BATCH = 16


def _make_solver(compute_dtype=None):
    netp = load_net_prototxt(open(REF_NET).read())
    netp = replace_data_layers(netp, BATCH, BATCH, 3, 32, 32)
    sp = load_solver_prototxt_with_net(SOLVER_TXT, netp)
    import jax.numpy as jnp
    dt = jnp.bfloat16 if compute_dtype == "bf16" else None
    return Solver(sp, seed=0, compute_dtype=dt)


def _batches(n_steps, seed=3):
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n_steps):
        out.append({
            "data": rng.normal(size=(BATCH, 3, 32, 32)).astype(np.float32),
            "label": rng.integers(0, 10, size=(BATCH,)).astype(np.float32),
        })
    return out


# -- independent torch model -------------------------------------------------

class TorchQuick:
    """cifar10_quick transcribed from the prototxt, NOT from this repo's
    graph code: conv1→maxpool→relu / conv2→relu→avepool /
    conv3→relu→avepool / ip1→ip2, caffe ceil-mode pooling."""

    LAYERS = ["conv1", "conv2", "conv3", "ip1", "ip2"]
    # (lr_mult_w, lr_mult_b) per the prototxt param blocks; decay_mult
    # defaults to 1 (caffe.proto ParamSpec)
    LR_MULTS = {n: (1.0, 2.0) for n in LAYERS}

    def __init__(self, caffemodel_blobs):
        self.p = {}
        self.hist = {}
        for name in self.LAYERS:
            w, b = caffemodel_blobs[name]
            self.p[name + ".w"] = torch.tensor(np.asarray(w),
                                               requires_grad=True)
            self.p[name + ".b"] = torch.tensor(np.asarray(b),
                                               requires_grad=True)
        for k, v in self.p.items():
            self.hist[k] = torch.zeros_like(v)

    @staticmethod
    def _ave_pool_caffe(x):
        # caffe AVE 3x3 s2 ceil-mode, denominator = window clipped to the
        # input extent (pooling_layer.cpp AVE branch, pad == 0)
        return F.avg_pool2d(x, 3, 2, ceil_mode=True,
                            count_include_pad=False)

    def forward(self, x, y):
        p = self.p
        h = F.conv2d(x, p["conv1.w"], p["conv1.b"], padding=2)
        h = F.max_pool2d(h, 3, 2, ceil_mode=True)
        h = F.relu(h)
        h = F.conv2d(h, p["conv2.w"], p["conv2.b"], padding=2)
        h = F.relu(h)
        h = self._ave_pool_caffe(h)
        h = F.conv2d(h, p["conv3.w"], p["conv3.b"], padding=2)
        h = F.relu(h)
        h = self._ave_pool_caffe(h)
        h = h.reshape(h.shape[0], -1)
        h = F.linear(h, p["ip1.w"], p["ip1.b"])
        h = F.linear(h, p["ip2.w"], p["ip2.b"])
        return h, F.cross_entropy(h, y)

    def sgd_step(self, loss, base_lr=0.001, momentum=0.9, wd=0.004):
        """sgd_solver.cpp update order: Regularize (L2: grad += λ·w),
        ComputeUpdateValue (history = local_rate·grad + m·history),
        Blob::Update (w -= history)."""
        grads = torch.autograd.grad(loss, list(self.p.values()))
        with torch.no_grad():
            for (k, v), g in zip(self.p.items(), grads):
                layer, kind = k.split(".")
                lmw, lmb = self.LR_MULTS[layer]
                local_lr = base_lr * (lmw if kind == "w" else lmb)
                g = g + wd * v  # decay_mult 1 on weights AND biases here
                self.hist[k] = local_lr * g + momentum * self.hist[k]
                v -= self.hist[k]


def _export_initial_weights(solver, tmp_path):
    model, _ = solver.snapshot_caffe(str(tmp_path / "init"))
    from sparknet_tpu.proto.caffemodel import load_caffemodel
    return load_caffemodel(model)


# -- tests -------------------------------------------------------------------

def test_forward_activation_fixture(tmp_path):
    """Golden-activation check: identical weights (through the
    .caffemodel interchange), identical input ⇒ layer-by-layer identical
    activations between the two frameworks."""
    solver = _make_solver()
    blobs = _export_initial_weights(solver, tmp_path)
    tq = TorchQuick(blobs)
    b = _batches(1)[0]
    ours = solver.train_net.apply_all(
        solver.params, {"data": b["data"], "label": b["label"]}, train=False)
    x = torch.tensor(b["data"])
    p = tq.p
    h = F.conv2d(x, p["conv1.w"], p["conv1.b"], padding=2)
    np.testing.assert_allclose(np.asarray(ours["conv1"]), h.detach().numpy(),
                               atol=1e-5, rtol=1e-4)
    h = F.relu(F.max_pool2d(h, 3, 2, ceil_mode=True))
    np.testing.assert_allclose(np.asarray(ours["pool1"]), h.detach().numpy(),
                               atol=1e-5, rtol=1e-4)
    h = F.relu(F.conv2d(h, p["conv2.w"], p["conv2.b"], padding=2))
    h = TorchQuick._ave_pool_caffe(h)
    np.testing.assert_allclose(np.asarray(ours["pool2"]), h.detach().numpy(),
                               atol=1e-5, rtol=1e-4)
    h = F.relu(F.conv2d(h, p["conv3.w"], p["conv3.b"], padding=2))
    h = TorchQuick._ave_pool_caffe(h)
    np.testing.assert_allclose(np.asarray(ours["pool3"]), h.detach().numpy(),
                               atol=1e-5, rtol=1e-4)
    h = F.linear(h.reshape(h.shape[0], -1), p["ip1.w"], p["ip1.b"])
    np.testing.assert_allclose(np.asarray(ours["ip1"]), h.detach().numpy(),
                               atol=1e-5, rtol=1e-4)
    h = F.linear(h, p["ip2.w"], p["ip2.b"])
    np.testing.assert_allclose(np.asarray(ours["ip2"]), h.detach().numpy(),
                               atol=1e-4, rtol=1e-4)


def test_training_trajectory_tracks_torch(tmp_path):
    """~300 steps of the full solver loop: per-step losses of the two
    frameworks track within float32 drift tolerance, and final weights
    agree — same config ⇒ same trajectory."""
    n_steps = 300
    solver = _make_solver()
    blobs = _export_initial_weights(solver, tmp_path)
    tq = TorchQuick(blobs)
    batches = _batches(n_steps)

    solver.set_train_data(iter(batches))
    ours = []
    for _ in range(n_steps):
        solver.step(1)
        ours.append(solver._smoothed[-1])

    theirs = []
    for b in batches:
        _, loss = tq.forward(torch.tensor(b["data"]),
                             torch.tensor(b["label"], dtype=torch.long))
        tq.sgd_step(loss)
        theirs.append(float(loss))

    ours = np.asarray(ours)
    theirs = np.asarray(theirs)
    # identical math in different frameworks: tight at the start, f32
    # accumulation drift allowed to grow with steps
    np.testing.assert_allclose(ours[:10], theirs[:10], rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(ours[:100], theirs[:100],
                               rtol=5e-3, atol=5e-4)
    np.testing.assert_allclose(ours, theirs, rtol=2e-2, atol=2e-3)
    # and the trained weights still agree at the end
    final = dict(_export_initial_weights(solver, tmp_path))  # iter_300 file
    for name in TorchQuick.LAYERS:
        np.testing.assert_allclose(
            np.asarray(final[name][0]), tq.p[name + ".w"].detach().numpy(),
            rtol=2e-2, atol=2e-3)


def test_multistep_lr_trajectory_tracks_torch(tmp_path):
    """lr_policy "multistep" crossing TWO boundaries: the per-iteration
    rate schedule of SGDSolver::GetLearningRate (sgd_solver.cpp:27-79,
    multistep branch: current_step_ advances when iter_ >= stepvalue)
    must agree with an independent transcription — rate factor at iter i
    is gamma^#{v : i >= v}."""
    n_steps = 75
    netp = load_net_prototxt(open(REF_NET).read())
    netp = replace_data_layers(netp, BATCH, BATCH, 3, 32, 32)
    sp = load_solver_prototxt_with_net(
        ("base_lr: 0.001\nmomentum: 0.9\nweight_decay: 0.004\n"
         'lr_policy: "multistep"\ngamma: 0.1\n'
         "stepvalue: 25\nstepvalue: 50\n"), netp)
    solver = Solver(sp, seed=0)
    blobs = _export_initial_weights(solver, tmp_path)
    tq = TorchQuick(blobs)
    batches = _batches(n_steps, seed=7)

    solver.set_train_data(iter(batches))
    ours, wdeltas = [], []
    prev_w = np.array(np.asarray(solver.params["conv1"][0]))
    for _ in range(n_steps):
        solver.step(1)
        ours.append(solver._smoothed[-1])
        cur_w = np.asarray(solver.params["conv1"][0])
        wdeltas.append(float(np.abs(cur_w - prev_w).mean()))
        prev_w = np.array(cur_w)
    theirs = []
    for i, b in enumerate(batches):
        _, loss = tq.forward(torch.tensor(b["data"]),
                             torch.tensor(b["label"], dtype=torch.long))
        rate = 0.001 * (0.1 ** sum(i >= v for v in (25, 50)))
        tq.sgd_step(loss, base_lr=rate)
        theirs.append(float(loss))
    np.testing.assert_allclose(ours, theirs, rtol=5e-3, atol=5e-4)
    # the boundaries bite: weight motion scales with the rate (modulo the
    # 0.9^k decay of pre-boundary momentum history) — two drops of 10x
    # leave late-window motion far below the full-rate window
    assert np.mean(wdeltas[65:75]) < 0.3 * np.mean(wdeltas[15:25])


# -- BN-bearing net (cifar10_full_sigmoid_bn shape) --------------------------

BN_NET = ("/root/reference/caffe/examples/cifar10/"
          "cifar10_full_sigmoid_train_test_bn.prototxt")


class TorchSigmoidBN:
    """cifar10_full_sigmoid_bn transcribed from the prototxt and
    batch_norm_layer.cpp, NOT from this repo's graph code:
    conv(no bias)→maxpool→BN→sigmoid / conv→BN→sigmoid→avepool /
    conv→BN→sigmoid→avepool / ip1.  Caffe BatchNorm: train-mode
    normalization by BATCH stats (biased variance), running blobs kept as
    λ-decayed sums with a scale factor (blobs_[2]), variance stored with
    the m/(m-1) unbiased correction; eval divides blobs by the scale
    factor (batch_norm_layer.cpp:Forward_cpu)."""

    CONVS = ["conv1", "conv2", "conv3"]
    BNS = ["bn1", "bn2", "bn3"]
    EPS, LAM = 1e-5, 0.999

    def __init__(self, caffemodel_blobs):
        self.p, self.hist, self.bn = {}, {}, {}
        for name in self.CONVS:
            (w,) = caffemodel_blobs[name]  # bias_term: false
            self.p[name + ".w"] = torch.tensor(np.asarray(w),
                                               requires_grad=True)
        w, b = caffemodel_blobs["ip1"]
        self.p["ip1.w"] = torch.tensor(np.asarray(w), requires_grad=True)
        self.p["ip1.b"] = torch.tensor(np.asarray(b), requires_grad=True)
        for k, v in self.p.items():
            self.hist[k] = torch.zeros_like(v)
        for name in self.BNS:
            mean, var, scale = caffemodel_blobs[name]
            self.bn[name] = [torch.tensor(np.asarray(mean)),
                             torch.tensor(np.asarray(var)),
                             torch.tensor(np.asarray(scale))]

    def _bn(self, x, name, training):
        mean_b, var_b, scale_b = self.bn[name]
        view = (1, -1, 1, 1)
        if not training:
            factor = 0.0 if float(scale_b[0]) == 0 else 1.0 / float(scale_b[0])
            mean = mean_b * factor
            var = var_b * factor
            return (x - mean.view(view)) / torch.sqrt(var.view(view)
                                                      + self.EPS)
        mean = x.mean(dim=(0, 2, 3))
        xc = x - mean.view(view)
        var = (xc * xc).mean(dim=(0, 2, 3))
        with torch.no_grad():
            m = x.numel() // x.shape[1]
            corr = m / max(m - 1, 1)
            self.bn[name][0] = self.LAM * mean_b + mean.detach()
            self.bn[name][1] = self.LAM * var_b + corr * var.detach()
            self.bn[name][2] = self.LAM * scale_b + 1.0
        return xc / torch.sqrt(var.view(view) + self.EPS)

    def forward(self, x, y, training=True):
        p = self.p
        h = F.conv2d(x, p["conv1.w"], padding=2)
        h = F.max_pool2d(h, 3, 2, ceil_mode=True)
        h = torch.sigmoid(self._bn(h, "bn1", training))
        h = F.conv2d(h, p["conv2.w"], padding=2)
        h = torch.sigmoid(self._bn(h, "bn2", training))
        h = F.avg_pool2d(h, 3, 2, ceil_mode=True, count_include_pad=False)
        h = F.conv2d(h, p["conv3.w"], padding=2)
        h = torch.sigmoid(self._bn(h, "bn3", training))
        h = F.avg_pool2d(h, 3, 2, ceil_mode=True, count_include_pad=False)
        h = F.linear(h.reshape(h.shape[0], -1), p["ip1.w"], p["ip1.b"])
        return h, F.cross_entropy(h, y)

    def sgd_step(self, loss, base_lr=0.001, momentum=0.9, wd=0.004):
        # conv params: one ParamSpec {lr_mult: 1}, decay_mult defaults 1;
        # ip1: w (1, 1), b (1, 0); BN blobs lr_mult 0 -> never updated by
        # the solver (their only motion is the forward moving average)
        grads = torch.autograd.grad(loss, list(self.p.values()))
        with torch.no_grad():
            for (k, v), g in zip(self.p.items(), grads):
                decay_mult = 0.0 if k == "ip1.b" else 1.0
                g = g + wd * decay_mult * v
                self.hist[k] = base_lr * g + momentum * self.hist[k]
                v -= self.hist[k]


def test_bn_trajectory_and_running_stats_track_torch(tmp_path):
    """BN-bearing net over the full solver loop: per-step train losses
    track, the λ-decayed running-stat blobs agree after training, and a
    TEST-phase (use_global_stats) forward produces the same logits —
    pinning caffe's BN update semantics end to end
    (batch_norm_layer.cpp + sgd_solver.cpp)."""
    n_steps = 60
    netp = load_net_prototxt(open(BN_NET).read())
    netp = replace_data_layers(netp, BATCH, BATCH, 3, 32, 32)
    sp = load_solver_prototxt_with_net(SOLVER_TXT, netp)
    solver = Solver(sp, seed=0)
    blobs = _export_initial_weights(solver, tmp_path)
    tbn = TorchSigmoidBN(blobs)
    batches = _batches(n_steps, seed=9)

    solver.set_train_data(iter(batches))
    ours = []
    for _ in range(n_steps):
        solver.step(1)
        ours.append(solver._smoothed[-1])
    theirs = []
    for b in batches:
        _, loss = tbn.forward(torch.tensor(b["data"]),
                              torch.tensor(b["label"], dtype=torch.long))
        tbn.sgd_step(loss)
        theirs.append(float(loss))
    np.testing.assert_allclose(ours[:10], theirs[:10], rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(ours, theirs, rtol=1e-2, atol=1e-3)

    # running-stat blobs: λ-decayed sums + scale factor agree
    final = dict(_export_initial_weights(solver, tmp_path))
    for name in TorchSigmoidBN.BNS:
        for i in range(3):
            np.testing.assert_allclose(
                np.asarray(final[name][i]),
                tbn.bn[name][i].numpy(), rtol=1e-3, atol=1e-4)

    # TEST-phase forward (use_global_stats) on a held-out batch: same
    # logits from the accumulated statistics
    hb = _batches(1, seed=11)[0]
    out = solver.test_net.apply_all(
        solver.params, {"data": hb["data"], "label": hb["label"]},
        train=False)
    logits, _ = tbn.forward(torch.tensor(hb["data"]),
                            torch.tensor(hb["label"], dtype=torch.long),
                            training=False)
    np.testing.assert_allclose(np.asarray(out["ip1"]),
                               logits.detach().numpy(),
                               rtol=1e-3, atol=1e-4)


def test_bf16_trajectory_tracks_f32_torch(tmp_path):
    """The bf16 mixed-precision path follows the same trajectory at bf16
    resolution — parity of the reduced-precision config against the
    independent f32 reference."""
    n_steps = 60
    solver = _make_solver(compute_dtype="bf16")
    blobs = _export_initial_weights(solver, tmp_path)
    tq = TorchQuick(blobs)
    batches = _batches(n_steps, seed=4)

    solver.set_train_data(iter(batches))
    ours = []
    for _ in range(n_steps):
        solver.step(1)
        ours.append(solver._smoothed[-1])
    theirs = []
    for b in batches:
        _, loss = tq.forward(torch.tensor(b["data"]),
                             torch.tensor(b["label"], dtype=torch.long))
        tq.sgd_step(loss)
        theirs.append(float(loss))
    ours = np.asarray(ours)
    theirs = np.asarray(theirs)
    # bf16 has ~3 decimal digits; curves must track loosely and end in
    # the same regime
    assert float(np.max(np.abs(ours - theirs))) < 0.15
    assert abs(ours[-5:].mean() - theirs[-5:].mean()) < 0.05


# -- AlexNet-class layer mix: LRN + grouped conv ------------------------------

MIX_NET = """
name: "alexmix"
layer { name: "data" type: "Input" top: "data"
  input_param { shape { dim: 16 dim: 3 dim: 16 dim: 16 } } }
layer { name: "label" type: "Input" top: "label"
  input_param { shape { dim: 16 } } }
layer { name: "conv1" type: "Convolution" bottom: "data" top: "conv1"
  param { lr_mult: 1 } param { lr_mult: 2 }
  convolution_param { num_output: 16 kernel_size: 5 pad: 2
    weight_filler { type: "gaussian" std: 0.05 }
    bias_filler { type: "constant" value: 0.1 } } }
layer { name: "relu1" type: "ReLU" bottom: "conv1" top: "conv1" }
layer { name: "norm1" type: "LRN" bottom: "conv1" top: "norm1"
  lrn_param { local_size: 5 alpha: 0.1 beta: 0.75 } }
layer { name: "pool1" type: "Pooling" bottom: "norm1" top: "pool1"
  pooling_param { pool: MAX kernel_size: 3 stride: 2 } }
layer { name: "conv2" type: "Convolution" bottom: "pool1" top: "conv2"
  param { lr_mult: 1 } param { lr_mult: 2 }
  convolution_param { num_output: 32 kernel_size: 3 pad: 1 group: 2
    weight_filler { type: "gaussian" std: 0.05 }
    bias_filler { type: "constant" value: 0.0 } } }
layer { name: "relu2" type: "ReLU" bottom: "conv2" top: "conv2" }
layer { name: "pool2" type: "Pooling" bottom: "conv2" top: "pool2"
  pooling_param { pool: AVE kernel_size: 3 stride: 2 } }
layer { name: "ip" type: "InnerProduct" bottom: "pool2" top: "ip"
  param { lr_mult: 1 } param { lr_mult: 2 }
  inner_product_param { num_output: 10
    weight_filler { type: "gaussian" std: 0.05 }
    bias_filler { type: "constant" value: 0.0 } } }
layer { name: "loss" type: "SoftmaxWithLoss" bottom: "ip" bottom: "label" }
"""


class TorchAlexMix:
    """The CaffeNet layer-mix slice — LRN (lrn_layer.cpp cross-channel
    formula, which torch's local_response_norm shares) + GROUPED conv
    (conv_layer.cpp group>1) + ceil-mode max/ave pooling — transcribed
    into torch independently of this repo's graph code."""

    LAYERS = ["conv1", "conv2", "ip"]
    LR_MULTS = {n: (1.0, 2.0) for n in LAYERS}

    def __init__(self, blobs):
        self.p, self.hist = {}, {}
        for name in self.LAYERS:
            w, b = blobs[name]
            self.p[name + ".w"] = torch.tensor(np.asarray(w),
                                               requires_grad=True)
            self.p[name + ".b"] = torch.tensor(np.asarray(b),
                                               requires_grad=True)
        for k, v in self.p.items():
            self.hist[k] = torch.zeros_like(v)

    def forward(self, x, y):
        p = self.p
        h = F.relu(F.conv2d(x, p["conv1.w"], p["conv1.b"], padding=2))
        h = F.local_response_norm(h, 5, alpha=0.1, beta=0.75, k=1.0)
        h = F.max_pool2d(h, 3, 2, ceil_mode=True)
        h = F.relu(F.conv2d(h, p["conv2.w"], p["conv2.b"], padding=1,
                            groups=2))
        h = F.avg_pool2d(h, 3, 2, ceil_mode=True, count_include_pad=False)
        h = F.linear(h.reshape(h.shape[0], -1), p["ip.w"], p["ip.b"])
        return h, F.cross_entropy(h, y)

    def sgd_step(self, loss, base_lr=0.001, momentum=0.9, wd=0.004):
        grads = torch.autograd.grad(loss, list(self.p.values()))
        with torch.no_grad():
            for (k, v), g in zip(self.p.items(), grads):
                layer, kind = k.split(".")
                lmw, lmb = self.LR_MULTS[layer]
                local_lr = base_lr * (lmw if kind == "w" else lmb)
                g = g + wd * v  # decay_mult defaults 1 on w and b
                self.hist[k] = local_lr * g + momentum * self.hist[k]
                v -= self.hist[k]


def test_alexnet_mix_trajectory_tracks_torch(tmp_path):
    """LRN + grouped-conv layer mix over the full solver loop: the last
    CaffeNet-family gradient paths not yet pinned end-to-end (LRN VJP,
    group>1 conv backward, lr_mult 2 biases) track an independent torch
    transcription step for step."""
    n_steps = 60
    netp = load_net_prototxt(MIX_NET)
    sp = load_solver_prototxt_with_net(SOLVER_TXT, netp)
    solver = Solver(sp, seed=0)
    blobs = _export_initial_weights(solver, tmp_path)
    tam = TorchAlexMix(blobs)
    rng = np.random.default_rng(13)
    batches = [{
        "data": rng.normal(size=(16, 3, 16, 16)).astype(np.float32),
        "label": rng.integers(0, 10, size=(16,)).astype(np.float32),
    } for _ in range(n_steps)]

    solver.set_train_data(iter(batches))
    ours = []
    for _ in range(n_steps):
        solver.step(1)
        ours.append(solver._smoothed[-1])
    theirs = []
    for b in batches:
        _, loss = tam.forward(torch.tensor(b["data"]),
                              torch.tensor(b["label"], dtype=torch.long))
        tam.sgd_step(loss)
        theirs.append(float(loss))
    np.testing.assert_allclose(ours[:10], theirs[:10], rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(ours, theirs, rtol=1e-2, atol=1e-3)
    # grouped-conv weights agree at the end (the group split is the
    # likeliest silent-divergence point)
    final = dict(_export_initial_weights(solver, tmp_path))
    np.testing.assert_allclose(
        np.asarray(final["conv2"][0]), tam.p["conv2.w"].detach().numpy(),
        rtol=1e-2, atol=1e-3)


# -- the non-SGD update rules, transcribed from the reference solvers --------

def _caffe_rule_step(rule, p, hist, grads, lr_mults, base_lr, it,
                     momentum=0.9, wd=0.004):
    """One update under `rule`, transcribing the reference solver .cpp
    files verbatim (adam/adadelta/adagrad/nesterov/rmsprop_solver.cpp)
    after Regularize (g += wd*decay_mult*w, sgd_solver.cpp:Regularize)."""
    with torch.no_grad():
        for (k, v), g in zip(p.items(), grads):
            layer, kind = k.split(".")
            lmw, lmb = lr_mults[layer]
            local_lr = base_lr * (lmw if kind == "w" else lmb)
            g = g + wd * v
            if rule == "Nesterov":
                h_old = hist[k].clone()
                hist[k] = momentum * hist[k] + local_lr * g
                v -= (1 + momentum) * hist[k] - momentum * h_old
            elif rule == "AdaGrad":
                hist[k] = hist[k] + g * g
                v -= local_lr * g / (torch.sqrt(hist[k]) + 1e-8)
            elif rule == "RMSProp":
                hist[k] = 0.98 * hist[k] + 0.02 * g * g
                v -= local_lr * g / (torch.sqrt(hist[k]) + 1e-8)
            elif rule == "Adam":
                b1, b2, eps = 0.9, 0.999, 1e-8
                m, vv = hist[k]
                m = b1 * m + (1 - b1) * g
                vv = b2 * vv + (1 - b2) * g * g
                hist[k] = (m, vv)
                t = it + 1
                corr = (1 - b2 ** t) ** 0.5 / (1 - b1 ** t)
                v -= local_lr * corr * m / (torch.sqrt(vv) + eps)
            elif rule == "AdaDelta":
                delta = 1e-6
                h1, h2 = hist[k]
                h1 = momentum * h1 + (1 - momentum) * g * g  # grad² hist
                upd = g * torch.sqrt((h2 + delta) / (h1 + delta))
                h2 = momentum * h2 + (1 - momentum) * upd * upd
                hist[k] = (h1, h2)
                v -= local_lr * upd
            else:
                raise ValueError(rule)


RULE_SOLVERS = {
    "Nesterov": ('type: "Nesterov"\nbase_lr: 0.001\nmomentum: 0.9\n'
                 'weight_decay: 0.004\nlr_policy: "fixed"\n'),
    "AdaGrad": ('type: "AdaGrad"\nbase_lr: 0.01\ndelta: 1e-8\n'
                'weight_decay: 0.004\nlr_policy: "fixed"\n'),
    "RMSProp": ('type: "RMSProp"\nbase_lr: 0.001\nrms_decay: 0.98\n'
                'delta: 1e-8\nweight_decay: 0.004\nlr_policy: "fixed"\n'),
    "Adam": ('type: "Adam"\nbase_lr: 0.001\nmomentum: 0.9\n'
             'momentum2: 0.999\ndelta: 1e-8\nweight_decay: 0.004\n'
             'lr_policy: "fixed"\n'),
    "AdaDelta": ('type: "AdaDelta"\nbase_lr: 1.0\nmomentum: 0.95\n'
                 'delta: 1e-6\nweight_decay: 0.004\nlr_policy: "fixed"\n'),
}


@pytest.mark.parametrize("rule", sorted(RULE_SOLVERS))
def test_rule_trajectory_tracks_torch(rule, tmp_path):
    """Every non-SGD update rule over the full solver loop on
    cifar10_quick: gradients from torch autograd + the reference solver's
    transcribed update must reproduce this framework's losses step for
    step (adam/adadelta/adagrad/nesterov/rmsprop_solver.cpp)."""
    n_steps = 30
    netp = load_net_prototxt(open(REF_NET).read())
    netp = replace_data_layers(netp, BATCH, BATCH, 3, 32, 32)
    sp = load_solver_prototxt_with_net(RULE_SOLVERS[rule], netp)
    solver = Solver(sp, seed=0)
    blobs = _export_initial_weights(solver, tmp_path)
    tq = TorchQuick(blobs)
    momentum = 0.95 if rule == "AdaDelta" else 0.9
    base_lr = {"AdaGrad": 0.01, "AdaDelta": 1.0}.get(rule, 0.001)
    hist = {}
    for k, v in tq.p.items():
        if rule in ("Adam", "AdaDelta"):
            hist[k] = (torch.zeros_like(v), torch.zeros_like(v))
        else:
            hist[k] = torch.zeros_like(v)
    batches = _batches(n_steps, seed=17)

    solver.set_train_data(iter(batches))
    ours = []
    for _ in range(n_steps):
        solver.step(1)
        ours.append(solver._smoothed[-1])
    theirs = []
    for it, b in enumerate(batches):
        _, loss = tq.forward(torch.tensor(b["data"]),
                             torch.tensor(b["label"], dtype=torch.long))
        grads = torch.autograd.grad(loss, list(tq.p.values()))
        _caffe_rule_step(rule, tq.p, hist, grads, TorchQuick.LR_MULTS,
                         base_lr, it, momentum=momentum)
        theirs.append(float(loss))
    np.testing.assert_allclose(ours[:5], theirs[:5], rtol=5e-4, atol=5e-5)
    np.testing.assert_allclose(ours, theirs, rtol=2e-2, atol=2e-3)


# -- inception-style branching net with an auxiliary loss head ---------------

INCEPTION_NET = """
name: "miniception"
layer { name: "data" type: "Input" top: "data"
  input_param { shape { dim: 8 dim: 3 dim: 16 dim: 16 } } }
layer { name: "label" type: "Input" top: "label"
  input_param { shape { dim: 8 } } }
layer { name: "stem" type: "Convolution" bottom: "data" top: "stem"
  param { lr_mult: 1 } param { lr_mult: 2 }
  convolution_param { num_output: 16 kernel_size: 3 pad: 1
    weight_filler { type: "gaussian" std: 0.05 }
    bias_filler { type: "constant" value: 0.1 } } }
layer { name: "stem/relu" type: "ReLU" bottom: "stem" top: "stem" }
layer { name: "pool_stem" type: "Pooling" bottom: "stem" top: "pool_stem"
  pooling_param { pool: MAX kernel_size: 3 stride: 2 } }
layer { name: "b1x1" type: "Convolution" bottom: "pool_stem" top: "b1x1"
  param { lr_mult: 1 } param { lr_mult: 2 }
  convolution_param { num_output: 8 kernel_size: 1
    weight_filler { type: "gaussian" std: 0.05 }
    bias_filler { type: "constant" value: 0.0 } } }
layer { name: "b1x1/relu" type: "ReLU" bottom: "b1x1" top: "b1x1" }
layer { name: "b3x3_reduce" type: "Convolution" bottom: "pool_stem"
  top: "b3x3_reduce" param { lr_mult: 1 } param { lr_mult: 2 }
  convolution_param { num_output: 8 kernel_size: 1
    weight_filler { type: "gaussian" std: 0.05 }
    bias_filler { type: "constant" value: 0.0 } } }
layer { name: "b3x3_reduce/relu" type: "ReLU" bottom: "b3x3_reduce"
  top: "b3x3_reduce" }
layer { name: "b3x3" type: "Convolution" bottom: "b3x3_reduce" top: "b3x3"
  param { lr_mult: 1 } param { lr_mult: 2 }
  convolution_param { num_output: 12 kernel_size: 3 pad: 1
    weight_filler { type: "gaussian" std: 0.05 }
    bias_filler { type: "constant" value: 0.0 } } }
layer { name: "b3x3/relu" type: "ReLU" bottom: "b3x3" top: "b3x3" }
layer { name: "bpool" type: "Pooling" bottom: "pool_stem" top: "bpool"
  pooling_param { pool: MAX kernel_size: 3 stride: 1 pad: 1 } }
layer { name: "pool_proj" type: "Convolution" bottom: "bpool" top: "pool_proj"
  param { lr_mult: 1 } param { lr_mult: 2 }
  convolution_param { num_output: 8 kernel_size: 1
    weight_filler { type: "gaussian" std: 0.05 }
    bias_filler { type: "constant" value: 0.0 } } }
layer { name: "pool_proj/relu" type: "ReLU" bottom: "pool_proj"
  top: "pool_proj" }
layer { name: "concat" type: "Concat" bottom: "b1x1" bottom: "b3x3"
  bottom: "pool_proj" top: "concat" }
layer { name: "gpool" type: "Pooling" bottom: "concat" top: "gpool"
  pooling_param { pool: AVE global_pooling: true } }
layer { name: "ip_main" type: "InnerProduct" bottom: "gpool" top: "ip_main"
  param { lr_mult: 1 } param { lr_mult: 2 }
  inner_product_param { num_output: 10
    weight_filler { type: "gaussian" std: 0.05 }
    bias_filler { type: "constant" value: 0.0 } } }
layer { name: "loss_main" type: "SoftmaxWithLoss" bottom: "ip_main"
  bottom: "label" top: "loss_main" loss_weight: 1.0 }
layer { name: "ip_aux" type: "InnerProduct" bottom: "concat" top: "ip_aux"
  param { lr_mult: 1 } param { lr_mult: 2 }
  inner_product_param { num_output: 10
    weight_filler { type: "gaussian" std: 0.05 }
    bias_filler { type: "constant" value: 0.0 } } }
layer { name: "loss_aux" type: "SoftmaxWithLoss" bottom: "ip_aux"
  bottom: "label" top: "loss_aux" loss_weight: 0.3 }
"""


class TorchMiniception:
    """GoogLeNet's training-graph mechanics in miniature, transcribed
    independently of this repo's graph code: concat fan-out (pool_stem
    feeds THREE branches and concat feeds TWO heads — the InsertSplits
    gradient-accumulation paths), ceil-mode pooling, global AVE pooling,
    and two SoftmaxWithLoss heads combined per Caffe's loss_weight
    semantics (net.cpp: total objective = sum loss_weight_i * loss_i)."""

    LAYERS = ["stem", "b1x1", "b3x3_reduce", "b3x3", "pool_proj",
              "ip_main", "ip_aux"]
    LR_MULTS = {n: (1.0, 2.0) for n in LAYERS}

    def __init__(self, blobs):
        self.p, self.hist = {}, {}
        for name in self.LAYERS:
            w, b = blobs[name]
            self.p[name + ".w"] = torch.tensor(np.asarray(w),
                                               requires_grad=True)
            self.p[name + ".b"] = torch.tensor(np.asarray(b),
                                               requires_grad=True)
        for k, v in self.p.items():
            self.hist[k] = torch.zeros_like(v)

    def forward(self, x, y):
        p = self.p
        h = F.relu(F.conv2d(x, p["stem.w"], p["stem.b"], padding=1))
        h = F.max_pool2d(h, 3, 2, ceil_mode=True)
        b1 = F.relu(F.conv2d(h, p["b1x1.w"], p["b1x1.b"]))
        b3 = F.relu(F.conv2d(h, p["b3x3_reduce.w"], p["b3x3_reduce.b"]))
        b3 = F.relu(F.conv2d(b3, p["b3x3.w"], p["b3x3.b"], padding=1))
        bp = F.max_pool2d(h, 3, 1, padding=1)
        bp = F.relu(F.conv2d(bp, p["pool_proj.w"], p["pool_proj.b"]))
        cat = torch.cat([b1, b3, bp], dim=1)
        g = cat.mean(dim=(2, 3))
        main = F.linear(g, p["ip_main.w"], p["ip_main.b"])
        aux = F.linear(cat.reshape(cat.shape[0], -1),
                       p["ip_aux.w"], p["ip_aux.b"])
        loss = (F.cross_entropy(main, y)
                + 0.3 * F.cross_entropy(aux, y))
        return main, loss

    def sgd_step(self, loss, base_lr=0.001, momentum=0.9, wd=0.004):
        grads = torch.autograd.grad(loss, list(self.p.values()))
        with torch.no_grad():
            for (k, v), g in zip(self.p.items(), grads):
                layer, kind = k.split(".")
                lmw, lmb = self.LR_MULTS[layer]
                local_lr = base_lr * (lmw if kind == "w" else lmb)
                g = g + wd * v
                self.hist[k] = local_lr * g + momentum * self.hist[k]
                v -= self.hist[k]


def test_inception_aux_loss_trajectory_tracks_torch(tmp_path):
    """The GoogLeNet mechanics not pinned by any other trajectory test:
    branch fan-out gradient accumulation (one blob feeding several
    consumers), Concat backward slicing, global AVE pooling, and
    multi-head loss_weight combination — per-step total losses and final
    stem weights track an independent torch transcription."""
    n_steps = 60
    netp = load_net_prototxt(INCEPTION_NET)
    sp = load_solver_prototxt_with_net(SOLVER_TXT, netp)
    solver = Solver(sp, seed=0)
    blobs = _export_initial_weights(solver, tmp_path)
    tm = TorchMiniception(blobs)
    rng = np.random.default_rng(23)
    batches = [{
        "data": rng.normal(size=(8, 3, 16, 16)).astype(np.float32),
        "label": rng.integers(0, 10, size=(8,)).astype(np.float32),
    } for _ in range(n_steps)]

    solver.set_train_data(iter(batches))
    ours = []
    for _ in range(n_steps):
        solver.step(1)
        ours.append(solver._smoothed[-1])
    theirs = []
    for b in batches:
        _, loss = tm.forward(torch.tensor(b["data"]),
                             torch.tensor(b["label"], dtype=torch.long))
        tm.sgd_step(loss)
        theirs.append(float(loss))
    np.testing.assert_allclose(ours[:10], theirs[:10], rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(ours, theirs, rtol=1e-2, atol=1e-3)
    # the stem sits behind BOTH heads and all three branches — its final
    # weights agreeing pins the whole fan-out/fan-in gradient flow
    final = dict(_export_initial_weights(solver, tmp_path))
    np.testing.assert_allclose(
        np.asarray(final["stem"][0]), tm.p["stem.w"].detach().numpy(),
        rtol=1e-2, atol=1e-3)


# -- siamese: shared weights + ContrastiveLoss end-to-end --------------------

SIAMESE_NET = """
name: "mini_siamese"
input: "pair_data"
input_shape { dim: 16 dim: 2 dim: 12 dim: 12 }
input: "sim"
input_shape { dim: 16 }
layer { name: "slice_pair" type: "Slice" bottom: "pair_data"
  top: "data" top: "data_p" slice_param { slice_dim: 1 slice_point: 1 } }
layer { name: "conv1" type: "Convolution" bottom: "data" top: "conv1"
  param { name: "conv1_w" lr_mult: 1 } param { name: "conv1_b" lr_mult: 2 }
  convolution_param { num_output: 8 kernel_size: 3 stride: 1
    weight_filler { type: "xavier" } bias_filler { type: "constant" } } }
layer { name: "pool1" type: "Pooling" bottom: "conv1" top: "pool1"
  pooling_param { pool: MAX kernel_size: 2 stride: 2 } }
layer { name: "ip1" type: "InnerProduct" bottom: "pool1" top: "ip1"
  param { name: "ip1_w" lr_mult: 1 } param { name: "ip1_b" lr_mult: 2 }
  inner_product_param { num_output: 16 weight_filler { type: "xavier" }
    bias_filler { type: "constant" } } }
layer { name: "relu1" type: "ReLU" bottom: "ip1" top: "ip1" }
layer { name: "feat" type: "InnerProduct" bottom: "ip1" top: "feat"
  param { name: "feat_w" lr_mult: 1 } param { name: "feat_b" lr_mult: 2 }
  inner_product_param { num_output: 2 weight_filler { type: "xavier" }
    bias_filler { type: "constant" } } }
layer { name: "conv1_p" type: "Convolution" bottom: "data_p" top: "conv1_p"
  param { name: "conv1_w" lr_mult: 1 } param { name: "conv1_b" lr_mult: 2 }
  convolution_param { num_output: 8 kernel_size: 3 stride: 1
    weight_filler { type: "xavier" } bias_filler { type: "constant" } } }
layer { name: "pool1_p" type: "Pooling" bottom: "conv1_p" top: "pool1_p"
  pooling_param { pool: MAX kernel_size: 2 stride: 2 } }
layer { name: "ip1_p" type: "InnerProduct" bottom: "pool1_p" top: "ip1_p"
  param { name: "ip1_w" lr_mult: 1 } param { name: "ip1_b" lr_mult: 2 }
  inner_product_param { num_output: 16 weight_filler { type: "xavier" }
    bias_filler { type: "constant" } } }
layer { name: "relu1_p" type: "ReLU" bottom: "ip1_p" top: "ip1_p" }
layer { name: "feat_p" type: "InnerProduct" bottom: "ip1_p" top: "feat_p"
  param { name: "feat_w" lr_mult: 1 } param { name: "feat_b" lr_mult: 2 }
  inner_product_param { num_output: 2 weight_filler { type: "xavier" }
    bias_filler { type: "constant" } } }
layer { name: "loss" type: "ContrastiveLoss"
  bottom: "feat" bottom: "feat_p" bottom: "sim" top: "loss"
  contrastive_loss_param { margin: 1.0 } }
"""


class TorchSiamese:
    """mnist_siamese transcribed from the reference prototxt
    (examples/siamese/mnist_siamese_train_test.prototxt, shrunk): ONE
    set of weights applied to both slices of the pair — torch autograd
    then sums the two branches' gradients into the shared tensors, which
    is exactly Caffe's AppendParam owner-accumulation (net.cpp) that the
    solver-side trajectory must reproduce."""

    LAYERS = ["conv1", "ip1", "feat"]
    LR_MULTS = {n: (1.0, 2.0) for n in LAYERS}

    def __init__(self, caffemodel_blobs):
        self.p = {}
        self.hist = {}
        for name in self.LAYERS:
            # sharer layers (conv1_p, ...) carry the same blobs; owners
            # are enough
            w, b = caffemodel_blobs[name]
            self.p[name + ".w"] = torch.tensor(np.asarray(w),
                                               requires_grad=True)
            self.p[name + ".b"] = torch.tensor(np.asarray(b),
                                               requires_grad=True)
        for k, v in self.p.items():
            self.hist[k] = torch.zeros_like(v)

    def branch(self, x):
        p = self.p
        h = F.conv2d(x, p["conv1.w"], p["conv1.b"])
        h = F.max_pool2d(h, 2, 2, ceil_mode=True)
        h = F.relu(F.linear(h.reshape(h.shape[0], -1),
                            p["ip1.w"], p["ip1.b"]))
        return F.linear(h, p["feat.w"], p["feat.b"])

    def forward(self, pair, sim):
        a = self.branch(pair[:, :1])
        b = self.branch(pair[:, 1:])
        # contrastive_loss_layer.cpp (non-legacy): y*d^2 +
        # (1-y)*max(margin - d, 0)^2 over 2N; the +1e-12 inside the
        # sqrt mirrors ops/loss.py's guard so gradients match exactly
        d2 = ((a - b) ** 2).sum(dim=1)
        dist = torch.clamp(1.0 - torch.sqrt(d2 + 1e-12), min=0.0)
        loss = (sim * d2 + (1.0 - sim) * dist * dist).sum() / (2.0 * a.shape[0])
        return loss

    def sgd_step(self, loss, base_lr=0.01, momentum=0.9, wd=0.0005):
        grads = torch.autograd.grad(loss, list(self.p.values()))
        with torch.no_grad():
            for (k, v), g in zip(self.p.items(), grads):
                layer, kind = k.split(".")
                lmw, lmb = self.LR_MULTS[layer]
                local_lr = base_lr * (lmw if kind == "w" else lmb)
                g = g + wd * v
                self.hist[k] = local_lr * g + momentum * self.hist[k]
                v -= self.hist[k]


def test_siamese_shared_weight_trajectory_tracks_torch(tmp_path):
    """End-to-end siamese training pin (examples/siamese/): the solver's
    gradient ACCUMULATION through shared blobs — both branches' grads
    summed into the owner before Regularize/momentum, Caffe's
    AppendParam semantics — tracked against torch for 60 steps, weights
    compared at the end."""
    netp = load_net_prototxt(SIAMESE_NET)
    sp = load_solver_prototxt_with_net(
        'base_lr: 0.01\nmomentum: 0.9\nweight_decay: 0.0005\n'
        'lr_policy: "fixed"\n', netp)
    solver = Solver(sp, seed=0)
    tm = TorchSiamese(_export_initial_weights(solver, tmp_path))

    n_steps, B = 60, 16
    rng = np.random.default_rng(9)
    batches = []
    for _ in range(n_steps):
        batches.append({
            "pair_data": rng.normal(
                size=(B, 2, 12, 12)).astype(np.float32),
            "sim": rng.integers(0, 2, size=(B,)).astype(np.float32),
        })
    solver.set_train_data(iter(batches))
    ours = []
    for _ in range(n_steps):
        solver.step(1)
        ours.append(solver._smoothed[-1])
    theirs = []
    for b in batches:
        loss = tm.forward(torch.tensor(b["pair_data"]),
                          torch.tensor(b["sim"]))
        tm.sgd_step(loss)
        theirs.append(float(loss))
    np.testing.assert_allclose(ours[:10], theirs[:10], rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(ours, theirs, rtol=1e-2, atol=1e-3)
    # final shared weights agree -> the two-branch accumulation into the
    # owner matched step for step (the subtlest AppendParam behavior)
    final = dict(_export_initial_weights(solver, tmp_path))
    for name in TorchSiamese.LAYERS:
        np.testing.assert_allclose(
            np.asarray(final[name][0]), tm.p[name + ".w"].detach().numpy(),
            rtol=1e-2, atol=1e-4, err_msg=name)
    # and the sharer layers serialized the same (shared) blobs
    np.testing.assert_array_equal(np.asarray(final["conv1"][0]),
                                  np.asarray(final["conv1_p"][0]))
