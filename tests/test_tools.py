"""Dataset/CLI tool tests: convert_imageset -> compute_image_mean ->
caffe_cli train/test -> extract_features over a tiny generated dataset —
the analog of exercising caffe/tools/*.cpp end to end."""

import json
import os

import numpy as np
import pytest

from sparknet_tpu.data.db import datum_to_array, open_db
from sparknet_tpu.proto.caffemodel import load_mean_binaryproto
from sparknet_tpu.tools import (
    caffe_cli,
    compute_image_mean,
    convert_imageset,
    extract_features,
)


@pytest.fixture(scope="module")
def image_dataset(tmp_path_factory):
    root = tmp_path_factory.mktemp("imgs")
    from PIL import Image
    rng = np.random.default_rng(0)
    lines = []
    for i in range(12):
        arr = rng.integers(0, 256, size=(10, 10, 3)).astype(np.uint8)
        name = f"im{i}.png"
        Image.fromarray(arr).save(str(root / name))
        lines.append(f"{name} {i % 3}")
    listfile = root / "list.txt"
    listfile.write_text("".join(l + "\n" for l in lines))
    return root, listfile


def test_convert_imageset_and_mean(image_dataset, tmp_path):
    root, listfile = image_dataset
    db = str(tmp_path / "db_lmdb")
    rc = convert_imageset.main([str(root), str(listfile), db,
                                "--resize_height", "8",
                                "--resize_width", "8"])
    assert rc == 0
    with open_db(db, "LMDB") as r:
        assert len(r) == 12
        _k, v = r.first()
        img, label = datum_to_array(v)
        assert img.shape == (3, 8, 8)
        assert label == 0

    mean_file = str(tmp_path / "mean.binaryproto")
    assert compute_image_mean.main([db, mean_file]) == 0
    mean = load_mean_binaryproto(mean_file)
    assert mean.shape == (3, 8, 8)
    assert 64 < mean.mean() < 192  # uniform-random pixels


def test_convert_imageset_leveldb(image_dataset, tmp_path):
    root, listfile = image_dataset
    db = str(tmp_path / "db_ldb")
    rc = convert_imageset.main([str(root), str(listfile), db,
                                "--backend", "leveldb",
                                "--resize_height", "8",
                                "--resize_width", "8", "--gray"])
    assert rc == 0
    with open_db(db, "LEVELDB") as r:
        assert len(r) == 12
        img, _ = datum_to_array(r.first()[1])
        assert img.shape == (1, 8, 8)


@pytest.fixture()
def db_net(image_dataset, tmp_path):
    root, listfile = image_dataset
    db = str(tmp_path / "train_lmdb")
    convert_imageset.main([str(root), str(listfile), db,
                           "--resize_height", "8", "--resize_width", "8"])
    model = tmp_path / "net.prototxt"
    model.write_text(f"""
name: "toolnet"
layer {{ name: "data" type: "Data" top: "data" top: "label"
        data_param {{ source: "{db}" batch_size: 4 backend: LMDB }} }}
layer {{ name: "ip" type: "InnerProduct" bottom: "data" top: "ip"
        inner_product_param {{ num_output: 3
                              weight_filler {{ type: "xavier" }} }} }}
layer {{ name: "loss" type: "SoftmaxWithLoss" bottom: "ip" bottom: "label"
        top: "loss" include {{ phase: TRAIN }} }}
layer {{ name: "acc" type: "Accuracy" bottom: "ip" bottom: "label"
        top: "acc" include {{ phase: TEST }} }}
""")
    return tmp_path, model


def test_caffe_cli_train_and_test(db_net, capsys):
    tmp_path, model = db_net
    solver = tmp_path / "solver.prototxt"
    solver.write_text(f"""
net: "{model}"
base_lr: 0.01
momentum: 0.9
lr_policy: "fixed"
max_iter: 6
test_iter: 2
test_interval: 3
snapshot_prefix: "{tmp_path / 'snap'}"
snapshot: 1
""")
    assert caffe_cli.main(["train", "--solver", str(solver)]) == 0
    out = capsys.readouterr().out
    assert "Iteration 6" in out and "Optimization Done." in out
    model_file = str(tmp_path / "snap_iter_6.caffemodel")
    assert os.path.exists(model_file)

    assert caffe_cli.main(["test", "--model", str(model),
                           "--weights", model_file,
                           "--iterations", "2"]) == 0
    out = capsys.readouterr().out
    assert "acc =" in out


@pytest.mark.parametrize("strategy,tau,devices,extra,topo", [
    ("sync", 1, 2, [], "2 devices"),
    ("local_sgd", 2, 2, [], "2 devices"),
    ("hierarchical", 2, 4, ["--hosts", "2"], "2x2 pod"),
])
def test_caffe_cli_train_multi_device(db_net, capsys, strategy, tau,
                                      devices, extra, topo):
    """`caffe train --devices N` routes to DistributedTrainer (the
    `caffe train --gpu 0,1` P2PSync path, caffe/tools/caffe.cpp:81-103,
    208-211), end to end from the CLI on the virtual CPU mesh: DB-backed
    feed fanned out one minibatch per device, loss/test logging, npz
    snapshot.  The hierarchical case drives the composed (host, chip)
    pod from the same flag surface."""
    tmp_path, model = db_net
    solver = tmp_path / f"solver_{strategy}.prototxt"
    solver.write_text(f"""
net: "{model}"
base_lr: 0.01
momentum: 0.9
lr_policy: "fixed"
max_iter: 4
display: 2
test_iter: 2
test_interval: 2
snapshot_prefix: "{tmp_path / ('multi_' + strategy)}"
""")
    args = ["train", "--solver", str(solver),
            "--devices", str(devices), "--strategy", strategy,
            "--tau", str(tau)] + extra
    rc = caffe_cli.main(args)
    assert rc == 0
    out = capsys.readouterr().out
    assert f"Multi-device training: {topo}" in out
    assert f"strategy={strategy}" in out
    assert "loss = " in out and "Optimization Done." in out
    assert "Testing net (#0)" in out and "acc = " in out
    snap = tmp_path / f"multi_{strategy}_iter_4.npz"
    assert snap.exists()

    # resume from the snapshot picks up at iter 4 and finishes cleanly
    solver.write_text(solver.read_text().replace("max_iter: 4",
                                                 "max_iter: 6"))
    rc = caffe_cli.main(args + ["--snapshot", str(snap)])
    assert rc == 0
    out = capsys.readouterr().out
    assert "Resuming from" in out and "(iter 4)" in out


def test_extract_features(db_net, tmp_path, capsys):
    tpath, model = db_net
    solver = tpath / "solver.prototxt"
    solver.write_text(f"""
net: "{model}"
base_lr: 0.01
lr_policy: "fixed"
max_iter: 2
snapshot_prefix: "{tpath / 'ef'}"
snapshot: 1
""")
    caffe_cli.main(["train", "--solver", str(solver)])
    weights = str(tpath / "ef_iter_2.caffemodel")
    feat_db = str(tmp_path / "feat_lmdb")
    rc = extract_features.main([weights, str(model), "ip", feat_db, "2"])
    assert rc == 0
    with open_db(feat_db, "LMDB") as r:
        assert len(r) == 8  # 2 batches x 4
        img, _ = datum_to_array(r.first()[1])
        assert img.shape == (3, 1, 1)


def test_device_query(capsys):
    assert caffe_cli.main(["device_query"]) == 0
    assert "Device kind" in capsys.readouterr().out


def test_upgrade_net_proto_text(tmp_path):
    """V0 prototxt -> upgraded V2 prototxt that parses as new-style and
    builds (upgrade_net_proto_text.cpp analog)."""
    from sparknet_tpu.tools import upgrade_net_proto

    src = tmp_path / "v0.prototxt"
    src.write_text("""
name: "v0"
input: "data"
input_dim: 1 input_dim: 1 input_dim: 8 input_dim: 8
layers { layer { name: "pad" type: "padding" pad: 1 }
         bottom: "data" top: "p" }
layers { layer { name: "c" type: "conv" num_output: 2 kernelsize: 3
                 weight_filler { type: "xavier" } } bottom: "p" top: "c" }
layers { layer { name: "r" type: "relu" } bottom: "c" top: "c" }
""")
    out = tmp_path / "v2.prototxt"
    assert upgrade_net_proto.main([str(src), str(out)]) == 0
    text = out.read_text()
    assert "layers" not in text.replace("layer {", "")  # new-style only
    assert 'type: "Convolution"' in text

    import jax

    from sparknet_tpu.graph import Net
    from sparknet_tpu.proto import load_net_prototxt
    net = Net(load_net_prototxt(str(out)))
    params = net.init(jax.random.PRNGKey(0))
    assert params["c"][0].shape == (2, 1, 3, 3)
    assert net.blob_shapes["c"] == (1, 2, 8, 8)  # pad survived the upgrade


def test_upgrade_net_proto_binary(tmp_path):
    """Binary round-trip preserves weight blobs (upgrade_net_proto_binary)."""
    from sparknet_tpu.proto.caffemodel import (
        load_net_binaryproto,
        save_caffemodel,
    )
    from sparknet_tpu.tools import upgrade_net_proto

    src = str(tmp_path / "w.caffemodel")
    w = np.arange(12, dtype=np.float32).reshape(3, 4)
    save_caffemodel(src, {"ip": [w]})
    out = str(tmp_path / "upgraded.caffemodel")
    assert upgrade_net_proto.main([src, out, "--binary"]) == 0
    net = load_net_binaryproto(out)
    by_name = {l.name: l for l in net.layer}
    np.testing.assert_array_equal(by_name["ip"].blobs[0], w)


def test_upgrade_sniffs_named_caffemodel(tmp_path):
    """A binary NetParameter whose first bytes are the name field
    (b'\\n...' — printable ASCII) must still be detected as binary."""
    from sparknet_tpu.proto.caffemodel import (
        load_net_binaryproto,
        save_caffemodel,
    )
    from sparknet_tpu.tools import upgrade_net_proto

    src = str(tmp_path / "named.caffemodel")
    w = np.ones((2, 2), np.float32)
    save_caffemodel(src, {"ip": [w]}, name="CaffeNet")
    with open(src, "rb") as f:
        assert f.read(1) == b"\n"  # the sniffing trap: looks like text
    out = str(tmp_path / "out.caffemodel")
    assert upgrade_net_proto.main([src, out, "--binary"]) == 0
    net = load_net_binaryproto(out)
    assert net.name == "CaffeNet"


def test_upgrade_preserves_net_state(tmp_path):
    from sparknet_tpu.proto import load_net_prototxt
    from sparknet_tpu.tools import upgrade_net_proto

    src = tmp_path / "s.prototxt"
    src.write_text("""
name: "staged"
state { phase: TEST stage: "deploy" }
layer { name: "d" type: "Input" top: "x"
        input_param { shape { dim: 1 dim: 2 } } }
""")
    out = tmp_path / "out.prototxt"
    assert upgrade_net_proto.main([str(src), str(out)]) == 0
    net = load_net_prototxt(str(out))
    assert net.state.stage == ["deploy"]


def test_classifier_predict(tmp_path):
    """pycaffe Classifier analog: deploy prototxt + caffemodel ->
    center-crop and 10-crop-averaged predictions."""
    from sparknet_tpu.classify import Classifier, oversample

    deploy = tmp_path / "deploy.prototxt"
    deploy.write_text("""
name: "tinydeploy"
layer { name: "data" type: "Input" top: "data"
        input_param { shape { dim: 1 dim: 3 dim: 8 dim: 8 } } }
layer { name: "ip" type: "InnerProduct" bottom: "data" top: "ip"
        inner_product_param { num_output: 4
                              weight_filler { type: "xavier" } } }
layer { name: "prob" type: "Softmax" bottom: "ip" top: "prob" }
""")
    clf = Classifier(str(deploy), image_dims=(10, 10))
    imgs = [np.random.default_rng(i).normal(size=(3, 10, 10)) for i in range(2)]
    probs = clf.predict(imgs, oversample_crops=True)
    assert probs.shape == (2, 4)
    np.testing.assert_allclose(probs.sum(axis=1), 1.0, rtol=1e-4)
    probs_c = clf.predict(imgs, oversample_crops=False)
    assert probs_c.shape == (2, 4)

    crops = oversample(np.stack([np.asarray(i, np.float32) for i in imgs]), 8)
    assert crops.shape == (20, 3, 8, 8)
    # crop 4 is the center crop; crop 9 is its mirror
    np.testing.assert_allclose(crops[4 * 2], crops[9 * 2][:, :, ::-1])


def test_draw_net(tmp_path):
    from sparknet_tpu.tools import draw_net

    net = tmp_path / "net.prototxt"
    net.write_text("""
name: "toy"
layer { name: "data" type: "Input" top: "data"
        input_param { shape { dim: 1 dim: 3 dim: 8 dim: 8 } } }
layer { name: "conv" type: "Convolution" bottom: "data" top: "conv"
        convolution_param { num_output: 2 kernel_size: 3
                            weight_filler { type: "xavier" } } }
layer { name: "relu" type: "ReLU" bottom: "conv" top: "conv" }
""")
    out = tmp_path / "net.dot"
    assert draw_net.main([str(net), str(out)]) == 0
    dot = out.read_text()
    assert dot.startswith('digraph "toy"')
    assert '"L_conv"' in dot and '"B_data" -> "L_conv"' in dot
    assert "kernel 3" in dot
    assert dot.count("{") == dot.count("}")


def test_detector_windows(tmp_path):
    from sparknet_tpu.classify import Detector

    deploy = tmp_path / "det.prototxt"
    deploy.write_text("""
layer { name: "data" type: "Input" top: "data"
        input_param { shape { dim: 1 dim: 3 dim: 8 dim: 8 } } }
layer { name: "ip" type: "InnerProduct" bottom: "data" top: "ip"
        inner_product_param { num_output: 3
                              weight_filler { type: "xavier" } } }
layer { name: "prob" type: "Softmax" bottom: "ip" top: "prob" }
""")
    det = Detector(str(deploy), context_pad=2)
    img = np.random.default_rng(0).normal(size=(3, 32, 32)).astype(np.float32)
    out = det.detect_windows([(img, [(0, 0, 15, 15), (8, 8, 31, 31)])])
    assert len(out) == 2
    assert out[0]["window"] == (0, 0, 15, 15)
    assert out[0]["prediction"].shape == (3,)
    np.testing.assert_allclose(out[0]["prediction"].sum(), 1.0, rtol=1e-4)


def test_classifier_crop_sized_mean(tmp_path):
    """pycaffe-style mean arrays are net-input (crop) sized; subtraction
    must happen per-crop, not at image_dims (Transformer.set_mean)."""
    from sparknet_tpu.classify import Classifier, Detector

    deploy = tmp_path / "m.prototxt"
    deploy.write_text("""
layer { name: "data" type: "Input" top: "data"
        input_param { shape { dim: 1 dim: 3 dim: 8 dim: 8 } } }
layer { name: "ip" type: "InnerProduct" bottom: "data" top: "ip"
        inner_product_param { num_output: 2
                              weight_filler { type: "xavier" } } }
layer { name: "prob" type: "Softmax" bottom: "ip" top: "prob" }
""")
    mean = np.ones((3, 8, 8), np.float32) * 7  # crop-sized, pycaffe-style
    clf = Classifier(str(deploy), image_dims=(12, 12), mean=mean)
    img = np.random.default_rng(0).normal(size=(3, 12, 12))
    probs = clf.predict([img], oversample_crops=True)
    assert probs.shape == (1, 2)

    # detector: crop-sized mean + border-clipped window + grayscale->RGB-ish
    det = Detector(str(deploy), mean=mean, context_pad=2)
    gray = np.random.default_rng(1).normal(size=(20, 20))  # 2-D image
    out = det.detect_windows([(np.tile(gray[None], (3, 1, 1)),
                               [(0, 0, 10, 10)])])
    assert out[0]["prediction"].shape == (2,)


def test_bench_cpu_smoke(tmp_path):
    """bench.py must emit exactly one valid JSON line on stdout with the
    documented schema — the contract the benchmark driver consumes."""
    import subprocess
    import sys
    env = dict(os.environ,
               BENCH_PLATFORM="cpu", BENCH_MODEL="lenet", BENCH_BATCH="4",
               BENCH_ITERS="1", BENCH_REPS="1", BENCH_WINDOWS="1",
               BENCH_DTYPE="f32", BENCH_FEED_ITERS="2",
               BENCH_FEED_BATCH="8",
               BENCH_ATTEMPTS="1", BENCH_TIMEOUT_S="280",
               BENCH_ROUND="0",  # the round leg has its own gate (roundbench)
               BENCH_SERVING="0",  # as does serving (servesmoke)
               BENCH_FUSE="off")  # and vertical fusion (fusebench)
    env.pop("XLA_FLAGS", None)  # conftest's 8-device flag slows the child
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run([sys.executable, os.path.join(root, "bench.py")],
                          capture_output=True, timeout=300, cwd=root, env=env)
    assert proc.returncode == 0, proc.stderr.decode()[-800:]
    lines = proc.stdout.decode().strip().splitlines()
    assert len(lines) == 1, lines
    result = json.loads(lines[0])
    assert result["metric"] == "lenet_train_images_per_sec"
    assert result["value"] > 0
    assert result["dtype"] == "f32"
    assert result["by_dtype"]["f32"]["images_per_sec"] == result["value"]
    feed = result["feed_in_loop"]
    assert feed["images_per_sec"] > 0 and "overlap_pct" in feed
    # the three legs are measured at the same (overridden) batch and are
    # mutually consistent: 0 <= overlap <= 100 and the in-loop step can't
    # beat a perfect pipeline by more than timer noise
    assert feed["batch"] == 8
    assert feed["feed_alone_s_per_batch"] > 0
    assert feed["compute_s_per_step"] > 0
    assert 0.0 <= feed["overlap_pct"] <= 100.0
    assert feed["bound"] in ("feed", "compute")
    assert feed["feed_compute_ratio"] > 0
    assert feed["step_s"] > 0.25 * max(feed["feed_alone_s_per_batch"],
                                       feed["compute_s_per_step"])


def test_bench_feed_overlap_nondegenerate(tmp_path):
    """The prefetch pipeline must MEASURABLY overlap feed and compute in
    the non-degenerate regime (round-3 verdict: 'measured, not
    asserted').  BENCH_FEED_DELAY_S injects a deterministic per-batch
    host cost (decode stand-in) that dominates this platform's compute,
    so the verdict is pinned: in-loop total must land near
    max(feed, compute), well under serial feed+compute — i.e. the
    producer thread genuinely hides its work behind the step."""
    import subprocess
    import sys
    delay = 0.15
    env = dict(os.environ,
               BENCH_PLATFORM="cpu", BENCH_MODEL="lenet", BENCH_BATCH="4",
               BENCH_ITERS="1", BENCH_REPS="1", BENCH_WINDOWS="1",
               BENCH_DTYPE="f32", BENCH_FEED_ITERS="6",
               BENCH_FEED_BATCH="16", BENCH_FEED_DELAY_S=str(delay),
               BENCH_ATTEMPTS="1", BENCH_TIMEOUT_S="280",
               BENCH_ROUND="0",  # the round leg has its own gate (roundbench)
               BENCH_SERVING="0",  # as does serving (servesmoke)
               BENCH_FUSE="off")  # and vertical fusion (fusebench)
    env.pop("XLA_FLAGS", None)
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run([sys.executable, os.path.join(root, "bench.py")],
                          capture_output=True, timeout=300, cwd=root, env=env)
    assert proc.returncode == 0, proc.stderr.decode()[-800:]
    feed = json.loads(proc.stdout.decode().strip().splitlines()[-1]
                      )["feed_in_loop"]
    fa, cs, tot = (feed["feed_alone_s_per_batch"],
                   feed["compute_s_per_step"], feed["step_s"])
    # the injected delay dominates: this IS the feed-bound non-degenerate
    # regime (compute nonzero but smaller)
    assert fa >= delay and cs < fa, feed
    # overlap verdict: total ≈ max(fa, cs), not fa + cs.  Slack covers
    # CI timer noise; a synchronous feed (total = fa + cs) must fail.
    assert tot < fa + 0.5 * cs, feed
    assert tot < 1.35 * fa, feed
    assert feed["bound"] == "feed"


def test_bench_rejects_bad_dtype():
    import subprocess
    import sys
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, os.path.join(root, "bench.py")],
        capture_output=True, timeout=60, cwd=root,
        env=dict(os.environ, BENCH_DTYPE="fp32"))
    assert proc.returncode == 2
    assert b"BENCH_DTYPE" in proc.stderr


def test_time_net_runs_and_trace_degrades(capsys):
    """time_net whole-net timing works on CPU; --trace degrades gracefully
    when the platform has no device plane (TPU feature)."""
    from sparknet_tpu.tools import time_net
    time_net.main(["--model", "lenet", "--batch", "4", "--iterations", "1",
                   "--trace"])
    out = capsys.readouterr().out
    assert "Average Forward-Backward" in out
    assert ("Per-layer device time" in out      # TPU/GPU rig
            or "layer scopes" in out            # captured, no device plane
            or "device plane" in out)           # no plane at all


def test_caffe_cli_resolves_test_net_files(tmp_path):
    """`test_net:` file references load into test_net_param (the
    Solver::InitTestNets path), alongside `net:` resolution."""
    (tmp_path / "train.prototxt").write_text("""
layer { name: "data" type: "DummyData" top: "data" top: "label"
  dummy_data_param { shape { dim: 4 dim: 3 } shape { dim: 4 } } }
layer { name: "ip" type: "InnerProduct" bottom: "data" top: "ip"
  inner_product_param { num_output: 2 weight_filler { type: "xavier" } } }
layer { name: "loss" type: "SoftmaxWithLoss" bottom: "ip" bottom: "label" }
""")
    (tmp_path / "test.prototxt").write_text("""
layer { name: "data" type: "DummyData" top: "data" top: "label"
  dummy_data_param { shape { dim: 2 dim: 3 } shape { dim: 2 } } }
layer { name: "ip" type: "InnerProduct" bottom: "data" top: "ip"
  inner_product_param { num_output: 2 weight_filler { type: "xavier" } } }
layer { name: "loss" type: "SoftmaxWithLoss" bottom: "ip" bottom: "label" }
""")
    solver_path = tmp_path / "solver.prototxt"
    solver_path.write_text('train_net: "train.prototxt"\n'
                           'test_net: "test.prototxt"\n'
                           'base_lr: 0.1\ntest_iter: 1\n')
    from sparknet_tpu.proto import load_solver_prototxt
    from sparknet_tpu.solvers import Solver
    from sparknet_tpu.tools.caffe_cli import _resolve_solver_net
    sp = load_solver_prototxt(str(solver_path))
    _resolve_solver_net(sp, str(solver_path))
    assert len(sp.test_net_param) == 1
    solver = Solver(sp, seed=0)
    # dedicated test net: batch 2, not the train net's 4
    scores = solver.test(1)
    assert "loss" in scores
    assert solver.test_net.blob_shapes["data"] == (2, 3)


def test_parse_log_roundtrip(tmp_path, capsys):
    """parse_log (tools/extra/parse_log.py analog) splits a real solve()
    log into train/test CSVs."""
    import contextlib
    import csv
    import io as _io

    from sparknet_tpu.proto import load_solver_prototxt_with_net, \
        load_net_prototxt
    from sparknet_tpu.solvers import Solver
    from sparknet_tpu.tools.parse_log import parse_log, write_csvs

    netp = load_net_prototxt("""
layer { name: "data" type: "DummyData" top: "data" top: "label"
  dummy_data_param { shape { dim: 4 dim: 3 } shape { dim: 4 }
    data_filler { type: "gaussian" std: 1.0 }
    data_filler { type: "constant" value: 1.0 } } }
layer { name: "ip" type: "InnerProduct" bottom: "data" top: "ip"
  inner_product_param { num_output: 2 weight_filler { type: "xavier" } } }
layer { name: "loss" type: "SoftmaxWithLoss" bottom: "ip" bottom: "label" }
layer { name: "acc" type: "Accuracy" bottom: "ip" bottom: "label"
  top: "accuracy" include { phase: TEST } }
""")
    sp = load_solver_prototxt_with_net(
        "base_lr: 0.1\nmax_iter: 6\ndisplay: 2\ntest_interval: 3\n"
        "test_iter: 2\ntest_initialization: true\n", netp)
    solver = Solver(sp, seed=0)
    buf = _io.StringIO()
    with contextlib.redirect_stdout(buf):
        solver.solve()
    log = tmp_path / "train.log"
    log.write_text(buf.getvalue())

    train, test = parse_log(str(log))
    iters = [it for it, _ in train]
    assert 6 in iters and all(np.isfinite(l) for _, l in train)
    assert (0, 0) in test          # test_initialization pass at iter 0
    assert any(it == 6 for it, _ in test)  # final pass
    assert all("accuracy" in row and "loss" in row
               for row in test.values())

    tr_path, te_path = write_csvs(str(log), str(tmp_path))
    rows = list(csv.reader(open(tr_path)))
    assert rows[0] == ["NumIters", "Seconds", "LearningRate", "loss"]
    assert len(rows) > 1
    # glog timestamps + lr lines are emitted by the Solver now: every
    # train row carries Seconds (monotone from 0) and LearningRate
    secs = [float(r[1]) for r in rows[1:]]
    assert secs == sorted(secs) and secs[0] >= 0.0
    assert all(float(r[2]) == 0.1 for r in rows[1:])  # base_lr, fixed
    te_rows = list(csv.reader(open(te_path)))
    assert te_rows[0][:3] == ["NumIters", "Seconds", "TestNet"]
    assert "accuracy" in te_rows[0]
    assert all(r[1] != "" for r in te_rows[1:])

    # all 8 reference chart types render from this real log
    # (plot_training_log.py.example supported_chart_types)
    from sparknet_tpu.tools.plot_training_log import main as plot_main
    for ct in range(8):
        out = tmp_path / f"chart{ct}.png"
        assert plot_main([str(ct), str(out), str(log)]) == 0
        assert out.stat().st_size > 1000


def test_parse_log_resume_and_inf(tmp_path):
    """Scores printed by a pre-training test pass on RESUME key to the
    solver's iteration (via the 'Testing net' marker), and inf/nan
    losses parse instead of crashing."""
    from sparknet_tpu.tools.parse_log import parse_log

    log = tmp_path / "resume.log"
    log.write_text(
        "Iteration 300, Testing net (#0)\n"
        "    Test net output: accuracy = 0.75\n"
        "Iteration 302, loss = -inf\n"
        "Iteration 304, loss = nan\n"
        "Iteration 304, Testing net (#1)\n"
        "    Test net output: loss = 1e+30\n")
    train, test = parse_log(str(log))
    assert train[0] == (302, float("-inf"))
    assert np.isnan(train[1][1])
    assert test[(300, 0)]["accuracy"] == 0.75
    assert test[(304, 1)]["loss"] == 1e30


def test_parse_log_non_leap_feb28_mar1_span(tmp_path):
    """Regression (ADVICE.md): _glog_seconds used a FIXED leap year
    (2024) for day-of-year, so a non-leap-year log spanning
    Feb 28 → Mar 1 gained a phantom Feb 29: +86400 s.  The year now
    comes from the log's mtime and deltas from full datetimes."""
    import calendar
    import datetime
    import os as _os

    from sparknet_tpu.tools.parse_log import parse_log

    log = tmp_path / "wrap.log"
    log.write_text(
        "I0228 23:59:50.000000  1 solver.py:1] Iteration 0, loss = 1.0\n"
        "I0301 00:00:10.000000  1 solver.py:1] Iteration 2, loss = 0.9\n")
    # pin the file into a non-leap year (the log "was written" then)
    mt = datetime.datetime(2025, 3, 1, 1, 0, 0).timestamp()
    _os.utime(log, (mt, mt))
    train, _ = parse_log(str(log))
    deltas = [row.seconds for row in train]
    assert deltas == [0.0, 20.0]   # was 86420.0 with the 2024 anchor

    # a leap-year log keeps its real Feb 29: same stamps, 2024 mtime
    mt = datetime.datetime(2024, 3, 1, 1, 0, 0).timestamp()
    _os.utime(log, (mt, mt))
    train, _ = parse_log(str(log))
    assert [row.seconds for row in train] == [0.0, 86420.0]

    # Feb 29 stamps in a log whose mtime landed in a later, non-leap
    # year (copied file) walk back to the nearest leap year, not crash
    leap = tmp_path / "leap.log"
    leap.write_text(
        "I0229 10:00:00.000000  1 solver.py:1] Iteration 0, loss = 1.0\n"
        "I0301 10:00:00.000000  1 solver.py:1] Iteration 2, loss = 0.9\n")
    _os.utime(leap, (mt + 370 * 86400, mt + 370 * 86400))  # 2025 mtime
    train, _ = parse_log(str(leap))
    assert [row.seconds for row in train] == [0.0, 86400.0]

    # new-year wrap: Dec 31 → Jan 1 is one day, leap or not
    wrap = tmp_path / "newyear.log"
    wrap.write_text(
        "I1231 23:59:00.000000  1 solver.py:1] Iteration 0, loss = 1.0\n"
        "I0101 00:01:00.000000  1 solver.py:1] Iteration 2, loss = 0.9\n")
    mt = datetime.datetime(2026, 1, 1, 2, 0, 0).timestamp()
    _os.utime(wrap, (mt, mt))
    train, _ = parse_log(str(wrap))
    assert [row.seconds for row in train] == [0.0, 120.0]
    assert not calendar.isleap(2025) and not calendar.isleap(2026)


def test_plot_training_log(tmp_path):
    """plot_training_log (tools/extra analog): charts parse_log output;
    unsupported Seconds/lr chart types refuse clearly."""
    from sparknet_tpu.tools.plot_training_log import main, plot

    log = tmp_path / "t.log"
    log.write_text(
        "Iteration 0, Testing net (#0)\n"
        "    Test net output: accuracy = 0.1\n"
        "    Test net output: loss = 2.3\n"
        "Iteration 2, loss = 2.0\n"
        "Iteration 4, loss = 1.5\n"
        "Iteration 4, Testing net (#0)\n"
        "    Test net output: accuracy = 0.6\n"
        "    Test net output: loss = 1.4\n")
    for ct, name in ((0, "acc.png"), (2, "tloss.png"), (6, "loss.png")):
        out = tmp_path / name
        assert main([str(ct), str(out), str(log)]) == 0
        assert out.stat().st_size > 1000  # a real png
    # a log with no glog timestamps / lr lines refuses the Seconds and
    # LearningRate chart types with a clear message
    with pytest.raises(ValueError, match="timestamp"):
        plot(1, str(tmp_path / "x.png"), [str(log)])
    with pytest.raises(ValueError, match="lr"):
        plot(4, str(tmp_path / "x.png"), [str(log)])
    with pytest.raises(ValueError, match="unknown chart type"):
        plot(9, str(tmp_path / "x.png"), [str(log)])


DEPLOY_NET = """
name: "deploy"
input: "data"
input_shape { dim: 1 dim: 3 dim: 8 dim: 8 }
layer { name: "conv" type: "Convolution" bottom: "data" top: "conv"
  convolution_param { num_output: 4 kernel_size: 3 pad: 1 stride: 2
    weight_filler { type: "xavier" } } }
layer { name: "ip" type: "InnerProduct" bottom: "conv" top: "ip"
  inner_product_param { num_output: 3 weight_filler { type: "xavier" } } }
layer { name: "prob" type: "Softmax" bottom: "ip" top: "prob" }
"""


def test_classify_cli(tmp_path):
    """classify CLI (python/classify.py analog): image dir and npy
    inputs -> probability npy; channel_swap honored."""
    from PIL import Image

    from sparknet_tpu.tools import classify_cli

    model = tmp_path / "deploy.prototxt"
    model.write_text(DEPLOY_NET)
    rng = np.random.default_rng(0)
    imgdir = tmp_path / "imgs"
    imgdir.mkdir()
    for i in range(3):
        Image.fromarray(rng.integers(0, 256, size=(10, 12, 3)
                                     ).astype(np.uint8)).save(
            str(imgdir / f"im{i}.jpg"))
    out = tmp_path / "probs.npy"
    rc = classify_cli.main([str(imgdir), str(out),
                            "--model_def", str(model),
                            "--images_dim", "8,8", "--center_only"])
    assert rc == 0
    probs = np.load(out)
    assert probs.shape == (3, 3)
    np.testing.assert_allclose(probs.sum(1), 1.0, rtol=1e-4)

    # npy input path + oversampling
    batch = rng.uniform(size=(2, 10, 10, 3)).astype(np.float32)
    npy_in = tmp_path / "batch.npy"
    np.save(npy_in, batch)
    out2 = tmp_path / "probs2.npy"
    assert classify_cli.main([str(npy_in), str(out2),
                              "--model_def", str(model),
                              "--images_dim", "10,10"]) == 0
    assert np.load(out2).shape == (2, 3)


def test_classifier_channel_swap(tmp_path):
    """channel_swap permutes channels before scaling: swapping the input
    channels and un-swapping via the flag gives identical predictions."""
    from sparknet_tpu.classify import Classifier

    model = tmp_path / "deploy.prototxt"
    model.write_text(DEPLOY_NET)
    rng = np.random.default_rng(1)
    img = rng.uniform(size=(8, 8, 3)).astype(np.float32)
    base = Classifier(str(model), image_dims=(8, 8))
    swapped = Classifier(str(model), image_dims=(8, 8),
                         channel_swap=(2, 1, 0))
    p1 = base.predict([img], oversample_crops=False)
    p2 = swapped.predict([img[:, :, ::-1]], oversample_crops=False)
    np.testing.assert_allclose(p1, p2, rtol=1e-5, atol=1e-6)


def test_detect_cli(tmp_path):
    """detect CLI (python/detect.py analog, crop_mode=list): window CSV
    in, per-window class scores CSV out."""
    import csv as _csv

    from PIL import Image

    from sparknet_tpu.tools import detect_cli

    model = tmp_path / "deploy.prototxt"
    model.write_text(DEPLOY_NET)
    rng = np.random.default_rng(2)
    img_path = tmp_path / "scene.jpg"
    Image.fromarray(rng.integers(0, 256, size=(24, 24, 3)
                                 ).astype(np.uint8)).save(str(img_path))
    wins = tmp_path / "windows.csv"
    wins.write_text(
        "filename,ymin,xmin,ymax,xmax\n"
        f"{img_path},0,0,12,12\n"
        f"{img_path},8,8,24,24\n")
    out = tmp_path / "dets.csv"
    rc = detect_cli.main([str(wins), str(out), "--model_def", str(model),
                          "--context_pad", "2"])
    assert rc == 0
    rows = list(_csv.reader(open(out)))
    assert rows[0] == ["filename", "ymin", "xmin", "ymax", "xmax",
                       "class0", "class1", "class2"]
    assert len(rows) == 3
    scores = np.asarray([[float(v) for v in r[5:]] for r in rows[1:]])
    np.testing.assert_allclose(scores.sum(1), 1.0, rtol=1e-4)


def test_detector_channel_swap_and_vector_mean(tmp_path):
    """detect path honors channel_swap (swap+unswap is identity) and a
    per-channel vector mean broadcasts on the channel axis."""
    from sparknet_tpu.classify import Detector

    model = tmp_path / "deploy.prototxt"
    model.write_text(DEPLOY_NET)
    rng = np.random.default_rng(3)
    img = rng.uniform(size=(3, 16, 16)).astype(np.float32)
    wins = [(0, 0, 8, 8)]
    base = Detector(str(model), mean=np.array([0.1, 0.2, 0.3]
                                              ).reshape(3, 1, 1))
    swapped = Detector(str(model), channel_swap=(2, 1, 0),
                       mean=np.array([0.1, 0.2, 0.3]).reshape(3, 1, 1))
    p1 = base.detect_windows([(img, wins)])[0]["prediction"]
    p2 = swapped.detect_windows([(img[::-1], wins)])[0]["prediction"]
    np.testing.assert_allclose(p1, p2, rtol=1e-5, atol=1e-6)


def test_caffe_cli_multi_device_weights_and_errors(db_net, capsys):
    """--devices finetune path (--weights from a single-device
    .caffemodel) plus the clean-error contracts: non-integer --devices,
    .solverstate resume rejection, distributed flags without --devices."""
    tmp_path, model = db_net
    solver = tmp_path / "solver_w.prototxt"
    solver.write_text(f"""
net: "{model}"
base_lr: 0.01
lr_policy: "fixed"
max_iter: 2
snapshot_prefix: "{tmp_path / 'seed'}"
snapshot: 1
""")
    assert caffe_cli.main(["train", "--solver", str(solver)]) == 0
    capsys.readouterr()
    weights = tmp_path / "seed_iter_2.caffemodel"
    state = tmp_path / "seed_iter_2.solverstate"
    assert weights.exists() and state.exists()

    rc = caffe_cli.main(["train", "--solver", str(solver),
                         "--devices", "2", "--weights", str(weights)])
    assert rc == 0
    out = capsys.readouterr().out
    assert "Finetuning from" in out and "Optimization Done." in out

    with pytest.raises(SystemExit, match="integer or 'all'"):
        caffe_cli.main(["train", "--solver", str(solver),
                        "--devices", "two"])
    with pytest.raises(SystemExit, match="solverstate"):
        caffe_cli.main(["train", "--solver", str(solver),
                        "--devices", "2", "--snapshot", str(state)])
    with pytest.raises(SystemExit, match="require --devices"):
        caffe_cli.main(["train", "--solver", str(solver),
                        "--strategy", "local_sgd"])


def test_plot_learning_proxy_renders_png(tmp_path):
    """The paper's headline figure renders from a RESULTS JSON — per-row
    wall_s when present, else a linear reconstruction from the curve
    total, and corrupt walls are dropped rather than plotted wrong."""
    import subprocess
    import sys
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    curve = [{"iter": i, "lr": 0.001, "train_loss": 1.0,
              "train_acc": 0.5 + 0.04 * n, "test_acc": 0.4 + 0.04 * n}
             for n, i in enumerate(range(100, 1100, 100))]
    rows_with_wall = [dict(r, wall_s=2.0 * n + 1)
                      for n, r in enumerate(curve)]
    results = {
        "config": {"scale": 10, "max_iter": 1000,
                   "stepvalues": [600, 800], "batch": 100},
        "device": "cpu/test",
        "curve_1x": rows_with_wall,          # per-row wall: used as-is
        "curve_8way": curve,                 # no rows: reconstructed
        "curve_hier": curve,                 # corrupt total: dropped
        "final": {"acc_1x": 0.8, "acc_8way": 0.76, "acc_hier": 0.75,
                  "wall_s_1x": 99.0, "wall_s_8way": 50.0,
                  "wall_s_hier": 0.1},
    }
    src = tmp_path / "r.json"
    src.write_text(json.dumps(results))
    out = tmp_path / "r.png"
    proc = subprocess.run(
        [sys.executable, os.path.join(root, "tools",
                                      "plot_learning_proxy.py"),
         "--in", str(src), "--out", str(out)],
        capture_output=True, timeout=120)
    assert proc.returncode == 0, proc.stderr.decode()
    assert out.exists() and out.stat().st_size > 10_000
    verdict = json.loads(proc.stdout.decode().strip().splitlines()[-1])
    assert verdict["synthesized_wall"] == ["8way"]
    assert verdict["dropped"] == ["hierarchical 2×4"]
