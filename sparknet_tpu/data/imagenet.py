"""ImageNet-style loader: tar archives of JPEGs + a filename→label map.

The analog of the reference's S3 loader chain (reference:
src/main/scala/loaders/ImageNetLoader.scala — list tar objects :25-38, read
the ``train.txt`` label map :41-54, workers stream-untar JPEG bytes :56-86,
``apply`` :91 yielding (bytes, label) pairs) followed by decode/force-resize
(reference: src/main/scala/preprocessing/ScaleAndConvert.scala:16-27, with
undecodable images silently dropped :23-25).

Sources are local paths or directories (the cluster data plane ships bytes
to hosts; S3/GCS staging is the launcher's job, as EC2 scripts were for the
reference).  Decode runs through the native C++ pipeline
(sparknet_tpu.native.decode_jpeg_resize) with a PIL fallback.
"""

from __future__ import annotations

import os
import tarfile
from typing import Iterator

import numpy as np

from .. import native
from .partition import PartitionedDataset


def read_label_map(path: str) -> dict[str, int]:
    """Parse a ``train.txt``-style "filename label" map
    (ImageNetLoader.getLabels, reference: ImageNetLoader.scala:41-54)."""
    labels: dict[str, int] = {}
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            name, lab = line.rsplit(None, 1)
            labels[os.path.basename(name)] = int(lab)
    return labels


def list_tars(root: str, prefix: str = "") -> list[str]:
    """All .tar files under ``root`` matching the key prefix
    (ImageNetLoader.getFilePathsRDD, reference: ImageNetLoader.scala:25-38)."""
    out = []
    for dirpath, _, files in os.walk(root):
        for f in sorted(files):
            if f.endswith(".tar"):
                rel = os.path.relpath(os.path.join(dirpath, f), root)
                if rel.startswith(prefix):
                    out.append(os.path.join(dirpath, f))
    return sorted(out)


def stream_tar_images(tar_path: str, labels: dict[str, int],
                      ) -> Iterator[tuple[bytes, int]]:
    """Stream (jpeg bytes, label) from one tar
    (ImageNetLoader.loadImagesFromTar, reference: ImageNetLoader.scala:56-86).
    Entries missing from the label map are skipped."""
    with tarfile.open(tar_path) as tf:
        for member in tf:
            if not member.isfile():
                continue
            name = os.path.basename(member.name)
            if name not in labels:
                continue
            f = tf.extractfile(member)
            if f is None:
                continue
            yield f.read(), labels[name]


def decode_and_resize(pairs: Iterator[tuple[bytes, int]], size: int = 256,
                      ) -> Iterator[tuple[np.ndarray, int]]:
    """JPEG → planar f32 (3, size, size), force-resize; undecodable images
    dropped (ScaleAndConvert semantics)."""
    for data, label in pairs:
        img = native.decode_jpeg_resize(data, size, size)
        if img is not None:
            yield img, label


def load_imagenet(tar_root: str, label_file: str, num_partitions: int,
                  size: int = 256, prefix: str = "") -> PartitionedDataset:
    """Full chain: tars → (bytes, label) → decoded images, sharded into
    partitions (ImageNetLoader.apply + ScaleAndConvert.makeMinibatchRDD's
    decode half, reference: ImageNetLoader.scala:91)."""
    labels = read_label_map(label_file)
    items = []
    total = 0
    for tar in list_tars(tar_root, prefix):
        for pair in stream_tar_images(tar, labels):
            total += 1
            for decoded in decode_and_resize(iter([pair]), size):
                items.append(decoded)
    if total and not items:
        raise RuntimeError(
            f"all {total} images failed to decode — the JPEG decode layer "
            f"(native libjpeg / PIL fallback) is unavailable or broken, "
            f"not the data")
    if not total:
        raise FileNotFoundError(
            f"no labeled images found under {tar_root!r} "
            f"(labels: {len(labels)} entries)")
    return PartitionedDataset.from_items(items, num_partitions, shuffle=True)
