"""Sync-vs-async outer-loop parity microbench (the round_overhead gate).

The zero-stall outer loop (TrainerConfig.harvest_lag round pipelining +
the AsyncCheckpointWriter) must be a pure LATENCY optimization: with
checkpointing + numerics guard + cross-replica audit all enabled, the
async loop has to produce exactly the same round losses, bit-identical
final parameters, and byte-identical newest checkpoint content as the
synchronous loop.  This tool runs both loops on a small CPU mesh
(~seconds), FAILS on any divergence, and reports the per-component host
stall seconds (loss_fetch / finite_check / audit_fetch / checkpoint)
for each mode — the same accounting bench.py's ``round_overhead`` leg
captures on the real chip.

Wired into tools/run_tier1.sh behind SPARKNET_ROUNDBENCH=1 (or
``--roundbench``); also exercised in-process by tests/test_resilience.py.

Usage:
    python tools/roundbench.py [--rounds 6] [--lag 2] [--devices 4]
        [--out FILE]

Prints one JSON line on stdout; rc 0 = parity holds.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rounds", type=int, default=6)
    ap.add_argument("--lag", type=int, default=2,
                    help="harvest_lag / pipeline depth of the async loop")
    ap.add_argument("--devices", type=int, default=4,
                    help="CPU mesh width (virtual devices)")
    ap.add_argument("--batch", type=int, default=16)
    ap.add_argument("--out", default=None)
    args = ap.parse_args(argv)

    import jax
    import numpy as np

    from sparknet_tpu.models import lenet
    from sparknet_tpu.parallel import (
        DistributedTrainer, TrainerConfig, make_mesh,
    )
    from sparknet_tpu.proto import load_solver_prototxt_with_net
    from sparknet_tpu.utils.checkpoint import load_checkpoint

    tau = 2
    sp = load_solver_prototxt_with_net(
        'base_lr: 0.005\nmomentum: 0.9\nlr_policy: "fixed"\n',
        lenet(args.batch, args.batch))

    def batch(r):
        rng = np.random.default_rng(4200 + r)
        return {"data": rng.normal(size=(tau, args.batch, 1, 28, 28)
                                   ).astype(np.float32),
                "label": rng.integers(0, 10, size=(tau, args.batch)
                                      ).astype(np.float32)}

    def run(mode: str, ckdir: str) -> dict:
        from sparknet_tpu.utils import knobs
        saved = knobs.raw("SPARKNET_ASYNC_CKPT")
        os.environ["SPARKNET_ASYNC_CKPT"] = "1" if mode == "async" else "0"
        try:
            cfg = TrainerConfig(
                strategy="local_sgd", tau=tau, checkpoint_dir=ckdir,
                checkpoint_keep=4, guard_numerics=True, audit_every=1,
                harvest_lag=args.lag if mode == "async" else 0)
            tr = DistributedTrainer(sp, make_mesh(args.devices), cfg,
                                    seed=0)
            t0 = time.perf_counter()
            while tr.round < args.rounds:
                tr.train_round(batch(tr.round))
            losses = tr.drain()
            dt = time.perf_counter() - t0
        finally:
            if saved is None:
                os.environ.pop("SPARKNET_ASYNC_CKPT", None)
            else:
                os.environ["SPARKNET_ASYNC_CKPT"] = saved
        newest = sorted(f for f in os.listdir(ckdir)
                        if f.endswith(".npz"))[-1]
        return {
            "losses": [losses[r] for r in range(args.rounds)],
            "params": {k: [np.asarray(b) for b in v]
                       for k, v in tr.params.items()},
            "newest_ckpt": newest,
            "ckpt_blob": load_checkpoint(os.path.join(ckdir, newest)),
            "wall_s": round(dt, 3),
            "stall_s": {k: round(v, 4) for k, v in tr.stall_s.items()},
        }

    failures: list[str] = []
    with tempfile.TemporaryDirectory() as d_sync, \
            tempfile.TemporaryDirectory() as d_async:
        sync = run("sync", d_sync)
        async_ = run("async", d_async)

    if sync["losses"] != async_["losses"]:
        failures.append(f"round losses diverge: sync {sync['losses']} "
                        f"vs async {async_['losses']}")
    for name, blobs in sync["params"].items():
        for i, b in enumerate(blobs):
            if not np.array_equal(b, async_["params"][name][i]):
                failures.append(f"param {name}[{i}] not bit-identical")
    if sync["newest_ckpt"] != async_["newest_ckpt"]:
        failures.append(f"newest checkpoint differs: "
                        f"{sync['newest_ckpt']} vs {async_['newest_ckpt']}")
    else:
        for key in ("params", "state", "iter", "round", "rng"):
            a = jax.tree_util.tree_leaves(sync["ckpt_blob"][key])
            b = jax.tree_util.tree_leaves(async_["ckpt_blob"][key])
            if len(a) != len(b) or any(
                    not np.array_equal(x, y) for x, y in zip(a, b)):
                failures.append(f"checkpoint field {key!r} not "
                                f"bit-identical")

    result = {
        "ok": not failures,
        "failures": failures,
        "rounds": args.rounds,
        "harvest_lag": args.lag,
        "devices": args.devices,
        "sync": {k: sync[k] for k in ("wall_s", "stall_s", "losses")},
        "async": {k: async_[k] for k in ("wall_s", "stall_s")},
        "stall_total_sync_s": round(sum(sync["stall_s"].values()), 4),
        "stall_total_async_s": round(sum(async_["stall_s"].values()), 4),
    }
    line = json.dumps(result)
    print(line, flush=True)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")
    if failures:
        print(f"[roundbench] PARITY FAILURE: {failures}", file=sys.stderr,
              flush=True)
        return 1
    print(f"[roundbench] parity holds over {args.rounds} rounds; host "
          f"stall {result['stall_total_sync_s']}s sync -> "
          f"{result['stall_total_async_s']}s async", file=sys.stderr,
          flush=True)
    return 0


if __name__ == "__main__":
    # standalone: force the CPU backend with a virtual mesh BEFORE jax
    # initializes (the same rig contract as tests/conftest.py)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ.setdefault("JAX_PLATFORM_NAME", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=4").strip()
    raise SystemExit(main())
