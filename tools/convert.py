#!/usr/bin/env python
"""Convert LMDB / LevelDB / HDF5 / imagenet-tar sources into pre-decoded
record shards (``sparknet_tpu.data.records`` format v1).

This is the convert-once half of the feed-at-device-speed path: decode
every record ONE time here (Caffe's convert_imageset lesson, arXiv
1408.5093), write fixed-stride uint8 blocks with per-record crc32s, and
every later epoch is ranged reads — no decode, no re-parse.  Records
that fail to decode or are not uint8-representable route through the
quarantine path (bounded budget from the ``SPARKNET_QUARANTINE_*``
knobs; the default zero-tolerance policy makes any corruption a loud
typed failure, ``--max-bad-fraction`` budgets it).

Shard roll size comes from ``SPARKNET_RECORD_SHARD_MB`` (default 64).
Prints ONE JSON summary line (shards, records, quarantine report).

Usage:
  python tools/convert.py --source /data/train_lmdb --backend lmdb \
      --out /data/train_shards
  python tools/convert.py --source /data/train.h5 --backend hdf5 \
      --out shards [--data-key data --label-key label]
  python tools/convert.py --source /data/tars --backend tar \
      --labels labels.txt --resize 256 --out shards

Backends: lmdb, leveldb, hdf5, tar, auto (default: sniff the source).
The output directory feeds straight back in: a ``Data`` layer with
``backend: "RECORDS"`` (or any ``source`` holding ``*.rec``) streams it
through ``records_feed``, and ``tools/feedbench.py --records-leg``
proves the round trip bit-identical to the serial decode path.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def _to_uint8(img: np.ndarray, *, source: str, key=None,
              quantize: bool = False) -> np.ndarray:
    """uint8 view of a decoded record.  Exact-valued floats (the datum
    decode path yields 0..255 integers as f32) cast losslessly; with
    ``quantize`` (the JPEG-resize path, whose interpolation is
    fractional by nature) values are round-clipped — a deliberate,
    one-time quantization at convert time.  Anything else is typed
    corruption for the quarantine."""
    from sparknet_tpu.data.integrity import DataCorruptionError
    img = np.asarray(img)
    if img.dtype == np.uint8:
        return img
    if quantize:
        return np.clip(np.round(img), 0, 255).astype(np.uint8)
    as_u8 = img.astype(np.uint8)
    if np.array_equal(as_u8.astype(img.dtype), img):
        return as_u8
    raise DataCorruptionError(
        "record is not uint8-representable (float pixels outside exact "
        "0..255); pass --quantize to round-clip at convert time",
        source=source, key=key)


def iter_db(source: str, backend: str, quantize: bool = False):
    """(img_u8, label) stream off an LMDB/LevelDB cursor, in cursor
    order (the order ``db_feed`` replays — bit-identity depends on it)."""
    from sparknet_tpu.data.db import datum_to_array, open_db
    reader = open_db(source, backend)
    for key, val in reader.items():
        img, label = datum_to_array(val, key=key, source=source)
        yield _to_uint8(img, source=source, key=key,
                        quantize=quantize), label


def iter_hdf5(source: str, data_key: str, label_key: str,
              quantize: bool = False):
    from sparknet_tpu.data.hdf5 import load_hdf5_blobs
    blobs = load_hdf5_blobs(source, [data_key, label_key])
    data, labels = blobs[data_key], blobs[label_key]
    if data.ndim != 4:
        raise ValueError(
            f"{source}:{data_key} must be [n, c, h, w], got {data.shape}")
    for i in range(data.shape[0]):
        yield _to_uint8(data[i], source=source, key=i,
                        quantize=quantize), int(labels[i])


def iter_tars(source: str, label_file: str, resize: int):
    """Decoded (img_u8, label) stream over every tar under ``source`` —
    the ImageNetLoader path (stream-untar → JPEG decode → force-resize),
    paid once here instead of per epoch.  Resize interpolation is
    fractional, so this path always quantizes."""
    from sparknet_tpu.data.imagenet import (
        decode_and_resize, list_tars, read_label_map, stream_tar_images)
    labels = read_label_map(label_file)
    for tar in list_tars(source):
        pairs = stream_tar_images(tar, labels)
        for img, label in decode_and_resize(pairs, resize):
            yield _to_uint8(img, source=tar, quantize=True), label


def sniff_backend(source: str) -> str:
    from sparknet_tpu.data.hdf5 import is_hdf5_file
    if os.path.isfile(source):
        return "hdf5" if is_hdf5_file(source) else "lmdb"
    if os.path.isdir(source):
        names = os.listdir(source)
        if any(n.endswith(".tar") for n in names):
            return "tar"
        if any(n.endswith(".mdb") for n in names):
            return "lmdb"
        if any(n.endswith((".ldb", ".sst", ".log")) for n in names):
            return "leveldb"
    raise ValueError(
        f"cannot sniff a backend for {source!r}; pass --backend")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--source", required=True,
                    help="LMDB/LevelDB dir, .h5 file, or tar root")
    ap.add_argument("--out", required=True,
                    help="output shard directory (created)")
    ap.add_argument("--backend", default="auto",
                    choices=["auto", "lmdb", "leveldb", "hdf5", "tar"])
    ap.add_argument("--labels", default=None,
                    help="label map file (tar backend)")
    ap.add_argument("--resize", type=int, default=256,
                    help="force-resize edge for the tar backend")
    ap.add_argument("--data-key", default="data")
    ap.add_argument("--label-key", default="label")
    ap.add_argument("--quantize", action="store_true",
                    help="round-clip non-integer float pixels to uint8 "
                         "instead of quarantining them")
    ap.add_argument("--max-bad-fraction", type=float, default=None,
                    help="quarantine budget override (default: the "
                         "SPARKNET_QUARANTINE_* knobs)")
    ap.add_argument("--shard-mb", type=int, default=None,
                    help="shard roll size override "
                         "(default SPARKNET_RECORD_SHARD_MB)")
    args = ap.parse_args(argv)

    from sparknet_tpu.data.integrity import Quarantine, QuarantinePolicy
    from sparknet_tpu.data.records import convert_to_shards

    backend = args.backend
    if backend == "auto":
        backend = sniff_backend(args.source)
    if backend in ("lmdb", "leveldb"):
        records = iter_db(args.source, backend.upper(),
                          quantize=args.quantize)
    elif backend == "hdf5":
        records = iter_hdf5(args.source, args.data_key, args.label_key,
                            quantize=args.quantize)
    else:
        if not args.labels:
            ap.error("--backend tar requires --labels")
        records = iter_tars(args.source, args.labels, args.resize)

    policy = (QuarantinePolicy(max_fraction=args.max_bad_fraction)
              if args.max_bad_fraction is not None
              else QuarantinePolicy.from_env())
    quarantine = Quarantine(policy, source=args.source)
    summary = convert_to_shards(
        records, args.out, quarantine=quarantine,
        shard_bytes=args.shard_mb * (1 << 20) if args.shard_mb else None)
    summary["source"] = args.source
    summary["backend"] = backend
    print(json.dumps(summary, default=str), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
