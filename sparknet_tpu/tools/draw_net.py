"""draw_net — render a net prototxt as a Graphviz .dot file (reference:
caffe/python/caffe/draw.py + caffe/python/draw_net.py).  Pure text output;
run `dot -Tpng net.dot -o net.png` wherever graphviz exists.

Usage:
  python -m sparknet_tpu.tools.draw_net NET_PROTOTXT OUT_DOT \
      [--rankdir LR|TB] [--phase TRAIN|TEST|ALL]
"""

from __future__ import annotations

import argparse

_LAYER_STYLE = ('shape=record, fillcolor="#6495ED", style=filled')
_DATA_STYLE = ('shape=record, fillcolor="#90EE90", style=filled')
_BLOB_STYLE = ('shape=octagon, fillcolor="#E0E0E0", style=filled')
_DATA_TYPES = {"Data", "ImageData", "WindowData", "HDF5Data", "DummyData",
               "MemoryData", "JavaData", "Input"}


def _label(lp) -> str:
    """Layer node label with key geometry, like draw.py get_layer_label."""
    parts = [lp.name, lp.type]
    if lp.type in ("Convolution", "Deconvolution"):
        p = lp.sub("convolution_param")
        parts.append(f"kernel {p.get('kernel_size', '?')}"
                     f" stride {p.get('stride', 1)}"
                     f" pad {p.get('pad', 0)}")
    elif lp.type == "Pooling":
        p = lp.sub("pooling_param")
        parts.append(f"{p.get('pool', 'MAX')} kernel "
                     f"{p.get('kernel_size', '?')} stride "
                     f"{p.get('stride', 1)}")
    elif lp.type == "InnerProduct":
        parts.append(f"num_output {lp.sub('inner_product_param').get('num_output', '?')}")
    return r"\n".join(str(p) for p in parts)


def net_to_dot(net_param, rankdir: str = "LR") -> str:
    lines = [
        f'digraph "{net_param.name or "net"}" {{',
        f"  rankdir={rankdir};",
    ]
    for lp in net_param.layer:
        style = _DATA_STYLE if lp.type in _DATA_TYPES else _LAYER_STYLE
        lines.append(f'  "L_{lp.name}" [label="{_label(lp)}", {style}];')
    blobs = set()
    for lp in net_param.layer:
        for t in lp.top:
            if t not in blobs:
                blobs.add(t)
                lines.append(f'  "B_{t}" [label="{t}", {_BLOB_STYLE}];')
        for b in lp.bottom:
            if b not in blobs:
                blobs.add(b)
                lines.append(f'  "B_{b}" [label="{b}", {_BLOB_STYLE}];')
    for lp in net_param.layer:
        for b in lp.bottom:
            if b in lp.top:  # in-place layer: annotate, no cycle
                lines.append(f'  "B_{b}" -> "L_{lp.name}" '
                             f'[dir=both, style=dashed];')
            else:
                lines.append(f'  "B_{b}" -> "L_{lp.name}";')
        for t in lp.top:
            if t not in lp.bottom:
                lines.append(f'  "L_{lp.name}" -> "B_{t}";')
    lines.append("}")
    return "\n".join(lines) + "\n"


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("net_prototxt")
    ap.add_argument("out_dot")
    ap.add_argument("--rankdir", default="LR", choices=["LR", "TB", "RL", "BT"])
    ap.add_argument("--phase", default="ALL",
                    choices=["TRAIN", "TEST", "ALL"])
    args = ap.parse_args(argv)

    from ..proto import NetState, Phase, load_net_prototxt
    net = load_net_prototxt(args.net_prototxt)
    if args.phase != "ALL":
        net = net.filtered(NetState(Phase[args.phase]))
    with open(args.out_dot, "w") as f:
        f.write(net_to_dot(net, args.rankdir))
    print(f"Wrote {args.out_dot} ({len(net.layer)} layers)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())


# -- pycaffe caffe.draw API (reference: python/caffe/draw.py:180-208) -------

def _as_net_param(caffe_net):
    """Accept a typed NetParameter, a caffe_pb2 NetParameter message, a
    raw PMessage, or prototxt text/path."""
    from ..proto.caffe_pb import NetParameter
    from ..proto.textformat import PMessage
    if isinstance(caffe_net, NetParameter):
        return caffe_net
    pm = getattr(caffe_net, "_p", caffe_net)
    if isinstance(pm, PMessage):
        return NetParameter.from_pmsg(pm)
    from ..proto import load_net_prototxt
    return load_net_prototxt(str(caffe_net))


def draw_net(caffe_net, rankdir: str = "LR", ext: str = "png") -> bytes:
    """Render the net; returns image bytes (draw.py draw_net).  The
    reference renders through pydot+graphviz; here 'dot'/'gv' return the
    Graphviz source directly and image formats shell out to a `dot`
    binary when one exists (clear error otherwise — this box has none)."""
    dot_text = net_to_dot(_as_net_param(caffe_net), rankdir)
    if ext in ("dot", "gv"):
        return dot_text.encode()
    import shutil
    import subprocess
    exe = shutil.which("dot")
    if exe is None:
        raise RuntimeError(
            f"rendering {ext!r} needs graphviz's `dot` binary (not "
            f"installed here); use ext='dot' for the Graphviz source")
    p = subprocess.run([exe, f"-T{ext}"], input=dot_text.encode(),
                       stdout=subprocess.PIPE, check=True)
    return p.stdout


def draw_net_to_file(caffe_net, filename: str, rankdir: str = "LR") -> None:
    """draw.py draw_net_to_file: extension picks the format."""
    import os
    ext = os.path.splitext(os.path.basename(filename))[1].lstrip(".")
    with open(filename, "wb") as f:
        f.write(draw_net(caffe_net, rankdir, ext or "dot"))
