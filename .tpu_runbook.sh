#!/bin/bash
# TPU capture runbook — round 4 executed this fully on 2026-07-31 (all
# committed: verified bench, per-layer profiles for every model/dtype,
# time_net --trace validation, poolbwd settle [closed: measured out],
# non-degenerate feed tier at BENCH_FEED_BATCH=8).  Kept as the re-run
# recipe for future rounds / after tunnel outages.
set -x
cd "$(dirname "$0")"
mkdir -p .tpu_runbook_logs profiles

# 0. sanity probe (fail fast if tunnel died again)
timeout 120 python -c "import jax; print(jax.devices())" \
    > .tpu_runbook_logs/probe.log 2>&1 || exit 7

# 1. headline bench (hardened path; persists .bench_last_good.json)
timeout 2400 python bench.py \
    > .tpu_runbook_logs/bench.json 2> .tpu_runbook_logs/bench.log

# 2. per-layer profiles (one per model/dtype the headlines quote)
for spec in "caffenet 256 f32" "caffenet 256 bf16" \
            "googlenet 128 f32" "googlenet 128 bf16" "vgg16 64 f32" "vgg16 64 bf16"; do
  set -- $spec
  out="profiles/$1$([ "$3" = bf16 ] && echo _bf16)"
  timeout 1800 python tools/profile_step.py --model "$1" --batch "$2" \
      --dtype "$3" --out "$out" \
      > ".tpu_runbook_logs/profile_$1_$3.log" 2>&1
done

# 3. time_net --trace validation (the `caffe time` per-layer view)
timeout 1200 python -m sparknet_tpu.tools.time_net --model googlenet \
    --batch 128 --iterations 4 --trace \
    > .tpu_runbook_logs/time_net_trace.log 2>&1

# 4. non-degenerate feed-overlap tier (batch 8 = the regime where feed
#    and compute are comparable on this rig; batches 2-4 crash upstream
#    XLA SpaceToBatchConverter — see RESULTS.md)
timeout 1200 env BENCH_DTYPE=bf16 BENCH_SCAN=0 BENCH_REPS=2 \
    BENCH_WINDOWS=2 BENCH_FEED_BATCH=8 BENCH_FEED_ITERS=10 \
    BENCH_ATTEMPTS=2 python bench.py \
    > .tpu_runbook_logs/feed_b8.json 2> .tpu_runbook_logs/feed_b8.log

echo DONE
