"""Typed schema layer tests: solver/net parsing, phase filtering, data-layer
replacement, V1 upgrade (reference parity: ProtoLoader.scala,
util/upgrade_proto.cpp)."""

from sparknet_tpu.proto import (
    NetState, Phase,
    load_net_prototxt, load_solver_prototxt, load_solver_prototxt_with_net,
    replace_data_layers,
)
from sparknet_tpu.proto.caffe_pb import NetParameter, SolverParameter
from sparknet_tpu.proto.textformat import parse

SOLVER_TXT = """
net: "train_val.prototxt"
test_iter: 100
test_interval: 500
base_lr: 0.01
lr_policy: "step"
gamma: 0.1
stepsize: 100000
display: 20
max_iter: 450000
momentum: 0.9
weight_decay: 0.0005
snapshot: 10000
snapshot_prefix: "model"
solver_mode: GPU
"""

NET_TXT = """
name: "tiny"
layer {
  name: "data" type: "Input" top: "data"
  input_param { shape { dim: 2 dim: 3 dim: 8 dim: 8 } }
}
layer {
  name: "conv" type: "Convolution" bottom: "data" top: "conv"
  convolution_param { num_output: 4 kernel_size: 3 }
}
layer {
  name: "acc" type: "Accuracy" bottom: "conv" bottom: "label" top: "acc"
  include { phase: TEST }
}
layer {
  name: "trainonly" type: "ReLU" bottom: "conv" top: "conv"
  exclude { phase: TEST }
}
"""


def test_solver_parse():
    sp = load_solver_prototxt(SOLVER_TXT)
    assert sp.base_lr == 0.01
    assert sp.lr_policy == "step"
    assert sp.gamma == 0.1
    assert sp.stepsize == 100000
    assert sp.momentum == 0.9
    assert sp.weight_decay == 0.0005
    assert sp.test_iter == [100]
    assert sp.solver_type == "SGD"
    assert sp.snapshot == 10000


def test_solver_with_net_clears_snapshot():
    net = load_net_prototxt(NET_TXT)
    sp = load_solver_prototxt_with_net(SOLVER_TXT, net)
    assert sp.snapshot == 0 and sp.snapshot_prefix == ""
    assert sp.net is None and sp.net_param is net
    sp2 = load_solver_prototxt_with_net(SOLVER_TXT, net, snapshot_prefix="/tmp/x")
    assert sp2.snapshot_prefix == "/tmp/x"


def test_phase_filtering():
    net = load_net_prototxt(NET_TXT)
    train = net.filtered(NetState(Phase.TRAIN))
    test = net.filtered(NetState(Phase.TEST))
    train_names = [l.name for l in train.layer]
    test_names = [l.name for l in test.layer]
    assert "acc" not in train_names and "trainonly" in train_names
    assert "acc" in test_names and "trainonly" not in test_names


def test_replace_data_layers():
    net_txt = """
    name: "x"
    layer { name: "d" type: "Data" top: "data" top: "label" }
    layer { name: "ip" type: "InnerProduct" bottom: "data" top: "ip"
            inner_product_param { num_output: 10 } }
    """
    net = load_net_prototxt(net_txt)
    out = replace_data_layers(net, 16, 8, 3, 32, 32)
    assert out.layer[0].type == "JavaData"
    assert out.layer[0].phase == Phase.TRAIN
    assert out.layer[1].phase == Phase.TEST
    shape = out.layer[0].sub("java_data_param").get("shape").get_all("dim")
    assert shape == [16, 3, 32, 32]
    assert [l.name for l in out.layer[2:]] == ["ip"]


def test_v1_layer_upgrade():
    txt = """
    name: "old"
    layers { name: "c" type: CONVOLUTION bottom: "data" top: "c"
             blobs_lr: 1 blobs_lr: 2 weight_decay: 1 weight_decay: 0
             convolution_param { num_output: 2 kernel_size: 1 } }
    layers { name: "s" type: SOFTMAX_LOSS bottom: "c" bottom: "label" }
    """
    net = NetParameter.from_pmsg(parse(txt))
    assert net.layer[0].type == "Convolution"
    assert net.layer[1].type == "SoftmaxWithLoss"
    assert [p.lr_mult for p in net.layer[0].param] == [1.0, 2.0]
    assert [p.decay_mult for p in net.layer[0].param] == [1.0, 0.0]


def test_legacy_input_dim():
    txt = 'input: "data"\ninput_dim: 1\ninput_dim: 3\ninput_dim: 4\ninput_dim: 4'
    net = load_net_prototxt(txt)
    assert net.input == ["data"]
    assert net.input_shape[0].dim == [1, 3, 4, 4]
