"""Weight-initialization fillers.

Caffe-exact semantics for the filler family (reference:
caffe/include/caffe/filler.hpp:31-146): constant, uniform, gaussian, xavier,
msra, positive_unitball, bilinear.  Fan-in/fan-out conventions follow
XavierFiller/MSRAFiller exactly: fan_in = count/num_output(=shape[0]),
fan_out = count/channels(=shape[1]).
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ..proto.caffe_pb import FillerParameter

Shape = tuple[int, ...]


def fill(rng: jax.Array, filler: FillerParameter, shape: Shape,
         dtype=jnp.float32) -> jax.Array:
    t = filler.type
    if t == "constant":
        return jnp.full(shape, filler.value, dtype)
    if t == "uniform":
        return jax.random.uniform(rng, shape, dtype, minval=filler.min, maxval=filler.max)
    if t == "gaussian":
        # sparse gaussian (filler.hpp GaussianFiller sparse_) is not supported;
        # no zoo model uses it.
        return filler.mean + filler.std * jax.random.normal(rng, shape, dtype)
    if t in ("xavier", "msra"):
        count = math.prod(shape)
        fan_in = count // shape[0] if shape else 1
        fan_out = count // shape[1] if len(shape) > 1 else count
        vn = filler.variance_norm
        if vn == "AVERAGE":
            n = (fan_in + fan_out) / 2.0
        elif vn == "FAN_OUT":
            n = fan_out
        else:
            n = fan_in
        if t == "xavier":
            scale = math.sqrt(3.0 / n)
            return jax.random.uniform(rng, shape, dtype, minval=-scale, maxval=scale)
        std = math.sqrt(2.0 / n)
        return std * jax.random.normal(rng, shape, dtype)
    if t == "positive_unitball":
        x = jax.random.uniform(rng, shape, dtype)
        flat = x.reshape(shape[0], -1)
        flat = flat / jnp.sum(flat, axis=1, keepdims=True)
        return flat.reshape(shape)
    if t == "bilinear":
        # upsampling kernel for deconv (filler.hpp BilinearFiller)
        kh, kw = shape[-2], shape[-1]
        f = math.ceil(kw / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        xs = jnp.arange(kw)
        ys = jnp.arange(kh)
        wx = 1 - jnp.abs(xs / f - c)
        wy = 1 - jnp.abs(ys / f - c)
        k = jnp.outer(wy, wx)
        return jnp.broadcast_to(k, shape).astype(dtype)
    raise ValueError(f"unknown filler type {t!r}")
