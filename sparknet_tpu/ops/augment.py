"""Device-side data augmentation: crop / mirror / mean-subtract / scale
inside the compiled train step.

``DeviceFeed.device_cast`` already proved the transfer half of the feed
win — shipping uint8 over PCIe and casting on device cuts host→HBM bytes
4×.  This module removes the host TRANSFORM stage too: the host ships
raw uint8 record blocks untouched (``records_feed(raw=True)`` /
``db_feed`` without a transform), and Caffe's DataTransformer semantics
(data_transformer.cpp: cast → full-size mean subtract → random/center
crop → random mirror → scale) run as traced XLA ops on the batch already
resident in HBM — a handful of elementwise ops and slices that fuse into
the step's first layer, vs a host stage that was costing more than the
matmuls it fed.

Exact replay is non-negotiable (the audit plane diffs losses bitwise),
so all randomness draws from the TRACED rng key via ``jax.random``
(threefry is counter-based — the same key yields the same offsets on
CPU, TPU, eager, and jit), and the op order matches the host
``DataTransformer`` exactly.  ``transforms.augment_batch_host`` is the
independent numpy implementation of the same spec used as the bit-parity
oracle: cast, subtract, slice, flip, and multiply are all IEEE-exact in
both f32 implementations, so device-augmented training must reproduce
host-augmented losses bit for bit at the same seed
(``Solver.set_augment(device=True/False)``, tested in
tests/test_records.py).

No custom kernels here by design: crop is ``lax.dynamic_slice`` under
``vmap``, mirror is a reversed gather — both lower to plain XLA slices
that fuse with the first conv's input handling on TPU and CPU alike.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np


class AugmentSpec(NamedTuple):
    """The transform_param subset that augmentation folds on device.
    ``mean`` is a broadcastable f32 array ((c,1,1) per-channel values or
    a full (c,h,w) mean image — full-size subtract happens BEFORE the
    crop, Caffe's window-indexed mean) or None.  ``train`` selects
    random crop+mirror vs deterministic center crop."""

    crop: int = 0
    mirror: bool = False
    mean: np.ndarray | None = None
    scale: float = 1.0
    train: bool = True

    @classmethod
    def from_transform_param(cls, transform_param, phase) -> "AugmentSpec":
        """Build from a LayerParameter ``transform_param`` sub-message —
        the same fields ``db.DataTransformer`` reads, so host and device
        paths are configured from one prototxt source of truth."""
        from ..proto.caffe_pb import Phase
        p = transform_param
        mean = None
        mean_file = p.get("mean_file")
        if mean_file is not None:
            from ..proto.caffemodel import load_mean_binaryproto
            mean = np.asarray(load_mean_binaryproto(str(mean_file)),
                              np.float32)
        else:
            if hasattr(p, "get_all"):      # PMessage sub-message
                mv = p.get_all("mean_value")
            else:                          # plain-dict transform_param
                mv = p.get("mean_value") or []
                if not isinstance(mv, (list, tuple)):
                    mv = [mv]
            values = [float(v) for v in mv]
            if values:
                mean = np.asarray(values, np.float32).reshape(-1, 1, 1)
        return cls(crop=int(p.get("crop_size", 0)),
                   mirror=bool(p.get("mirror", False)),
                   mean=mean, scale=float(p.get("scale", 1.0)),
                   train=(phase == Phase.TRAIN))


def draw_offsets(key, n: int, h: int, w: int, spec: AugmentSpec):
    """(ys, xs, flips) int32 draws for a batch of n images — the ONE
    place augmentation randomness is sampled, shared verbatim by the
    device (:func:`apply`) and host (``transforms.augment_batch_host``)
    paths so their streams cannot diverge.  Test phase: center offsets,
    zero flips, no draws consumed."""
    if spec.crop and spec.train:
        ky, kx, kf = jax.random.split(key, 3)
        ys = jax.random.randint(ky, (n,), 0, h - spec.crop + 1,
                                dtype=jnp.int32)
        xs = jax.random.randint(kx, (n,), 0, w - spec.crop + 1,
                                dtype=jnp.int32)
    elif spec.crop:
        ys = jnp.full((n,), (h - spec.crop) // 2, jnp.int32)
        xs = jnp.full((n,), (w - spec.crop) // 2, jnp.int32)
    else:
        ys = xs = jnp.zeros((n,), jnp.int32)
    if spec.mirror and spec.train:
        kf = jax.random.split(key, 3)[2] if spec.crop else key
        flips = jax.random.randint(kf, (n,), 0, 2, dtype=jnp.int32)
    else:
        flips = jnp.zeros((n,), jnp.int32)
    return ys, xs, flips


def apply(imgs, ys, xs, flips, spec: AugmentSpec):
    """DataTransformer.batch as traced ops over an [n, c, h, w] uint8
    (or f32) batch: cast → full-size mean subtract → per-sample dynamic
    crop → per-sample mirror → scale.  Offsets come from
    :func:`draw_offsets`."""
    x = imgs.astype(jnp.float32)
    if spec.mean is not None:
        x = x - jnp.asarray(spec.mean, jnp.float32)
    if spec.crop:
        c = x.shape[1]

        def crop_one(img, y, xo):
            return jax.lax.dynamic_slice(
                img, (0, y, xo), (c, spec.crop, spec.crop))

        x = jax.vmap(crop_one)(x, ys, xs)
    if spec.mirror and spec.train:
        x = jnp.where(flips[:, None, None, None] == 1, x[..., ::-1], x)
    if spec.scale != 1.0:
        x = x * jnp.float32(spec.scale)
    return x


def augment_batch(imgs, key, spec: AugmentSpec):
    """Draw + apply in one call — the train step's entry point."""
    n, _c, h, w = imgs.shape
    ys, xs, flips = draw_offsets(key, n, h, w, spec)
    return apply(imgs, ys, xs, flips, spec)


def out_shape(in_shape: tuple, spec: AugmentSpec) -> tuple:
    """Augmented batch shape for an [n, c, h, w] input."""
    n, c, h, w = in_shape
    return (n, c, spec.crop, spec.crop) if spec.crop else (n, c, h, w)
