"""Checkpoint IO.

The reference snapshots model + solver state (momentum history, iter) as
binaryproto or HDF5 (reference: caffe/src/caffe/solver.cpp:447-459,
solvers/sgd_solver.cpp:242-296) and restores via ``Solver::Restore``
(solver.cpp:510).  Here a checkpoint is any pytree, written as an ``.npz``
of flattened leaves plus a pickled treedef-free key list — no pickle of
arbitrary objects, so checkpoints are portable and safe to load.

Robustness contract (the recovery layer leans on this):
- writes are atomic (tmp + ``os.replace``), so a crash mid-write never
  leaves a half-checkpoint under the final name;
- the meta block carries a content checksum over every leaf, verified on
  load — bit-rot or a torn copy fails loudly;
- ANY malformed file (truncated zip, missing arrays, bad meta, checksum
  mismatch) surfaces as ``CheckpointError`` carrying ``.path``, never a
  raw ``zipfile.BadZipFile``/``KeyError`` from deep inside numpy.

Zero-stall tier: :class:`AsyncCheckpointWriter` moves the serialize +
checksum + rename work onto a background thread behind a bounded queue,
so the training loop's checkpoint cost shrinks to a non-blocking
device→host snapshot (``begin_host_transfer``) and a queue put.  The
on-disk contract above is unchanged — the same ``save_checkpoint`` runs,
just off-thread — and ``flush()`` is the barrier that restores strict
durability ordering wherever the caller needs it (rollback, preemption,
fault-injection windows).  ``SPARKNET_ASYNC_CKPT=0`` disables the tier
globally, restoring the fully synchronous write path.
"""

from __future__ import annotations

import atexit
import hashlib
import json
import os
import queue
import threading
import weakref
import zipfile
from typing import Any, Callable

from . import knobs

import time

import jax
import numpy as np

from . import telemetry


class CheckpointError(Exception):
    """A checkpoint file is missing, truncated, corrupt, or fails its
    checksum.  ``path`` names the offending file."""

    def __init__(self, message: str, path: str):
        super().__init__(f"{path}: {message}")
        self.path = path


class CheckpointFencedError(CheckpointError):
    """A writer from a fenced-off incarnation tried to publish into a
    checkpoint dir a successor has claimed — the zombie-writer refusal.
    Carries the writer's ``token`` and the dir's current ``fence``."""

    def __init__(self, path: str, token: int, fence: int):
        super().__init__(
            f"incarnation fence: writer token {token} < dir fence "
            f"{fence} — a successor owns this checkpoint dir; refusing "
            f"to publish", path)
        self.token = token
        self.fence = fence


# -- incarnation fencing ----------------------------------------------------
# A checkpoint dir carries a monotonic fence token (FENCE.json).  Every
# writer claims the dir with its own incarnation token before writing;
# a claim can only RAISE the fence.  A gang requeued past a partition
# gets a strictly larger token (fleet episode x 1e5 + restart attempt,
# stamped into SPARKNET_FENCE_TOKEN by the launch stack), so a zombie
# writer returning from behind the partition finds fence > token and is
# refused with ``CheckpointFencedError`` BEFORE its npz write and again
# at manifest-rename time — the successor's state is never clobbered.
# The discipline is cooperative and targets STALE writers (whose tokens
# are, by construction, lower); it is not a general concurrent-writer
# lock.

FENCE_FILE = "FENCE.json"


def read_fence(directory: str) -> int:
    """The dir's current fence token (0 = never claimed/unreadable)."""
    try:
        with open(os.path.join(directory, FENCE_FILE)) as f:
            return int(json.load(f)["token"])
    except (OSError, ValueError, KeyError, json.JSONDecodeError):
        return 0


def check_fence(directory: str, token: int) -> None:
    """Raise ``CheckpointFencedError`` when ``directory`` has been
    claimed by a higher incarnation than ``token``."""
    fence = read_fence(directory)
    if fence > token:
        raise CheckpointFencedError(os.path.join(directory, FENCE_FILE),
                                    token, fence)


def advance_fence(directory: str, token: int) -> int:
    """Claim ``directory`` for incarnation ``token`` (monotonic max,
    atomic tmp+rename).  Returns the resulting fence.  A claim BELOW the
    current fence raises — the claimant is the zombie."""
    check_fence(directory, token)
    fence = max(read_fence(directory), token)
    os.makedirs(directory, exist_ok=True)
    path = os.path.join(directory, FENCE_FILE)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump({"token": fence, "pid": os.getpid(),
                   "time": round(time.time(), 3)}, f)
    os.replace(tmp, path)
    return fence


def _flatten(tree: Any, prefix: str, out: dict[str, np.ndarray],
             meta: dict[str, Any]) -> None:
    if isinstance(tree, dict):
        meta[prefix] = {"kind": "dict", "keys": sorted(tree.keys())}
        for k in sorted(tree.keys()):
            _flatten(tree[k], f"{prefix}/{k}", out, meta)
    elif isinstance(tree, (list, tuple)):
        meta[prefix] = {"kind": "list", "len": len(tree)}
        for i, v in enumerate(tree):
            _flatten(v, f"{prefix}/{i}", out, meta)
    else:
        meta[prefix] = {"kind": "leaf"}
        out[prefix] = np.asarray(tree)


def _unflatten(prefix: str, data: dict[str, np.ndarray],
               meta: dict[str, Any]) -> Any:
    info = meta[prefix]
    if info["kind"] == "dict":
        return {k: _unflatten(f"{prefix}/{k}", data, meta) for k in info["keys"]}
    if info["kind"] == "list":
        return [_unflatten(f"{prefix}/{i}", data, meta) for i in range(info["len"])]
    return data[prefix]


def content_checksum(arrays: dict[str, np.ndarray]) -> str:
    """Order-independent sha256 over every leaf's name, dtype, shape, and
    bytes — what the meta block stores and the loader re-verifies."""
    h = hashlib.sha256()
    for k in sorted(arrays):
        a = np.ascontiguousarray(arrays[k])
        h.update(k.encode())
        h.update(str(a.dtype).encode())
        h.update(str(a.shape).encode())
        h.update(a.tobytes())
    return h.hexdigest()


def save_checkpoint(path: str, tree: Any) -> None:
    arrays: dict[str, np.ndarray] = {}
    meta: dict[str, Any] = {}
    host_tree = jax.tree_util.tree_map(np.asarray, tree)
    _flatten(host_tree, "root", arrays, meta)
    meta["__checksum__"] = content_checksum(arrays)
    # pid-stamped temp name: a writer killed mid-write leaves an orphan
    # that can never collide with a later writer's live temp file; the
    # .npz suffix keeps np.savez from appending its own
    tmp = f"{path}.tmp.{os.getpid()}.npz"
    np.savez(tmp, __meta__=np.frombuffer(
        json.dumps(meta).encode(), dtype=np.uint8), **arrays)
    os.replace(tmp, path)


def load_checkpoint(path: str, verify: bool = True) -> Any:
    try:
        with np.load(path) as z:
            meta = json.loads(bytes(z["__meta__"]).decode())
            data = {k: z[k] for k in z.files if k != "__meta__"}
    except (zipfile.BadZipFile, OSError, EOFError, ValueError, KeyError,
            json.JSONDecodeError) as e:
        raise CheckpointError(
            f"unreadable checkpoint ({type(e).__name__}: {e})", path) from e
    expect = meta.pop("__checksum__", None)
    if verify and expect is not None:
        got = content_checksum(data)
        if got != expect:
            raise CheckpointError(
                f"checksum mismatch (file says {expect[:12]}…, content is "
                f"{got[:12]}…) — truncated or bit-rotted snapshot", path)
    try:
        return _unflatten("root", data, meta)
    except (KeyError, IndexError, TypeError) as e:
        raise CheckpointError(
            f"malformed checkpoint structure ({type(e).__name__}: {e})",
            path) from e


# ---------------------------------------------------------------------------
# Sharded-blob split/join (hybrid model+data sharding checkpoint plane)
# ---------------------------------------------------------------------------

def split_sharded_tree(params: dict, shard_dims: dict[str, int],
                       n_shards: int):
    """Split a WeightCollection (``dict[name, list[leaf]]``) into a
    ``common`` part plus ``n_shards`` per-shard parts, per a partition
    plan's ``shard_dims`` map (``"name/idx" -> axis``).

    The parts keep the ``{name: {str(idx): leaf}}`` shape (dicts all the
    way down, so ``_flatten`` round-trips them without list-hole
    surgery) and together cover every leaf exactly once: unsharded
    leaves land in ``common``, sharded leaves are split into equal tiles
    along their plan dim, tile *k* in part *k*.  Inverse:
    :func:`join_sharded_tree` — bit-exact by construction (pure
    ``np.split``/``np.concatenate``, no arithmetic)."""
    common: dict[str, dict[str, np.ndarray]] = {}
    shards: list[dict[str, dict[str, np.ndarray]]] = [
        {} for _ in range(n_shards)]
    for name, blobs in params.items():
        for i, leaf in enumerate(blobs):
            leaf = np.asarray(leaf)
            dim = shard_dims.get(f"{name}/{i}")
            if dim is None:
                common.setdefault(name, {})[str(i)] = leaf
            else:
                if leaf.shape[dim] % n_shards:
                    raise CheckpointError(
                        f"leaf {name}/{i} dim {dim} size {leaf.shape[dim]} "
                        f"not divisible into {n_shards} shards")
                for k, tile in enumerate(np.split(leaf, n_shards, axis=dim)):
                    shards[k].setdefault(name, {})[str(i)] = tile
    return common, shards


def join_sharded_tree(common: dict, shards: list, shard_dims: dict[str, int],
                      ) -> dict:
    """Inverse of :func:`split_sharded_tree`: reassemble the
    WeightCollection (``dict[name, list[leaf]]``) from a common part and
    per-shard parts written at ANY world size — the full logical leaf is
    identical whatever n it was tiled by, which is what lets elastic
    re-form re-tile to a new world bit-exactly."""
    merged: dict[str, dict[int, np.ndarray]] = {}
    for name, idx_map in common.items():
        for i, leaf in idx_map.items():
            merged.setdefault(name, {})[int(i)] = np.asarray(leaf)
    by_leaf: dict[tuple[str, int], list[np.ndarray]] = {}
    for part in shards:
        for name, idx_map in part.items():
            for i, tile in idx_map.items():
                by_leaf.setdefault((name, int(i)), []).append(
                    np.asarray(tile))
    for (name, i), tiles in by_leaf.items():
        dim = shard_dims.get(f"{name}/{i}")
        if dim is None:
            raise CheckpointError(
                f"shard files carry leaf {name}/{i} but the manifest's "
                f"shard_dims does not — mismatched checkpoint halves")
        merged.setdefault(name, {})[i] = np.concatenate(tiles, axis=dim)
    out: dict[str, list[np.ndarray]] = {}
    for name, idx_map in merged.items():
        n = max(idx_map) + 1
        if sorted(idx_map) != list(range(n)):
            raise CheckpointError(
                f"layer {name}: blob indices {sorted(idx_map)} have holes "
                f"— common/shard parts do not cover the collection")
        out[name] = [idx_map[i] for i in range(n)]
    return out


# ---------------------------------------------------------------------------
# Async checkpoint tier (the zero-stall outer-loop piece)
# ---------------------------------------------------------------------------

def async_checkpoints_enabled() -> bool:
    """Whether the async checkpoint tier is on (``SPARKNET_ASYNC_CKPT=0``
    is the escape hatch restoring the synchronous write path)."""
    return knobs.raw("SPARKNET_ASYNC_CKPT", "") != "0"


_DEVICE_COPY = None


def snapshot_tree(tree: Any) -> Any:
    """Non-blocking snapshot of a checkpoint pytree: every jax leaf is
    (1) copied ON-DEVICE through a jitted identity-copy — a fresh buffer
    the training loop can never donate out from under the pending write
    (the next compiled round donates the ORIGINAL params/state buffers)
    — and (2) started on its device→host transfer with
    ``copy_to_host_async``, so the writer thread's later ``np.asarray``
    completes against a copy already in flight instead of paying the
    full device sync on the training thread.  Both steps are async
    dispatches; the call returns immediately.  Non-array leaves (ints,
    strings, numpy) pass through unchanged."""
    global _DEVICE_COPY
    if _DEVICE_COPY is None:
        import jax.numpy as jnp
        _DEVICE_COPY = jax.jit(lambda x: jnp.copy(x))

    def snap(x):
        if not isinstance(x, jax.Array):
            return x
        try:
            y = _DEVICE_COPY(x)
        except Exception:
            return np.asarray(x)   # fallback: synchronous host fetch
        try:
            y.copy_to_host_async()
        except Exception:
            pass  # best-effort: np.asarray in the writer still works
        return y
    return jax.tree_util.tree_map(snap, tree)


# every live writer, so cross-instance consumers (a fresh trainer's
# resume_latest scanning a directory another trainer is still writing
# into) can wait for in-flight writes without holding a reference
_WRITERS: "weakref.WeakSet[AsyncCheckpointWriter]" = weakref.WeakSet()
_STOP = object()


class AsyncCheckpointWriter:
    """Single background thread executing checkpoint-write jobs in FIFO
    order behind a bounded queue.

    A *job* is a zero-arg callable that performs one complete durable
    write (npz + manifest + prune), built by the caller with all its
    inputs captured at submission time.  ``submit`` blocks only when
    ``depth`` jobs are already queued (backpressure bounds host memory to
    ``depth`` staged snapshots).  A job that raises parks the exception
    and every later job still runs — the error surfaces on the next
    ``submit``/``flush``, exactly where a synchronous write would have
    raised.  ``flush()`` is the durability barrier: it returns only when
    every previously submitted job has finished."""

    def __init__(self, depth: int = 2, name: str = "ckpt-writer"):
        if depth < 1:
            raise ValueError(f"writer queue depth must be >= 1, got {depth}")
        self._q: "queue.Queue[Any]" = queue.Queue(maxsize=depth)
        self._cond = threading.Condition()
        self._submitted = 0
        self._completed = 0
        self._err: BaseException | None = None
        self._closed = False
        self._thread = threading.Thread(target=self._run, name=name,
                                        daemon=True)
        self._thread.start()
        _WRITERS.add(self)

    # -- writer side ------------------------------------------------------
    def _run(self) -> None:
        m_lat = telemetry.get_registry().histogram(
            "ckpt_write_seconds", "durable checkpoint write latency")
        m_depth = telemetry.get_registry().gauge(
            "ckpt_queue_depth", "checkpoint jobs queued or in flight")
        while True:
            job = self._q.get()
            if job is _STOP:
                return
            t0 = time.perf_counter()
            try:
                job()
            except BaseException as e:  # surfaced on next submit()/flush()
                with self._cond:
                    if self._err is None:
                        self._err = e
            finally:
                dur = time.perf_counter() - t0
                m_lat.observe(dur)
                telemetry.note_span("ckpt.write", dur, cat="ckpt")
                with self._cond:
                    self._completed += 1
                    self._cond.notify_all()
                m_depth.set(self.pending)

    # -- caller side ------------------------------------------------------
    @property
    def pending(self) -> int:
        with self._cond:
            return self._submitted - self._completed

    def _take_error(self) -> BaseException | None:
        with self._cond:
            e, self._err = self._err, None
            return e

    def submit(self, job: Callable[[], None]) -> None:
        """Queue one write job (FIFO).  Blocks while the queue is full;
        re-raises the first error of any PREVIOUS job."""
        if self._closed:
            raise RuntimeError("checkpoint writer is closed")
        err = self._take_error()
        if err is not None:
            raise err
        with self._cond:
            self._submitted += 1
        self._q.put(job)
        telemetry.get_registry().gauge(
            "ckpt_queue_depth", "checkpoint jobs queued or in flight"
        ).set(self.pending)

    def flush(self, raise_errors: bool = True) -> None:
        """Wait until every submitted job has completed (the durability
        barrier).  With ``raise_errors``, a parked job exception is
        re-raised here."""
        with self._cond:
            while self._completed < self._submitted:
                if not self._thread.is_alive():
                    break  # interpreter teardown killed the daemon
                self._cond.wait(0.1)
        if raise_errors:
            err = self._take_error()
            if err is not None:
                raise err

    def close(self, raise_errors: bool = False) -> None:
        """Flush, then stop the writer thread.  Safe to call twice."""
        if self._closed:
            return
        self.flush(raise_errors=raise_errors)
        self._closed = True
        self._q.put(_STOP)
        self._thread.join(timeout=5.0)
        _WRITERS.discard(self)


def flush_all_writers() -> None:
    """Barrier over every live :class:`AsyncCheckpointWriter` — used by
    ``resume_latest`` (and atexit) so a directory scan never races a
    write still in another instance's queue.  Errors stay parked on
    their own writer (the owning trainer surfaces them); this only
    waits."""
    for w in list(_WRITERS):
        try:
            w.flush(raise_errors=False)
        except Exception:
            pass


# normal interpreter exit must not drop queued round checkpoints (the
# preemption contract: snapshot, then clean exit); crashes (os._exit)
# still tear mid-write, which is exactly what the tmp+rename layout and
# manifest checksums exist to survive
atexit.register(flush_all_writers)
