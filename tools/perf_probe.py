"""Where-the-time-goes probe for the headline bench (VERDICT r2 item 1).

The tunneled chip makes per-op profiler micro-timings unreliable (async
dispatch skew), so every number here is a block-granular measurement:
each experiment runs `iters` chained repetitions of the op inside ONE
compiled fori_loop (a scalar tap from each output feeds a tiny
perturbation of the next iteration's *weights*, so XLA can neither DCE
nor hoist the op), with block_until_ready around the whole block and the
median of `reps` blocks reported.

Parts (select with argv, default all):
  ops    — isolated fwd and fwd+bwd cost of every CaffeNet-shaped
           conv/fc/LRN/pool, in NCHW vs NHWC, plus a space-to-depth
           variant of conv1 (C=3 occupies 3/128 MXU lanes; s2d repacks
           the stride-4 11x11 conv as a stride-1 conv at C=48).
  net    — full CaffeNet train-step ablations on the real Solver:
           baseline / no-LRN / no-dropout / eval-forward, batch 256.
  hlo    — transpose/copy census of the optimized HLO for the compiled
           train step (layout-assignment cost evidence).
  lrn    — the cross-channel LRN window sum as reduce_window (default)
           vs the prefix-sum-difference reformulation, pinned per
           variant via one-entry SPARKNET_TUNE tables
           (VERDICT r5 weak #2), fwd and fwd+bwd, at both
           LRN-bearing headline models' shapes.  PROBE_LRN_DTYPE=f32
           switches from the bf16 default.

Usage: python tools/perf_probe.py [ops|net|hlo|poolbwd|lrn ...]
       [--platform cpu]
Prints one JSON line per experiment to stdout; diagnostics to stderr.
"""

from __future__ import annotations

import dataclasses
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR",
    os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                 ".jax_cache"))

BATCH = int(os.environ.get("PROBE_BATCH", 256))
REPS = int(os.environ.get("PROBE_REPS", 3))


def log(msg: str) -> None:
    print(f"[probe] {msg}", file=sys.stderr, flush=True)


def emit(rec: dict) -> None:
    print(json.dumps(rec), flush=True)


# ---------------------------------------------------------------------------
# Block timer
# ---------------------------------------------------------------------------

TARGET_BLOCK_S = float(os.environ.get("PROBE_TARGET_S", 2.0))


def time_block(name: str, make_iter, iters: int = 0,
               extra: dict | None = None):
    """make_iter(s) -> new scalar s; time chained evaluations.

    The tunneled chip has a ~0.1 s per-dispatch floor, so the trip count
    is a *traced* fori_loop bound (one compile) calibrated per experiment
    until the block runs ≥ TARGET_BLOCK_S; the floor is then subtracted
    out by differencing two block sizes (N and N/2).

    A candidate that RAISES (Pallas kernel on CPU, an op a backend can't
    lower, OOM on a small rig) records a typed ``skipped`` entry and
    returns None instead of aborting the whole probe run — callers must
    treat a None per-iter time as "no measurement", never 0.  The
    autotuner (sparknet_tpu/graph/tuner.py) inherits this contract."""
    try:
        return _time_block_measured(name, make_iter, extra)
    except Exception as e:  # noqa: BLE001 — typed skip, not abort
        msg = str(e).strip().split("\n")[0][:200]
        reason = f"{type(e).__name__}: {msg}" if msg else type(e).__name__
        emit({"exp": name, "skipped": reason, **(extra or {})})
        log(f"{name}: SKIPPED ({reason})")
        return None


def _time_block_measured(name: str, make_iter, extra: dict | None = None):
    import jax
    import jax.numpy as jnp
    from jax import lax

    @jax.jit
    def block(s, n):
        # no explicit unroll kwarg: it is already the default, and some
        # jax versions reject it outright when the bound is traced
        return lax.fori_loop(0, n, lambda i, s: make_iter(s), s)

    s0 = jnp.zeros((), jnp.float32)
    t0 = time.perf_counter()
    jax.block_until_ready(block(s0, 4))
    compile_s = time.perf_counter() - t0

    # calibrate N for the target block time
    n = 64
    while True:
        t0 = time.perf_counter()
        jax.block_until_ready(block(s0, n))
        dt = time.perf_counter() - t0
        if dt >= TARGET_BLOCK_S or n >= 1 << 16:
            break
        n = min(max(int(n * TARGET_BLOCK_S / max(dt, 1e-3) * 1.3), n * 2),
                1 << 16)

    full, half = [], []
    for _ in range(REPS):
        t0 = time.perf_counter()
        jax.block_until_ready(block(s0, n))
        full.append(time.perf_counter() - t0)
        t0 = time.perf_counter()
        jax.block_until_ready(block(s0, n // 2))
        half.append(time.perf_counter() - t0)
    fmed = sorted(full)[len(full) // 2]
    hmed = sorted(half)[len(half) // 2]
    per_iter_ms = (fmed - hmed) / (n - n // 2) * 1e3  # floor cancels
    rec = {"exp": name, "ms_per_iter": round(per_iter_ms, 4),
           "block_s": round(fmed, 3), "iters": n,
           "compile_s": round(compile_s, 1), **(extra or {})}
    if n >= 1 << 16 and fmed < TARGET_BLOCK_S / 4:
        # 65k reps finishing "instantly" = XLA elided the op; the number
        # is NOT a measurement
        rec["collapsed"] = True
    emit(rec)
    log(f"{name}: {per_iter_ms:.3f} ms/iter (block {fmed:.2f}s @ {n}, "
        f"compile {compile_s:.0f}s)")
    return per_iter_ms


# ---------------------------------------------------------------------------
# Part A: isolated ops
# ---------------------------------------------------------------------------

# CaffeNet conv shapes at batch 256 (in_c, h, w, out_c, k, stride, pad, group)
CONVS = {
    "conv1": (3, 227, 227, 96, 11, 4, 0, 1),
    "conv2": (96, 27, 27, 256, 5, 1, 2, 2),
    "conv3": (256, 13, 13, 384, 3, 1, 1, 1),
    "conv4": (384, 13, 13, 384, 3, 1, 1, 2),
    "conv5": (384, 13, 13, 256, 3, 1, 1, 2),
}
FCS = {"fc6": (9216, 4096), "fc7": (4096, 4096), "fc8": (4096, 1000)}


def run_ops() -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import lax

    rng = np.random.default_rng(0)

    def conv_iter_fn(x, w, strides, pad, group, dn, backward):
        def it(s):
            wp = w + s * 1e-30

            def f(xx, ww):
                return lax.conv_general_dilated(
                    xx, ww, strides, pad, feature_group_count=group,
                    dimension_numbers=dn)

            if backward:
                y, vjp = jax.vjp(f, x, wp)
                # cotangent must depend on the carry too, else dw =
                # conv(x, cot) is loop-invariant and XLA hoists it
                dx, dw = vjp(jnp.ones_like(y) * (1.0 + s * 1e-30))
                return (jnp.sum(y) + jnp.sum(dx) + jnp.sum(dw)) * 1e-30
            return jnp.sum(f(x, wp)) * 1e-30
        return it

    def conv_flops(ci, h, w_, co, k, st, pd, g):
        oh = (h + 2 * pd - k) // st + 1
        return 2 * BATCH * oh * oh * co * (ci // g) * k * k

    only = os.environ.get("PROBE_ONLY", "")
    only_list = [t for t in only.split(",") if t]

    def wanted(name: str) -> bool:
        return not only_list or any(name.startswith(t) for t in only_list)

    for lname, (ci, h, w_, co, k, st, pd, g) in CONVS.items():
        if not wanted(lname):
            continue
        fl = conv_flops(ci, h, w_, co, k, st, pd, g)
        for layout in ("NCHW", "NHWC"):
            if layout == "NCHW":
                x = jnp.asarray(rng.normal(size=(BATCH, ci, h, w_)),
                                jnp.float32)
                dn = ("NCHW", "OIHW", "NCHW")
            else:
                x = jnp.asarray(rng.normal(size=(BATCH, h, w_, ci)),
                                jnp.float32)
                dn = ("NHWC", "HWIO", "NHWC")
            wshape = ((co, ci // g, k, k) if layout == "NCHW"
                      else (k, k, ci // g, co))
            wt = jnp.asarray(rng.normal(size=wshape) * 0.01, jnp.float32)
            for backward in (False, True):
                if backward and layout == "NHWC" and g > 1:
                    # grouped NHWC conv backward FAULTS the v5e chip
                    # (kernel fault -> UNAVAILABLE; XLA bug) — skip
                    emit({"exp": f"{lname}_NHWC_fb", "skipped":
                          "grouped NHWC bwd faults the TPU (XLA bug)"})
                    continue
                tag = "fb" if backward else "fwd"
                time_block(
                    f"{lname}_{layout}_{tag}",
                    conv_iter_fn(x, wt, (st, st), ((pd, pd), (pd, pd)), g, dn,
                                 backward),
                    extra={"gflops": round(fl * (3 if backward else 1) / 1e9,
                                           1)})

    # conv1 space-to-depth: 227x227x3 s4 11x11 -> pad to 228, reshape to
    # 57x57x48 (4x4 blocks), k=3 stride 1 equivalent channel-packed conv.
    # We time the exact-FLOPs repacked conv (weights repacked offline).
    x = jnp.asarray(rng.normal(size=(BATCH, 228, 228, 3)), jnp.float32)
    xs2d = x.reshape(BATCH, 57, 4, 57, 4, 3).transpose(0, 1, 3, 2, 4, 5)
    xs2d = xs2d.reshape(BATCH, 57, 57, 48)
    # 11x11 kernel at stride 4 -> 3x3 kernel over 4x4 blocks needs k=12 cover:
    # pad kernel 11->12, reshape (12,12,3,96) -> (3,3,48,96)
    wt = jnp.asarray(rng.normal(size=(12, 12, 3, 96)) * 0.01, jnp.float32)
    ws2d = wt.reshape(3, 4, 3, 4, 3, 96).transpose(0, 2, 1, 3, 4, 5)
    ws2d = ws2d.reshape(3, 3, 48, 96)

    def s2d_iter(backward):
        def it(s):
            wp = ws2d + s * 1e-30

            def f(xx, ww):
                return lax.conv_general_dilated(
                    xx, ww, (1, 1), ((0, 0), (0, 0)),
                    dimension_numbers=("NHWC", "HWIO", "NHWC"))
            if backward:
                y, vjp = jax.vjp(f, xs2d, wp)
                dx, dw = vjp(jnp.ones_like(y) * (1.0 + s * 1e-30))
                return (jnp.sum(y) + jnp.sum(dx) + jnp.sum(dw)) * 1e-30
            return jnp.sum(f(xs2d, wp)) * 1e-30
        return it

    if wanted("conv1_s2d"):
        time_block("conv1_s2d_NHWC_fwd", s2d_iter(False))
        time_block("conv1_s2d_NHWC_fb", s2d_iter(True))

    # FC layers
    for lname, (cin, cout) in FCS.items():
        if not wanted(lname):
            continue
        xf = jnp.asarray(rng.normal(size=(BATCH, cin)), jnp.float32)
        wf = jnp.asarray(rng.normal(size=(cin, cout)) * 0.01, jnp.float32)

        def fc_iter(xf=xf, wf=wf, backward=True):
            def it(s):
                wp = wf + s * 1e-30

                def f(xx, ww):
                    return xx @ ww
                y, vjp = jax.vjp(f, xf, wp)
                dx, dw = vjp(jnp.ones_like(y) * (1.0 + s * 1e-30))
                return (jnp.sum(y) + jnp.sum(dx) + jnp.sum(dw)) * 1e-30
            return it
        time_block(f"{lname}_fb", fc_iter(), 60)

    # LRN + pool at CaffeNet stage-1/2 shapes (these perturb x, so ~one
    # extra elementwise pass over x is included; note in analysis)
    from sparknet_tpu.ops.vision import ave_pool, max_pool
    for lname, shape in (("norm1", (BATCH, 96, 27, 27)),
                         ("norm2", (BATCH, 256, 13, 13))):
        if not wanted(lname):
            continue
        xl = jnp.asarray(rng.normal(size=shape), jnp.float32)

        def lrn_iter(xl=xl, backward=True):
            def it(s):
                xp = xl + s * 1e-30

                def f(xx):
                    sq = xx * xx
                    ssum = lax.reduce_window(
                        sq, 0.0, lax.add, (1, 5, 1, 1), (1, 1, 1, 1),
                        ((0, 0), (2, 2), (0, 0), (0, 0)))
                    return xx / (1.0 + (1e-4 / 5) * ssum) ** 0.75
                if backward:
                    y, vjp = jax.vjp(f, xp)
                    (dx,) = vjp(jnp.ones_like(y))
                    return (jnp.sum(y) + jnp.sum(dx)) * 1e-30
                return jnp.sum(f(xp)) * 1e-30
            return it
        time_block(f"{lname}_fb", lrn_iter(), 60)

    for lname, (shape, oh) in (("pool1", ((BATCH, 96, 55, 55), 27)),
                               ("pool2", ((BATCH, 256, 27, 27), 13)),
                               ("pool5", ((BATCH, 256, 13, 13), 6))):
        if not wanted(lname):
            continue
        xp_ = jnp.asarray(rng.normal(size=shape), jnp.float32)

        def pool_iter(xp_=xp_, oh=oh):
            def it(s):
                xq = xp_ + s * 1e-30

                def f(xx):
                    return max_pool(xx, 3, 3, 2, 2, 0, 0, oh, oh)
                y, vjp = jax.vjp(f, xq)
                (dx,) = vjp(jnp.ones_like(y))
                return (jnp.sum(y) + jnp.sum(dx)) * 1e-30
            return it
        time_block(f"{lname}_fb", pool_iter(), 60)


# ---------------------------------------------------------------------------
# Part B: full-net ablations
# ---------------------------------------------------------------------------

def _strip_layers(net, names: set[str]):
    """Remove layers by name, rewiring consumers of their tops to their
    bottoms (valid for in-place-style unary layers like LRN/Dropout)."""
    rewire: dict[str, str] = {}
    kept = []
    for lp in net.layer:
        if lp.name in names:
            rewire[lp.top[0]] = lp.bottom[0]
        else:
            kept.append(lp)
    for lp in kept:
        lp.bottom = [rewire.get(b, b) for b in lp.bottom]
    return dataclasses.replace(net, layer=kept)


def run_net() -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import lax

    from sparknet_tpu.models import caffenet
    from sparknet_tpu.proto import load_solver_prototxt_with_net
    from sparknet_tpu.solvers import Solver

    solver_txt = ('base_lr: 0.01\nmomentum: 0.9\nweight_decay: 0.0005\n'
                  'lr_policy: "step"\ngamma: 0.1\nstepsize: 100000\n')
    variants = {
        "baseline": lambda n: n,
        "no_lrn": lambda n: _strip_layers(n, {"norm1", "norm2"}),
        "no_dropout": lambda n: _strip_layers(n, {"drop6", "drop7"}),
        "no_lrn_no_drop": lambda n: _strip_layers(
            n, {"norm1", "norm2", "drop6", "drop7"}),
    }
    rng = np.random.default_rng(0)
    data = jnp.asarray(rng.normal(size=(1, BATCH, 3, 227, 227)), jnp.float32)
    label = jnp.asarray(rng.integers(0, 1000, size=(1, BATCH)), jnp.float32)
    batch = {"data": data, "label": label}
    iters = int(os.environ.get("PROBE_NET_ITERS", 60))

    for vname, tf in variants.items():
        net = tf(caffenet(BATCH, BATCH))
        sp = load_solver_prototxt_with_net(solver_txt, net)
        solver = Solver(sp, seed=0)
        raw_step = solver.make_train_step()

        def block_fn(params, state, rng):
            def body(i, carry):
                params, state, rng, _ = carry
                rng, sub = jax.random.split(rng)
                params, state, loss = raw_step(params, state, i, batch, sub)
                return (params, state, rng, loss)
            return lax.fori_loop(0, iters, body,
                                 (params, state, rng, jnp.zeros(())))
        block = jax.jit(block_fn)

        t0 = time.perf_counter()
        out = block(solver.params, solver.state, jax.random.PRNGKey(0))
        jax.block_until_ready(out)
        compile_s = time.perf_counter() - t0
        times = []
        for _ in range(REPS):
            t0 = time.perf_counter()
            out = block(solver.params, solver.state, jax.random.PRNGKey(0))
            jax.block_until_ready(out)
            times.append(time.perf_counter() - t0)
        med = sorted(times)[len(times) // 2]
        emit({"exp": f"net_{vname}", "ms_per_step": round(med / iters * 1e3, 3),
              "img_s": round(BATCH * iters / med, 1),
              "compile_s": round(compile_s, 1)})
        log(f"net_{vname}: {med / iters * 1e3:.2f} ms/step "
            f"({BATCH * iters / med:.0f} img/s)")

    # eval forward for scale
    net = caffenet(BATCH, BATCH)
    sp = load_solver_prototxt_with_net(solver_txt, net)
    solver = Solver(sp, seed=0)
    ebatch = {"data": data[0], "label": label[0]}
    out = solver._test_fwd(solver.params, ebatch)
    jax.block_until_ready(out)
    times = []
    for _ in range(REPS):
        t0 = time.perf_counter()
        for _ in range(iters):
            out = solver._test_fwd(solver.params, ebatch)
        jax.block_until_ready(out)
        times.append(time.perf_counter() - t0)
    med = sorted(times)[len(times) // 2]
    emit({"exp": "net_eval_fwd", "ms_per_step": round(med / iters * 1e3, 3),
          "img_s": round(BATCH * iters / med, 1)})
    log(f"net_eval_fwd: {med / iters * 1e3:.2f} ms/step")


# ---------------------------------------------------------------------------
# Part B2: GoogLeNet maxpool backward — select-and-scatter vs the
# VMEM-resident Pallas kernel (VERDICT r3 item 6).  All 13 pools of
# bvlc_googlenet at PROBE_BATCH, fwd+bwd per pool, both paths.
# ---------------------------------------------------------------------------

# (name, c, h/w, kernel, stride, pad) — models/googlenet geometry
GOOGLENET_POOLS = [
    ("pool1_112", 64, 112, 3, 2, 0),
    ("pool2_56", 192, 56, 3, 2, 0),
    ("icp3a_28", 192, 28, 3, 1, 1),
    ("icp3b_28", 256, 28, 3, 1, 1),
    ("pool3_28", 480, 28, 3, 2, 0),
    ("icp4a_14", 480, 14, 3, 1, 1),
    ("icp4b_14", 512, 14, 3, 1, 1),
    ("icp4c_14", 512, 14, 3, 1, 1),
    ("icp4d_14", 512, 14, 3, 1, 1),
    ("icp4e_14", 528, 14, 3, 1, 1),
    ("pool4_14", 832, 14, 3, 2, 0),
    ("icp5a_7", 832, 7, 3, 1, 1),
    ("icp5b_7", 832, 7, 3, 1, 1),
]


def run_poolbwd() -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from sparknet_tpu.ops.pallas_kernels import max_pool_vmem_bwd
    from sparknet_tpu.ops.vision import max_pool, pool_output_size

    rng = np.random.default_rng(0)
    batch = int(os.environ.get("PROBE_POOL_BATCH", 128))
    dtype = jnp.bfloat16 if os.environ.get(
        "PROBE_DTYPE", "bf16") == "bf16" else jnp.float32
    totals = {"s&s": 0.0, "pallas": 0.0}
    skipped: set = set()
    for name, c, hw, k, s, p in GOOGLENET_POOLS:
        oh, ow = pool_output_size(hw, hw, k, k, s, s, p, p)
        x = jnp.asarray(
            np.maximum(rng.normal(size=(batch, c, hw, hw)), 0), dtype)

        def make_iter(fn):
            def it(sc):
                # cast the f32 loop scalar BEFORE the add: bf16 + f32
                # would silently promote the timed tensor to f32
                xq = x + (sc * 1e-30).astype(dtype)

                def f(xx):
                    return fn(xx, k, k, s, s, p, p, oh, ow)
                y, vjp = jax.vjp(f, xq)
                (dx,) = vjp(jnp.ones_like(y))
                return (jnp.sum(y) + jnp.sum(dx)).astype(jnp.float32) * 1e-30
            return it

        for label, fn in (("ss", max_pool), ("pallas", max_pool_vmem_bwd)):
            ms = time_block(f"poolbwd_{name}_{label}", make_iter(fn), 0,
                            extra={"c": c, "hw": hw, "stride": s,
                                   "batch": batch, "dtype": str(dtype.__name__)})
            key = "s&s" if label == "ss" else "pallas"
            if ms is None:  # typed skip (e.g. Pallas on CPU) — a total
                skipped.add(key)  # with holes would read as a win
            else:
                totals[key] += ms
    emit({"exp": "poolbwd_total_ms_per_step",
          "select_and_scatter": (None if "s&s" in skipped
                                 else round(totals["s&s"], 3)),
          "pallas_vmem": (None if "pallas" in skipped
                          else round(totals["pallas"], 3)),
          "incomplete": sorted(skipped) or None,
          "note": "sum over all 13 GoogLeNet pools, fwd+bwd per iter"})
    log(f"poolbwd totals: s&s {totals['s&s']:.2f} ms vs pallas "
        f"{totals['pallas']:.2f} ms per step-equivalent")


# ---------------------------------------------------------------------------
# Part: LRN window-sum reformulation (VERDICT r5 weak #2)
# ---------------------------------------------------------------------------

def run_lrn() -> None:
    """reduce_window vs prefix-sum-difference cross-channel LRN,
    forward and forward+backward, at the LRN shapes of both LRN-bearing
    headline models.  Each pinned variant runs under a one-entry
    SPARKNET_TUNE table (the sanctioned pin path since the env shim was
    retired); tables are read at trace time, so each variant compiles
    its own block.  The layer code under test is the production
    ``ops.vision.LRNLayer``."""
    import tempfile

    import jax
    import jax.numpy as jnp
    import numpy as np

    from sparknet_tpu.graph import tuner
    from sparknet_tpu.models.dsl import layer
    from sparknet_tpu.utils import knobs
    from sparknet_tpu.ops.registry import get_layer_impl

    impl = get_layer_impl("LRN")
    lp = layer("probe_lrn", "LRN", ["x"], ["y"],
               lrn_param={"local_size": 5, "alpha": 1e-4, "beta": 0.75})
    dtype = (jnp.float32 if os.environ.get("PROBE_LRN_DTYPE") == "f32"
             else jnp.bfloat16)
    rng = np.random.default_rng(0)
    div = max(1, int(os.environ.get("PROBE_LRN_BATCH_DIV", "1") or 1))
    shapes = {
        f"googlenet_norm1_b{128 // div}": (128 // div, 64, 56, 56),
        f"googlenet_norm2_b{128 // div}": (128 // div, 192, 56, 56),
        f"caffenet_norm1_b{256 // div}": (256 // div, 96, 55, 55),
        f"caffenet_norm2_b{256 // div}": (256 // div, 256, 27, 27),
    }
    only = os.environ.get("PROBE_LRN_SHAPES", "")
    if only:  # comma-separated substring filter (CPU smokes)
        shapes = {k: v for k, v in shapes.items()
                  if any(s and s in k for s in only.split(","))}
    saved = knobs.raw("SPARKNET_TUNE")
    tmpdir = tempfile.mkdtemp(prefix="probe_lrn_tables_")
    results: dict[str, dict[str, float]] = {}
    try:
        for name, shape in shapes.items():
            x = jnp.asarray(rng.normal(size=shape), dtype)
            nbytes = x.size * x.dtype.itemsize

            def loss(xx):
                y = impl.apply(lp, [], [xx], True, None)[0]
                return jnp.mean(y).astype(jnp.float32)

            # a one-entry table pins each form; the shipping auto
            # default (committed table, else lrn_use_cumsum by channel
            # count) is measured as its own variant so the flip is
            # auditable
            for variant in ("reduce_window", "cumsum", "auto"):
                if variant == "auto":
                    if saved is None:
                        os.environ.pop("SPARKNET_TUNE", None)
                    else:
                        os.environ["SPARKNET_TUNE"] = saved
                else:
                    key = tuner.key_str("lrn", shape, jnp.dtype(dtype),
                                        tuner.lrn_extra(5))
                    path = os.path.join(tmpdir, f"{name}_{variant}.json")
                    tuner.TuningTable(tuner._backend(), [
                        {"key": key, "winner": variant,
                         "timings": {}}]).save(path)
                    os.environ["SPARKNET_TUNE"] = path
                tuner._clear_caches()

                def fwd(s, x=x, loss=loss):
                    return loss(x + s.astype(dtype))

                def fwdbwd(s, x=x, loss=loss):
                    g = jax.grad(loss)(x + s.astype(dtype))
                    return jnp.mean(g).astype(jnp.float32)

                extra = {"shape": list(shape), "dtype": str(jnp.dtype(dtype))}
                f_ms = time_block(f"lrn_{name}_{variant}_fwd", fwd,
                                  extra=extra)
                fb_ms = time_block(f"lrn_{name}_{variant}_fwdbwd", fwdbwd,
                                   extra=extra)
                # None = typed skip (time_block contract) — leave the
                # variant out of the verdict rather than divide by it
                if fb_ms is not None:
                    results.setdefault(name, {})[variant] = fb_ms
                if f_ms is not None:
                    # effective traffic at the fwd floor: read x, write y
                    results.setdefault(name, {})[f"{variant}_fwd_gbps"] = \
                        round(2 * nbytes / max(f_ms, 1e-6) / 1e6, 1)
    finally:
        if saved is None:
            os.environ.pop("SPARKNET_TUNE", None)
        else:
            os.environ["SPARKNET_TUNE"] = saved
        tuner._clear_caches()
    verdict = {
        name: {"speedup_fwdbwd": (
                   round(r["reduce_window"] / max(r["cumsum"], 1e-9), 3)
                   if "reduce_window" in r and "cumsum" in r else None),
               **{k: v for k, v in r.items()}}
        for name, r in results.items()}
    emit({"exp": "lrn_verdict", "dtype": str(jnp.dtype(dtype)),
          "per_shape": verdict})


# ---------------------------------------------------------------------------
# Part C: HLO transpose census
# ---------------------------------------------------------------------------

def run_hlo() -> None:
    import re

    import jax
    import jax.numpy as jnp
    import numpy as np

    from sparknet_tpu.models import caffenet
    from sparknet_tpu.proto import load_solver_prototxt_with_net
    from sparknet_tpu.solvers import Solver

    net = caffenet(BATCH, BATCH)
    sp = load_solver_prototxt_with_net(
        'base_lr: 0.01\nmomentum: 0.9\nweight_decay: 0.0005\n'
        'lr_policy: "step"\ngamma: 0.1\nstepsize: 100000\n', net)
    solver = Solver(sp, seed=0)
    rng = np.random.default_rng(0)
    batch = {"data": jnp.asarray(rng.normal(size=(1, BATCH, 3, 227, 227)),
                                 jnp.float32),
             "label": jnp.asarray(rng.integers(0, 1000, size=(1, BATCH)),
                                  jnp.float32)}
    compiled = solver._step.lower(solver.params, solver.state, 0, batch,
                                  jax.random.PRNGKey(1)).compile()
    txt = compiled.as_text()
    ops: dict[str, int] = {}
    bytes_by_op: dict[str, float] = {}
    for line in txt.splitlines():
        m = re.search(r"=\s+\S+\s+([\w-]+)\(", line)
        mshape = re.search(r"=\s+f32\[([\d,]*)\]", line)
        if not m:
            continue
        op = m.group(1)
        ops[op] = ops.get(op, 0) + 1
        if mshape and op in ("transpose", "copy", "reshape"):
            dims = [int(d) for d in mshape.group(1).split(",") if d]
            nbytes = 4 * int(np.prod(dims)) if dims else 4
            bytes_by_op[op] = bytes_by_op.get(op, 0.0) + nbytes
    top = dict(sorted(ops.items(), key=lambda kv: -kv[1])[:25])
    emit({"exp": "hlo_census", "op_counts": top,
          "layout_bytes_mb": {k: round(v / 1e6, 1)
                              for k, v in bytes_by_op.items()},
          "n_lines": len(txt.splitlines())})
    outp = os.environ.get("PROBE_HLO_OUT")
    if outp:
        with open(outp, "w") as f:
            f.write(txt)
        log(f"HLO written to {outp}")


if __name__ == "__main__":
    argv = list(sys.argv[1:])
    if "--platform" in argv:
        i = argv.index("--platform")
        plat = argv[i + 1]
        del argv[i:i + 2]
        import jax
        jax.config.update("jax_platforms", plat)
    parts = [a for a in argv if not a.startswith("-")] or ["ops", "net", "hlo"]
    import jax
    dev = jax.devices()[0]
    log(f"device: {dev.platform}/{dev.device_kind}")
    emit({"exp": "device", "device": f"{dev.platform}/{dev.device_kind}",
          "batch": BATCH})
    for p in parts:
        {"ops": run_ops, "net": run_net, "hlo": run_hlo,
         "poolbwd": run_poolbwd, "lrn": run_lrn}[p]()
