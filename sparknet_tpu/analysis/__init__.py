"""sparklint — the project-contract static analyzer.

AST-based, multi-pass, stdlib-only (runs in CI without JAX devices).
Four rule families, each machine-checking a contract the repo already
claims in prose:

- **TP (trace purity)** — functions reachable from jit / custom_vjp /
  pallas_call roots must not read env, clocks, host RNG, files, or
  print: those silently bake trace-time constants into compiled code
  and break off-vs-auto bit parity and jit cache keys.
- **KR (knob registry)** — every ``SPARKNET_*`` env read resolves
  through the typed registry in ``utils/knobs.py``; unregistered
  reads, registry bypasses, dead registrations, and KNOBS.md drift are
  errors.
- **CD (concurrency discipline)** — classes that spawn threads guard
  cross-thread attribute mutation (or declare ``_unguarded_ok``),
  worker loops surface errors as typed failures instead of swallowing
  them, and broad ``except`` needs a reason.
- **DP (deprecation hygiene)** — knobs/symbols past their one-release
  window fail lint wherever they still appear.

Entry points: :func:`sparknet_tpu.analysis.engine.load_project`,
:func:`sparknet_tpu.analysis.engine.run_rules`, and the
``tools/lint.py`` CLI.  See WALKTHROUGH §6.16 for the suppression
(``# sparklint: disable=...``) and baseline workflow.
"""

from .core import Baseline, Finding, SourceFile, Project  # noqa: F401
from .engine import load_project, run_rules  # noqa: F401
