"""Serving plane: dynamic micro-batched inference with admission control.

The eval path sustains tens of thousands of images per second through one
compiled forward (BENCH_r05: 46,343 img/s bf16 eval on a v5e chip), but a
caller arriving with ONE image sees none of it — a batch-1 dispatch pays
the whole per-call overhead and leaves the matrix units idle.  Caffe con
Troll's core lesson (arXiv 1504.04343) applies directly: with a fixed,
fast kernel library the remaining win is the batching/scheduling harness
around it — exactly the layer the original Caffe deployment story
(arXiv 1408.5093) left to the integrator.  This module is that harness:

- :class:`InferenceEngine` — a thread-safe request queue with **dynamic
  micro-batching**: pending requests for the same model coalesce until
  either the oldest request's deadline (``SPARKNET_SERVE_MAX_DELAY_MS``)
  expires or the largest compiled batch shape fills, whichever is first.
  The coalesced batch is padded to one of a SMALL, FIXED set of
  pre-compiled batch shapes (``SPARKNET_SERVE_SHAPES``) so the hot path
  never recompiles; pad rows are zeros and their outputs are masked off
  at demux (per-example nets make row ``i`` depend only on input row
  ``i``, so a request batched with strangers returns bit-identical
  logits to a solo run padded to the same shape — tested).  Per-request
  latency stamps (queue / infer / total) ride every result.
- :class:`ModelHouse` — multi-model hot-load from the zoo by name (plus
  optional ``.caffemodel``/npz weights), LRU-evicted under an HBM budget
  (``SPARKNET_SERVE_HBM_MB``).  Loading compiles every batch shape as
  warm-up, OFF the request path; ``submit`` to an unloaded model is a
  typed error, never an inline compile.
- **Admission control** — a bounded queue depth (typed
  :class:`Overloaded` rejection instead of unbounded latency) and
  per-tenant QPS token buckets reusing the fleet's tenant vocabulary
  (``tools/serve.py --quota tenant=qps``).
- **Liveness** — the engine publishes PR-2 health-plane beacons
  (``health.write_beat`` into ``SPARKNET_HEARTBEAT_DIR``) with serving
  extras: queue depth, in-flight batches, p50/p99 latency, completion
  and rejection counters — so ``tools/fleet.py status`` folds a serving
  job into the same table as training jobs.
- **Closed-loop load harness** — :func:`run_closed_loop` /
  :func:`solo_references` drive paced or saturating clients against an
  engine and report p50/p95/p99 latency, achieved vs offered QPS,
  rejection counts, and batch-occupancy histograms.  Shared by
  ``tools/serveload.py``, the ``bench.py`` serving leg, and the tests.

Failure semantics mirror ``data.pipeline.DecodePool``: a dead engine is
a typed :class:`EngineDead` on every waiter and every later submit —
never a hang; a per-batch model failure fails THAT batch's requests and
leaves the engine serving.

- **SLO monitor** — :class:`SLOMonitor` evaluates declared SLOs (a p99
  latency bound and a rejection-rate budget) burn-rate-style over
  periodic snapshots of the engine's PR-8 metrics: a breach requires
  the error budget to burn in BOTH a fast and a slow window (the
  multi-window pattern — a one-second blip never pages, a sustained
  overload does).  Breaching windows are dumped through the existing
  telemetry FlightRecorder; ``tools/serve.py`` surfaces the verdict at
  ``GET /slo`` and the health beacons carry it into
  ``tools/fleet.py status``.

Env knobs (defaults in :class:`ServeConfig`):
  SPARKNET_SERVE_MAX_DELAY_MS — coalesce deadline (default 5 ms).
  SPARKNET_SERVE_SHAPES       — compiled batch shapes (default 1,4,16,64).
  SPARKNET_SERVE_QUEUE        — admission bound on queued requests (256).
  SPARKNET_SERVE_HBM_MB       — model-house HBM budget (2048 MB).
  SPARKNET_SERVE_FORCE_ADMIT  — 1 admits models larger than the whole
                                budget (default: typed OverBudget).
  SPARKNET_SERVE_QUOTAS       — tenant=qps[,tenant=qps...] caps (the
                                env spelling of --quota; how fleet
                                replicas inherit tenant caps).
  SPARKNET_SERVE_DTYPE        — compute dtype, bf16 (default) or f32.
  SPARKNET_SLO_P99_MS         — declared p99 bound (default: latency SLO
                                undeclared).
  SPARKNET_SLO_REJECT_BUDGET  — rejection+failure budget as a fraction
                                of offered requests (default 0.02).
  SPARKNET_SLO_WINDOW_S       — slow burn window (default 60 s; the
                                fast window is SPARKNET_SLO_FAST_S,
                                default 5 s).
"""

from __future__ import annotations

import dataclasses
import os
import threading
import time
from collections import OrderedDict, deque
from typing import Any, Callable, Mapping, Sequence

import numpy as np

from ..utils import knobs, telemetry


# ---------------------------------------------------------------------------
# Typed errors — admission and liveness failures are API, not stack traces
# ---------------------------------------------------------------------------

class ServingError(RuntimeError):
    """Base class for serving-plane failures."""


class Overloaded(ServingError):
    """Typed admission rejection — the bounded-queue / rate-cap answer to
    overload (callers see THIS, not an unbounded latency tail).
    ``reason`` is ``"queue_full"`` or ``"tenant_rate"``."""

    def __init__(self, reason: str, detail: str = ""):
        self.reason = reason
        super().__init__(f"overloaded ({reason})"
                         + (f": {detail}" if detail else ""))


class EngineDead(ServingError):
    """The engine stopped or its dispatcher died: every pending waiter and
    every later submit gets this — a dead serving plane is a typed error,
    never a hang (the ``DecodePool`` contract)."""


class UnknownModel(ServingError):
    """Submit against a model the house has not loaded.  Loading compiles
    (warm-up), which belongs OFF the request path — load explicitly via
    ``ModelHouse.load`` / the server's ``/v1/models/load``."""


class OverBudget(ServingError):
    """Typed load-time rejection: the model ALONE exceeds the house's
    HBM budget (``SPARKNET_SERVE_HBM_MB``), so no amount of LRU eviction
    could make it fit.  Raised before any warm-up compile is paid.
    Override with ``ModelHouse.load(..., force=True)`` (the server's
    ``{"force": true}`` load payload, or ``SPARKNET_SERVE_FORCE_ADMIT=1``
    for every load) when oversubscribing HBM is a deliberate choice."""

    def __init__(self, name: str, param_mb: float, budget_mb: float):
        self.model = name
        self.param_mb = param_mb
        self.budget_mb = budget_mb
        super().__init__(
            f"model {name!r} needs {param_mb:.1f} MB of params but the "
            f"HBM budget is {budget_mb:g} MB — it could never fit; "
            f"load with force=True (or SPARKNET_SERVE_FORCE_ADMIT=1) to "
            f"admit it anyway")


# ---------------------------------------------------------------------------
# Env knob parsing
# ---------------------------------------------------------------------------

def _env_float(name: str, default: float) -> float:
    raw = knobs.raw(name, "")
    if not raw:
        return default
    try:
        return float(raw)
    except ValueError:
        raise ValueError(f"{name} must be a number, got {raw!r}") from None


def _env_quotas(name: str) -> dict[str, float]:
    """``SPARKNET_SERVE_QUOTAS=acme=200,beta=50`` -> {tenant: qps} (the
    env spelling of ``--quota``, so fleet-launched replicas inherit
    tenant caps with no per-replica CLI)."""
    raw = knobs.raw(name, "")
    quotas: dict[str, float] = {}
    for item in raw.split(","):
        item = item.strip()
        if not item:
            continue
        tenant, _, qps = item.partition("=")
        try:
            quotas[tenant] = float(qps)
        except ValueError:
            raise ValueError(
                f"{name} wants tenant=qps pairs, got {item!r}") from None
    return quotas


def _env_shapes(name: str, default: tuple[int, ...]) -> tuple[int, ...]:
    raw = knobs.raw(name, "")
    if not raw:
        return default
    try:
        shapes = tuple(sorted({int(s) for s in raw.split(",") if s.strip()}))
    except ValueError:
        raise ValueError(
            f"{name} must be comma-separated ints, got {raw!r}") from None
    if not shapes or shapes[0] < 1:
        raise ValueError(f"{name} needs positive batch shapes, got {raw!r}")
    return shapes


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Engine + model-house configuration (env defaults, CLI-overridable)."""

    batch_shapes: tuple[int, ...] = dataclasses.field(
        default_factory=lambda: _env_shapes("SPARKNET_SERVE_SHAPES",
                                            (1, 4, 16, 64)))
    max_delay_ms: float = dataclasses.field(
        default_factory=lambda: _env_float("SPARKNET_SERVE_MAX_DELAY_MS",
                                           5.0))
    max_queue: int = dataclasses.field(
        default_factory=lambda: int(_env_float("SPARKNET_SERVE_QUEUE", 256)))
    # dispatched-but-not-demuxed batch window: >1 pipelines host
    # pad/demux under device compute (jax async dispatch)
    inflight_batches: int = dataclasses.field(
        default_factory=lambda: int(_env_float("SPARKNET_SERVE_INFLIGHT",
                                               2)))
    hbm_budget_mb: float = dataclasses.field(
        default_factory=lambda: _env_float("SPARKNET_SERVE_HBM_MB", 2048.0))
    dtype: str = dataclasses.field(
        default_factory=lambda: knobs.raw("SPARKNET_SERVE_DTYPE",
                                          "bf16"))
    # per-tenant offered-QPS caps (the fleet's tenant vocabulary; absent
    # tenant = uncapped, "*" caps every tenant without an explicit entry)
    tenant_qps: Mapping[str, float] = dataclasses.field(
        default_factory=lambda: _env_quotas("SPARKNET_SERVE_QUOTAS"))
    beat_every_s: float = 1.0
    seed: int = 0
    # declared SLOs (see SLOMonitor): a p99 bound (None = latency SLO
    # undeclared) and a rejection-rate error budget, evaluated over a
    # fast + slow burn window pair
    slo_p99_ms: float | None = dataclasses.field(
        default_factory=lambda: (
            _env_float("SPARKNET_SLO_P99_MS", 0.0) or None))
    slo_reject_budget: float = dataclasses.field(
        default_factory=lambda: _env_float("SPARKNET_SLO_REJECT_BUDGET",
                                           0.02))
    slo_window_s: float = dataclasses.field(
        default_factory=lambda: _env_float("SPARKNET_SLO_WINDOW_S", 60.0))
    slo_fast_window_s: float = dataclasses.field(
        default_factory=lambda: _env_float("SPARKNET_SLO_FAST_S", 5.0))
    slo_burn_fast: float = 4.0    # fast-window burn-rate trip point
    slo_burn_slow: float = 1.0    # slow-window burn-rate trip point
    slo_min_requests: int = 20    # don't page on a handful of requests
    slo_sample_every_s: float = 0.5

    def __post_init__(self):
        shapes = tuple(sorted(set(int(s) for s in self.batch_shapes)))
        if not shapes or shapes[0] < 1:
            raise ValueError(f"batch_shapes must be positive: {shapes}")
        object.__setattr__(self, "batch_shapes", shapes)
        if self.max_delay_ms < 0:
            raise ValueError(f"max_delay_ms must be >= 0, "
                             f"got {self.max_delay_ms}")
        if self.max_queue < 1:
            raise ValueError(f"max_queue must be >= 1, got {self.max_queue}")
        if self.inflight_batches < 1:
            raise ValueError(f"inflight_batches must be >= 1, "
                             f"got {self.inflight_batches}")
        if self.dtype not in ("bf16", "f32"):
            raise ValueError(f"dtype must be bf16 or f32, got {self.dtype!r}")
        for t, q in dict(self.tenant_qps).items():
            if q <= 0:
                raise ValueError(f"tenant {t!r}: qps cap must be > 0")
        if self.slo_p99_ms is not None and self.slo_p99_ms <= 0:
            raise ValueError(f"slo_p99_ms must be > 0, "
                             f"got {self.slo_p99_ms}")
        if not 0.0 < self.slo_reject_budget <= 1.0:
            raise ValueError(f"slo_reject_budget must be in (0, 1], "
                             f"got {self.slo_reject_budget}")
        if self.slo_fast_window_s <= 0 or (self.slo_window_s
                                           < self.slo_fast_window_s):
            raise ValueError(
                f"SLO windows need 0 < fast ({self.slo_fast_window_s}) "
                f"<= slow ({self.slo_window_s})")


# ---------------------------------------------------------------------------
# Deploy-net transform + zoo registry
# ---------------------------------------------------------------------------

# data-source layer types (their tops come from the host, not the graph)
_DATA_TYPES = frozenset({
    "JavaData", "MemoryData", "Data", "DummyData", "HDF5Data", "ImageData",
    "WindowData", "Input",
})


def zoo_models() -> dict[str, Callable[[], Any]]:
    """Name -> NetParameter factory for every servable zoo model."""
    from ..models import (
        alexnet, caffenet, cifar10_full, cifar10_quick, googlenet, lenet,
        vgg16,
    )
    return {
        "lenet": lambda: lenet(1, 1),
        "cifar10_quick": lambda: cifar10_quick(1, 1),
        "cifar10_full": lambda: cifar10_full(1, 1),
        "alexnet": lambda: alexnet(1, 1),
        "caffenet": lambda: caffenet(1, 1),
        "googlenet": lambda: googlenet(1, 1, crop=224),
        "vgg16": lambda: vgg16(1, 1, crop=224),
    }


def deploy_from(net_param, max_batch: int):
    """Train/test zoo NetParameter -> deploy NetParameter: data layers,
    loss layers and Accuracy dropped, a trailing Softmax ``prob`` head
    added on the (last TEST-phase) loss layer's logits, and a net-level
    ``input: "data"`` declaration at ``max_batch`` (the largest compiled
    serving shape).  Layer names are untouched, so trained weights load
    by name exactly as ``Net::CopyTrainedLayersFrom`` would."""
    from ..models.dsl import softmax_layer
    from ..proto.caffe_pb import BlobShape, NetParameter, NetState, Phase

    test_state = NetState(Phase.TEST)
    kept = []
    logits = None
    for lp in net_param.layer:
        if not lp.included_in(test_state):
            continue
        if lp.type in _DATA_TYPES:
            continue
        if lp.type == "Accuracy":
            continue
        if lp.type.endswith("Loss"):
            # per-head loss layers (googlenet aux heads are TRAIN-only and
            # already phase-filtered); the LAST surviving loss names the
            # deploy head's logits
            if lp.bottom:
                logits = lp.bottom[0]
            continue
        kept.append(lp)
    # the data-layer shape, via the phase-filtered original net's shape
    # inference (cheap: no params are built)
    from ..graph.net import Net
    probe = Net(net_param, test_state)
    if "data" not in probe.input_blobs:
        raise ValueError(
            f"net {net_param.name!r} has no 'data' input blob")
    in_shape = tuple(probe.input_blobs["data"][1:])
    if logits is None:
        raise ValueError(
            f"net {net_param.name!r}: no loss layer to derive the deploy "
            f"head from")
    if not (kept and kept[-1].type == "Softmax" and logits in kept[-1].top):
        kept = kept + [softmax_layer("prob", logits, "prob")]
    return NetParameter(
        name=f"{net_param.name}_deploy", layer=kept,
        input=["data"],
        input_shape=[BlobShape(dim=[int(max_batch), *in_shape])]), in_shape


class LoadedModel:
    """One servable model: deploy net + params + a jitted forward with
    every serving batch shape pre-compiled (warm-up at load time — the
    request path never compiles)."""

    def __init__(self, name: str, net_param, cfg: ServeConfig,
                 weights: str | None = None,
                 max_param_mb: float | None = None,
                 version: str | None = None):
        import jax
        import jax.numpy as jnp

        from ..graph.net import Net
        from ..proto.caffe_pb import NetState, Phase

        t0 = time.perf_counter()
        deploy, self.in_shape = deploy_from(net_param, cfg.batch_shapes[-1])
        self.name = name
        self.version = version     # registry version id, None by-name
        self.dtype = cfg.dtype
        self.batch_shapes = cfg.batch_shapes
        self.net = Net(deploy, NetState(Phase.TEST),
                       compute_dtype=jnp.bfloat16 if cfg.dtype == "bf16"
                       else None)
        self.params = self.net.init(jax.random.PRNGKey(cfg.seed))
        if weights:
            from ..solvers.solver import load_weights_into
            self.params = load_weights_into(self.net, self.params, weights)
        self.weights = weights
        self.param_bytes = sum(
            np.asarray(b).nbytes for blobs in self.params.values()
            for b in blobs)
        # budget verdict BEFORE warm-up: an over-budget model is a typed
        # rejection that never pays (or holds the house through) the
        # per-shape compiles
        if max_param_mb is not None and self.param_bytes > max_param_mb \
                * 2**20:
            raise OverBudget(name, self.param_bytes / 2**20, max_param_mb)
        out_blob = self.net.output_blobs[-1]
        self.classes = int(self.net.blob_shapes[out_blob][-1])
        # f32 result rows regardless of compute dtype: the demux hands
        # callers a stable dtype and the cast is deterministic, so the
        # bit-identity contract survives it
        self._fwd = jax.jit(
            lambda p, x: self.net.apply(
                p, {"data": x}, train=False).blobs[out_blob]
            .astype(jnp.float32))
        # warm-up: compile every serving shape now, off the request path
        for s in self.batch_shapes:
            jax.block_until_ready(self._fwd(
                self.params,
                jnp.zeros((s,) + self.in_shape, jnp.float32)))
        from ..utils.profiling import fwd_cost_flops
        big = self.batch_shapes[-1]
        flops = fwd_cost_flops(
            self._fwd, self.params,
            jnp.zeros((big,) + self.in_shape, jnp.float32))
        self.flops_per_image = flops / big if flops else None
        self.compile_s = round(time.perf_counter() - t0, 3)
        self.last_used = time.monotonic()

    def pad_shape(self, n: int) -> int:
        """Smallest pre-compiled batch shape holding ``n`` requests."""
        for s in self.batch_shapes:
            if s >= n:
                return s
        return self.batch_shapes[-1]

    def infer_async(self, batch: np.ndarray):
        """Dispatch one compiled forward on an already-padded batch and
        return the on-device result WITHOUT waiting (jax async dispatch —
        the engine pipelines host pad/demux under device compute)."""
        import jax.numpy as jnp
        return self._fwd(self.params, jnp.asarray(batch))

    def infer(self, batch: np.ndarray) -> np.ndarray:
        """One compiled forward on an already-padded batch -> (S, classes)
        f32 probabilities (synchronous convenience)."""
        import jax
        return np.asarray(jax.device_get(self.infer_async(batch)))

    def info(self) -> dict[str, Any]:
        return {"name": self.name, "version": self.version,
                "in_shape": list(self.in_shape),
                "classes": self.classes, "dtype": self.dtype,
                "param_mb": round(self.param_bytes / 2**20, 3),
                "batch_shapes": list(self.batch_shapes),
                "flops_per_image": self.flops_per_image,
                "compile_s": self.compile_s,
                "weights": self.weights}


class ModelHouse:
    """Hot-load/evict zoo models by name under an HBM budget.

    ``load`` builds + warm-up-compiles OUTSIDE the lock (loading model B
    must not stall serving model A), then admits it and LRU-evicts until
    the budget holds again (the newly loaded model is never the victim).
    A single model larger than the whole budget is a typed
    :class:`OverBudget` rejection at load time, BEFORE any warm-up
    compile — unless forced (``force=True`` per call, or
    ``SPARKNET_SERVE_FORCE_ADMIT=1`` for every load), in which case it
    is admitted alone with a stderr note.
    """

    def __init__(self, cfg: ServeConfig):
        self.cfg = cfg
        self._lock = threading.Lock()
        self._models: "OrderedDict[str, LoadedModel]" = OrderedDict()
        self.evictions = 0

    def load(self, name: str, weights: str | None = None,
             force: bool | None = None) -> LoadedModel:
        with self._lock:
            hit = self._models.get(name)
            if hit is not None and hit.weights == weights:
                self._models.move_to_end(name)
                return hit
        zoo = zoo_models()
        if name not in zoo:
            raise UnknownModel(
                f"model {name!r} not in the zoo (known: {sorted(zoo)})")
        if force is None:
            force = knobs.raw("SPARKNET_SERVE_FORCE_ADMIT") == "1"
        lm = LoadedModel(name, zoo[name](), self.cfg, weights=weights,
                         max_param_mb=None if force
                         else self.cfg.hbm_budget_mb)
        with self._lock:
            self._models[name] = lm
            self._models.move_to_end(name)
            self._evict_over_budget(keep=name)
        return lm

    def load_version(self, model: str, version: str, registry=None,
                     force: bool | None = None) -> LoadedModel:
        """Load one PUBLISHED registry version under its versioned
        serving key (``model@version``): the manifest resolves the
        weights (sha-checked against the bundle) and the model serves
        bit-identically wherever that version id lands.  ``registry``
        defaults to the ``SPARKNET_REGISTRY_DIR`` one; no registry
        configured is a loud error, not a silent by-name fallback."""
        from .registry import active_registry, versioned
        if registry is None:
            registry = active_registry()
        if registry is None:
            raise ValueError(
                f"cannot load {model!r} version {version!r}: no model "
                f"registry configured — set SPARKNET_REGISTRY_DIR (or "
                f"pass one) so version ids resolve to artifact bundles")
        manifest = registry.manifest(model, version)  # typed when absent
        key = versioned(model, version)
        with self._lock:
            hit = self._models.get(key)
            if hit is not None:
                self._models.move_to_end(key)
                return hit
        zoo = zoo_models()
        if model not in zoo:
            raise UnknownModel(
                f"model {model!r} not in the zoo (known: {sorted(zoo)})")
        if force is None:
            force = knobs.raw("SPARKNET_SERVE_FORCE_ADMIT") == "1"
        lm = LoadedModel(key, zoo[model](), self.cfg,
                         weights=registry.weights_path(model, version),
                         max_param_mb=None if force
                         else self.cfg.hbm_budget_mb, version=version)
        lm.declared_slo = manifest.get("slo")
        with self._lock:
            self._models[key] = lm
            self._models.move_to_end(key)
            self._evict_over_budget(keep=key)
        return lm

    def _evict_over_budget(self, keep: str) -> None:
        import sys
        budget = self.cfg.hbm_budget_mb * 2**20
        while (sum(m.param_bytes for m in self._models.values()) > budget
               and len(self._models) > 1):
            victim, _ = next(iter(self._models.items()))
            if victim == keep:
                self._models.move_to_end(victim, last=True)
                continue
            self._models.pop(victim)
            self.evictions += 1
        total = sum(m.param_bytes for m in self._models.values())
        if total > budget:
            print(f"[serving] model {keep!r} alone exceeds the "
                  f"{self.cfg.hbm_budget_mb:g} MB HBM budget "
                  f"({total / 2**20:.1f} MB) — force-admitted anyway",
                  file=sys.stderr)

    def get(self, name: str) -> LoadedModel:
        """The loaded model, LRU-touched — typed UnknownModel when absent
        (loading compiles; it never happens implicitly on this path)."""
        with self._lock:
            lm = self._models.get(name)
            if lm is None:
                raise UnknownModel(
                    f"model {name!r} is not loaded "
                    f"(loaded: {sorted(self._models) or '[]'}); load it "
                    f"first — warm-up compile stays off the request path")
            self._models.move_to_end(name)
            lm.last_used = time.monotonic()
            return lm

    def evict(self, name: str) -> bool:
        with self._lock:
            return self._models.pop(name, None) is not None

    def loaded(self) -> dict[str, dict[str, Any]]:
        with self._lock:
            return {n: m.info() for n, m in self._models.items()}


# ---------------------------------------------------------------------------
# Admission control
# ---------------------------------------------------------------------------

class TokenBucket:
    """Per-tenant QPS cap: ``rate`` tokens/s, burst of ``max(1, rate)``.
    Thread-compatible (callers hold the engine lock)."""

    def __init__(self, rate: float, clock: Callable[[], float]):
        self.rate = float(rate)
        self.burst = max(1.0, self.rate)
        self._tokens = self.burst
        self._clock = clock
        self._last = clock()

    def allow(self) -> bool:
        now = self._clock()
        self._tokens = min(self.burst,
                           self._tokens + (now - self._last) * self.rate)
        self._last = now
        if self._tokens >= 1.0:
            self._tokens -= 1.0
            return True
        return False


# ---------------------------------------------------------------------------
# Requests, futures, results
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class ServeResult:
    """One demultiplexed prediction with its latency stamps."""

    model: str
    probs: np.ndarray          # (classes,) float32
    tenant: str
    request_id: int
    queue_ms: float            # submit -> batch dispatch
    infer_ms: float            # dispatch -> results on host
    total_ms: float            # submit -> demux
    batch_n: int               # real requests in the coalesced batch
    padded_to: int             # compiled shape the batch ran at

    @property
    def top(self) -> int:
        return int(np.argmax(self.probs))


class _Request:
    __slots__ = ("id", "model", "x", "tenant", "t_submit", "event",
                 "result", "error")

    def __init__(self, rid: int, model: str, x: np.ndarray, tenant: str,
                 t_submit: float):
        self.id = rid
        self.model = model
        self.x = x
        self.tenant = tenant
        self.t_submit = t_submit
        self.event = threading.Event()
        self.result: ServeResult | None = None
        self.error: BaseException | None = None


class ServeFuture:
    """Handle for one in-flight request.  ``result()`` polls in bounded
    slices and re-checks engine liveness each wake, so a dead engine is a
    typed :class:`EngineDead` within ~2 polls — never a hang."""

    _POLL_S = 0.1

    def __init__(self, engine: "InferenceEngine", req: _Request):
        self._engine = engine
        self._req = req

    def done(self) -> bool:
        return self._req.event.is_set()

    def result(self, timeout: float | None = None) -> ServeResult:
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        while not self._req.event.wait(self._POLL_S):
            if not self._engine.alive:
                raise EngineDead(
                    f"engine died while request #{self._req.id} "
                    f"({self._req.model}) was pending: "
                    f"{self._engine.death_note}")
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError(
                    f"request #{self._req.id} ({self._req.model}) not "
                    f"served within {timeout:.1f}s")
        if self._req.error is not None:
            raise self._req.error
        assert self._req.result is not None
        return self._req.result


# ---------------------------------------------------------------------------
# SLO monitor — declared objectives evaluated burn-rate-style
# ---------------------------------------------------------------------------

class SLOMonitor:
    """Evaluate declared serving SLOs over periodic snapshots of the
    engine's telemetry counters (the PR-8 metrics: completed / rejected
    / failed totals + trailing p99).

    Two objectives:

    - **availability**: rejections + failures may consume at most
      ``reject_budget`` of offered requests.  Evaluated as a burn rate
      (observed bad-fraction / budget) over a fast AND a slow window —
      the multi-window pattern: the fast window (default 5 s) must burn
      at ``burn_fast``× (default 4×) and the slow window (default 60 s)
      at ``burn_slow``× before a breach is declared, so a one-batch
      blip never pages but a sustained overload does within seconds.
    - **latency**: the windowed p99 (max of sampled trailing p99s) must
      stay under the declared ``p99_ms`` bound in both windows.  The
      bound is ``None`` by default — an undeclared latency SLO is
      honestly not evaluated, never silently passed.

    ``p99_ms`` is runtime-declarable (``monitor.p99_ms = bound``) so a
    load harness can pin the bound it just measured.  On a healthy →
    breach transition the breaching windows are dumped through the
    telemetry FlightRecorder (the crash black box picks up SLO context
    even when nothing crashes); the transition back is recorded too.

    Deliberately engine-agnostic: ``stats_fn`` is any callable
    returning ``{"completed": int, "rejected": {reason: int},
    "failed": int, "p99_ms": float}`` — the tests drive it with a
    scripted fake and a fake clock."""

    def __init__(self, stats_fn: Callable[[], Mapping[str, Any]],
                 cfg: ServeConfig | None = None,
                 clock: Callable[[], float] = time.monotonic):
        self.cfg = cfg or ServeConfig()
        self.stats_fn = stats_fn
        self.p99_ms = self.cfg.slo_p99_ms
        self._clock = clock
        keep = int(max(self.cfg.slo_window_s
                       / max(self.cfg.slo_sample_every_s, 0.05) * 2, 16))
        self._samples: deque[dict] = deque(maxlen=keep)
        self._lock = threading.Lock()
        self.state = "ok"
        self.breaches = 0
        self.dumps = 0
        self.sample_errors = 0
        self.last_sample_error: str | None = None
        self._since: float | None = None
        reg = telemetry.get_registry()
        self._m_breach = reg.counter(
            "slo_breach_total", "SLO breach transitions by kind")
        self._m_ok = reg.gauge(
            "slo_healthy", "1 while every declared SLO holds")
        self._m_ok.set(1.0)
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # -- sampling ---------------------------------------------------------
    def start(self) -> None:
        """Run the background sampler (idempotent)."""
        if self._thread is not None:
            return
        self._thread = threading.Thread(target=self._loop,
                                        name="slo-monitor", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.cfg.slo_sample_every_s + 5.0)

    def _loop(self) -> None:
        while not self._stop.wait(self.cfg.slo_sample_every_s):
            try:
                self.evaluate()
            except Exception as e:
                # a broken scrape must not kill the sampler — park it
                # where summary() carries it out instead of swallowing
                with self._lock:
                    self.sample_errors += 1
                    self.last_sample_error = f"{type(e).__name__}: {e}"

    def _snapshot(self) -> dict:
        st = self.stats_fn()
        rejected = st.get("rejected") or {}
        if isinstance(rejected, Mapping):
            rejected = sum(rejected.values())
        return {"t": self._clock(),
                "completed": int(st.get("completed", 0)),
                "rejected": int(rejected),
                "failed": int(st.get("failed", 0)),
                "p99_ms": float(st.get("p99_ms", 0.0) or 0.0)}

    def _window(self, samples: list[dict], seconds: float) -> dict:
        newest = samples[-1]
        cutoff = newest["t"] - seconds
        oldest = samples[0]
        for s in samples:
            if s["t"] >= cutoff:
                oldest = s
                break
        d_done = newest["completed"] - oldest["completed"]
        d_rej = newest["rejected"] - oldest["rejected"]
        d_fail = newest["failed"] - oldest["failed"]
        total = max(d_done + d_rej + d_fail, 0)
        bad = max(d_rej + d_fail, 0)
        frac = bad / total if total else 0.0
        p99 = max((s["p99_ms"] for s in samples if s["t"] >= cutoff),
                  default=0.0)
        return {"seconds": round(newest["t"] - oldest["t"], 2),
                "requests": total, "bad": bad,
                "bad_frac": round(frac, 4),
                "burn": round(frac / self.cfg.slo_reject_budget, 2),
                "p99_ms": round(p99, 3)}

    # -- evaluation -------------------------------------------------------
    def evaluate(self) -> dict[str, Any]:
        """Take a fresh snapshot, evaluate both windows, handle state
        transitions (recorder events + flight dump on breach).  The
        returned doc is the ``GET /slo`` body."""
        snap = self._snapshot()
        with self._lock:
            self._samples.append(snap)
            samples = list(self._samples)
        fast = self._window(samples, self.cfg.slo_fast_window_s)
        slow = self._window(samples, self.cfg.slo_window_s)
        breaches: list[str] = []
        if (fast["requests"] >= self.cfg.slo_min_requests
                and fast["burn"] >= self.cfg.slo_burn_fast
                and slow["burn"] >= self.cfg.slo_burn_slow):
            breaches.append("availability")
        if (self.p99_ms is not None and fast["requests"] > 0
                and fast["p99_ms"] > self.p99_ms
                and slow["p99_ms"] > self.p99_ms):
            breaches.append("latency")
        new_state = "breach" if breaches else "ok"
        dump_doc = None
        with self._lock:
            old_state = self.state
            self.state = new_state
            if new_state == "breach" and old_state == "ok":
                self.breaches += 1
                self._since = snap["t"]
            elif new_state == "ok":
                self._since = None
            since = self._since
        if new_state == "breach" and old_state == "ok":
            for kind in breaches:
                self._m_breach.inc(kind=kind)
            rec = telemetry.get_recorder()
            rec.record("slo_breach", kinds=breaches, fast=fast,
                       slow=slow, p99_bound_ms=self.p99_ms,
                       reject_budget=self.cfg.slo_reject_budget)
            dump_doc = rec.dump("slo_" + "_".join(breaches))
            with self._lock:
                self.dumps += 1
        elif new_state == "ok" and old_state == "breach":
            telemetry.get_recorder().record(
                "slo_recovered", fast=fast, slow=slow)
        self._m_ok.set(0.0 if breaches else 1.0)
        return {
            "state": new_state,
            "breaches": breaches,
            "declared": {
                "p99_ms": self.p99_ms,
                "reject_budget": self.cfg.slo_reject_budget,
                "window_s": self.cfg.slo_window_s,
                "fast_window_s": self.cfg.slo_fast_window_s,
                "burn_fast": self.cfg.slo_burn_fast,
                "burn_slow": self.cfg.slo_burn_slow,
            },
            "windows": {"fast": fast, "slow": slow},
            "breach_count": self.breaches,
            "flight_dumps": self.dumps,
            "breach_since_s": (round(snap["t"] - since, 1)
                               if since is not None else None),
        }

    def reset(self) -> None:
        """Forget windowed history (a deployment/measurement fence):
        the next evaluation starts from fresh windows.  Load harnesses
        use it to keep a deliberate saturation probe — whose engine-
        level rejections are real but intentional — from burning the
        budget of the leg that follows.  Cumulative counters are
        untouched; only the window samples and breach state clear."""
        with self._lock:
            self._samples.clear()
            self.state = "ok"
            self._since = None
        self._m_ok.set(1.0)

    def summary(self) -> dict[str, Any]:
        """The cheap, lock-light view the health beacons carry."""
        with self._lock:
            out = {"state": self.state, "breaches": self.breaches}
            if self.sample_errors:
                out["sample_errors"] = self.sample_errors
                out["last_sample_error"] = self.last_sample_error
            return out


# ---------------------------------------------------------------------------
# The engine
# ---------------------------------------------------------------------------

_HARVEST_STOP = object()


class InferenceEngine:
    """Thread-safe dynamic micro-batching over a :class:`ModelHouse`.

    Two threads own the hot path.  The **dispatcher** collects the
    ripest model queue (full largest-shape batch, or oldest request past
    the coalesce deadline), pads to the smallest compiled shape, and
    DISPATCHES the forward without waiting (jax async dispatch); the
    **harvester** drains completed batches and demuxes rows back to
    their waiters with latency stamps.  The bounded in-flight window
    between them (``cfg.inflight_batches``) pipelines host pad/demux
    under device compute — the serving analog of the trainer's
    harvest_lag.  ``submit`` applies admission control synchronously;
    rejected work raises :class:`Overloaded` and never occupies queue
    space."""

    def __init__(self, models: ModelHouse, cfg: ServeConfig | None = None,
                 clock: Callable[[], float] = time.monotonic):
        import queue as _queue
        self.models = models
        self.cfg = cfg or models.cfg
        self._clock = clock
        self._cond = threading.Condition()
        self._queues: dict[str, deque[_Request]] = {}
        self._depth = 0
        self._next_id = 0
        self._stopping = False
        self._dead = False
        self.death_note = ""
        self._in_flight = 0
        self._batches_in_flight = 0
        self._buckets: dict[str, TokenBucket] = {}
        # telemetry (guarded by _cond's lock)
        self.completed = 0
        self.failed = 0
        self.rejected = {"queue_full": 0, "tenant_rate": 0}
        self.dispatches = 0
        self._lat_ms: deque[float] = deque(maxlen=4096)
        self._queue_ms: deque[float] = deque(maxlen=4096)
        # occupancy["<padded shape>"][<real n>] = batches dispatched
        self.occupancy: dict[str, dict[int, int]] = {}
        self._t_start = time.monotonic()
        # telemetry plane: live histograms/counters observed on the hot
        # path, point-in-time gauges filled by a scrape-time collector
        # (GET /metrics on tools/serve.py renders the registry)
        reg = telemetry.get_registry()
        self._m_lat = reg.histogram(
            "serve_request_seconds", "request latency, submit to demux")
        self._m_infer = reg.histogram(
            "serve_infer_seconds", "batch dispatch-to-host latency")
        self._m_done = reg.counter(
            "serve_completed_total", "requests answered")
        self._m_failed = reg.counter(
            "serve_failed_total", "requests failed by model errors")
        self._m_rej = reg.counter(
            "serve_rejected_total", "admission rejections by reason")
        reg.add_collector(self._publish_gauges)
        # SLO monitor: burn-rate evaluation over snapshots of the
        # counters above; its sampler rides a small daemon thread
        self.slo = SLOMonitor(self.stats, self.cfg, clock=clock)
        self.slo.start()
        self._harvest_q: "_queue.Queue[Any]" = _queue.Queue(
            maxsize=self.cfg.inflight_batches)
        self._harvester = threading.Thread(
            target=self._harvest_loop, name="serve-harvest", daemon=True)
        self._harvester.start()
        self._dispatcher = threading.Thread(
            target=self._loop, name="serve-dispatch", daemon=True)
        self._dispatcher.start()
        self._beacon: threading.Thread | None = None
        if knobs.is_set("SPARKNET_HEARTBEAT_DIR"):
            self._beacon = threading.Thread(
                target=self._beat_loop, name="serve-beacon", daemon=True)
            self._beacon.start()

    # -- liveness ---------------------------------------------------------
    @property
    def alive(self) -> bool:
        return not (self._dead or self._stopping)

    def _mark_dead(self, note: str) -> None:
        import queue as _queue
        with self._cond:
            self._dead = True
            self.death_note = note
            pending = [r for dq in self._queues.values() for r in dq]
            for dq in self._queues.values():
                dq.clear()
            self._depth = 0
            self._cond.notify_all()
        # batches already dispatched into the harvest window: their
        # waiters must not hang on a dead harvester
        while True:
            try:
                item = self._harvest_q.get_nowait()
            except _queue.Empty:
                break
            if item is not _HARVEST_STOP:
                pending.extend(item[1])
        try:
            self._harvest_q.put_nowait(_HARVEST_STOP)
        except _queue.Full:
            pass
        for r in pending:
            r.error = EngineDead(f"engine died with request pending: {note}")
            r.event.set()

    # -- submission (admission control happens HERE) ----------------------
    def submit(self, model: str, x, tenant: str = "anon") -> ServeFuture:
        """Enqueue one example for ``model``; returns a future.  Raises
        Overloaded / UnknownModel / EngineDead synchronously — admission
        failures never consume queue space."""
        if not self.alive:
            raise EngineDead(f"engine is not serving: "
                             f"{self.death_note or 'stopped'}")
        lm = self.models.get(model)          # typed UnknownModel if absent
        x = np.ascontiguousarray(x, np.float32)
        if x.shape != lm.in_shape:
            raise ServingError(
                f"model {model!r} expects input {lm.in_shape}, "
                f"got {x.shape}")
        with self._cond:
            cap = self.cfg.tenant_qps.get(tenant,
                                          self.cfg.tenant_qps.get("*"))
            if cap is not None:
                bucket = self._buckets.get(tenant)
                if bucket is None or bucket.rate != float(cap):
                    bucket = self._buckets[tenant] = TokenBucket(
                        cap, self._clock)
                if not bucket.allow():
                    self.rejected["tenant_rate"] += 1
                    self._m_rej.inc(reason="tenant_rate")
                    raise Overloaded(
                        "tenant_rate",
                        f"tenant {tenant!r} over its {cap:g} qps cap")
            # the admission bound covers OUTSTANDING work (queued +
            # dispatched-not-answered): it is the engine's worst-case
            # backlog, so max_queue / throughput bounds accepted latency
            if self._depth + self._in_flight >= self.cfg.max_queue:
                self.rejected["queue_full"] += 1
                self._m_rej.inc(reason="queue_full")
                raise Overloaded(
                    "queue_full",
                    f"{self._depth} queued + {self._in_flight} in flight "
                    f"(bound {self.cfg.max_queue})")
            req = _Request(self._next_id, model, x, tenant, self._clock())
            self._next_id += 1
            self._queues.setdefault(model, deque()).append(req)
            self._depth += 1
            self._cond.notify_all()
        return ServeFuture(self, req)

    def classify(self, model: str, x, tenant: str = "anon",
                 timeout: float | None = 30.0) -> ServeResult:
        """Blocking convenience: submit + wait."""
        return self.submit(model, x, tenant).result(timeout)

    # -- dispatcher -------------------------------------------------------
    def _loop(self) -> None:
        import queue as _queue
        try:
            while True:
                work = self._collect()
                if work is None:
                    break                        # clean stop
                self._dispatch(*work)
            # graceful stop: let the harvester drain dispatched batches
            # (their waiters get real results), then exit on the sentinel
            while True:
                try:
                    self._harvest_q.put(_HARVEST_STOP, timeout=0.1)
                    return
                except _queue.Full:
                    if self._dead:
                        return
        except BaseException as e:  # dispatcher death -> typed, never a hang
            self._mark_dead(f"dispatcher died: {e!r}")

    def _collect(self):
        """Block until a model queue is ripe: the largest compiled shape
        fills (dispatch NOW — waiting longer buys nothing), or the oldest
        request crosses the coalesce deadline.  Returns (model, requests)
        or None when stopping."""
        max_shape = self.cfg.batch_shapes[-1]
        delay_s = self.cfg.max_delay_ms / 1000.0
        with self._cond:
            while True:
                if self._stopping or self._dead:
                    return None
                now = self._clock()
                ripe = None
                next_deadline = None
                for name, dq in self._queues.items():
                    if not dq:
                        continue
                    if len(dq) >= max_shape:
                        ripe = name
                        break
                    deadline = dq[0].t_submit + delay_s
                    if deadline <= now:
                        ripe = name
                        break
                    if next_deadline is None or deadline < next_deadline:
                        next_deadline = deadline
                if ripe is not None:
                    dq = self._queues[ripe]
                    take = min(len(dq), max_shape)
                    reqs = [dq.popleft() for _ in range(take)]
                    self._depth -= take
                    self._in_flight += take
                    self._cond.notify_all()
                    return ripe, reqs
                self._cond.wait(0.05 if next_deadline is None
                                else max(next_deadline - now, 1e-4))

    def _fail_batch(self, reqs: list[_Request], model: str,
                    cause: Exception) -> None:
        """A model failure fails THIS batch's requests (typed) and leaves
        the engine alive."""
        err = ServingError(
            f"batch of {len(reqs)} on {model!r} failed: {cause}")
        err.__cause__ = cause
        self._m_failed.inc(len(reqs))
        telemetry.get_recorder().record(
            "serve_batch_failed", model=model, n=len(reqs),
            cause=repr(cause))
        with self._cond:
            self.failed += len(reqs)
            self._in_flight -= len(reqs)
        for r in reqs:
            r.error = err
            r.event.set()

    def _dispatch(self, model: str, reqs: list[_Request]) -> None:
        """Pad + async-dispatch one coalesced batch into the harvest
        window (backpressured at ``cfg.inflight_batches``)."""
        import queue as _queue
        n = len(reqs)
        t_dispatch = self._clock()
        try:
            with telemetry.span("serve.dispatch", cat="serving",
                                model=model, n=n):
                lm = self.models.get(model)
                shape = lm.pad_shape(n)
                batch = np.zeros((shape,) + lm.in_shape, np.float32)
                for i, r in enumerate(reqs):
                    batch[i] = r.x
                # pad rows computed, masked at demux
                out = lm.infer_async(batch)
        except Exception as e:
            self._fail_batch(reqs, model, e)
            return
        with self._cond:
            self._batches_in_flight += 1
        item = (model, reqs, shape, t_dispatch, out)
        while True:
            try:
                self._harvest_q.put(item, timeout=0.1)
                return
            except _queue.Full:
                if self._dead:       # harvester died; _mark_dead drains
                    return

    def _harvest_loop(self) -> None:
        try:
            while True:
                item = self._harvest_q.get()
                if item is _HARVEST_STOP:
                    return
                self._finish(*item)
        except BaseException as e:  # harvester death -> typed, never a hang
            self._mark_dead(f"harvester died: {e!r}")

    def _finish(self, model: str, reqs: list[_Request], shape: int,
                t_dispatch: float, out) -> None:
        """Wait for one dispatched batch, then demux rows to waiters."""
        import jax
        n = len(reqs)
        try:
            with telemetry.span("serve.batch", cat="serving",
                                model=model, n=n, padded_to=shape):
                probs = np.asarray(jax.device_get(out))
                t_done = self._clock()
        except Exception as e:
            with self._cond:
                self._batches_in_flight -= 1
            self._fail_batch(reqs, model, e)
            return
        from ..utils import faults
        if faults.get_injector().bad_canary(model):
            probs = np.full_like(probs, np.nan)
        if not np.isfinite(probs[:n]).all():
            # a poisoned head (nan/inf rows) must never reach a caller:
            # fail the batch typed — the per-version SLO judge sees the
            # availability burn and the rollout controller rolls back
            with self._cond:
                self._batches_in_flight -= 1
            self._fail_batch(reqs, model, ServingError(
                f"model {model!r} produced non-finite probabilities — "
                f"refusing to serve them"))
            return
        infer_ms = (t_done - t_dispatch) * 1e3
        self._m_infer.observe(infer_ms / 1e3)
        results = []
        for i, r in enumerate(reqs):
            results.append(ServeResult(
                model=model, probs=probs[i], tenant=r.tenant,
                request_id=r.id,
                queue_ms=round((t_dispatch - r.t_submit) * 1e3, 3),
                infer_ms=round(infer_ms, 3),
                total_ms=round((t_done - r.t_submit) * 1e3, 3),
                batch_n=n, padded_to=shape))
        with self._cond:
            self.dispatches += 1
            self.completed += n
            self._in_flight -= n
            self._batches_in_flight -= 1
            by_n = self.occupancy.setdefault(str(shape), {})
            by_n[n] = by_n.get(n, 0) + 1
            for res in results:
                self._lat_ms.append(res.total_ms)
                self._queue_ms.append(res.queue_ms)
        self._m_done.inc(n)
        for res in results:
            self._m_lat.observe(res.total_ms / 1e3)
        tr = telemetry.get_tracer()
        if tr is not None:
            # per-request queue spans, anchored from the latency stamps
            # (submit -> dispatch): with the dispatch and batch spans
            # these make the queue -> coalesce -> infer -> demux story
            # one connected timeline per request
            now_us = time.time() * 1e6
            for res in results:
                tr.complete("serve.queue", "serving",
                            now_us - res.total_ms * 1e3,
                            res.queue_ms * 1e3,
                            {"model": model, "rid": res.request_id})
        for r, res in zip(reqs, results):
            r.result = res
            r.event.set()

    # -- telemetry --------------------------------------------------------
    def _publish_gauges(self) -> None:
        """Scrape-time registry filler (weakly registered): the
        point-in-time numbers a Prometheus scrape or file snapshot
        should carry — queue depth, in-flight work, latency
        percentiles over the trailing window."""
        reg = telemetry.get_registry()
        with self._cond:
            depth = self._depth
            in_flight = self._in_flight
            batches = self._batches_in_flight
            pcts = self._percentiles(self._lat_ms)
        reg.gauge("serve_queue_depth",
                  "requests queued awaiting coalesce").set(depth)
        reg.gauge("serve_in_flight",
                  "requests dispatched, not yet demuxed").set(in_flight)
        reg.gauge("serve_in_flight_batches",
                  "batches dispatched, not yet demuxed").set(batches)
        reg.gauge("serve_p50_ms",
                  "trailing-window p50 request latency").set(pcts["p50_ms"])
        reg.gauge("serve_p99_ms",
                  "trailing-window p99 request latency").set(pcts["p99_ms"])
        reg.gauge("serve_alive", "1 while the engine serves").set(
            1.0 if self.alive else 0.0)

    def _percentiles(self, samples: Sequence[float]) -> dict[str, float]:
        if not samples:
            return {"p50_ms": 0.0, "p95_ms": 0.0, "p99_ms": 0.0}
        arr = np.asarray(samples, np.float64)
        p50, p95, p99 = np.percentile(arr, [50, 95, 99])
        return {"p50_ms": round(float(p50), 3),
                "p95_ms": round(float(p95), 3),
                "p99_ms": round(float(p99), 3)}

    def stats(self) -> dict[str, Any]:
        with self._cond:
            out = {
                "alive": self.alive,
                "uptime_s": round(time.monotonic() - self._t_start, 1),
                "queue_depth": self._depth,
                "in_flight": self._in_flight,
                "in_flight_batches": self._batches_in_flight,
                "completed": self.completed,
                "failed": self.failed,
                "rejected": dict(self.rejected),
                "dispatches": self.dispatches,
                "occupancy": {s: dict(v)
                              for s, v in self.occupancy.items()},
                **self._percentiles(self._lat_ms),
                "queue_p50_ms": self._percentiles(
                    self._queue_ms)["p50_ms"],
                "batch_shapes": list(self.cfg.batch_shapes),
                "max_delay_ms": self.cfg.max_delay_ms,
                "max_queue": self.cfg.max_queue,
            }
        out["models"] = self.models.loaded()
        out["slo"] = self.slo.summary()
        return out

    # -- liveness beacons (PR-2 health plane) -----------------------------
    def _beat_extras(self) -> dict[str, Any]:
        with self._cond:
            return {
                "serving": True,
                "queue_depth": self._depth,
                "in_flight_batches": self._batches_in_flight,
                "in_flight": self._in_flight,
                "completed": self.completed,
                "rejected": dict(self.rejected),
                **self._percentiles(self._lat_ms),
                "models": sorted(self.models.loaded()),
                "slo": self.slo.summary(),
            }

    def _beat_loop(self) -> None:
        from . import health
        directory = knobs.raw("SPARKNET_HEARTBEAT_DIR")
        rank = knobs.get_int("SPARKNET_PROC_ID", 0)
        attempt = knobs.get_int("SPARKNET_FAULT_ATTEMPT", 0)
        while True:
            with self._cond:
                self._cond.wait(self.cfg.beat_every_s)
                stopping = self._stopping or self._dead
                round_idx = self.dispatches
            try:
                health.write_beat(directory, rank, round_idx,
                                  "final" if stopping else "serving",
                                  attempt=attempt,
                                  extras=self._beat_extras())
            except OSError:
                pass  # an unwritable beacon dir must not kill serving
            if stopping:
                return

    # -- shutdown ---------------------------------------------------------
    def stop(self) -> None:
        """Stop serving: pending + in-flight waiters get typed EngineDead;
        idempotent."""
        with self._cond:
            if self._stopping:
                return
            self._stopping = True
            pending = [r for dq in self._queues.values() for r in dq]
            for dq in self._queues.values():
                dq.clear()
            self._depth = 0
            self.death_note = self.death_note or "engine stopped"
            self._cond.notify_all()
        for r in pending:
            r.error = EngineDead("engine stopped with request queued")
            r.event.set()
        self._dispatcher.join(timeout=10.0)
        self._harvester.join(timeout=10.0)
        self.slo.stop()
        if self._beacon is not None:
            self._beacon.join(timeout=self.cfg.beat_every_s + 5.0)

    def __enter__(self) -> "InferenceEngine":
        return self

    def __exit__(self, *exc) -> None:
        self.stop()


# ---------------------------------------------------------------------------
# Closed-loop load harness (shared by tools/serveload.py, bench.py serving
# leg, and the tests)
# ---------------------------------------------------------------------------

def solo_references(lm: LoadedModel,
                    inputs: Sequence[np.ndarray]) -> dict[int, dict[int,
                                                                    np.ndarray]]:
    """Per compiled shape, the SOLO result row for every input: input i
    padded with zero rows to each batch shape, row 0 kept.  The oracle
    for the bit-identity acceptance check — a request that rides a batch
    of strangers at shape s must equal refs[s][i] exactly."""
    refs: dict[int, dict[int, np.ndarray]] = {}
    for s in lm.batch_shapes:
        by_idx = {}
        for i, x in enumerate(inputs):
            batch = np.zeros((s,) + lm.in_shape, np.float32)
            batch[0] = np.asarray(x, np.float32)
            by_idx[i] = lm.infer(batch)[0]
        refs[s] = by_idx
    return refs


def run_closed_loop(engine: InferenceEngine, model: str,
                    inputs: Sequence[np.ndarray], *,
                    clients: int = 4, window: int = 1,
                    duration_s: float = 2.0,
                    offered_qps: float | None = None,
                    tenant: str = "loadgen",
                    timeout_s: float = 30.0,
                    refs: Mapping[int, Mapping[int, np.ndarray]] | None
                    = None,
                    submit: Callable[[int, np.ndarray], ServeFuture] | None
                    = None) -> dict[str, Any]:
    """Drive ``clients`` closed-loop workers for ``duration_s``, each
    keeping up to ``window`` requests outstanding (a pipelined frontend:
    ``window=1`` is the classic one-at-a-time closed loop; larger
    windows model an async RPC handler and let a handful of threads
    saturate the engine — total concurrency = clients x window).

    ``offered_qps=None`` saturates (each client resubmits the moment
    its window has room — the max-throughput point); with a rate,
    client j schedules arrival k at ``t0 + (j + k*clients)/qps`` and
    sleeps to it (an overloaded engine answers with typed rejections,
    so offered > capacity shows up as ``rejected``, not as a latency
    collapse).  ``refs`` (from :func:`solo_references`) turns on the
    exactness audit: every completed request is compared bit-for-bit
    against its solo reference at the shape it actually rode.
    ``submit(idx, x) -> ServeFuture`` overrides transport (the
    remote-HTTP load path).
    """
    from collections import deque as _deque
    t0 = time.monotonic()
    t_end = t0 + duration_s
    lat_ms: list[list[float]] = [[] for _ in range(clients)]
    done = [0] * clients
    rejected = [0] * clients
    errors = [0] * clients
    mismatches = [0] * clients

    def do_submit(idx: int, x: np.ndarray) -> ServeFuture:
        if submit is not None:
            return submit(idx, x)
        return engine.submit(model, x, tenant=tenant)

    def client(j: int) -> None:
        pend: "_deque[tuple[float, int, ServeFuture]]" = _deque()

        def harvest_one() -> None:
            t_s, idx, fut = pend.popleft()
            try:
                res = fut.result(timeout_s)
            except (ServingError, TimeoutError):
                errors[j] += 1
                return
            lat_ms[j].append((time.monotonic() - t_s) * 1e3)
            done[j] += 1
            if refs is not None:
                ref = refs.get(res.padded_to, {}).get(idx)
                if ref is None or not np.array_equal(res.probs, ref):
                    mismatches[j] += 1

        k = 0
        while True:
            now = time.monotonic()
            if now >= t_end:
                break
            if len(pend) >= window:
                harvest_one()
                continue
            if offered_qps:
                t_arrive = t0 + (j + k * clients) / offered_qps
                if t_arrive >= t_end:
                    break
                if t_arrive > now:
                    if pend and pend[0][2].done():
                        harvest_one()
                    else:
                        time.sleep(min(t_arrive - now, 0.02))
                    continue
            idx = (j + k * clients) % len(inputs)
            k += 1
            t_s = time.monotonic()
            try:
                pend.append((t_s, idx, do_submit(idx, inputs[idx])))
            except Overloaded:
                if offered_qps:
                    # paced: the rejection IS the datum (admission
                    # control absorbing offered > capacity)
                    rejected[j] += 1
                else:
                    # unpaced saturation: back off instead of burning
                    # the loop on rejections — a real closed-loop
                    # client waits for its outstanding work
                    k -= 1
                    if pend:
                        harvest_one()
                    else:
                        time.sleep(0.001)
            except (ServingError, TimeoutError):
                errors[j] += 1
        while pend:   # drain the window; completions past t_end count
            harvest_one()

    threads = [threading.Thread(target=client, args=(j,), daemon=True)
               for j in range(clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=duration_s + timeout_s + 10.0)
    wall = time.monotonic() - t0
    all_lat = sorted(x for lst in lat_ms for x in lst)
    pct = (lambda q: round(float(np.percentile(all_lat, q)), 3)
           if all_lat else 0.0)
    completed = sum(done)
    return {
        "offered_qps": round(offered_qps, 1) if offered_qps else None,
        "achieved_qps": round(completed / wall, 1),
        "clients": clients,
        "window": window,
        "duration_s": round(wall, 2),
        "completed": completed,
        "rejected": sum(rejected),
        "errors": sum(errors),
        "exact_mismatches": sum(mismatches) if refs is not None else None,
        "p50_ms": pct(50), "p95_ms": pct(95), "p99_ms": pct(99),
        "max_ms": round(all_lat[-1], 3) if all_lat else 0.0,
        "mean_ms": round(float(np.mean(all_lat)), 3) if all_lat else 0.0,
    }
