"""Spark RDD → partition bridge: the data tier the reference builds its
whole driver loop around (reference: src/main/scala/apps/ImageNetApp.scala
:89-95 — coalesce(numWorkers) → persist → count → per-partition sizes RDD
→ zipPartitions task dispatch).

The north star keeps Spark for multi-host data loading/sharding.  This
bridge is written against the *minimal* RDD protocol the logic needs —
``getNumPartitions()``, ``coalesce(n)``, ``mapPartitionsWithIndex(f)``,
``collect()`` — which a live ``pyspark.RDD`` satisfies directly and a
local fake can satisfy in tests (this rig has no pyspark; the import is
gated exactly like the s3:// object store).

Topology: on a TPU-VM pod each host process (jax.process_index) owns the
partitions ``i ≡ process_index (mod nprocs)``; worker-side
``mapPartitionsWithIndex`` ships each partition's records to its owner
host, which feeds them to the trainer as a PartitionedDataset — the
zipPartitions data-locality contract without the JVM."""

from __future__ import annotations

from typing import Any, Callable, Iterable

from .partition import PartitionedDataset


def _require_rdd(rdd: Any) -> None:
    for attr in ("getNumPartitions", "coalesce", "mapPartitionsWithIndex",
                 "collect"):
        if not hasattr(rdd, attr):
            raise TypeError(
                f"object {type(rdd).__name__} does not satisfy the RDD "
                f"protocol (missing {attr}); pass a pyspark RDD or a "
                "compatible fake")


def spark_context(app_name: str = "sparknet_tpu"):
    """A live SparkContext — requires pyspark on the driver host
    (gated; reference cluster setup: SETUP.md, ec2/)."""
    try:
        from pyspark import SparkConf, SparkContext
    except ImportError as e:
        raise ImportError(
            "the Spark data tier needs pyspark, which is not in this "
            "build — use PartitionedDataset/load_imagenet for local "
            "sharding, or install pyspark on the driver host") from e
    conf = SparkConf().setAppName(app_name)
    # the reference disables task retry: re-running a side-effectful
    # training task corrupts state (CifarApp.scala:36)
    conf.set("spark.task.maxFailures", "1")
    return SparkContext(conf=conf)


class SparkPartitionBridge:
    """Shard an RDD of records across hosts the way the reference's apps
    shard across executors."""

    def __init__(self, rdd: Any, num_workers: int,
                 process_index: int = 0, num_processes: int = 1):
        _require_rdd(rdd)
        if num_workers % num_processes:
            raise ValueError(
                f"num_workers={num_workers} must divide evenly across "
                f"{num_processes} host processes")
        n = rdd.getNumPartitions()
        if n < num_workers and hasattr(rdd, "repartition"):
            # pyspark coalesce cannot INCREASE partition count without a
            # shuffle — repartition does
            rdd = rdd.repartition(num_workers)
        elif n != num_workers:
            rdd = rdd.coalesce(num_workers)
        if rdd.getNumPartitions() != num_workers:
            raise ValueError(
                f"could not shard RDD into {num_workers} partitions "
                f"(got {rdd.getNumPartitions()}); repartition the source")
        self.rdd = rdd
        self.num_workers = num_workers
        self.process_index = process_index
        self.num_processes = num_processes

    def partition_sizes(self) -> list[int]:
        """Per-partition element counts (the trainPartitionSizes RDD,
        reference: ImageNetApp.scala:94-95)."""
        pairs = self.rdd.mapPartitionsWithIndex(
            lambda i, it: [(i, sum(1 for _ in it))]).collect()
        sizes = [0] * self.num_workers
        for i, n in pairs:
            sizes[i] = n
        return sizes

    def local_partition_indices(self) -> list[int]:
        """Partitions owned by this host process."""
        return list(range(self.process_index, self.num_workers,
                          self.num_processes))

    def to_local_dataset(self,
                         transform: Callable[[Any], Any] | None = None,
                         ) -> PartitionedDataset:
        """Materialize THIS host's partitions as a PartitionedDataset
        (records optionally mapped by ``transform`` worker-side).  The
        collect ships only the owned partitions' records."""
        owned = set(self.local_partition_indices())

        def keep(i: int, it: Iterable[Any]):
            if i not in owned:
                return iter(())
            if transform is None:
                return ((i, x) for x in it)
            return ((i, transform(x)) for x in it)

        parts: dict[int, list[Any]] = {i: [] for i in owned}
        for i, x in self.rdd.mapPartitionsWithIndex(keep).collect():
            parts[i].append(x)
        return PartitionedDataset([parts[i] for i in sorted(parts)])

    def compute_mean(self, to_array: Callable[[Any], Any]) -> Any:
        """Distributed mean image: per-partition pixel sums reduced on the
        driver (ComputeMean.apply, reference: ComputeMean.scala:8-44)."""
        import numpy as np

        def partial(i: int, it: Iterable[Any]):
            acc = None
            n = 0
            for rec in it:
                arr = np.asarray(to_array(rec), np.float64)
                acc = arr if acc is None else acc + arr
                n += 1
            return [(acc, n)] if n else []

        total, count = None, 0
        for acc, n in self.rdd.mapPartitionsWithIndex(partial).collect():
            total = acc if total is None else total + acc
            count += n
        if not count:
            raise ValueError("empty RDD")
        return (total / count).astype(np.float32)
