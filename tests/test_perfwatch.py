"""Performance observatory tests: perf-ledger statistics (median/MAD
bands, small-sample refusal, fingerprint isolation, verdict taxonomy),
the artifact ingesters, the op-profile differ + fusion worklist, the
regress sentinel's stage attribution, the trajectory renderer, and the
serving SLO monitor's burn-rate state machine."""

import json
import os
import sys

import pytest

from sparknet_tpu.utils import perfledger as pl
from sparknet_tpu.utils import telemetry

pytestmark = pytest.mark.perf

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

import perfwatch  # noqa: E402


# ---------------------------------------------------------------------------
# Baseline math
# ---------------------------------------------------------------------------

def test_band_is_median_plus_k_mad():
    hist = [100.0, 102.0, 98.0, 101.0, 99.0]
    b = pl.compute_baseline("train_img_s", "fpk", hist, k=4.0)
    assert b.gated
    assert b.median == 100.0
    assert b.mad == 1.0                      # median(|v-100|) = 1
    assert b.lo == pytest.approx(100.0 - 4.0 * 1.4826)
    assert b.hi == pytest.approx(100.0 + 4.0 * 1.4826)


def test_band_mad_robust_to_one_outlier():
    # one wild run must not blow the band open (k·stdev would reach
    # ~100 ± 711 here; k·1.4826·MAD stays at ~100 ± 6)
    wild = pl.compute_baseline("train_img_s", "fpk",
                               [100, 101, 99, 100, 500.0])
    assert wild.median == 100.0
    assert wild.mad == 1.0
    assert wild.hi < 110.0


def test_min_band_frac_floors_zero_width_band():
    # three identical smoke runs -> MAD 0; the wide-CPU-bands knob keeps
    # the band non-degenerate
    tight = pl.compute_baseline("train_img_s", "fpk", [100.0] * 3)
    assert tight.lo == tight.hi == 100.0
    wide = pl.compute_baseline("train_img_s", "fpk", [100.0] * 3,
                               min_band_frac=0.10)
    assert wide.lo == pytest.approx(90.0)
    assert wide.hi == pytest.approx(110.0)


def test_window_uses_trailing_values_only():
    hist = [10.0] * 10 + [100.0] * 8        # old regime must age out
    b = pl.compute_baseline("train_img_s", "fpk", hist, window=8)
    assert b.median == 100.0


def test_small_sample_refuses_to_gate():
    for n in (0, 1, 2):
        b = pl.compute_baseline("train_img_s", "fpk", [100.0] * n)
        assert not b.gated
        assert "refusing to gate" in b.reason
        assert pl.verdict("train_img_s", 1.0, b) == "not_gated"
    assert pl.compute_baseline("train_img_s", "fpk", [100.0] * 3).gated


def test_unknown_metric_direction_never_gates():
    b = pl.compute_baseline("mystery_widgets", "fpk", [1.0] * 5)
    assert not b.gated
    assert pl.verdict("mystery_widgets", 9.0, b) == "not_gated"


# ---------------------------------------------------------------------------
# Verdicts
# ---------------------------------------------------------------------------

def _base(metric, hist, **kw):
    return pl.compute_baseline(metric, "fpk", hist, **kw)


def test_verdict_taxonomy_higher_is_better():
    b = _base("train_img_s", [100.0, 101.0, 99.0, 100.0])
    assert pl.verdict("train_img_s", 100.5, b) == "within_band"
    assert pl.verdict("train_img_s", 50.0, b) == "regression"
    assert pl.verdict("train_img_s", 200.0, b) == "improvement"


def test_verdict_taxonomy_lower_is_better():
    # _ms metrics: DOWN is good — direction must flip the verdicts
    b = _base("serve_sat_p99_ms", [10.0, 10.5, 9.5, 10.0])
    assert pl.verdict("serve_sat_p99_ms", 10.2, b) == "within_band"
    assert pl.verdict("serve_sat_p99_ms", 50.0, b) == "regression"
    assert pl.verdict("serve_sat_p99_ms", 1.0, b) == "improvement"


def test_direction_heuristics():
    assert pl.higher_is_better("train_img_s") is True
    assert pl.higher_is_better("serve_sat_qps") is True
    assert pl.higher_is_better("mfu") is True
    assert pl.higher_is_better("step_ms") is False
    assert pl.higher_is_better("round_stall_async_s") is False
    assert pl.higher_is_better("cat_ms/loop fusion") is False
    assert pl.higher_is_better("cat_gbs/loop fusion") is True
    assert pl.higher_is_better("what_is_this") is None


# ---------------------------------------------------------------------------
# Fingerprint isolation
# ---------------------------------------------------------------------------

def test_fingerprint_isolates_device_and_dtype(tmp_path):
    led = pl.PerfLedger(str(tmp_path / "L.jsonl"))
    tpu = pl.fingerprint(model="caffenet", dtype="bf16", batch=256,
                         world=1, device="tpu/TPU v5 lite")
    cpu = pl.fingerprint(model="caffenet", dtype="bf16", batch=256,
                         world=1, device="cpu/cpu")
    f32 = pl.fingerprint(model="caffenet", dtype="f32", batch=256,
                         world=1, device="tpu/TPU v5 lite")
    for i in range(4):
        led.append(pl.make_entry("bench", None, tpu,
                                 {"train_img_s": 18000.0 + i}, t=float(i)))
    # plenty of TPU bf16 history; the CPU and f32 fingerprints must see
    # NONE of it — a CPU capture never gates against TPU baselines
    assert led.baseline("train_img_s", pl.fp_key(tpu)).gated
    for other in (cpu, f32):
        b = led.baseline("train_img_s", pl.fp_key(other))
        assert not b.gated
        assert b.n == 0
    assert pl.fp_key(tpu) != pl.fp_key(cpu) != pl.fp_key(f32)


def test_backend_defaults_from_device():
    fp = pl.fingerprint(model="m", device="tpu/TPU v5 lite")
    assert fp["backend"] == "tpu"
    assert pl.fingerprint(model="m")["backend"] == "unknown"


# ---------------------------------------------------------------------------
# Ledger IO
# ---------------------------------------------------------------------------

def test_ledger_appends_and_survives_torn_lines(tmp_path):
    path = str(tmp_path / "L.jsonl")
    led = pl.PerfLedger(path)
    fp = pl.fingerprint(model="lenet", dtype="f32", batch=8,
                        device="cpu/cpu")
    led.append(pl.make_entry("bench", "a.json", fp,
                             {"train_img_s": 100.0}, t=1.0))
    led.append(pl.make_entry("bench", "b.json", fp,
                             {"train_img_s": 101.0}, t=2.0))
    with open(path, "a") as f:
        f.write('{"torn": ')             # crash mid-append
    led2 = pl.PerfLedger(path)
    assert [e["path"] for e in led2.entries()] == ["a.json", "b.json"]
    assert led2.skipped_lines == 1
    assert led2.history("train_img_s", pl.fp_key(fp)) == [100.0, 101.0]


def test_make_entry_drops_non_numeric_and_non_finite():
    e = pl.make_entry("bench", None, pl.fingerprint(),
                      {"ok": 1.5, "nan": float("nan"),
                       "inf": float("inf"), "text": "fast"})
    assert e["metrics"] == {"ok": 1.5}
    assert e["v"] == pl.SCHEMA_VERSION


def test_history_before_t_excludes_self(tmp_path):
    led = pl.PerfLedger(str(tmp_path / "L.jsonl"))
    fp = pl.fingerprint(model="m", dtype="f32", batch=1, device="cpu/cpu")
    for i in range(3):
        led.append(pl.make_entry("bench", None, fp,
                                 {"train_img_s": 100.0}, t=float(i)))
    led.append(pl.make_entry("bench", None, fp,
                             {"train_img_s": 42.0}, t=10.0))
    assert led.history("train_img_s", pl.fp_key(fp),
                       before_t=10.0) == [100.0] * 3


def test_round_tag_from_path():
    assert pl.round_tag_from_path("BENCH_r05.json") == "r05"
    assert pl.round_tag_from_path("BENCH_serving_r07.json") == "r07"
    assert pl.round_tag_from_path("RESULTS_bench_tpu.json") is None


# ---------------------------------------------------------------------------
# Ingesters
# ---------------------------------------------------------------------------

def _bench_doc():
    return {
        "metric": "lenet_train_images_per_sec", "value": 120.0,
        "dtype": "f32", "batch": 8, "device": "cpu/cpu",
        "by_dtype": {"f32": {"images_per_sec": 120.0,
                             "eval_images_per_sec": 3000.0,
                             "block_20x256_s": 1.2, "mfu": 0.01}},
        "feed_in_loop": {"batch": 8, "images_per_sec": 800.0,
                         "step_s": 0.01, "staged_dtype": "uint8",
                         "decode_s": 0.001, "transform_s": 0.0,
                         "device_put_s": 0.002},
        "provenance": {"git_sha": "abc1234", "run": "run-x", "rank": 0},
    }


def test_bench_ingester_splits_train_and_feed_entries():
    entries = pl.entries_from_bench(_bench_doc(), "BENCH_r09.json",
                                    round_tag="r09")
    by_src = {e["source"]: e for e in entries}
    assert set(by_src) == {"bench", "bench_feed"}
    assert by_src["bench"]["metrics"]["train_img_s"] == 120.0
    assert by_src["bench"]["sha"] == "abc1234"
    assert by_src["bench"]["fp"]["model"] == "lenet"
    assert by_src["bench_feed"]["metrics"]["feed_decode_s"] == 0.001
    assert all(e["round"] == "r09" for e in entries)


def test_bench_ingester_skips_failed_captures():
    assert pl.entries_from_bench({"parsed": None, "rc": 1}) == []
    assert pl.entries_from_bench({"error": "boom"}) == []
    assert pl.entries_from_bench({"metric": "m", "value": 0}) == []


def test_driver_wrapper_unwraps():
    doc = {"n": 2, "rc": 0, "tail": "...", "parsed": _bench_doc()}
    entries = pl.entries_from_any(doc, "BENCH_r09.json")
    assert {e["source"] for e in entries} == {"bench", "bench_feed"}


def test_op_table_ingester_prefixes_profile_metrics():
    doc = {"summary": {"model": "caffenet", "dtype": "bf16", "batch": 256,
                       "device": "tpu/TPU v5 lite", "step_ms": 50.0,
                       "img_s": 5000.0, "mfu": 0.2},
           "by_category": [{"op": "loop fusion", "total_ms": 30.0,
                            "gb_per_s": 1000.0}]}
    (e,) = pl.entries_from_op_table(doc, "profiles/x/op_table.json")
    # profile captures carry profiling overhead: their img_s/mfu must
    # not pool into the bench baselines
    assert "profile_img_s" in e["metrics"]
    assert "profile_mfu" in e["metrics"]
    assert "mfu" not in e["metrics"]
    assert e["metrics"]["cat_ms/loop fusion"] == 30.0


def test_entries_from_any_dispatches_serving():
    doc = {"metric": "serving_dynamic_vs_batch1_speedup_x", "value": 5.9,
           "model": "lenet", "dtype": "bf16", "batch_shapes": [1, 4, 16],
           "device": "cpu/cpu",
           "saturation": {"achieved_qps": 4000.0, "p99_ms": 20.0},
           "batch1": {"achieved_qps": 700.0},
           "overload": {"p99_ms": 110.0, "achieved_qps": 2500.0,
                        "rejected": 100}}
    (e,) = pl.entries_from_any(doc, "BENCH_serving_r07.json")
    assert e["source"] == "serving"
    assert e["round"] == "r07"
    assert e["metrics"]["serve_sat_qps"] == 4000.0
    assert e["metrics"]["serve_speedup_x"] == 5.9


# ---------------------------------------------------------------------------
# The regress sentinel
# ---------------------------------------------------------------------------

def _seeded_ledger(tmp_path, n=3, img_s=800.0):
    led = pl.PerfLedger(str(tmp_path / "L.jsonl"))
    for i in range(n):
        for e in pl.entries_from_bench(_bench_doc(), "seed",
                                       t=float(i)):
            led.append(e)
    assert img_s == 800.0    # seed feed rate the tests regress against
    return led


def test_regress_within_band_exits_ok(tmp_path):
    led = _seeded_ledger(tmp_path)
    out = perfwatch.run_regress(_bench_doc(), led, min_band_frac=0.10)
    assert out["ok"]
    assert out["regressions"] == 0
    assert out["metrics_gated"] > 0


def test_regress_catches_slowed_feed_and_names_decode(tmp_path):
    led = _seeded_ledger(tmp_path)
    slow = _bench_doc()
    # a 4x slower feed leg whose growth sits in the decode stage — the
    # synthetic regression of the acceptance criteria
    slow["feed_in_loop"].update(images_per_sec=200.0, step_s=0.04,
                                decode_s=0.031)
    out = perfwatch.run_regress(slow, led, min_band_frac=0.10)
    assert not out["ok"]
    tripped = {r["metric"]: r for r in out["results"]
               if r["verdict"] == "regression"}
    assert "feed_img_s" in tripped
    attr = tripped["feed_img_s"]["attribution"]
    assert attr["metric"] == "feed_decode_s"
    assert "decode" in attr["stage"]


def test_regress_cpu_capture_never_gates_on_tpu_ledger(tmp_path):
    led = pl.PerfLedger(str(tmp_path / "L.jsonl"))
    tpu_fp = pl.fingerprint(model="lenet", dtype="f32", batch=8,
                            device="tpu/TPU v5 lite")
    for i in range(5):
        led.append(pl.make_entry("bench", None, tpu_fp,
                                 {"train_img_s": 18000.0}, t=float(i)))
    # same model/dtype/batch, CPU device, catastrophically "slower" —
    # and still not a regression, because it has no baseline to gate on
    out = perfwatch.run_regress(_bench_doc(), led)
    assert out["ok"]
    assert out["metrics_gated"] == 0
    assert all(r["verdict"] == "not_gated" for r in out["results"])


def test_regress_stage_metrics_attribute_but_never_gate(tmp_path):
    led = _seeded_ledger(tmp_path)
    out = perfwatch.run_regress(_bench_doc(), led, min_band_frac=0.10)
    checked = {r["metric"] for r in out["results"]}
    assert "feed_decode_s" not in checked
    assert "feed_device_put_s" not in checked


# ---------------------------------------------------------------------------
# The op-profile differ + fusion worklist
# ---------------------------------------------------------------------------

def _profile_fixture(step_ms, lrn_ms, lrn_gbs, with_lrn_cat=True):
    by_cat = [
        {"op": "convolution fusion", "total_ms": 100.0, "pct": 50.0,
         "gb_per_s": 480.0, "gflops_per_s": 80000.0},
        {"op": "loop fusion", "total_ms": 40.0, "pct": 20.0,
         "gb_per_s": 1000.0},
    ]
    if with_lrn_cat:
        by_cat.append({"op": "reduce-window", "total_ms": 15.0,
                       "pct": 7.0, "gb_per_s": 620.0})
    return {
        "summary": {"model": "googlenet", "dtype": "bf16", "batch": 128,
                    "device": "tpu/TPU v5 lite", "step_ms": step_ms},
        "by_category": by_cat,
        "by_layer": [
            # MXU-bound conv: high achieved GFLOP/s, must be excluded
            {"op": "conv2/3x3", "total_ms": 50.0, "pct": 25.0,
             "gb_per_s": 400.0, "gflops_per_s": 90000.0},
            # the unfused LRN chain — the worklist's raison d'etre
            {"op": "conv2/norm2", "total_ms": lrn_ms, "pct": 30.0,
             "gb_per_s": lrn_gbs, "gflops_per_s": 900.0},
            # the fused neighbor that sets the reference bandwidth
            {"op": "inception_3a/output", "total_ms": 20.0, "pct": 10.0,
             "gb_per_s": 1013.0, "gflops_per_s": 1200.0},
            # sub-floor sliver: must not become a candidate
            {"op": "tiny/relu", "total_ms": 0.1, "pct": 0.05,
             "gb_per_s": 100.0, "gflops_per_s": 10.0},
            {"op": "(outside layers)", "total_ms": 5.0, "pct": 2.0,
             "gb_per_s": 50.0},
        ],
    }


def test_diff_joins_categories_and_ranks_lrn_chain():
    a = _profile_fixture(step_ms=60.0, lrn_ms=61.0, lrn_gbs=555.0)
    b = _profile_fixture(step_ms=50.0, lrn_ms=55.0, lrn_gbs=555.0)
    out = perfwatch.diff_profiles(a, b)
    assert out["step_delta_ms"] == pytest.approx(-10.0)
    cats = {c["op"]: c for c in out["categories"]}
    assert cats["convolution fusion"]["status"] == "both"
    assert cats["convolution fusion"]["delta_ms"] == 0.0
    wl = out["fusion_worklist"]
    top = wl["candidates"][0]
    assert top["chain"] == "conv2/norm2"
    assert top["kind"] == "conv+bias+relu+LRN"
    assert top["gb_per_s"] == 555.0
    assert "555 GB/s" in top["note"]
    # reclaimable = total_ms * (1 - gb/ref) against the fused neighbor
    assert top["ref_gb_per_s"] == pytest.approx(1013.0)
    assert top["reclaimable_ms"] == pytest.approx(
        55.0 * (1 - 555.0 / 1013.0), abs=0.02)
    # MXU-bound conv and the sliver are excluded
    names = {c["chain"] for c in wl["candidates"]}
    assert "conv2/3x3" not in names
    assert "tiny/relu" not in names
    assert "(outside layers)" not in names


def test_diff_missing_category_edge():
    # a category vanishing between captures (e.g. LRN custom-call after
    # a fusion pass) must surface as only_in_a with its full time
    a = _profile_fixture(60.0, 61.0, 555.0, with_lrn_cat=True)
    b = _profile_fixture(50.0, 55.0, 555.0, with_lrn_cat=False)
    out = perfwatch.diff_profiles(a, b)
    rw = next(c for c in out["categories"] if c["op"] == "reduce-window")
    assert rw["status"] == "only_in_a"
    assert rw["b_ms"] is None
    assert rw["delta_ms"] == pytest.approx(-15.0)
    out2 = perfwatch.diff_profiles(b, a)
    rw2 = next(c for c in out2["categories"]
               if c["op"] == "reduce-window")
    assert rw2["status"] == "only_in_b"
    assert rw2["delta_ms"] == pytest.approx(15.0)


def test_worklist_without_by_layer_says_so():
    doc = {"summary": {"model": "m"}, "by_category": []}
    wl = perfwatch.fusion_worklist(doc)
    assert wl["candidates"] == []
    assert "by_layer" in wl["note"]


def test_diff_on_committed_profiles_names_the_verdict_chain():
    # the acceptance pair: the googlenet bf16 LRN chain VERDICT.md pins
    # at 555 GB/s must top the committed-profile worklist
    with open(os.path.join(REPO, "profiles", "googlenet_bf16",
                           "op_table.json")) as f:
        b = json.load(f)
    with open(os.path.join(REPO, "profiles", "googlenet",
                           "op_table.json")) as f:
        a = json.load(f)
    out = perfwatch.diff_profiles(a, b)
    top = out["fusion_worklist"]["candidates"][0]
    assert top["chain"] == "conv2/norm2"
    assert top["gb_per_s"] == pytest.approx(555.2, abs=0.5)


# ---------------------------------------------------------------------------
# Trajectory
# ---------------------------------------------------------------------------

def test_trajectory_builds_rounds_and_splices_idempotently(tmp_path):
    led = pl.PerfLedger(str(tmp_path / "L.jsonl"))
    fp = pl.fingerprint(model="caffenet", dtype="bf16", batch=256,
                        device="tpu/TPU v5 lite")
    led.append(pl.make_entry("bench", "BENCH_r02.json", fp,
                             {"train_img_s": 10000.0, "mfu": 0.2},
                             round_tag="r02", t=1.0, sha="aaa"))
    led.append(pl.make_entry("bench", "BENCH_r05.json", fp,
                             {"train_img_s": 18000.0, "mfu": 0.35},
                             round_tag="r05", t=2.0, sha="bbb"))
    traj = perfwatch.build_trajectory(led)
    assert [r["round"] for r in traj["rounds"]] == ["r02", "r05"]
    assert traj["rounds"][1]["train_img_s"] == 18000.0
    block = perfwatch.render_trajectory_md(traj)
    text = "# RESULTS\n\n## Old section\nbody\n"
    once = perfwatch.splice_markers(text, block)
    twice = perfwatch.splice_markers(once, block)
    assert once == twice                      # idempotent
    assert once.count(perfwatch._TRAJ_BEGIN) == 1
    assert "| r02 |" in once and "| r05 |" in once
    assert "## Old section" in once


def test_trajectory_prefers_best_train_capture_per_round(tmp_path):
    led = pl.PerfLedger(str(tmp_path / "L.jsonl"))
    slow = pl.fingerprint(model="caffenet", dtype="f32", batch=256,
                          device="tpu/TPU v5 lite")
    fast = pl.fingerprint(model="caffenet", dtype="bf16", batch=256,
                          device="tpu/TPU v5 lite")
    led.append(pl.make_entry("bench", None, slow,
                             {"train_img_s": 13000.0}, round_tag="r05",
                             t=1.0))
    led.append(pl.make_entry("bench", None, fast,
                             {"train_img_s": 18000.0}, round_tag="r05",
                             t=1.1))
    (row,) = perfwatch.build_trajectory(led)["rounds"]
    assert row["train_img_s"] == 18000.0
    assert row["dtype"] == "bf16"


# ---------------------------------------------------------------------------
# SLO monitor
# ---------------------------------------------------------------------------

from sparknet_tpu.parallel.serving import ServeConfig, SLOMonitor  # noqa: E402


class _Clock:
    def __init__(self):
        self.t = 1000.0

    def __call__(self):
        return self.t


def _slo_cfg(**kw):
    kw.setdefault("slo_reject_budget", 0.02)
    kw.setdefault("slo_window_s", 60.0)
    kw.setdefault("slo_fast_window_s", 5.0)
    return ServeConfig(**kw)


class _Stats:
    """Scripted engine counters the monitor samples."""

    def __init__(self):
        self.completed = 0
        self.rejected = 0
        self.failed = 0
        self.p99 = 10.0

    def __call__(self):
        return {"completed": self.completed,
                "rejected": {"queue_full": self.rejected},
                "failed": self.failed, "p99_ms": self.p99}


@pytest.fixture
def tel(monkeypatch):
    for k in ("SPARKNET_TELEMETRY", "SPARKNET_TRACE_DIR",
              "SPARKNET_METRICS_SNAP"):
        monkeypatch.delenv(k, raising=False)
    telemetry.reset()
    yield monkeypatch
    telemetry.reset()


def test_slo_healthy_traffic_stays_ok(tel):
    clock, st = _Clock(), _Stats()
    mon = SLOMonitor(st, _slo_cfg(), clock=clock)
    for _ in range(20):
        clock.t += 0.5
        st.completed += 100           # zero rejections
        doc = mon.evaluate()
    assert doc["state"] == "ok"
    assert doc["breaches"] == []
    assert mon.breaches == 0


def test_slo_sustained_overload_breaches_with_flight_dump(tel, tmp_path):
    tel.setenv("SPARKNET_TRACE_DIR", str(tmp_path))
    telemetry.reset()
    clock, st = _Clock(), _Stats()
    mon = SLOMonitor(st, _slo_cfg(), clock=clock)
    doc = None
    for _ in range(20):               # 10 s of 50% rejections: 25x burn
        clock.t += 0.5
        st.completed += 50
        st.rejected += 50
        doc = mon.evaluate()
    assert doc["state"] == "breach"
    assert "availability" in doc["breaches"]
    assert doc["windows"]["fast"]["burn"] >= 4.0
    assert mon.breaches == 1          # one transition, not one per sample
    assert mon.dumps == 1
    dumps = [p for p in os.listdir(tmp_path) if p.startswith("flight_")]
    assert len(dumps) == 1
    with open(os.path.join(tmp_path, dumps[0])) as f:
        dumped = json.load(f)
    assert any(e["kind"] == "slo_breach" for e in dumped["events"])


def test_slo_short_blip_never_pages(tel):
    # the multi-window pattern: a burst of rejections inside an
    # otherwise long healthy window burns the fast window but not the
    # slow one — no page
    clock, st = _Clock(), _Stats()
    mon = SLOMonitor(st, _slo_cfg(), clock=clock)
    for _ in range(110):              # 55 s of clean traffic
        clock.t += 0.5
        st.completed += 100
        mon.evaluate()
    clock.t += 0.5                    # one bad second
    st.rejected += 200
    st.completed += 60
    doc = mon.evaluate()
    assert doc["windows"]["fast"]["burn"] >= 4.0
    assert doc["windows"]["slow"]["burn"] < 1.0
    assert doc["state"] == "ok"


def test_slo_min_requests_guards_tiny_samples(tel):
    clock, st = _Clock(), _Stats()
    mon = SLOMonitor(st, _slo_cfg(), clock=clock)
    clock.t += 0.5
    st.rejected += 5                  # 100% bad, but only 5 requests
    doc = mon.evaluate()
    assert doc["state"] == "ok"


def test_slo_latency_bound_breaches_and_recovers(tel):
    clock, st = _Clock(), _Stats()
    mon = SLOMonitor(st, _slo_cfg(), clock=clock)
    mon.p99_ms = 100.0                # runtime-declared bound
    for _ in range(4):
        clock.t += 0.5
        st.completed += 100
        st.p99 = 250.0                # sustained over the bound
        doc = mon.evaluate()
    assert doc["state"] == "breach"
    assert doc["breaches"] == ["latency"]
    # p99 windows use max-of-samples, so recovery needs the bad samples
    # to age out of BOTH windows
    clock.t += 61.0
    st.p99 = 20.0
    st.completed += 100
    doc = mon.evaluate()
    assert doc["state"] == "ok"
    assert mon.breaches == 1


def test_slo_undeclared_latency_not_evaluated(tel):
    clock, st = _Clock(), _Stats()
    mon = SLOMonitor(st, _slo_cfg(), clock=clock)
    assert mon.p99_ms is None
    for _ in range(10):
        clock.t += 0.5
        st.completed += 100
        st.p99 = 1e9                  # absurd p99, no declared bound
        doc = mon.evaluate()
    assert doc["state"] == "ok"


def test_slo_reset_fences_history(tel):
    clock, st = _Clock(), _Stats()
    mon = SLOMonitor(st, _slo_cfg(), clock=clock)
    for _ in range(10):
        clock.t += 0.5
        st.completed += 50
        st.rejected += 50
        mon.evaluate()
    assert mon.state == "breach"
    mon.reset()                       # the measurement fence
    assert mon.state == "ok"
    clock.t += 0.5
    st.completed += 100               # clean traffic after the fence
    doc = mon.evaluate()
    assert doc["state"] == "ok"
    assert doc["windows"]["fast"]["bad"] == 0


def test_slo_config_validation():
    with pytest.raises(ValueError):
        _slo_cfg(slo_reject_budget=0.0)
    with pytest.raises(ValueError):
        _slo_cfg(slo_reject_budget=1.5)
    with pytest.raises(ValueError):
        _slo_cfg(slo_p99_ms=-5.0)
    with pytest.raises(ValueError):
        _slo_cfg(slo_window_s=1.0, slo_fast_window_s=5.0)


def test_slo_summary_rides_engine_stats_shape(tel):
    mon = SLOMonitor(_Stats(), _slo_cfg(), clock=_Clock())
    s = mon.summary()
    assert s == {"state": "ok", "breaches": 0}
