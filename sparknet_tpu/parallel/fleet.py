"""Multi-tenant training fleet: gang scheduling, preempt/resume, quotas.

Everything PRs 1–5 built protects exactly ONE job at a time: a
``ResilientRunner`` restarts it, the health plane watches it, the
checkpoint chain makes every recovery exact.  This module composes that
machinery into the long-lived shared-cluster posture the paper argues
for (and Caffe con Troll's thesis predicts: with the kernels fixed, the
remaining wins live in the scheduling harness around them): a queue of
heterogeneous training jobs, gang-scheduled onto a budget of device
slices, each supervised by its own per-job ResilientRunner, all kept
alive through faults — and through the death of the scheduler itself.

The moving parts:

**JobSpec** — a JSON-serializable description of one training job:
model / strategy / rounds / world size (the gang: how many device
slices the job needs, all-or-nothing), tenant, priority, restart
budget, optional explicit ``cmd`` for jobs outside the built-in zoo
driver, optional ``fault`` (the chaos harness's injection channel).

**GangAllocator** — the device budget.  A job's gang is allocated
atomically (a half-placed SPMD job is a deadlock, not a job), and a
freed gang is immediately re-offerable.

**Quotas + fairness** — each tenant owns a slot quota; a job only
places while its tenant is under quota.  Queue order is effective
priority (static priority + starvation aging: a queued job gains
``aging_rate`` priority per waiting second, so low-priority work is
delayed, never starved), tie-broken by tenant fair-share (the tenant
using the smallest fraction of its quota goes first), then FIFO.

**Preempt/resume** — when a higher-priority job cannot be placed, the
scheduler preempts the cheapest set of strictly-lower-priority running
jobs: ``runner.cancel()`` stops the supervision loop, SIGTERM starts
each worker's grace window (``utils.signals.preemption_guard`` turns it
into one final round checkpoint + clean exit — the same SNAPSHOT_STOP
path a cloud preemption takes), and past ``preempt_grace_s`` the
stragglers are SIGKILLed (losing at most ``checkpoint_every`` rounds,
exactly like a crash).  The preempted job is REQUEUED, not failed; its
next launch resumes from its checkpoint directory, and the composed run
is bit-identical to an unpreempted one (the round-granular resume
contract).  Static priority alone decides preemption — aging only
reorders the queue, so a long wait can outrank but never evict.

**Escalation, not infinite retries** — crash/straggle/hang handling is
delegated to the per-job ResilientRunner; a job that exhausts its
restart budget is QUARANTINED with a post-mortem written next to its
artifacts (culprit rank, cause, log tail, heartbeat age), and its gang
is re-offered in the same scheduling step.

**Crash-safe fleet state** — every transition is appended to a
fsync'd JSONL journal.  ``FleetScheduler.resume`` replays it: completed
jobs stay completed (even if they finished AFTER the scheduler died —
the done-marker check makes recovery idempotent), running jobs have
their recorded worker pids verified (via /proc environ tagging, so a
recycled pid is never someone else's process) and killed before the job
is requeued — a killed scheduler resumes its queue without ever
double-launching a job, and leaves zero orphan workers behind.

**Status** — ``status()`` folds together the journal state, each job's
newest checkpoint manifest (round progress), and the per-rank
heartbeats of its live attempt — including the ``stall_s`` /
``FeedStats`` telemetry the trainer rides on its round_end beats — into
one fleet view; ``format_status`` renders it as a table.

``tools/fleet.py`` is the CLI; ``tools/soak.py --fleet N`` is the chaos
acceptance harness (seeded crash/straggle/preempt/nan schedules, all
jobs must finish bit-identical to fault-free baselines with no orphan
processes, scheduler kill/restart included).
"""

from __future__ import annotations

import dataclasses
import glob
import json
import os
import queue
import signal
import sys
import threading
import time
from typing import Any, Callable, Iterable, Mapping

from ..utils import telemetry
from .resilience import ResilientRunner, RestartPolicy

# job lifecycle states (journaled verbatim)
QUEUED = "QUEUED"
RUNNING = "RUNNING"
PREEMPTING = "PREEMPTING"
COMPLETED = "COMPLETED"
QUARANTINED = "QUARANTINED"
TERMINAL = (COMPLETED, QUARANTINED)

# the env tag every fleet-spawned worker carries — pid liveness checks
# verify it through /proc/<pid>/environ before signalling, so a recycled
# pid can never be mistaken for (and never killed as) a fleet worker
ENV_JOB_TAG = "SPARKNET_FLEET_JOB"

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
DRIVER = os.path.join(_REPO, "tests", "multihost_driver.py")
SERVE_TOOL = os.path.join(_REPO, "tools", "serve.py")

# models the built-in driver workload can train (the zoo driver trains
# lenet; anything else needs an explicit JobSpec.cmd)
DRIVER_MODELS = ("lenet",)

# job kinds: "train" runs to a completion artifact; "serve" is a
# long-lived serving replica — it never finishes on its own, the
# scheduler decides its end (release_job -> drain -> COMPLETED, or
# preemption -> drain -> requeue)
JOB_KINDS = ("train", "serve")


class FleetError(RuntimeError):
    pass


@dataclasses.dataclass(frozen=True)
class JobSpec:
    """One training job, JSON-round-trippable (the journal stores it).

    Either describe a zoo driver workload (``model``/``strategy``/
    ``rounds``/``global_batch``) or pass an explicit ``cmd`` argv whose
    elements may use the placeholders ``{out}`` (completion artifact —
    REQUIRED: its existence is how the fleet distinguishes "finished"
    from "checkpointed and stopped"), ``{ckpt}`` (the job's checkpoint
    dir), ``{world}``, ``{rounds}`` and ``{endpoint}`` (the replica
    endpoint file serve-kind jobs publish).

    ``kind="serve"`` makes the job a serving replica: the built-in cmd
    launches ``tools/serve.py --models <model>`` on an ephemeral port
    publishing its endpoint into the job dir, the completion-artifact
    rule is waived (a replica never "finishes" — the scheduler's
    ``release_job`` ends it through the drain path), and ``model`` may
    be any zoo name or comma list (the replica process validates it
    loudly at load time)."""

    name: str
    kind: str = "train"
    tenant: str = "default"
    priority: int = 0
    world: int = 4                 # gang size in device slices
    model: str = "lenet"
    strategy: str = "sync"
    rounds: int = 4
    global_batch: int = 16
    cmd: tuple[str, ...] | None = None
    guard: bool = False            # arm the numerical-integrity guard
    audit_every: int = 0           # cross-replica audit cadence
    max_restarts: int = 2          # per launch episode (see FleetScheduler)
    timeout_s: float | None = 300.0   # per attempt (None = unbounded —
                                      # the serve-kind default: replicas
                                      # are long-lived by design)
    round_deadline_s: float | None = None   # straggler deadline
    preemptible: bool = True
    not_before_s: float = 0.0      # delay placement this long after submit
    fault: str | None = None       # SPARKNET_FAULT for the chaos harness
    env: Mapping[str, str] = dataclasses.field(default_factory=dict)

    def __post_init__(self):
        if not self.name or any(c in self.name for c in "/\\ \t\n"):
            raise ValueError(f"bad job name {self.name!r} (must be "
                             f"non-empty, no slashes or whitespace)")
        if self.world < 1:
            raise ValueError(f"{self.name}: world must be >= 1, "
                             f"got {self.world}")
        if self.rounds < 1:
            raise ValueError(f"{self.name}: rounds must be >= 1")
        if self.max_restarts < 0:
            raise ValueError(f"{self.name}: max_restarts must be >= 0")
        if self.kind not in JOB_KINDS:
            raise ValueError(f"{self.name}: kind must be one of "
                             f"{JOB_KINDS}, got {self.kind!r}")
        if self.cmd is not None:
            object.__setattr__(self, "cmd", tuple(self.cmd))
            if self.kind == "train" \
                    and not any("{out}" in c for c in self.cmd):
                raise ValueError(
                    f"{self.name}: explicit cmd must reference {{out}} — "
                    f"the completion artifact is how the fleet tells a "
                    f"finished job from a preempted one (serve-kind jobs "
                    f"are exempt: the scheduler decides their end)")
        elif self.kind == "train" and self.model not in DRIVER_MODELS:
            raise ValueError(
                f"{self.name}: model {self.model!r} has no built-in "
                f"driver (known: {', '.join(DRIVER_MODELS)}); pass an "
                f"explicit cmd for zoo jobs outside the driver")
        object.__setattr__(self, "env", dict(self.env))

    def to_json(self) -> dict[str, Any]:
        d = dataclasses.asdict(self)
        d["cmd"] = list(self.cmd) if self.cmd is not None else None
        return d

    @classmethod
    def from_json(cls, d: Mapping[str, Any]) -> "JobSpec":
        known = {f.name for f in dataclasses.fields(cls)}
        extra = set(d) - known
        if extra:
            raise ValueError(f"unknown JobSpec field(s) {sorted(extra)} "
                             f"(known: {sorted(known)})")
        d = dict(d)
        if d.get("cmd") is not None:
            d["cmd"] = tuple(d["cmd"])
        return cls(**d)


# host lifecycle states (journaled verbatim, host-control channel too)
HOST_LIVE = "live"
HOST_SUSPECT = "suspect"     # link silent, machine maybe alive: gangs keep
                             # running SUSPENDED (partition != death); the
                             # host just stops taking new placements
HOST_DRAINING = "draining"   # spot notice: evict gracefully, stop placing
HOST_LOST = "lost"           # dead: its gangs are already gone
HOST_STATES = (HOST_LIVE, HOST_SUSPECT, HOST_DRAINING, HOST_LOST)


@dataclasses.dataclass(frozen=True)
class HostSpec:
    """One machine in the pod: a name, its device budget, and how to
    reach it.  ``addr`` in ``launch.LOCAL_ADDRS`` (the default) means
    "spawn here" — an inventory of all-local hosts is the simulated
    N-host rig that runs every cross-host path on one CPU box."""

    name: str
    devices: int
    addr: str = "local"

    def __post_init__(self):
        if not self.name or any(c in self.name for c in "/\\ \t\n,=@"):
            raise ValueError(f"bad host name {self.name!r}")
        if self.devices < 1:
            raise ValueError(f"host {self.name}: devices must be >= 1")


class HostPool:
    """The fleet's machine inventory + liveness state.  Hosts are
    ``live`` (placeable), ``draining`` (spot/preemption notice: existing
    gangs get the SNAPSHOT_STOP eviction, nothing new lands), or
    ``lost`` (dead — slots unplaceable until marked live again).

    Inventory sources: ``HostPool.parse("a=4,b=4@10.0.0.2")`` (inline,
    ``name=devices[@addr]``), a JSON file (``[{"name", "devices",
    "addr"}]``), or ``from_env()`` reading SPARKNET_FLEET_HOSTS (a path
    to such a file, or the inline form)."""

    def __init__(self, hosts: Iterable[HostSpec]):
        self._specs: dict[str, HostSpec] = {}
        for h in hosts:
            if h.name in self._specs:
                raise ValueError(f"duplicate host {h.name!r}")
            self._specs[h.name] = h
        if not self._specs:
            raise ValueError("empty host inventory")
        self.state: dict[str, str] = {n: HOST_LIVE for n in self._specs}

    # -- inventory --------------------------------------------------------
    def specs(self) -> list[HostSpec]:
        return list(self._specs.values())

    def spec(self, name: str) -> HostSpec:
        try:
            return self._specs[name]
        except KeyError:
            raise FleetError(f"unknown host {name!r} (inventory: "
                             f"{sorted(self._specs)})") from None

    @property
    def total_devices(self) -> int:
        return sum(h.devices for h in self._specs.values())

    def __contains__(self, name: str) -> bool:
        return name in self._specs

    def __len__(self) -> int:
        return len(self._specs)

    # -- liveness ---------------------------------------------------------
    def mark(self, name: str, state: str) -> None:
        if state not in HOST_STATES:
            raise FleetError(f"bad host state {state!r} "
                             f"(one of {HOST_STATES})")
        self.spec(name)   # loud on unknown hosts
        self.state[name] = state

    def placeable(self, name: str) -> bool:
        return self.state.get(name) == HOST_LIVE

    def lost(self) -> list[str]:
        return sorted(n for n, s in self.state.items() if s == HOST_LOST)

    # -- serialization (journaled in the "fleet" record) ------------------
    def to_json(self) -> list[dict]:
        return [{"name": h.name, "devices": h.devices, "addr": h.addr}
                for h in self._specs.values()]

    @classmethod
    def from_json(cls, rows: Iterable[Mapping]) -> "HostPool":
        return cls(HostSpec(name=str(r["name"]), devices=int(r["devices"]),
                            addr=str(r.get("addr", "local")))
                   for r in rows)

    @classmethod
    def parse(cls, text: str) -> "HostPool":
        """Inline inventory: ``name=devices[@addr]`` comma-separated."""
        specs = []
        for part in text.split(","):
            part = part.strip()
            if not part:
                continue
            if "=" not in part:
                raise ValueError(f"bad host entry {part!r} "
                                 f"(want name=devices[@addr])")
            name, rest = part.split("=", 1)
            addr = "local"
            if "@" in rest:
                rest, addr = rest.split("@", 1)
            specs.append(HostSpec(name=name.strip(),
                                  devices=int(rest), addr=addr.strip()))
        return cls(specs)

    @classmethod
    def from_spec(cls, spec: str) -> "HostPool":
        """A path to a JSON inventory file, else the inline form."""
        if os.path.exists(spec):
            with open(spec) as f:
                return cls.from_json(json.load(f))
        return cls.parse(spec)

    @classmethod
    def from_env(cls) -> "HostPool | None":
        from ..utils import knobs
        spec = knobs.get_str("SPARKNET_FLEET_HOSTS", "")
        return cls.from_spec(spec) if spec else None


class GangAllocator:
    """All-or-nothing slice allocation out of a fixed device budget.
    Slots are fungible integers — on the local rig they are virtual CPU
    devices, on a pod they are chip indices of a slice.  With a
    ``pool``, slots map onto hosts (consecutive ranges in inventory
    order), only slots on LIVE hosts are offerable (draining/lost hosts
    take no new gangs), and allocation packs the fewest hosts that fit —
    a gang never spans more machines than it must.  Freeing is
    state-blind: a lost host's slots come back to the free set but stay
    unplaceable until the host is marked live again."""

    def __init__(self, total: int | None = None, *,
                 pool: HostPool | None = None):
        self.pool = pool
        self.slot_host: dict[int, str] = {}
        if pool is not None:
            i = 0
            for h in pool.specs():
                for _ in range(h.devices):
                    self.slot_host[i] = h.name
                    i += 1
            if total is not None and total != i:
                raise ValueError(f"total={total} contradicts the pool's "
                                 f"{i} devices")
            total = i
        if total is None or total < 1:
            raise ValueError(f"total devices must be >= 1, got {total}")
        self.total = total
        self._free = set(range(total))

    def _offerable(self) -> set[int]:
        if self.pool is None:
            return self._free
        return {s for s in self._free
                if self.pool.placeable(self.slot_host[s])}

    @property
    def free_count(self) -> int:
        return len(self._offerable())

    def allocate(self, n: int,
                 avoid: Iterable[str] = ()) -> tuple[int, ...] | None:
        """The gang, or None when it does not fit — never a partial.

        ``avoid`` is SOFT anti-affinity: the named hosts sort last, so a
        serving replica prefers a host its siblings are not already on
        (one host loss then kills some replicas, never the whole tier).
        It never blocks placement — when only avoided hosts have room,
        the gang still lands there."""
        free = self._offerable()
        if n > len(free):
            return None
        if self.pool is None:
            slots = tuple(sorted(free)[:n])
        else:
            shun = set(avoid)
            by_host: dict[str, list[int]] = {}
            for s in free:
                by_host.setdefault(self.slot_host[s], []).append(s)
            chosen: list[int] = []
            for host in sorted(by_host,
                               key=lambda h: (h in shun,
                                              -len(by_host[h]), h)):
                take = by_host[host][:n - len(chosen)]
                chosen.extend(take)
                if len(chosen) == n:
                    break
            slots = tuple(sorted(chosen))
        self._free.difference_update(slots)
        return slots

    def hosts_of(self, slots: Iterable[int]) -> tuple[str, ...]:
        """The (ordered, de-duplicated) hosts a gang spans; empty
        without a pool."""
        out: list[str] = []
        for s in slots:
            h = self.slot_host.get(s)
            if h is not None and h not in out:
                out.append(h)
        return tuple(out)

    def host_vector(self, slots: Iterable[int]) -> list[str]:
        """Per-slot host labels in slot order (the launcher's
        ``host_map`` shape); empty without a pool."""
        return [self.slot_host[s] for s in slots] if self.slot_host else []

    def free(self, slots: Iterable[int]) -> None:
        for s in slots:
            if s in self._free or not 0 <= s < self.total:
                raise FleetError(f"double free / bad slot {s}")
            self._free.add(s)


class FleetJournal:
    """Append-only fsync'd JSONL of every fleet state transition.
    Replayable (see ``FleetScheduler.resume``); writes are idempotent to
    re-apply because each carries the full fact, not a delta."""

    def __init__(self, path: str):
        self.path = path
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._lock = threading.Lock()
        self._seq = 0
        existing = self.read(path)
        if existing:
            self._seq = existing[-1]["seq"] + 1
        self._f = open(path, "a")

    def append(self, ev: str, **fields) -> None:
        with self._lock:
            rec = {"seq": self._seq, "t": round(time.time(), 3), "ev": ev}
            rec.update(fields)
            self._f.write(json.dumps(rec) + "\n")
            self._f.flush()
            os.fsync(self._f.fileno())
            self._seq += 1

    def close(self) -> None:
        with self._lock:
            self._f.close()

    @staticmethod
    def read(path: str) -> list[dict]:
        """Every parseable record (a torn final line — the scheduler died
        mid-append — is skipped, not fatal)."""
        out = []
        try:
            with open(path) as f:
                for line in f:
                    try:
                        out.append(json.loads(line))
                    except json.JSONDecodeError:
                        continue
        except OSError:
            pass
        return out


class FleetJob:
    """Runtime state of one submitted job (the mutable half; the spec is
    frozen)."""

    def __init__(self, spec: JobSpec, job_dir: str, seq: int,
                 submitted_at: float):
        self.spec = spec
        self.job_dir = job_dir
        self.seq = seq
        self.submitted_at = submitted_at
        self.state = QUEUED
        self.slots: tuple[int, ...] = ()
        self.hosts: tuple[str, ...] = ()   # the machines this gang spans
        self.episodes = 0            # launch episodes (fresh runner each)
        self.restarts_used = 0       # cumulative attempts across episodes
        self.preempt_count = 0
        self.started_at: float | None = None
        self.preempt_requested = False
        self.preempt_deadline: float | None = None
        self.release_requested = False       # scale-down, not eviction
        self.drain_deadline: float | None = None
        self.runner = None
        self.thread: threading.Thread | None = None
        self.procs: list = []        # live Popen handles (latest attempt)
        self.signaled_pids: set[int] = set()
        self.all_pids: set[int] = set()
        self.error: str | None = None
        # remote placements checkpoint into a host-local dir (set per
        # launch); None = the shared default ``ckpt/``
        self.active_ckpt_dir: str | None = None

    @property
    def name(self) -> str:
        return self.spec.name

    @property
    def out_path(self) -> str:
        return os.path.join(self.job_dir, "out.npz")

    @property
    def ckpt_dir(self) -> str:
        return os.path.join(self.job_dir, "ckpt")

    def host_ckpt_dir(self, host: str) -> str:
        """Where a gang placed on ``host`` (over a remote transport)
        keeps its checkpoints — host-local state, NOT assumed shared.
        A requeue onto a different host must SHIP the newest valid
        checkpoint here before launch (see FleetScheduler._launch)."""
        return os.path.join(self.job_dir, f"ckpt_host_{host}")

    def ckpt_dirs(self) -> list[str]:
        """Every checkpoint dir this job has ever written (shared
        default + any per-host dirs), existing ones only."""
        out = [d for d in glob.glob(os.path.join(self.job_dir, "ckpt*"))
               if os.path.isdir(d)]
        return sorted(out)

    @property
    def endpoint_path(self) -> str:
        """Where a serve-kind replica publishes its ephemeral endpoint
        (url + pid + models) once its socket is up."""
        return os.path.join(self.job_dir, "endpoint.json")

    def completed_ok(self) -> bool:
        """The completion artifact exists — the ONLY signal that a clean
        exit was the job finishing rather than checkpoint-and-stop.
        Serve-kind jobs have no artifact: their end is a scheduler
        decision (release), never something the process proves."""
        if self.spec.kind == "serve":
            return False
        return os.path.exists(self.out_path)

    def build_cmd(self) -> list[str]:
        spec = self.spec
        ckpt = self.active_ckpt_dir or self.ckpt_dir
        os.makedirs(ckpt, exist_ok=True)
        if spec.cmd is not None:
            sub = {"out": self.out_path, "ckpt": ckpt,
                   "world": str(spec.world), "rounds": str(spec.rounds),
                   "endpoint": self.endpoint_path}
            return [c.format(**sub) for c in spec.cmd]
        if spec.kind == "serve":
            # a serving replica: ephemeral port, endpoint published into
            # the job dir (the ServingFleet poll loop registers it with
            # the router); SPARKNET_SERVE_* knobs ride spec.env.  A
            # stale endpoint from the previous attempt must not route —
            # the fresh attempt republishes once its socket is up.
            try:
                os.unlink(self.endpoint_path)
            except OSError:
                pass
            return [sys.executable, SERVE_TOOL, "--models", spec.model,
                    "--port", "0", "--endpoint-file", self.endpoint_path]
        cmd = [sys.executable, DRIVER, "--strategy", spec.strategy,
               "--out", self.out_path, "--ckpt-dir", ckpt,
               "--rounds", str(spec.rounds),
               "--global-batch", str(spec.global_batch),
               "--local-devices", str(spec.world),
               "--expect-devices", str(spec.world)]
        if spec.guard:
            cmd.append("--guard")
        if spec.audit_every:
            cmd += ["--audit-every", str(spec.audit_every)]
        return cmd

    def newest_round(self) -> int | None:
        """Round progress from the newest checkpoint manifest across
        every checkpoint dir (None before the first checkpoint)."""
        best = None
        for m in glob.glob(os.path.join(self.job_dir, "ckpt*",
                                        "manifest_*.json")):
            stem = os.path.basename(m)
            try:
                r = int(stem[len("manifest_"):-len(".json")])
            except ValueError:
                continue
            best = r if best is None else max(best, r)
        return best


def _pid_is_fleet_job(pid: int, job_name: str) -> bool:
    """True only when /proc says ``pid`` is alive AND carries our env
    tag for ``job_name``.  Any doubt (dead, unreadable, recycled by a
    stranger) is False — the fleet must never signal a process it cannot
    prove it spawned."""
    try:
        with open(f"/proc/{pid}/environ", "rb") as f:
            env = f.read()
    except OSError:
        return False
    return f"{ENV_JOB_TAG}={job_name}".encode() in env.split(b"\0")


class FleetScheduler:
    """The long-lived supervisor.  Single-threaded scheduling core
    (``step()``) + one supervisor thread per running job (each blocked
    inside its ResilientRunner).  ``run()`` loops ``step`` until every
    job is terminal; tests drive ``step()`` directly for determinism."""

    def __init__(self, workdir: str, total_devices: int | None = None, *,
                 hosts: HostPool | None = None,
                 tenants: Mapping[str, int] | None = None,
                 aging_rate: float = 1.0 / 60.0,
                 preempt: bool = True,
                 preempt_grace_s: float = 10.0,
                 drain_grace_s: float | None = None,
                 max_preempts: int = 10,
                 platform: str | None = "cpu",
                 backoff_base: float = 0.2,
                 extra_env: Mapping[str, str] | None = None,
                 runner_factory: Callable | None = None,
                 clock: Callable[[], float] = time.monotonic,
                 _journal: bool = True):
        self.workdir = os.path.abspath(workdir)
        os.makedirs(self.workdir, exist_ok=True)
        self.pool = hosts
        self.allocator = GangAllocator(total_devices, pool=hosts)
        # operator channel for host state changes from OUTSIDE this
        # process (tools/fleet.py mark-host, chaos harnesses): appended
        # JSONL, polled at every step
        self._host_control_path = os.path.join(self.workdir,
                                               "host_control.jsonl")
        self._host_control_pos = 0
        self.tenants = dict(tenants or {})   # tenant -> slot quota
        for t, q in self.tenants.items():
            if q < 1:
                raise ValueError(f"tenant {t!r}: quota must be >= 1")
        self.aging_rate = aging_rate
        self.preempt_enabled = preempt
        self.preempt_grace_s = preempt_grace_s
        self.drain_grace_s = (preempt_grace_s if drain_grace_s is None
                              else drain_grace_s)
        self.max_preempts = max_preempts
        # job name -> drain hook (start() / done() -> bool): a stopping
        # job with a hook drains FIRST (no new work routed, queued work
        # finishes), then takes the SIGTERM path — how evicting a
        # serving replica stays lossless (see router.RouterDrainHook)
        self.drain_hooks: dict[str, Any] = {}
        self.platform = platform
        self.backoff_base = backoff_base
        self.extra_env = dict(extra_env or {})
        self.runner_factory = runner_factory or self._default_runner
        self._clock = clock
        self.jobs: dict[str, FleetJob] = {}
        # host -> transport kind of the most recent launch that placed
        # a gang there ("local"/"ssh"/"chaos+..."): the status view's
        # transport column, reconstructed offline from launch events
        self._host_transports: dict[str, str] = {}
        self._results: "queue.Queue" = queue.Queue()
        self._submit_seq = 0
        self.journal = FleetJournal(
            os.path.join(self.workdir, "fleet_journal.jsonl")) \
            if _journal else None
        self._journal_ev("fleet", devices=self.allocator.total,
                         tenants=self.tenants,
                         hosts=hosts.to_json() if hosts else None)

    # -- journal ----------------------------------------------------------
    def _journal_ev(self, ev: str, **fields) -> None:
        if self.journal is not None:
            self.journal.append(ev, **fields)
        # every scheduling decision also rides the telemetry plane: a
        # bounded flight-recorder trail (embedded into quarantine
        # postmortems) plus a per-kind counter — the journal stays the
        # durable source of truth, this is the observable echo
        telemetry.get_recorder().record(
            f"fleet_{ev}",
            **{k: v for k, v in fields.items()
               if k in ("job", "rc", "reason", "by", "episode",
                        "preempts", "recovered", "ok", "slots",
                        "host", "state")})
        telemetry.get_registry().counter(
            "fleet_events_total", "fleet scheduler events by kind"
        ).inc(ev=ev)

    # -- submission -------------------------------------------------------
    def job_dir(self, name: str) -> str:
        return os.path.join(self.workdir, "jobs", name)

    def submit(self, spec: JobSpec, *, _journal: bool = True) -> FleetJob:
        if spec.name in self.jobs:
            raise FleetError(f"duplicate job name {spec.name!r}")
        if spec.world > self.allocator.total:
            raise FleetError(
                f"{spec.name!r} wants a gang of {spec.world} but the "
                f"fleet budget is {self.allocator.total} devices — it "
                f"could never be placed")
        job = FleetJob(spec, self.job_dir(spec.name), self._submit_seq,
                       self._clock())
        self._submit_seq += 1
        os.makedirs(job.job_dir, exist_ok=True)
        self.jobs[spec.name] = job
        if _journal:
            self._journal_ev("submit", job=spec.name, spec=spec.to_json())
        if job.completed_ok():
            # idempotent re-submit of a finished job (resume path)
            job.state = COMPLETED
            self._journal_ev("complete", job=spec.name, recovered=True)
        return job

    # -- scheduling policy ------------------------------------------------
    def effective_priority(self, job: FleetJob) -> float:
        """Static priority plus starvation aging over QUEUED time."""
        if job.state != QUEUED:
            return float(job.spec.priority)
        wait = max(self._clock() - job.submitted_at, 0.0)
        return job.spec.priority + self.aging_rate * wait

    def _tenant_used(self, tenant: str) -> int:
        return sum(len(j.slots) for j in self.jobs.values()
                   if j.spec.tenant == tenant
                   and j.state in (RUNNING, PREEMPTING))

    def _quota_ok(self, job: FleetJob) -> bool:
        quota = self.tenants.get(job.spec.tenant)
        if quota is None:
            return True
        return self._tenant_used(job.spec.tenant) + job.spec.world <= quota

    def _fair_frac(self, job: FleetJob) -> float:
        quota = self.tenants.get(job.spec.tenant, self.allocator.total)
        return self._tenant_used(job.spec.tenant) / max(quota, 1)

    def _rank_key(self, job: FleetJob):
        # highest effective priority first — FLOORED, so aging promotes
        # in whole priority units and microsecond wait differences can't
        # defeat the tie-breaks; ties go to the tenant using the smallest
        # share of its quota (fair-share), then FIFO
        return (-int(self.effective_priority(job)),
                self._fair_frac(job), job.seq)

    def _placeable_now(self, job: FleetJob) -> bool:
        return (self._clock() - job.submitted_at) >= job.spec.not_before_s

    # -- launch -----------------------------------------------------------
    def _job_transport(self, job: FleetJob):
        """The host transport for ``job``'s placement, or None when the
        gang is purely local (the direct-spawn path, unchanged).  Remote
        means SPARKNET_SSH_CMD is set (the fake-ssh CI rig included) or
        any placed host has a non-local address; network fault specs
        chaos-wrap it."""
        if not job.hosts or self.pool is None:
            return None
        from .transport import default_transport
        tp = default_transport(
            [self.pool.spec(h).addr for h in job.hosts])
        return None if tp.local else tp

    def _default_runner(self, job: FleetJob, cmd: list[str],
                        env: dict) -> ResilientRunner:
        # with a pool, the runner knows its placement (one supervised
        # process per gang on the simulated rig → a 1-entry host_map on
        # the gang's primary host) and can ask the pool whether a host
        # is down — the authoritative channel for host-granular budget
        # accounting (one host death = one budget unit, see resilience).
        # A suspect mark is the OTHER answer: the monitor suspends the
        # host's ranks instead of killing them (partition != death).
        host_kw: dict = {}
        place_kw: dict = dict(nprocs=1)
        if job.hosts and self.pool is not None:
            pool = self.pool
            host_kw = dict(
                host_map=[job.hosts[0]],
                host_down_probe=lambda h: pool.state.get(h) == HOST_LOST,
                host_suspect_probe=(
                    lambda h: pool.state.get(h) == HOST_SUSPECT))
            transport = self._job_transport(job)
            if transport is not None:
                # gang rides the transport: ssh wire format, staged
                # beats + lease discipline, host-local checkpoints
                place_kw = dict(
                    hosts=[pool.spec(job.hosts[0]).addr],
                    transport=transport)
        return ResilientRunner(
            cmd, platform=self.platform,
            timeout=job.spec.timeout_s,
            policy=RestartPolicy(max_restarts=job.spec.max_restarts,
                                 backoff_base=self.backoff_base),
            round_deadline=job.spec.round_deadline_s,
            workdir=os.path.join(job.job_dir, "runner",
                                 f"ep_{job.episodes:03d}"),
            extra_env=env,
            on_spawn=lambda procs: self._on_spawn(job, procs),
            **place_kw, **host_kw)

    def _on_spawn(self, job: FleetJob, procs: list) -> None:
        """Runs on the supervisor thread at every (re)launch: record the
        gang's pids for preemption signalling + orphan accounting."""
        job.procs = procs
        pids = [p.pid for p in procs]
        job.all_pids.update(pids)
        job.restarts_used += 1
        self._journal_ev("pids", job=job.name, pids=pids)
        # a preemption requested while the previous attempt was dying
        # must reach the fresh gang too (cancel() already stops restarts,
        # but this attempt raced the cancel and spawned anyway)
        if job.preempt_requested:
            self._signal_job(job, signal.SIGTERM)

    def _ship_checkpoints(self, job: FleetJob, transport) -> None:
        """Pre-launch checkpoint locality: a gang placed (over a remote
        transport) on a host whose local checkpoint dir lacks the newest
        valid round pulls it from wherever the job last checkpointed —
        crc-verified resumable chunks, sha256-checked against the
        manifest at the destination, manifest shipped last.  A ship that
        ultimately fails is loud but not fatal: the gang launches from
        whatever state its host has (an older round resumes correctly,
        just further back; round 0 launches cold)."""
        from .transport import (TransportError, newest_valid_round,
                                ship_latest_checkpoint)
        dst = job.active_ckpt_dir
        best_dir, best_r = None, None
        for d in job.ckpt_dirs():
            if os.path.realpath(d) == os.path.realpath(dst):
                continue
            r = newest_valid_round(d)
            if r is not None and (best_r is None or r > best_r):
                best_dir, best_r = d, r
        if best_dir is None:
            return
        try:
            rec = ship_latest_checkpoint(transport, job.hosts[0],
                                         best_dir, dst)
        except (TransportError, OSError) as e:
            print(f"fleet: checkpoint ship to {job.hosts[0]!r} failed "
                  f"({e}); launching from local state", file=sys.stderr,
                  flush=True)
            self._journal_ev("ship_fail", job=job.name,
                             host=job.hosts[0], error=str(e))
            return
        if rec and not rec.get("skipped"):
            print(f"fleet: shipped round {rec['round']} checkpoint "
                  f"({rec['bytes']} B) to {job.hosts[0]!r} for "
                  f"{job.name!r}", file=sys.stderr, flush=True)
            self._journal_ev("ship", job=job.name, host=job.hosts[0],
                             **rec)

    def _launch(self, job: FleetJob, slots: tuple[int, ...]) -> None:
        job.slots = slots
        job.hosts = self.allocator.hosts_of(slots)
        job.state = RUNNING
        job.started_at = self._clock()
        job.preempt_requested = False
        job.preempt_deadline = None
        job.release_requested = False
        job.drain_deadline = None
        job.signaled_pids = set()
        job.procs = []
        job.episodes += 1
        transport = self._job_transport(job)
        job.active_ckpt_dir = (job.host_ckpt_dir(job.hosts[0])
                               if transport is not None and job.hosts
                               else None)
        if job.active_ckpt_dir is not None:
            self._ship_checkpoints(job, transport)
        cmd = job.build_cmd()
        env = dict(self.extra_env)
        env.update(job.spec.env)
        env[ENV_JOB_TAG] = job.name
        # fence base: each launch episode fences off every earlier one
        # (the runner adds its attempt number — see resilience)
        env["SPARKNET_FENCE_BASE"] = str(job.episodes * 100000)
        if job.hosts:
            # placement facts ride the env: the gang's primary host tag
            # plus the full per-slot host vector (informational on the
            # simulated rig; a real pod launcher consumes the vector)
            env.setdefault("SPARKNET_FLEET_HOST", job.hosts[0])
            env.setdefault("SPARKNET_FLEET_HOSTVEC",
                           ",".join(self.allocator.host_vector(slots)))
        # telemetry: workers snapshot their metrics registry into the
        # job dir (throttled, atomic) so status views can fold them in
        # without a live channel; spec/env overrides win
        env.setdefault("SPARKNET_METRICS_SNAP",
                       os.path.join(job.job_dir, "metrics"))
        if job.spec.fault:
            env["SPARKNET_FAULT"] = job.spec.fault
        job.runner = self.runner_factory(job, cmd, env)
        tkind = transport.kind if transport is not None else "local"
        for h in job.hosts:
            self._host_transports[h] = tkind
        self._journal_ev("launch", job=job.name, episode=job.episodes,
                         slots=list(slots), hosts=list(job.hosts), cmd=cmd,
                         transport=tkind)
        job.thread = threading.Thread(
            target=self._supervise, args=(job, job.runner),
            name=f"fleet-{job.name}", daemon=True)
        job.thread.start()

    def _supervise(self, job: FleetJob, runner) -> None:
        try:
            rc = runner.run()
        except BaseException as e:   # a broken runner is a job failure
            job.error = f"{type(e).__name__}: {e}"
            rc = -1
        self._results.put((job, rc))

    # -- preemption -------------------------------------------------------
    def _signal_job(self, job: FleetJob, sig: int,
                    only_new: bool = True) -> None:
        for p in job.procs:
            if p.poll() is not None:
                continue
            if only_new and sig == signal.SIGTERM \
                    and p.pid in job.signaled_pids:
                continue
            try:
                p.send_signal(sig)
                if sig == signal.SIGTERM:
                    job.signaled_pids.add(p.pid)
            except (ProcessLookupError, OSError):
                pass

    def register_drain_hook(self, name: str, hook) -> None:
        """Attach a drain fence to job ``name``: any stop (preemption,
        release, shutdown) will ``hook.start()`` first and hold the
        SIGTERM until ``hook.done()`` or ``drain_grace_s`` expires."""
        self.drain_hooks[name] = hook

    def _begin_stop(self, job: FleetJob, *, release: bool,
                    by: str = "") -> None:
        """Common preempt/release entry: stop the supervision loop, then
        either open the drain window (hooked jobs — SIGTERM is deferred
        to :meth:`_escalate_preemptions`) or SIGTERM immediately."""
        if job.state not in (RUNNING, PREEMPTING):
            return
        job.preempt_requested = True
        job.release_requested = job.release_requested or release
        job.state = PREEMPTING
        if job.runner is not None:
            job.runner.cancel()
        hook = self.drain_hooks.get(job.name)
        if hook is not None and job.preempt_deadline is None \
                and job.drain_deadline is None:
            try:
                hook.start()
                job.drain_deadline = self._clock() + self.drain_grace_s
                self._journal_ev("drain", job=job.name,
                                 release=release, by=by)
            except Exception as e:
                print(f"fleet: drain hook for {job.name!r} failed "
                      f"({e!r}); falling through to SIGTERM",
                      file=sys.stderr, flush=True)
                job.drain_deadline = None
        if job.drain_deadline is None and job.preempt_deadline is None:
            job.preempt_deadline = self._clock() + self.preempt_grace_s
            self._signal_job(job, signal.SIGTERM)

    def preempt_job(self, job: FleetJob, *, by: str = "") -> None:
        """Start a graceful preemption: stop the supervision loop, drain
        if hooked, open the SIGTERM grace window.  Harvest decides
        requeue-vs-complete when the runner returns."""
        if job.state not in (RUNNING, PREEMPTING):
            return
        self._begin_stop(job, release=False, by=by)
        self._journal_ev("preempt", job=job.name, by=by)
        print(f"fleet: preempting {job.name!r}"
              + (f" for {by!r}" if by else ""), file=sys.stderr, flush=True)

    def release_job(self, name: str) -> None:
        """Gracefully END a job by scheduler decision — the serving
        scale-down path: drain (via the registered hook), SIGTERM, and
        at harvest the job is COMPLETED, not requeued.  Loud on unknown
        names; a no-op on already-terminal jobs."""
        job = self.jobs.get(name)
        if job is None:
            raise FleetError(f"release of unknown job {name!r}")
        if job.state in TERMINAL:
            return
        if job.state == QUEUED:
            # never launched: nothing to drain or signal
            job.state = COMPLETED
            job.release_requested = True
            self._journal_ev("release", job=name, queued=True)
            self._journal_ev("complete", job=name, released=True)
            return
        self._begin_stop(job, release=True, by="release")
        self._journal_ev("release", job=name)
        print(f"fleet: releasing {job.name!r} (drain, then stop)",
              file=sys.stderr, flush=True)

    # -- host lifecycle ---------------------------------------------------
    def jobs_on_host(self, host: str) -> list[FleetJob]:
        """Non-terminal jobs whose gang touches ``host``."""
        return [j for j in self.jobs.values()
                if host in j.hosts and j.state in (RUNNING, PREEMPTING)]

    def mark_host(self, host: str, state: str, *, by: str = "") -> None:
        """Change a host's liveness and act on its gangs.  ``draining``
        (a spot/preemption notice) evicts each gang gracefully — drain
        fence, SIGTERM→SNAPSHOT_STOP, requeue — while placement stops
        offering the host's slots.  ``lost`` (the machine is gone) is
        the abrupt path: every touching gang is killed outright and
        requeued onto surviving hosts, checkpoint-resumed bit-identical.
        ``suspect`` (the LINK is silent but the machine may be alive —
        a lease expiry, not a death certificate) deliberately touches
        no gang: placement stops, the per-job health monitor suspends
        straggler discipline for the host's ranks, and nothing is
        killed or requeued until a down-probe confirms death or an
        operator marks it lost.  ``live`` readmits the host's slots to
        placement (for a suspect host, that is the heal)."""
        if self.pool is None:
            raise FleetError("mark_host needs a HostPool "
                             "(scheduler built with total_devices only)")
        self.pool.mark(host, state)   # loud on unknown host / bad state
        self._journal_ev("host", host=host, state=state, by=by)
        print(f"fleet: host {host!r} -> {state}"
              + (f" (by {by})" if by else ""), file=sys.stderr, flush=True)
        if state == HOST_DRAINING:
            for job in self.jobs_on_host(host):
                self.preempt_job(job, by=f"drain:{host}")
        elif state == HOST_LOST:
            for job in self.jobs_on_host(host):
                self._host_lost_stop(job, host)

    def _host_lost_stop(self, job: FleetJob, host: str) -> None:
        """A machine under ``job`` died.  No drain fence, no SIGTERM
        grace — a dead host cannot drain, and the launcher's fail-fast
        would tear the surviving ranks off a dead collective anyway.
        Kill the whole gang now (on the simulated rig this IS the host
        kill), requeue at harvest, resume from checkpoint."""
        if job.state not in (RUNNING, PREEMPTING):
            return
        job.preempt_requested = True
        job.state = PREEMPTING
        if job.runner is not None:
            job.runner.cancel()
        job.drain_deadline = None
        job.preempt_deadline = self._clock()   # escalation owes no grace
        self._signal_job(job, signal.SIGKILL, only_new=False)
        self._journal_ev("host_kill", job=job.name, host=host)

    def _poll_host_control(self) -> None:
        """Apply host state changes appended to ``host_control.jsonl``
        by OTHER processes (tools/fleet.py mark-host, chaos harnesses).
        Torn trailing lines are retried next step, bad records are loud
        but not fatal."""
        if self.pool is None:
            return
        try:
            with open(self._host_control_path, "rb") as f:
                f.seek(self._host_control_pos)
                chunk = f.read()
        except OSError:
            return
        for line in chunk.splitlines(keepends=True):
            if not line.endswith(b"\n"):
                break   # torn append: re-read once the writer finishes
            self._host_control_pos += len(line)
            try:
                rec = json.loads(line)
                self.mark_host(str(rec["host"]), str(rec["state"]),
                               by=str(rec.get("by", "control")))
            except (ValueError, KeyError, FleetError) as e:
                print(f"fleet: bad host-control record {line!r}: {e}",
                      file=sys.stderr, flush=True)

    def _escalate_preemptions(self) -> None:
        now = self._clock()
        for job in self.jobs.values():
            if job.state != PREEMPTING:
                continue
            if job.drain_deadline is not None:
                # drain window: no signals while the hook drains — the
                # queued work this stop must not lose is still finishing
                hook = self.drain_hooks.get(job.name)
                try:
                    done = True if hook is None else bool(hook.done())
                except Exception:
                    done = True      # a broken hook must not wedge a stop
                if done or now > job.drain_deadline:
                    self._journal_ev("drain_done", job=job.name,
                                     ok=bool(done))
                    job.drain_deadline = None
                    job.preempt_deadline = now + self.preempt_grace_s
                    self._signal_job(job, signal.SIGTERM)
                continue
            # catch workers spawned after the first SIGTERM volley
            self._signal_job(job, signal.SIGTERM)
            if job.preempt_deadline is not None \
                    and now > job.preempt_deadline:
                self._signal_job(job, signal.SIGKILL, only_new=False)

    def _maybe_preempt(self) -> None:
        """At most one preemption decision per step: for the single
        highest-ranked queued job that quota allows but capacity blocks,
        evict the cheapest set of strictly-lower-priority running jobs
        that frees its gang."""
        if not self.preempt_enabled:
            return
        queued = sorted(
            (j for j in self.jobs.values()
             if j.state == QUEUED and self._placeable_now(j)),
            key=self._rank_key)
        for cand in queued:
            if not self._quota_ok(cand):
                continue
            deficit = cand.spec.world - self.allocator.free_count
            if deficit <= 0:
                return   # placeable — no preemption needed
            victims = sorted(
                (j for j in self.jobs.values()
                 if j.state == RUNNING and j.spec.preemptible
                 and j.spec.priority < cand.spec.priority),
                key=lambda j: (j.spec.priority, -(j.started_at or 0.0)))
            chosen, freed = [], 0
            for v in victims:
                if freed >= deficit:
                    break
                chosen.append(v)
                freed += len(v.slots)
            if freed < deficit:
                continue   # even evicting everything eligible won't fit
            for v in chosen:
                self.preempt_job(v, by=cand.name)
            return

    # -- harvest ----------------------------------------------------------
    def _harvest(self) -> None:
        while True:
            try:
                job, rc = self._results.get_nowait()
            except queue.Empty:
                return
            if job.thread is not None:
                job.thread.join(timeout=5)
            if job.slots:
                self.allocator.free(job.slots)
                job.slots = ()
            job.hosts = ()
            job.procs = []
            self._journal_ev("exit", job=job.name, rc=rc,
                             episode=job.episodes)
            if job.completed_ok():
                job.state = COMPLETED
                self._journal_ev("complete", job=job.name)
                print(f"fleet: {job.name!r} completed", file=sys.stderr,
                      flush=True)
            elif job.release_requested:
                # a scheduler-decided end (serving scale-down): the
                # drain already emptied it, the exit IS the completion
                job.state = COMPLETED
                job.drain_deadline = None
                self._journal_ev("complete", job=job.name, released=True)
                print(f"fleet: {job.name!r} released", file=sys.stderr,
                      flush=True)
            elif job.preempt_requested or rc == 0:
                # a clean exit WITHOUT the completion artifact is a
                # checkpoint-and-stop (our preemption, or the job's own
                # SIGTERM — e.g. the injected `preempt` fault); requeue
                # to resume from the checkpoint.  Bounded: a job that
                # keeps stopping cleanly without finishing quarantines
                # after max_preempts.
                job.preempt_count += 1
                if job.preempt_count > self.max_preempts:
                    self._quarantine(job, rc,
                                     reason="preempt/requeue loop "
                                            f"exceeded {self.max_preempts}")
                else:
                    job.state = QUEUED
                    job.submitted_at = self._clock()  # aging restarts
                    job.preempt_requested = False
                    job.preempt_deadline = None
                    job.drain_deadline = None
                    self._journal_ev("requeue", job=job.name,
                                     preempts=job.preempt_count)
            else:
                self._quarantine(job, rc)

    def _quarantine(self, job: FleetJob, rc: int,
                    reason: str = "") -> None:
        """Out of the rotation for good, with the post-mortem on disk —
        never retried forever, never silently dropped."""
        job.state = QUARANTINED
        failure = getattr(job.runner, "failure", None)
        post = {
            "job": job.name, "rc": rc,
            "reason": reason or (str(failure) if failure else
                                 job.error or f"exit rc={rc}"),
            "episodes": job.episodes,
            "attempts": job.restarts_used,
            "preempts": job.preempt_count,
        }
        if failure is not None:
            post.update(cause=failure.cause, rank=failure.rank,
                        heartbeat_age=failure.heartbeat_age,
                        log_tail=failure.log_tail)
        # the flight-recorder tail: the scheduling decisions (and any
        # restarts this process supervised) that led here — the black
        # box a post-mortem reader wants next to the exit code.  The
        # verdict itself is recorded BEFORE the tail is captured (the
        # journal echo lands after this file is written).
        telemetry.get_recorder().record(
            "fleet_quarantine", job=job.name, rc=rc,
            reason=post["reason"])
        post["flight_recorder"] = telemetry.get_recorder().tail(64)
        path = os.path.join(job.job_dir, "postmortem.json")
        with open(path, "w") as f:
            json.dump(post, f, indent=1)
        self._journal_ev("quarantine", job=job.name, rc=rc,
                         reason=post["reason"])
        print(f"fleet: {job.name!r} QUARANTINED ({post['reason']}); "
              f"post-mortem at {path}", file=sys.stderr, flush=True)

    # -- placement --------------------------------------------------------
    def _place(self) -> None:
        queued = sorted(
            (j for j in self.jobs.values()
             if j.state == QUEUED and self._placeable_now(j)),
            key=self._rank_key)
        for job in queued:
            if not self._quota_ok(job):
                continue
            slots = self.allocator.allocate(
                job.spec.world, avoid=self._replica_hosts(job))
            if slots is None:
                continue   # backfill: smaller jobs behind may still fit
            self._launch(job, slots)

    def _replica_hosts(self, job: FleetJob) -> set[str]:
        """Hosts already carrying a live replica of the same served
        model — serve gangs prefer a fresh host (soft anti-affinity in
        :meth:`GangAllocator.allocate`) so one host loss never takes
        every replica of a model at once."""
        if self.pool is None or job.spec.kind != "serve":
            return set()
        return {h for j in self.jobs.values()
                if j is not job and j.spec.kind == "serve"
                and j.spec.model == job.spec.model
                and j.state not in TERMINAL
                for h in j.hosts}

    # -- the loop ---------------------------------------------------------
    def step(self) -> None:
        """One scheduling pass: apply external host state changes,
        harvest exits, escalate overdue preemptions, decide at most one
        new preemption, place."""
        self._poll_host_control()
        self._harvest()
        self._escalate_preemptions()
        self._maybe_preempt()
        self._place()

    def done(self) -> bool:
        return all(j.state in TERMINAL for j in self.jobs.values())

    def run(self, *, tick_s: float = 0.2, timeout_s: float | None = None,
            status_every_s: float = 0.0) -> int:
        """Schedule until every job is terminal.  Returns 0 when all
        completed, 3 when any quarantined.  ``timeout_s`` bounds the
        whole fleet (everything still live is killed and quarantined —
        a wedged fleet must fail loudly, not hang CI forever)."""
        t0 = self._clock()
        last_status = t0
        while not self.done():
            self.step()
            now = self._clock()
            if status_every_s and now - last_status >= status_every_s:
                print(format_status(self.status()), flush=True)
                last_status = now
            if timeout_s is not None and now - t0 > timeout_s:
                self.shutdown()
                for j in self.jobs.values():
                    if j.state not in TERMINAL:
                        self._quarantine(j, -1, reason="fleet timeout")
                self._journal_ev("done", ok=False, timeout=True)
                return 3
            time.sleep(tick_s)
        ok = all(j.state == COMPLETED for j in self.jobs.values())
        self._journal_ev("done", ok=ok)
        return 0 if ok else 3

    def shutdown(self, grace_s: float | None = None) -> None:
        """Cancel and kill everything still running (used on operator
        interrupt and fleet timeout).  Jobs stay requeue-able: their
        checkpoints survive, only the processes die."""
        grace = self.preempt_grace_s if grace_s is None else grace_s
        live = [j for j in self.jobs.values()
                if j.state in (RUNNING, PREEMPTING)]
        for j in live:
            if j.runner is not None:
                j.runner.cancel()
            self._signal_job(j, signal.SIGTERM)
        deadline = time.monotonic() + grace
        for j in live:
            if j.thread is not None:
                j.thread.join(timeout=max(deadline - time.monotonic(), 0.1))
        for j in live:
            self._signal_job(j, signal.SIGKILL, only_new=False)
            if j.thread is not None:
                j.thread.join(timeout=5)
        self._harvest()
        self._journal_ev("shutdown")

    # -- orphan accounting ------------------------------------------------
    def live_worker_pids(self) -> dict[str, list[int]]:
        """Every recorded worker pid still alive AND provably ours —
        the soak harness's zero-orphans check."""
        out: dict[str, list[int]] = {}
        for job in self.jobs.values():
            alive = [p for p in sorted(job.all_pids)
                     if _pid_is_fleet_job(p, job.name)]
            if alive:
                out[job.name] = alive
        return out

    # -- status -----------------------------------------------------------
    def _heartbeats(self, job: FleetJob) -> dict[int, dict]:
        """Per-rank beats of the job's newest attempt (with the
        trainer's stall_s / FeedStats telemetry when present)."""
        from . import health
        if job.runner is None:
            return {}
        workdir = getattr(job.runner, "workdir", None)
        if not workdir:
            return {}
        attempts = sorted(glob.glob(os.path.join(workdir, "attempt_*")))
        if not attempts:
            return {}
        beats = health.read_all(os.path.join(attempts[-1], "hb"))
        return {rank: {"round": b.round, "phase": b.phase,
                       "age_s": round(b.age(), 2),
                       **({"extras": b.extras} if b.extras else {})}
                for rank, b in beats.items()}

    def status(self) -> dict[str, Any]:
        jobs = []
        for job in sorted(self.jobs.values(), key=lambda j: j.seq):
            round_done = job.newest_round()
            metrics = job_metrics(job.job_dir)
            jobs.append({
                "job": job.name,
                "kind": job.spec.kind,
                "model": job.spec.model,
                "tenant": job.spec.tenant,
                "state": job.state,
                "priority": job.spec.priority,
                "eff_priority": round(self.effective_priority(job), 2),
                "world": job.spec.world,
                "slots": list(job.slots),
                "episodes": job.episodes,
                "attempts": job.restarts_used,
                "preempts": job.preempt_count,
                "round": (job.spec.rounds if job.state == COMPLETED
                          else round_done),
                "rounds_target": job.spec.rounds,
                "hosts": list(job.hosts),
                "heartbeats": self._heartbeats(job),
                "metrics": metrics,
                "metrics_note": metrics_note(metrics),
            })
        by_tenant = {}
        for t in sorted({j.spec.tenant for j in self.jobs.values()}):
            by_tenant[t] = {"used": self._tenant_used(t),
                            "quota": self.tenants.get(t)}
        out = {"devices": {"total": self.allocator.total,
                           "free": self.allocator.free_count},
               "tenants": by_tenant, "jobs": jobs}
        if self.pool is not None:
            out["hosts"] = hosts_view(
                self.pool, jobs,
                beat_ages=host_beat_ages(self.workdir, jobs),
                transports=self._host_transports)
        serving = serving_status(self.workdir, jobs)
        if serving:
            out["serving"] = serving
        return out

    # -- crash recovery ---------------------------------------------------
    @classmethod
    def resume(cls, workdir: str, **kwargs) -> "FleetScheduler":
        """Rebuild a scheduler from ``workdir``'s journal after a
        scheduler death.  Completed/quarantined jobs stay terminal; a
        job that finished while unsupervised (out artifact on disk) is
        recognized as completed; everything else has its recorded
        worker pids killed (after the /proc environ identity check)
        and is requeued to resume from its checkpoints — no job is
        ever double-launched."""
        path = os.path.join(os.path.abspath(workdir),
                            "fleet_journal.jsonl")
        events = FleetJournal.read(path)
        if not events:
            raise FleetError(f"no journal to resume at {path}")
        devices = None
        tenants: dict[str, int] = {}
        pool: HostPool | None = None
        host_states: dict[str, str] = {}
        specs: dict[str, JobSpec] = {}
        terminal: dict[str, str] = {}
        pids: dict[str, set[int]] = {}
        counters: dict[str, dict[str, int]] = {}
        for ev in events:
            kind = ev.get("ev")
            name = ev.get("job")
            if kind == "fleet":
                devices = ev.get("devices", devices)
                tenants = dict(ev.get("tenants") or {})
                if ev.get("hosts"):
                    pool = HostPool.from_json(ev["hosts"])
            elif kind == "host":
                host_states[ev.get("host")] = ev.get("state")
            elif kind == "submit":
                specs[name] = JobSpec.from_json(ev["spec"])
                counters.setdefault(name, {"episodes": 0, "preempts": 0,
                                           "attempts": 0})
            elif kind == "launch":
                c = counters.setdefault(name, {"episodes": 0,
                                               "preempts": 0,
                                               "attempts": 0})
                c["episodes"] = ev.get("episode", c["episodes"] + 1)
            elif kind == "pids":
                pids.setdefault(name, set()).update(ev.get("pids", []))
                counters.setdefault(name, {"episodes": 0, "preempts": 0,
                                           "attempts": 0})["attempts"] += 1
            elif kind == "requeue":
                c = counters.setdefault(name, {"episodes": 0,
                                               "preempts": 0,
                                               "attempts": 0})
                c["preempts"] = ev.get("preempts", c["preempts"] + 1)
            elif kind in ("complete", "quarantine"):
                terminal[name] = (COMPLETED if kind == "complete"
                                  else QUARANTINED)
        if devices is None:
            raise FleetError(f"journal at {path} has no fleet record")
        kwargs.setdefault("tenants", tenants)
        if pool is not None and "hosts" not in kwargs:
            # re-apply the journaled host states so a host that was
            # draining/lost when the scheduler died stays unplaceable
            for host, st in host_states.items():
                if host in pool and st in HOST_STATES:
                    pool.mark(host, st)
            kwargs["hosts"] = pool
        sched = cls(workdir, devices if kwargs.get("hosts") is None
                    else None, **kwargs)
        try:
            # host-control records from before the death are already
            # reflected in the journaled host states replayed above —
            # re-applying them would re-fire their side effects
            sched._host_control_pos = os.path.getsize(
                sched._host_control_path)
        except OSError:
            pass
        for name, spec in specs.items():
            # reap survivors of the dead scheduler FIRST: resuming the
            # job while its old gang still trains is the double-launch
            # this journal exists to prevent
            if terminal.get(name) != COMPLETED:
                sched._reap(name, pids.get(name, set()))
            job = sched.submit(spec, _journal=False)
            c = counters.get(name, {})
            job.episodes = c.get("episodes", 0)
            job.restarts_used = c.get("attempts", 0)
            job.preempt_count = c.get("preempts", 0)
            job.all_pids = set(pids.get(name, set()))
            if terminal.get(name) == QUARANTINED:
                job.state = QUARANTINED
            elif terminal.get(name) == COMPLETED \
                    and spec.kind == "serve":
                # a released replica has no out artifact; the journal's
                # word is the only (and sufficient) completion proof
                job.state = COMPLETED
            # submit() already flipped state to COMPLETED when the out
            # artifact exists — covering jobs that finished unsupervised
            if job.state == QUEUED:
                sched._journal_ev("recover", job=name)
        sched._journal_ev("resumed", jobs=len(specs))
        return sched

    def _reap(self, job_name: str, pids: set[int]) -> None:
        """Kill recorded workers of ``job_name`` that are still alive
        (identity-checked): SIGTERM, short grace, SIGKILL."""
        alive = [p for p in sorted(pids)
                 if _pid_is_fleet_job(p, job_name)]
        if not alive:
            return
        print(f"fleet: resume reaping {len(alive)} surviving worker(s) "
              f"of {job_name!r}: {alive}", file=sys.stderr, flush=True)
        for p in alive:
            try:
                os.kill(p, signal.SIGTERM)
            except OSError:
                pass
        deadline = time.monotonic() + 3.0
        while time.monotonic() < deadline:
            if not any(_pid_is_fleet_job(p, job_name) for p in alive):
                return
            time.sleep(0.05)
        for p in alive:
            if _pid_is_fleet_job(p, job_name):
                try:
                    os.kill(p, signal.SIGKILL)
                except OSError:
                    pass


def host_beat_ages(workdir: str, jobs: list[dict]) -> dict[str, float]:
    """Newest relayed-beat age per host, scanned from each running
    job's newest attempt heartbeat tree.  Remote-transport gangs write
    (via the relay) into ``host_<name>`` subdirs, so attribution is
    direct; a single-host gang's flat rank beats are attributed to its
    only host.  Powers the lease column of the status host rows — live
    and offline read the same files."""
    from . import health
    ages: dict[str, float] = {}

    def fold(host: str, beats: dict) -> None:
        if not beats:
            return
        age = min(b.age() for b in beats.values())
        if host not in ages or age < ages[host]:
            ages[host] = age

    for j in jobs:
        hosts = j.get("hosts") or []
        if not hosts or j.get("state") not in (RUNNING, PREEMPTING):
            continue
        attempts = sorted(glob.glob(os.path.join(
            os.path.abspath(workdir), "jobs", j["job"],
            "runner", "ep_*", "attempt_*", "hb")))
        if not attempts:
            continue
        for host, beats in health.read_hosts(attempts[-1]).items():
            if host is None:
                if len(hosts) == 1:
                    fold(hosts[0], beats)
            elif host in hosts:
                fold(host, beats)
    return ages


def hosts_view(pool: HostPool, jobs: list[dict], *,
               beat_ages: Mapping[str, float] | None = None,
               transports: Mapping[str, str] | None = None
               ) -> dict[str, dict]:
    """The hosts section of a status view: per-host liveness state,
    device budget/usage, which gangs sit on it — computed the same
    way live and offline (slot→host is deterministic: consecutive
    ranges in inventory order) — plus, when the caller supplies them,
    the network-liveness columns: ``beat_age_s`` (newest relayed beat,
    see :func:`host_beat_ages`), ``transport`` (kind of the last launch
    that placed a gang there), and ``lease`` — the operator state
    verbatim when not live, else ``suspect`` iff a hosted gang's beats
    have gone silent past the lease window, else ``live``.  A live host
    with gangs but no beats yet is still ``live`` (startup grace,
    mirroring the in-gang LeaseMonitor)."""
    slot_host: dict[int, str] = {}
    i = 0
    for h in pool.specs():
        for _ in range(h.devices):
            slot_host[i] = h.name
            i += 1
    out: dict[str, dict] = {}
    for h in pool.specs():
        out[h.name] = {"state": pool.state.get(h.name, HOST_LIVE),
                       "addr": h.addr, "devices": h.devices,
                       "used": 0, "gangs": []}
    for j in jobs:
        for s in j.get("slots") or []:
            host = slot_host.get(s)
            if host is not None:
                out[host]["used"] += 1
        for host in j.get("hosts") or []:
            if host in out and j["job"] not in out[host]["gangs"]:
                out[host]["gangs"].append(j["job"])
    window: float | None = None
    for name, row in out.items():
        age = (beat_ages or {}).get(name)
        if age is not None:
            row["beat_age_s"] = round(age, 2)
        row["transport"] = (transports or {}).get(name, "local")
        if row["state"] != HOST_LIVE:
            row["lease"] = row["state"]
        elif row["gangs"] and age is not None:
            if window is None:
                from .health import lease_window_s
                window = lease_window_s()
            row["lease"] = ("suspect" if age > window else "live")
        else:
            row["lease"] = "live"
    return out


def request_mark_host(workdir: str, host: str, state: str,
                      by: str = "") -> None:
    """Ask the (possibly remote, possibly separate-process) scheduler
    owning ``workdir`` to mark ``host`` — appended to the host-control
    channel it polls every step.  Validation of the host NAME happens at
    apply time (the scheduler owns the inventory); the state is checked
    here so a typo fails at the operator's prompt, not in the log."""
    if state not in HOST_STATES:
        raise FleetError(f"bad host state {state!r} (one of {HOST_STATES})")
    path = os.path.join(os.path.abspath(workdir), "host_control.jsonl")
    rec = {"host": host, "state": state, "by": by,
           "t": round(time.time(), 3)}
    with open(path, "a") as f:
        f.write(json.dumps(rec) + "\n")
        f.flush()
        os.fsync(f.fileno())


def job_metrics(job_dir: str) -> dict[str, Any]:
    """Fold the registry snapshots a job's workers wrote into
    ``<job_dir>/metrics`` (see telemetry.MetricsRegistry.maybe_snapshot;
    the scheduler points workers there via SPARKNET_METRICS_SNAP).
    Empty when the job never snapshotted — older jobs simply lack it."""
    paths = glob.glob(os.path.join(job_dir, "metrics",
                                   "metrics_rank*.json"))
    if not paths:
        return {}
    return telemetry.fold_snapshots(sorted(paths))


def metrics_note(metrics: Mapping[str, Any]) -> str:
    """One compact table cell out of a job's folded registry snapshot."""
    if not metrics:
        return ""

    def total(name: str) -> float:
        agg = metrics.get(name)
        if not agg:
            return 0.0
        return sum(s.get("value", s.get("count", 0)) or 0
                   for s in agg.get("samples", ()))

    parts = []
    rounds = total("trainer_rounds_total")
    if rounds:
        parts.append(f"rounds {int(rounds)}")
    trips = total("trainer_guard_trips_total")
    if trips:
        parts.append(f"guard {int(trips)}")
    audits = total("trainer_audit_trips_total")
    if audits:
        parts.append(f"audit {int(audits)}")
    batches = total("feed_batches_total")
    if batches:
        parts.append(f"feed {int(batches)}b")
    served = total("serve_completed_total")
    if served:
        parts.append(f"served {int(served)}")
    return " ".join(parts)


def offline_status(workdir: str) -> dict[str, Any]:
    """The fleet status view reconstructed from ``workdir``'s journal
    alone — no scheduler process, nothing launched, nothing signalled.
    The data source for ``tools/fleet.py --status [--json]``: external
    scrapers get the same facts the live table shows (journal state,
    newest checkpoint manifests, per-rank heartbeats, folded registry
    snapshots) without parsing the human rendering."""
    path = os.path.join(os.path.abspath(workdir), "fleet_journal.jsonl")
    events = FleetJournal.read(path)
    if not events:
        raise FleetError(f"no journal to read at {path}")
    devices = 0
    tenants: dict[str, int] = {}
    pool: HostPool | None = None
    order: list[str] = []
    specs: dict[str, JobSpec] = {}
    state: dict[str, str] = {}
    slots: dict[str, list[int]] = {}
    job_hosts: dict[str, list[str]] = {}
    host_transports: dict[str, str] = {}
    counters: dict[str, dict[str, int]] = {}
    for ev in events:
        kind = ev.get("ev")
        name = ev.get("job")
        c = counters.setdefault(name, {"episodes": 0, "attempts": 0,
                                       "preempts": 0}) if name else None
        if kind == "fleet":
            devices = ev.get("devices", devices)
            tenants = dict(ev.get("tenants") or {})
            if ev.get("hosts"):
                pool = HostPool.from_json(ev["hosts"])
        elif kind == "host":
            if pool is not None and ev.get("host") in pool \
                    and ev.get("state") in HOST_STATES:
                pool.mark(ev["host"], ev["state"])
        elif kind == "submit":
            specs[name] = JobSpec.from_json(ev["spec"])
            order.append(name)
            state[name] = QUEUED
        elif kind == "launch":
            state[name] = RUNNING
            slots[name] = list(ev.get("slots", []))
            job_hosts[name] = list(ev.get("hosts") or [])
            for h in job_hosts[name]:
                host_transports[h] = ev.get("transport", "local")
            c["episodes"] = ev.get("episode", c["episodes"] + 1)
        elif kind == "pids":
            c["attempts"] += 1
        elif kind == "preempt":
            state[name] = PREEMPTING
        elif kind == "release":
            # scale-down in flight: draining, then stopping; the
            # matching "complete" (released=True) lands at harvest
            if state.get(name) not in TERMINAL:
                state[name] = PREEMPTING
        elif kind == "requeue":
            state[name] = QUEUED
            slots.pop(name, None)
            job_hosts.pop(name, None)
            c["preempts"] = ev.get("preempts", c["preempts"] + 1)
        elif kind == "exit":
            if state.get(name) not in TERMINAL:
                state[name] = "EXITED"
            slots.pop(name, None)
            job_hosts.pop(name, None)
        elif kind == "complete":
            state[name] = COMPLETED
            slots.pop(name, None)
            job_hosts.pop(name, None)
        elif kind == "quarantine":
            state[name] = QUARANTINED
            slots.pop(name, None)
            job_hosts.pop(name, None)
        elif kind == "recover":
            state[name] = QUEUED
    jobs = []
    used_by_tenant: dict[str, int] = {}
    free = devices
    for name in order:
        spec = specs[name]
        job_dir = os.path.join(os.path.abspath(workdir), "jobs", name)
        probe = FleetJob(spec, job_dir, 0, 0.0)
        st = state.get(name, QUEUED)
        if st not in TERMINAL and probe.completed_ok():
            st = COMPLETED   # finished after the journal's last word
        job_slots = slots.get(name, []) if st in (RUNNING,
                                                  PREEMPTING) else []
        host_list = (job_hosts.get(name, []) if st in (RUNNING, PREEMPTING)
                     else [])
        if job_slots:
            free -= len(job_slots)
            used_by_tenant[spec.tenant] = (
                used_by_tenant.get(spec.tenant, 0) + len(job_slots))
        # newest attempt's heartbeat dir, scanned without a runner handle
        beats: dict[int, dict] = {}
        attempts = sorted(glob.glob(os.path.join(
            job_dir, "runner", "ep_*", "attempt_*", "hb")))
        if attempts:
            from . import health
            beats = {rank: {"round": b.round, "phase": b.phase,
                            "age_s": round(b.age(), 2),
                            **({"extras": b.extras} if b.extras else {})}
                     for rank, b in health.read_all(attempts[-1]).items()}
        metrics = job_metrics(job_dir)
        c = counters.get(name, {})
        jobs.append({
            "job": name, "kind": spec.kind, "model": spec.model,
            "tenant": spec.tenant,
            "state": st,
            "priority": spec.priority,
            "eff_priority": float(spec.priority),  # no live clock offline
            "world": spec.world, "slots": job_slots,
            "episodes": c.get("episodes", 0),
            "attempts": c.get("attempts", 0),
            "preempts": c.get("preempts", 0),
            "round": (spec.rounds if st == COMPLETED
                      else probe.newest_round()),
            "rounds_target": spec.rounds,
            "hosts": host_list,
            "heartbeats": beats,
            "metrics": metrics,
            "metrics_note": metrics_note(metrics),
        })
    by_tenant = {t: {"used": used_by_tenant.get(t, 0),
                     "quota": tenants.get(t)}
                 for t in sorted({j["tenant"] for j in jobs} |
                                 set(tenants))}
    out = {"devices": {"total": devices, "free": max(free, 0)},
           "tenants": by_tenant, "jobs": jobs}
    if pool is not None:
        out["hosts"] = hosts_view(
            pool, jobs, beat_ages=host_beat_ages(workdir, jobs),
            transports=host_transports)
    serving = serving_status(os.path.abspath(workdir), jobs)
    if serving:
        out["serving"] = serving
    return out


def serving_status(workdir: str, jobs: list[dict]) -> dict[str, Any]:
    """The serving-fleet half of a status view: per-model replica
    counts (from serve-kind job rows), the autoscaler's last decision +
    reason (``autoscale.json``), and the router table
    (``router.json``) — both written atomically by the live fleet, so
    this works on a dead one too.  Empty when the workdir never served."""
    out: dict[str, Any] = {}
    serve_jobs = [j for j in jobs if j.get("kind") == "serve"]
    if serve_jobs:
        models: dict[str, dict[str, int]] = {}
        for j in serve_jobs:
            key = j.get("model") or j["job"].rsplit("-", 1)[0]
            m = models.setdefault(key, {"replicas": 0, "running": 0})
            m["replicas"] += 1
            if j["state"] in (RUNNING, PREEMPTING):
                m["running"] += 1
        out["models"] = models
    for fname, key in (("autoscale.json", "autoscale"),
                       ("router.json", "router")):
        try:
            with open(os.path.join(workdir, fname)) as f:
                out[key] = json.load(f)
        except (OSError, json.JSONDecodeError):
            pass
    try:
        # journal-replayed channel state: works with the rollout
        # controller dead, which is exactly when status matters most
        from .rollout import status as rollout_status
        ro = rollout_status(workdir)
        if ro:
            out["rollout"] = ro
    except (OSError, ValueError):
        pass
    return out


def format_status(status: Mapping[str, Any]) -> str:
    """Render ``FleetScheduler.status()`` as a fixed-width table."""
    dev = status["devices"]
    lines = [f"fleet: devices {dev['total'] - dev['free']}/{dev['total']} "
             f"in use | tenants: "
             + ", ".join(f"{t} {v['used']}/{v['quota'] or '∞'}"
                         for t, v in status["tenants"].items())]
    hdr = (f"{'JOB':<16} {'TENANT':<8} {'STATE':<11} {'PRI':>5} "
           f"{'EFF':>6} {'GANG':>4} {'ROUND':>7} {'EP':>3} {'PRE':>3}  "
           f"HEARTBEAT")
    lines.append(hdr)
    for j in status["jobs"]:
        rnd = "-" if j["round"] is None else str(j["round"])
        hb = ""
        for rank, b in sorted(j["heartbeats"].items()):
            hb = (f"r{rank} {b['phase']}@{b['round']} "
                  f"({b['age_s']:.1f}s ago)")
            extras = b.get("extras") or {}
            stall = extras.get("stall_s")
            if stall:
                hb += f" stall {sum(stall.values()):.2f}s"
            if extras.get("serving"):
                # a serving job's beat: fold queue/latency telemetry the
                # way training jobs fold stall_s
                hb += (f" q{extras.get('queue_depth', 0)}"
                       f"+{extras.get('in_flight', 0)} "
                       f"p50 {extras.get('p50_ms', 0):.0f}ms "
                       f"p99 {extras.get('p99_ms', 0):.0f}ms")
                slo = extras.get("slo") or {}
                if slo.get("state") == "breach":
                    hb += f" SLO:BREACH({slo.get('breaches', 0)})"
                elif slo.get("state"):
                    hb += " SLO:ok"
            break   # first rank is enough for the one-liner
        note = j.get("metrics_note")
        if note:
            hb = f"{hb} [{note}]" if hb else f"[{note}]"
        lines.append(
            f"{j['job']:<16} {j['tenant']:<8} {j['state']:<11} "
            f"{j['priority']:>5} {j['eff_priority']:>6.1f} "
            f"{j['world']:>4} {rnd:>3}/{j['rounds_target']:<3} "
            f"{j['episodes']:>3} {j['preempts']:>3}  {hb}")
    for hname, h in (status.get("hosts") or {}).items():
        gangs = ",".join(h.get("gangs") or []) or "-"
        extra = ""
        if h.get("lease"):
            extra += f" lease={h['lease']}"
        if h.get("beat_age_s") is not None:
            extra += f" beat={h['beat_age_s']:.1f}s"
        if h.get("transport") and h["transport"] != "local":
            extra += f" via={h['transport']}"
        lines.append(f"host:    {hname:<16} {h.get('state', '?'):<9} "
                     f"{h.get('used', 0)}/{h.get('devices', 0)} devices "
                     f"@{h.get('addr', '?')} gangs={gangs}{extra}")
    serving = status.get("serving") or {}
    auto = (serving.get("autoscale") or {}).get("models") or {}
    for model, m in sorted((serving.get("models") or {}).items()):
        line = (f"serving: {model:<20} replicas "
                f"{m['running']}/{m['replicas']}")
        rec = auto.get(model) or {}
        last = rec.get("last")
        if rec.get("backlog") is not None:
            line += f" backlog {rec['backlog']}"
        if last:
            age = time.time() - (serving.get("autoscale") or {}).get(
                "t", time.time())
            line += (f" | last {last['action']} ({last['reason']})"
                     + (f" {age:.0f}s ago" if age >= 1 else ""))
        lines.append(line)
    router = serving.get("router") or {}
    for rid, r in sorted((router.get("replicas") or {}).items()):
        lines.append(f"router:  {rid:<20} {r.get('state', '?'):<9} "
                     f"out={r.get('outstanding', 0)} "
                     f"done={r.get('completed', 0)} "
                     f"fail={r.get('failed', 0)} "
                     f"models={','.join(r.get('models') or [])}")
    counts = router.get("counts")
    if counts:
        lines.append("router:  " + " ".join(
            f"{k}={v}" for k, v in sorted(counts.items())))
    for model, ro in sorted((serving.get("rollout") or {}).items()):
        line = (f"rollout: {model:<20} {ro.get('phase', '?'):<14} "
                f"stable={ro.get('stable') or '-'}")
        if ro.get("canary"):
            line += (f" canary={ro['canary']}"
                     f"@{ro.get('weight', 0.0):g}")
        if ro.get("last_verdict"):
            line += f" verdict={ro['last_verdict']}"
        if ro.get("last_rollback_reason"):
            line += f" | rolled back: {ro['last_rollback_reason']}"
        lines.append(line)
    return "\n".join(lines)
