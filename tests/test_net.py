"""Graph compiler tests — the analog of the reference's net-construction
tests (test_net.cpp: graph build/sharing) and LayerSpec (DSL + prototxt nets
load and run; reference: src/test/scala/libs/LayerSpec.scala)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sparknet_tpu.graph import Net
from sparknet_tpu.models import lenet, cifar10_quick
from sparknet_tpu.proto import NetState, Phase, load_net_prototxt


def test_lenet_builds_and_runs(rng):
    net = Net(lenet(train_batch=4, test_batch=4), NetState(Phase.TRAIN))
    assert net.input_blobs == {"data": (4, 1, 28, 28), "label": (4,)}
    params = net.init(rng)
    assert params["conv1"][0].shape == (20, 1, 5, 5)
    assert params["ip1"][0].shape == (500, 50 * 4 * 4)
    out = net.apply(params, {
        "data": jnp.zeros((4, 1, 28, 28)),
        "label": jnp.zeros((4,)),
    }, rng=rng)
    assert out.loss.shape == ()
    assert float(out.loss) == pytest.approx(np.log(10), rel=0.05)


def test_phase_split():
    train = Net(lenet(4, 8), NetState(Phase.TRAIN))
    test = Net(lenet(4, 8), NetState(Phase.TEST))
    assert "accuracy" not in train.layer_names()
    assert "accuracy" in test.layer_names()
    # test batch size differs
    assert test.input_blobs["data"] == (8, 1, 28, 28)


def test_test_net_shares_train_params(rng):
    train = Net(lenet(4, 4), NetState(Phase.TRAIN))
    test = Net(lenet(4, 4), NetState(Phase.TEST))
    params = train.init(rng)
    out = test.apply(params, {
        "data": jnp.zeros((4, 1, 28, 28)),
        "label": jnp.zeros((4,)),
    }, train=False)
    assert "accuracy" in out.blobs


def test_inplace_layers(rng):
    # relu1 in lenet is in-place on ip1
    net = Net(lenet(2, 2), NetState(Phase.TRAIN))
    params = net.init(rng)
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 1, 28, 28))
    blobs = net.apply_all(params, {"data": x, "label": jnp.zeros((2,))},
                          rng=jax.random.PRNGKey(2))
    assert np.all(np.asarray(blobs["ip1"]) >= 0)


def test_unknown_bottom_raises():
    txt = """
    name: "bad"
    layer { name: "r" type: "ReLU" bottom: "nope" top: "r" }
    """
    with pytest.raises(ValueError, match="bottom 'nope' unknown"):
        Net(load_net_prototxt(txt))


def test_prototxt_net_runs(rng):
    txt = """
    name: "toy"
    layer { name: "data" type: "Input" top: "data"
            input_param { shape { dim: 2 dim: 3 dim: 8 dim: 8 } } }
    layer { name: "conv" type: "Convolution" bottom: "data" top: "conv"
            convolution_param { num_output: 4 kernel_size: 3 pad: 1
                                weight_filler { type: "xavier" } } }
    layer { name: "relu" type: "ReLU" bottom: "conv" top: "conv" }
    layer { name: "pool" type: "Pooling" bottom: "conv" top: "pool"
            pooling_param { pool: MAX kernel_size: 2 stride: 2 } }
    """
    net = Net(load_net_prototxt(txt))
    params = net.init(rng)
    out = net.apply(params, {"data": jnp.ones((2, 3, 8, 8))}, train=False)
    assert out.blobs["pool"].shape == (2, 4, 4, 4)


def test_param_sharing_siamese(rng):
    txt = """
    name: "siamese"
    layer { name: "d" type: "Input" top: "a" top: "b"
            input_param { shape { dim: 2 dim: 4 } } }
    layer { name: "ip_a" type: "InnerProduct" bottom: "a" top: "fa"
            param { name: "w" } param { name: "bias" }
            inner_product_param { num_output: 3
                                  weight_filler { type: "xavier" } } }
    layer { name: "ip_b" type: "InnerProduct" bottom: "b" top: "fb"
            param { name: "w" } param { name: "bias" }
            inner_product_param { num_output: 3
                                  weight_filler { type: "xavier" } } }
    """
    net = Net(load_net_prototxt(txt))
    params = net.init(rng)
    assert "ip_a" in params and "ip_b" not in params  # shared -> one owner
    x = jax.random.normal(rng, (2, 4))
    out = net.apply(params, {"a": x, "b": x}, train=False)
    np.testing.assert_allclose(np.asarray(out.blobs["fa"]),
                               np.asarray(out.blobs["fb"]), rtol=1e-6)


def test_param_sharing_per_blob(rng):
    """Only the weight is shared; biases stay independent (per-ParamSpec
    sharing granularity of net.cpp AppendParam)."""
    txt = """
    name: "partial"
    layer { name: "d" type: "Input" top: "a" top: "b"
            input_param { shape { dim: 2 dim: 4 } } }
    layer { name: "ip_a" type: "InnerProduct" bottom: "a" top: "fa"
            param { name: "w" }
            inner_product_param { num_output: 3
                                  weight_filler { type: "xavier" }
                                  bias_filler { type: "constant" value: 1 } } }
    layer { name: "ip_b" type: "InnerProduct" bottom: "b" top: "fb"
            param { name: "w" }
            inner_product_param { num_output: 3
                                  weight_filler { type: "xavier" }
                                  bias_filler { type: "constant" value: 2 } } }
    """
    net = Net(load_net_prototxt(txt))
    params = net.init(rng)
    assert len(params["ip_a"]) == 2        # owns weight + bias
    assert len(params["ip_b"]) == 1        # owns only its bias
    x = jax.random.normal(rng, (2, 4))
    out = net.apply(params, {"a": x, "b": x}, train=False)
    np.testing.assert_allclose(
        np.asarray(out.blobs["fb"]) - np.asarray(out.blobs["fa"]),
        np.ones((2, 3)), rtol=1e-5)        # same weight, bias differs by 1


def test_param_sharing_shape_mismatch_raises():
    txt = """
    name: "bad"
    layer { name: "d" type: "Input" top: "a" top: "b"
            input_param { shape { dim: 2 dim: 4 } shape { dim: 2 dim: 5 } } }
    layer { name: "ip_a" type: "InnerProduct" bottom: "a" top: "fa"
            param { name: "w" }
            inner_product_param { num_output: 3
                                  weight_filler { type: "xavier" } } }
    layer { name: "ip_b" type: "InnerProduct" bottom: "b" top: "fb"
            param { name: "w" }
            inner_product_param { num_output: 3
                                  weight_filler { type: "xavier" } } }
    """
    with pytest.raises(ValueError, match="shape mismatch"):
        Net(load_net_prototxt(txt))


def test_jit_apply(rng):
    net = Net(cifar10_quick(4, 4), NetState(Phase.TRAIN))
    params = net.init(rng)

    @jax.jit
    def fwd(params, data, label):
        return net.apply(params, {"data": data, "label": label},
                         rng=jax.random.PRNGKey(0)).loss

    loss = fwd(params, jnp.zeros((4, 3, 32, 32)), jnp.zeros((4,)))
    assert np.isfinite(float(loss))


def test_googlenet_builds(rng):
    from sparknet_tpu.models import googlenet
    net = Net(googlenet(2, 2, crop=224), NetState(Phase.TRAIN))
    params = net.init(rng)
    # 3 losses in train phase
    losses = [n for n in net.layer_names() if "loss" in n.lower()
              and "classifier" not in n and "fc" not in n.lower()]
    out = net.apply(params, {
        "data": jnp.zeros((2, 3, 224, 224)), "label": jnp.zeros((2,))},
        rng=rng)
    # total loss ≈ ln(1000)·(1 + 0.3 + 0.3)
    assert float(out.loss) == pytest.approx(np.log(1000) * 1.6, rel=0.05)


def test_weight_collection_math(rng):
    from sparknet_tpu.graph.net import weights_add, weights_scalar_divide
    net = Net(lenet(2, 2), NetState(Phase.TRAIN))
    a = net.init(rng)
    b = net.init(jax.random.PRNGKey(7))
    s = weights_scalar_divide(weights_add(a, b), 2.0)
    np.testing.assert_allclose(
        np.asarray(s["conv1"][0]),
        (np.asarray(a["conv1"][0]) + np.asarray(b["conv1"][0])) / 2,
        rtol=1e-6)


def test_bf16_compute_dtype(rng):
    """compute_dtype=bf16 runs the mixed-precision path: activations cast
    per layer, master params / loss / BN state stay float32."""
    net = Net(lenet(4, 4), NetState(Phase.TRAIN), compute_dtype=jnp.bfloat16)
    params = net.init(rng)
    assert all(b.dtype == jnp.float32 for bl in params.values() for b in bl)
    out = net.apply(params, {
        "data": jnp.zeros((4, 1, 28, 28)),
        "label": jnp.zeros((4,)),
    }, rng=rng)
    assert out.loss.dtype == jnp.float32
    assert float(out.loss) == pytest.approx(np.log(10), rel=0.1)
    # grads flow in f32 through the casts
    def loss_fn(p):
        return net.apply(p, {"data": jnp.ones((4, 1, 28, 28)),
                             "label": jnp.zeros((4,))}, rng=rng).loss
    g = jax.grad(loss_fn)(params)
    assert g["conv1"][0].dtype == jnp.float32
    assert float(jnp.max(jnp.abs(g["ip2"][0]))) > 0


def test_output_blobs_order_and_inplace_survivors():
    """output_blobs: Caffe's available-blob walk (in-place tails stay
    outputs), ordered by first production — Classifier/Detector index
    output_blobs[-1] expecting the LAST-produced head (classify.py:112)."""
    from sparknet_tpu.graph import Net as GraphNet
    from sparknet_tpu.proto import NetState, Phase, load_net_prototxt

    text = """
input: "data"
input_shape { dim: 1 dim: 2 dim: 4 dim: 4 }
layer { name: "feat" type: "InnerProduct" bottom: "data" top: "feat"
  inner_product_param { num_output: 3 weight_filler { type: "xavier" } } }
layer { name: "prob" type: "Softmax" bottom: "feat" top: "prob" }
layer { name: "featrelu" type: "ReLU" bottom: "feat" top: "feat" }
"""
    net = GraphNet(load_net_prototxt(text), NetState(Phase.TEST))
    # 'feat' survives (the trailing in-place ReLU re-adds it) but 'prob'
    # is produced last -> output_blobs[-1] stays the classifier head
    assert net.output_blobs == ["feat", "prob"]

    # a net ENDING with an in-place layer still has an output at all
    tail = """
input: "data"
input_shape { dim: 1 dim: 2 }
layer { name: "ip" type: "InnerProduct" bottom: "data" top: "ip"
  inner_product_param { num_output: 2 weight_filler { type: "xavier" } } }
layer { name: "relu" type: "ReLU" bottom: "ip" top: "ip" }
"""
    net2 = GraphNet(load_net_prototxt(tail), NetState(Phase.TEST))
    assert net2.output_blobs == ["ip"]


HFUSE_NET = """
input: "data"
input_shape { dim: 2 dim: 6 dim: 8 dim: 8 }
input: "label"
input_shape { dim: 2 }
layer { name: "b1x1" type: "Convolution" bottom: "data" top: "b1x1"
  convolution_param { num_output: 3 kernel_size: 1
    weight_filler { type: "gaussian" std: 0.1 }
    bias_filler { type: "constant" value: 0.1 } } }
layer { name: "b3r" type: "Convolution" bottom: "data" top: "b3r"
  convolution_param { num_output: 4 kernel_size: 1
    weight_filler { type: "gaussian" std: 0.1 }
    bias_filler { type: "constant" value: 0.2 } } }
layer { name: "b3" type: "Convolution" bottom: "b3r" top: "b3"
  convolution_param { num_output: 5 kernel_size: 3 pad: 1
    weight_filler { type: "gaussian" std: 0.1 }
    bias_filler { type: "constant" value: 0.0 } } }
layer { name: "b5r" type: "Convolution" bottom: "data" top: "b5r"
  convolution_param { num_output: 2 kernel_size: 1
    weight_filler { type: "gaussian" std: 0.1 }
    bias_filler { type: "constant" value: 0.3 } } }
layer { name: "cat" type: "Concat" bottom: "b1x1" bottom: "b3"
  bottom: "b5r" top: "cat" }
layer { name: "ip" type: "InnerProduct" bottom: "cat" top: "ip"
  inner_product_param { num_output: 4
    weight_filler { type: "gaussian" std: 0.1 } } }
layer { name: "loss" type: "SoftmaxWithLoss" bottom: "ip" bottom: "label" }
"""


def test_hfuse_sibling_1x1_convs_exact(rng, monkeypatch):
    """Horizontal fusion (default ON) runs sibling 1x1 convs over the
    same input as ONE fused conv + split — forward loss, every blob, and
    gradients must be EXACTLY the unfused values (per-output-channel
    reductions are untouched by filter concatenation);
    SPARKNET_NO_HFUSE=1 gives the per-layer reference path."""
    netp = load_net_prototxt(HFUSE_NET)
    net = Net(netp, NetState(Phase.TRAIN))
    # detection: the three data-fed 1x1s group; the 3x3 (b3) stays out
    assert set(net._hfuse_first) == {"b1x1"}
    assert [m.lp.name for m in net._hfuse_first["b1x1"]] == \
        ["b1x1", "b3r", "b5r"]
    params = net.init(rng)
    inputs = {"data": jnp.asarray(
        np.random.default_rng(0).normal(size=(2, 6, 8, 8)),
        jnp.float32), "label": jnp.zeros((2,))}

    def loss_fn(p):
        return net.apply(p, inputs, rng=rng).loss

    monkeypatch.setenv("SPARKNET_NO_HFUSE", "1")
    ref_out = net.apply_all(params, inputs, rng=rng)
    ref_loss, ref_grads = jax.value_and_grad(loss_fn)(params)
    monkeypatch.delenv("SPARKNET_NO_HFUSE")
    fused_out = net.apply_all(params, inputs, rng=rng)
    fused_loss, fused_grads = jax.value_and_grad(loss_fn)(params)

    assert float(fused_loss) == float(ref_loss)
    for b in ref_out:
        np.testing.assert_array_equal(np.asarray(fused_out[b]),
                                      np.asarray(ref_out[b]))
    for k in ref_grads:
        for g1, g2 in zip(ref_grads[k], fused_grads[k]):
            np.testing.assert_allclose(np.asarray(g1), np.asarray(g2),
                                       rtol=1e-6, atol=1e-7)


def test_hfuse_inplace_versioning_blocks_cross_version_group():
    """Two 1x1 convs reading blob 'x' BEFORE and AFTER an in-place ReLU
    rewrites it read different tensors — they must not fuse."""
    text = """
input: "x"
input_shape { dim: 1 dim: 3 dim: 4 dim: 4 }
layer { name: "a" type: "Convolution" bottom: "x" top: "a"
  convolution_param { num_output: 2 kernel_size: 1
    weight_filler { type: "xavier" } } }
layer { name: "relu" type: "ReLU" bottom: "x" top: "x" }
layer { name: "b" type: "Convolution" bottom: "x" top: "b"
  convolution_param { num_output: 2 kernel_size: 1
    weight_filler { type: "xavier" } } }
"""
    net = Net(load_net_prototxt(text), NetState(Phase.TEST))
    assert net._hfuse_first == {}


def test_hfuse_matches_unfused_under_bf16_compute(rng, monkeypatch):
    """compute_dtype=bf16: the fused path casts the concatenated filters
    once where the per-layer path casts each member — same bf16 values
    either way, so outputs must match exactly."""
    netp = load_net_prototxt(HFUSE_NET)
    net = Net(netp, NetState(Phase.TRAIN), compute_dtype=jnp.bfloat16)
    params = net.init(rng)
    inputs = {"data": jnp.asarray(
        np.random.default_rng(1).normal(size=(2, 6, 8, 8)), jnp.float32),
        "label": jnp.zeros((2,))}
    monkeypatch.setenv("SPARKNET_NO_HFUSE", "1")
    ref = net.apply_all(params, inputs, rng=rng)
    ref_loss = net.apply(params, inputs, rng=rng).loss
    monkeypatch.delenv("SPARKNET_NO_HFUSE")
    fused = net.apply_all(params, inputs, rng=rng)
    fused_loss = net.apply(params, inputs, rng=rng).loss
    assert float(fused_loss) == float(ref_loss)
    for b in ref:
        np.testing.assert_array_equal(np.asarray(fused[b]),
                                      np.asarray(ref[b]))
