"""Shared app driver: the outer training loop both apps run.

The reference's driver loop per round (reference:
src/main/scala/apps/ImageNetApp.scala:100-182): broadcast weights → each
worker trains τ local steps on minibatches sampled from its partition →
collect & average weights → every 10 rounds, a distributed eval whose
per-worker scores are summed on the driver (:138-140).  Here broadcast/
collect/average live inside the trainer's compiled round; the app loop only
assembles per-round feeds and logs.
"""

from __future__ import annotations

import time
from typing import Callable, Sequence

import numpy as np

from ..data.minibatch import make_minibatches
from ..data.partition import PartitionedDataset
from ..parallel.trainer import DistributedTrainer
from ..utils.timing import PhaseLogger


class RoundFeed:
    """Assembles [τ, global_batch, ...] round feeds from a partitioned
    dataset — one partition per worker, τ contiguous minibatches per round
    per partition (MinibatchSampler's contiguous-run semantics, reference:
    src/main/scala/libs/MinibatchSampler.scala:18-19), with a per-batch
    preprocessing closure (the setTrainData(preprocess) argument, reference:
    src/main/scala/libs/Net.scala:79-84)."""

    def __init__(self, dataset: PartitionedDataset, per_worker_batch: int,
                 tau: int,
                 preprocess: Callable[[np.ndarray], np.ndarray] | None = None,
                 seed: int = 0):
        self.tau = tau
        self.preprocess = preprocess
        self._rng = np.random.default_rng(seed)
        self._parts = []
        for p in dataset.partitions:
            images = np.stack([x for x, _ in p])
            labels = np.asarray([y for _, y in p], np.float32)
            batches = make_minibatches(images, labels, per_worker_batch)
            if len(batches) < tau:
                raise ValueError(
                    f"partition has {len(batches)} minibatches < tau={tau}")
            self._parts.append(batches)

    def next_round(self) -> dict[str, np.ndarray]:
        data_steps, label_steps = [], []
        starts = [int(self._rng.integers(0, len(b) - self.tau + 1))
                  for b in self._parts]
        for t in range(self.tau):
            imgs, labs = [], []
            for w, batches in enumerate(self._parts):
                x, y = batches[starts[w] + t]
                if self.preprocess is not None:
                    x = self.preprocess(x)
                imgs.append(x)
                labs.append(y)
            data_steps.append(np.concatenate(imgs))
            label_steps.append(np.concatenate(labs))
        return {"data": np.stack(data_steps),
                "label": np.stack(label_steps)}


def eval_feed(dataset: PartitionedDataset, per_worker_batch: int,
              preprocess: Callable[[np.ndarray], np.ndarray] | None = None):
    """Global test minibatches spanning all partitions (the zipPartitions
    test pass, reference: ImageNetApp.scala:108-137)."""
    n_parts = dataset.num_partitions
    per_part = [make_minibatches(
        np.stack([x for x, _ in p]),
        np.asarray([y for _, y in p], np.float32), per_worker_batch)
        for p in dataset.partitions]
    steps = min(len(b) for b in per_part)
    if steps == 0:
        sizes = dataset.partition_sizes()
        raise ValueError(
            f"eval would run 0 steps: smallest test partition has "
            f"{min(sizes)} items < per-worker batch {per_worker_batch}")

    def factory():
        for t in range(steps):
            imgs, labs = [], []
            for w in range(n_parts):
                x, y = per_part[w][t]
                if preprocess is not None:
                    x = preprocess(x)
                imgs.append(x)
                labs.append(y)
            yield {"data": np.concatenate(imgs), "label": np.concatenate(labs)}

    return factory, steps


def run_training(trainer: DistributedTrainer, feed: RoundFeed,
                 test_factory, test_steps: int, *, rounds: int,
                 test_interval: int = 10,
                 logger: PhaseLogger | None = None,
                 snapshot_path: str | None = None) -> dict[str, float]:
    """The outer while-loop (reference: CifarApp.scala:87-128 — infinite
    there; bounded by ``rounds`` here).  SIGINT stops cleanly (snapshotting
    first when a path is given), SIGHUP snapshots and continues — the
    SignalHandler→Solver::Step contract (reference:
    caffe/src/caffe/util/signal_handler.cpp, solver.cpp:270-281).
    Returns the last eval scores."""
    from ..utils.signals import SignalGuard, SolverAction

    log = logger or PhaseLogger()
    last_scores: dict[str, float] = {}

    def maybe_snapshot(reason: str) -> None:
        if snapshot_path:
            trainer.snapshot(snapshot_path)
            log.log(f"snapshot ({reason}) -> {snapshot_path}")

    with SignalGuard() as guard:
        for r in range(rounds):
            action = guard.check()
            if action == SolverAction.SNAPSHOT:
                maybe_snapshot("SIGHUP")
            elif action == SolverAction.STOP:
                log.log("stop requested (SIGINT); halting at round boundary")
                maybe_snapshot("stop")
                return last_scores
            if test_interval and r % test_interval == 0 and r > 0:
                log.log("testing")
                totals = trainer.test(test_factory(), test_steps)
                last_scores = {k: v / test_steps for k, v in totals.items()}
                log.log(f"round {r}: eval {last_scores}")
            t0 = time.perf_counter()
            batches = feed.next_round()
            loss = trainer.train_round(batches)
            log.log(f"round {r}: tau={feed.tau} loss={loss:.4f} "
                    f"({time.perf_counter() - t0:.2f}s)")
    totals = trainer.test(test_factory(), test_steps)
    last_scores = {k: v / test_steps for k, v in totals.items()}
    log.log(f"final eval: {last_scores}")
    return last_scores
