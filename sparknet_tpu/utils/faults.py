"""Deterministic fault injection — chaos testing for the resilience layer.

SparkNet's recovery story was only ever exercised by luck (a preempted
EC2 spot node during a paper run); here every failure mode is a
first-class, deterministic test input.  Faults are described by the
``SPARKNET_FAULT`` env var and fire at well-defined hook points:

    SPARKNET_FAULT=<spec>[,<spec>...]
    spec     := kind[:arg][@round:<N>][@rank:<R>][@attempt:<A>]
    kind     := crash        — os._exit(43) at the start of round N
              | hang         — block forever at the start of round N
              | slow_feed    — arg = per-batch delay ("200ms", "0.5s", "2")
              | corrupt_ckpt — scribble over the checkpoint written at
                               round N, after its manifest exists

Scoping:
  @round:N   — fire at round N (required for crash/hang; for corrupt_ckpt
               it names the checkpointed round; slow_feed ignores it)
  @rank:R    — only on process R (default: every rank)
  @attempt:A — only on job attempt A.  The ResilientRunner stamps every
               (re)launch with SPARKNET_FAULT_ATTEMPT; crash / hang /
               corrupt_ckpt default to attempt 0 ONLY, so an injected
               fault fires once and the automatic restart then runs
               clean — the deterministic replacement for "the spot
               instance came back".  slow_feed defaults to every attempt
               (it models degradation, not death).

Hook points: ``FaultInjector.on_round`` in training drivers,
``feed_delay`` in ``data.prefetch.PrefetchIterator``, and
``corrupt_checkpoint`` in the trainer's round-checkpoint writer.
"""

from __future__ import annotations

import dataclasses
import os
import sys
import time
from typing import Callable, Mapping

KINDS = ("crash", "hang", "slow_feed", "corrupt_ckpt")


@dataclasses.dataclass(frozen=True)
class FaultSpec:
    kind: str
    round: int | None = None
    rank: int | None = None
    attempt: int | None = None     # None => kind-specific default (see doc)
    delay_s: float = 0.0           # slow_feed only


def _parse_duration(text: str) -> float:
    t = text.strip()
    try:
        if t.endswith("ms"):
            return float(t[:-2]) / 1000.0
        if t.endswith("s"):
            return float(t[:-1])
        return float(t)
    except ValueError:
        raise ValueError(f"bad duration {text!r} (want e.g. '200ms', "
                         f"'1.5s', or plain seconds)") from None


def parse_faults(text: str) -> tuple[FaultSpec, ...]:
    """Parse a SPARKNET_FAULT value; raises ValueError with the offending
    spec named (config errors must be loud, not silently inert)."""
    specs = []
    for raw in text.split(","):
        raw = raw.strip()
        if not raw:
            continue
        head, *mods = raw.split("@")
        kind, _, arg = head.partition(":")
        kind = kind.strip()
        if kind not in KINDS:
            raise ValueError(f"unknown fault kind {kind!r} in {raw!r} "
                             f"(known: {', '.join(KINDS)})")
        delay = 0.0
        if kind == "slow_feed":
            if not arg:
                raise ValueError(f"slow_feed needs a duration arg in {raw!r}")
            delay = _parse_duration(arg)
        elif arg:
            raise ValueError(f"{kind} takes no ':' arg (got {raw!r})")
        fields: dict[str, int] = {}
        for mod in mods:
            key, _, val = mod.partition(":")
            key = key.strip()
            if key not in ("round", "rank", "attempt") or not val:
                raise ValueError(f"bad modifier {mod!r} in {raw!r} "
                                 f"(want @round:N / @rank:R / @attempt:A)")
            try:
                fields[key] = int(val)
            except ValueError:
                raise ValueError(
                    f"modifier {mod!r} in {raw!r}: not an integer") from None
        if kind in ("crash", "hang") and "round" not in fields:
            raise ValueError(f"{kind} needs @round:N ({raw!r})")
        specs.append(FaultSpec(kind=kind, round=fields.get("round"),
                               rank=fields.get("rank"),
                               attempt=fields.get("attempt"),
                               delay_s=delay))
    return tuple(specs)


class FaultInjector:
    """Evaluates parsed fault specs at the hook points.  ``_exit`` and
    ``_sleep`` are injectable for unit tests; production uses the real
    ones (crash must be un-catchable, like a SIGKILLed worker)."""

    def __init__(self, specs: tuple[FaultSpec, ...], *, attempt: int = 0,
                 rank: int = 0,
                 _exit: Callable[[int], None] = os._exit,
                 _sleep: Callable[[float], None] = time.sleep):
        self.specs = specs
        self.attempt = attempt
        self.rank = rank
        self._exit = _exit
        self._sleep = _sleep

    @classmethod
    def from_env(cls, env: Mapping[str, str] | None = None,
                 **kwargs) -> "FaultInjector":
        env = os.environ if env is None else env
        text = env.get("SPARKNET_FAULT", "")
        return cls(parse_faults(text) if text else (),
                   attempt=int(env.get("SPARKNET_FAULT_ATTEMPT", "0") or 0),
                   rank=int(env.get("SPARKNET_PROC_ID", "0") or 0),
                   **kwargs)

    def _active(self, spec: FaultSpec, rank: int | None) -> bool:
        r = self.rank if rank is None else rank
        if spec.rank is not None and spec.rank != r:
            return False
        want = spec.attempt
        if want is None:
            # one-shot faults fire on the first attempt only; slow_feed
            # degrades every attempt
            want = None if spec.kind == "slow_feed" else 0
        return want is None or want == self.attempt

    def on_round(self, round_idx: int, rank: int | None = None) -> None:
        """Call at the start of every training round."""
        for spec in self.specs:
            if spec.kind not in ("crash", "hang") or spec.round != round_idx:
                continue
            if not self._active(spec, rank):
                continue
            who = self.rank if rank is None else rank
            print(f"FAULT: {spec.kind} at round {round_idx} on rank {who} "
                  f"(attempt {self.attempt})", file=sys.stderr, flush=True)
            if spec.kind == "crash":
                self._exit(43)
                return  # only reached with a test-injected _exit
            while True:  # hang: a stuck worker, killable only from outside
                self._sleep(3600)

    def feed_delay(self, rank: int | None = None) -> float:
        """Seconds each prefetched batch should be delayed by."""
        return sum(s.delay_s for s in self.specs
                   if s.kind == "slow_feed" and self._active(s, rank))

    def corrupt_checkpoint(self, round_idx: int,
                           rank: int | None = None) -> bool:
        """True when the checkpoint just written for ``round_idx`` should
        be scribbled over (exercises manifest-fallback on resume)."""
        return any(
            s.kind == "corrupt_ckpt"
            and (s.round is None or s.round == round_idx)
            and self._active(s, rank)
            for s in self.specs)


_CACHE: tuple[tuple[str, ...], FaultInjector] | None = None


def get_injector() -> FaultInjector:
    """Process-wide injector, re-parsed whenever the driving env vars
    change (so tests can monkeypatch the env between uses)."""
    global _CACHE
    key = tuple(os.environ.get(k, "") for k in
                ("SPARKNET_FAULT", "SPARKNET_FAULT_ATTEMPT",
                 "SPARKNET_PROC_ID"))
    if _CACHE is None or _CACHE[0] != key:
        _CACHE = (key, FaultInjector.from_env())
    return _CACHE[1]


def scribble(path: str) -> None:
    """Corrupt a file in place: truncate to half and overwrite the tail —
    breaks both the zip directory of an .npz and any content checksum."""
    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        f.truncate(max(size // 2, 1))
        f.seek(max(size // 2 - 64, 0))
        f.write(b"\xde\xad\xbe\xef" * 4)
