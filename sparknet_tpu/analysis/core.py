"""sparklint core: findings, source files, suppressions, baseline.

Everything here is deliberately stdlib-only and JAX-free — the linter
must run on a machine with no accelerator and no heavy imports, in
about a second, so it can sit in tier-1 CI unconditionally.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import re
from pathlib import Path

# Trailing same-line or next-line suppression:
#   x = 1  # sparklint: disable=TP001,CD003
#   # sparklint: disable-next-line=KR002
_SUPPRESS_RE = re.compile(
    r"#\s*sparklint:\s*disable(?P<next>-next-line)?\s*=\s*"
    r"(?P<rules>all|[A-Z]{2}\d{3}(?:\s*,\s*[A-Z]{2}\d{3})*)")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation at one site."""

    rule: str          # e.g. "TP001"
    severity: str      # "error" | "warning"
    path: str          # repo-relative, forward slashes
    line: int
    symbol: str        # enclosing def/class qualname, or "<module>"
    message: str
    fix: str = ""      # one-line fix hint

    def key(self) -> tuple[str, str, str]:
        """Baseline identity: line-number-free so entries survive
        unrelated edits above the finding."""
        return (self.rule, self.path, self.symbol)

    def render(self) -> str:
        out = (f"{self.path}:{self.line}: {self.rule} {self.severity} "
               f"[{self.symbol}] {self.message}")
        if self.fix:
            out += f"\n    fix: {self.fix}"
        return out


class SourceFile:
    """A parsed file plus the lookups every rule needs: suppression
    lines, and line -> enclosing-scope qualname."""

    def __init__(self, root: Path, rel: str, text: str) -> None:
        self.rel = rel
        self.path = root / rel
        self.text = text
        self.tree = ast.parse(text, filename=rel)
        self.module = self._module_name(rel)
        self.package = self.module.rpartition(".")[0]
        self._suppress = self._parse_suppressions(text)
        self._scopes = self._index_scopes(self.tree)

    @staticmethod
    def _module_name(rel: str) -> str:
        parts = rel[:-3].split("/") if rel.endswith(".py") else rel.split("/")
        if parts[-1] == "__init__":
            parts = parts[:-1]
        return ".".join(parts)

    @staticmethod
    def _parse_suppressions(text: str) -> dict[int, set[str]]:
        out: dict[int, set[str]] = {}
        for i, line in enumerate(text.splitlines(), start=1):
            m = _SUPPRESS_RE.search(line)
            if not m:
                continue
            rules = {r.strip() for r in m.group("rules").split(",")}
            target = i + 1 if m.group("next") else i
            out.setdefault(target, set()).update(rules)
        return out

    @staticmethod
    def _index_scopes(tree: ast.AST) -> list[tuple[int, int, str]]:
        """(start, end, qualname) for every def/class, innermost
        resolvable by smallest span."""
        scopes: list[tuple[int, int, str]] = []

        def visit(node: ast.AST, prefix: str) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                      ast.ClassDef)):
                    qual = f"{prefix}.{child.name}" if prefix else child.name
                    scopes.append((child.lineno,
                                   child.end_lineno or child.lineno, qual))
                    visit(child, qual)
                else:
                    visit(child, prefix)

        visit(tree, "")
        return scopes

    def symbol_at(self, line: int) -> str:
        best = None
        for start, end, qual in self._scopes:
            if start <= line <= end:
                if best is None or (end - start) < (best[1] - best[0]):
                    best = (start, end, qual)
        return best[2] if best else "<module>"

    def suppressed(self, line: int, rule: str) -> bool:
        rules = self._suppress.get(line, ())
        return "all" in rules or rule in rules


class Project:
    """The scanned file set plus cross-file lookups."""

    def __init__(self, root: Path, files: list[SourceFile]) -> None:
        self.root = root
        self.files = files
        self.by_module = {f.module: f for f in files}
        self.by_rel = {f.rel: f for f in files}

    def finding(self, sf: SourceFile, rule: str, severity: str, line: int,
                message: str, fix: str = "") -> Finding | None:
        """Build a finding unless a suppression comment covers it."""
        if sf.suppressed(line, rule):
            return None
        return Finding(rule, severity, sf.rel, line, sf.symbol_at(line),
                       message, fix)


class Baseline:
    """Committed grandfather list for findings that are correct by
    design (trace-time knobs, deliberate broad excepts).  Entries are
    keyed (rule, path, symbol) — no line numbers — and each carries a
    mandatory one-line reason; `tools/lint.py baseline` regenerates the
    file, preserving reasons for surviving keys."""

    VERSION = 1

    def __init__(self, entries: list[dict[str, str]]) -> None:
        for e in entries:
            missing = {"rule", "path", "symbol", "reason"} - set(e)
            if missing:
                raise ValueError(f"baseline entry {e} missing {missing}")
            if not e["reason"].strip():
                raise ValueError(
                    f"baseline entry for {e['rule']} at {e['path']} "
                    f"[{e['symbol']}] needs a non-empty reason")
        self.entries = entries
        self._keys = {(e["rule"], e["path"], e["symbol"]) for e in entries}
        self._hit: set[tuple[str, str, str]] = set()

    @classmethod
    def load(cls, path: Path) -> "Baseline":
        doc = json.loads(path.read_text())
        if doc.get("kind") != "sparklint_baseline" or \
                doc.get("version") != cls.VERSION:
            raise ValueError(f"{path}: not a v{cls.VERSION} sparklint "
                             f"baseline file")
        return cls(doc["entries"])

    @classmethod
    def empty(cls) -> "Baseline":
        return cls([])

    def covers(self, finding: Finding) -> bool:
        if finding.key() in self._keys:
            self._hit.add(finding.key())
            return True
        return False

    def unused(self) -> list[dict[str, str]]:
        return [e for e in self.entries
                if (e["rule"], e["path"], e["symbol"]) not in self._hit]

    @staticmethod
    def render(entries: list[dict[str, str]]) -> str:
        doc = {"kind": "sparklint_baseline", "version": Baseline.VERSION,
               "entries": sorted(entries, key=lambda e: (
                   e["rule"], e["path"], e["symbol"]))}
        return json.dumps(doc, indent=1) + "\n"


def dotted(node: ast.AST) -> str:
    """'jax.experimental.pallas_call' for a Name/Attribute chain, ''
    when the expression is anything else."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""
