"""Resilient-runtime coverage: fault-spec grammar, injector semantics,
restart policy/backoff, launcher supervision, bounded control-plane
retries, checkpoint integrity (checksums, CheckpointError), round-granular
trainer checkpoint/resume, and the end-to-end chaos paths — a rank killed
mid-job recovers through ResilientRunner and matches the fault-free run
(the recovery half of the reference's spark.task.maxFailures contract,
CifarApp.scala:36; snapshots-as-recovery per Caffe's Solver::Snapshot).
"""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from sparknet_tpu.parallel.resilience import (
    Attempt, ResilientRunner, RestartPolicy,
)
from sparknet_tpu.utils import faults
from sparknet_tpu.utils.checkpoint import (
    CheckpointError, load_checkpoint, save_checkpoint,
)
from sparknet_tpu.utils.retry import backoff_delays, retry_call

DRIVER = os.path.join(os.path.dirname(__file__), "multihost_driver.py")
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# fault grammar + injector
# ---------------------------------------------------------------------------

def test_parse_faults_grammar():
    specs = faults.parse_faults(
        "crash@round:3@rank:1, slow_feed:200ms, corrupt_ckpt@round:2,"
        "hang@round:5@attempt:2")
    assert specs[0] == faults.FaultSpec("crash", round=3, rank=1)
    assert specs[1].kind == "slow_feed"
    assert specs[1].delay_s == pytest.approx(0.2)
    assert specs[2] == faults.FaultSpec("corrupt_ckpt", round=2)
    assert specs[3] == faults.FaultSpec("hang", round=5, attempt=2)


def test_parse_faults_elastic_kinds():
    specs = faults.parse_faults(
        "perma_crash@rank:3, straggle:1.5s@round:2, nan_inject@round:4,"
        "crash_in_ckpt@round:1")
    assert specs[0] == faults.FaultSpec("perma_crash", rank=3)
    assert specs[1].kind == "straggle" and specs[1].delay_s == 1.5
    assert specs[1].round == 2
    assert specs[2] == faults.FaultSpec("nan_inject", round=4)
    assert specs[3] == faults.FaultSpec("crash_in_ckpt", round=1)


@pytest.mark.parametrize("bad, msg", [
    ("explode@round:1", "unknown fault kind"),
    ("crash", "needs @round"),
    ("crash@round:x", "not an integer"),
    ("crash@rnd:1", "bad modifier"),
    ("slow_feed", "needs a duration"),
    ("slow_feed:fast", "bad duration"),
    ("crash:3@round:1", "takes no ':' arg"),
    ("straggle@round:1", "needs a duration"),
    ("nan_inject", "needs @round"),
    ("crash_in_ckpt", "needs @round"),
    ("perma_crash", "needs @rank"),
])
def test_parse_faults_rejects(bad, msg):
    with pytest.raises(ValueError, match=msg):
        faults.parse_faults(bad)


def test_perma_crash_fires_on_every_attempt_matching_rank_only():
    inj, calls = _injector("perma_crash@rank:2", attempt=5, rank=2)
    with pytest.raises(_Exit):
        inj.on_round(0, rank=2)            # any round, any attempt
    assert calls["exit"] == [43]
    inj2, calls2 = _injector("perma_crash@rank:2", attempt=5, rank=1)
    inj2.on_round(0, rank=1)               # survivor ranks untouched
    assert calls2["exit"] == []


def test_straggle_sleeps_then_continues():
    inj, calls = _injector("straggle:2.5s@round:1")
    inj.on_round(0)                        # wrong round: no-op
    assert calls["sleep"] == []
    with pytest.raises(_Exit):             # test sleep raises to observe
        inj.on_round(1)
    assert calls["sleep"] == [2.5]
    # one-shot default: the relaunched attempt runs clean
    inj2, calls2 = _injector("straggle:2.5s@round:1", attempt=1)
    inj2.on_round(1)
    assert calls2["sleep"] == []


def test_nan_inject_fires_once_per_process():
    inj, _ = _injector("nan_inject@round:2")
    assert not inj.nan_inject(1)
    assert inj.nan_inject(2)
    assert not inj.nan_inject(2)           # rollback replay runs clean
    inj2, _ = _injector("nan_inject@round:2@rank:1", rank=0)
    assert not inj2.nan_inject(2)          # other ranks unpoisoned


def test_crash_in_ckpt_hook():
    inj, calls = _injector("crash_in_ckpt@round:3")
    inj.on_checkpoint_write(2)             # wrong round: no-op
    assert calls["exit"] == []
    with pytest.raises(_Exit):
        inj.on_checkpoint_write(3)
    assert calls["exit"] == [43]
    inj1, calls1 = _injector("crash_in_ckpt@round:3", attempt=1)
    inj1.on_checkpoint_write(3)            # restarted job writes clean
    assert calls1["exit"] == []


def test_duration_units():
    assert faults.parse_faults("slow_feed:1.5s")[0].delay_s == 1.5
    assert faults.parse_faults("slow_feed:2")[0].delay_s == 2.0


class _Exit(Exception):
    pass


def _injector(spec, attempt=0, rank=0):
    calls = {"exit": [], "sleep": []}

    def fake_exit(code):
        calls["exit"].append(code)
        raise _Exit()  # real os._exit never returns; simulate that

    def fake_sleep(s):
        calls["sleep"].append(s)
        raise _Exit()  # break the hang loop

    inj = faults.FaultInjector(faults.parse_faults(spec), attempt=attempt,
                               rank=rank, _exit=fake_exit, _sleep=fake_sleep)
    return inj, calls


def test_crash_fires_on_matching_round_and_rank_only():
    inj, calls = _injector("crash@round:3@rank:1", rank=1)
    inj.on_round(2, rank=1)            # wrong round: no-op
    inj.on_round(3, rank=0)            # wrong rank: no-op
    assert calls["exit"] == []
    with pytest.raises(_Exit):
        inj.on_round(3, rank=1)
    assert calls["exit"] == [43]


def test_one_shot_faults_default_to_first_attempt_only():
    inj, calls = _injector("crash@round:1", attempt=1)
    inj.on_round(1)                    # restarted job: fault suppressed
    assert calls["exit"] == []
    inj0, calls0 = _injector("crash@round:1", attempt=0)
    with pytest.raises(_Exit):
        inj0.on_round(1)


def test_attempt_scoped_fault():
    inj, calls = _injector("hang@round:2@attempt:1", attempt=1)
    with pytest.raises(_Exit):
        inj.on_round(2)
    assert calls["sleep"]              # entered the hang loop


def test_slow_feed_applies_on_every_attempt():
    inj, _ = _injector("slow_feed:50ms", attempt=3)
    assert inj.feed_delay() == pytest.approx(0.05)
    assert inj.feed_delay(rank=7) == pytest.approx(0.05)
    inj2, _ = _injector("slow_feed:50ms@rank:1", attempt=0)
    assert inj2.feed_delay(rank=0) == 0.0


def test_corrupt_ckpt_matching():
    inj, _ = _injector("corrupt_ckpt@round:2")
    assert inj.corrupt_checkpoint(2)
    assert not inj.corrupt_checkpoint(3)
    inj1, _ = _injector("corrupt_ckpt@round:2", attempt=1)
    assert not inj1.corrupt_checkpoint(2)   # one-shot: attempt 0 only


def test_get_injector_tracks_env(monkeypatch):
    monkeypatch.setenv("SPARKNET_FAULT", "slow_feed:10ms")
    monkeypatch.setenv("SPARKNET_FAULT_ATTEMPT", "0")
    assert faults.get_injector().feed_delay() == pytest.approx(0.01)
    monkeypatch.setenv("SPARKNET_FAULT", "")
    assert faults.get_injector().feed_delay() == 0.0


# ---------------------------------------------------------------------------
# restart policy + ResilientRunner (fake launcher)
# ---------------------------------------------------------------------------

def test_restart_policy_backoff_sequence_and_cap():
    p = RestartPolicy(max_restarts=5, backoff_base=1.0, backoff_factor=3.0,
                      backoff_max=10.0, jitter=0.0)
    assert [p.delay(i) for i in range(4)] == [1.0, 3.0, 9.0, 10.0]


def test_restart_policy_jitter_spreads_but_bounds_delays():
    """Jitter (on by default) must keep every delay inside
    [d·(1-j), d·(1+j)] and actually decorrelate two runners — the
    anti-thundering-herd contract."""
    import random
    p = RestartPolicy(backoff_base=4.0, jitter=0.25)
    a = [p.delay(0, random.Random(1)) for _ in range(50)]
    b = [p.delay(0, random.Random(2)) for _ in range(50)]
    assert all(3.0 <= d <= 5.0 for d in a + b)
    assert a[0] != b[0]                      # different rank seeds differ
    deterministic = RestartPolicy(backoff_base=4.0, jitter=0.0)
    assert deterministic.delay(0) == 4.0


def test_runner_requires_exactly_one_mode():
    with pytest.raises(ValueError, match="exactly one"):
        ResilientRunner(["true"])
    with pytest.raises(ValueError, match="exactly one"):
        ResilientRunner(["true"], nprocs=2, hosts=["a"])


def _fake_runner(monkeypatch, rcs):
    """ResilientRunner whose launch returns scripted rcs and records the
    per-attempt env stamps and sleeps."""
    import sparknet_tpu.parallel.resilience as R
    seen = {"envs": [], "sleeps": []}
    it = iter(rcs)

    def fake_local(cmd, nprocs, **kw):
        seen["envs"].append(dict(kw["extra_env"]))
        return next(it)

    monkeypatch.setattr(R, "launch_local", fake_local)
    runner = ResilientRunner(
        ["job"], nprocs=2,
        policy=RestartPolicy(max_restarts=3, backoff_base=0.5, jitter=0.0),
        sleep=lambda s: seen["sleeps"].append(s))
    return runner, seen


def test_runner_success_first_try_no_restart(monkeypatch):
    runner, seen = _fake_runner(monkeypatch, [0])
    assert runner.run() == 0
    assert seen["sleeps"] == []
    assert [a.returncode for a in runner.attempts] == [0]
    assert seen["envs"][0]["SPARKNET_FAULT_ATTEMPT"] == "0"


def test_runner_restarts_with_backoff_and_attempt_stamp(monkeypatch):
    runner, seen = _fake_runner(monkeypatch, [43, 1, 0])
    assert runner.run() == 0
    assert seen["sleeps"] == [0.5, 1.0]          # exponential backoff
    assert [e["SPARKNET_FAULT_ATTEMPT"] for e in seen["envs"]] == \
        ["0", "1", "2"]
    assert [a.returncode for a in runner.attempts] == [43, 1, 0]
    assert isinstance(runner.attempts[0], Attempt)


def test_runner_bounded_budget_gives_up(monkeypatch):
    runner, seen = _fake_runner(monkeypatch, [7, 7, 7, 7])
    assert runner.run() == 7
    assert len(runner.attempts) == 4             # max_restarts=3 → 4 tries
    assert seen["sleeps"] == [0.5, 1.0, 2.0]     # no sleep after final try


# ---------------------------------------------------------------------------
# bounded retry helper + control-plane edges
# ---------------------------------------------------------------------------

def test_backoff_delays_shape():
    assert list(backoff_delays(4, 0.1, 2.0, 0.3)) == \
        pytest.approx([0.1, 0.2, 0.3])
    assert list(backoff_delays(1, 0.1)) == []


def test_retry_call_recovers_then_gives_up():
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise OSError("transient")
        return "ok"

    sleeps = []
    assert retry_call(flaky, attempts=3, base_delay=0.01,
                      sleep=sleeps.append) == "ok"
    assert sleeps == pytest.approx([0.01, 0.02])

    calls["n"] = -10  # always failing now
    with pytest.raises(OSError, match="transient"):
        retry_call(flaky, attempts=2, base_delay=0.01, sleep=sleeps.append)


def test_retry_call_non_matching_exception_propagates_immediately():
    def boom():
        raise KeyError("nope")

    sleeps = []
    with pytest.raises(KeyError):
        retry_call(boom, attempts=5, sleep=sleeps.append)
    assert sleeps == []


def test_io_retry_env_knobs(monkeypatch, tmp_path):
    monkeypatch.setenv("SPARKNET_IO_RETRIES", "4")
    monkeypatch.setenv("SPARKNET_IO_BACKOFF", "0")
    from sparknet_tpu.utils.retry import io_retry
    calls = {"n": 0}

    def flaky_open():
        calls["n"] += 1
        raise OSError("gone")

    with pytest.raises(OSError):
        io_retry(flaky_open)
    assert calls["n"] == 4


def test_lmdb_reader_retries_transient_open(tmp_path, monkeypatch):
    from sparknet_tpu.data import lmdb_io
    db = tmp_path / "db"
    lmdb_io.write_lmdb(str(db), [(b"k", b"v")])
    monkeypatch.setenv("SPARKNET_IO_RETRIES", "3")
    monkeypatch.setenv("SPARKNET_IO_BACKOFF", "0")
    real_open, state = open, {"n": 0}

    def flaky(path, *a, **kw):
        state["n"] += 1
        if state["n"] == 1:
            raise OSError("NFS blip")
        return real_open(path, *a, **kw)

    monkeypatch.setattr("builtins.open", flaky)
    with lmdb_io.LmdbReader(str(db)) as r:
        assert r.first() == (b"k", b"v")
    assert state["n"] >= 2


def test_init_cluster_from_env_validation(monkeypatch):
    from sparknet_tpu.parallel import cluster
    joined = []
    monkeypatch.setattr(cluster, "init_cluster",
                        lambda *a: joined.append(a))
    for var in ("SPARKNET_COORDINATOR", "SPARKNET_NUM_PROCS",
                "SPARKNET_PROC_ID"):
        monkeypatch.delenv(var, raising=False)
    assert cluster.init_cluster_from_env() is False

    monkeypatch.setenv("SPARKNET_COORDINATOR", "127.0.0.1:1234")
    with pytest.raises(ValueError, match="SPARKNET_NUM_PROCS is missing"):
        cluster.init_cluster_from_env()
    monkeypatch.setenv("SPARKNET_NUM_PROCS", "two")
    monkeypatch.setenv("SPARKNET_PROC_ID", "0")
    with pytest.raises(ValueError, match="SPARKNET_NUM_PROCS='two' is not"):
        cluster.init_cluster_from_env()
    monkeypatch.setenv("SPARKNET_NUM_PROCS", "2")
    monkeypatch.setenv("SPARKNET_PROC_ID", "2")
    with pytest.raises(ValueError, match="out of range"):
        cluster.init_cluster_from_env()
    monkeypatch.setenv("SPARKNET_PROC_ID", "1")
    assert cluster.init_cluster_from_env() is True
    assert joined == [("127.0.0.1:1234", 2, 1)]
    # partial contract without coordinator is named, not silently ignored
    monkeypatch.delenv("SPARKNET_COORDINATOR")
    with pytest.raises(ValueError, match="SPARKNET_COORDINATOR is not"):
        cluster.init_cluster_from_env()


# ---------------------------------------------------------------------------
# checkpoint integrity
# ---------------------------------------------------------------------------

def test_checkpoint_roundtrip_with_checksum(tmp_path):
    p = str(tmp_path / "c.npz")
    tree = {"w": np.arange(6.0).reshape(2, 3), "n": [np.int64(3)]}
    save_checkpoint(p, tree)
    out = load_checkpoint(p)
    np.testing.assert_array_equal(out["w"], tree["w"])
    assert int(out["n"][0]) == 3


def test_truncated_checkpoint_raises_checkpoint_error(tmp_path):
    p = str(tmp_path / "trunc.npz")
    save_checkpoint(p, {"w": np.zeros(1000)})
    with open(p, "r+b") as f:
        f.truncate(os.path.getsize(p) // 2)
    with pytest.raises(CheckpointError) as ei:
        load_checkpoint(p)
    assert ei.value.path == p


def test_bitflip_fails_checksum(tmp_path):
    p = str(tmp_path / "rot.npz")
    save_checkpoint(p, {"w": np.zeros(4096, np.float32)})
    faults.scribble(p)
    with pytest.raises(CheckpointError):
        load_checkpoint(p)


def test_missing_checkpoint_raises_checkpoint_error(tmp_path):
    with pytest.raises(CheckpointError):
        load_checkpoint(str(tmp_path / "absent.npz"))


# ---------------------------------------------------------------------------
# launcher supervision
# ---------------------------------------------------------------------------

def test_first_worker_death_tears_down_survivors_fast():
    """One worker exits nonzero while its sibling would sleep for 60s: the
    supervisor must kill the sibling and return well before that (the
    stage-abort, without waiting for the job timeout)."""
    from sparknet_tpu.tools.launch import _wait_all
    quick = subprocess.Popen([sys.executable, "-c", "import sys; sys.exit(5)"])
    slow = subprocess.Popen([sys.executable, "-c",
                             "import time; time.sleep(60)"])
    t0 = time.monotonic()
    rc = _wait_all([quick, slow], timeout=50)
    assert rc == 5
    assert time.monotonic() - t0 < 30
    assert slow.poll() is not None  # sibling was killed


def test_wait_all_timeout_returns_124():
    from sparknet_tpu.tools.launch import _wait_all
    p = subprocess.Popen([sys.executable, "-c", "import time; time.sleep(60)"])
    assert _wait_all([p], timeout=0.5) == 124


def test_launch_local_extra_env_reaches_children(tmp_path):
    from sparknet_tpu.tools.launch import launch_local
    out = tmp_path / "env.txt"
    code = (f"import os; open({str(out)!r}, 'a').write("
            f"os.environ['SPARKNET_FAULT_ATTEMPT'] + '\\n')")
    rc = launch_local([sys.executable, "-c", code], nprocs=2,
                      timeout=60, extra_env={"SPARKNET_FAULT_ATTEMPT": "7"})
    assert rc == 0
    assert out.read_text().splitlines() == ["7", "7"]


# ---------------------------------------------------------------------------
# trainer round-granular checkpoint / resume (in-process, 4 virtual devices)
# ---------------------------------------------------------------------------

def _make_trainer(ckpt_dir, seed=0, every=1, keep=3, *, strategy="local_sgd",
                  batch=16, workers=4, lr=0.05, **cfg_kw):
    from sparknet_tpu.models import lenet
    from sparknet_tpu.parallel import (
        DistributedTrainer, TrainerConfig, make_mesh,
    )
    from sparknet_tpu.proto import load_solver_prototxt_with_net
    sp = load_solver_prototxt_with_net(
        f'base_lr: {lr}\nmomentum: 0.9\nlr_policy: "fixed"\n',
        lenet(batch, batch))
    cfg = TrainerConfig(strategy=strategy, tau=2,
                        checkpoint_dir=str(ckpt_dir) if ckpt_dir else None,
                        checkpoint_every=every, checkpoint_keep=keep,
                        **cfg_kw)
    return DistributedTrainer(sp, make_mesh(workers), cfg, seed=seed)


def _batch(r, batch=16):
    rng = np.random.default_rng(100 + r)
    return {"data": rng.normal(size=(2, batch, 1, 28, 28)).astype(np.float32),
            "label": rng.integers(0, 10, size=(2, batch)).astype(np.float32)}


def test_round_checkpoint_resume_is_exact(tmp_path):
    d = tmp_path / "ck"
    tr = _make_trainer(d)
    for r in range(3):
        tr.data_cursor = {"next_round": r + 1}
        tr.train_round(_batch(r))
    # fresh trainer auto-resumes at round 3 with identical state
    tr2 = _make_trainer(d, seed=99)
    assert tr2.resumed is not None
    assert tr2.round == 3 and tr2.iter == 6
    assert tr2.data_cursor == {"next_round": 3}
    np.testing.assert_allclose(np.asarray(tr2.params["conv1"][0]),
                               np.asarray(tr.params["conv1"][0]))
    # one more round on both: bit-identical continuation (RNG restored too)
    tr.train_round(_batch(3))
    tr2.train_round(_batch(3))
    np.testing.assert_allclose(np.asarray(tr2.params["conv1"][0]),
                               np.asarray(tr.params["conv1"][0]))
    np.testing.assert_allclose(np.asarray(tr2.params["ip2"][0]),
                               np.asarray(tr.params["ip2"][0]))


def test_checkpoint_every_and_pruning(tmp_path):
    d = tmp_path / "ck"
    tr = _make_trainer(d, every=2, keep=2)
    for r in range(8):
        tr.train_round(_batch(r))
    tr.flush_checkpoints()             # settle the async writer
    rounds = sorted(int(f[len("manifest_"):-len(".json")])
                    for f in os.listdir(d) if f.startswith("manifest_"))
    assert rounds == [6, 8]            # every 2 rounds, newest 2 kept
    assert sorted(f for f in os.listdir(d) if f.endswith(".npz")) == \
        ["ckpt_round_00000006.npz", "ckpt_round_00000008.npz"]


@pytest.mark.chaos
def test_corrupt_checkpoint_falls_back_to_previous_manifest(tmp_path):
    d = tmp_path / "ck"
    tr = _make_trainer(d)
    for r in range(3):
        tr.train_round(_batch(r))
    tr.flush_checkpoints()
    # scribble the NEWEST snapshot (round 3) — manifest checksum now lies
    faults.scribble(str(d / "ckpt_round_00000003.npz"))
    tr2 = _make_trainer(d, seed=99)
    assert tr2.resumed is not None
    assert tr2.round == 2              # fell back, did not crash
    assert tr2.resumed["file"] == "ckpt_round_00000002.npz"


@pytest.mark.chaos
def test_corrupt_ckpt_fault_injection_end_to_end(tmp_path, monkeypatch):
    """The writer-side corrupt_ckpt fault produces exactly the
    corrupt-newest layout, and auto-resume survives it."""
    d = tmp_path / "ck"
    monkeypatch.setenv("SPARKNET_FAULT", "corrupt_ckpt@round:3")
    monkeypatch.setenv("SPARKNET_FAULT_ATTEMPT", "0")
    tr = _make_trainer(d)
    for r in range(3):
        tr.train_round(_batch(r))
    monkeypatch.setenv("SPARKNET_FAULT_ATTEMPT", "1")  # the restarted job
    tr2 = _make_trainer(d, seed=99)
    assert tr2.resumed is not None and tr2.round == 2
    # and the restarted job's own round-3 checkpoint is clean this time
    tr2.train_round(_batch(2))
    tr2.flush_checkpoints()
    blob = load_checkpoint(str(d / "ckpt_round_00000003.npz"))
    assert int(blob["round"]) == 3


def test_mesh_shape_mismatch_raises_not_skips(tmp_path):
    from sparknet_tpu.models import lenet
    from sparknet_tpu.parallel import (
        DistributedTrainer, TrainerConfig, make_mesh,
    )
    from sparknet_tpu.proto import load_solver_prototxt_with_net
    d = tmp_path / "ck"
    tr = _make_trainer(d)
    tr.train_round(_batch(0))
    sp = load_solver_prototxt_with_net(
        'base_lr: 0.05\nmomentum: 0.9\nlr_policy: "fixed"\n', lenet(16, 16))
    cfg = TrainerConfig(strategy="local_sgd", tau=2, checkpoint_dir=str(d))
    with pytest.raises(ValueError, match="mesh shape|workers"):
        DistributedTrainer(sp, make_mesh(8), cfg, seed=0)


# ---------------------------------------------------------------------------
# end-to-end chaos: crash → automatic restart → exact recovery
# ---------------------------------------------------------------------------

def _clean_launch_env():
    saved = dict(os.environ)
    os.environ.pop("XLA_FLAGS", None)  # conftest's 8-device flag
    for k in list(os.environ):
        if k.startswith("SPARKNET_"):
            os.environ.pop(k)
    return saved


def _run_crash_restart(tmp_path, *, nprocs, devices_per_proc,
                       local_devices, fault):
    """Shared body: fault-free baseline vs ResilientRunner-supervised run
    with an injected crash; returns (runner, baseline npz, chaos npz,
    ckpt dir)."""
    base = str(tmp_path / "base.npz")
    out = str(tmp_path / "chaos.npz")
    ck = str(tmp_path / "ck")
    extra = ["--rounds", "4"]
    if local_devices:
        extra += ["--local-devices", str(local_devices)]

    saved = _clean_launch_env()
    try:
        from sparknet_tpu.tools.launch import launch_local
        rc = launch_local(
            [sys.executable, DRIVER, "--strategy", "sync", "--out", base]
            + extra,
            nprocs=nprocs, platform="cpu",
            devices_per_proc=devices_per_proc, timeout=300)
        assert rc == 0, f"fault-free run failed rc={rc}"

        runner = ResilientRunner(
            [sys.executable, DRIVER, "--strategy", "sync", "--out", out,
             "--ckpt-dir", ck] + extra,
            nprocs=nprocs, platform="cpu",
            devices_per_proc=devices_per_proc, timeout=300,
            policy=RestartPolicy(max_restarts=2, backoff_base=0.2),
            extra_env={"SPARKNET_FAULT": fault})
        rc = runner.run()
    finally:
        os.environ.clear()
        os.environ.update(saved)

    assert rc == 0, f"job did not recover, rc={rc}"
    # exactly one failed attempt (the injected crash) then a clean recovery
    assert len(runner.attempts) == 2
    assert runner.attempts[0].returncode != 0
    assert runner.attempts[1].returncode == 0
    a, b = np.load(base), np.load(out)
    for k in a.files:
        if k.startswith("__"):
            continue
        np.testing.assert_allclose(a[k], b[k], rtol=1e-5, atol=1e-6,
                                   err_msg=f"param {k} diverged after "
                                           f"crash-restart recovery")
    np.testing.assert_allclose(a["__scores__"], b["__scores__"],
                               rtol=1e-5, atol=1e-5)
    # the crash cost one round, not the run: manifests exist on disk
    assert any(f.startswith("manifest_") for f in os.listdir(ck))
    return runner, base, out, ck


@pytest.mark.chaos
def test_crash_restart_completes_and_matches_fault_free(tmp_path):
    """THE acceptance path: the worker dies at round 3 of 4
    (SPARKNET_FAULT=crash@round:3); ResilientRunner relaunches, the job
    auto-resumes from the newest valid manifest, and the final params
    equal a fault-free run of the same config — recovery is exact at
    round granularity."""
    runner, _, _, ck = _run_crash_restart(
        tmp_path, nprocs=1, devices_per_proc=None, local_devices=4,
        fault="crash@round:3")
    assert runner.attempts[0].returncode == 43  # the injected os._exit


@pytest.mark.chaos
def test_crash_restart_two_process_one_rank(tmp_path, multiprocess_cpu):
    """Same contract with a REAL 2-process mesh and only rank 1 dying:
    the supervisor must tear down the surviving rank and relaunch both.
    Skips on CPU backends without multiprocess computations (those rigs
    skip test_multihost identically)."""
    if not multiprocess_cpu:
        pytest.skip("CPU backend lacks multiprocess XLA computations")
    _run_crash_restart(
        tmp_path, nprocs=2, devices_per_proc=2, local_devices=None,
        fault="crash@round:3@rank:1")


# ---------------------------------------------------------------------------
# preemption (SNAPSHOT_STOP) x in-flight AsyncCheckpointWriter: a preempt
# that lands while a background checkpoint write is still queued must
# FLUSH the write, never tear it (the PR-2 x PR-5 interaction)
# ---------------------------------------------------------------------------

@pytest.mark.chaos
def test_preemption_guard_flushes_inflight_async_writer(tmp_path,
                                                        monkeypatch):
    import signal as _signal

    from sparknet_tpu.utils import checkpoint as ckpt_mod
    from sparknet_tpu.utils.signals import SolverAction, preemption_guard

    # slow the durable write down so the preemption provably arrives
    # while the writer job is still in the queue/in flight
    real_save = ckpt_mod.save_checkpoint

    def slow_save(path, tree):
        time.sleep(0.4)
        real_save(path, tree)

    monkeypatch.setattr(ckpt_mod, "save_checkpoint", slow_save)

    d = tmp_path / "ck"
    tr = _make_trainer(d)          # async checkpointing is the default
    tr.train_round(_batch(0))      # round-1 checkpoint enters the queue
    assert tr._ckpt_writer is not None
    pending_at_signal = tr._ckpt_writer.pending
    assert pending_at_signal >= 1  # the write is genuinely in flight

    with preemption_guard() as guard:
        os.kill(os.getpid(), _signal.SIGTERM)   # the preemption notice
        action = SolverAction.NONE
        for _ in range(200):       # delivery is at a bytecode boundary
            action = guard.check()
            if action != SolverAction.NONE:
                break
            time.sleep(0.01)
        assert action == SolverAction.SNAPSHOT_STOP
        # the driver's preemption sequence (multihost_driver.py): settle
        # in-flight rounds, one final checkpoint, durability barrier
        tr.drain()
        tr.save_round_checkpoint()
        tr.flush_checkpoints()     # must flush the queued write, not tear

    # every manifest on disk validates, and the newest is the final round
    tr2 = _make_trainer(d, seed=99)
    assert tr2.resumed is not None
    assert tr2.round == tr.round == 1
    assert np.array_equal(np.asarray(tr2.params["conv1"][0]),
                          np.asarray(tr.params["conv1"][0]))
    assert np.array_equal(np.asarray(tr2.params["ip2"][0]),
                          np.asarray(tr.params["ip2"][0]))


@pytest.mark.chaos
def test_sigterm_preemption_with_async_writer_driver_e2e(tmp_path):
    """End to end across processes: SIGTERM a live driver mid-run (async
    checkpoint writer active), expect a clean rc-0 exit with a durable
    final snapshot, then resume and finish — params bit-identical to an
    uninterrupted run."""
    import signal as _signal

    saved = _clean_launch_env()
    try:
        base = str(tmp_path / "base.npz")
        r = subprocess.run(
            [sys.executable, DRIVER, "--strategy", "sync", "--out", base,
             "--local-devices", "4", "--rounds", "5"],
            timeout=300, capture_output=True)
        assert r.returncode == 0, r.stdout.decode(errors="replace")

        out = str(tmp_path / "out.npz")
        ck = str(tmp_path / "ck")
        cmd = [sys.executable, DRIVER, "--strategy", "sync", "--out", out,
               "--local-devices", "4", "--rounds", "5", "--ckpt-dir", ck]
        p = subprocess.Popen(cmd, stdout=subprocess.PIPE,
                             stderr=subprocess.STDOUT)
        deadline = time.monotonic() + 240
        tail = []
        for line in iter(p.stdout.readline, b""):
            tail.append(line)
            if b"round 1 done" in line:
                p.send_signal(_signal.SIGTERM)
                break
            assert time.monotonic() < deadline, b"".join(tail).decode()
        rest, _ = p.communicate(timeout=240)
        text = (b"".join(tail) + rest).decode(errors="replace")
        assert p.returncode == 0, text     # preemption is a CLEAN exit
        assert "preempted; stopped cleanly" in text
        assert not os.path.exists(out)     # stopped, not finished
        assert any(f.startswith("manifest_") for f in os.listdir(ck))

        r = subprocess.run(cmd, timeout=300, capture_output=True)
        text2 = r.stdout.decode(errors="replace")
        assert r.returncode == 0, text2
        assert "driver: resumed at round" in text2
    finally:
        os.environ.clear()
        os.environ.update(saved)

    a, b = np.load(base), np.load(out)
    for k in a.files:
        if k.startswith("__"):
            continue
        assert np.array_equal(a[k], b[k]), \
            f"param {k} diverged across preempt/resume"


@pytest.mark.chaos
@pytest.mark.slow
def test_hang_restart_recovers_via_timeout(tmp_path):
    """A HUNG worker (not dead — blocked forever) is only detectable by
    the job timeout: the supervisor must kill it (rc 124) and the restart
    must still recover from the checkpoint."""
    out = str(tmp_path / "hang.npz")
    ck = str(tmp_path / "ck")
    saved = _clean_launch_env()
    try:
        runner = ResilientRunner(
            [sys.executable, DRIVER, "--strategy", "sync", "--out", out,
             "--local-devices", "4", "--rounds", "2", "--ckpt-dir", ck],
            nprocs=1, platform="cpu", timeout=60,
            policy=RestartPolicy(max_restarts=1, backoff_base=0.2),
            extra_env={"SPARKNET_FAULT": "hang@round:1"})
        rc = runner.run()
    finally:
        os.environ.clear()
        os.environ.update(saved)
    assert rc == 0, f"hung job did not recover, rc={rc}"
    assert [a.returncode for a in runner.attempts] == [124, 0]
    assert os.path.exists(out)


# ---------------------------------------------------------------------------
# jittered retry backoff (satellite: anti-thundering-herd)
# ---------------------------------------------------------------------------

def test_backoff_delays_jitter_bounds_and_validation():
    import random
    base = list(backoff_delays(4, 1.0, 2.0, 10.0))
    jittered = list(backoff_delays(4, 1.0, 2.0, 10.0, jitter=0.5,
                                   rng=random.Random(7)))
    assert len(jittered) == len(base) == 3
    for d, j in zip(base, jittered):
        assert d * 0.5 <= j <= d * 1.5
    assert jittered != base                  # jitter actually moved them
    # two processes (different rng seeds) must NOT sleep in lockstep
    a = list(backoff_delays(3, 1.0, jitter=0.3, rng=random.Random(1)))
    b = list(backoff_delays(3, 1.0, jitter=0.3, rng=random.Random(2)))
    assert a != b
    with pytest.raises(ValueError, match="jitter"):
        list(backoff_delays(3, 1.0, jitter=1.5))


def test_retry_call_accepts_jitter():
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 2:
            raise OSError("blip")
        return "ok"

    sleeps = []
    assert retry_call(flaky, attempts=3, base_delay=1.0, jitter=0.5,
                      sleep=sleeps.append) == "ok"
    assert len(sleeps) == 1 and 0.5 <= sleeps[0] <= 1.5


# ---------------------------------------------------------------------------
# resume_latest edge cases (satellite)
# ---------------------------------------------------------------------------

def test_resume_latest_empty_and_missing_dir(tmp_path):
    tr = _make_trainer(tmp_path / "empty")          # dir never written to
    assert tr.resumed is None and tr.round == 0
    assert tr.resume_latest(str(tmp_path / "never_created")) is None


def test_resume_latest_all_manifests_corrupt(tmp_path):
    d = tmp_path / "ck"
    tr = _make_trainer(d)
    for r in range(2):
        tr.train_round(_batch(r))
    tr.flush_checkpoints()
    for f in os.listdir(d):
        if f.startswith("manifest_"):
            (d / f).write_text("{ not json at all")
    tr2 = _make_trainer(d, seed=99)
    assert tr2.resumed is None and tr2.round == 0   # fresh start, no crash


def test_resume_latest_mixed_valid_and_corrupt(tmp_path):
    d = tmp_path / "ck"
    tr = _make_trainer(d)
    for r in range(3):
        tr.train_round(_batch(r))
    tr.flush_checkpoints()
    # newest manifest: unparsable JSON; next: points at a missing file;
    # round 1 stays intact — resume must land exactly there
    (d / "manifest_00000003.json").write_text("!!")
    m2 = json.loads((d / "manifest_00000002.json").read_text())
    m2["file"] = "ckpt_round_99999999.npz"
    (d / "manifest_00000002.json").write_text(json.dumps(m2))
    tr2 = _make_trainer(d, seed=99)
    assert tr2.resumed is not None
    assert tr2.round == 1
    assert tr2.resumed["file"] == "ckpt_round_00000001.npz"


def test_pruning_keeps_exactly_checkpoint_keep_newest(tmp_path):
    d = tmp_path / "ck"
    tr = _make_trainer(d, keep=2)
    for r in range(5):
        tr.train_round(_batch(r))
    tr.flush_checkpoints()
    rounds = sorted(int(f[len("manifest_"):-len(".json")])
                    for f in os.listdir(d) if f.startswith("manifest_"))
    assert rounds == [4, 5]
    npzs = sorted(f for f in os.listdir(d) if f.endswith(".npz"))
    assert npzs == ["ckpt_round_00000004.npz", "ckpt_round_00000005.npz"]


# ---------------------------------------------------------------------------
# crash-safe checkpoint writes (satellite)
# ---------------------------------------------------------------------------

@pytest.mark.chaos
def test_kill_during_npz_write_leaves_no_referenced_garbage(tmp_path,
                                                            monkeypatch):
    """A worker killed INSIDE the npz write (before the atomic rename)
    must leave no final-name npz, no manifest, and a resumable dir.
    Pinned to the SYNCHRONOUS write path (the kill is simulated by an
    exception through the caller's stack); the async-writer variant is
    test_async_ckpt_crash_in_background_write."""
    monkeypatch.setenv("SPARKNET_ASYNC_CKPT", "0")
    d = tmp_path / "ck"
    tr = _make_trainer(d)
    for r in range(2):
        tr.train_round(_batch(r))

    class _Killed(BaseException):
        pass

    real_replace = os.replace

    def killed_replace(src, dst):
        if dst.endswith(".npz"):           # die before the rename lands
            raise _Killed()
        return real_replace(src, dst)

    monkeypatch.setattr(os, "replace", killed_replace)
    with pytest.raises(_Killed):
        tr.train_round(_batch(2))
    monkeypatch.setattr(os, "replace", real_replace)
    names = set(os.listdir(d))
    assert "ckpt_round_00000003.npz" not in names
    assert "manifest_00000003.json" not in names
    assert any(".tmp." in n for n in names)         # the orphan temp
    tr2 = _make_trainer(d, seed=99)
    assert tr2.resumed is not None and tr2.round == 2
    # the next successful checkpoint sweeps the orphan temp away
    tr2.train_round(_batch(2))
    assert not any(".tmp." in n for n in os.listdir(d))


@pytest.mark.chaos
def test_crash_between_npz_and_manifest_is_invisible_to_resume(tmp_path,
                                                               monkeypatch):
    """The crash_in_ckpt fault kills in the torn-write window: npz
    durable, manifest never written.  resume_latest must skip the orphan
    npz (no manifest references it) and land on the previous round.
    Synchronous-path variant (the fake _exit raises through train_round);
    the async window is test_async_ckpt_crash_in_background_write."""
    monkeypatch.setenv("SPARKNET_ASYNC_CKPT", "0")
    d = tmp_path / "ck"
    monkeypatch.setenv("SPARKNET_FAULT", "crash_in_ckpt@round:3")
    monkeypatch.setenv("SPARKNET_FAULT_ATTEMPT", "0")

    class _Killed(BaseException):
        pass

    def fake_exit(code):
        raise _Killed()

    import sparknet_tpu.utils.faults as F
    monkeypatch.setattr(F.get_injector(), "_exit", fake_exit)
    tr = _make_trainer(d)
    tr.train_round(_batch(0))
    tr.train_round(_batch(1))
    with pytest.raises(_Killed):
        tr.train_round(_batch(2))          # dies mid-checkpoint of round 3
    names = set(os.listdir(d))
    assert "ckpt_round_00000003.npz" in names       # npz IS durable...
    assert "manifest_00000003.json" not in names    # ...but unreferenced
    monkeypatch.setenv("SPARKNET_FAULT_ATTEMPT", "1")   # the restart
    tr2 = _make_trainer(d, seed=99)
    assert tr2.resumed is not None and tr2.round == 2
    # the restarted job replays round 2 and overwrites the orphan cleanly
    tr2.train_round(_batch(2))
    blob = load_checkpoint(str(d / "ckpt_round_00000003.npz"))
    assert int(blob["round"]) == 3


# ---------------------------------------------------------------------------
# elastic degraded-mode resume (tentpole: re-form on the survivors)
# ---------------------------------------------------------------------------

def test_elastic_resume_shrink_preserves_consensus_params(tmp_path):
    """4-worker sync job checkpoints; a 3-worker elastic trainer resumes
    it: the averaged params ARE the consensus and must restore exactly."""
    d = tmp_path / "ck"
    a = _make_trainer(d, strategy="sync", batch=24, workers=4, lr=0.005)
    for r in range(2):
        a.train_round(_batch(r, 24))
    b = _make_trainer(d, seed=99, strategy="sync", batch=24, workers=3, lr=0.005,
                      elastic=True)
    assert b.resumed is not None
    assert b.round == 2 and b.iter == a.iter
    np.testing.assert_array_equal(np.asarray(b.params["conv1"][0]),
                                  np.asarray(a.params["conv1"][0]))
    # the degraded world trains on: 24-row batches over 3 workers
    loss = b.train_round(_batch(2, 24))
    assert np.isfinite(loss)


def test_elastic_resume_without_flag_still_raises(tmp_path):
    d = tmp_path / "ck"
    a = _make_trainer(d, strategy="sync", batch=24, workers=4, lr=0.005)
    a.train_round(_batch(0, 24))
    with pytest.raises(ValueError, match="elastic"):
        _make_trainer(d, seed=99, strategy="sync", batch=24, workers=3, lr=0.005)


def test_elastic_retier_local_sgd_state_shrink_and_grow(tmp_path):
    """Per-worker optimizer state re-tiers deterministically: survivor i
    inherits saved row i mod saved_n (shrink drops the dead rows; a
    rejoined worker is seeded from row 0)."""
    d = tmp_path / "ck"
    a = _make_trainer(d, batch=24, workers=4, lr=0.005)      # local_sgd
    for r in range(2):
        a.train_round(_batch(r, 24))

    def rows(tr):
        leaf = jax.tree_util.tree_leaves(tr.state)[0]
        return np.asarray(leaf)

    import jax
    a_rows = rows(a)
    assert a_rows.shape[0] == 4
    b = _make_trainer(d, seed=99, batch=24, workers=3, lr=0.005, elastic=True)
    b_rows = rows(b)
    assert b_rows.shape[0] == 3
    np.testing.assert_array_equal(b_rows, a_rows[:3])
    loss = b.train_round(_batch(2, 24))            # degraded world trains
    assert np.isfinite(loss)
    # grow (rejoin): a 4-worker trainer resumes the 3-worker checkpoint
    c = _make_trainer(d, seed=7, batch=24, workers=4, lr=0.005, elastic=True)
    c_rows = rows(c)
    assert c_rows.shape[0] == 4
    np.testing.assert_array_equal(c_rows[3], c_rows[0])   # seeded from row 0
    loss = c.train_round(_batch(c.round, 24))
    assert np.isfinite(loss)


@pytest.mark.chaos
def test_elastic_reform_matches_native_3worker_run_bit_for_bit(tmp_path):
    """THE elastic acceptance contract: from the re-form point, the
    elastic continuation (4-worker checkpoint resumed on 3 workers) is
    bit-for-bit the 3-worker fault-free run from the same consensus
    state.  The 'native' side resumes a checkpoint REWRITTEN as a
    genuine 3-worker checkpoint (elastic=False), so the two runs share
    state but take entirely different resume paths."""
    import jax
    d4 = tmp_path / "ck4"
    a = _make_trainer(d4, batch=24, workers=4, lr=0.005)     # local_sgd, the
    for r in range(2):                             # re-tier-bearing case
        a.train_round(_batch(r, 24))
    a.flush_checkpoints()

    # elastic side: resume the 4-worker checkpoint on 3 workers
    b = _make_trainer(d4, seed=99, batch=24, workers=3, lr=0.005, elastic=True)
    assert b.resumed is not None and b.round == 2

    # native side: rewrite the same state as a true 3-worker checkpoint
    blob = load_checkpoint(str(d4 / "ckpt_round_00000002.npz"))
    blob["n_workers"] = np.int64(3)
    blob["state"] = jax.tree_util.tree_map(
        lambda x: np.asarray(x)[:3] if np.asarray(x).ndim else x,
        blob["state"])
    d3 = tmp_path / "ck3"
    c = _make_trainer(None, seed=7, batch=24, workers=3, lr=0.005)
    c._apply_blob(blob)
    c.round = 2

    for r in range(2, 4):                          # the shared continuation
        lb = b.train_round(_batch(r, 24))
        lc = c.train_round(_batch(r, 24))
        assert lb == lc
    for name in ("conv1", "ip2"):
        np.testing.assert_array_equal(
            np.asarray(b.params[name][0]), np.asarray(c.params[name][0]),
            err_msg=f"elastic re-form diverged from the native 3-worker "
                    f"run at {name}")


def test_elastic_retile_sharded_matches_native_2worker_run_bit_for_bit(
        tmp_path):
    """The elastic contract survives tensor sharding: a 4-worker
    checkpoint written as per-shard npz tiles (shard="auto" +
    shard_checkpoint=True, so ip1 lives as four 125-row tiles on disk)
    resumes on 2 workers — a DIFFERENT plan with different tile shapes —
    and the continuation is bit-for-bit the 2-worker run started from
    the same consensus state natively.  Blobs carry full logical leaves
    (the per-shard layout is a write-side split), so the re-tile is a
    re-slice, not arithmetic."""
    import jax
    d4 = tmp_path / "ck4"
    a = _make_trainer(d4, batch=24, workers=4, lr=0.005, shard="auto",
                      shard_checkpoint=True)
    assert a.shard_plan is not None and a.shard_plan.n_shards == 4
    for r in range(2):
        a.train_round(_batch(r, 24))
    a.flush_checkpoints()
    tiles = sorted(p.name for p in d4.glob("ckpt_round_00000002.shard*"))
    assert len(tiles) == 4, tiles

    # elastic side: re-tile the 4-shard tiles onto a 2-shard plan
    b = _make_trainer(d4, seed=99, batch=24, workers=2, lr=0.005,
                      shard="auto", shard_checkpoint=True, elastic=True)
    assert b.resumed is not None and b.round == 2
    assert b.shard_plan is not None and b.shard_plan.n_shards == 2

    # native side: the same consensus applied to a fresh sharded
    # 2-worker trainer that never saw the 4-worker checkpoint
    blob = a._host_blob()
    blob["n_workers"] = np.int64(2)
    blob["state"] = jax.tree_util.tree_map(
        lambda x: np.asarray(x)[:2] if np.asarray(x).ndim else x,
        blob["state"])
    c = _make_trainer(None, seed=7, batch=24, workers=2, lr=0.005,
                      shard="auto")
    c._apply_blob(blob)
    c.round = 2

    for r in range(2, 4):
        lb = b.train_round(_batch(r, 24))
        lc = c.train_round(_batch(r, 24))
        assert lb == lc
    for name in ("conv1", "ip1", "ip2"):
        np.testing.assert_array_equal(
            np.asarray(b.params[name][0]), np.asarray(c.params[name][0]),
            err_msg=f"sharded elastic re-tile diverged from the native "
                    f"2-worker run at {name}")


# ---------------------------------------------------------------------------
# numerical-integrity guard (tentpole: never checkpoint poisoned weights)
# ---------------------------------------------------------------------------

def test_guard_requires_checkpoint_dir():
    with pytest.raises(ValueError, match="guard_numerics"):
        _make_trainer(None, guard_numerics=True)


@pytest.mark.chaos
def test_nan_inject_rolls_back_and_matches_fault_free(tmp_path, monkeypatch):
    """Acceptance: nan_inject at round 2 trips the guard, the poisoned
    round is dropped, the checkpoint chain stays NaN/Inf-free, and the
    run converges to the fault-free result EXACTLY (rollback restores
    params+state+RNG, and the replayed round is clean)."""
    clean_dir, chaos_dir = tmp_path / "clean", tmp_path / "chaos"
    clean = _make_trainer(clean_dir, guard_numerics=True)
    clean_losses = [clean.train_round(_batch(r)) for r in range(4)]

    monkeypatch.setenv("SPARKNET_FAULT", "nan_inject@round:2")
    monkeypatch.setenv("SPARKNET_FAULT_ATTEMPT", "0")
    faults.reset_injector()      # re-arm the once-per-process fault
    tr = _make_trainer(chaos_dir, guard_numerics=True)
    losses = []
    while tr.round < 4:
        losses.append(tr.train_round(_batch(tr.round)))
    tr.flush_checkpoints()
    assert tr.guard_trips == 1
    assert sum(1 for l in losses if not np.isfinite(l)) == 1  # the dropped one
    # checkpoint chain: every surviving snapshot is finite
    for f in sorted(os.listdir(chaos_dir)):
        if f.endswith(".npz"):
            blob = load_checkpoint(str(chaos_dir / f))
            import jax
            for leaf in jax.tree_util.tree_leaves(blob["params"]):
                assert np.all(np.isfinite(leaf)), f"NaN survived in {f}"
    # exact recovery: the fault-free trajectory, bit for bit
    np.testing.assert_array_equal(np.asarray(tr.params["conv1"][0]),
                                  np.asarray(clean.params["conv1"][0]))
    finite = [l for l in losses if np.isfinite(l)]
    np.testing.assert_allclose(finite, clean_losses, rtol=1e-6)


def test_guard_loss_spike_detection(tmp_path):
    tr = _make_trainer(tmp_path / "ck", guard_numerics=True,
                       loss_spike_factor=3.0)
    tr._loss_history = [1.0, 1.1, 0.9]
    assert tr._poison_reason(10.0) is not None        # 10 > 3 x ~1.0
    assert tr._poison_reason(2.0) is None
    assert tr._poison_reason(float("inf")) is not None
    assert tr._poison_reason(float("nan")) is not None


def test_guard_lr_backoff_applies_and_checkpoints(tmp_path, monkeypatch):
    monkeypatch.setenv("SPARKNET_FAULT", "nan_inject@round:1")
    monkeypatch.setenv("SPARKNET_FAULT_ATTEMPT", "0")
    faults.reset_injector()      # re-arm the once-per-process fault
    d = tmp_path / "ck"
    tr = _make_trainer(d, guard_numerics=True, guard_lr_backoff=0.5)
    while tr.round < 3:
        tr.train_round(_batch(tr.round))
    assert tr.guard_trips == 1
    assert tr.lr_scale == pytest.approx(0.5)
    # the backed-off scale persists through checkpoint/resume
    monkeypatch.setenv("SPARKNET_FAULT", "")
    tr2 = _make_trainer(d, seed=99, guard_numerics=True)
    assert tr2.lr_scale == pytest.approx(0.5)


def test_guard_max_trips_raises_training_diverged(tmp_path, monkeypatch):
    from sparknet_tpu.parallel import TrainingDivergedError
    monkeypatch.setenv("SPARKNET_FAULT", "nan_inject@round:1")
    monkeypatch.setenv("SPARKNET_FAULT_ATTEMPT", "0")
    faults.reset_injector()      # re-arm the once-per-process fault
    tr = _make_trainer(tmp_path / "ck", guard_numerics=True,
                       guard_max_trips=0)
    tr.train_round(_batch(0))
    with pytest.raises(TrainingDivergedError, match="guard_max_trips"):
        tr.train_round(_batch(1))


@pytest.mark.chaos
def test_nan_inject_driver_end_to_end(tmp_path):
    """The guard through the real driver: a single run (no relaunch —
    rollback is in-process) absorbs the poison and lands on the
    fault-free params bit-for-bit."""
    base, out = str(tmp_path / "base.npz"), str(tmp_path / "chaos.npz")
    saved = _clean_launch_env()
    try:
        from sparknet_tpu.tools.launch import launch_local
        common = [sys.executable, DRIVER, "--strategy", "sync",
                  "--local-devices", "4", "--rounds", "4", "--guard"]
        rc = launch_local(
            common + ["--out", base, "--ckpt-dir", str(tmp_path / "ck_a")],
            nprocs=1, platform="cpu", timeout=300)
        assert rc == 0
        rc = launch_local(
            common + ["--out", out, "--ckpt-dir", str(tmp_path / "ck_b")],
            nprocs=1, platform="cpu", timeout=300,
            extra_env={"SPARKNET_FAULT": "nan_inject@round:2"})
        assert rc == 0
    finally:
        os.environ.clear()
        os.environ.update(saved)
    a, b = np.load(base), np.load(out)
    assert int(b["__guard_trips__"]) == 1 and int(a["__guard_trips__"]) == 0
    for k in a.files:
        if k.startswith("__"):
            continue
        assert np.all(np.isfinite(b[k])), f"NaN reached final params at {k}"
        np.testing.assert_array_equal(
            a[k], b[k], err_msg=f"guard recovery diverged at {k}")


# ---------------------------------------------------------------------------
# zero-stall outer loop: async checkpointing + deferred guard/audit harvest
# ---------------------------------------------------------------------------

def test_harvest_lag_retention_validation(tmp_path):
    """harvest_lag must not outrun checkpoint retention: a poison at
    round r surfaces up to lag (+ audit cadence) rounds later, and the
    pre-poison checkpoint must still exist then."""
    with pytest.raises(ValueError, match="harvest_lag must be >= 0"):
        _make_trainer(tmp_path / "ck", harvest_lag=-1)
    with pytest.raises(ValueError, match="outruns the checkpoint"):
        _make_trainer(tmp_path / "ck", keep=2, guard_numerics=True,
                      harvest_lag=2)
    with pytest.raises(ValueError, match="outruns the checkpoint"):
        # the audit's own cadence adds to the detection latency
        _make_trainer(tmp_path / "ck", keep=3, audit_every=1,
                      harvest_lag=2)
    # enough retention: fine (and lag without guard/audit needs none)
    _make_trainer(tmp_path / "ck", keep=4, audit_every=1,
                  guard_numerics=True, harvest_lag=2)
    _make_trainer(None, harvest_lag=3)


def test_async_pipelined_loop_matches_sync_bit_for_bit(tmp_path):
    """THE zero-stall parity contract: with checkpointing + numerics
    guard + cross-replica audit ALL enabled, the pipelined loop
    (harvest_lag=2, async checkpoint writer) produces the same
    per-round losses and bit-identical params as the fully synchronous
    loop — the tentpole is a latency optimization, not a semantics
    change."""
    kw = dict(lr=0.005, keep=4, guard_numerics=True, audit_every=1)
    sync = _make_trainer(tmp_path / "sync", **kw)
    sync_losses = [sync.train_round(_batch(r)) for r in range(5)]
    sync.drain()
    tr = _make_trainer(tmp_path / "async", harvest_lag=2, **kw)
    first = tr.train_round(_batch(0))
    assert np.isnan(first)          # nothing harvested yet — by design
    while tr.round < 5:
        tr.train_round(_batch(tr.round))
    losses = tr.drain()
    assert [losses[r] for r in range(5)] == sync_losses
    for name in ("conv1", "ip2"):
        np.testing.assert_array_equal(
            np.asarray(tr.params[name][0]),
            np.asarray(sync.params[name][0]),
            err_msg=f"pipelined loop diverged at {name}")
    # both modes wrote the same checkpoint chain (content-identical)
    for d in (tmp_path / "sync", tmp_path / "async"):
        assert "manifest_00000005.json" in os.listdir(d)
    a = load_checkpoint(str(tmp_path / "sync" / "ckpt_round_00000005.npz"))
    b = load_checkpoint(str(tmp_path / "async" / "ckpt_round_00000005.npz"))
    import jax
    for x, y in zip(jax.tree_util.tree_leaves(a["params"]),
                    jax.tree_util.tree_leaves(b["params"])):
        np.testing.assert_array_equal(x, y)
    # the async loop recorded its (near-zero) stalls under the same keys
    assert set(tr.stall_s) == {"loss_fetch", "finite_check",
                               "audit_fetch", "checkpoint"}


def test_async_ckpt_escape_hatch_restores_sync_path(tmp_path, monkeypatch):
    """SPARKNET_ASYNC_CKPT=0 restores today's fully synchronous write:
    durable before train_round returns, no writer thread at all."""
    monkeypatch.setenv("SPARKNET_ASYNC_CKPT", "0")
    d = tmp_path / "ck"
    tr = _make_trainer(d)
    tr.train_round(_batch(0))
    assert tr._ckpt_writer is None
    assert "manifest_00000001.json" in os.listdir(d)
    # flipping the env back re-enables the async tier mid-run
    monkeypatch.delenv("SPARKNET_ASYNC_CKPT")
    tr.train_round(_batch(1))
    assert tr._ckpt_writer is not None
    tr.flush_checkpoints()
    assert "manifest_00000002.json" in os.listdir(d)


@pytest.mark.chaos
def test_async_ckpt_crash_in_background_write(tmp_path, monkeypatch):
    """crash_in_ckpt with the ASYNC writer: the kill lands on the writer
    thread inside the torn window (npz durable, manifest not yet), the
    failure surfaces at the flush barrier — not silently — and resume
    treats the orphan npz as if the checkpoint never happened."""
    d = tmp_path / "ck"
    monkeypatch.setenv("SPARKNET_FAULT", "crash_in_ckpt@round:2")
    monkeypatch.setenv("SPARKNET_FAULT_ATTEMPT", "0")

    class _Killed(BaseException):
        pass

    def fake_exit(code):
        raise _Killed()

    faults.reset_injector()
    monkeypatch.setattr(faults.get_injector(), "_exit", fake_exit)
    tr = _make_trainer(d)
    tr.train_round(_batch(0))
    tr.train_round(_batch(1))      # round-2 job dies on the writer thread
    with pytest.raises(_Killed):
        tr.flush_checkpoints()
    names = set(os.listdir(d))
    assert "ckpt_round_00000002.npz" in names        # npz IS durable...
    assert "manifest_00000002.json" not in names     # ...but unreferenced
    monkeypatch.setenv("SPARKNET_FAULT_ATTEMPT", "1")    # the restart
    tr2 = _make_trainer(d, seed=99)
    assert tr2.resumed is not None and tr2.round == 1


@pytest.mark.chaos
def test_async_guard_trip_at_harvest_lag_bit_for_bit(tmp_path,
                                                     monkeypatch):
    """Acceptance: nan_inject at round 2 under harvest_lag=2 — the
    verdict arrives up to two rounds late, every in-flight round after
    the poison is discarded, newer (poison-descended) checkpoints are
    pruned, and the replay lands bit-for-bit on the fault-free run."""
    kw = dict(lr=0.005, keep=4, guard_numerics=True)
    clean = _make_trainer(tmp_path / "clean", **kw)
    clean_losses = [clean.train_round(_batch(r)) for r in range(5)]
    clean.drain()

    monkeypatch.setenv("SPARKNET_FAULT", "nan_inject@round:2")
    monkeypatch.setenv("SPARKNET_FAULT_ATTEMPT", "0")
    faults.reset_injector()
    tr = _make_trainer(tmp_path / "chaos", harvest_lag=2, **kw)
    while tr.round < 5:
        tr.train_round(_batch(tr.round))
    losses = tr.drain()
    assert tr.guard_trips == 1
    assert [losses[r] for r in range(5)] == clean_losses
    for name in ("conv1", "ip2"):
        np.testing.assert_array_equal(
            np.asarray(tr.params[name][0]),
            np.asarray(clean.params[name][0]),
            err_msg=f"deferred guard recovery diverged at {name}")
    # no checkpoint on disk carries the poison (lag-window snapshots
    # were pruned on the trip, then re-written clean by the replay)
    import jax
    for f in sorted(os.listdir(tmp_path / "chaos")):
        if f.endswith(".npz"):
            blob = load_checkpoint(str(tmp_path / "chaos" / f))
            for leaf in jax.tree_util.tree_leaves(blob["params"]):
                assert np.all(np.isfinite(leaf)), f"NaN survived in {f}"


@pytest.mark.chaos
def test_async_audit_trip_at_harvest_lag_bit_for_bit(tmp_path,
                                                     monkeypatch):
    """bitflip_params at round 3 under harvest_lag=2 and audit_every=1:
    the fingerprint mismatch is harvested late, rolls back to a
    checkpoint at or before the last PASSED audit, and the replay (flip
    is once-per-process) finishes bit-for-bit fault-free."""
    kw = dict(lr=0.005, keep=5, audit_every=1)
    clean = _make_trainer(tmp_path / "clean", **kw)
    while clean.round < 6:
        clean.train_round(_batch(clean.round))
    clean.drain()
    assert clean.audit_trips == 0

    monkeypatch.setenv("SPARKNET_FAULT", "bitflip_params@rank:1@round:3")
    monkeypatch.setenv("SPARKNET_FAULT_ATTEMPT", "0")
    faults.reset_injector()
    tr = _make_trainer(tmp_path / "chaos", harvest_lag=2, **kw)
    while tr.round < 6:
        tr.train_round(_batch(tr.round))
    losses = tr.drain()
    assert tr.audit_trips == 1
    assert [losses[r] for r in range(6)] == \
        [clean.round_losses[r] for r in range(6)]
    for name in ("conv1", "ip2"):
        np.testing.assert_array_equal(
            np.asarray(tr.params[name][0]),
            np.asarray(clean.params[name][0]),
            err_msg=f"deferred audit recovery diverged at {name}")


@pytest.mark.chaos
def test_nan_inject_driver_end_to_end_pipelined(tmp_path):
    """The guard acceptance path re-run under the async loop: the real
    driver with --harvest-lag 2, nan_inject at round 2, absorbs the
    poison through the DEFERRED verdict and still lands on the
    fault-free params bit-for-bit."""
    base, out = str(tmp_path / "base.npz"), str(tmp_path / "chaos.npz")
    saved = _clean_launch_env()
    try:
        from sparknet_tpu.tools.launch import launch_local
        common = [sys.executable, DRIVER, "--strategy", "sync",
                  "--local-devices", "4", "--rounds", "4", "--guard",
                  "--harvest-lag", "2"]
        rc = launch_local(
            common + ["--out", base, "--ckpt-dir", str(tmp_path / "ck_a")],
            nprocs=1, platform="cpu", timeout=300)
        assert rc == 0
        rc = launch_local(
            common + ["--out", out, "--ckpt-dir", str(tmp_path / "ck_b")],
            nprocs=1, platform="cpu", timeout=300,
            extra_env={"SPARKNET_FAULT": "nan_inject@round:2"})
        assert rc == 0
    finally:
        os.environ.clear()
        os.environ.update(saved)
    a, b = np.load(base), np.load(out)
    assert int(b["__guard_trips__"]) == 1 and int(a["__guard_trips__"]) == 0
    for k in a.files:
        if k.startswith("__"):
            continue
        assert np.all(np.isfinite(b[k])), f"NaN reached final params at {k}"
        np.testing.assert_array_equal(
            a[k], b[k], err_msg=f"pipelined guard recovery diverged at {k}")


def test_roundbench_smoke(tmp_path):
    """tools/roundbench.py (the SPARKNET_ROUNDBENCH=1 CI gate) passes
    in-process: the async loop reproduces the sync loop's losses,
    params, and newest checkpoint, and reports the stall accounting."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "roundbench", os.path.join(REPO, "tools", "roundbench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    out = tmp_path / "rb.json"
    assert mod.main(["--rounds", "3", "--out", str(out)]) == 0
    rec = json.loads(out.read_text())
    assert rec["ok"] is True and rec["failures"] == []
    assert rec["stall_total_sync_s"] >= 0


@pytest.mark.chaos
@pytest.mark.slow
def test_ssh_mode_crash_restart_via_shim(tmp_path, multiprocess_cpu):
    """ResilientRunner over launch_ssh (shimmed ssh, as in
    test_multihost.test_ssh_mode_via_shim): a crashed 'host' is restarted
    and the job completes from its checkpoint."""
    if not multiprocess_cpu:
        pytest.skip("CPU backend lacks multiprocess XLA computations")
    shim_dir = tmp_path / "bin"
    shim_dir.mkdir()
    shim = shim_dir / "ssh"
    shim.write_text("#!/bin/bash\nexec bash -c \"$4\"\n")
    shim.chmod(0o755)

    out = str(tmp_path / "ssh_chaos.npz")
    ck = str(tmp_path / "ck")
    saved = _clean_launch_env()
    os.environ["PATH"] = f"{shim_dir}:{os.environ['PATH']}"
    try:
        runner = ResilientRunner(
            [sys.executable, DRIVER, "--strategy", "sync", "--out", out,
             "--local-devices", "2", "--rounds", "3", "--ckpt-dir", ck],
            hosts=["127.0.0.1", "localhost"], cwd=REPO, timeout=300,
            policy=RestartPolicy(max_restarts=2, backoff_base=0.2),
            extra_env={"SPARKNET_FAULT": "crash@round:2@rank:1"})
        rc = runner.run()
    finally:
        os.environ.clear()
        os.environ.update(saved)
    assert rc == 0, f"ssh-mode job did not recover, rc={rc}"
    assert len(runner.attempts) == 2
    assert os.path.exists(out)
