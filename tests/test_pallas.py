"""Pallas kernel tests (interpret mode on the CPU rig): the fused LRN
must match the XLA lowering in forward and VJP, including through the
LRNLayer dispatch."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax

from sparknet_tpu.models.dsl import layer
from sparknet_tpu.ops import get_layer_impl
from sparknet_tpu.ops.pallas_kernels import lrn_across_channels

SIZE, ALPHA, BETA, K = 5, 1e-2, 0.75, 1.0


def _xla_lrn(x, size=SIZE, alpha=ALPHA, beta=BETA, k=K):
    pre = (size - 1) // 2
    post = size - 1 - pre
    ssum = lax.reduce_window(x * x, 0.0, lax.add, (1, size, 1, 1),
                             (1, 1, 1, 1),
                             ((0, 0), (pre, post), (0, 0), (0, 0)))
    return x / (k + (alpha / size) * ssum) ** beta


@pytest.fixture
def x(np_rng):
    return jnp.asarray(np_rng.normal(size=(2, 6, 5, 7)).astype(np.float32))


def test_pallas_lrn_forward(x):
    y = lrn_across_channels(x, SIZE, ALPHA, BETA, K)
    np.testing.assert_allclose(np.asarray(y), np.asarray(_xla_lrn(x)),
                               rtol=1e-5, atol=1e-6)


def test_pallas_lrn_vjp(x):
    g1 = jax.grad(lambda x: jnp.sum(
        jnp.sin(lrn_across_channels(x, SIZE, ALPHA, BETA, K))))(x)
    g2 = jax.grad(lambda x: jnp.sum(jnp.sin(_xla_lrn(x))))(x)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2),
                               rtol=1e-4, atol=1e-5)


def test_pallas_lrn_odd_window(np_rng):
    x = jnp.asarray(np_rng.normal(size=(1, 8, 3, 3)).astype(np.float32))
    y = lrn_across_channels(x, 3, 0.1, 0.5, 2.0)
    np.testing.assert_allclose(
        np.asarray(y), np.asarray(_xla_lrn(x, 3, 0.1, 0.5, 2.0)),
        rtol=1e-5, atol=1e-6)


def test_lrn_layer_pallas_dispatch(x, monkeypatch):
    """SPARKNET_PALLAS_LRN=1 routes LRNLayer through the kernel (interpret
    mode here) and matches the default XLA path."""
    lp = layer("n", "LRN", ["x"], ["y"],
               lrn_param={"local_size": SIZE, "alpha": ALPHA, "beta": BETA})
    impl = get_layer_impl("LRN")
    monkeypatch.setenv("SPARKNET_PALLAS_LRN", "0")
    ref = impl.apply(lp, [], [x], True, None)[0]
    monkeypatch.setenv("SPARKNET_PALLAS_LRN", "1")
    got = impl.apply(lp, [], [x], True, None)[0]
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-5, atol=1e-6)


def test_pallas_lrn_even_window_vjp(np_rng):
    """Even local_size has an asymmetric window — the VJP must use the
    reflected offsets (regression for the window-reflection bug)."""
    x = jnp.asarray(np_rng.normal(size=(1, 8, 3, 3)).astype(np.float32))
    g1 = jax.grad(lambda x: jnp.sum(
        jnp.sin(lrn_across_channels(x, 4, 0.1, 0.5, 2.0))))(x)
    g2 = jax.grad(lambda x: jnp.sum(jnp.sin(_xla_lrn(x, 4, 0.1, 0.5, 2.0))))(x)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2),
                               rtol=1e-4, atol=1e-5)


# ---------------------------------------------------------------------------
# VMEM-resident maxpool backward
# ---------------------------------------------------------------------------

from sparknet_tpu.ops.pallas_kernels import max_pool_vmem_bwd  # noqa: E402
from sparknet_tpu.ops.vision import max_pool, pool_output_size  # noqa: E402

POOL_GEOMS = [
    # (h, w, kh, sh, ph) — GoogLeNet's two pool families + a padded s2
    (14, 14, 3, 1, 1),   # inception branch pool (SAME, stride 1)
    (28, 28, 3, 2, 0),   # pool3-style ceil-mode stride 2
    (13, 13, 3, 2, 1),   # padded + ceil (odd remainder)
    (7, 7, 5, 3, 2),     # kernel > 2*stride, fat overlap
    (17, 17, 2, 3, 1),   # stride > kernel: ceil-clip can leave
                         # (ow-1)*sw+kw < w+pw (padded-width floor)
]


def _np_caffe_maxpool_bwd(x, dy, kh, kw, sh, sw, ph, pw, oh, ow):
    """Literal transcription of pooling_layer.cpp Backward_cpu MAX: the
    forward's row-major argmax scan keeps the FIRST maximum; backward
    adds each dy into its recorded argmax."""
    n, c, h, w = x.shape
    dx = np.zeros_like(x, np.float32)
    for ni in range(n):
        for ci in range(c):
            for oi in range(oh):
                for oj in range(ow):
                    hs, ws = oi * sh - ph, oj * sw - pw
                    he, we = min(hs + kh, h), min(ws + kw, w)
                    hs, ws = max(hs, 0), max(ws, 0)
                    win = x[ni, ci, hs:he, ws:we]
                    k = np.argmax(win)  # first max (row-major), like caffe
                    dx[ni, ci, hs + k // win.shape[1],
                       ws + k % win.shape[1]] += dy[ni, ci, oi, oj]
    return dx


@pytest.mark.parametrize("h,w,kh,sh,ph", POOL_GEOMS)
def test_maxpool_vmem_bwd_matches_select_and_scatter(np_rng, h, w, kh, sh, ph):
    x = jnp.asarray(np_rng.normal(size=(2, 4, h, w)).astype(np.float32))
    oh, ow = pool_output_size(h, w, kh, kh, sh, sh, ph, ph)

    def f_pallas(x):
        return jnp.sum(jnp.sin(
            max_pool_vmem_bwd(x, kh, kh, sh, sh, ph, ph, oh, ow)))

    def f_xla(x):
        return jnp.sum(jnp.sin(
            max_pool(x, kh, kh, sh, sh, ph, ph, oh, ow)))

    np.testing.assert_allclose(np.asarray(f_pallas(x)), np.asarray(f_xla(x)),
                               rtol=1e-6)
    g1 = jax.grad(f_pallas)(x)
    g2 = jax.grad(f_xla)(x)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2),
                               rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("h,w,kh,sh,ph", POOL_GEOMS)
def test_maxpool_vmem_bwd_first_max_ties(np_rng, h, w, kh, sh, ph):
    """Post-ReLU activations tie constantly (zeros); the gradient must go
    to the FIRST max of each window, exactly like caffe's argmax scan."""
    x = np.maximum(np_rng.normal(size=(1, 3, h, w)), 0).astype(np.float32)
    # quantize to force many non-zero ties too
    x = np.round(x * 2) / 2
    oh, ow = pool_output_size(h, w, kh, kh, sh, sh, ph, ph)
    dy = np_rng.normal(size=(1, 3, oh, ow)).astype(np.float32)

    _, vjp = jax.vjp(
        lambda x: max_pool_vmem_bwd(x, kh, kh, sh, sh, ph, ph, oh, ow),
        jnp.asarray(x))
    (dx,) = vjp(jnp.asarray(dy))
    expect = _np_caffe_maxpool_bwd(x, dy, kh, kh, sh, sh, ph, ph, oh, ow)
    np.testing.assert_allclose(np.asarray(dx), expect, rtol=1e-5, atol=1e-6)


def test_maxpool_layer_pallas_dispatch(np_rng, monkeypatch):
    """SPARKNET_PALLAS_MAXPOOL=1 routes MAX pooling's backward through
    the kernel; forward and gradient match the default path."""
    from sparknet_tpu.ops.registry import get_layer_impl as gli
    lp = layer("p", "Pooling", ["x"], ["y"],
               pooling_param={"pool": "MAX", "kernel_size": 3, "stride": 2})
    impl = gli("Pooling")
    x = jnp.asarray(np_rng.normal(size=(2, 4, 13, 13)).astype(np.float32))
    monkeypatch.setenv("SPARKNET_PALLAS_MAXPOOL", "0")
    ref, gref = jax.value_and_grad(
        lambda x: jnp.sum(jnp.sin(impl.apply(lp, [], [x], True, None)[0])))(x)
    monkeypatch.setenv("SPARKNET_PALLAS_MAXPOOL", "1")
    got, ggot = jax.value_and_grad(
        lambda x: jnp.sum(jnp.sin(impl.apply(lp, [], [x], True, None)[0])))(x)
    np.testing.assert_allclose(float(got), float(ref), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(ggot), np.asarray(gref),
                               rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("stride,pad", [(1, 1), (2, 0)])
def test_maxpool_vmem_bwd_bf16(np_rng, stride, pad):
    """bf16 activations through BOTH kernels (stride-1 and strided):
    accumulation stays f32 inside, output comes back bf16."""
    x = jnp.asarray(np_rng.normal(size=(1, 4, 14, 14)), jnp.bfloat16)
    oh, ow = pool_output_size(14, 14, 3, 3, stride, stride, pad, pad)
    _, vjp = jax.vjp(
        lambda x: max_pool_vmem_bwd(x, 3, 3, stride, stride, pad, pad,
                                    oh, ow), x)
    (dx,) = vjp(jnp.ones((1, 4, oh, ow), jnp.bfloat16))
    _, vjp2 = jax.vjp(
        lambda x: max_pool(x.astype(jnp.float32), 3, 3, stride, stride,
                           pad, pad, oh, ow), x)
    (dx2,) = vjp2(jnp.ones((1, 4, oh, ow), jnp.float32))
    assert dx.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(dx, np.float32),
                               np.asarray(dx2, np.float32),
                               rtol=2e-2, atol=1e-2)


def test_pallas_lrn_bf16(np_rng):
    """bf16 I/O with f32 in-kernel math: forward and gradient track the
    f32 reference to bf16 tolerance (the mixed-precision train path)."""
    xf = np_rng.normal(size=(2, 16, 5, 5)).astype(np.float32)
    x16 = jnp.asarray(xf, jnp.bfloat16)
    y = lrn_across_channels(x16, SIZE, ALPHA, BETA, K)
    assert y.dtype == jnp.bfloat16
    yref = _xla_lrn(jnp.asarray(xf))
    np.testing.assert_allclose(np.asarray(y, np.float32), np.asarray(yref),
                               rtol=2e-2, atol=2e-2)
    g = jax.grad(lambda x: jnp.sum(
        lrn_across_channels(x, SIZE, ALPHA, BETA, K).astype(jnp.float32)))(x16)
    gref = jax.grad(lambda x: jnp.sum(_xla_lrn(x)))(jnp.asarray(xf))
    assert g.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(g, np.float32), np.asarray(gref),
                               rtol=5e-2, atol=2e-2)
