"""Chaos soak runner: N short supervised training runs under randomized —
but seeded — fault schedules, each checked for exact recovery, with a
JSON verdict.

The per-fault chaos tests (tests/test_resilience.py, marker ``chaos``)
pin one failure mode each; this runner is the composition check the
ROADMAP's production posture needs: pick a fault *schedule* at random
(crash, torn checkpoint write, NaN poison, replica bit flip, straggle ...
each with a random round/rank), run the standard 4-round driver workload
under ResilientRunner supervision, and assert the finished params are
bit-for-bit the fault-free baseline of the same configuration.  The
randomness is fully derived from ``--seed``, so any red verdict is
replayable with the same command line.

Usage:
  python tools/soak.py --runs 8 --seed 0 --out soak.json
  SPARKNET_SOAK=1 tools/run_tier1.sh     # the 2-run CI smoke

Exit code 0 iff every run recovered exactly; the JSON verdict names each
run's schedule, exit code, attempt count, and whether the params matched.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
DRIVER = os.path.join(REPO, "tests", "multihost_driver.py")


def _schedules(rng):
    """One randomized-but-seeded fault schedule: (name, SPARKNET_FAULT
    value, extra driver flags).  Rounds land in [1, 3) so the 4-round
    workload always has a checkpoint before and rounds after the fault."""
    r = int(rng.integers(1, 3))
    return [
        ("crash", f"crash@round:{r}", []),
        ("crash_in_ckpt", f"crash_in_ckpt@round:{r}", []),
        ("corrupt_ckpt", f"corrupt_ckpt@round:{r}", []),
        ("nan_inject", f"nan_inject@round:{r}", ["--guard"]),
        ("bitflip_params",
         f"bitflip_params@rank:{int(rng.integers(0, 4))}@round:{r}",
         ["--audit-every", "1"]),
        ("straggle+crash",
         f"straggle:0.5s@round:{r},crash@round:{r}@attempt:0", []),
    ]


def _clean_env():
    os.environ.pop("XLA_FLAGS", None)
    for k in list(os.environ):
        if k.startswith("SPARKNET_") and k != "SPARKNET_SOAK":
            os.environ.pop(k)


def _run_driver(out, ckpt, flags, fault=None, max_restarts=2):
    from sparknet_tpu.parallel.resilience import ResilientRunner, RestartPolicy
    cmd = [sys.executable, DRIVER, "--strategy", "sync", "--out", out,
           "--local-devices", "4", "--rounds", "4"] + flags
    if ckpt:
        cmd += ["--ckpt-dir", ckpt]
    runner = ResilientRunner(
        cmd, nprocs=1, platform="cpu", timeout=300,
        policy=RestartPolicy(max_restarts=max_restarts, backoff_base=0.2),
        extra_env={"SPARKNET_FAULT": fault} if fault else None)
    rc = runner.run()
    return rc, len(runner.attempts)


def _params_match(base_npz, out_npz):
    import numpy as np
    a, b = np.load(base_npz), np.load(out_npz)
    for k in a.files:
        if k.startswith("__"):
            continue
        if not np.array_equal(a[k], b[k]):
            return False, k
    return True, None


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description="chaos soak runner")
    ap.add_argument("--runs", type=int, default=8)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--out", default=None,
                    help="write the JSON verdict here (default: stdout)")
    ap.add_argument("--workdir", default=None,
                    help="scratch dir (default: a TemporaryDirectory)")
    args = ap.parse_args(argv)

    import numpy as np
    _clean_env()
    rng = np.random.default_rng(args.seed)

    own_tmp = args.workdir is None
    workdir = args.workdir or tempfile.mkdtemp(prefix="sparknet_soak_")
    os.makedirs(workdir, exist_ok=True)

    baselines: dict[tuple[str, ...], str] = {}

    def baseline_for(flags):
        """Fault-free reference run per flag set (cached — the guard and
        audit change checkpoint traffic but not the training math, so
        matching flags keeps the comparison honest)."""
        key = tuple(flags)
        if key not in baselines:
            path = os.path.join(workdir, f"base_{len(baselines)}.npz")
            ck = os.path.join(workdir, f"base_ck_{len(baselines)}")
            rc, _ = _run_driver(path, ck if flags else None, list(flags))
            if rc != 0:
                raise RuntimeError(f"fault-free baseline failed rc={rc} "
                                   f"(flags={flags})")
            baselines[key] = path
        return baselines[key]

    runs = []
    t0 = time.monotonic()
    for i in range(args.runs):
        options = _schedules(rng)
        name, fault, flags = options[int(rng.integers(0, len(options)))]
        out = os.path.join(workdir, f"run_{i}.npz")
        ck = os.path.join(workdir, f"ck_{i}")
        verdict = {"run": i, "schedule": name, "fault": fault,
                   "flags": flags}
        try:
            base = baseline_for(flags)
            rc, attempts = _run_driver(out, ck, list(flags), fault=fault)
            verdict.update(rc=rc, attempts=attempts)
            if rc == 0:
                match, bad_key = _params_match(base, out)
                verdict.update(match=match,
                               **({"diverged_at": bad_key}
                                  if not match else {}))
            else:
                verdict.update(match=False)
        except Exception as e:   # a broken run is a red verdict, not a crash
            verdict.update(rc=-1, attempts=0, match=False, error=str(e))
        verdict["ok"] = bool(verdict.get("rc") == 0 and verdict["match"])
        runs.append(verdict)
        print(f"soak: run {i} [{fault}] -> "
              f"{'OK' if verdict['ok'] else 'FAIL'} "
              f"(rc={verdict.get('rc')}, attempts="
              f"{verdict.get('attempts')})", flush=True)

    passed = sum(1 for r in runs if r["ok"])
    report = {"seed": args.seed, "runs": runs, "passed": passed,
              "failed": len(runs) - passed,
              "elapsed_s": round(time.monotonic() - t0, 1),
              "ok": passed == len(runs)}
    text = json.dumps(report, indent=1)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text + "\n")
        print(f"soak: verdict written to {args.out} "
              f"({passed}/{len(runs)} passed)")
    else:
        print(text)
    if own_tmp and report["ok"]:
        import shutil
        shutil.rmtree(workdir, ignore_errors=True)
    elif not report["ok"]:
        print(f"soak: scratch kept at {workdir} for post-mortem",
              file=sys.stderr)
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
