"""Pallas TPU kernels for ops XLA fuses poorly.

Cross-channel LRN is AlexNet/CaffeNet's one non-matmul hot op (~13% of
the measured f32 train step: 24.2 -> 21.2 ms/step with LRN stripped, TPU
v5e batch 256).  XLA lowers it as reduce_window + pow + div in forward
and a second windowed reduction in backward; these kernels do each pass
in ONE trip through VMEM with the channel-window sums computed as
unrolled shifted adds on the VPU, and a custom VJP that saves only
``scale`` (Caffe's own trick — lrn_layer.cpp stores scale_ for
CrossMapBackward).

Math (reference: caffe/src/caffe/layers/lrn_layer.cpp):
  scale(c) = k + alpha/n * sum_{d in window} x(c+d)^2
  y        = x * scale^-beta
  dx(c)    = dy(c)*scale(c)^-beta
             - (2*alpha*beta/n) * x(c) * sum_{d} dy(c+d)*y(c+d)/scale(c+d)

Layout: (N, C, H, W) -> grid over (batch, spatial tiles), block (C, TS)
so the windowed sum runs along sublanes and the spatial axis rides the
128-wide lanes.  Runs in interpreter mode off-TPU (tests/CPU rig).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_TS = 512  # spatial tile (lanes); f32 block C×TS stays well under VMEM


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _window_sum(v: jnp.ndarray, pre: int, post: int) -> jnp.ndarray:
    """Σ over the [-pre, +post] channel window along axis 0, zero-padded
    — unrolled shifted adds.  Forward uses Caffe's (pre=(n-1)/2, post);
    the VJP uses the REFLECTED window (post, pre): c' contributes to c's
    gradient iff c lies in c''s forward window."""
    c = v.shape[0]
    padded = jnp.pad(v, ((pre, post), (0, 0)))
    out = padded[0:c]
    for d in range(1, pre + post + 1):
        out = out + padded[d:d + c]
    return out


def _fwd_window(size: int) -> tuple[int, int]:
    pre = (size - 1) // 2
    return pre, size - 1 - pre


def _lrn_fwd_kernel(x_ref, y_ref, scale_ref, *, size, alpha, beta, k,
                    relu=False):
    # Math in f32 regardless of I/O dtype; bf16 blocks cast at the VMEM
    # boundary so mixed-precision nets keep f32 window sums.  With
    # ``relu`` the block consumes the producer conv's biased output
    # directly and applies the chain's ReLU in-register — the vertical
    # fusion pass's LRN epilogue (graph/fusion.py) — so the post-ReLU
    # activation never round-trips through HBM between the two layers.
    x = x_ref[:].astype(jnp.float32)
    a = jnp.maximum(x, 0.0) if relu else x
    pre, post = _fwd_window(size)
    scale = k + (alpha / size) * _window_sum(a * a, pre, post)
    scale_ref[:] = scale.astype(scale_ref.dtype)
    y_ref[:] = (a * scale ** -beta).astype(y_ref.dtype)


def _lrn_infer_kernel(x_ref, y_ref, *, size, alpha, beta, k, relu=False):
    """Forward without the scale residual — the primal/inference path
    (a pallas output cannot be dead-code-eliminated by XLA, so writing
    scale when nothing consumes it costs a full HBM pass)."""
    x = x_ref[:].astype(jnp.float32)
    a = jnp.maximum(x, 0.0) if relu else x
    pre, post = _fwd_window(size)
    scale = k + (alpha / size) * _window_sum(a * a, pre, post)
    y_ref[:] = (a * scale ** -beta).astype(y_ref.dtype)


def _lrn_bwd_kernel(x_ref, scale_ref, dy_ref, dx_ref, *, size, alpha, beta,
                    relu=False):
    # The ReLU'd activation is recomputed from the saved pre-activation
    # (one VPU max) rather than stored — residuals stay (x, scale),
    # exactly Caffe's CrossMapBackward memory footprint even with the
    # epilogue fused on top.
    x = x_ref[:].astype(jnp.float32)
    scale = scale_ref[:].astype(jnp.float32)
    dy = dy_ref[:].astype(jnp.float32)
    a = jnp.maximum(x, 0.0) if relu else x
    y = a * scale ** -beta
    pre, post = _fwd_window(size)
    ratio = _window_sum(dy * y / scale, post, pre)  # reflected window
    da = (dy * scale ** -beta
          - (2.0 * alpha * beta / size) * a * ratio)
    if relu:
        # relu_layer.cpp Backward: dx = da * (x > 0); ties at exactly 0
        # route no gradient, matching the unfused ReLU->LRN pair
        da = jnp.where(x > 0, da, 0.0)
    dx_ref[:] = da.astype(dx_ref.dtype)


def _specs(n, c, s):
    grid = (n, pl.cdiv(s, _TS))
    spec = pl.BlockSpec((None, c, _TS), lambda i, j: (i, 0, j))
    return grid, spec


def _fwd_call(x, size, alpha, beta, k, relu):
    n, c, h, w = x.shape
    xs = x.reshape(n, c, h * w)
    grid, spec = _specs(n, c, h * w)
    y, scale = pl.pallas_call(
        functools.partial(_lrn_fwd_kernel, size=size, alpha=alpha,
                          beta=beta, k=k, relu=relu),
        out_shape=(jax.ShapeDtypeStruct(xs.shape, xs.dtype),
                   jax.ShapeDtypeStruct(xs.shape, xs.dtype)),
        grid=grid,
        in_specs=[spec],
        out_specs=(spec, spec),
        interpret=_interpret(),
    )(xs)
    return y.reshape(x.shape), scale.reshape(x.shape)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4, 5))
def relu_lrn_across_channels(x, size: int, alpha: float, beta: float,
                             k: float, relu: bool = False):
    """Caffe ACROSS_CHANNELS LRN as a fused Pallas kernel, with the
    producing chain's zero-slope ReLU optionally folded in-register
    (``relu=True``) — the vertical fusion pass's LRN epilogue: the conv
    output is read from HBM ONCE, bias/ReLU/window-sum/normalize all
    happen in VMEM, and only the normalized activation is written back
    (plus ``scale`` on the VJP path, Caffe's own residual)."""
    n, c, h, w = x.shape
    xs = x.reshape(n, c, h * w)
    grid, spec = _specs(n, c, h * w)
    y = pl.pallas_call(
        functools.partial(_lrn_infer_kernel, size=size, alpha=alpha,
                          beta=beta, k=k, relu=relu),
        out_shape=jax.ShapeDtypeStruct(xs.shape, xs.dtype),
        grid=grid,
        in_specs=[spec],
        out_specs=spec,
        interpret=_interpret(),
    )(xs)
    return y.reshape(x.shape)


def _lrn_vjp_fwd(x, size, alpha, beta, k, relu):
    y, scale = _fwd_call(x, size, alpha, beta, k, relu)
    return y, (x, scale)


def _lrn_vjp_bwd(size, alpha, beta, k, relu, res, dy):
    x, scale = res
    n, c, h, w = x.shape
    grid, spec = _specs(n, c, h * w)
    dx = pl.pallas_call(
        functools.partial(_lrn_bwd_kernel, size=size, alpha=alpha,
                          beta=beta, relu=relu),
        out_shape=jax.ShapeDtypeStruct((n, c, h * w), x.dtype),
        grid=grid,
        in_specs=[spec, spec, spec],
        out_specs=spec,
        interpret=_interpret(),
    )(x.reshape(n, c, h * w), scale.reshape(n, c, h * w),
      dy.reshape(n, c, h * w))
    return (dx.reshape(x.shape),)


relu_lrn_across_channels.defvjp(_lrn_vjp_fwd, _lrn_vjp_bwd)


def lrn_across_channels(x, size: int, alpha: float, beta: float, k: float):
    """Caffe ACROSS_CHANNELS LRN as a fused Pallas kernel (the
    ``relu=False`` face of :func:`relu_lrn_across_channels`)."""
    return relu_lrn_across_channels(x, size, alpha, beta, k, False)


# ---------------------------------------------------------------------------
# VMEM-resident MAX-pool backward
#
# XLA lowers maxpool backward as select-and-scatter, measured at an HBM
# traffic floor ~2.5x the minimum on GoogLeNet's 13 pools (5.3 ms of the
# 26.4 ms bf16 step); two pure-XLA rewrites measured OUT (see
# RESULTS.md).  This kernel does the whole backward in ONE trip: read x
# and dy once, recompute each window's FIRST argmax on the VPU (Caffe's
# tie-break — pooling_layer.cpp Forward_cpu MAX branch scans row-major
# and keeps the first maximum), route dy through the argmax, write dx
# once.  The grid tiles (batch, channels) and keeps the full spatial
# plane per block in VMEM, so no halo exchange is needed.
# ---------------------------------------------------------------------------


def _pool_taps(kh: int, kw: int):
    """Window taps in Caffe's scan order (row-major; first max wins)."""
    return [(dh, dw) for dh in range(kh) for dw in range(kw)]


def _maxpool_bwd_kernel_s1(x_ref, dy_ref, dx_ref, *, kh, kw, ph, pw,
                           oh, ow, h, w):
    """Stride-1 path: row taps are contiguous sublane slices; column
    taps ride the MXU as exact one-hot matmuls.  Hard-won Mosaic
    constraints (each crashes the compiler if violated): no lane-offset
    pads of compare-derived values, compares in f32 (bf16 cmpf
    miscompiles at 3-D shapes), and the padded plane widened to >=128
    lanes (free — vregs are 128 lanes regardless; narrow matmul K-dims
    crash at 7x7)."""
    x = x_ref[:]
    dy = dy_ref[:]
    c = x.shape[0]
    hp = oh + kh - 1
    wp = max(ow + kw - 1, 128)
    # Sentinel must be exactly bf16-representable: the MXU's bf16-pass
    # f32 matmul turns finfo(f32).min into -inf and the one-hot gather
    # into NaN (inf*0), silently zeroing every f32-mode gradient.
    # Domain restriction this buys: f32 activations below bf16 min
    # (-3.3895e38) would lose the argmax to padding — next stop after
    # that magnitude is inf, so no practical net is affected.
    neg = jnp.asarray(jnp.finfo(jnp.bfloat16).min, x.dtype)
    xp = jnp.pad(x, ((0, 0), (ph, hp - h - ph), (pw, wp - w - pw)),
                 constant_values=neg)
    gathers = [_col_onehot(dw, 1, wp, ow, x.dtype) for dw in range(kw)]
    # True-f32 nets need the exact multi-pass matmul: the default
    # single bf16 pass rounds the gathered VALUES and corrupts argmax
    # routing.  bf16 nets are single-pass-exact, and HIGHEST on bf16
    # inputs crashes Mosaic — so pick per dtype.
    prec = (jax.lax.Precision.HIGHEST if x.dtype == jnp.float32
            else jax.lax.Precision.DEFAULT)

    def window(dh, dw):
        # f32 MXU accumulator doubles as the compare domain (exact —
        # the matmul just selects single bf16 values).
        slab = xp[:, dh:dh + oh, :]
        return jax.lax.dot_general(
            slab.reshape(c * oh, wp), gathers[dw], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=prec).reshape(c, oh, ow)

    taps = _pool_taps(kh, kw)
    wins = [window(dh, dw) for dh, dw in taps]  # one gather per tap
    best = functools.reduce(jnp.maximum, wins)
    # Route dy to the FIRST tap equal to the max (Caffe's row-major
    # tie-break).  A boolean "claimed" plane replaces an int argmax
    # plane: constant-init int planes get a replicated Mosaic layout
    # that the mask relayout then rejects.
    dyf = dy.astype(jnp.float32)
    scatters = [_col_onehot(dw, 1, wp, ow, jnp.float32) for dw in range(kw)]
    acc = None
    claimed = None
    for (dh, dw), v in zip(taps, wins):
        eq = v == best
        m = eq if claimed is None else eq & ~claimed
        claimed = eq if claimed is None else claimed | eq
        cont = jnp.where(m, dyf, 0.0)
        wide = jax.lax.dot_general(
            cont.reshape(c * oh, ow), scatters[dw], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=prec).reshape(c, oh, wp)
        part = jnp.pad(wide, ((0, 0), (dh, hp - oh - dh), (0, 0)))
        acc = part if acc is None else acc + part
    dx_ref[:] = acc[:, ph:ph + h, pw:pw + w].astype(dx_ref.dtype)


def _col_onehot(dw: int, sw: int, wp: int, ow: int, dtype):
    """(wp, ow) selection matrix: column s picks padded-plane lane
    dw + s*sw.  Lane-strided gather/placement isn't lowerable on the
    VPU, so both directions ride the MXU as exact one-hot matmuls."""
    rowi = jax.lax.broadcasted_iota(jnp.int32, (wp, ow), 0)
    coli = jax.lax.broadcasted_iota(jnp.int32, (wp, ow), 1)
    return (rowi == dw + coli * sw).astype(dtype)


def _maxpool_bwd_kernel_strided(x_ref, dy_ref, dx_ref, *, kh, kw, sh, sw,
                                ph, pw, oh, ow, h, w):
    """General strided path.  Row stride is handled by splitting the
    sublane dim into (rows, sh) phases (a reshape Mosaic supports);
    column stride via one-hot selection matmuls (_col_onehot), since
    lane-dim strided slices and interior pads don't lower."""
    x = x_ref[:]
    dy = dy_ref[:]
    c = x.shape[0]
    rows = (kh - 1) // sh + oh
    hp = rows * sh
    # >=128-lane widening as in the stride-1 kernel: free (vregs are
    # 128 lanes regardless) and keeps the matmul K-dim off the narrow
    # sizes that crash Mosaic.  The w + pw floor covers stride > kernel
    # under Caffe's ceil-mode clip, where (ow-1)*sw + kw can fall short
    # of the input width and the pad amount would go negative.
    wp = max((ow - 1) * sw + kw, w + pw, 128)
    # bf16-representable sentinel — see the stride-1 kernel's comment.
    neg = jnp.asarray(jnp.finfo(jnp.bfloat16).min, x.dtype)
    xp = jnp.pad(x, ((0, 0), (ph, hp - h - ph), (pw, wp - w - pw)),
                 constant_values=neg)
    x4 = xp.reshape(c, rows, sh, wp)
    taps = _pool_taps(kh, kw)
    gathers = [_col_onehot(dw, sw, wp, ow, x.dtype) for dw in range(kw)]
    # True-f32 nets need the exact multi-pass matmul: the default
    # single bf16 pass rounds the gathered VALUES and corrupts argmax
    # routing.  bf16 nets are single-pass-exact, and HIGHEST on bf16
    # inputs crashes Mosaic — so pick per dtype.
    prec = (jax.lax.Precision.HIGHEST if x.dtype == jnp.float32
            else jax.lax.Precision.DEFAULT)

    def window(dh, dw):
        # One-hot MXU gather; keep the mandatory 32-bit accumulator as
        # the compare domain too (bf16 cmpf crashes Mosaic; exact both
        # ways since the matmul just selects single values).
        slab = x4[:, dh // sh:dh // sh + oh, dh % sh, :]
        return jax.lax.dot_general(
            slab.reshape(c * oh, wp), gathers[dw], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=prec).reshape(c, oh, ow)

    wins = [window(dh, dw) for dh, dw in taps]  # one gather per tap
    best = functools.reduce(jnp.maximum, wins)
    # First-equal-claims routing (see the stride-1 kernel's comment).
    dyf = dy.astype(jnp.float32)
    scatters = [_col_onehot(dw, sw, wp, ow, jnp.float32) for dw in range(kw)]
    phase_acc = [None] * sh
    claimed = None
    for (dh, dw), v in zip(taps, wins):
        eq = v == best
        m = eq if claimed is None else eq & ~claimed
        claimed = eq if claimed is None else claimed | eq
        cont = jnp.where(m, dyf, 0.0)
        wide = jax.lax.dot_general(
            cont.reshape(c * oh, ow), scatters[dw], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=prec).reshape(c, oh, wp)
        q = dh // sh
        part = jnp.pad(wide, ((0, 0), (q, rows - oh - q), (0, 0)))
        p = dh % sh
        phase_acc[p] = part if phase_acc[p] is None else phase_acc[p] + part
    phase_acc = [a if a is not None else jnp.zeros((c, rows, wp), jnp.float32)
                 for a in phase_acc]  # kh < sh leaves untouched phases
    acc = jnp.stack(phase_acc, axis=2).reshape(c, hp, wp)
    dx_ref[:] = acc[:, ph:ph + h, pw:pw + w].astype(dx_ref.dtype)


def _pool_ctile(c: int, h: int, w: int, kh: int, kw: int) -> int:
    """Channels per block, capped at 8 — larger channel tiles crash
    Mosaic on these kernels (empirical: ct=24 dies after 130 s of
    compile, ct<=8 compiles in seconds; the grid pipelines the extra
    steps, so small tiles cost nothing measurable).  The VMEM model:
    kh*kw live f32 window planes (the ``wins`` list) plus ~5 padded
    >=128-lane input/acc/mask planes, kept under a conservative 64 MB
    so the ct<=8 Mosaic cap — not memory — binds for every zoo pool
    shape (~2 MB at ct=8 for 3x3 pools)."""
    per_c = max(h * max(w, 128) * 4 * (kh * kw + 5), 1)
    t = max(1, min(c, 8, (64 << 20) // per_c))
    while c % t:
        t -= 1
    return t


def _maxpool_bwd_call(x, dy, kh, kw, sh, sw, ph, pw, oh, ow):
    n, c, h, w = x.shape
    ct = _pool_ctile(c, h, w, kh, kw)
    grid = (n, c // ct)
    kern = (_maxpool_bwd_kernel_s1 if sh == 1 and sw == 1 else
            functools.partial(_maxpool_bwd_kernel_strided, sh=sh, sw=sw))
    return pl.pallas_call(
        functools.partial(kern, kh=kh, kw=kw, ph=ph, pw=pw,
                          oh=oh, ow=ow, h=h, w=w),
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        grid=grid,
        in_specs=[pl.BlockSpec((None, ct, h, w), lambda i, j: (i, j, 0, 0)),
                  pl.BlockSpec((None, ct, oh, ow),
                               lambda i, j: (i, j, 0, 0))],
        out_specs=pl.BlockSpec((None, ct, h, w), lambda i, j: (i, j, 0, 0)),
        interpret=_interpret(),
    )(x, dy)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4, 5, 6, 7, 8))
def max_pool_vmem_bwd(x, kh: int, kw: int, sh: int, sw: int,
                      ph: int, pw: int, oh: int, ow: int):
    """MAX pool whose forward is XLA's reduce_window (fuses with
    neighbors) and whose BACKWARD is the VMEM-resident Pallas kernel
    instead of select-and-scatter.  The primal IS ops/vision.max_pool —
    one home for the Caffe ceil-mode geometry.

    Domain restriction: the backward pads windows with a bf16-min
    sentinel (-3.3895e38) even in f32 mode (f32-min becomes -inf through
    the MXU's bf16 pass and NaN-poisons the one-hot gather), so an f32
    activation below bf16-min would lose its argmax to padding and
    mis-route the gradient.  No practical activation reaches -3.4e38;
    the next representable magnitude beyond the sentinel is -inf."""
    from .vision import max_pool
    return max_pool(x, kh, kw, sh, sw, ph, pw, oh, ow)


def _maxpool_vjp_fwd(x, kh, kw, sh, sw, ph, pw, oh, ow):
    return max_pool_vmem_bwd(x, kh, kw, sh, sw, ph, pw, oh, ow), x


def _maxpool_vjp_bwd(kh, kw, sh, sw, ph, pw, oh, ow, x, dy):
    return (_maxpool_bwd_call(x, dy, kh, kw, sh, sw, ph, pw, oh, ow),)


max_pool_vmem_bwd.defvjp(_maxpool_vjp_fwd, _maxpool_vjp_bwd)
