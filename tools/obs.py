#!/usr/bin/env python
"""obs — telemetry plane CLI: merge trace shards, roll up metrics, smoke.

``merge`` joins the per-rank Chrome-trace JSONL shards a run wrote under
``SPARKNET_TRACE_DIR`` (trainer rounds, feed stages, checkpoint writes,
restarts, fleet decisions, serving batches — every rank/attempt/
incarnation of the run) into ONE clock-aligned, perfetto-loadable
timeline, prints a span + metrics rollup, and optionally validates the
trace (``--check``: spans present, ranks covered, correlation IDs on
every span, non-negative rebased timestamps).  Because shard timestamps
are epoch microseconds, alignment across processes is a single global
rebase — a fault injection on rank 1, the supervisor's restart, and the
recovered round on every rank land on one axis.

``smoke`` is the CI gate (SPARKNET_OBSSMOKE=1 / --obssmoke in
tools/run_tier1.sh): a 2-round training run per rank (two single-process
driver runs sharing one run id — the trace-plumbing contract, not a
collective), plus a live tools/serve.py instance driven over HTTP whose
``GET /metrics`` must parse as Prometheus text; then ``merge --check``
must produce a valid merged trace with spans from both ranks.

Usage:
  python tools/obs.py merge TRACE_DIR [--out trace.json] [--check]
      [--expect-ranks 2]
  python tools/obs.py smoke [--out verdict.json]
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


# ---------------------------------------------------------------------------
# Shard loading + merge
# ---------------------------------------------------------------------------

def load_shards(directory: str) -> tuple[list[dict], list[str]]:
    """Every parseable event from every trace_*.jsonl under
    ``directory`` (recursive).  A torn final line — the process died
    mid-flush — is skipped, not fatal."""
    shards = sorted(glob.glob(os.path.join(directory, "**",
                                           "trace_*.jsonl"),
                              recursive=True))
    events: list[dict] = []
    for path in shards:
        try:
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        events.append(json.loads(line))
                    except json.JSONDecodeError:
                        continue
        except OSError:
            continue
    return events, shards


def merge_events(events: list[dict]) -> dict:
    """Rebase every timestamped event to the run's earliest microsecond
    and sort — the clock alignment step (shards stamp epoch micros, so
    cross-rank alignment is one global offset)."""
    timed = [e for e in events if "ts" in e]
    meta = [e for e in events if "ts" not in e]
    t0 = min((e["ts"] for e in timed), default=0)
    out = []
    for e in timed:
        e = dict(e)
        e["ts"] = e["ts"] - t0
        out.append(e)
    out.sort(key=lambda e: (e["ts"], e.get("dur", 0)))
    return {"traceEvents": meta + out, "displayTimeUnit": "ms",
            "otherData": {"epoch_us_origin": t0}}


def trace_rollup(events: list[dict]) -> dict:
    """Per-span-name counts/durations and per-rank event counts — the
    merge command's printed summary."""
    spans: dict[str, dict] = {}
    ranks: dict[str, int] = {}
    runs: set = set()
    flights = 0
    for e in events:
        args = e.get("args") or {}
        if "rank" in args:
            ranks[str(args["rank"])] = ranks.get(str(args["rank"]), 0) + 1
        if "run" in args:
            runs.add(str(args["run"]))
        if e.get("ph") == "X":
            s = spans.setdefault(e.get("name", "?"),
                                 {"count": 0, "total_us": 0, "max_us": 0})
            s["count"] += 1
            dur = int(e.get("dur", 0))
            s["total_us"] += dur
            s["max_us"] = max(s["max_us"], dur)
        elif e.get("cat") == "flight":
            flights += 1
    return {"spans": spans, "ranks": ranks, "runs": sorted(runs),
            "flight_events": flights}


def fold_metrics_dir(directory: str) -> dict:
    from sparknet_tpu.utils.telemetry import fold_snapshots
    paths = glob.glob(os.path.join(directory, "**", "metrics_rank*.json"),
                      recursive=True)
    return fold_snapshots(sorted(paths))


def check_trace(events: list[dict], rollup: dict,
                expect_ranks: int) -> list[str]:
    """The --check validations: the trace must be usable evidence, not
    just a file that exists."""
    failures: list[str] = []
    spans = [e for e in events if e.get("ph") == "X"]
    if not spans:
        failures.append("no complete spans (ph=X) in any shard")
    if len(rollup["ranks"]) < expect_ranks:
        failures.append(f"spans from {len(rollup['ranks'])} rank(s) "
                        f"{sorted(rollup['ranks'])}, expected >= "
                        f"{expect_ranks}")
    bad_corr = sum(1 for e in spans
                   if "run" not in (e.get("args") or {})
                   or "rank" not in (e.get("args") or {}))
    if bad_corr:
        failures.append(f"{bad_corr} span(s) missing run/rank "
                        f"correlation IDs")
    bad_ts = sum(1 for e in events
                 if "ts" in e and (e["ts"] < 0 or e.get("dur", 0) < 0))
    if bad_ts:
        failures.append(f"{bad_ts} event(s) with negative rebased ts or "
                        f"negative dur — clocks are not aligned")
    prev = -1
    for e in events:
        ts = e.get("ts")
        if ts is None:
            continue
        if ts < prev:
            failures.append("merged events are not time-sorted")
            break
        prev = ts
    return failures


def cmd_merge(args) -> int:
    events, shards = load_shards(args.trace_dir)
    if not shards:
        print(f"obs merge: no trace_*.jsonl shards under "
              f"{args.trace_dir!r}", file=sys.stderr)
        return 2
    merged = merge_events(events)
    rollup = trace_rollup(merged["traceEvents"])
    out = args.out or os.path.join(args.trace_dir, "trace_merged.json")
    with open(out, "w") as f:
        json.dump(merged, f)
    print(f"obs merge: {len(shards)} shard(s), "
          f"{len(merged['traceEvents'])} events -> {out}")
    print(f"  runs: {', '.join(rollup['runs']) or '-'}")
    print(f"  ranks: " + ", ".join(
        f"{r} ({n} ev)" for r, n in sorted(rollup["ranks"].items())))
    if rollup["flight_events"]:
        print(f"  flight-recorder events on the timeline: "
              f"{rollup['flight_events']}")
    for name, s in sorted(rollup["spans"].items(),
                          key=lambda kv: -kv[1]["total_us"]):
        print(f"  span {name:<24} x{s['count']:<6} "
              f"total {s['total_us'] / 1e6:.3f}s "
              f"max {s['max_us'] / 1e3:.1f}ms")
    metrics = fold_metrics_dir(args.metrics_dir or args.trace_dir)
    if metrics:
        print("  metrics rollup:")
        for name, m in sorted(metrics.items()):
            for s in m["samples"]:
                lbl = ",".join(f"{k}={v}" for k, v in
                               sorted(s["labels"].items()))
                if m["kind"] == "histogram":
                    print(f"    {name}{{{lbl}}} count={s['count']} "
                          f"sum={s['sum']:.4g}")
                else:
                    print(f"    {name}{{{lbl}}} {s['value']:g}")
    if args.check:
        failures = check_trace(merged["traceEvents"], rollup,
                               args.expect_ranks)
        if failures:
            print("obs merge: CHECK FAILED:", file=sys.stderr)
            for msg in failures:
                print(f"  - {msg}", file=sys.stderr)
            return 1
        print(f"obs merge: check OK ({len(rollup['ranks'])} ranks, "
              f"{sum(s['count'] for s in rollup['spans'].values())} spans)")
    return 0


# ---------------------------------------------------------------------------
# Prometheus text parsing (the /metrics validation half of the smoke)
# ---------------------------------------------------------------------------

_PROM_LINE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^}]*\})?\s+"
    r"([-+]?(?:[0-9]*\.?[0-9]+(?:[eE][-+]?[0-9]+)?|Inf|NaN))$")


def parse_prometheus(text: str) -> dict[str, list[tuple[str, float]]]:
    """Strict-enough parser of the text exposition format: every
    non-comment, non-blank line must be ``name{labels} value``.  Raises
    ValueError on the first malformed line; returns name -> samples."""
    out: dict[str, list[tuple[str, float]]] = {}
    for i, line in enumerate(text.splitlines(), 1):
        line = line.rstrip()
        if not line or line.startswith("#"):
            continue
        m = _PROM_LINE.match(line)
        if not m:
            raise ValueError(f"line {i} is not Prometheus text: {line!r}")
        name, labels, value = m.group(1), m.group(2) or "", m.group(3)
        out.setdefault(name, []).append((labels, float(value)))
    return out


# ---------------------------------------------------------------------------
# The CI smoke (SPARKNET_OBSSMOKE=1)
# ---------------------------------------------------------------------------

def _scrubbed_env(**extra: str) -> dict:
    env = {k: v for k, v in os.environ.items()
           if not k.startswith("SPARKNET_") and k != "XLA_FLAGS"}
    env["JAX_PLATFORMS"] = "cpu"
    env.update(extra)
    return env


def _http(url: str, payload: dict | None = None,
          timeout: float = 30.0) -> dict | str:
    import urllib.request
    if payload is None:
        req = urllib.request.Request(url)
    else:
        req = urllib.request.Request(
            url, data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        body = resp.read().decode()
        ctype = resp.headers.get("Content-Type", "")
    return json.loads(body) if "json" in ctype else body


def cmd_smoke(args) -> int:
    import base64
    import signal
    import subprocess

    import numpy as np

    t_start = time.monotonic()
    work = tempfile.mkdtemp(prefix="sparknet_obssmoke_")
    trace_dir = os.path.join(work, "trace")
    snap_dir = os.path.join(work, "metrics")
    verdict: dict = {"ok": False, "trace_dir": trace_dir}
    failures: list[str] = []

    # -- leg 1: 2-round training per rank (two single-process driver
    # runs sharing one run id: the shard/correlation plumbing contract)
    driver = os.path.join(REPO, "tests", "multihost_driver.py")
    for rank in (0, 1):
        env = _scrubbed_env(
            SPARKNET_TRACE_DIR=trace_dir,
            SPARKNET_METRICS_SNAP=snap_dir,
            SPARKNET_METRICS_SNAP_S="0",
            SPARKNET_RUN_ID="obssmoke",
            SPARKNET_TELEMETRY_RANK=str(rank))
        cmd = [sys.executable, driver, "--strategy", "sync",
               "--out", os.path.join(work, f"out{rank}.npz"),
               "--local-devices", "2", "--expect-devices", "2",
               "--rounds", "2", "--global-batch", "8",
               "--ckpt-dir", os.path.join(work, f"ck{rank}")]
        r = subprocess.run(cmd, env=env, capture_output=True, text=True,
                           timeout=240)
        if r.returncode != 0:
            failures.append(f"training rank {rank} failed rc="
                            f"{r.returncode}: {r.stderr[-500:]}")

    # -- leg 2: live serving over HTTP, /metrics must parse ---------------
    serve = os.path.join(REPO, "tools", "serve.py")
    env = _scrubbed_env(
        SPARKNET_TRACE_DIR=trace_dir,
        SPARKNET_RUN_ID="obssmoke",
        SPARKNET_TELEMETRY_RANK="9")  # distinct shard; 0/1 are training
    proc = subprocess.Popen(
        [sys.executable, serve, "--models", "lenet", "--port", "0",
         "--shapes", "1,4", "--max-delay-ms", "2", "--dtype", "f32"],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
        text=True)
    url = None
    try:
        assert proc.stdout is not None
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            line = proc.stdout.readline()
            if line.startswith("serving on "):
                url = line.split()[2]
                break
            if proc.poll() is not None:
                break
        if not url:
            failures.append("serve.py never printed its ready line")
        else:
            x = np.zeros((1, 28, 28), np.float32)
            res = _http(f"{url}/v1/classify", {
                "model": "lenet", "tenant": "obssmoke",
                "shape": [1, 28, 28], "dtype": "float32",
                "data_b64": base64.b64encode(x.tobytes()).decode()})
            if not isinstance(res, dict) or "probs" not in res:
                failures.append(f"classify answer malformed: {res!r:.200}")
            text = _http(f"{url}/metrics")
            try:
                samples = parse_prometheus(str(text))
            except ValueError as e:
                failures.append(f"/metrics is not Prometheus text: {e}")
                samples = {}
            for need in ("serve_queue_depth", "serve_p99_ms",
                         "serve_request_seconds_bucket",
                         "serve_completed_total"):
                if need not in samples:
                    failures.append(f"/metrics missing {need}")
            verdict["metrics_families"] = len(samples)
    finally:
        if proc.poll() is None:
            proc.send_signal(signal.SIGTERM)
            try:
                proc.wait(timeout=20)
            except subprocess.TimeoutExpired:
                proc.kill()

    # -- leg 3: the merged trace must validate ----------------------------
    events, shards = load_shards(trace_dir)
    verdict["shards"] = len(shards)
    if not shards:
        failures.append("no trace shards were written")
    else:
        merged = merge_events(events)
        rollup = trace_rollup(merged["traceEvents"])
        failures.extend(check_trace(merged["traceEvents"], rollup,
                                    expect_ranks=2))
        if "trainer.round" not in rollup["spans"]:
            failures.append("no trainer.round spans in the merged trace")
        training_ranks = {str(e.get("args", {}).get("rank"))
                          for e in merged["traceEvents"]
                          if e.get("name") == "trainer.round"}
        if not {"0", "1"} <= training_ranks:
            failures.append(f"trainer.round spans from ranks "
                            f"{sorted(training_ranks)}, want 0 and 1")
        out_path = os.path.join(trace_dir, "trace_merged.json")
        with open(out_path, "w") as f:
            json.dump(merged, f)
        verdict.update(events=len(merged["traceEvents"]),
                       ranks=sorted(rollup["ranks"]),
                       spans={k: v["count"]
                              for k, v in rollup["spans"].items()},
                       merged=out_path)
    verdict["metrics_rollup"] = bool(fold_metrics_dir(snap_dir))

    verdict["failures"] = failures
    verdict["ok"] = not failures
    verdict["elapsed_s"] = round(time.monotonic() - t_start, 1)
    text = json.dumps(verdict, indent=1)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text + "\n")
    print(text)
    if failures:
        print(f"[obssmoke] FAILED: {failures}", file=sys.stderr)
        print(f"[obssmoke] scratch kept at {work}", file=sys.stderr)
        return 1
    print(f"[obssmoke] OK — merged trace + /metrics validated in "
          f"{verdict['elapsed_s']}s", file=sys.stderr)
    import shutil
    shutil.rmtree(work, ignore_errors=True)
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description="telemetry plane CLI")
    sub = ap.add_subparsers(dest="cmd", required=True)
    mp = sub.add_parser("merge", help="join per-rank trace shards into "
                                      "one perfetto timeline + rollup")
    mp.add_argument("trace_dir")
    mp.add_argument("--out", default=None,
                    help="merged trace path (default: "
                         "<trace_dir>/trace_merged.json)")
    mp.add_argument("--metrics-dir", default=None,
                    help="fold metrics_rank*.json snapshots from here "
                         "(default: the trace dir)")
    mp.add_argument("--check", action="store_true",
                    help="validate the merged trace (spans present, "
                         "ranks covered, correlation IDs, aligned ts)")
    mp.add_argument("--expect-ranks", type=int, default=1,
                    help="--check: minimum distinct ranks required")
    sp = sub.add_parser("smoke", help="the SPARKNET_OBSSMOKE CI gate")
    sp.add_argument("--out", default=None,
                    help="write the JSON verdict here too")
    args = ap.parse_args(argv)
    if args.cmd == "merge":
        return cmd_merge(args)
    return cmd_smoke(args)


if __name__ == "__main__":
    sys.exit(main())
