"""Hybrid-sharding parity gate (the tensor-sharded analog of commbench).

Six verdicts on a small CPU mesh (~seconds), any failure = rc 1:

1. **three-strategy bit parity** — a trainer with ``shard="auto"``
   (parallel/partition.py's rule table sharding FC weights across chips)
   must produce bit-identical losses AND bit-identical gathered params
   to the replicated (``shard="off"``) trainer, same seed, codec none,
   for every strategy: local_sgd and sync on the flat mesh,
   hierarchical on a (host, chip) pod mesh.  The reduce-scatter/pmean
   identity is asserted, not assumed.
2. **codec composition** — the int8 compressed exchange composed with
   sharding stays bit-identical to the int8 dp run (decode lands the
   params sharded; the wire arithmetic is untouched).
3. **per-shard checkpoint roundtrip** — ``shard_checkpoint=True`` writes
   one common npz + one npz per shard tile under a checksummed manifest;
   a fresh trainer resumes from them with bit-identical params and an
   identical continuation loss.
4. **elastic re-tile** — a checkpoint written under the world-N shard
   plan restores into a world-M trainer (different plan, different tile
   shapes) with gathered params bit-identical to the consensus that was
   checkpointed, and training continues finite.
5. **audit under sharding** — the [n_pos, 2] shard-aware fingerprint
   passes on a healthy mesh, a planted one-bit flip on replica 2 is
   caught with exactly [2] as the culprit set, and the audit trip's
   checkpoint rollback restores a state that re-passes the audit.
6. **boundary-byte shrink** — analytic per-chip τ-boundary bytes under
   the plan must shrink vs pure DP on BOTH the gate model and
   caffenet-class shapes (where FC dominates: the shrink the paper's
   cheap-interconnect regime actually buys; asserted ≥ 2× at 8 shards).

Wired into tools/run_tier1.sh behind SPARKNET_SHARDSMOKE=1 (or
``--shardsmoke``); the JSON doc ingests into the perf ledger via
``perfwatch regress --ingest`` (entries_from_shardbench).

Usage:
    python tools/shardbench.py [--rounds 3] [--devices 8] [--out FILE]

Prints one JSON line on stdout; rc 0 = all gates hold.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

CAFFENET_MIN_SHRINK_X = 2.0   # at 8 shards the analytic value is ~5.6x


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rounds", type=int, default=3)
    ap.add_argument("--devices", type=int, default=4,
                    help="CPU mesh width (virtual devices); 4 keeps "
                    "lenet's 500-unit ip1 divisible")
    ap.add_argument("--batch", type=int, default=16)
    ap.add_argument("--tau", type=int, default=2)
    ap.add_argument("--out", default=None)
    args = ap.parse_args(argv)

    import jax
    import numpy as np

    from sparknet_tpu.graph.net import Net
    from sparknet_tpu.models import lenet
    from sparknet_tpu.models.alexnet import caffenet
    from sparknet_tpu.parallel import (
        DistributedTrainer, TrainerConfig, comms, make_mesh,
        make_pod_mesh, partition,
    )
    from sparknet_tpu.proto import load_solver_prototxt_with_net
    from sparknet_tpu.proto.caffe_pb import NetState, Phase

    tau = args.tau
    sp = load_solver_prototxt_with_net(
        'base_lr: 0.005\nmomentum: 0.9\nlr_policy: "fixed"\n',
        lenet(args.batch, args.batch))
    mesh = make_mesh(args.devices)

    def batch(r):
        rng = np.random.default_rng(4200 + r)
        return {"data": rng.normal(size=(tau, args.batch, 1, 28, 28)
                                   ).astype(np.float32),
                "label": rng.integers(0, 10, size=(tau, args.batch)
                                      ).astype(np.float32)}

    def run(cfg: TrainerConfig, use_mesh=None, rounds=None) -> dict:
        tr = DistributedTrainer(sp, use_mesh or mesh, cfg, seed=0)
        losses = []
        t0 = time.perf_counter()
        for r in range(rounds or args.rounds):
            losses.append(tr.train_round(batch(r)))
        tr.drain()
        jax.block_until_ready(tr.params)
        dt = time.perf_counter() - t0
        # sharded leaves are still GLOBAL arrays with full logical
        # shape; np.asarray fetches the assembled value either way
        return {
            "trainer": tr,
            "losses": losses,
            "params": {k: [np.asarray(b) for b in v]
                       for k, v in tr.params.items()},
            "round_s": round(dt / (rounds or args.rounds), 4),
        }

    def bit_identical(a: dict, b: dict) -> list[str]:
        out = []
        if a["losses"] != b["losses"]:
            out.append(f"losses diverge: {a['losses']} vs {b['losses']}")
        for name, blobs in a["params"].items():
            for i, x in enumerate(blobs):
                if not np.array_equal(x, b["params"][name][i]):
                    out.append(f"param {name}[{i}] not bit-identical")
        return out

    failures: list[str] = []
    pod = make_pod_mesh(2, args.devices // 2)

    # -- 1. dp vs sharded bit parity, all three strategies ----------------
    parity: dict[str, bool] = {}
    legs: dict[str, dict] = {}
    for strat, m in (("local_sgd", mesh), ("sync", mesh),
                     ("hierarchical", pod)):
        dp = run(TrainerConfig(strategy=strat, tau=tau, shard="off"),
                 use_mesh=m)
        sh = run(TrainerConfig(strategy=strat, tau=tau, shard="auto"),
                 use_mesh=m)
        if sh["trainer"].shard_plan is None:
            failures.append(f"[plan] {strat}: shard='auto' resolved to "
                            f"no plan — nothing was sharded")
        mismatch = bit_identical(dp, sh)
        parity[strat] = not mismatch
        failures += [f"[parity-{strat}] {m2}" for m2 in mismatch]
        legs[strat] = {"dp": dp, "sharded": sh}
    plan = legs["local_sgd"]["sharded"]["trainer"].shard_plan
    plan_id = legs["local_sgd"]["sharded"]["trainer"].shard_plan_id

    # -- 2. int8 compressed exchange composed with sharding ---------------
    int8_dp = run(TrainerConfig(strategy="local_sgd", tau=tau,
                                comm_codec="int8", shard="off"))
    int8_sh = run(TrainerConfig(strategy="local_sgd", tau=tau,
                                comm_codec="int8", shard="auto"))
    codec_mismatch = bit_identical(int8_dp, int8_sh)
    failures += [f"[codec-int8] {m2}" for m2 in codec_mismatch]

    # -- 3 + 4 + 5. the sharded safety plane ------------------------------
    ckpt_ok = elastic_ok = audit_ok = False
    with tempfile.TemporaryDirectory() as ck:
        cfg = TrainerConfig(strategy="local_sgd", tau=tau, shard="auto",
                            shard_checkpoint=True, checkpoint_dir=ck,
                            checkpoint_every=1, checkpoint_keep=8,
                            audit_every=1, elastic=True)
        tr = DistributedTrainer(sp, mesh, cfg, seed=0)
        for r in range(2):
            tr.train_round(batch(r))
        tr.drain()
        consensus = {k: [np.asarray(b) for b in v]
                     for k, v in tr.params.items()}
        shard_files = glob.glob(os.path.join(ck, "*.shard*.npz"))
        if not shard_files:
            failures.append("[ckpt] shard_checkpoint=True wrote no "
                            "per-shard npz tiles")
        # 3: fresh same-world trainer resumes the tiles bit-exactly;
        # checkpoint_every bumped so only tr keeps writing into ck
        cfg2 = TrainerConfig(strategy="local_sgd", tau=tau, shard="auto",
                             shard_checkpoint=True, checkpoint_dir=ck,
                             checkpoint_every=64, checkpoint_keep=8,
                             audit_every=1, elastic=True)
        tr2 = DistributedTrainer(sp, mesh, cfg2, seed=0)
        got = {k: [np.asarray(b) for b in v]
               for k, v in tr2.params.items()}
        ckpt_mismatch = bit_identical({"losses": [], "params": consensus},
                                      {"losses": [], "params": got})
        cont_a = tr.train_round(batch(2))
        cont_b = tr2.train_round(batch(2))
        if np.float32(cont_a).tobytes() != np.float32(cont_b).tobytes():
            ckpt_mismatch.append(
                f"continuation loss diverges: {cont_a} vs {cont_b}")
        tr.drain()
        tr2.drain()
        ckpt_ok = not ckpt_mismatch
        failures += [f"[ckpt] {m2}" for m2 in ckpt_mismatch]
        # 4: restore the world-N tiles on a world-M mesh (new plan)
        half = make_mesh(args.devices // 2)
        tr_half = DistributedTrainer(sp, half, cfg2, seed=0)
        got_half = {k: [np.asarray(b) for b in v]
                    for k, v in tr_half.params.items()}
        # tr_half resumed the round-2 checkpoint tr wrote after its
        # continuation round — compare against tr's current params
        now = {k: [np.asarray(b) for b in v]
               for k, v in tr.params.items()}
        elastic_mismatch = bit_identical(
            {"losses": [], "params": now},
            {"losses": [], "params": got_half})
        cont = tr_half.train_round(batch(3))
        tr_half.drain()
        if not np.isfinite(list(tr_half.round_losses.values())[-1]
                           if tr_half.round_losses else cont):
            elastic_mismatch.append("re-tiled continuation non-finite")
        elastic_ok = not elastic_mismatch
        failures += [f"[elastic] {m2}" for m2 in elastic_mismatch]
        # 5: audit — healthy pass, planted flip caught, rollback re-passes
        fps = tr.audit_params()
        audit_msgs = []
        if np.asarray(fps).shape != (args.devices, 2):
            audit_msgs.append(f"sharded fingerprint shape "
                              f"{np.asarray(fps).shape} != "
                              f"({args.devices}, 2)")
        if not tr._audit_ok(fps):
            audit_msgs.append(f"healthy mesh failed the audit: {fps}")
        tr._inject_bitflip(2)
        fps2 = tr.audit_params()
        culprits = tr._audit_culprits(fps2)
        if culprits != [2]:
            audit_msgs.append(f"planted flip on replica 2 blamed "
                              f"{culprits}")
        nan = tr.train_round(batch(4))     # trips, rolls back
        if not np.isnan(nan):
            audit_msgs.append("tripped round did not report nan")
        if not tr._audit_ok(tr.audit_params()):
            audit_msgs.append("audit still failing after rollback")
        audit_ok = not audit_msgs
        failures += [f"[audit] {m2}" for m2 in audit_msgs]

    # -- 6. analytic boundary/exchange bytes ------------------------------
    probe = legs["local_sgd"]["sharded"]["trainer"]
    bytes_dp = partition.boundary_bytes_per_chip(probe.params, None)
    bytes_sh = partition.boundary_bytes_per_chip(probe.params, plan)
    shrink = round(bytes_dp / max(bytes_sh, 1), 3)
    none = comms.get_codec("none")
    ex_dp = comms.exchange_bytes(none, probe.params, args.devices)
    ex_sh = comms.sharded_exchange_bytes(none, probe.params,
                                         args.devices, plan)
    if not bytes_sh < bytes_dp:
        failures.append(f"[bytes] plan did not shrink the boundary: "
                        f"{bytes_sh} vs {bytes_dp}")
    # caffenet-class shapes: FC-dominated, the regime the rule table
    # targets.  eval_shape only — no 200 MB of params on the CPU rig.
    cnet_sp = load_solver_prototxt_with_net(
        'base_lr: 0.01\nlr_policy: "fixed"\n', caffenet(8, 8))
    cnet = Net(cnet_sp.net_param or cnet_sp.train_net_param,
               NetState(Phase.TRAIN))
    cnet_shapes = jax.eval_shape(cnet.init, jax.random.PRNGKey(0))
    cnet_plan = partition.resolve_plan("auto", cnet_shapes, axis="data",
                                       n_shards=8)
    cnet_dp = partition.boundary_bytes_per_chip(cnet_shapes, None)
    cnet_sh = partition.boundary_bytes_per_chip(cnet_shapes, cnet_plan)
    cnet_shrink = round(cnet_dp / max(cnet_sh, 1), 3)
    if cnet_plan is None or cnet_shrink < CAFFENET_MIN_SHRINK_X:
        failures.append(f"[bytes] caffenet-class shrink {cnet_shrink}x "
                        f"< {CAFFENET_MIN_SHRINK_X}x at 8 shards")

    result = {
        "shardbench": True,  # ingest sniff key (perfledger.entries_from_any)
        "ok": not failures,
        "failures": failures,
        "model": "lenet",
        "rounds": args.rounds,
        "tau": tau,
        "batch": args.batch,
        "devices": args.devices,
        "plan": plan_id,
        "plan_dims": plan.dims_dict() if plan else {},
        "parity": parity,
        "codec_int8_parity": not codec_mismatch,
        "ckpt_roundtrip_ok": ckpt_ok,
        "elastic_ok": elastic_ok,
        "audit_ok": audit_ok,
        "dp": {"round_s": legs["local_sgd"]["dp"]["round_s"],
               "boundary_bytes_per_chip": bytes_dp,
               "exchange_bytes": ex_dp},
        "sharded": {"round_s": legs["local_sgd"]["sharded"]["round_s"],
                    "boundary_bytes_per_chip": bytes_sh,
                    "exchange_bytes": ex_sh},
        "shard_bytes_shrink_x": shrink,
        "caffenet": {"plan": partition.shard_plan_id(cnet_plan),
                     "boundary_bytes_dp": cnet_dp,
                     "boundary_bytes_sharded": cnet_sh,
                     "shrink_x": cnet_shrink},
    }
    line = json.dumps(result)
    print(line, flush=True)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")
    if failures:
        print(f"[shardbench] GATE FAILURE: {failures}", file=sys.stderr,
              flush=True)
        return 1
    print(f"[shardbench] all gates hold: 3-strategy bit parity, int8 "
          f"composition, per-shard ckpt roundtrip, elastic re-tile, "
          f"shard-aware audit; boundary bytes {shrink}x smaller "
          f"(caffenet-class {cnet_shrink}x at 8 shards)",
          file=sys.stderr, flush=True)
    return 0


if __name__ == "__main__":
    # standalone: force the CPU backend with a virtual mesh BEFORE jax
    # initializes (the same rig contract as tests/conftest.py)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ.setdefault("JAX_PLATFORM_NAME", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    raise SystemExit(main())
