"""Structured tracing — the profiling tier the reference lacked.

The reference's tracing is wall-clock logs + CUDA-event timers (reference:
caffe/src/caffe/util/benchmark.cpp:26-145, app logs CifarApp.scala:41-50,
Spark event log ImageNetApp.scala:44; SURVEY.md §5 "No structured
tracing").  Here: ``jax.profiler`` traces viewable in TensorBoard/Perfetto,
plus annotation helpers that mark app phases inside the trace.
"""

from __future__ import annotations

import contextlib
from typing import Iterator

import jax


@contextlib.contextmanager
def trace(log_dir: str) -> Iterator[None]:
    """Capture a device+host profiler trace for the enclosed block
    (open in TensorBoard's profile tab or Perfetto)."""
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def annotate(name: str):
    """Named region inside a trace (TraceAnnotation), usable as decorator
    or context manager."""
    return jax.profiler.TraceAnnotation(name)


@contextlib.contextmanager
def server(port: int = 9999) -> Iterator[None]:
    """Live profiling server for `jax.profiler`-compatible clients."""
    s = jax.profiler.start_server(port)
    try:
        yield
    finally:
        del s


def device_memory_summary() -> list[dict]:
    """Per-device HBM usage (bytes in use / limit / peak) — the
    observability the reference's SyncedMemory world never exposed; used
    by `caffe device_query` and available for app logs."""
    out = []
    for d in jax.devices():
        stats = getattr(d, "memory_stats", lambda: None)() or {}
        out.append({
            "device": f"{d.platform}:{d.id}",
            "kind": d.device_kind,
            "bytes_in_use": stats.get("bytes_in_use"),
            "peak_bytes_in_use": stats.get("peak_bytes_in_use"),
            "bytes_limit": stats.get("bytes_limit"),
        })
    return out


def save_memory_profile(path: str) -> None:
    """Write a pprof-format device memory profile
    (jax.profiler.save_device_memory_profile)."""
    jax.profiler.save_device_memory_profile(path)
