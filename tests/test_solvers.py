"""Solver tests — the analog of test_gradient_based_solver.cpp (all six
solvers, snapshot/restore equivalence) plus LR-policy value checks against
the closed forms in sgd_solver.cpp:27-79."""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sparknet_tpu.proto.caffe_pb import SolverParameter
from sparknet_tpu.solvers import learning_rate, make_update_rule
from sparknet_tpu.solvers.update_rules import preprocess_grads


def sp_of(**kw) -> SolverParameter:
    sp = SolverParameter()
    for k, v in kw.items():
        setattr(sp, k, v)
    return sp


def test_lr_policies():
    assert float(learning_rate(sp_of(base_lr=0.1), 100)) == pytest.approx(0.1)
    assert float(learning_rate(
        sp_of(base_lr=0.1, lr_policy="step", gamma=0.5, stepsize=10), 25)
    ) == pytest.approx(0.1 * 0.25)
    assert float(learning_rate(
        sp_of(base_lr=0.1, lr_policy="exp", gamma=0.99), 10)
    ) == pytest.approx(0.1 * 0.99 ** 10, rel=1e-5)
    assert float(learning_rate(
        sp_of(base_lr=0.1, lr_policy="inv", gamma=1e-4, power=0.75), 1000)
    ) == pytest.approx(0.1 * (1 + 0.1) ** -0.75, rel=1e-5)
    assert float(learning_rate(
        sp_of(base_lr=0.1, lr_policy="multistep", gamma=0.1,
              stepvalue=[10, 20]), 15)) == pytest.approx(0.01, rel=1e-5)
    assert float(learning_rate(
        sp_of(base_lr=0.1, lr_policy="poly", power=2.0, max_iter=100), 50)
    ) == pytest.approx(0.1 * 0.25, rel=1e-5)
    assert float(learning_rate(
        sp_of(base_lr=0.1, lr_policy="sigmoid", gamma=-0.1, stepsize=50), 50)
    ) == pytest.approx(0.05, rel=1e-4)


def test_sgd_momentum_matches_manual():
    sp = sp_of(base_lr=0.1, momentum=0.9)
    rule = make_update_rule(sp)
    params = {"w": [jnp.array([1.0])]}
    state = rule.init(params)
    grads = {"w": [jnp.array([1.0])]}
    p1, s1 = rule.apply(params, grads, state, 0.1, 0)
    assert float(p1["w"][0][0]) == pytest.approx(1.0 - 0.1)
    p2, s2 = rule.apply(p1, grads, s1, 0.1, 1)
    # v2 = 0.9*0.1 + 0.1 = 0.19
    assert float(p2["w"][0][0]) == pytest.approx(0.9 - 0.19)


def test_regularize_l2_l1_and_clip():
    params = {"w": [jnp.array([2.0, -2.0])]}
    grads = {"w": [jnp.array([0.0, 0.0])]}
    g2 = preprocess_grads(sp_of(weight_decay=0.1), params, grads, None, None)
    np.testing.assert_allclose(np.asarray(g2["w"][0]), [0.2, -0.2], rtol=1e-6)
    g1 = preprocess_grads(sp_of(weight_decay=0.1, regularization_type="L1"),
                          params, grads, None, None)
    np.testing.assert_allclose(np.asarray(g1["w"][0]), [0.1, -0.1], rtol=1e-6)
    big = {"w": [jnp.array([3.0, 4.0])]}  # norm 5
    gc = preprocess_grads(sp_of(clip_gradients=1.0), params, big, None, None)
    np.testing.assert_allclose(np.asarray(gc["w"][0]), [0.6, 0.8], rtol=1e-5)


@pytest.mark.parametrize("solver_type", [
    "SGD", "NESTEROV", "ADAGRAD", "RMSPROP", "ADADELTA", "ADAM"])
def test_all_rules_reduce_quadratic(solver_type):
    # minimize ||x - c||² — every rule must make progress
    c = jnp.asarray(np.arange(4, dtype=np.float32))
    # canonical per-solver hyperparameters (AdaDelta wants base_lr 1.0 +
    # momentum-as-decay 0.95, caffe examples/mnist solver configs)
    cfg = {
        "SGD": dict(base_lr=0.1, momentum=0.9),
        "NESTEROV": dict(base_lr=0.1, momentum=0.9),
        "ADAGRAD": dict(base_lr=0.5),
        "RMSPROP": dict(base_lr=0.1, rms_decay=0.9),
        "ADADELTA": dict(base_lr=1.0, momentum=0.95, delta=1e-6),
        "ADAM": dict(base_lr=0.1, momentum=0.9),
    }[solver_type]
    sp = sp_of(solver_type=solver_type, **cfg)
    rule = make_update_rule(sp)
    params = {"x": [jnp.zeros(4)]}
    state = rule.init(params)

    def loss(p):
        return jnp.sum((p["x"][0] - c) ** 2)

    l0 = float(loss(params))
    for it in range(200):
        grads = jax.grad(loss)(params)
        rate = learning_rate(sp, it)
        params, state = rule.apply(params, grads, state, rate, it)
    # AdaDelta's update magnitude grows from √δ — intrinsically slow on a
    # short horizon (matches the reference implementation's behavior)
    bound = 0.5 if solver_type == "ADADELTA" else 0.2
    assert float(loss(params)) < bound * l0, solver_type


def test_lr_mult_freezes_param():
    sp = sp_of(base_lr=0.1, solver_type="SGD")
    rule = make_update_rule(sp)
    params = {"a": [jnp.ones(2)], "b": [jnp.ones(2)]}
    lr_mults = {"a": [jnp.asarray(0.0)], "b": [jnp.asarray(2.0)]}
    grads = {"a": [jnp.ones(2)], "b": [jnp.ones(2)]}
    state = rule.init(params)
    p1, _ = rule.apply(params, grads, state, 0.1, 0, lr_mults=lr_mults)
    np.testing.assert_allclose(np.asarray(p1["a"][0]), [1.0, 1.0])
    np.testing.assert_allclose(np.asarray(p1["b"][0]), [0.8, 0.8], rtol=1e-6)


def test_debug_info_logging(capsys):
    """sp.debug_info produces per-blob forward asums and per-param update
    dumps (net.cpp:711-735 ForwardDebugInfo/UpdateDebugInfo analog)."""
    import numpy as np

    from sparknet_tpu.models import lenet
    from sparknet_tpu.proto import load_solver_prototxt_with_net
    from sparknet_tpu.solvers import Solver

    sp = load_solver_prototxt_with_net(
        "base_lr: 0.01\ndebug_info: true\n", lenet(2, 2))
    solver = Solver(sp, seed=0)
    rng = np.random.default_rng(0)

    def feed():
        while True:
            yield {"data": rng.normal(size=(2, 1, 28, 28)).astype(np.float32),
                   "label": rng.integers(0, 10, size=(2,)).astype(np.float32)}

    solver.set_train_data(feed())
    solver.step(1)
    out = capsys.readouterr().out
    assert "[Forward] Layer conv1, top blob conv1 data:" in out
    assert "[Update] Layer conv1, param 0 data:" in out
    assert "diff:" in out


def test_solver_solve_schedule(capsys):
    """Solver.solve: test_initialization pass, interval-aligned test
    passes, final pass, stop at max_iter (solver.cpp Solve/Step)."""
    import numpy as np

    from sparknet_tpu.models import lenet
    from sparknet_tpu.proto import load_solver_prototxt_with_net
    from sparknet_tpu.solvers import Solver

    sp = load_solver_prototxt_with_net(
        "base_lr: 0.01\nmax_iter: 4\ntest_interval: 2\ntest_iter: 1\n",
        lenet(2, 2))
    solver = Solver(sp, seed=0)
    rng = np.random.default_rng(0)

    def feed():
        while True:
            yield {"data": rng.normal(size=(2, 1, 28, 28)).astype(np.float32),
                   "label": rng.integers(0, 10, size=(2,)).astype(np.float32)}

    calls = []
    orig = solver.test
    solver.set_train_data(feed())
    solver.set_test_data(lambda: feed())
    solver.test = lambda n=None, net_id=0: (calls.append(solver.iter),
                                            orig(1))[1]
    solver.solve()
    assert solver.iter == 4
    # test at iters 0 (test_initialization), 2, 4 (final)
    assert calls == [0, 2, 4]
    assert "Optimization Done." in capsys.readouterr().out


def test_solver_solve_signal_stop(tmp_path):
    """SIGINT during solve: snapshot (when a prefix is set) then stop at
    the chunk boundary (solver.cpp:270-281 SignalHandler contract)."""
    import os
    import signal

    import numpy as np

    from sparknet_tpu.models import lenet
    from sparknet_tpu.proto import load_solver_prototxt_with_net
    from sparknet_tpu.solvers import Solver

    sp = load_solver_prototxt_with_net(
        f'base_lr: 0.01\nmax_iter: 100\ntest_interval: 2\ntest_iter: 1\n'
        f'snapshot_prefix: "{tmp_path}/sig"\n', lenet(2, 2),
        snapshot_prefix=str(tmp_path / "sig"))
    solver = Solver(sp, seed=0)
    rng = np.random.default_rng(0)

    def feed():
        while True:
            yield {"data": rng.normal(size=(2, 1, 28, 28)).astype(np.float32),
                   "label": rng.integers(0, 10, size=(2,)).astype(np.float32)}

    solver.set_train_data(feed())
    solver.set_test_data(lambda: feed())
    calls = {"n": 0}
    orig_step = solver.step

    def step_and_interrupt(n):
        calls["n"] += 1
        if calls["n"] == 2:
            os.kill(os.getpid(), signal.SIGINT)  # caught by the guard
        return orig_step(n)

    solver.step = step_and_interrupt
    solver.solve()
    # signal queued before chunk 2 ran; the per-iteration poll inside
    # step() stops after ONE more iteration (iter 3), not chunk end
    assert solver.iter == 3
    snaps = list(tmp_path.glob("sig_iter_3.caffemodel"))
    assert snaps, "no snapshot written on signal stop"


def test_remat_matches_plain():
    """jax.checkpoint'd training (remat=True) is numerically identical to
    plain training — it only changes what the backward stores."""
    import numpy as np

    from sparknet_tpu.models import lenet
    from sparknet_tpu.proto import load_solver_prototxt_with_net
    from sparknet_tpu.solvers import Solver

    def run(remat):
        sp = load_solver_prototxt_with_net(
            "base_lr: 0.01\nmomentum: 0.9\n", lenet(2, 2))
        s = Solver(sp, seed=0, remat=remat)
        rng = np.random.default_rng(0)

        def feed():
            while True:
                yield {"data": rng.normal(size=(2, 1, 28, 28)).astype(np.float32),
                       "label": rng.integers(0, 10, size=(2,)).astype(np.float32)}

        s.set_train_data(feed())
        s.step(3)
        return s.params

    a, b = run(False), run(True)
    for k in a:
        for x, y in zip(a[k], b[k]):
            np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                       rtol=1e-6, atol=1e-7)


def test_solver_test_per_class_accumulation():
    """Vector test-net outputs (Accuracy's per-class top) accumulate
    element-wise like Solver::TestAndStoreResult, not collapsed to a
    scalar sum (solver.cpp:413-445)."""
    import numpy as np

    from sparknet_tpu.models.dsl import java_data_layer, layer, net_param
    from sparknet_tpu.proto import Phase, load_solver_prototxt_with_net
    from sparknet_tpu.solvers import Solver

    net = net_param("pc", [
        java_data_layer("input", ["data", "label"], None, (6, 4), (6,)),
        layer("ip", "InnerProduct", ["data"], ["ip"],
              inner_product_param={"num_output": 3,
                                   "weight_filler": {"type": "xavier"}}),
        layer("loss", "SoftmaxWithLoss", ["ip", "label"], ["loss"],
              phase=Phase.TRAIN),
        layer("acc", "Accuracy", ["ip", "label"], ["acc", "per_class"],
              phase=Phase.TEST),
    ])
    sp = load_solver_prototxt_with_net("base_lr: 0.01\n", net)
    solver = Solver(sp, seed=0)
    rng = np.random.default_rng(0)

    def feed():
        while True:
            yield {"data": rng.normal(size=(6, 4)).astype(np.float32),
                   "label": rng.integers(0, 3, size=(6,)).astype(np.float32)}

    solver.set_test_data(lambda: feed())
    scores = solver.test(4)
    assert isinstance(scores["acc"], float)
    assert np.shape(scores["per_class"]) == (3,)   # element-wise, not summed
