"""plot_training_log — chart a training log (reference:
caffe/tools/extra/plot_training_log.py.example).

Chart types follow the reference numbering; this framework's logs carry
iterations but not wall-clock timestamps or per-iter learning rates, so
the Seconds/LearningRate variants (1, 3, 4, 5, 7) raise with a clear
message rather than plotting wrong axes.

  0: Test accuracy  vs. Iters        2: Test loss  vs. Iters
  6: Train loss     vs. Iters

Usage:
  python -m sparknet_tpu.tools.plot_training_log CHART_TYPE OUT.png \
      LOG [LOG ...]
"""

from __future__ import annotations

import argparse
import os

_SUPPORTED = {
    0: ("Test accuracy vs. Iters", "accuracy", "test"),
    2: ("Test loss vs. Iters", "loss", "test"),
    6: ("Train loss vs. Iters", "loss", "train"),
}
_UNSUPPORTED = {
    1: "Seconds axes need glog timestamps this framework does not emit",
    3: "Seconds axes need glog timestamps this framework does not emit",
    4: "learning rate is not logged per iteration here",
    5: "learning rate is not logged per iteration here",
    7: "Seconds axes need glog timestamps this framework does not emit",
}


def _series(path: str, field: str, which: str):
    """-> {label_suffix: (xs, ys)} — one series per test net, so
    multi-test-net logs don't interleave into a zigzag."""
    from .parse_log import parse_log
    train, test = parse_log(path)
    if which == "train":
        return {"": ([it for it, _ in train],
                     [loss for _, loss in train])}
    by_net: dict[int, tuple[list, list]] = {}
    for (it, net), row in sorted(test.items()):
        if field in row:
            xs, ys = by_net.setdefault(net, ([], []))
            xs.append(it)
            ys.append(row[field])
    multi = len(by_net) > 1
    return {(f" (test net #{n})" if multi else ""): s
            for n, s in sorted(by_net.items())}


def plot(chart_type: int, out_path: str, logs: list[str]) -> None:
    if chart_type in _UNSUPPORTED:
        raise ValueError(
            f"chart type {chart_type} unsupported: "
            f"{_UNSUPPORTED[chart_type]} (supported: {sorted(_SUPPORTED)})")
    if chart_type not in _SUPPORTED:
        raise ValueError(
            f"unknown chart type {chart_type} "
            f"(supported: {sorted(_SUPPORTED)})")
    title, field, which = _SUPPORTED[chart_type]

    import matplotlib
    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    fig, ax = plt.subplots(figsize=(8, 5))
    for path in logs:
        series = _series(path, field, which)
        if not any(xs for xs, _ in series.values()):
            raise ValueError(f"{path}: no {which} '{field}' entries found")
        for suffix, (xs, ys) in series.items():
            ax.plot(xs, ys, marker=".", linewidth=1,
                    label=os.path.basename(path) + suffix)
    ax.set_xlabel("Iters")
    ax.set_ylabel(title.split(" vs.")[0])
    ax.set_title(title)
    ax.legend(loc="best")
    fig.tight_layout()
    fig.savefig(out_path)
    plt.close(fig)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("chart_type", type=int)
    ap.add_argument("out_path")
    ap.add_argument("logs", nargs="+")
    args = ap.parse_args(argv)
    plot(args.chart_type, args.out_path, args.logs)
    print(args.out_path)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
