"""Solver update rules — the six Caffe solvers as pure functions.

Mirrors the solver hierarchy (reference:
caffe/src/caffe/solvers/sgd_solver.cpp ComputeUpdateValue:207,
nesterov_solver.cpp, adagrad_solver.cpp, rmsprop_solver.cpp,
adadelta_solver.cpp, adam_solver.cpp; dispatch via solver_factory.hpp).
``ApplyUpdate`` order is preserved exactly (sgd_solver.cpp:102-143):
ClipGradients (global L2, on raw accumulated grads) → Normalize (÷iter_size)
→ Regularize (L2/L1 via weight_decay·decay_mult) → per-rule update with
local_rate = rate·lr_mult.

State is a pytree mirroring the params pytree (history blobs, reference:
sgd_solver.cpp history_ / update_ / temp_), so the whole update jits and
shards with the params.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable

import jax
import jax.numpy as jnp

from ..proto.caffe_pb import SolverParameter

Pytree = Any


@dataclasses.dataclass(frozen=True)
class SolverUpdate:
    """A pure (params, grads, state, rate, step) -> (params, state) rule."""

    name: str
    init: Callable[[Pytree], Pytree]
    apply: Callable[..., tuple[Pytree, Pytree]]


def _tmap(f, *trees):
    return jax.tree_util.tree_map(f, *trees)


def _global_l2(tree: Pytree) -> jnp.ndarray:
    leaves = jax.tree_util.tree_leaves(tree)
    return jnp.sqrt(sum(jnp.sum(x.astype(jnp.float32) ** 2) for x in leaves))


def preprocess_grads(sp: SolverParameter, params: Pytree, grads: Pytree,
                     lr_mults: Pytree | None, decay_mults: Pytree | None
                     ) -> Pytree:
    """ClipGradients → Normalize → Regularize (reference:
    sgd_solver.cpp:81-205).  Returns adjusted grads."""
    if sp.clip_gradients > 0:
        norm = _global_l2(grads)
        scale = jnp.minimum(1.0, sp.clip_gradients / jnp.maximum(norm, 1e-12))
        grads = _tmap(lambda g: g * scale, grads)
    if sp.iter_size > 1:
        grads = _tmap(lambda g: g / sp.iter_size, grads)
    if sp.weight_decay > 0:
        dm = decay_mults if decay_mults is not None else _tmap(
            lambda g: jnp.asarray(1.0), grads)
        if sp.regularization_type == "L2":
            grads = _tmap(lambda g, p, d: g + sp.weight_decay * d * p,
                          grads, params, dm)
        elif sp.regularization_type == "L1":
            grads = _tmap(lambda g, p, d: g + sp.weight_decay * d * jnp.sign(p),
                          grads, params, dm)
        else:
            raise ValueError(
                f"unknown regularization_type {sp.regularization_type!r}")
    return grads


def make_update_rule(sp: SolverParameter) -> SolverUpdate:
    t = sp.solver_type
    if t == "SGD":
        return _sgd(sp)
    if t == "NESTEROV":
        return _nesterov(sp)
    if t == "ADAGRAD":
        return _adagrad(sp)
    if t == "RMSPROP":
        return _rmsprop(sp)
    if t == "ADADELTA":
        return _adadelta(sp)
    if t == "ADAM":
        return _adam(sp)
    raise ValueError(f"unknown solver type {t!r}")


def _zeros_like_tree(params: Pytree) -> Pytree:
    return _tmap(jnp.zeros_like, params)


def _local_rates(rate, lr_mults, grads):
    if lr_mults is None:
        return _tmap(lambda g: rate, grads)
    return _tmap(lambda m: rate * m, lr_mults)


def _sgd(sp: SolverParameter) -> SolverUpdate:
    """v ← μv + local_rate·g;  p ← p − v (sgd_solver.cpp:207-244)."""

    def init(params):
        return {"history": _zeros_like_tree(params)}

    def apply(params, grads, state, rate, step, lr_mults=None):
        lr = _local_rates(rate, lr_mults, grads)
        hist = _tmap(lambda h, g, r: sp.momentum * h + r * g,
                     state["history"], grads, lr)
        new_params = _tmap(lambda p, h: p - h, params, hist)
        return new_params, {"history": hist}

    return SolverUpdate("SGD", init, apply)


def _nesterov(sp: SolverParameter) -> SolverUpdate:
    """v' ← μv + r·g;  p ← p − ((1+μ)v' − μv) (nesterov_solver.cpp)."""

    def init(params):
        return {"history": _zeros_like_tree(params)}

    def apply(params, grads, state, rate, step, lr_mults=None):
        lr = _local_rates(rate, lr_mults, grads)
        old = state["history"]
        hist = _tmap(lambda h, g, r: sp.momentum * h + r * g, old, grads, lr)
        upd = _tmap(lambda hn, ho: (1 + sp.momentum) * hn - sp.momentum * ho,
                    hist, old)
        return _tmap(lambda p, u: p - u, params, upd), {"history": hist}

    return SolverUpdate("NESTEROV", init, apply)


def _adagrad(sp: SolverParameter) -> SolverUpdate:
    """h ← h + g²;  p ← p − r·g/(√h + δ) (adagrad_solver.cpp)."""

    def init(params):
        return {"history": _zeros_like_tree(params)}

    def apply(params, grads, state, rate, step, lr_mults=None):
        lr = _local_rates(rate, lr_mults, grads)
        hist = _tmap(lambda h, g: h + g * g, state["history"], grads)
        upd = _tmap(lambda g, h, r: r * g / (jnp.sqrt(h) + sp.delta),
                    grads, hist, lr)
        return _tmap(lambda p, u: p - u, params, upd), {"history": hist}

    return SolverUpdate("ADAGRAD", init, apply)


def _rmsprop(sp: SolverParameter) -> SolverUpdate:
    """h ← ρh + (1−ρ)g²;  p ← p − r·g/(√h + δ) (rmsprop_solver.cpp)."""

    def init(params):
        return {"history": _zeros_like_tree(params)}

    def apply(params, grads, state, rate, step, lr_mults=None):
        lr = _local_rates(rate, lr_mults, grads)
        rd = sp.rms_decay
        hist = _tmap(lambda h, g: rd * h + (1 - rd) * g * g,
                     state["history"], grads)
        upd = _tmap(lambda g, h, r: r * g / (jnp.sqrt(h) + sp.delta),
                    grads, hist, lr)
        return _tmap(lambda p, u: p - u, params, upd), {"history": hist}

    return SolverUpdate("RMSPROP", init, apply)


def _adadelta(sp: SolverParameter) -> SolverUpdate:
    """Accumulate g² and Δ² with momentum as decay; update scaled by
    √((Δ²+δ)/(g²+δ)) × local_rate (adadelta_solver.cpp)."""

    def init(params):
        return {"sq_grad": _zeros_like_tree(params),
                "sq_update": _zeros_like_tree(params)}

    def apply(params, grads, state, rate, step, lr_mults=None):
        lr = _local_rates(rate, lr_mults, grads)
        mu = sp.momentum
        sq_g = _tmap(lambda h, g: mu * h + (1 - mu) * g * g,
                     state["sq_grad"], grads)
        upd = _tmap(
            lambda g, hg, hu: g * jnp.sqrt((hu + sp.delta) / (hg + sp.delta)),
            grads, sq_g, state["sq_update"])
        sq_u = _tmap(lambda h, u: mu * h + (1 - mu) * u * u,
                     state["sq_update"], upd)
        scaled = _tmap(lambda u, r: r * u, upd, lr)
        return (_tmap(lambda p, u: p - u, params, scaled),
                {"sq_grad": sq_g, "sq_update": sq_u})

    return SolverUpdate("ADADELTA", init, apply)


def _adam(sp: SolverParameter) -> SolverUpdate:
    """m ← β₁m + (1−β₁)g; v ← β₂v + (1−β₂)g²;
    p ← p − r·√(1−β₂ᵗ)/(1−β₁ᵗ)·m/(√v + δ) (adam_solver.cpp:74-113 —
    note Caffe adds δ outside the sqrt and bias-corrects via the rate)."""

    def init(params):
        return {"m": _zeros_like_tree(params), "v": _zeros_like_tree(params)}

    def apply(params, grads, state, rate, step, lr_mults=None):
        lr = _local_rates(rate, lr_mults, grads)
        b1, b2 = sp.momentum, sp.momentum2
        t = jnp.asarray(step, jnp.float32) + 1.0
        m = _tmap(lambda m_, g: b1 * m_ + (1 - b1) * g, state["m"], grads)
        v = _tmap(lambda v_, g: b2 * v_ + (1 - b2) * g * g, state["v"], grads)
        correction = jnp.sqrt(1.0 - jnp.power(b2, t)) / (1.0 - jnp.power(b1, t))
        upd = _tmap(lambda m_, v_, r: r * correction * m_ / (jnp.sqrt(v_) + sp.delta),
                    m, v, lr)
        return _tmap(lambda p, u: p - u, params, upd), {"m": m, "v": v}

    return SolverUpdate("ADAM", init, apply)
