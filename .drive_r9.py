"""Drive the PR-4 parallel input pipeline end-to-end (public surface).

Run: python .drive_r9.py   (from the repo root; prints DRIVE OK)

Flows: (1) training THROUGH the parallel feed path — db_feed(workers=2) →
device_feed(u8 cast path exercised separately) → Solver.step, loss drops;
(2) serial-vs-parallel bit-identity incl. corrupt_record quarantine parity;
(3) DeviceFeed: deep depth, uint8 staging + on-device cast, per-stage
stats, watchdog (feeder_die) still lossless through the new staging tier;
(4) DistributedTrainer.input_feed on an 8-virtual-device mesh;
(5) PartitionedDataset.cached() multi-epoch decode-once;
(6) typed error paths: DecodeWorkerError on a dead pool, bad knob values.
"""
import os

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
if "xla_force_host_platform_device_count" not in os.environ["XLA_FLAGS"]:
    os.environ["XLA_FLAGS"] += " --xla_force_host_platform_device_count=8"

import jax

jax.config.update("jax_platforms", "cpu")  # the only reliable CPU route here

import numpy as np

from sparknet_tpu.data import (
    DecodePool, DecodeWorkerError, FeedStats, PartitionedDataset,
    Quarantine, QuarantinePolicy, device_feed, feed_depth, feed_workers,
)
from sparknet_tpu.data.db import array_to_datum, db_feed
from sparknet_tpu.data.lmdb_io import write_lmdb
from sparknet_tpu.models.dsl import layer
from sparknet_tpu.proto.caffe_pb import Phase
from sparknet_tpu.utils import faults

checks = 0


def ok(cond, what):
    global checks
    assert cond, what
    checks += 1
    print(f"  ok: {what}")


# -- a tiny LMDB ------------------------------------------------------------
tmp = "/tmp/drive_r9"
os.makedirs(tmp, exist_ok=True)
db = os.path.join(tmp, "lmdb")
rng = np.random.default_rng(0)
N = 64
imgs = rng.integers(0, 256, size=(N, 3, 12, 12)).astype(np.uint8)
labels = rng.integers(0, 10, size=N)
if not os.path.exists(db):
    write_lmdb(db, [(b"%08d" % i, array_to_datum(imgs[i], int(labels[i])))
                    for i in range(N)])
LP = dict(data_param={"source": db, "batch_size": 8, "backend": "LMDB"},
          transform_param={"scale": 1 / 255.0})


def make_feed(workers, quarantine=None, stats=None):
    lp = layer("d", "Data", [], ["data", "label"], **LP)
    return db_feed(lp, Phase.TRAIN, seed=3, quarantine=quarantine,
                   workers=workers, stats=stats)


# -- (1) train through the parallel pipeline --------------------------------
print("[1] train through db_feed(workers=2) -> device_feed -> Solver")
from sparknet_tpu.proto import load_net_prototxt, load_solver_prototxt_with_net

net_txt = """
name: "drv"
layer { name: "data" type: "Input" top: "data" top: "label"
        input_param { shape { dim: 8 dim: 3 dim: 12 dim: 12 }
                      shape { dim: 8 } } }
layer { name: "ip" type: "InnerProduct" bottom: "data" top: "ip"
        inner_product_param { num_output: 10
                              weight_filler { type: "xavier" } } }
layer { name: "loss" type: "SoftmaxWithLoss" bottom: "ip" bottom: "label"
        top: "loss" }
"""
from sparknet_tpu.solvers import Solver

sp = load_solver_prototxt_with_net("base_lr: 0.05\nmomentum: 0.9\n",
                                   load_net_prototxt(net_txt))
solver = Solver(sp, seed=0)
stats = FeedStats()
feed = device_feed(make_feed(2, stats=stats), depth=4, stats=stats)
solver.set_train_data(feed)
l0 = solver.step(3)
l1 = solver.step(25)
feed.close()
ok(np.isfinite(l0) and np.isfinite(l1) and l1 < l0,
   f"loss dropped through the parallel feed ({l0:.3f} -> {l1:.3f})")
snap = stats.snapshot()
ok(snap["batches"] > 0 and snap["device_put_s"] > 0 and snap["decode_s"] > 0,
   f"per-stage stats populated: {snap}")

# -- (2) serial vs parallel bit-identity (clean + corrupt) ------------------
print("[2] serial-vs-parallel bit-identity, clean + corrupt_record")


def stream(workers, n=10, quarantine=None):
    f = make_feed(workers, quarantine=quarantine)
    out = [next(f) for _ in range(n)]
    f.close()
    return out


for b_s, b_p in zip(stream(0), stream(4)):
    assert all(np.array_equal(b_s[k], b_p[k]) for k in b_s)
ok(True, "clean streams bit-identical (workers=0 vs 4)")

os.environ["SPARKNET_FAULT"] = "corrupt_record:0.2"
os.environ["SPARKNET_FAULT_ATTEMPT"] = "0"
reports = []
streams = []
for w in (0, 4):
    faults.reset_injector()
    q = Quarantine(QuarantinePolicy(max_fraction=0.5), epoch_size=N,
                   source=db)
    streams.append(stream(w, quarantine=q))
    r = q.report()
    r.pop("examples")
    reports.append(r)
del os.environ["SPARKNET_FAULT"]
faults.reset_injector()
for b_s, b_p in zip(*streams):
    assert all(np.array_equal(b_s[k], b_p[k]) for k in b_s)
ok(reports[0]["total_bad"] > 0 and reports[0] == reports[1],
   f"quarantine parity under faults: {reports[0]['total_bad']} bad, "
   f"identical accounting")

# -- (3) DeviceFeed: u8 cast, watchdog through the staging tier -------------
print("[3] DeviceFeed: uint8 staging + device cast; feeder_die lossless")
import jax.numpy as jnp

host = [{"data": np.full((4, 2), i, np.uint8)} for i in range(6)]
with device_feed(iter(host), depth=feed_depth(),
                 device_cast={"data": jnp.float32}) as df:
    got = list(df)
ok(len(got) == 6 and all(b["data"].dtype == jnp.float32 for b in got)
   and all(float(np.asarray(b["data"]).max()) == i
           for i, b in enumerate(got)),
   "uint8 shipped, f32 on device, order and values intact")

os.environ["SPARKNET_FAULT"] = "feeder_die@round:3"
os.environ["SPARKNET_FAULT_ATTEMPT"] = "0"
faults.reset_injector()
with device_feed(iter([{"x": np.full(2, i, np.float32)}
                       for i in range(8)]), depth=2) as df:
    vals = [int(np.asarray(b["x"])[0]) for b in df]
del os.environ["SPARKNET_FAULT"]
faults.reset_injector()
ok(vals == list(range(8)),
   "feeder death mid-stream: watchdog restart lost no batches through "
   "the staging pool")

# -- (4) DistributedTrainer.input_feed on the 8-device mesh -----------------
print("[4] DistributedTrainer.input_feed round path")
from sparknet_tpu.parallel.trainer import DistributedTrainer, TrainerConfig

tr = DistributedTrainer(sp, config=TrainerConfig(strategy="local_sgd",
                                                 tau=2), seed=0)
gb = 8 * tr.n_workers


def rounds():
    while True:
        yield {"data": rng.normal(size=(2, gb, 3, 12, 12)
                                  ).astype(np.float32),
               "label": rng.integers(0, 10, size=(2, gb)
                                     ).astype(np.float32)}


with tr.input_feed(rounds(), depth=2) as rit:
    losses = [tr.train_round(next(rit)) for _ in range(3)]
ok(all(np.isfinite(l) for l in losses),
   f"3 sharded rounds through input_feed: losses {['%.3f' % l for l in losses]}")

# -- (5) decoded-shard cache ------------------------------------------------
print("[5] PartitionedDataset.cached: decode once per shard")


class Counting(list):
    mat = 0

    def __getitem__(self, i):
        if isinstance(i, slice):
            type(self).mat += 1
        return super().__getitem__(i)


parts = [Counting([(imgs[j], int(labels[j])) for j in range(16)])
         for _ in range(3)]
ds = PartitionedDataset(parts).cached(max_shards=3)
for _epoch in range(4):
    for p in range(3):
        _ = list(ds.partitions[p])
ok(Counting.mat == 3, f"3 shards materialized once across 4 epochs "
   f"(got {Counting.mat})")

# -- (6) typed error paths --------------------------------------------------
print("[6] error paths")
pool = DecodePool(lambda x: x, workers=2)
pool.submit(1)
pool._closed = True
pool.close()
try:
    pool.submit(2)
    raise SystemExit("closed pool accepted work")
except RuntimeError:
    ok(True, "closed pool rejects submit")

boom = DecodePool(lambda x: 1 // 0, workers=2)
boom.submit(1)
try:
    boom.result()
    raise SystemExit("pool ate the work-fn exception")
except ZeroDivisionError:
    ok(True, "work-fn exception re-raised at its ordinal")
boom.close()

try:
    feed_workers_bad = int(os.environ.setdefault("SPARKNET_FEED_WORKERS",
                                                 "-2"))
    feed_workers()
    raise SystemExit("negative SPARKNET_FEED_WORKERS accepted")
except ValueError:
    ok(True, "negative SPARKNET_FEED_WORKERS raises")
finally:
    del os.environ["SPARKNET_FEED_WORKERS"]

print(f"DRIVE OK ({checks} checks)")
