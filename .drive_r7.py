"""Drive PR 2 surfaces end-to-end: numerical guard rollback, elastic
resume, heartbeats/straggler supervision, preemption signals.
Run from repo root: python .drive_r7.py"""
import os, sys, tempfile, time

os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
import jax
jax.config.update("jax_platforms", "cpu")

import numpy as np
from sparknet_tpu.models import lenet
from sparknet_tpu.parallel import (
    DistributedTrainer, TrainerConfig, TrainingDivergedError, make_mesh,
    ElasticPolicy, ResilienceError, ResilientRunner, RestartPolicy, health,
)
from sparknet_tpu.proto import load_solver_prototxt_with_net
from sparknet_tpu.utils import faults

SP = 'base_lr: 0.005\nmomentum: 0.9\nlr_policy: "fixed"\n'

def trainer(d, workers, **kw):
    sp = load_solver_prototxt_with_net(SP, lenet(24, 24))
    return DistributedTrainer(sp, make_mesh(workers),
                              TrainerConfig(strategy="local_sgd", tau=2,
                                            checkpoint_dir=d, **kw), seed=0)

def batch(r):
    rng = np.random.default_rng(100 + r)
    return {"data": rng.normal(size=(2, 24, 1, 28, 28)).astype(np.float32),
            "label": rng.integers(0, 10, size=(2, 24)).astype(np.float32)}

# 1) numerical guard: poison round 2, roll back, match fault-free exactly
da, db = tempfile.mkdtemp(), tempfile.mkdtemp()
clean = trainer(da, 4, guard_numerics=True)
clean_losses = [clean.train_round(batch(r)) for r in range(4)]
os.environ["SPARKNET_FAULT"] = "nan_inject@round:2"
faults.reset_injector()
tr = trainer(db, 4, guard_numerics=True)
while tr.round < 4:
    tr.train_round(batch(tr.round))
os.environ.pop("SPARKNET_FAULT"); faults.reset_injector()
assert tr.guard_trips == 1, tr.guard_trips
np.testing.assert_array_equal(np.asarray(tr.params["conv1"][0]),
                              np.asarray(clean.params["conv1"][0]))
print("1) guard: NaN round dropped, rollback exact, trips =", tr.guard_trips)

# 2) elastic resume: the 4-worker checkpoint re-forms on 3 workers
b = trainer(db, 3, elastic=True)
assert b.round == 4 and b.n_workers == 3
l = b.train_round(batch(4))
assert np.isfinite(l)
print(f"2) elastic: resumed 4->3 workers at round {b.round - 1}, "
      f"continued with loss {l:.3f}")

# 3) error paths: non-elastic mismatch raises; guard needs a ckpt dir
try:
    trainer(da, 3); raise AssertionError("should have raised")
except ValueError as e:
    assert "elastic" in str(e)
try:
    trainer(None, 4, guard_numerics=True); raise AssertionError("no raise")
except ValueError as e:
    assert "guard_numerics" in str(e)
print("3) error paths: mismatch/config errors raise with guidance")

# 4) heartbeats + straggler supervision + elastic re-form, real processes
saved = dict(os.environ)
os.environ.pop("XLA_FLAGS", None)
for k in list(os.environ):
    if k.startswith("SPARKNET_"):
        os.environ.pop(k)
try:
    wd = tempfile.mkdtemp()
    worker = os.path.join(wd, "w.py")
    with open(worker, "w") as f:
        f.write("""import os, sys, time
sys.path.insert(0, %r)
from sparknet_tpu.parallel import health
from sparknet_tpu.utils import faults
rank = int(os.environ["SPARKNET_PROC_ID"])
inj = faults.FaultInjector.from_env()
for r in range(3):
    health.maybe_beat(r, "round_start")
    inj.on_round(r, rank=rank)
    time.sleep(0.05)
print("ok", rank, os.environ["SPARKNET_NUM_PROCS"])
""" % os.getcwd())
    runner = ResilientRunner(
        [sys.executable, worker], nprocs=4, timeout=120,
        policy=RestartPolicy(max_restarts=1, backoff_base=0.05, jitter=0.0),
        elastic=ElasticPolicy(enabled=True, min_workers=2),
        extra_env={"SPARKNET_FAULT": "perma_crash@rank:3"})
    rc = runner.run()
    assert rc == 0 and runner.nprocs == 3 and runner.incarnation == 1
    print("4) elastic re-form: perma-crashed rank dropped, survivors "
          "completed; attempts:",
          [(a.returncode, a.world) for a in runner.attempts])

    # 5) straggler: hung worker killed at the deadline, post-mortem raised
    with open(worker, "a") as f:
        f.write("\nif rank == 1:\n    print('HUNG-HERE', flush=True)\n"
                "    time.sleep(600)\n")
    runner2 = ResilientRunner(
        [sys.executable, worker], nprocs=2, timeout=300, round_deadline=3.0,
        policy=RestartPolicy(max_restarts=0))
    t0 = time.monotonic()
    try:
        runner2.run_or_raise(); raise AssertionError("should have raised")
    except ResilienceError as e:
        took = time.monotonic() - t0
        assert e.cause == "straggler" and e.rank == 1, (e.cause, e.rank)
        assert "HUNG-HERE" in (e.log_tail or ""), "log tail missing"
        assert e.heartbeat_age is not None
        assert took < 60, took
        print(f"5) straggler: killed at deadline in {took:.1f}s (not "
              f"600s); post-mortem has log tail + heartbeat age "
              f"{e.heartbeat_age:.1f}s")
finally:
    os.environ.clear(); os.environ.update(saved)
print("DRIVE OK")
