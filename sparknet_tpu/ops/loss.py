"""Loss layers.

Reference implementations: caffe/src/caffe/layers/{softmax_loss,
euclidean_loss,hinge_loss,infogain_loss,sigmoid_cross_entropy_loss,
multinomial_logistic_loss,contrastive_loss}_layer.cpp (headers:
caffe/include/caffe/loss_layers.hpp).  Normalization conventions are matched
exactly — they determine effective learning rates, hence accuracy-trajectory
parity (SURVEY.md §7.3).
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from .common import _canon_axis
from .registry import LayerImpl, register_layer

_LOG_THRESHOLD = 1e-20
_FLT_MIN = 1.1754944e-38


class LossLayer(LayerImpl):
    def min_bottoms(self) -> int:
        return 2

    def out_shapes(self, lp, bottom_shapes):
        return [()]

    def top_has_batch_axis(self, lp, top_index: int) -> bool:
        return False  # scalar loss


@register_layer("SoftmaxWithLoss")
class SoftmaxWithLossLayer(LossLayer):
    """Softmax + multinomial logistic loss, fused for stability
    (softmax_loss_layer.cpp).  `loss_param { ignore_label, normalize }`:
    normalize=true (default) divides by the count of valid predictions
    (N × spatial), false divides by N."""

    def apply(self, lp, params, bottoms, train, rng):
        p = lp.sub("loss_param")
        ignore = p.get("ignore_label")
        normalize = bool(p.get("normalize", True))
        axis = _canon_axis(int(lp.sub("softmax_param").get("axis", 1)),
                           bottoms[0].ndim)
        scores, labels = bottoms[0], bottoms[1]
        logp = jax.nn.log_softmax(scores, axis=axis)
        lp_ = jnp.moveaxis(logp, axis, -1)
        n = lp_.shape[0]
        lp_ = lp_.reshape(n, -1, lp_.shape[-1])            # (N, spatial, C)
        lab = labels.astype(jnp.int32).reshape(n, -1)      # (N, spatial)
        # ignored labels may be out of range (e.g. 255); clip the gather
        # index — the masked term is dropped below anyway
        safe = jnp.clip(lab, 0, lp_.shape[-1] - 1)
        nll = -jnp.take_along_axis(lp_, safe[:, :, None], axis=-1)[..., 0]
        if ignore is not None:
            mask = (lab != int(ignore)).astype(nll.dtype)
            nll = nll * mask
            count = jnp.maximum(jnp.sum(mask), 1.0)
        else:
            count = float(nll.size)
        total = jnp.sum(nll)
        # normalize=false divides by outer_num_ = prod(shape[:softmax_axis])
        # (softmax_loss_layer.cpp Forward), not the batch dim — they differ
        # when softmax_param.axis != 1
        outer = math.prod(scores.shape[:axis])
        return [total / count if normalize else total / outer]


@register_layer("MultinomialLogisticLoss")
class MultinomialLogisticLossLayer(LossLayer):
    """-log(prob[label]) averaged over batch; input is already a probability
    distribution (multinomial_logistic_loss_layer.cpp)."""

    def apply(self, lp, params, bottoms, train, rng):
        probs, labels = bottoms[0], bottoms[1]
        n = probs.shape[0]
        lab = labels.astype(jnp.int32).reshape(n)
        p = probs.reshape(n, -1)[jnp.arange(n), lab]
        return [-jnp.sum(jnp.log(jnp.maximum(p, _LOG_THRESHOLD))) / n]


@register_layer("EuclideanLoss")
class EuclideanLossLayer(LossLayer):
    """sum((a-b)²) / 2N (euclidean_loss_layer.cpp)."""

    def apply(self, lp, params, bottoms, train, rng):
        d = bottoms[0] - bottoms[1]
        return [jnp.sum(d * d) / (2.0 * d.shape[0])]


@register_layer("SigmoidCrossEntropyLoss")
class SigmoidCrossEntropyLossLayer(LossLayer):
    """Per-element logistic loss from logits, summed and divided by N
    (sigmoid_cross_entropy_loss_layer.cpp), computed in the same stable form:
    x - x·t + log(1 + e^-|x|) + max(-x, 0)·0 rearrangement."""

    def apply(self, lp, params, bottoms, train, rng):
        x, t = bottoms[0], bottoms[1].astype(bottoms[0].dtype)
        n = x.shape[0]
        loss = jnp.maximum(x, 0.0) - x * t + jnp.log1p(jnp.exp(-jnp.abs(x)))
        return [jnp.sum(loss) / n]


@register_layer("HingeLoss")
class HingeLossLayer(LossLayer):
    """One-vs-all hinge loss with L1/L2 norm (hinge_loss_layer.cpp)."""

    def apply(self, lp, params, bottoms, train, rng):
        norm = str(lp.sub("hinge_loss_param").get("norm", "L1"))
        scores, labels = bottoms[0], bottoms[1]
        n = scores.shape[0]
        s = scores.reshape(n, -1)
        lab = labels.astype(jnp.int32).reshape(n)
        sign = jnp.where(jax.nn.one_hot(lab, s.shape[1], dtype=s.dtype) > 0, 1.0, -1.0)
        margin = jnp.maximum(0.0, 1.0 - sign * s)
        if norm == "L2":
            return [jnp.sum(margin * margin) / n]
        return [jnp.sum(margin) / n]


@register_layer("InfogainLoss")
class InfogainLossLayer(LossLayer):
    """-Σ_j H[label, j]·log(p_j) / N with an infogain matrix H supplied
    either as a third bottom or via ``infogain_loss_param { source }`` —
    a BlobProto binaryproto file, loaded once at trace time and folded
    into the graph as a constant (infogain_loss_layer.cpp LayerSetUp)."""

    _H_CACHE: dict = {}

    def _matrix(self, lp, bottoms):
        if len(bottoms) >= 3:
            return bottoms[2]
        source = lp.sub("infogain_loss_param").get("source")
        if source is None:
            raise ValueError(
                "InfogainLoss needs H: a third bottom or "
                "infogain_loss_param.source (infogain_loss_layer.cpp)")
        source = str(source)
        if source not in self._H_CACHE:
            from ..proto.caffemodel import load_mean_binaryproto
            self._H_CACHE[source] = load_mean_binaryproto(source)
        return jnp.asarray(self._H_CACHE[source])

    def apply(self, lp, params, bottoms, train, rng):
        probs, labels = bottoms[0], bottoms[1]
        H = self._matrix(lp, bottoms).reshape(probs.shape[1],
                                              probs.shape[1])
        n = probs.shape[0]
        lab = labels.astype(jnp.int32).reshape(n)
        logp = jnp.log(jnp.maximum(probs.reshape(n, -1), _LOG_THRESHOLD))
        return [-jnp.sum(H[lab] * logp) / n]


@register_layer("ContrastiveLoss")
class ContrastiveLossLayer(LossLayer):
    """Siamese contrastive loss (contrastive_loss_layer.cpp):
    y·d² + (1−y)·max(margin − d, 0)² (legacy: margin − d²), over 2N."""

    def min_bottoms(self) -> int:
        return 3

    def apply(self, lp, params, bottoms, train, rng):
        p = lp.sub("contrastive_loss_param")
        margin = float(p.get("margin", 1.0))
        legacy = bool(p.get("legacy_version", False))
        a, b, y = bottoms[0], bottoms[1], bottoms[2].astype(bottoms[0].dtype)
        n = a.shape[0]
        d2 = jnp.sum((a - b) ** 2, axis=1)
        y = y.reshape(n)
        if legacy:
            neg = jnp.maximum(margin - d2, 0.0)
        else:
            dist = jnp.maximum(margin - jnp.sqrt(d2 + 1e-12), 0.0)
            neg = dist * dist
        return [jnp.sum(y * d2 + (1.0 - y) * neg) / (2.0 * n)]
