from .lr_policies import learning_rate
from .update_rules import SolverUpdate, make_update_rule
from .solver import Solver
